"""Kafka ``orders`` topic ingestion: OrderResult wire decode + consumer.

Wire compatibility contract (field numbers from the reference schema,
/root/reference/pb/demo.proto:203-214 — ``OrderResult{order_id=1,
shipping_tracking_id=2, shipping_cost=3, shipping_address=4, items=5}``,
``OrderItem{item=1 CartItem{product_id=1, quantity=2}, cost=2
Money{currency_code=1, units=2, nanos=3}}``): any producer that feeds the
reference's fraud-detection consumer
(/root/reference/src/fraud-detection/src/main/kotlin/frauddetection/main.kt:64)
feeds this one unchanged.

The decoded order is projected onto the detector's span shape: one
record per order, keyed by order id (cardinality signal = distinct
orders), with item count/value as the monitored attribute (heavy-hitter
signal = one product dominating order flow — the business-level anomaly
the reference's accounting/fraud pair exists to catch).

The consumer transport prefers ``confluent_kafka`` when installed and
otherwise uses the framework's own wire client
(``runtime.kafka_client`` — real Kafka protocol over a real socket; the
in-repo broker ``runtime.kafka_broker`` stands in for the compose
topology's broker in tests). Consumer-group offsets are surfaced on
every poll so ``checkpoint`` can key sketch snapshots to them
(exactly-once-ish resume; SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, NamedTuple, Sequence

import numpy as np

from . import native, wire
from ..currency_data import to_usd_factor
from .tensorize import SpanColumns, SpanRecord, SpanTensorizer

ORDERS_SERVICE = "checkout-orders"


class Order(NamedTuple):
    order_id: str
    tracking_id: str
    shipping_cost_units: float
    item_count: int
    product_ids: tuple[str, ...]
    total_quantity: int
    currency: str = "USD"  # shipping_cost Money currency on the wire


def _money_units(buf: bytes | None) -> tuple[float, str]:
    if not buf:
        return 0.0, "USD"
    f = wire.scan_fields(buf)
    code = wire.first(f, 1, b"USD")
    units = wire.first(f, 2, 0)
    nanos = wire.first(f, 3, 0)
    # zigzag not used (int64/int32 plain varints in the schema)
    return (
        float(units) + float(nanos) * 1e-9,
        code.decode("utf-8", "replace") if isinstance(code, bytes) else "USD",
    )


def decode_order(payload: bytes) -> Order:
    """Decode an OrderResult protobuf payload (see module docstring)."""
    f = wire.scan_fields(payload)
    order_id = (wire.first(f, 1, b"") or b"").decode("utf-8", "replace")
    tracking = (wire.first(f, 2, b"") or b"").decode("utf-8", "replace")
    shipping, currency = _money_units(wire.first(f, 3))
    products: list[str] = []
    qty = 0
    for item_buf in f.get(5, []):
        item_f = wire.scan_fields(item_buf)
        cart_buf = wire.first(item_f, 1)
        if cart_buf:
            cart_f = wire.scan_fields(cart_buf)
            pid = wire.first(cart_f, 1, b"")
            if pid:
                products.append(pid.decode("utf-8", "replace"))
            qty += int(wire.first(cart_f, 2, 0) or 0)
    return Order(
        order_id, tracking, shipping, len(products), tuple(products), qty,
        currency,
    )


def order_to_record(order: Order, duration_us: float = 0.0) -> SpanRecord:
    """Project an order onto the detector's span shape.

    Trace-id analogue = order id (distinct-order cardinality); monitored
    attribute = the order's first product id (heavy-hitter per service
    'checkout-orders'); latency lane carries order value so the EWMA head
    doubles as an order-value anomaly tracker. The value is normalized
    to USD (the wire carries the user currency, reference parity with
    main.go's localized shipping cost) so a burst of JPY checkouts is
    not a ~150x false value anomaly.
    """
    value = order.shipping_cost_units * to_usd_factor(order.currency)
    return SpanRecord(
        service="checkout-orders",
        duration_us=duration_us if duration_us else value,
        trace_id=order.order_id.encode() or b"\0",
        is_error=False,
        attr=order.product_ids[0] if order.product_ids else "",
    )


def decode_orders_columnar(
    payloads: Sequence[bytes], tensorizer: SpanTensorizer
) -> SpanColumns:
    """Batch-decode OrderResult payloads straight to pipeline columns.

    Uses the native C++ decoder when available (one call for the whole
    poll batch), the per-message Python path otherwise — identical
    columns either way (pinned by tests/test_native_ingest.py). Feed the
    result to ``DetectorPipeline.submit_columns``.
    """
    sid = tensorizer.service_id(ORDERS_SERVICE)
    n = len(payloads)
    if native.available():
        cols = native.decode_orders(payloads)
        return SpanColumns(
            svc=np.full(n, sid, np.int32),
            lat_us=cols.value_units,
            is_error=np.zeros(n, np.float32),
            trace_key=cols.order_key,
            attr_crc=cols.attr_crc.astype(np.uint64),
        )
    records = [order_to_record(decode_order(p)) for p in payloads]
    return tensorizer.columns_from_records(records)


class DeferredOffsets:
    """Bounded deferred-confirmation offset list (the daemon's orders
    pump): flushes whose pool ticket hasn't resolved park here until
    the flush confirms cleanly, and only THEN do their offsets join the
    checkpointable map (the PR-3 at-least-once rule).

    Unbounded, a permanently-failing flush path would grow this list
    forever (one entry per pump). Bounded: over ``cap`` entries the
    OLDEST is shed — its records simply replay from the broker on
    restart (at-least-once preserved, never silent loss), the shed is
    counted (``anomaly_offset_defer_dropped_total``) and
    ``barrier_needed`` flips so the daemon forces an immediate
    checkpoint, persisting what IS confirmed and bounding the replay
    window the sheds opened.
    """

    def __init__(self, cap: int = 64):
        self.cap = max(int(cap), 1)
        self._items: deque = deque()
        self.dropped_total = 0
        self.barrier_needed = False

    def __len__(self) -> int:
        return len(self._items)

    def add(self, ticket, offsets: dict) -> None:
        self._items.append((ticket, offsets))
        while len(self._items) > self.cap:
            self._items.popleft()
            self.dropped_total += 1
            self.barrier_needed = True

    def resolve(self) -> dict:
        """Merged offsets of every flush that has since confirmed
        CLEANLY; failed/unresolved flushes stay out (failed ones are
        dropped — their records replay on restart)."""
        merged: dict = {}
        unresolved: deque = deque()
        for ticket, offsets in self._items:
            if not ticket._done:
                unresolved.append((ticket, offsets))
            elif ticket._error is None:
                merged.update(offsets)
        self._items = unresolved
        return merged

    def take_barrier(self) -> bool:
        """True once per cap-hit episode: the caller owes a checkpoint."""
        if self.barrier_needed:
            self.barrier_needed = False
            return True
        return False


MoneyTuple = tuple  # (currency: str, units: int, nanos: int)


def encode_money(currency: str, units: int, nanos: int) -> bytes:
    """Money submessage; zero units/nanos omitted (proto3 defaults)."""
    out = wire.encode_len(1, currency.encode())
    if units:
        out += wire.encode_int(2, units)
    if nanos:
        out += wire.encode_int(3, nanos)
    return out


def encode_order_result(
    order_id: str,
    tracking_id: str,
    shipping: MoneyTuple,
    lines: Sequence[tuple[str, int, MoneyTuple | None]],
) -> bytes:
    """The ONE wire-compatible OrderResult encoder.

    Both transports that emit OrderResult — checkout's Kafka publish and
    the gRPC edge's PlaceOrder response — go through here, so they can
    never disagree about quantities or costs on the same proto message.
    ``lines`` = (product_id, quantity, (currency, units, nanos) | None).
    """
    out = (
        wire.encode_len(1, order_id.encode())
        + wire.encode_len(2, tracking_id.encode())
        + wire.encode_len(3, encode_money(*shipping))
    )
    for pid, qty, cost in lines:
        cart = wire.encode_len(1, pid.encode()) + wire.encode_int(2, qty)
        item = wire.encode_len(1, cart)
        if cost is not None:
            item += wire.encode_len(2, encode_money(*cost))
        out += wire.encode_len(5, item)
    return out


def encode_placed_order(placed) -> bytes:
    """OrderResult bytes from a ``services.checkout.PlacedOrder``.

    Duck-typed (``.shipping``/``.items`` with Money-shaped members) so
    the runtime layer needs no services import. This is the ONE
    marshalling of PlacedOrder onto the wire — checkout's Kafka publish
    and the gRPC edge's PlaceOrder response both call it, so neither
    call site can drift back to e.g. encoding the grand total as
    shipping_cost.
    """
    return encode_order_result(
        placed.order_id,
        placed.tracking_id,
        (placed.shipping.currency, placed.shipping.units,
         placed.shipping.nanos),
        [
            (line.product_id, line.quantity,
             (line.cost.currency, line.cost.units, line.cost.nanos))
            for line in placed.items
        ],
    )


def encode_order(order: Order) -> bytes:
    """OrderResult from the compact :class:`Order` shape (simulator +
    tests — real producers carry exact lines via
    :func:`encode_order_result`; this synthesizes uniform quantities)."""
    units = int(order.shipping_cost_units)
    nanos = int((order.shipping_cost_units - units) * 1e9)
    qty = max(order.total_quantity // max(order.item_count, 1), 1)
    return encode_order_result(
        order.order_id,
        order.tracking_id,
        (order.currency, units, nanos),
        [(pid, qty, None) for pid in order.product_ids],
    )


class OrdersSource:
    """Kafka consumer for topic ``orders``.

    Mirrors the reference consumer contract: own group id, auto-commit
    offsets (/root/reference/src/accounting/Consumer.cs:77-80), value =
    OrderResult bytes. Yields ``(offset_by_partition, SpanRecord)``.

    Transport: ``confluent_kafka`` when installed (production images
    that ship it), else the built-in wire client
    (:class:`~.kafka_client.KafkaConsumer`) — real Kafka protocol over a
    real socket either way, so the leg never silently degrades to
    in-proc simulation.
    """

    TOPIC = "orders"
    RECONNECT_BACKOFF_S = 1.0

    QUARANTINE_KEEP = 32  # most-recent poison records retained for triage

    def __init__(self, bootstrap: str, group_id: str = "anomaly-detector"):
        self._bootstrap = bootstrap
        self._group_id = group_id
        self._pending_seek: dict[int, int] = {}
        # Epoch fencing (runtime.replication.EpochFence, set by the
        # daemon): every explicit commit is fence-checked and
        # epoch-tagged in the commit metadata string, so a resurrected
        # stale primary can neither commit past its successor nor boot
        # without discovering the successor's epoch
        # (:meth:`last_committed_epoch`).
        self.fence = None
        self.decode_failures = 0  # poison pills skipped (not crashed on)
        # Consumer-side quarantine, mirroring the producer-side
        # dead-letter discipline in services.kafka_bus: the poison
        # record's coordinates + error + payload head are kept (bounded)
        # so an operator can triage the bad producer, and last_error
        # feeds the daemon's last-error metric.
        self.quarantine: deque = deque(maxlen=self.QUARANTINE_KEEP)
        self.last_error: str | None = None
        self.last_error_ts: float = 0.0
        self._wire = None
        self._next_connect = 0.0  # wire-transport reconnect backoff
        try:
            from confluent_kafka import Consumer  # type: ignore

            self._consumer = Consumer(
                {
                    "bootstrap.servers": bootstrap,
                    "group.id": group_id,
                    "auto.offset.reset": "earliest",
                    "enable.auto.commit": True,
                }
            )
            self._consumer.subscribe([self.TOPIC])
        except ImportError:
            # Built-in wire transport, connected lazily on first poll:
            # the compose topology starts services in parallel, so a
            # broker that isn't up yet must mean "retry", not a boot
            # crash (confluent buffers the same way internally). A
            # malformed address is NOT transient — validate it now, so
            # a config error refuses to boot (mustMapEnv discipline)
            # instead of retrying silently forever.
            from .kafka_client import _parse_bootstrap

            _parse_bootstrap(bootstrap)
            self._consumer = None
            self._ensure_wire(raise_on_fail=False)

    def _ensure_wire(self, raise_on_fail: bool = False):
        import time as _time

        if self._wire is not None:
            return self._wire
        now = _time.monotonic()
        if now < self._next_connect:
            return None
        self._next_connect = now + self.RECONNECT_BACKOFF_S
        try:
            from .kafka_client import KafkaConsumer

            self._wire = KafkaConsumer(self._bootstrap, self._group_id, self.TOPIC)
            self._last_connect_error = None
        except Exception as e:  # noqa: BLE001 — any connect/handshake
            # fault (DNS, RST, wire-version mismatch) means "no broker
            # yet": back off and retry on the next poll.
            if raise_on_fail:
                raise
            # Log once per distinct failure — a silent forever-retry
            # would hide a permanently unreachable broker.
            msg = f"{type(e).__name__}: {e}"
            if msg != getattr(self, "_last_connect_error", None):
                import logging

                logging.getLogger(__name__).warning(
                    "Kafka connect to %s failed (%s); retrying every %.0fs",
                    self._bootstrap, msg, self.RECONNECT_BACKOFF_S,
                )
                self._last_connect_error = msg
            return None
        if self._pending_seek:
            for partition, offset in self._pending_seek.items():
                self._wire.seek(partition, offset)
        return self._wire

    def _drop_wire(self) -> None:
        if self._wire is not None:
            # Remember positions so a reconnect resumes where we were
            # even if the last auto-commit didn't land.
            self._pending_seek.update(self._wire.positions)
            try:
                self._wire.close()
            finally:
                self._wire = None

    def seek(self, offsets: dict[int, int]) -> None:
        """Seek to checkpointed next-to-read offsets (resume): sketch
        state corresponds to the checkpoint's offsets, which win over
        broker-committed ones. Applied now if connected, and re-applied
        on every (re)connect."""
        offsets = {int(p): int(o) for p, o in offsets.items()}
        self._pending_seek.update(offsets)
        if self._wire is not None:
            for partition, offset in offsets.items():
                self._wire.seek(partition, offset)
        elif self._consumer is not None:  # pragma: no cover - confluent
            from confluent_kafka import TopicPartition  # type: ignore

            self._consumer.assign(
                [
                    TopicPartition(self.TOPIC, p, o)
                    for p, o in offsets.items()
                ]
            )

    def poll(
        self, timeout_s: float = 0.1
    ) -> Iterator[tuple[dict, SpanRecord | None]]:
        """Yield ``(offsets, record)``; ``record`` is None for a skipped
        message (tombstone or undecodable poison pill) whose offset must
        STILL advance — otherwise a pill at a partition tail is never
        committed past and replays (and re-logs) on every restart.

        Next-offset semantics (Kafka committed-offset convention): a
        checkpoint taken after a message seeks *past* it on resume, so
        nothing is double-counted into the CMS.
        """
        if self._consumer is None:
            wire = self._ensure_wire()
            if wire is None:
                return  # broker unreachable: retry next poll
            try:
                msgs = wire.poll(max_wait_ms=int(timeout_s * 1000))
            except Exception:
                # Transient transport failure (broker restart, half-open
                # socket): drop the connection and reconnect with
                # backoff instead of killing the daemon loop.
                self._drop_wire()
                return
            for msg in msgs:
                record = (
                    None if msg.value is None
                    else self._decode(msg.value, msg.partition, msg.offset)
                )
                yield {msg.partition: msg.offset + 1}, record
            return
        msg = self._consumer.poll(timeout_s)  # pragma: no cover - confluent
        if msg is None or msg.error():
            return
        record = (
            None if msg.value() is None
            else self._decode(msg.value(), msg.partition(), msg.offset())
        )
        yield {msg.partition(): msg.offset() + 1}, record

    def poll_batch(
        self, timeout_s: float = 0.1
    ) -> tuple[dict, list[SpanRecord]]:
        """One poll → (merged next-offsets, decoded records).

        The batch shape the parallel ingest engine wants: the daemon's
        pump hands the whole poll to ``IngestPool.submit_records`` so
        the Kafka leg shares the pool's one-tensorize-per-flush
        amortization instead of a per-record pipeline submit (which
        took the pipeline lock once per message). Tombstones and
        quarantined poison pills still advance their offsets.
        """
        offsets: dict = {}
        records: list[SpanRecord] = []
        for off, rec in self.poll(timeout_s):
            offsets.update(off)
            if rec is not None:
                records.append(rec)
        return offsets, records

    def _decode(self, value: bytes, partition: int, offset: int):
        """Decode one message, treating a malformed payload as a skip.

        A bad producer payload must not be a poison pill: the transport
        try in :meth:`poll` guards the socket, not the decode, and
        auto-commit means a crash here would skip the message *silently*
        after restart — crash plus data loss. Instead: log, count,
        continue (the reference consumers do the same — a deser error in
        the Kotlin consumer logs and polls on, main.kt:64).
        """
        try:
            return order_to_record(decode_order(value))
        except Exception as e:
            # Deliberately broad: a wrong-schema payload that parses as
            # valid wire format surfaces as TypeError/AttributeError
            # (scan_fields returns an int where bytes were expected),
            # not WireError — and ANY decode failure is the same poison
            # pill from the consumer's point of view.
            import time as _time

            self.decode_failures += 1
            self.last_error = f"{type(e).__name__}: {e}"
            self.last_error_ts = _time.time()
            self.quarantine.append(
                (partition, offset, type(e).__name__, bytes(value[:64]))
            )
            import logging

            logging.getLogger(__name__).warning(
                "orders[%s@%s]: undecodable payload quarantined (%s); "
                "%d total", partition, offset, self.last_error,
                self.decode_failures,
            )
            return None

    def commit(self, offsets: dict[int, int], epoch: int = 0) -> None:
        """Epoch-tagged offset commit (fence-guarded).

        The commit metadata string carries ``{"epoch": N}`` — durable
        fencing evidence beside the offsets themselves, readable by any
        later consumer via OFFSET_FETCH. The fence check runs FIRST: a
        process that has observed a newer epoch must not write, however
        briefly (``checkpoint.StaleEpochError``). Raises on transport
        failure too — the caller (a supervised step) owns the retry.
        """
        if self.fence is not None:
            self.fence.check(path="kafka-offset-commit")
        offsets = {int(p): int(o) for p, o in offsets.items()}
        if not offsets:
            return
        import json as _json

        tag = _json.dumps({"epoch": int(epoch)})
        if self._consumer is not None:  # pragma: no cover - confluent
            from confluent_kafka import TopicPartition  # type: ignore

            try:
                # metadata kwarg exists on confluent-kafka >= 1.9 —
                # the epoch tag must ride on REAL Kafka too, or the
                # broker-witness fencing leg only exists against the
                # in-repo broker.
                tps = [
                    TopicPartition(self.TOPIC, p, o, metadata=tag)
                    for p, o in offsets.items()
                ]
            except TypeError:  # ancient client: commit untagged
                tps = [
                    TopicPartition(self.TOPIC, p, o)
                    for p, o in offsets.items()
                ]
            self._consumer.commit(offsets=tps, asynchronous=False)
            return
        wire_c = self._ensure_wire(raise_on_fail=True)
        if wire_c is None:
            raise ConnectionError("Kafka broker unreachable for commit")
        wire_c.commit(offsets, metadata=tag)

    def last_committed_epoch(self) -> int:
        """Largest epoch tag on the group's committed offsets (0 when
        untagged/unreachable): the boot-time fencing probe a
        resurrected primary runs before its first write."""
        import json as _json

        def parse(meta: str | None) -> int:
            if not meta:
                return 0
            try:
                return int(_json.loads(meta).get("epoch", 0))
            except (ValueError, TypeError):
                return 0

        try:
            if self._consumer is not None:  # pragma: no cover - confluent
                from confluent_kafka import TopicPartition  # type: ignore

                tps = self._consumer.committed(
                    [TopicPartition(self.TOPIC, p) for p in range(8)],
                    timeout=5.0,
                )
                return max(
                    (parse(getattr(tp, "metadata", None)) for tp in tps),
                    default=0,
                )
            wire_c = self._ensure_wire(raise_on_fail=False)
            if wire_c is None:
                return 0
            return max(
                (
                    parse(meta)
                    for _p, (_off, meta) in wire_c.committed_meta().items()
                ),
                default=0,
            )
        except Exception:  # noqa: BLE001 — fencing evidence is
            # best-effort here; the checkpoint + frame paths still fence
            return 0

    def close(self) -> None:
        if self._wire is not None:
            self._wire.close()
            self._wire = None
        elif self._consumer is not None:  # pragma: no cover
            self._consumer.close()
