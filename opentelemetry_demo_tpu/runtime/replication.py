"""Hot-standby replication: epoch-fenced failover with mergeable-sketch
anti-entropy.

The crash-safe (checkpoint) and overload-safe (backpressure) detector
is still one process: a host loss costs the cold-restart window plus
Kafka replay — exactly the blind window a production observability
sidecar must not have. The paper's kernel choice makes a warm standby
cheap: HLL registers and CMS counters are **commutative monoids**
(``ops.hll.hll_merge`` = elementwise max, ``ops.cms.cms_merge`` =
elementwise add), so replica state ships asynchronously and reconciles
by merge — no ordering, no dedup protocol, the same property the ICI
collectives in ``parallel/`` exploit across chips, here exploited
across *processes*.

Topology: the PRIMARY listens (``ANOMALY_REPLICATION_PORT``); each
STANDBY dials it (``ANOMALY_REPLICATION_TARGET``) and receives a
full-snapshot bootstrap followed by periodic deltas. Messages are
length-prefixed (4-byte big-endian) protobuf-style envelopes built from
``runtime.wire``'s encoding helpers — the same wire discipline as the
Kafka and OTLP seams — and every SNAPSHOT/DELTA payload is ONE verified
columnar frame (``runtime.frame``: magic, version, schema hash,
per-column CRC32C, trailer checksum — the same bytes checkpoints write
to disk and the ingest pool moves to the device feed). The standby
VERIFIES before it merges: a payload failing its checksums is counted
(``anomaly_frame_corrupt_total{hop="replication"}``), quarantined, and
never applied — the ACK of the unchanged ``applied_seq`` doubles as the
NACK that makes the primary reship against the retained base, so a
flipped bit on the link costs one retransmit instead of silently
poisoning sketch state. A corrupt frame still counts as LIVENESS
(``last_frame_t``), so a lossy-but-alive link never starves the
promotion watchdog into split-brain. Un-upgraded peers that still ship
the pre-frame npz payload ("v0") are accepted through
``frame.decode_arrays``'s sniffing shim — a rolling upgrade never
bricks replication mid-failover.

Delta algebra — why a lossy link still converges bit-exactly:

- ``hll_bank`` ships FULL every delta and merges by elementwise max
  (``hll_merge``: idempotent + commutative — any subset of deltas in
  any order, then any later one, equals the primary's registers).
  One caveat the monoid does not cover: window ROTATION resets HLL
  banks, and max can never lower a register. The primary therefore
  checks monotonicity against the peer's acked base and tags the rare
  rotation-spanning frame ``hll_monotone: false`` — the standby
  replaces instead of merging for exactly that frame (the two are
  identical whenever no rotation intervened, because the frame always
  carries the full registers).
- ``cms_bank`` ships as an AGGREGATE delta against the last **acked**
  base: ``delta = current − state_at_last_ack``. If N deltas vanish
  into a blackhole, the primary's base never advances, so the first
  delta through after the partition carries the sum of everything
  missed — one ``cms_merge`` (add) and the standby's counters equal
  the primary's exactly (rotation clears ride through as negative
  delta entries). No replay, no journal.
- Everything else (EWMA means/vars, CUSUM accumulators, window
  counters, ``step_idx``) is replace-latest, tagged by sequence
  number: during flow it lags by at most one replication interval;
  at quiescence (a final delta after load stops) it is bit-identical.
  That bound is the documented EWMA tolerance the anti-entropy test
  asserts.

Epoch fencing (split-brain prevention): every frame, checkpoint and
Kafka offset commit carries a monotonically increasing **epoch**. A
standby promotes by bumping it. A resurrected stale primary is fenced
three ways: replication frames at an old epoch are answered FENCED
(never applied), checkpoint saves refuse when the on-disk snapshot
carries a newer epoch (``checkpoint.StaleEpochError``), and offset
commits are epoch-tagged + fence-guarded (``kafka_orders``). The
:class:`EpochFence` is the process-local authority: it remembers the
largest epoch seen on any channel and refuses writes the moment it
exceeds its own.

Protocol (all frames carry the sender's epoch):

==========  ===========================================================
HELLO       standby → primary: standby id, applied seq, config
            fingerprint. Primary resumes with deltas when it still
            holds that standby's acked base; otherwise snapshots.
SNAPSHOT    primary → standby: full state arrays + meta; replaces
            everything, becomes the acked base.
DELTA       primary → standby: hll full / cms aggregate-delta /
            latest block, tagged (base_seq, seq). Applied only when
            base_seq == the standby's applied seq.
ACK         standby → primary: applied seq. Advances the primary's
            base only when it matches the last ship.
FENCED      standby → primary: your epoch is old; carries the newer
            one. The primary's fence observes it and every subsequent
            guarded write raises :class:`checkpoint.StaleEpochError`.
==========  ===========================================================

``tests/test_replication.py`` is the proof: a SIGKILLed primary under
live Kafka + OTLP load fails over with offset continuity, a blackholed
standby converges bit-identically by merge, and a stale primary is
rejected on all three write paths.
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time
import uuid
from collections import deque
from typing import Callable

import numpy as np

from . import wire
from . import frame as frame_fmt
from .checkpoint import StaleEpochError

log = logging.getLogger(__name__)

# Roles (the daemon's replication state machine; anomaly_role metric).
ROLE_PRIMARY = "primary"
ROLE_STANDBY = "standby"
ROLE_PROMOTING = "promoting"
# A fenced ex-primary: it discovered a newer epoch, stopped all writes,
# and awaits operator action — visibly, not as a silent zombie.
ROLE_FENCED = "fenced"

# Frame types.
HELLO = 1
SNAPSHOT = 2
DELTA = 3
ACK = 4
FENCED = 5

# Frame fields (protobuf-style numbers over runtime.wire helpers).
_F_TYPE = 1
_F_EPOCH = 2
_F_SEQ = 3
_F_BASE_SEQ = 4
_F_ARRAYS = 5  # ONE columnar frame (runtime.frame); legacy peers: npz
_F_META = 6  # JSON bytes
# Trailing envelope checksum: CRC32C over every preceding body byte,
# appended as a fixed64 (low 32 bits used). The columnar payload
# already self-verifies, but the ENVELOPE — type, epoch, seq, meta —
# did not, and a flipped bit in an ACK's epoch varint could fence a
# healthy primary (the one corruption that causes a ROLE regression
# rather than a bad merge). Presence is sniffed positionally (the
# field is always last), so envelopes from un-upgraded peers that
# never append it still decode — and a frame whose envelope CRC fails
# is SKIPPED (counted, liveness still credited), not a session kill:
# the length-prefixed stream is still aligned, only this frame lied.
_F_BODY_CRC = 7
_CRC_TAG_BYTE = wire.encode_tag(_F_BODY_CRC, 1)[0]  # fixed64 tag
_CRC_FIELD_LEN = 9  # 1 tag byte + 8 value bytes

# State-key merge classes (DetectorState fields). HLL merges by max
# (idempotent), CMS by add (aggregate delta vs acked base); the rest is
# replace-latest — see the module docstring's delta algebra.
MAX_KEYS = ("hll_bank",)
ADD_KEYS = ("cms_bank",)

_MAX_FRAME_BYTES = 256 << 20  # corrupt length prefix guard


class ReplicationError(RuntimeError):
    """Transport/protocol failure on the replication link."""


class EnvelopeCorrupt(Exception):
    """A received envelope failed its trailing CRC: the frame is a lie
    but the length-prefixed stream is still aligned — receivers SKIP
    the frame (count + keep the session) instead of reconnecting.
    Deliberately neither a ReplicationError nor a ValueError so the
    session-fatal catch paths never swallow it."""


class EpochFence:
    """Process-local fencing authority.

    ``epoch`` is this process's own epoch; ``observed`` is the largest
    epoch seen on ANY channel (replication frames, checkpoint meta at
    boot, broker commit metadata). The invariant every guarded write
    relies on: once ``observed > epoch`` the process is stale and
    :meth:`check` raises until an explicit :meth:`bump` (promotion) or
    operator restart."""

    def __init__(self, epoch: int = 0):
        self._lock = threading.Lock()
        self.epoch = int(epoch)
        self.observed = int(epoch)
        self.fenced_writes = 0
        # Per-path rejection counts (checkpoint / offsets / …): the
        # split-brain audit trail the daemon exports as
        # anomaly_replication_fenced_total{path=} — a stale primary
        # hammering its checkpoint cadence must show up on the panel,
        # not just in its own logs.
        self.fenced_by_path: dict[str, int] = {}

    def observe(self, epoch: int) -> None:
        """Record fencing evidence from any channel."""
        with self._lock:
            if epoch > self.observed:
                self.observed = int(epoch)

    def stale(self) -> bool:
        with self._lock:
            return self.observed > self.epoch

    def check(self, path: str = "write") -> None:
        """Raise :class:`checkpoint.StaleEpochError` when stale."""
        with self._lock:
            if self.observed > self.epoch:
                self.fenced_writes += 1
                self.fenced_by_path[path] = (
                    self.fenced_by_path.get(path, 0) + 1
                )
                raise StaleEpochError(
                    f"{path} fenced: epoch {self.epoch} superseded by "
                    f"{self.observed}"
                )

    def bump(self) -> int:
        """Promotion: claim an epoch above everything ever observed."""
        with self._lock:
            self.epoch = max(self.epoch, self.observed) + 1
            self.observed = self.epoch
            return self.epoch


# -- framing -----------------------------------------------------------


def encode_frame(
    ftype: int,
    epoch: int,
    seq: int = 0,
    base_seq: int = 0,
    arrays: dict[str, np.ndarray] | None = None,
    meta: dict | None = None,
) -> bytes:
    body = wire.encode_int(_F_TYPE, ftype) + wire.encode_int(_F_EPOCH, epoch)
    if seq:
        body += wire.encode_int(_F_SEQ, seq)
    if base_seq:
        body += wire.encode_int(_F_BASE_SEQ, base_seq)
    if arrays:
        # The ONE columnar frame format (runtime.frame): self-describing
        # dtypes/shapes, per-column CRC32C + trailer checksum — the
        # standby VERIFIES before it merges (a flipped bit on this link
        # used to merge straight into sketch state). Uncompressed:
        # deltas are mostly small ints and the TCP link is local/rack-
        # scale, CPU beats wire here.
        body += wire.encode_len(_F_ARRAYS, frame_fmt.encode(arrays))
    if meta is not None:
        body += wire.encode_len(_F_META, json.dumps(meta).encode())
    body += wire.encode_fixed64(_F_BODY_CRC, frame_fmt.crc32c(body))
    return struct.pack(">I", len(body)) + body


def decode_frame(body: bytes) -> dict:
    """Protocol fields only — the ARRAYS payload stays RAW bytes.

    Deferring the columnar decode to the apply step is deliberate: a
    frame that ARRIVES but fails verification must still count as
    liveness (the primary is alive, the bytes are bad), so the receive
    loop touches the payload only after stamping ``last_frame_t`` —
    otherwise a corrupting link would starve the promotion watchdog
    into a split-brain promotion against a live primary."""
    # Positional probe for the trailing CRC field: the tag byte at -9
    # AND four zero bytes at the tail (the fixed64's unused high half,
    # always zeroed by the writer). The zero-tail requirement is what
    # tells a REAL CRC field from a legacy peer's coincidence — a
    # pre-CRC envelope ends in JSON text or varint bytes, neither of
    # which produces four NULs, so a legacy HELLO whose meta JSON
    # happens to put a '9' (0x39, the tag byte) 9 bytes from the end
    # is not misread as a failing CRC and dropped forever.
    probed = (
        len(body) >= _CRC_FIELD_LEN
        and body[-_CRC_FIELD_LEN] == _CRC_TAG_BYTE
        and body[-4:] == b"\0\0\0\0"
    )
    if probed:
        # Full 64-bit compare (the high half must BE zero — masking it
        # would leave those four bytes writable by line noise), and a
        # mismatch is corrupt with NO further sniffing: deciding by
        # what the scanner sees instead would let a single flip that
        # makes a length field absorb the CRC field downgrade the
        # envelope to "legacy, unverified".
        stored = int.from_bytes(body[-8:], "little")
        if frame_fmt.crc32c(body[: -_CRC_FIELD_LEN]) != stored:
            raise EnvelopeCorrupt("replication envelope CRC mismatch")
    f = wire.scan_fields(body)
    if not probed and _F_BODY_CRC in f:
        # The scanner sees a CRC field the positional probe didn't
        # (displaced, or its zero tail was overwritten): the envelope
        # claims a checksum it cannot cash.
        raise EnvelopeCorrupt("envelope CRC field displaced")

    def _int(no: int) -> int:
        v = wire.first(f, no, 0)
        if not isinstance(v, int):
            # A rewritten tag flipped the field's wire type: acting on
            # it (an epoch compared, a seq acked) would be acting on
            # line noise.
            raise EnvelopeCorrupt(f"envelope field {no} wrong type")
        return v

    meta = wire.first(f, _F_META)
    arrays = wire.first(f, _F_ARRAYS, b"")
    if meta is not None and not isinstance(meta, bytes):
        raise EnvelopeCorrupt("envelope meta field wrong type")
    if not isinstance(arrays, bytes):
        raise EnvelopeCorrupt("envelope arrays field wrong type")
    return {
        "type": _int(_F_TYPE),
        "epoch": _int(_F_EPOCH),
        "seq": _int(_F_SEQ),
        "base_seq": _int(_F_BASE_SEQ),
        "arrays": arrays,
        "meta": json.loads(meta.decode()) if meta else {},
    }


def decode_arrays(blob: bytes) -> dict[str, np.ndarray]:
    """Verify + decode an ARRAYS payload: a current frame, or — the
    rolling-upgrade shim — a pre-frame npz blob from an un-upgraded
    peer ("v0"). Raises :class:`frame.FrameError` when the bytes fail
    verification; callers quarantine instead of merging."""
    return frame_fmt.decode_arrays(blob)


def _recv_frame(sock: socket.socket) -> dict | None:
    """One length-prefixed frame; None on clean EOF at a boundary.

    A ``socket.timeout`` may surface ONLY before the first header byte
    ("no frame yet"); once any byte of a frame has been read, the
    stream is committed and the remainder is awaited (bounded) — the
    alternative, surrendering mid-frame, would desync the
    length-prefixed stream and make the next read interpret body bytes
    as a length prefix."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length > _MAX_FRAME_BYTES:
        raise ReplicationError(f"frame length {length} exceeds cap")
    body = _recv_exact(sock, length, mid_frame=True)
    if body is None:
        raise ReplicationError("connection died mid-frame")
    try:
        return decode_frame(body)
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        # Corrupted PROTOCOL fields (a bit flip in the tag/meta region
        # rather than the checksummed columnar payload): the stream can
        # no longer be trusted frame-aligned — end the session cleanly
        # and let the reconnect path resume, instead of letting a
        # WireError kill the thread.
        raise ReplicationError(f"undecodable frame: {e}") from e


def _recv_exact(
    sock: socket.socket, n: int, mid_frame: bool = False
) -> bytes | None:
    """Read exactly ``n`` bytes. None on clean EOF at a boundary.

    ``socket.timeout`` propagates only at a true frame boundary
    (nothing read yet, ``mid_frame`` False); once committed to a frame
    — partial buffer, or the caller says the length prefix already
    arrived — timeouts keep reading under a 30 s stall bound."""
    buf = b""
    deadline = None
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if not buf and not mid_frame:
                raise  # frame boundary: genuinely nothing to read
            now = time.monotonic()
            if deadline is None:
                deadline = now + 30.0
            if now > deadline:
                raise ReplicationError(
                    "peer stalled mid-frame"
                ) from None
            continue
        if not chunk:
            if buf or mid_frame:
                raise ReplicationError("connection died mid-frame")
            return None  # clean EOF at a frame boundary
        buf += chunk
    return buf


# -- primary side ------------------------------------------------------


class _PeerBase:
    """Per-standby acked base: the state the peer has confirmed.

    Retained ACROSS sessions (keyed by the standby's stable id) so a
    reconnecting standby that merely missed deltas resumes by merge
    instead of paying a full snapshot — the anti-entropy path.
    ``pending`` keeps the last few shipped snapshots by seq: shipping
    is pipelined (the primary does not stall on acks), so an ack
    normally lands one or two ships behind the latest and must still
    be able to advance the base to the exact state it confirmed."""

    PENDING_KEEP = 8

    __slots__ = ("arrays", "seq", "pending", "shipped_seq", "last_used")

    def __init__(self):
        self.arrays: dict[str, np.ndarray] | None = None
        self.seq = -1
        self.pending: dict[int, tuple[dict[str, np.ndarray], float]] = {}
        self.shipped_seq = -1
        self.last_used = 0.0

    def record_ship(self, seq: int, arrays: dict[str, np.ndarray]) -> None:
        self.pending[seq] = (arrays, time.monotonic())
        self.shipped_seq = seq
        while len(self.pending) > self.PENDING_KEEP:
            del self.pending[min(self.pending)]


class ReplicationPrimary:
    """Primary-side listener: snapshot bootstrap + delta shipping.

    ``snapshot_fn()`` → ``(arrays, meta)``: the CURRENT full state as
    host numpy arrays plus the meta block (offsets — confirmed only,
    the PR-3 deferred-confirmation rule — service names, clock, config
    fingerprint). It must be safe to call from this module's session
    threads (the daemon snapshots under the pipeline's dispatch lock).
    """

    MAX_PEERS = 4  # retained acked bases (LRU beyond this)

    def __init__(
        self,
        snapshot_fn: Callable[[], tuple[dict, dict]],
        fence: EpochFence,
        host: str = "127.0.0.1",
        port: int = 0,
        interval_s: float = 1.0,
        on_fenced: Callable[[int], None] | None = None,
    ):
        self.snapshot_fn = snapshot_fn
        self.fence = fence
        self.interval_s = interval_s
        self.on_fenced = on_fenced
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._peers: dict[str, _PeerBase] = {}
        self._peers_lock = threading.Lock()
        self._stop = False
        self._sessions: list[socket.socket] = []
        self._sessions_lock = threading.Lock()
        # Stats (the anomaly_replication_* exports + replbench).
        self.deltas_shipped = 0
        self.snapshots_shipped = 0
        self.acks_received = 0
        self.frames_corrupt = 0  # corrupt HELLO/ACK envelopes skipped
        self.fenced_events = 0
        self.last_ack_t: float = 0.0
        self.ack_lag_s: deque = deque(maxlen=1024)  # ship→ack round trips
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="replication-accept", daemon=True
        )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._acceptor.start()

    def alive(self) -> bool:
        return self._acceptor.is_alive() and not self._stop

    def stop(self) -> None:
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._sessions_lock:
            sessions, self._sessions = self._sessions, []
        for s in sessions:
            try:
                s.close()
            except OSError:
                pass
        self._acceptor.join(timeout=2.0)

    def kill(self) -> None:
        """Abrupt death (tests/replbench): RST every session, no FIN —
        what a SIGKILLed primary looks like from the standby."""
        self._stop = True
        with self._sessions_lock:
            sessions, self._sessions = self._sessions, []
        for s in sessions + [self._sock]:
            try:
                s.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    # -- session loop ---------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            with self._sessions_lock:
                self._sessions.append(conn)
            threading.Thread(
                target=self._session_guarded, args=(conn,),
                name="replication-session", daemon=True,
            ).start()

    def _session_guarded(self, conn: socket.socket) -> None:
        try:
            self._session(conn)
        except Exception as e:  # noqa: BLE001 — a session fault (incl.
            # a raising snapshot_fn) ends THIS session; the standby
            # reconnects and resumes from its acked base.
            log.warning("replication session crashed: %s", e)

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _peer(self, peer_id: str) -> _PeerBase:
        with self._peers_lock:
            peer = self._peers.get(peer_id)
            if peer is None:
                peer = self._peers[peer_id] = _PeerBase()
            peer.last_used = time.monotonic()
            while len(self._peers) > self.MAX_PEERS:
                oldest = min(self._peers, key=lambda k: self._peers[k].last_used)
                del self._peers[oldest]
        return peer

    def _session(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(max(self.interval_s, 0.05))
            hello = None
            try:
                hello = _recv_frame(conn)
            except EnvelopeCorrupt:
                # A corrupt HELLO: drop the session; the standby's
                # reconnect sends a fresh (hopefully clean) one.
                self.frames_corrupt += 1
                return
            except (socket.timeout, OSError, ReplicationError):
                return
            if hello is None or hello["type"] != HELLO:
                return
            if self._observe_peer_epoch(hello["epoch"]):
                # A peer already past our epoch: tell it nothing; we are
                # the stale side. (FENCED is the standby's reply shape;
                # a fenced primary simply stops shipping.)
                return
            peer_cfg = hello["meta"].get("config")
            if peer_cfg is not None and not self._config_compatible(peer_cfg):
                # A geometry-mismatched standby would replicate happily
                # and detonate only at promotion — the one moment there
                # is no other replica. Refuse loudly at attach instead.
                log.error(
                    "replication HELLO rejected: standby config %s does "
                    "not match primary's — fix the standby's detector "
                    "geometry before attaching", peer_cfg,
                )
                return
            peer_id = hello["meta"].get("standby_id", "anon")
            peer = self._peer(peer_id)
            applied = int(hello["meta"].get("applied_seq", -1))
            if peer.arrays is None or peer.seq != applied or applied < 0:
                # No resumable base for this standby: full bootstrap.
                # (A matching base means the standby merely missed
                # deltas — the next DELTA's aggregate vs that base IS
                # the anti-entropy merge, no snapshot needed.)
                if not self._ship_snapshot(conn, peer):
                    return
            # Steady state: drain responses for one interval, then ship
            # (drain-first so the bootstrap/resync ack lands before the
            # next ship decision — otherwise every interval without an
            # acked base would re-ship a full snapshot).
            t_ship = time.monotonic()
            while not self._stop:
                deadline = t_ship + self.interval_s
                while not self._stop:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    conn.settimeout(remaining)
                    try:
                        frame = _recv_frame(conn)
                    except socket.timeout:
                        break
                    except EnvelopeCorrupt:
                        # A corrupt ACK must neither kill the session
                        # nor — the real hazard — have its (possibly
                        # rewritten) epoch observed: skip exactly one
                        # frame, keep draining.
                        self.frames_corrupt += 1
                        continue
                    except (OSError, ReplicationError):
                        return
                    if frame is None:
                        return
                    if not self._handle_response(frame, peer, conn):
                        return
                if self._stop:
                    return
                t_ship = time.monotonic()
                if not self._ship_delta(conn, peer):
                    return
        finally:
            with self._sessions_lock:
                if conn in self._sessions:
                    self._sessions.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _config_compatible(self, peer_cfg) -> bool:
        """Compare the standby's config fingerprint against ours (the
        snapshot_fn meta's ``config``), normalized through JSON — the
        wire turns tuples into lists. An absent fingerprint on either
        side (bare-component tests, older peers) is accepted."""
        try:
            _arrays, meta = self.snapshot_fn()
        except Exception:  # noqa: BLE001 — can't snapshot now: let the
            return True  # session proceed; shipping will retry/fail
        ours = meta.get("config")
        if not ours or not peer_cfg:
            return True
        norm = lambda c: json.loads(json.dumps(c))  # noqa: E731
        return norm(ours) == norm(peer_cfg)

    def _observe_peer_epoch(self, epoch: int) -> bool:
        """Record a peer epoch; True (and fire on_fenced) when newer."""
        if epoch > self.fence.epoch:
            self.fence.observe(epoch)
            self.fenced_events += 1
            log.error(
                "replication peer at epoch %d > ours %d: we are fenced",
                epoch, self.fence.epoch,
            )
            if self.on_fenced is not None:
                try:
                    self.on_fenced(epoch)
                except Exception:  # noqa: BLE001 — callback must not
                    pass  # kill the session thread mid-teardown
            return True
        return False

    def _ship_snapshot(self, conn: socket.socket, peer: _PeerBase) -> bool:
        arrays, meta = self.snapshot_fn()
        seq = self._next_seq()
        try:
            conn.sendall(encode_frame(
                SNAPSHOT, self.fence.epoch, seq=seq, arrays=arrays, meta=meta
            ))
        except OSError:
            return False
        # A snapshot IS its own acked base candidate: the standby
        # replaces wholesale, so the ack rule below treats it like a
        # shipped delta.
        peer.record_ship(seq, arrays)
        self.snapshots_shipped += 1
        return True

    def _ship_delta(self, conn: socket.socket, peer: _PeerBase) -> bool:
        if peer.arrays is None:
            # Bootstrap not yet acked. Give in-flight snapshot ships a
            # few intervals of grace before re-shipping — a full-state
            # frame simply takes longer than a delta interval to apply
            # and ack, and re-snapshotting on every tick would churn
            # the link exactly when it is trying to catch up.
            in_flight = [t for _arr, t in peer.pending.values()]
            if in_flight and (
                time.monotonic() - max(in_flight) < 3 * self.interval_s
            ):
                return True  # wait for the ack; nothing shipped
            return self._ship_snapshot(conn, peer)
        arrays, meta = self.snapshot_fn()
        seq = self._next_seq()
        payload: dict[str, np.ndarray] = {}
        for key, cur in arrays.items():
            if key in ADD_KEYS:
                payload[key] = cur - peer.arrays[key]
            else:
                payload[key] = cur  # MAX_KEYS + replace-latest block
        # Rotation detection (see module docstring): max-merge is only
        # a valid reconciliation while registers are monotone vs the
        # peer's acked base; a window rotation lowers them, and that
        # frame must replace instead.
        meta = dict(meta)
        meta["hll_monotone"] = bool(all(
            (arrays[k] >= peer.arrays[k]).all()
            for k in MAX_KEYS if k in peer.arrays
        ))
        try:
            conn.sendall(encode_frame(
                DELTA, self.fence.epoch, seq=seq, base_seq=peer.seq,
                arrays=payload, meta=meta,
            ))
        except OSError:
            return False
        peer.record_ship(seq, arrays)
        self.deltas_shipped += 1
        return True

    def _handle_response(
        self, frame: dict, peer: _PeerBase, conn: socket.socket
    ) -> bool:
        if self._observe_peer_epoch(frame["epoch"]):
            return False
        if frame["type"] == FENCED:
            # Redundant with the epoch check, but a FENCED frame at an
            # equal epoch is protocol confusion worth ending the session
            # over.
            return False
        if frame["type"] != ACK:
            return True
        self.acks_received += 1
        self.last_ack_t = time.monotonic()
        acked = frame["seq"]
        hit = peer.pending.get(acked)
        if hit is not None:
            arrays, shipped_at = hit
            peer.arrays = arrays
            peer.seq = acked
            # Drop everything the ack supersedes (acks are monotone).
            for s in [s for s in peer.pending if s <= acked]:
                del peer.pending[s]
            self.ack_lag_s.append(time.monotonic() - shipped_at)
        elif acked == peer.seq:
            pass  # standby missed the ship; next delta reuses the base
        else:
            # An ack we can't map to a retained snapshot (older than
            # the pending window, or from before a primary restart):
            # resync with a full snapshot rather than guess.
            log.warning(
                "replication ack %d matches neither base %d nor any "
                "pending ship (last %d): full resync",
                acked, peer.seq, peer.shipped_seq,
            )
            peer.pending.clear()
            return self._ship_snapshot(conn, peer)
        return True

    # -- introspection --------------------------------------------------

    def lag_seconds(self) -> float:
        """Seconds since the last acked delta (0 before any ack —
        a just-started primary with no standby is not 'lagging')."""
        if not self.last_ack_t:
            return 0.0
        return max(time.monotonic() - self.last_ack_t, 0.0)

    def stats(self) -> dict:
        return {
            "deltas_shipped": self.deltas_shipped,
            "snapshots_shipped": self.snapshots_shipped,
            "acks_received": self.acks_received,
            "frames_corrupt": self.frames_corrupt,
            "fenced_events": self.fenced_events,
            "lag_s": self.lag_seconds(),
            "ack_lag_p99_ms": (
                float(np.percentile(np.asarray(self.ack_lag_s), 99) * 1e3)
                if self.ack_lag_s else None
            ),
        }


# -- standby side ------------------------------------------------------


class ReplicationStandby:
    """Standby-side client: bootstrap, apply, watchdog state.

    Maintains a host-numpy mirror of the primary's state (``arrays``)
    plus the latest meta block; the daemon promotes by device_put-ing
    the mirror into a live detector. Applying is pure monoid algebra —
    max for HLL, add for the CMS aggregate delta, replace for the
    latest block — so a standby that missed any number of deltas is
    correct again one frame after the link heals."""

    RECONNECT_BACKOFF_S = 0.5

    def __init__(
        self,
        target: str,
        fence: EpochFence,
        config_fingerprint: list | None = None,
        standby_id: str | None = None,
        silence_reconnect_s: float = 2.0,
    ):
        host, _, port = target.rpartition(":")
        self.addr = (host or "127.0.0.1", int(port))
        self.fence = fence
        self.config_fingerprint = config_fingerprint
        self.standby_id = standby_id or uuid.uuid4().hex
        # Session-level silence watchdog: a healthy primary ships every
        # interval, so a session that hears NOTHING for this long is a
        # half-open connection (the blackhole shape — the peer died
        # without an RST reaching us) and must be abandoned for a
        # reconnect. Distinct from the daemon's PROMOTION watchdog,
        # which keeps its own (longer) timeout on the same clock.
        self.silence_reconnect_s = silence_reconnect_s
        self.arrays: dict[str, np.ndarray] = {}
        self.meta: dict = {}
        self.applied_seq = -1
        self.deltas_applied = 0
        self.snapshots_applied = 0
        self.frames_rejected = 0  # base mismatch (would double-count)
        # Deltas refused for keyspace-generation drift (the eviction
        # plane recycled intern ids between our base and the frame):
        # refusal leaves applied_seq put, the stale ack triggers the
        # primary's full resync, and the snapshot adopts the new
        # generation wholesale — self-healing by the existing path.
        self.frames_generation_drift = 0
        # Frames whose columnar payload failed verification (corrupt
        # link / bit rot): quarantined — never merged — and the ACK
        # re-asserts our last GOOD position, so the primary reships
        # against the retained base. The daemon exports this as
        # anomaly_frame_corrupt_total{hop="replication"}.
        self.frames_corrupt = 0
        # Intact frames from a NEWER format version (upgrade-order
        # problem, not corruption — never quarantined).
        self.frames_version_skew = 0
        self.fenced_sent = 0
        self.last_frame_t: float = time.monotonic()
        self._have_state = threading.Event()
        self._lock = threading.Lock()
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="replication-standby", daemon=True
        )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stop

    def stop(self) -> None:
        self._stop = True
        self._thread.join(timeout=2.0)

    def retarget(self, target: str) -> None:
        """Re-point the mirror at a NEW primary (the ring-successor
        changed after an adoption/resize): end the session, drop the
        mirrored state — it belongs to the OLD peer's keyspace, and a
        merge against it would double-count — and dial the new
        address. ``applied_seq`` resets to -1, so the new primary
        leads with a full SNAPSHOT after HELLO and the mirror is
        correct again one frame after the dial lands."""
        self._stop = True
        if self._thread.ident is not None:
            self._thread.join(timeout=2.0)
        host, _, port = target.rpartition(":")
        with self._lock:
            self.addr = (host or "127.0.0.1", int(port))
            self.arrays = {}
            self.meta = {}
            self.applied_seq = -1
        self._have_state.clear()
        self.last_frame_t = time.monotonic()
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="replication-standby", daemon=True
        )
        self._thread.start()

    def wait_for_state(self, timeout: float = 10.0) -> bool:
        """Block until the first snapshot landed (tests/bootstrap)."""
        return self._have_state.wait(timeout)

    def seconds_since_frame(self) -> float:
        """The promotion watchdog's clock: time since ANY frame (or
        since start) — a quiet-but-alive primary still ships deltas
        every interval, so silence IS the death signal."""
        return time.monotonic() - self.last_frame_t

    def snapshot(self) -> tuple[dict[str, np.ndarray], dict]:
        """Mirror copy for promotion (and for a promoted standby's own
        ReplicationPrimary snapshot_fn until the live detector owns the
        state)."""
        with self._lock:
            return (
                {k: np.array(v, copy=True) for k, v in self.arrays.items()},
                dict(self.meta),
            )

    # -- client loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop:
            try:
                self._session()
            except Exception as e:  # noqa: BLE001 — the loop IS the
                # supervisor here: any transport/protocol fault becomes
                # a bounded-backoff reconnect, never a dead thread.
                log.debug("replication session ended: %s", e)
            if self._stop:
                return
            time.sleep(self.RECONNECT_BACKOFF_S)

    def _session(self) -> None:
        sock = socket.create_connection(self.addr, timeout=5.0)
        try:
            sock.sendall(encode_frame(
                HELLO, self.fence.epoch,
                meta={
                    "standby_id": self.standby_id,
                    "applied_seq": self.applied_seq,
                    "config": self.config_fingerprint,
                },
            ))
            sock.settimeout(min(1.0, self.silence_reconnect_s / 2))
            session_started = time.monotonic()
            while not self._stop:
                try:
                    frame = _recv_frame(sock)
                except EnvelopeCorrupt:
                    # A corrupt envelope still PROVES the primary is
                    # alive and framing correctly — credit liveness
                    # (else a lossy-but-alive link starves the
                    # promotion watchdog into split-brain), count, and
                    # skip exactly this frame. None of its fields —
                    # epoch included — may be acted on.
                    self.frames_corrupt += 1
                    self.last_frame_t = time.monotonic()
                    continue
                except socket.timeout:
                    quiet_since = max(self.last_frame_t, session_started)
                    if (
                        time.monotonic() - quiet_since
                        > self.silence_reconnect_s
                    ):
                        raise ReplicationError(
                            "session silent past the watchdog; "
                            "reconnecting"
                        ) from None
                    continue
                if frame is None:
                    return
                self.last_frame_t = time.monotonic()
                if frame["epoch"] < self.fence.epoch:
                    # Stale primary (we promoted past it, or saw a newer
                    # one): refuse the frame, teach it the epoch.
                    self.fenced_sent += 1
                    sock.sendall(encode_frame(FENCED, self.fence.epoch))
                    continue
                self.fence.observe(frame["epoch"])
                if frame["type"] == SNAPSHOT:
                    self._apply_snapshot(frame)
                elif frame["type"] == DELTA:
                    self._apply_delta(frame)
                else:
                    continue
                sock.sendall(encode_frame(
                    ACK, self.fence.epoch, seq=self.applied_seq
                ))
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _verified_arrays(self, frame: dict) -> dict[str, np.ndarray] | None:
        """Verify+decode a frame's columnar payload; None = quarantined.

        The corruption boundary: a payload that fails its checksums is
        counted, written aside (when ANOMALY_FRAME_QUARANTINE_DIR is
        set) and NEVER merged — the subsequent ACK of our unchanged
        ``applied_seq`` is the NACK that makes the primary reship
        against the retained base, so a clean retransmit converges
        without any extra protocol."""
        blob = frame["arrays"]
        try:
            return decode_arrays(blob)
        except frame_fmt.FrameVersionError as e:
            # An upgrade-order problem, NOT bad bytes (the frame is
            # intact — its version is simply outside our window):
            # never quarantined or counted as corruption. Not applied
            # either — the stale ACK tells the primary we are behind,
            # and the operator signal is this log + the skew counter,
            # not a "bad hardware" panel.
            self.frames_version_skew += 1
            log.error(
                "replication frame seq %d from a NEWER format (%s) — "
                "upgrade this standby; not applied",
                frame["seq"], e,
            )
            return None
        except frame_fmt.FrameError as e:
            self.frames_corrupt += 1
            path = frame_fmt.quarantine(blob, "replication")
            log.error(
                "replication frame seq %d failed verification (%s)%s — "
                "quarantined, not applied; acking last good seq %d",
                frame["seq"], e,
                f"; evidence at {path}" if path else "",
                self.applied_seq,
            )
            return None

    def _apply_snapshot(self, frame: dict) -> None:
        arrays = self._verified_arrays(frame)
        if arrays is None:
            return
        with self._lock:
            self.arrays = arrays
            self.meta = frame["meta"]
            self.applied_seq = frame["seq"]
        self.snapshots_applied += 1
        self._have_state.set()

    def _apply_delta(self, frame: dict) -> None:
        arrays = self._verified_arrays(frame)
        if arrays is None:
            return
        with self._lock:
            if frame["base_seq"] != self.applied_seq or not self.arrays:
                # Applying an add-delta against the wrong base would
                # double-count CMS rows; ack our real position instead
                # (the primary re-bases or resyncs).
                self.frames_rejected += 1
                return
            ours = int((self.meta or {}).get("generation") or 0)
            theirs = int(
                (frame["meta"] or {}).get("generation") or 0
            )
            if theirs != ours:
                # Keyspace generation drift: the primary's evictor
                # recycled intern ids since our mirror's base — a row-
                # wise merge could attribute an old key's registers to
                # the id's NEW owner. Refuse; the stale ack makes the
                # primary ship a full snapshot, which replaces
                # wholesale and adopts the new generation.
                self.frames_generation_drift += 1
                self.frames_rejected += 1
                return
            hll_monotone = frame["meta"].get("hll_monotone", True)
            for key, inc in arrays.items():
                if key in MAX_KEYS and hll_monotone:
                    # hll_merge: elementwise max (ops/hll.py:94) — the
                    # commutative-idempotent half of the monoid pair.
                    self.arrays[key] = np.maximum(self.arrays[key], inc)
                elif key in ADD_KEYS:
                    # cms_merge: elementwise add (ops/cms.py:301) over
                    # the aggregate delta vs OUR acked base (rotation
                    # clears arrive as negative entries).
                    self.arrays[key] = self.arrays[key] + inc
                else:
                    # Replace-latest block — and the rare rotation-
                    # spanning HLL frame (hll_monotone: false).
                    self.arrays[key] = inc
            self.meta = frame["meta"]
            self.applied_seq = frame["seq"]
        self.deltas_applied += 1

    def stats(self) -> dict:
        return {
            "deltas_applied": self.deltas_applied,
            "snapshots_applied": self.snapshots_applied,
            "frames_rejected": self.frames_rejected,
            "frames_generation_drift": self.frames_generation_drift,
            "frames_corrupt": self.frames_corrupt,
            "frames_version_skew": self.frames_version_skew,
            "fenced_sent": self.fenced_sent,
            "applied_seq": self.applied_seq,
            "seconds_since_frame": self.seconds_since_frame(),
        }
