"""Verdict provenance: the per-verdict evidence engine.

A flag used to be a bare ``(service, z, flagged)`` tuple; this module
turns it into an evidence bundle an operator (or the remediation
plane) can interrogate: which head fired, the head trajectories over
the last K harvested windows, the CMS heavy-hitter keys that drove the
window, the HLL cardinality estimate against its learned baseline, and
the trace ids — both the detector's own selftrace batch trace and the
flag-time exemplar shop traces — that deep-link the verdict into
Jaeger.

Design constraints the shape falls out of:

- **No extra device round trip for the trajectory.** Every harvested
  ``DetectorReport`` is already host numpy; ``observe_report`` rings
  the per-head columns (one global deque of compact rows, sliced
  per-service at flag time), so the K-window history costs an append,
  never a device_get.
- **Flag-time state comes from the dispatch-lock snapshot.** The EWMA
  baselines and CMS/HLL banks live on device; the pipeline fetches
  them ONCE per flagging batch under ``_dispatch_lock`` (flags are
  rare — the same discipline as the replication snapshot) and hands
  the arrays here. This module never touches the detector or a lock.
- **Bundles are plain JSON-able dicts with deterministic ids.** The
  id hashes (epoch, seq, service) through the same scalar splitmix64
  the selftrace ids use, so primary and replica — and a replay of the
  recorded stream — mint the SAME id for the same verdict. That is
  what lets remediation episodes and shadow refusals cite a bundle id
  that the replica's ``/query/explain`` can also resolve.
- **No ``runtime.frame`` import.** Bundle persistence through the
  retention ladder is history.py's job (the only frame consumer
  outside the live path); this module only builds dicts.

The ``HEAD_*`` / ``REASON_*`` constants below are the CLOSED evidence
vocabulary — the ``provenance-vocabulary`` staticcheck pass fences
every ``"head"``/``"reason"`` literal in runtime/ and the dashboards
to this table, so a typo'd head name fails the build instead of
minting an unqueryable label.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from ..ops.cms import cms_indices_np, cms_query_np
from ..ops.hashing import split_hi_lo_np, splitmix64_np
from ..ops.hll import hll_estimate_np
from .selftrace import splitmix64

# -- the closed evidence vocabulary -----------------------------------
# Head kinds: which detector head produced the verdict.
HEAD_EWMA_Z = "ewma-z"
HEAD_CUSUM = "cusum"
HEAD_CARDINALITY = "cardinality"

# Reasons: the per-signal vocabulary ``_capture_exemplars`` emits into
# anomaly events (and now into bundles) — one reason per flagged
# signal lane.
REASON_LATENCY = "latency"
REASON_ERROR_RATE = "error_rate"
REASON_THROUGHPUT = "throughput"
REASON_CARDINALITY = "cardinality"
REASON_CUSUM = "cusum"

# Reason → head: the three EWMA z lanes share one head; cardinality
# and CUSUM are their own. Closed mapping — an unknown reason maps to
# no head rather than a guessed one.
HEAD_FOR_REASON: dict[str, str] = {
    REASON_LATENCY: HEAD_EWMA_Z,
    REASON_ERROR_RATE: HEAD_EWMA_Z,
    REASON_THROUGHPUT: HEAD_EWMA_Z,
    REASON_CARDINALITY: HEAD_CARDINALITY,
    REASON_CUSUM: HEAD_CUSUM,
}

# Bundle schema version: bumped on any field-meaning change so history
# readers (and the collector pipeline downstream of the OTLP log
# export) can branch on it.
SCHEMA_VERSION = 1


def bundle_id(epoch: int, seq: int, service: int) -> str:
    """Deterministic 64-bit bundle id as 16 hex chars.

    Double-mixed so nearby (epoch, seq, service) triples don't share
    prefixes; pure function of the replicated coordinates, so every
    surface that sees the same verdict mints the same id."""
    return format(
        splitmix64(splitmix64((int(epoch) << 32) ^ int(seq)) ^ int(service)),
        "016x",
    )


def _row(arr, svc: int) -> list[float]:
    return [float(x) for x in np.asarray(arr)[svc]]


class ProvenanceEngine:
    """Builds evidence bundles at flag time.

    Owned by the daemon, fed by the pipeline: ``observe_report`` on
    every harvested report (any thread; internally locked), ``build``
    per flagged service (harvester thread, under the pipeline's query
    lock — cheap numpy only). The bundle RING lives in the pipeline
    beside the anomaly ring so it rides ``query_meta`` replication;
    this engine is stateless apart from the trajectory deque and the
    build-latency samples the daemon drains for the histogram.
    """

    def __init__(
        self,
        config,
        topk: int = 5,
        trajectory_windows: int = 16,
        epoch_fn: Callable[[], int] | None = None,
    ):
        self.config = config
        self.topk = max(int(topk), 1)
        self.trajectory_windows = max(int(trajectory_windows), 1)
        self._epoch_fn = epoch_fn
        self._traj: deque[dict] = deque(maxlen=self.trajectory_windows)
        self._lock = threading.Lock()
        # Build-latency samples (seconds), drained by the daemon into
        # anomaly_explain_latency_seconds; bounded so an unexported
        # burst can't grow without limit.
        self._build_s: deque[float] = deque(maxlen=1024)

    # -- trajectory ring ----------------------------------------------

    def observe_report(self, t_batch: float, report) -> None:
        """Ring one harvested report's head columns (all services).

        Fields are read defensively (``getattr`` with None) so a
        partial report — unit-test fakes carry only the lanes they
        exercise — rings what it has."""
        row = {"t": float(t_batch)}
        for name in ("lat_z", "err_z", "rate_z", "card_z", "card_est", "cusum"):
            val = getattr(report, name, None)
            if val is not None:
                row[name] = np.asarray(val)
        with self._lock:
            self._traj.append(row)

    def trajectory_for(self, svc: int) -> list[dict]:
        """The per-service slice of the ring, oldest first, JSON-able."""
        with self._lock:
            rows = list(self._traj)
        out = []
        for row in rows:
            ent: dict = {"t": row["t"]}
            for name in ("lat_z", "err_z", "rate_z", "card_z", "card_est", "cusum"):
                arr = row.get(name)
                if arr is None or svc >= arr.shape[0]:
                    continue
                ent[name] = [float(x) for x in np.atleast_1d(arr[svc])]
            out.append(ent)
        return out

    # -- flag-time assembly -------------------------------------------

    def build(
        self,
        *,
        t_batch: float,
        seq: int,
        service: int,
        label: str,
        signals: list[str],
        exemplars: list[str],
        state: dict | None,
        hh_candidates: list[int],
        trace_id: str | None,
    ) -> dict:
        """One flagged service → one evidence bundle (JSON-able dict).

        ``state`` is the dispatch-lock snapshot (host numpy arrays) or
        None when the fetch was skipped/failed — the bundle degrades to
        trajectory + signals rather than refusing to exist."""
        t0 = time.perf_counter()
        epoch = int(self._epoch_fn()) if self._epoch_fn is not None else 0
        cfg = self.config
        heads = sorted({
            HEAD_FOR_REASON[s] for s in signals if s in HEAD_FOR_REASON
        })
        bundle: dict = {
            "id": bundle_id(epoch, seq, service),
            "schema": SCHEMA_VERSION,
            "t": float(t_batch),
            "seq": int(seq),
            "epoch": epoch,
            "service": label,
            "service_id": int(service),
            "heads": heads,
            "signals": list(signals),
            "windows_s": [float(w) for w in cfg.windows_s],
            "taus_s": [float(x) for x in cfg.taus_s],
            "z_threshold": float(cfg.z_threshold),
            "trajectory": self.trajectory_for(int(service)),
            "exemplars": list(exemplars),
            "selftrace": trace_id,
        }
        if state is not None:
            try:
                self._attach_state(bundle, state, int(service), hh_candidates)
            except (KeyError, IndexError, ValueError):
                # A mismatched snapshot (mid-resize shapes) costs the
                # state block, not the bundle.
                pass
        with self._lock:
            self._build_s.append(time.perf_counter() - t0)
        return bundle

    def _attach_state(
        self, bundle: dict, state: dict, svc: int, cands: list[int]
    ) -> None:
        bundle["ewma"] = {
            "latency": {
                "mean": _row(state["lat_mean"], svc),
                "var": _row(state["lat_var"], svc),
            },
            "error_rate": {"mean": _row(state["err_mean"], svc)},
            "throughput": {
                "mean": _row(state["rate_mean"], svc),
                "var": _row(state["rate_var"], svc),
            },
        }
        cus = np.asarray(state["cusum"])[svc]
        thr = self.config.cusum_thresholds
        bundle["cusum"] = {
            "latency_up": float(cus[0]),
            "error_up": float(cus[1]),
            "rate_down": float(cus[2]),
            "thresholds": [float(x) for x in thr],
        }
        # Cardinality head evidence: live estimate per window vs the
        # learned EWMA baseline — the delta is the head's own signal.
        est = hll_estimate_np(np.asarray(state["hll_bank"])[:, 0])  # [W#, S]
        base = np.asarray(state["card_mean"])[svc]  # [W#]
        nw = min(est.shape[0], base.shape[0])
        if svc < est.shape[1]:
            bundle["cardinality"] = {
                "estimate": [float(est[w, svc]) for w in range(nw)],
                "baseline_mean": [float(base[w]) for w in range(nw)],
                "delta": [
                    float(est[w, svc]) - float(base[w]) for w in range(nw)
                ],
            }
        bundle["top_keys"] = self._topk_contributors(state, svc, cands)

    def _topk_contributors(
        self, state: dict, svc: int, cands: list[int]
    ) -> list[dict]:
        """Exact CMS point queries for the candidate keys — the SAME
        fold ``query.topk_heavy_hitters`` runs (key | svc<<32 →
        splitmix → rows), snapshotted into evidence at flag time so
        the bundle stays truthful after the window rolls."""
        if not cands:
            return []
        cur = np.asarray(state["cms_bank"])[:, 0]  # [W#, D, C]
        depth, width = cur.shape[-2], cur.shape[-1]
        span_total = np.asarray(state["span_total"])[:, 0]  # [W#]
        crc = np.asarray(cands, dtype=np.uint64)
        key = crc | (np.uint64(svc) << np.uint64(32))
        hi, lo = split_hi_lo_np(splitmix64_np(key))
        idx = cms_indices_np(hi, lo, depth, width)
        counts = cms_query_np(cur, idx)  # [W#, B]
        sel = counts[-1]  # longest window: the attribution horizon
        order = sorted(
            range(len(cands)), key=lambda i: (-int(sel[i]), int(crc[i]))
        )[: self.topk]
        denom = max(float(span_total[-1]), 1.0)
        return [
            {
                "attr_crc": f"0x{int(crc[i]):08x}",
                "count": int(sel[i]),
                "counts": [int(c) for c in counts[:, i]],
                "share": float(np.float32(int(sel[i]) / denom)),
            }
            for i in order
        ]

    # -- export helpers -----------------------------------------------

    def take_build_samples(self) -> list[float]:
        """Drain build-latency samples (seconds) for the histogram."""
        with self._lock:
            out = list(self._build_s)
            self._build_s.clear()
        return out


def log_doc(bundle: dict):
    """Bundle → LogDoc for ``otlp_export.encode_logs_request``.

    The body is the human sentence ("why was this flagged"); the
    machine-readable coordinates ride attributes so the collector
    pipeline can index/route without parsing the body. The selftrace
    batch trace id rides the record's trace_id field — the standard
    log↔trace correlation hop."""
    from ..telemetry.logstore import LogDoc

    heads = ",".join(bundle.get("heads") or [])
    signals = ",".join(bundle.get("signals") or [])
    attrs = {
        "anomaly.bundle_id": str(bundle.get("id")),
        "anomaly.heads": heads,
        "anomaly.signals": signals,
        "anomaly.seq": str(bundle.get("seq")),
        "anomaly.epoch": str(bundle.get("epoch")),
    }
    exemplars = bundle.get("exemplars") or []
    if exemplars:
        attrs["anomaly.exemplars"] = ",".join(str(x) for x in exemplars[:5])
    trace_id = bundle.get("selftrace")
    return LogDoc(
        ts=float(bundle.get("t") or 0.0),
        service=str(bundle.get("service")),
        severity="WARN",
        body=(
            f"anomaly flagged: service={bundle.get('service')} "
            f"heads={heads or 'none'} signals={signals or 'none'} "
            f"bundle={bundle.get('id')}"
        ),
        attrs=attrs,
        trace_id=bytes.fromhex(trace_id) if trace_id else None,
    )
