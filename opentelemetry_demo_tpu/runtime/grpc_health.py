"""grpc.health.v1 — ONE implementation for every server in the tree.

The reference registers the standard health service on each gRPC server
(/root/reference/src/checkout/main.go:223-224,
src/currency/src/server.cpp:92-102); here the gRPC shop edge and the
daemon's OTLP ingress both attach THIS module's handlers, and the
container probe (``runtime.health_probe``) shares its constants — the
protocol exists in exactly one place.

Raw-bytes handlers (no generated stubs): HealthCheckRequest{service=1},
HealthCheckResponse{status=1} with SERVING/NOT_SERVING.

Watch and thread budgets: a sync gRPC server pins one executor thread
per open server-stream, so unauthenticated Watch clients could starve
the pool (the OTLP ingress runs 4 workers). ``watcher_slots`` bounds
concurrent watchers; beyond it a Watch answers with the current status
and ENDS the stream — spec-legal (clients re-watch) and starvation-
proof, instead of silently queueing Export RPCs behind parked watchers.
"""

from __future__ import annotations

import threading
from typing import Iterable

from . import wire

SERVING = 1
NOT_SERVING = 2

CHECK_METHOD = "/grpc.health.v1.Health/Check"
WATCH_METHOD = "/grpc.health.v1.Health/Watch"


class HealthService:
    """Check/Watch handlers over a stop event + known-service set."""

    def __init__(
        self,
        known_services: Iterable[str],
        stop_event: threading.Event,
        watcher_slots: int = 2,
        component_status=None,
    ):
        self.known = set(known_services)
        self.stop_event = stop_event
        self._watchers = threading.Semaphore(max(watcher_slots, 0))
        # Optional per-service status hook (supervision.Supervisor.
        # health_status): consulted BEFORE the known-set rule so
        # supervised components answer their own SERVING/NOT_SERVING
        # (service names "anomaly.component.<name>"); it returns None
        # for names it doesn't own, falling back to server-wide status.
        self.component_status = component_status

    def _status_response(self, request: bytes) -> bytes | None:
        """Response bytes, or None for an unknown service name."""
        f = wire.scan_fields(request)
        raw = wire.first(f, 1, b"")
        service = raw.decode("utf-8", "replace") if isinstance(raw, bytes) else ""
        if service and self.component_status is not None:
            status = self.component_status(service)
            if status is not None:
                return wire.encode_int(1, status)
        if service and service not in self.known:
            return None
        status = NOT_SERVING if self.stop_event.is_set() else SERVING
        return wire.encode_int(1, status)

    # -- grpc handler callables ----------------------------------------

    def check(self, request: bytes, context) -> bytes:
        import grpc

        # Deliberately outside any application lock: health must answer
        # while the serving graph is busy — that is its whole job.
        resp = self._status_response(request)
        if resp is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
        return resp

    def watch(self, request: bytes, context):
        import grpc

        resp = self._status_response(request)
        if resp is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "unknown service")
            return
        yield resp
        if not self._watchers.acquire(blocking=False):
            # Slots exhausted: current status delivered, stream ends —
            # never park another executor thread.
            return
        try:
            # Stream the SERVING→NOT_SERVING transition at shutdown; a
            # cancelled/deadline-expired watcher exits the poll loop.
            while context.is_active() and not self.stop_event.wait(0.2):
                pass
            if context.is_active():
                yield wire.encode_int(1, NOT_SERVING)
        finally:
            self._watchers.release()

    def add_to_generic_handlers(self, grpc_module, method: str):
        """Method-path dispatch helper for GenericRpcHandler.service():
        returns the grpc method handler for ``method`` or None."""
        if method == CHECK_METHOD:
            return grpc_module.unary_unary_rpc_method_handler(
                self.check, request_deserializer=None,
                response_serializer=None,
            )
        if method == WATCH_METHOD:
            return grpc_module.unary_stream_rpc_method_handler(
                self.watch, request_deserializer=None,
                response_serializer=None,
            )
        return None
