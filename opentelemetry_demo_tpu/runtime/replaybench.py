"""History replay harness: recorded frames → the real pipeline, N×.

Detection-quality measurement (runtime.qualbench) was synthetic-only:
every TTD/FP number came from generated traffic. The time-travel tier
(runtime.history) turns the on-disk segment log into a REPLAY CORPUS —
with ``ANOMALY_HISTORY_SPANS=1`` the writer records every dispatched
span batch as a verified frame, and this module re-feeds those frames
through a fresh, REAL ``DetectorPipeline`` (same admission, same
tensorize/pack, same donated device step, same harvest) under
virtual-time clock injection: ``pump(t)`` gets each batch's RECORDED
timebase (the test_spine trick), so window rotation and EWMA dt replay
exactly while wall-clock runs as fast as the machine allows.

Two numbers come out, both in bench.py's artifact:

- ``replay_speedup`` — recorded virtual seconds per wall second of
  replay, gated ≥ the ``ANOMALY_HISTORY_REPLAY_RATE`` knob (10× on
  CI): regression-testing a day of recorded incidents must cost
  minutes, not a day.
- **bit-identical verdict pinning** — the replayed run's per-batch
  flag vectors must equal the recording run's exactly (the integer
  sketch monoids and the float head arithmetic are deterministic on a
  fixed platform; any divergence means the pipeline no longer treats
  recorded bytes like live bytes).

``measure_replay`` is self-contained for CI: it records a synthetic
incident (a paymentFailure-shaped error burst over clean warmup
traffic, the qualbench projection) into a temp store, then replays it.
Against a production log the same ``replay()`` entry point re-runs a
real recorded incident — every future detection head gets a backtest
for free. ``history_range_query_p99_ms`` (range reads over the
just-written ladder) rides along as the read path's cost number.
"""

from __future__ import annotations

import tempfile
import time

import numpy as np

from ..models.detector import DetectorConfig
from . import history
from .tensorize import SpanColumns

# CI-friendly geometry: the protocol (record → replay equivalence), not
# the kernels, is under test; qualbench owns quality numbers.
S = 8
B = 256
DT_S = 0.25
WARM_STEPS = 60
FAULT_STEPS = 60
FAULT_SVC = 5


def _replay_config() -> DetectorConfig:
    return DetectorConfig(num_services=S, hll_p=8, cms_width=256)


def _make_cols(rng, step: int, faulted: bool) -> SpanColumns:
    """One batch of shop-shaped traffic; past onset the faulted
    service takes a 25% error burst plus a latency step — the
    paymentFailure projection qualbench measures TTD on."""
    svc = rng.integers(0, S, size=B).astype(np.int32)
    lat = rng.gamma(4.0, 250.0, size=B).astype(np.float32)
    err = (rng.random(B) < 0.01).astype(np.float32)
    trace = (
        rng.integers(0, 64, size=B, dtype=np.uint64) * np.uint64(2654435761)
        + np.uint64(1)
    )
    attr = rng.zipf(1.5, size=B).astype(np.uint64)
    if faulted:
        hit = (rng.random(B) < 0.25).astype(np.float32)
        err = np.where(svc == FAULT_SVC, np.maximum(err, hit), err)
        lat = np.where(svc == FAULT_SVC, lat * 3.0, lat).astype(np.float32)
    return SpanColumns(
        svc=svc, lat_us=lat, is_error=err, trace_key=trace, attr_crc=attr
    )


def _make_pipeline(collect: dict) -> tuple[AnomalyDetector, DetectorPipeline]:
    # Delegates to the ONE shared builder (runtime.shadow) so the
    # counterfactual pre-flight verifier and this harness can never
    # drift: same pipeline construction, same verdict keying —
    # bit-identity between shadow and replaybench holds by
    # construction, and the mitigbench shadow leg pins it.
    from .shadow import build_shadow_pipeline

    return build_shadow_pipeline(_replay_config(), B, collect)


def record_incident(
    directory: str,
    seed: int = 0,
    warm_steps: int = WARM_STEPS,
    fault_steps: int = FAULT_STEPS,
) -> dict:
    """Drive the incident through a REAL pipeline while the history
    writer records both the span corpus and the bank ladder; returns
    the recording run's verdicts keyed by batch timebase."""
    rng = np.random.default_rng(seed)
    verdicts: dict = {}
    det, pipe = _make_pipeline(verdicts)
    store = history.HistoryStore(directory, retention_s=(86400.0, 86400.0))

    def snapshot():
        with pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in det.state._asdict().items()
            }
            clock_t_prev = det.clock._t_prev
        return arrays, {
            "clock_t_prev": clock_t_prev,
            "service_names": pipe.tensorizer.service_names,
            "config": list(det.config._replace(sketch_impl=None)),
            "query": pipe.query_meta(),
        }

    writer = history.HistoryWriter(
        store, snapshot, rungs=(1.0, 60.0), capture_spans=True,
        span_queue_max=4 * (warm_steps + fault_steps),
    )
    pipe.history_capture = writer.capture
    wall0 = time.time()
    for step in range(warm_steps + fault_steps):
        t = step * DT_S
        pipe.submit_columns(_make_cols(rng, step, step >= warm_steps))
        pipe.pump(t)
        writer.tick(now=wall0 + t)
    pipe.drain()
    writer.close()
    pipe.close()
    return verdicts


def replay(directory: str) -> tuple[dict, float, float, int]:
    """Re-feed the recorded span frames through a fresh real pipeline
    at max speed under the RECORDED virtual clock; returns
    (verdicts, virtual_span_s, wall_s, batches)."""
    store = history.HistoryStore(directory)
    reader = history.HistoryReader(store, rungs=(1.0, 60.0))
    # Compile off the clock: a throwaway detector at the same geometry
    # and batch width populates the XLA executable cache, so the timed
    # loop measures REPLAY, not the one-time jit (the repo's
    # warmup-before-timing rule; state is untouched — this detector is
    # discarded).
    warm_det, warm_pipe = _make_pipeline({})
    warm_pipe.submit_columns(_make_cols(np.random.default_rng(1), 0, False))
    warm_pipe.pump(0.0)
    warm_pipe.close()
    del warm_det
    verdicts: dict = {}
    _det, pipe = _make_pipeline(verdicts)
    batches = 0
    t_first = t_last = None
    pending_t: float | None = None
    wall0 = time.perf_counter()
    for arrays, t_batch in reader.span_batches():
        cols = SpanColumns(
            **{
                name: np.asarray(arrays[name])
                for name in history.SPAN_CAPTURE_COLUMNS
            }
        )
        # One-batch lookahead: batch k pumps while batch k+1 already
        # sits in the queue, so the sync harvest keeps one report in
        # flight (the pipeline's normal overlap regime) instead of
        # round-tripping the device per batch. Verdicts are computed
        # on device from (batch, t) alone — harvest timing cannot
        # change them.
        pipe.submit_columns(cols)
        if pending_t is not None:
            pipe.pump(pending_t)
            batches += 1
        pending_t = t_batch
        t_first = t_batch if t_first is None else t_first
        t_last = t_batch
    if pending_t is not None:
        pipe.pump(pending_t)
        batches += 1
    pipe.drain()
    wall = time.perf_counter() - wall0
    pipe.close()
    virtual = (t_last - t_first + DT_S) if t_first is not None else 0.0
    return verdicts, virtual, wall, batches


def measure_range_queries(
    directory: str, samples: int = 50, seed: int = 0
) -> dict:
    """p50/p99 ms of range reads over the just-written ladder — the
    ``history_range_query_p99_ms`` artifact field."""
    store = history.HistoryStore(directory)
    reader = history.HistoryReader(store, rungs=(1.0, 60.0))
    recs = store.records(kind=history.KIND_BANK, rung=0)
    if not recs:
        return {}
    t0, t1 = recs[0].t_start, recs[-1].t_end
    rng = np.random.default_rng(seed)
    lat_ms = []
    for _ in range(samples):
        a, b = sorted(rng.uniform(t0, t1, size=2))
        start = time.perf_counter()
        reader.range_state(float(a), float(b) + 1.0)
        lat_ms.append((time.perf_counter() - start) * 1e3)
    lat = np.asarray(lat_ms)
    return {
        "history_range_query_p50_ms": round(float(np.percentile(lat, 50)), 3),
        "history_range_query_p99_ms": round(float(np.percentile(lat, 99)), 3),
        "history_range_query_samples": samples,
    }


def measure_replay(seed: int = 0, directory: str | None = None) -> dict:
    """Record → replay → compare; ONE artifact dict (bench.py's
    ``replay_*`` fields and the ``make replaybench`` line)."""
    from ..utils.config import HISTORY_KNOBS, env_float

    target = env_float(
        "ANOMALY_HISTORY_REPLAY_RATE",
        HISTORY_KNOBS["ANOMALY_HISTORY_REPLAY_RATE"][1],
    )
    tmp = None
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="replaybench-")
        directory = tmp.name
    try:
        recorded = record_incident(directory, seed=seed)
        replayed, virtual, wall, batches = replay(directory)
        identical = recorded == replayed
        speedup = virtual / max(wall, 1e-9)
        out = {
            "replay_speedup": round(speedup, 2),
            "replay_rate_target": target,
            "replay_ok": bool(identical and speedup >= target),
            "replay_verdicts_identical": identical,
            "replay_batches": batches,
            "replay_virtual_s": round(virtual, 3),
            "replay_wall_s": round(wall, 4),
            "replay_flagged_batches": sum(
                1 for flags in recorded.values() if any(flags)
            ),
        }
        out.update(measure_range_queries(directory, seed=seed))
        return out
    finally:
        if tmp is not None:
            tmp.cleanup()


def main() -> None:
    import json

    out = {"metric": "history_replay", "unit": "x_wall_clock"}
    out.update(measure_replay())
    print(json.dumps(out))


if __name__ == "__main__":
    main()
