"""Metric stream → dense observations → metrics head (host glue).

The span pipeline (runtime.pipeline) owns high-rate batching; metric
points arrive at scrape cadence, so this feed is deliberately light: a
lock-guarded accumulator that folds incoming :class:`MetricRecord` s into
dense ``[S, M]`` arrays and one jitted head step per pump.

Cumulative monotonic sums difference against the last seen value
(counter resets clamp to the new value — the Prometheus rate() rule);
delta-temporality sums accumulate directly; gauges and non-monotonic
sums observe the latest level. Metric names intern into ``M`` slots;
names beyond capacity are DROPPED (counted in ``points_overflow``), not
folded: a shared overflow slot would interleave unrelated cumulative
counters, and the reset rule then fabricates huge deltas — a spurious
anomaly generator. First-come-first-monitored, shapes never change.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

import numpy as np

from ..models.metrics_head import (
    MetricsHead,
    MetricsHeadConfig,
    MetricsHeadReport,
)
from .otlp_metrics import TEMPORALITY_DELTA, MetricRecord


class MetricsFeed:
    """Accumulates metric points; pumps them through the metrics head.

    ``service_id`` interns service names to the SAME id space as the
    span pipeline's tensorizer so per-service results line up across
    both legs; pass ``SpanTensorizer.service_id`` when co-deployed.
    """

    def __init__(
        self,
        config: MetricsHeadConfig | None = None,
        service_id: Callable[[str], int] | None = None,
        on_report: Callable[[float, MetricsHeadReport], None] | None = None,
    ):
        self.config = config or MetricsHeadConfig()
        self.head = MetricsHead(self.config)
        self.on_report = on_report
        self._lock = threading.Lock()
        s, m = self.config.num_services, self.config.num_metrics
        self._service_id = service_id or self._intern_service
        self._service_names: list[str] = []
        self._service_table: dict[str, int] = {}
        self._metric_names: list[str] = []
        self._metric_table: dict[str, int] = {}
        # Cumulative-counter memory + per-pump accumulation.
        self._last = np.zeros((s, m), np.float64)
        self._has_last = np.zeros((s, m), bool)
        self._accum = np.zeros((s, m), np.float64)
        self._rate_obs = np.zeros((s, m), bool)
        self._level = np.zeros((s, m), np.float64)
        self._level_obs = np.zeros((s, m), bool)
        self._t_last: float | None = None
        self.points_total = 0
        self.points_overflow = 0

    # -- intern tables --------------------------------------------------

    def _intern_service(self, name: str) -> int:
        """Slot for ``name``, or -1 when capacity is exhausted — a
        shared overflow row would interleave unrelated services'
        cumulative counters (same hazard as the metric-name table)."""
        sid = self._service_table.get(name)
        if sid is None:
            if len(self._service_names) >= self.config.num_services:
                return -1
            sid = len(self._service_names)
            self._service_table[name] = sid
            self._service_names.append(name)
        return sid

    @property
    def service_names(self) -> list[str]:
        """Interned service names (only meaningful with the built-in
        intern table; with an external ``service_id`` the caller owns
        the name ↔ id map)."""
        return list(self._service_names)

    def metric_id(self, name: str) -> int:
        """Slot for ``name``, or -1 when capacity is exhausted."""
        mid = self._metric_table.get(name)
        if mid is None:
            if len(self._metric_names) >= self.config.num_metrics:
                return -1  # beyond capacity: caller drops the point
            mid = len(self._metric_names)
            self._metric_table[name] = mid
            self._metric_names.append(name)
        return mid

    @property
    def metric_names(self) -> list[str]:
        return list(self._metric_names)

    def metric_slot_names(self) -> list[str]:
        """Slot → metric name, padded to the configured width."""
        pad = self.config.num_metrics - len(self._metric_names)
        return self._metric_names + ["?"] * pad

    # -- ingest ---------------------------------------------------------

    def submit(self, records: list[MetricRecord]) -> None:
        with self._lock:
            for rec in records:
                sid = self._service_id(rec.service)
                mid = self.metric_id(rec.name)
                if sid < 0 or sid >= self.config.num_services or mid < 0:
                    self.points_overflow += 1
                    continue
                self.points_total += 1
                if rec.kind == "sum" and rec.monotonic:
                    if rec.temporality == TEMPORALITY_DELTA:
                        self._accum[sid, mid] += rec.value
                        self._rate_obs[sid, mid] = True
                    elif self._has_last[sid, mid]:
                        prev = self._last[sid, mid]
                        # Counter reset: the new cumulative IS the delta.
                        delta = rec.value - prev if rec.value >= prev else rec.value
                        self._accum[sid, mid] += delta
                        self._rate_obs[sid, mid] = True
                        self._last[sid, mid] = rec.value
                    else:
                        self._last[sid, mid] = rec.value
                        self._has_last[sid, mid] = True
                else:  # gauge / non-monotonic sum: observe the level
                    self._level[sid, mid] = rec.value
                    self._level_obs[sid, mid] = True

    # -- pump -----------------------------------------------------------

    def pump(self, t_now: float | None = None) -> MetricsHeadReport | None:
        """Fold accumulated points into one head step.

        Returns the report (and fires ``on_report``) when any cell was
        observed; None on an empty interval — the head state must not
        absorb fabricated zero-observations for quiet cells.

        When ``t_now`` is omitted, reuse the last timebase (the
        pipeline's rule: mixing ``time.monotonic()`` into a virtual-time
        stream would poison every subsequent dt) — which makes the
        elapsed time zero, and zero elapsed time means NO fold this
        call: rates divide by dt, so a clamped near-zero dt would
        inflate every accumulated counter delta into a guaranteed false
        flag. Accumulation simply continues until a real timestamp
        arrives.
        """
        with self._lock:
            if t_now is None:
                t_now = self._t_last if self._t_last is not None else time.monotonic()
            if self._t_last is None:
                self._t_last = t_now
                # First pump: counters have at most baselines recorded.
                self._rate_obs[:] = False
                self._level_obs[:] = False
                self._accum[:] = 0.0
                return None
            dt = t_now - self._t_last
            if dt <= 0.0:
                return None  # no elapsed time: keep accumulating
            observed = self._rate_obs | self._level_obs
            if not observed.any():
                self._t_last = t_now
                return None
            x = np.where(
                self._rate_obs, self._accum / dt, self._level
            ).astype(np.float32)
            obs = observed.copy()
            self._accum[:] = 0.0
            self._rate_obs[:] = False
            self._level_obs[:] = False
            self._t_last = t_now
        report = self.head.observe(x, obs, dt)
        if self.on_report is not None:
            self.on_report(t_now, report)
        return report

    def flagged_services(
        self, report: MetricsHeadReport, names: list[str]
    ) -> list[str]:
        mask = np.asarray(report.flags)
        return [n for i, n in enumerate(names) if i < mask.shape[0] and mask[i]]
