"""Front-door benchmark + million-key cardinality soak.

Two measurements this repo never had, one module, one JSON line:

- ``measure_frontdoor_vs_pool`` — the tentpole's perf gate: OTLP/HTTP
  spans/s through the NATIVE front door (socket → native buffer →
  ticket → decode pool, zero Python per payload) vs the in-process
  pool baseline (``ingestbench.measure_pooled``) at MATCHED workers
  and payload geometry. The front door pays real sockets and HTTP
  framing that the in-process number never does, so meeting the
  baseline means the native acceptor's framing is genuinely free
  relative to decode — the claim BENCH_r06 said the Python receiver
  could not make (pooled ingest flat at ~6.1M spans/s because the
  front end, not decode, was the wall).

- ``measure_million_key_soak`` — the repo's first scale-of-keys run:
  a synthetic shop-fleet generator drives ≥1M distinct
  (tenant × service) keys through ingest → sketch → query, measuring
  steady-state RSS per million keys, intern-table pressure (the
  snapshot-republish cost is REAL at this scale and is exactly what
  this soak exists to observe), sketch-geometry overflow behavior
  (keys past ``num_services`` fold into the overflow bucket by
  contract — counted, not hidden), and the fleet's drift refusal
  (``merge_shard_arrays`` must still refuse a mismatched geometry
  when the tables are a million keys deep, not just at the ~13
  services every other test uses).

Callers: ``make frontdoorbench`` (standalone, full-size soak) and
``bench.py``'s BENCH_FRONTDOOR leg (additive artifact fields).
"""

from __future__ import annotations

import socket
import threading
import time

from . import native, wire
from .ingest_pool import IngestPool, IngestPoolSaturated
from .ingestbench import make_payloads, measure_pooled
from .tensorize import SpanTensorizer

ONE_MILLION = 1_000_000


# ---------------------------------------------------------------------------
# synthetic shop fleet: many DISTINCT services per request
# ---------------------------------------------------------------------------

def make_fleet_payloads(
    n_requests: int,
    services_per_request: int = 4096,
    tenants: int = 16,
    start_index: int = 0,
) -> list[bytes]:
    """OTLP trace payloads whose every span belongs to a DISTINCT
    (tenant × service) key — one resource_spans block per service,
    one span each.

    ``ingestbench.make_payloads`` models today's demo (~10 services,
    fat resource blocks); this models the paper's north star (millions
    of users → millions of live keys). The span body is one shared
    template — what varies per key is the resource's service.name,
    which is the axis the interner, the sketches and the fleet table
    all key on.
    """

    def anyval(s: bytes) -> bytes:
        return wire.encode_len(1, s)

    def kv(k: bytes, v: bytes) -> bytes:
        return wire.encode_len(1, k) + wire.encode_len(2, anyval(v))

    start = 1_700_000_000_000_000_000
    span = (
        wire.encode_len(1, bytes(range(16)))
        + wire.encode_len(5, b"oteldemo.rpc/Call")
        + wire.encode_fixed64(7, start)
        + wire.encode_fixed64(8, start + 5_000_000)
        + wire.encode_len(9, kv(b"app.product.id", b"P-7"))
        + wire.encode_len(9, kv(b"rpc.system", b"grpc"))
    )
    # ResourceSpans.field2 = ScopeSpans, ScopeSpans.field2 = Span —
    # the same double wrap ingestbench.make_payloads emits.
    scope_spans = wire.encode_len(2, wire.encode_len(2, span))
    payloads = []
    key = start_index
    for _ in range(n_requests):
        rs_bufs = []
        for _ in range(services_per_request):
            tenant = key % tenants
            name = f"t{tenant:02d}.svc-{key:07d}".encode()
            resource = wire.encode_len(1, kv(b"service.name", name))
            rs_bufs.append(
                wire.encode_len(
                    1, wire.encode_len(1, resource) + scope_spans
                )
            )
            key += 1
        payloads.append(b"".join(rs_bufs))
    return payloads


# ---------------------------------------------------------------------------
# HTTP client for the front door (bench-side: Python is fine HERE —
# the claim under test is the SERVER's per-payload loop, not the load
# generator's)
# ---------------------------------------------------------------------------

def _post_loop(
    port: int,
    payloads: list[bytes],
    stop: threading.Event,
    counts: dict,
    lock: threading.Lock,
    depth: int = 4,
    path: bytes = b"/v1/traces",
) -> None:
    """Keep-alive client: send ``depth`` pipelined POSTs, read ``depth``
    responses, repeat until ``stop``. Pipelining keeps the connection's
    ticket slot busy without one thread per in-flight request."""
    reqs = [
        b"POST %s HTTP/1.1\r\nHost: bench\r\nContent-Length: %d\r\n\r\n"
        % (path, len(p)) + p
        for p in payloads
    ]
    s = socket.create_connection(("127.0.0.1", port))
    s.settimeout(30.0)
    try:
        i = 0
        buf = b""
        while not stop.is_set():
            burst = [reqs[(i + k) % len(reqs)] for k in range(depth)]
            i += depth
            s.sendall(b"".join(burst))
            need = depth
            ok = 0
            while need > 0:
                # Responses are header-only (Content-Length: 0), so a
                # complete response == one blank-line terminator.
                chunk = s.recv(65536)
                if not chunk:
                    raise ConnectionError("front door closed mid-burst")
                buf += chunk
                while b"\r\n\r\n" in buf and need > 0:
                    head, buf = buf.split(b"\r\n\r\n", 1)
                    if head.split(b" ", 2)[1] == b"200":
                        ok += 1
                    need -= 1
            with lock:
                counts["ok"] = counts.get("ok", 0) + ok
                counts["sent"] = counts.get("sent", 0) + depth
    except Exception:  # noqa: BLE001 — a bench client dying ends its lane
        pass
    finally:
        s.close()


def _run_frontdoor_clients(
    port: int,
    payloads: list[bytes],
    seconds: float,
    clients: int,
    depth: int,
) -> dict:
    stop = threading.Event()
    counts: dict = {}
    lock = threading.Lock()
    threads = [
        threading.Thread(
            target=_post_loop,
            args=(port, payloads, stop, counts, lock, depth),
            daemon=True,
        )
        for _ in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    counts["elapsed"] = time.perf_counter() - t0
    return counts


def measure_frontdoor_vs_pool(
    workers: int = 2,
    n_requests: int = 12,
    spans_per_request: int = 4096,
    seconds: float = 4.0,
    clients: int = 16,
    depth: int = 2,
    repeat: int = 2,
    payloads: list[bytes] | None = None,
) -> dict | None:
    """Front-door spans/s vs the in-process pool at matched geometry.

    Same payload set, same worker count, same null sink, same
    tensorizer width — the ONLY difference is the door: in-process
    ``pool.submit(bytes)`` vs real sockets through native framing
    into the same pool. Fat payloads (default 4096 spans/request) are
    deliberate: the gate is about the steady-state span path, and a
    49-byte request would measure connection scheduling, not ingest.
    Returns None when the native decoder or front door can't build.
    """
    if not native.available() or not native.frontdoor_available():
        return None
    from .frontdoor import FrontDoorServer

    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    pool_rate = measure_pooled(
        workers=workers, repeat=repeat, passes=16, coalesce=64,
        payloads=payloads,
        n_requests=n_requests, spans_per_request=spans_per_request,
    )
    if pool_rate is None:
        return None

    tz = SpanTensorizer(num_services=32)
    sink = lambda cols: None  # noqa: E731 — matched with measure_pooled
    pool = IngestPool(
        sink, tz, workers=workers, coalesce_max=64,
        max_pending=max(clients * depth * 4, 256),
    )
    fd = FrontDoorServer(
        pool,
        port=0,
        max_body_bytes=64 << 20,
        batch_max=64,
        max_conns=clients + 4,
    )
    try:
        # Warmup off the clock: size scratch, fault in the whole path.
        warm = _run_frontdoor_clients(
            fd.port, payloads, min(seconds, 1.0), clients, depth
        )
        timed = _run_frontdoor_clients(
            fd.port, payloads, seconds, clients, depth
        )
    finally:
        fd.stop()
        pool.close()
    fd_rate = (
        timed.get("ok", 0) * spans_per_request / timed["elapsed"]
        if timed.get("ok") else 0.0
    )
    return {
        "workers": workers,
        "spans_per_request": spans_per_request,
        "clients": clients,
        "pipeline_depth": depth,
        "pool_spans_per_sec": round(pool_rate, 1),
        "frontdoor_spans_per_sec": round(fd_rate, 1),
        "frontdoor_vs_pool": round(fd_rate / pool_rate, 4) if pool_rate else None,
        "requests_ok": timed.get("ok", 0),
        "requests_sent": timed.get("sent", 0),
        "warmup_ok": warm.get("ok", 0),
    }


# ---------------------------------------------------------------------------
# million-key soak
# ---------------------------------------------------------------------------

def _rss_kb() -> int | None:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    except Exception:  # noqa: BLE001 — RSS is best-effort off-linux
        return None


def measure_million_key_soak(
    target_keys: int = 1_048_576,
    services_per_request: int = 4096,
    tenants: int = 16,
    workers: int = 2,
    num_services: int = 4096,
    batch: int = 4096,
    via_frontdoor: bool = True,
    clients: int = 2,
) -> dict | None:
    """Drive ``target_keys`` DISTINCT (tenant × service) keys through
    ingest → sketch → query and report what scale actually costs.

    Payloads are generated in waves (a resident list of a million-key
    corpus would bill its own footprint to the thing under test);
    every wave goes through the REAL path — front door sockets when
    the native library is up, ``pool.submit`` otherwise — into a real
    ``DetectorPipeline`` + device sketch step, then the query-side
    checks run against the drained state:

    - ``distinct_interned`` must equal ``target_keys`` EXACTLY (the
      intern table is exact, not probabilistic — any gap is
      corruption, and the soak fails loudly);
    - a re-intern of a sample must return the same ids (read-back
      identity after a million publications);
    - sketch ids past ``num_services`` fold into the overflow bucket
      by contract — ``overflow_keys`` reports how many, because a soak
      that silently dropped 99% of its keys would be a lie;
    - ``merge_shard_arrays`` must still REFUSE a drifted geometry at
      this table size (``drift_refused``);
    - ``frames_corrupt`` must be 0 across every pooled flush.

    RSS is sampled before generation and after the final drain;
    ``rss_per_million_keys_mb`` is the headline the regression bound
    watches.
    """
    if not native.available():
        return None
    import numpy as np

    from ..models.detector import AnomalyDetector, DetectorConfig
    from .frontdoor import FrontDoorServer
    from .pipeline import DetectorPipeline

    n_requests = -(-target_keys // services_per_request)
    total_keys = n_requests * services_per_request
    rss_before = _rss_kb()

    config = DetectorConfig(
        num_services=num_services, hll_p=8, cms_width=1024
    )
    det = AnomalyDetector(config)
    reports = [0]
    pipe = DetectorPipeline(
        det,
        on_report=lambda t, r, flagged: reports.__setitem__(
            0, reports[0] + 1
        ),
        batch_size=batch,
    )
    pool = IngestPool(
        pipe.submit_columns, pipe.tensorizer, workers=workers,
        coalesce_max=64, max_pending=512,
    )
    use_fd = via_frontdoor and native.frontdoor_available()
    fd = (
        FrontDoorServer(pool, port=0, max_body_bytes=64 << 20,
                        max_conns=clients + 2)
        if use_fd else None
    )

    pump_stop = threading.Event()

    def pump_loop() -> None:
        while not pump_stop.is_set():
            pipe.pump()
            time.sleep(0.001)

    pump = threading.Thread(target=pump_loop, name="soak-pump", daemon=True)
    pump.start()

    def ship(wave: list[bytes]) -> None:
        if fd is not None:
            counts: dict = {}
            lock = threading.Lock()
            # One pass over the wave per client lane, no repeat loop:
            # _post_loop cycles forever, so ship waves directly here.
            per = -(-len(wave) // clients)
            lanes = [wave[i * per:(i + 1) * per] for i in range(clients)]

            def lane(payloads: list[bytes]) -> None:
                s = socket.create_connection(("127.0.0.1", fd.port))
                s.settimeout(60.0)
                try:
                    for p in payloads:
                        s.sendall(
                            b"POST /v1/traces HTTP/1.1\r\nHost: soak\r\n"
                            b"Content-Length: %d\r\n\r\n" % len(p) + p
                        )
                        buf = b""
                        while b"\r\n\r\n" not in buf:
                            chunk = s.recv(65536)
                            if not chunk:
                                raise ConnectionError("closed")
                            buf += chunk
                        with lock:
                            if buf.split(b" ", 2)[1] == b"200":
                                counts["ok"] = counts.get("ok", 0) + 1
                finally:
                    s.close()

            threads = [
                threading.Thread(target=lane, args=(ln,), daemon=True)
                for ln in lanes if ln
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300.0)
        else:
            for p in wave:
                while True:
                    try:
                        pool.submit(p)
                        break
                    except IngestPoolSaturated:
                        pipe.pump()
                        time.sleep(0.001)

    t0 = time.perf_counter()
    wave_requests = max(1, (32 << 20) // (services_per_request * 120))
    shipped = 0
    try:
        while shipped < n_requests:
            n = min(wave_requests, n_requests - shipped)
            wave = make_fleet_payloads(
                n, services_per_request, tenants,
                start_index=shipped * services_per_request,
            )
            ship(wave)
            shipped += n
            pipe.pump()
        pool.drain()
        pipe.pump()
        pipe.drain()
    finally:
        if fd is not None:
            fd.stop()
        pump_stop.set()
        pump.join(timeout=10.0)
        pool_stats = pool.stats()
        pool.close()
    elapsed = time.perf_counter() - t0
    rss_after = _rss_kb()

    tz = pipe.tensorizer
    distinct = len(tz.service_names)
    # Read-back identity: a sample of generated keys must ALREADY be
    # in the published snapshot (nothing lost across a million
    # publications) and a batched re-intern of known names must agree
    # with it without assigning anything new.
    sample = [
        f"t{(k % tenants):02d}.svc-{k:07d}"
        for k in range(0, total_keys, max(total_keys // 1024, 1))
    ]
    snap = tz._svc_snapshot  # noqa: SLF001 — the lock-free read surface
    readback_ok = all(n in snap for n in sample) and (
        tz.intern_many(sample) == [snap[n] for n in sample]
    )
    overflow_keys = max(distinct - (num_services - 1), 0)

    # Fleet drift refusal at scale: a shard whose sketch geometry
    # drifted by one row must still be REFUSED when the shared table
    # is a million keys deep.
    from .fleet import ShardMergeError, merge_shard_arrays

    rows = max(num_services, 1 << 14)
    a = {"cms_bank": np.ones((rows, 64), np.int32)}
    b = {"cms_bank": np.ones((rows + 1, 64), np.int32)}
    try:
        merge_shard_arrays(a, b)
        drift_refused = False
    except ShardMergeError:
        drift_refused = True

    keys_m = total_keys / ONE_MILLION
    rss_delta_mb = (
        (rss_after - rss_before) / 1024.0
        if rss_after is not None and rss_before is not None else None
    )
    return {
        "target_keys": target_keys,
        "distinct_keys": total_keys,
        "distinct_interned": distinct,
        "intern_exact": bool(distinct == total_keys),
        "readback_ok": bool(readback_ok),
        "overflow_keys": int(overflow_keys),
        "sketch_num_services": num_services,
        "tenants": tenants,
        "reports": reports[0],
        "frames_corrupt": int(pool_stats.get("frames_corrupt", 0)),
        "decode_errors": int(pool_stats.get("decode_errors", 0)),
        "drift_refused": bool(drift_refused),
        "via_frontdoor": bool(use_fd),
        "elapsed_s": round(elapsed, 2),
        "keys_per_sec": round(total_keys / elapsed, 1),
        "rss_before_kb": rss_before,
        "rss_after_kb": rss_after,
        "rss_per_million_keys_mb": (
            round(rss_delta_mb / keys_m, 1)
            if rss_delta_mb is not None else None
        ),
        "soak_ok": bool(
            distinct == total_keys
            and readback_ok
            and drift_refused
            and pool_stats.get("frames_corrupt", 0) == 0
        ),
    }


def main() -> None:
    import json
    import os

    perf = measure_frontdoor_vs_pool(
        workers=int(os.environ.get("BENCH_FRONTDOOR_WORKERS", "2")),
        seconds=float(os.environ.get("BENCH_FRONTDOOR_SECONDS", "4.0")),
    )
    soak = measure_million_key_soak(
        target_keys=int(
            os.environ.get("BENCH_FRONTDOOR_KEYS", str(1_048_576))
        ),
    )
    eligible = (os.cpu_count() or 1) >= 2
    print(
        json.dumps(
            {
                "metric": "frontdoor_vs_pool_and_million_key_soak",
                "frontdoor": perf or {},
                "soak": soak or {},
                # Same null-when-ineligible convention as bench.py's
                # decode_wall_ok: on a 1-core box neither door can
                # overlap anything, so pass/fail is unmeasurable.
                "frontdoor_ok": (
                    bool(
                        perf["frontdoor_spans_per_sec"]
                        >= perf["pool_spans_per_sec"]
                    )
                    if perf is not None and eligible else None
                ),
                "soak_ok": (soak or {}).get("soak_ok"),
            },
            sort_keys=True,
        )
    )


if __name__ == "__main__":
    main()
