"""Front-door benchmark + million-key cardinality soak.

Two measurements this repo never had, one module, one JSON line:

- ``measure_frontdoor_vs_pool`` — the tentpole's perf gate: OTLP/HTTP
  spans/s through the NATIVE front door (socket → native buffer →
  ticket → decode pool, zero Python per payload) vs the in-process
  pool baseline (``ingestbench.measure_pooled``) at MATCHED workers
  and payload geometry. The front door pays real sockets and HTTP
  framing that the in-process number never does, so meeting the
  baseline means the native acceptor's framing is genuinely free
  relative to decode — the claim BENCH_r06 said the Python receiver
  could not make (pooled ingest flat at ~6.1M spans/s because the
  front end, not decode, was the wall).

- ``measure_million_key_soak`` — the repo's first scale-of-keys run:
  a synthetic shop-fleet generator drives ≥1M distinct
  (tenant × service) keys through ingest → sketch → query. Since the
  keyspace plane (runtime/keyspace.py) the intern table is BOUNDED:
  the first ``capacity`` distinct keys win dense slots, every later
  key folds into the overflow bucket UNMEMORIZED — so the soak's
  memory claim flipped from "report the leak" (~935 MB per million
  keys, measured against the old append-only table) to "prove the
  bound" (``soak_rss_ok``: RSS per million keys must stay under
  ``SOAK_RSS_CEILING_MB_PER_MILLION``). Read-back identity, overflow
  accounting, drift refusal at scale and zero frame corruption ride
  along as before.

- ``measure_churn_soak`` — the key-lifecycle plane's survival gate: a
  keyspace-ENABLED pipeline streams ≥3× its key budget of distinct
  keys with churn (a stable live cohort re-shipped every wave + fresh
  one-shot churn keys), while the ``KeyspaceManager`` watchdog clocks
  the degradation ladder and the evictor folds idle keys into a real
  on-disk history tier. Proves: steady-state RSS slope ≈ 0, live-key
  ids bit-stable across every sweep (no mis-attribution), evicted
  keys still answerable via ``/query/*`` with ``source:"evicted"``,
  generation-drifted fleet merges refused, zero frame corruption.

Callers: ``make frontdoorbench`` (standalone, full-size soak) and
``bench.py``'s BENCH_FRONTDOOR leg (additive artifact fields).
"""

from __future__ import annotations

import socket
import threading
import time

from . import native, wire
from .ingest_pool import IngestPool, IngestPoolSaturated
from .ingestbench import make_payloads, measure_pooled
from .tensorize import SpanTensorizer

ONE_MILLION = 1_000_000

# The bounded-interner memory gate: RSS growth per million distinct
# keys streamed must stay under this. The OLD append-only table leaked
# ~935 MB/M (BENCH_r19's measured baseline — every name memorized
# forever); the bounded table admits ``capacity`` names and refuses
# the rest unmemorized, so steady-state growth is wave buffers + JAX
# scratch, far below the old leak. The ceiling is deliberately set at
# the old measured baseline: crossing it means the bomb leaks again.
SOAK_RSS_CEILING_MB_PER_MILLION = 900.0


# ---------------------------------------------------------------------------
# synthetic shop fleet: many DISTINCT services per request
# ---------------------------------------------------------------------------

def make_named_payload(names: list[str]) -> bytes:
    """One OTLP trace payload with one single-span resource_spans
    block per name in ``names`` — the span body is one shared
    template; what varies per key is the resource's service.name,
    which is the axis the interner, the sketches and the fleet table
    all key on."""

    def anyval(s: bytes) -> bytes:
        return wire.encode_len(1, s)

    def kv(k: bytes, v: bytes) -> bytes:
        return wire.encode_len(1, k) + wire.encode_len(2, anyval(v))

    start = 1_700_000_000_000_000_000
    span = (
        wire.encode_len(1, bytes(range(16)))
        + wire.encode_len(5, b"oteldemo.rpc/Call")
        + wire.encode_fixed64(7, start)
        + wire.encode_fixed64(8, start + 5_000_000)
        + wire.encode_len(9, kv(b"app.product.id", b"P-7"))
        + wire.encode_len(9, kv(b"rpc.system", b"grpc"))
    )
    # ResourceSpans.field2 = ScopeSpans, ScopeSpans.field2 = Span —
    # the same double wrap ingestbench.make_payloads emits.
    scope_spans = wire.encode_len(2, wire.encode_len(2, span))
    rs_bufs = []
    for name in names:
        resource = wire.encode_len(1, kv(b"service.name", name.encode()))
        rs_bufs.append(
            wire.encode_len(1, wire.encode_len(1, resource) + scope_spans)
        )
    return b"".join(rs_bufs)


def make_fleet_payloads(
    n_requests: int,
    services_per_request: int = 4096,
    tenants: int = 16,
    start_index: int = 0,
) -> list[bytes]:
    """OTLP trace payloads whose every span belongs to a DISTINCT
    (tenant × service) key — one resource_spans block per service,
    one span each.

    ``ingestbench.make_payloads`` models today's demo (~10 services,
    fat resource blocks); this models the paper's north star (millions
    of users → millions of live keys).
    """
    payloads = []
    key = start_index
    for _ in range(n_requests):
        names = []
        for _ in range(services_per_request):
            names.append(f"t{key % tenants:02d}.svc-{key:07d}")
            key += 1
        payloads.append(make_named_payload(names))
    return payloads


# ---------------------------------------------------------------------------
# HTTP client for the front door (bench-side: Python is fine HERE —
# the claim under test is the SERVER's per-payload loop, not the load
# generator's)
# ---------------------------------------------------------------------------

def _post_loop(
    port: int,
    payloads: list[bytes],
    stop: threading.Event,
    counts: dict,
    lock: threading.Lock,
    depth: int = 4,
    path: bytes = b"/v1/traces",
) -> None:
    """Keep-alive client: send ``depth`` pipelined POSTs, read ``depth``
    responses, repeat until ``stop``. Pipelining keeps the connection's
    ticket slot busy without one thread per in-flight request."""
    reqs = [
        b"POST %s HTTP/1.1\r\nHost: bench\r\nContent-Length: %d\r\n\r\n"
        % (path, len(p)) + p
        for p in payloads
    ]
    s = socket.create_connection(("127.0.0.1", port))
    s.settimeout(30.0)
    try:
        i = 0
        buf = b""
        while not stop.is_set():
            burst = [reqs[(i + k) % len(reqs)] for k in range(depth)]
            i += depth
            s.sendall(b"".join(burst))
            need = depth
            ok = 0
            while need > 0:
                # Responses are header-only (Content-Length: 0), so a
                # complete response == one blank-line terminator.
                chunk = s.recv(65536)
                if not chunk:
                    raise ConnectionError("front door closed mid-burst")
                buf += chunk
                while b"\r\n\r\n" in buf and need > 0:
                    head, buf = buf.split(b"\r\n\r\n", 1)
                    if head.split(b" ", 2)[1] == b"200":
                        ok += 1
                    need -= 1
            with lock:
                counts["ok"] = counts.get("ok", 0) + ok
                counts["sent"] = counts.get("sent", 0) + depth
    except Exception:  # noqa: BLE001 — a bench client dying ends its lane
        pass
    finally:
        s.close()


def _run_frontdoor_clients(
    port: int,
    payloads: list[bytes],
    seconds: float,
    clients: int,
    depth: int,
) -> dict:
    stop = threading.Event()
    counts: dict = {}
    lock = threading.Lock()
    threads = [
        threading.Thread(
            target=_post_loop,
            args=(port, payloads, stop, counts, lock, depth),
            daemon=True,
        )
        for _ in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    counts["elapsed"] = time.perf_counter() - t0
    return counts


def measure_frontdoor_vs_pool(
    workers: int = 2,
    n_requests: int = 12,
    spans_per_request: int = 4096,
    seconds: float = 4.0,
    clients: int = 16,
    depth: int = 2,
    repeat: int = 2,
    payloads: list[bytes] | None = None,
) -> dict | None:
    """Front-door spans/s vs the in-process pool at matched geometry.

    Same payload set, same worker count, same null sink, same
    tensorizer width — the ONLY difference is the door: in-process
    ``pool.submit(bytes)`` vs real sockets through native framing
    into the same pool. Fat payloads (default 4096 spans/request) are
    deliberate: the gate is about the steady-state span path, and a
    49-byte request would measure connection scheduling, not ingest.
    Returns None when the native decoder or front door can't build.
    """
    if not native.available() or not native.frontdoor_available():
        return None
    from .frontdoor import FrontDoorServer

    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    pool_rate = measure_pooled(
        workers=workers, repeat=repeat, passes=16, coalesce=64,
        payloads=payloads,
        n_requests=n_requests, spans_per_request=spans_per_request,
    )
    if pool_rate is None:
        return None

    tz = SpanTensorizer(num_services=32)
    sink = lambda cols: None  # noqa: E731 — matched with measure_pooled
    pool = IngestPool(
        sink, tz, workers=workers, coalesce_max=64,
        max_pending=max(clients * depth * 4, 256),
    )
    fd = FrontDoorServer(
        pool,
        port=0,
        max_body_bytes=64 << 20,
        batch_max=64,
        max_conns=clients + 4,
    )
    try:
        # Warmup off the clock: size scratch, fault in the whole path.
        warm = _run_frontdoor_clients(
            fd.port, payloads, min(seconds, 1.0), clients, depth
        )
        timed = _run_frontdoor_clients(
            fd.port, payloads, seconds, clients, depth
        )
    finally:
        fd.stop()
        pool.close()
    fd_rate = (
        timed.get("ok", 0) * spans_per_request / timed["elapsed"]
        if timed.get("ok") else 0.0
    )
    return {
        "workers": workers,
        "spans_per_request": spans_per_request,
        "clients": clients,
        "pipeline_depth": depth,
        "pool_spans_per_sec": round(pool_rate, 1),
        "frontdoor_spans_per_sec": round(fd_rate, 1),
        "frontdoor_vs_pool": round(fd_rate / pool_rate, 4) if pool_rate else None,
        "requests_ok": timed.get("ok", 0),
        "requests_sent": timed.get("sent", 0),
        "warmup_ok": warm.get("ok", 0),
    }


# ---------------------------------------------------------------------------
# million-key soak
# ---------------------------------------------------------------------------

def _rss_kb() -> int | None:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except OSError:
        pass
    try:
        import resource

        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    except Exception:  # noqa: BLE001 — RSS is best-effort off-linux
        return None


def measure_million_key_soak(
    target_keys: int = 1_048_576,
    services_per_request: int = 4096,
    tenants: int = 16,
    workers: int = 2,
    num_services: int = 4096,
    batch: int = 4096,
    via_frontdoor: bool = True,
    clients: int = 2,
) -> dict | None:
    """Drive ``target_keys`` DISTINCT (tenant × service) keys through
    ingest → sketch → query and report what scale actually costs.

    Payloads are generated in waves (a resident list of a million-key
    corpus would bill its own footprint to the thing under test);
    every wave goes through the REAL path — front door sockets when
    the native library is up, ``pool.submit`` otherwise — into a real
    ``DetectorPipeline`` + device sketch step, then the query-side
    checks run against the drained state:

    - the intern table is BOUNDED (keyspace plane): exactly
      ``min(target_keys, capacity)`` keys must hold dense slots after
      the storm (``intern_exact``) — WHICH keys win the slots is
      admission-order across concurrent lanes, so the check counts
      live rows, it does not enumerate names;
    - every published (name → id) pair must read back bit-stable
      through a batched re-intern (``readback_ok``);
    - keys past capacity fold into the overflow bucket UNMEMORIZED by
      contract — ``overflow_keys`` reports the refused-assign count,
      because a soak that silently dropped 99% of its keys would be a
      lie;
    - ``merge_shard_arrays`` must still REFUSE a drifted geometry at
      this table size (``drift_refused``);
    - ``frames_corrupt`` must be 0 across every pooled flush.

    RSS is sampled before generation and after the final drain;
    ``rss_per_million_keys_mb`` is the headline and ``soak_rss_ok``
    gates it under ``SOAK_RSS_CEILING_MB_PER_MILLION`` — the bounded
    table's whole point is that a million-key bomb no longer buys a
    gigabyte.
    """
    if not native.available():
        return None
    import numpy as np

    from ..models.detector import AnomalyDetector, DetectorConfig
    from .frontdoor import FrontDoorServer
    from .pipeline import DetectorPipeline

    n_requests = -(-target_keys // services_per_request)
    total_keys = n_requests * services_per_request
    rss_before = _rss_kb()

    config = DetectorConfig(
        num_services=num_services, hll_p=8, cms_width=1024
    )
    det = AnomalyDetector(config)
    reports = [0]
    pipe = DetectorPipeline(
        det,
        on_report=lambda t, r, flagged: reports.__setitem__(
            0, reports[0] + 1
        ),
        batch_size=batch,
    )
    pool = IngestPool(
        pipe.submit_columns, pipe.tensorizer, workers=workers,
        coalesce_max=64, max_pending=512,
    )
    use_fd = via_frontdoor and native.frontdoor_available()
    fd = (
        FrontDoorServer(pool, port=0, max_body_bytes=64 << 20,
                        max_conns=clients + 2)
        if use_fd else None
    )

    pump_stop = threading.Event()

    def pump_loop() -> None:
        while not pump_stop.is_set():
            pipe.pump()
            time.sleep(0.001)

    pump = threading.Thread(target=pump_loop, name="soak-pump", daemon=True)
    pump.start()

    def ship(wave: list[bytes]) -> None:
        if fd is not None:
            counts: dict = {}
            lock = threading.Lock()
            # One pass over the wave per client lane, no repeat loop:
            # _post_loop cycles forever, so ship waves directly here.
            per = -(-len(wave) // clients)
            lanes = [wave[i * per:(i + 1) * per] for i in range(clients)]

            def lane(payloads: list[bytes]) -> None:
                s = socket.create_connection(("127.0.0.1", fd.port))
                s.settimeout(60.0)
                try:
                    for p in payloads:
                        s.sendall(
                            b"POST /v1/traces HTTP/1.1\r\nHost: soak\r\n"
                            b"Content-Length: %d\r\n\r\n" % len(p) + p
                        )
                        buf = b""
                        while b"\r\n\r\n" not in buf:
                            chunk = s.recv(65536)
                            if not chunk:
                                raise ConnectionError("closed")
                            buf += chunk
                        with lock:
                            if buf.split(b" ", 2)[1] == b"200":
                                counts["ok"] = counts.get("ok", 0) + 1
                finally:
                    s.close()

            threads = [
                threading.Thread(target=lane, args=(ln,), daemon=True)
                for ln in lanes if ln
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300.0)
        else:
            for p in wave:
                while True:
                    try:
                        pool.submit(p)
                        break
                    except IngestPoolSaturated:
                        pipe.pump()
                        time.sleep(0.001)

    t0 = time.perf_counter()
    wave_requests = max(1, (32 << 20) // (services_per_request * 120))
    shipped = 0
    try:
        while shipped < n_requests:
            n = min(wave_requests, n_requests - shipped)
            wave = make_fleet_payloads(
                n, services_per_request, tenants,
                start_index=shipped * services_per_request,
            )
            ship(wave)
            shipped += n
            pipe.pump()
        pool.drain()
        pipe.pump()
        pipe.drain()
    finally:
        if fd is not None:
            fd.stop()
        pump_stop.set()
        pump.join(timeout=10.0)
        pool_stats = pool.stats()
        pool.close()
    elapsed = time.perf_counter() - t0
    rss_after = _rss_kb()

    tz = pipe.tensorizer
    capacity = tz.capacity
    expected_live = min(total_keys, capacity)
    distinct = tz.live_keys
    # Refused-assign count BEFORE the read-back below re-consults the
    # table (a re-intern of live names assigns nothing, but reading
    # the counter first keeps the number honest either way).
    overflow_keys = int(tz.overflow_assigns_total)
    # Read-back identity: every (name → id) pair the table PUBLISHED
    # must survive a batched re-intern bit-stable. The sample comes
    # from the actual snapshot, not the generated sequence — which
    # keys won the dense slots is admission-order across concurrent
    # client lanes, and the bounded table refused the rest by design.
    snap = tz._svc_snapshot  # noqa: SLF001 — the lock-free read surface
    live_names = list(snap)
    sample = live_names[:: max(len(live_names) // 1024, 1)] or live_names
    readback_ok = bool(sample) and (
        tz.intern_many(sample) == [snap[n] for n in sample]
    )

    # Fleet drift refusal at scale: a shard whose sketch geometry
    # drifted by one row must still be REFUSED when the shared table
    # is a million keys deep.
    from .fleet import ShardMergeError, merge_shard_arrays

    rows = max(num_services, 1 << 14)
    a = {"cms_bank": np.ones((rows, 64), np.int32)}
    b = {"cms_bank": np.ones((rows + 1, 64), np.int32)}
    try:
        merge_shard_arrays(a, b)
        drift_refused = False
    except ShardMergeError:
        drift_refused = True

    keys_m = total_keys / ONE_MILLION
    rss_delta_mb = (
        (rss_after - rss_before) / 1024.0
        if rss_after is not None and rss_before is not None else None
    )
    rss_per_million = (
        round(rss_delta_mb / keys_m, 1)
        if rss_delta_mb is not None else None
    )
    return {
        "target_keys": target_keys,
        "distinct_keys": total_keys,
        "distinct_interned": distinct,
        "intern_capacity": capacity,
        "intern_exact": bool(distinct == expected_live),
        "readback_ok": bool(readback_ok),
        "overflow_keys": overflow_keys,
        "sketch_num_services": num_services,
        "tenants": tenants,
        "reports": reports[0],
        "frames_corrupt": int(pool_stats.get("frames_corrupt", 0)),
        "decode_errors": int(pool_stats.get("decode_errors", 0)),
        "drift_refused": bool(drift_refused),
        "via_frontdoor": bool(use_fd),
        "elapsed_s": round(elapsed, 2),
        "keys_per_sec": round(total_keys / elapsed, 1),
        "rss_before_kb": rss_before,
        "rss_after_kb": rss_after,
        "rss_per_million_keys_mb": rss_per_million,
        # The bounded-memory gate: None where RSS is unmeasurable (no
        # /proc and no rusage) or the run was trimmed below half a
        # million keys — fixed overhead (JAX compile caches, device
        # buffers) divided by a small key count swamps the per-million
        # normalization, so a short run can't measure the claim. Same
        # null-when-ineligible convention as bench.py's decode_wall_ok.
        "soak_rss_ok": (
            bool(rss_per_million <= SOAK_RSS_CEILING_MB_PER_MILLION)
            if rss_per_million is not None
            and total_keys >= ONE_MILLION // 2 else None
        ),
        "soak_ok": bool(
            distinct == expected_live
            and readback_ok
            and drift_refused
            and pool_stats.get("frames_corrupt", 0) == 0
        ),
    }


# ---------------------------------------------------------------------------
# churn soak: the key-lifecycle plane's survival gate
# ---------------------------------------------------------------------------

def measure_churn_soak(
    num_services: int = 512,
    live_cohort: int = 32,
    churn_multiple: int = 3,
    waves: int = 8,
    tenants: int = 16,
    workers: int = 2,
    via_frontdoor: bool = True,
    idle_s: float = 0.25,
    hold_s: float = 0.02,
    rss_slope_limit_mb: float = 64.0,
) -> dict | None:
    """Stream ``churn_multiple`` × the key budget of DISTINCT keys
    through a keyspace-ENABLED pipeline and prove the lifecycle plane
    survives the bomb without losing the legitimate tenants.

    Every wave ships a fresh batch of one-shot churn keys plus the
    SAME ``live_cohort`` of legitimate services (re-shipped right
    before each eviction tick, so recency — not a whitelist — is what
    keeps them alive). The ``KeyspaceManager`` is ticked manually
    between waves: pressure saturates at the high watermark, the
    ladder engages after ``hold_s``, idle churn keys fold into a REAL
    on-disk history tier and their ids recycle under a generation
    bump. The gates:

    - ``live_ids_stable``: the live cohort's intern ids are
      bit-identical after every sweep — no eviction ever
      mis-attributed a legitimate key's rows;
    - ``evicted_query_ok``: an evicted churn key still answers on the
      query plane from history, labeled ``source:"evicted"``;
    - ``gen_refused``: a fleet merge across the generation bump
      raises ``ShardMergeError`` (the drift-refusal contract extended
      to recycled ids);
    - ``frames_corrupt == 0`` across every pooled flush;
    - ``rss_slope_ok``: RSS growth from mid-soak to end stays under
      ``rss_slope_limit_mb`` (steady-state slope ≈ 0 — the table is
      bounded, so sustained churn buys sweeps, not memory).

    Returns None when the native decoder can't build (same
    eligibility rule as the million-key soak).
    """
    if not native.available():
        return None
    import tempfile

    import numpy as np

    from ..models.detector import AnomalyDetector, DetectorConfig
    from .fleet import ShardMergeError, merge_shard_arrays
    from .frontdoor import FrontDoorServer
    from .history import HistoryReader, HistoryStore, HistoryWriter
    from .keyspace import KeyspaceManager
    from .pipeline import DetectorPipeline
    from .query import QueryEngine

    capacity = num_services - 1
    churn_per_wave = max(1, -(-churn_multiple * capacity // waves))
    live_names = [
        f"t{i % tenants:02d}.live-{i:03d}" for i in range(live_cohort)
    ]
    rungs = (0.5, 60.0)

    config = DetectorConfig(
        num_services=num_services, hll_p=8, cms_width=1024
    )
    det = AnomalyDetector(config)
    reports = [0]
    pipe = DetectorPipeline(
        det,
        on_report=lambda t, r, flagged: reports.__setitem__(
            0, reports[0] + 1
        ),
        batch_size=num_services,
        keyspace_enable=True,
        keyspace_high_watermark=0.85,
        keyspace_low_watermark=0.70,
        keyspace_hold_s=hold_s,
        # The churn soak exercises the EVICT rung; a huge refill rate
        # keeps a transient THROTTLE excursion from parking churn keys
        # (the throttle rung has its own unit coverage).
        keyspace_newkey_rate=1e9,
        keyspace_retry_after_s=0.5,
    )
    tz = pipe.tensorizer

    def snap() -> tuple[dict, dict]:
        with pipe._dispatch_lock:  # noqa: SLF001 — the snapshot contract
            arrays = {
                k: np.asarray(v)
                for k, v in pipe.detector.state._asdict().items()
            }
        meta = {
            "service_names": tz.service_names,
            "config": list(config._replace(sketch_impl=None)),
            "generation": tz.generation,
            "query": {},
        }
        return arrays, meta

    pool = IngestPool(
        pipe.submit_columns, pipe.tensorizer, workers=workers,
        coalesce_max=64, max_pending=256,
    )
    use_fd = via_frontdoor and native.frontdoor_available()
    fd = (
        FrontDoorServer(pool, port=0, max_body_bytes=8 << 20, max_conns=4)
        if use_fd else None
    )
    conn = (
        socket.create_connection(("127.0.0.1", fd.port))
        if fd is not None else None
    )
    if conn is not None:
        conn.settimeout(30.0)

    shed = [0]

    def post(payload: bytes) -> None:
        if conn is not None:
            conn.sendall(
                b"POST /v1/traces HTTP/1.1\r\nHost: churn\r\n"
                b"Content-Length: %d\r\n\r\n" % len(payload) + payload
            )
            buf = b""
            while b"\r\n\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError("front door closed mid-soak")
                buf += chunk
            if buf.split(b" ", 2)[1] != b"200":
                shed[0] += 1
        else:
            while True:
                try:
                    pool.submit(payload)
                    return
                except IngestPoolSaturated:
                    pipe.pump()
                    time.sleep(0.001)

    tmpdir = tempfile.TemporaryDirectory(prefix="churnsoak-")
    store = HistoryStore(tmpdir.name)
    # Writer thread NOT started: the regular window ladder is not
    # under test; the evictor calls record_eviction directly.
    writer = HistoryWriter(store, snap, rungs=rungs)
    mgr = KeyspaceManager(
        pipe, idle_s=idle_s, evict_batch=num_services,
        history_writer=writer,
    )

    live_ids: list[int] = []
    live_stable = True
    max_level = 0
    rss_mid = rss_end = None
    t0 = time.perf_counter()
    try:
        for w in range(waves):
            churn = [
                f"t{j % tenants:02d}.churn-{w:03d}-{j:05d}"
                for j in range(churn_per_wave)
            ]
            post(make_named_payload(churn))
            pool.drain()
            pipe.pump()
            max_level = max(max_level, mgr.tick()["level"])
            # Let this wave's churn go idle; the live cohort is
            # re-shipped AFTER the sleep so recency protects it at
            # the eviction tick below.
            time.sleep(idle_s + 0.05)
            post(make_named_payload(live_names))
            pool.drain()
            pipe.pump()
            max_level = max(max_level, mgr.tick()["level"])
            if not live_ids:
                snapshot = tz._svc_snapshot  # noqa: SLF001
                live_ids = [snapshot.get(n) for n in live_names]
            else:
                snapshot = tz._svc_snapshot  # noqa: SLF001
                live_stable = live_stable and all(
                    snapshot.get(n) == sid
                    for n, sid in zip(live_names, live_ids)
                )
            # De-escalation ticks: eviction dropped fill below the low
            # watermark; walk the ladder back down so a long soak
            # never staircases to the shed rung.
            for _ in range(3):
                time.sleep(hold_s + 0.01)
                mgr.tick()
            if w == waves // 2:
                rss_mid = _rss_kb()
        pool.drain()
        pipe.pump()
        pipe.drain()
        rss_end = _rss_kb()
    finally:
        if conn is not None:
            conn.close()
        if fd is not None:
            fd.stop()
        pool_stats = pool.stats()
        pool.close()
    elapsed = time.perf_counter() - t0

    live_stable = live_stable and bool(live_ids) and all(
        sid is not None for sid in live_ids
    )
    arrays, _meta = snap()
    live_rows_ok = bool(live_ids) and all(
        sid is not None and bool(np.any(arrays["hll_bank"][:, :, sid, :]))
        for sid in live_ids
    )

    # An evicted churn key must still answer from history, labeled.
    evicted_name = next(
        (
            n for w in range(waves) for n in (
                f"t{j % tenants:02d}.churn-{w:03d}-{j:05d}"
                for j in range(churn_per_wave)
            )
            if n not in tz._svc_snapshot  # noqa: SLF001
        ),
        None,
    ) if mgr.evictions else None
    evicted_query_ok = False
    if evicted_name is not None:
        engine = QueryEngine(
            snap, history=HistoryReader(store, rungs=rungs)
        )
        try:
            got = engine.cardinality(evicted_name)
            evicted_query_ok = got["meta"].get("source") == "evicted"
        except Exception:  # noqa: BLE001 — a failed read is a failed gate
            evicted_query_ok = False

    # Fleet pair across the generation bump: REFUSED.
    a = {"cms_bank": np.ones((64, 16), np.int32)}
    b = {"cms_bank": np.ones((64, 16), np.int32)}
    try:
        merge_shard_arrays(
            a, b, dst_generation=tz.generation, src_generation=0
        )
        gen_refused = False
    except ShardMergeError:
        gen_refused = True

    rss_slope_mb = (
        (rss_end - rss_mid) / 1024.0
        if rss_end is not None and rss_mid is not None else None
    )
    rss_slope_ok = (
        bool(rss_slope_mb <= rss_slope_limit_mb)
        if rss_slope_mb is not None else None
    )
    frames_corrupt = int(pool_stats.get("frames_corrupt", 0))
    tmpdir.cleanup()
    return {
        "capacity": capacity,
        "distinct_streamed": live_cohort + churn_per_wave * waves,
        "live_cohort": live_cohort,
        "waves": waves,
        "evictions": int(mgr.evictions),
        "sweeps": int(mgr.sweeps),
        "generation": int(tz.generation),
        "evicted_total": int(tz.evicted_total),
        "overflow_assigns": int(tz.overflow_assigns_total),
        "eviction_records": int(writer.evictions_recorded),
        "max_level": int(max_level),
        "shed_responses": int(shed[0]),
        "reports": int(reports[0]),
        "via_frontdoor": bool(use_fd),
        "elapsed_s": round(elapsed, 2),
        "live_ids_stable": bool(live_stable),
        "live_rows_ok": bool(live_rows_ok),
        "evicted_query_ok": bool(evicted_query_ok),
        "gen_refused": bool(gen_refused),
        "frames_corrupt": frames_corrupt,
        "rss_mid_kb": rss_mid,
        "rss_end_kb": rss_end,
        "rss_slope_mb": (
            round(rss_slope_mb, 1) if rss_slope_mb is not None else None
        ),
        "rss_slope_ok": rss_slope_ok,
        "churn_ok": bool(
            mgr.evictions > 0
            and tz.generation > 0
            and live_stable
            and live_rows_ok
            and evicted_query_ok
            and gen_refused
            and frames_corrupt == 0
            and rss_slope_ok is not False
        ),
    }


def main() -> None:
    import json
    import os

    perf = measure_frontdoor_vs_pool(
        workers=int(os.environ.get("BENCH_FRONTDOOR_WORKERS", "2")),
        seconds=float(os.environ.get("BENCH_FRONTDOOR_SECONDS", "4.0")),
    )
    soak = measure_million_key_soak(
        target_keys=int(
            os.environ.get("BENCH_FRONTDOOR_KEYS", str(1_048_576))
        ),
    )
    churn = measure_churn_soak(
        waves=int(os.environ.get("BENCH_CHURN_WAVES", "8")),
    )
    eligible = (os.cpu_count() or 1) >= 2
    print(
        json.dumps(
            {
                "metric": "frontdoor_vs_pool_and_million_key_soak",
                "frontdoor": perf or {},
                "soak": soak or {},
                "churn": churn or {},
                # Same null-when-ineligible convention as bench.py's
                # decode_wall_ok: on a 1-core box neither door can
                # overlap anything, so pass/fail is unmeasurable.
                "frontdoor_ok": (
                    bool(
                        perf["frontdoor_spans_per_sec"]
                        >= perf["pool_spans_per_sec"]
                    )
                    if perf is not None and eligible else None
                ),
                "soak_ok": (soak or {}).get("soak_ok"),
                "soak_rss_ok": (soak or {}).get("soak_rss_ok"),
                "churn_ok": (churn or {}).get("churn_ok"),
            },
            sort_keys=True,
        )
    )


if __name__ == "__main__":
    main()
