"""Failover + fleet-reshard drill driver: one methodology, bench + tests.

The overloadbench/lagbench sibling for the replication fault class:
run a REAL detector as primary with a live replication link, a standby
applying deltas, then kill the primary abruptly (RST, the SIGKILL
shape from the standby's point of view) and measure the hot-standby
contract end to end:

- ``replication_lag_p99_ms`` — p99 of ship→ack round trips while the
  link is healthy (how stale the standby's mirror can be);
- ``failover_ttd_s`` — wall time from primary death to the standby
  PROMOTED (watchdog fire + epoch bump + state hydration), the blind
  window a host loss actually costs;
- convergence — the promoted state's HLL/CMS equal the primary's last
  acked state exactly (merge semantics, not replay).

The FLEET drill (``--fleet`` / ``make fleetbench``; runtime.fleet +
runtime.aggregator) scales the same methodology to the N-way sharded
tier:

- :func:`measure_reshard` — an in-proc 3-shard fleet under
  deterministic virtual-time load beside an UNKILLED WITNESS fleet
  fed identically; kill one shard (RST, the SIGKILL shape), let
  membership declare it dead (health double-check + hysteresis),
  reshard its keyspace by monoid-merging its last replicated frame
  into the survivors, and pin every post-reshard ``/query/*`` answer
  for the victim's keys BIT-EXACT against the witness. Also drives
  the aggregator's partial-answer contract (one shard blackholed via
  runtime.faultwire → labeled partial 200, never 5xx) and the
  noisy-tenant quota isolation.
- :func:`measure_reshard_live` — the live-fire leg: the victim is a
  REAL daemon subprocess under live Kafka + OTLP load, SIGKILLed
  mid-stream; ``shard_reshard_ttd_s`` is kill → the survivor
  answering queries for the victim's keys from the adopted frame.

``tests/test_replication.py`` / ``tests/test_fleet.py`` assert on
these dicts (the acceptance bars); ``make replbench`` /
``make fleetbench`` print ONE json line each, the bench.py habit.
``bench.py`` lifts ``failover_ttd_s`` / ``replication_lag_p99_ms`` /
``shard_reshard_ttd_s`` / ``fleet_ok`` into the flagship artifact.
"""

from __future__ import annotations

import time

import numpy as np

from ..models import AnomalyDetector, DetectorConfig
from .lagbench import make_columns
from .pipeline import DetectorPipeline
from .replication import EpochFence, ReplicationPrimary, ReplicationStandby


def measure_failover(
    seconds: float = 2.0,
    batch: int = 256,
    interval_s: float = 0.05,
    failover_timeout_s: float = 0.5,
    pump_interval_s: float = 0.01,
    seed: int = 0,
    config: DetectorConfig | None = None,
) -> dict:
    """Drive a primary pipeline under load with a live standby, kill
    the primary, and time the standby's promotion decision + hydration.

    The watchdog here is the same rule the daemon's standby step runs
    (silence > ``failover_timeout_s`` after a completed bootstrap), so
    the number is the deployment's TTD floor, not a toy's.
    """
    config = config or DetectorConfig(
        num_services=8, hll_p=8, cms_width=512
    )
    detector = AnomalyDetector(config)
    pipe = DetectorPipeline(detector, batch_size=batch)
    offsets = {0: 0}

    def snapshot():
        with pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in detector.state._asdict().items()
            }
            clock_t_prev = detector.clock._t_prev
        return arrays, {
            "offsets": dict(offsets),
            "service_names": pipe.tensorizer.service_names,
            "clock_t_prev": clock_t_prev,
            "config": list(config._replace(sketch_impl=None)),
        }

    fence_p = EpochFence(0)
    primary = ReplicationPrimary(
        snapshot, fence_p, interval_s=interval_s
    )
    primary.start()
    fence_s = EpochFence(0)
    standby = ReplicationStandby(
        f"127.0.0.1:{primary.port}", fence_s,
        config_fingerprint=list(config._replace(sketch_impl=None)),
    )
    standby.start()
    if not standby.wait_for_state(10.0):
        raise RuntimeError("standby never bootstrapped")

    # Load: realistic columns at a steady cadence, offsets advancing
    # the way confirmed Kafka offsets would. One warmup dispatch first
    # so the jit compile doesn't eat the timed load window.
    rng = np.random.default_rng(seed)
    pipe.submit_columns(make_columns(rng, batch))
    pipe.pump(0.0)
    pipe.drain()
    offsets[0] += batch
    t_end = time.monotonic() + seconds
    t = pump_interval_s  # virtual clock continues past the warmup pump
    while time.monotonic() < t_end:
        pipe.submit_columns(make_columns(rng, batch))
        pipe.pump(t)
        offsets[0] += batch
        t += pump_interval_s
        time.sleep(pump_interval_s)
    pipe.drain()

    # Let the link quiesce so the standby's mirror reaches the final
    # state (one last delta + ack), then record the healthy-link lag.
    deadline = time.monotonic() + max(10 * interval_s, 2.0)
    final = snapshot()[0]
    while time.monotonic() < deadline:
        arrs, _meta = standby.snapshot()
        if arrs and (arrs["cms_bank"] == final["cms_bank"]).all():
            break
        time.sleep(interval_s / 2)
    stats = primary.stats()
    lag_p99_ms = stats["ack_lag_p99_ms"]

    # Death: RST every session — what a SIGKILLed host looks like.
    t_kill = time.monotonic()
    primary.kill()
    # The standby-side watchdog loop (the daemon's _standby_step rule).
    promoted_at = None
    give_up = t_kill + failover_timeout_s * 20 + 10.0
    while time.monotonic() < give_up:
        if (
            standby.seconds_since_frame() > failover_timeout_s
            and standby.applied_seq >= 0
        ):
            fence_s.bump()  # the promotion's first act
            promoted_at = time.monotonic()
            break
        time.sleep(0.005)
    if promoted_at is None:
        raise RuntimeError("standby never promoted")
    arrays, meta = standby.snapshot()
    standby.stop()
    converged = bool(
        arrays
        and (arrays["cms_bank"] == final["cms_bank"]).all()
        and (arrays["hll_bank"] == final["hll_bank"]).all()
    )
    return {
        "failover_ttd_s": round(promoted_at - t_kill, 4),
        "replication_lag_p99_ms": (
            round(lag_p99_ms, 3) if lag_p99_ms is not None else None
        ),
        "failover_timeout_s": failover_timeout_s,
        "replication_interval_s": interval_s,
        "deltas_shipped": stats["deltas_shipped"],
        "snapshots_shipped": stats["snapshots_shipped"],
        "converged_exact": converged,
        "promoted_epoch": fence_s.epoch,
        "replicated_offsets": meta.get("offsets"),
        "spans_fed": int(pipe.stats.spans),
    }


# -- the N-way fleet reshard drill (runtime.fleet) ----------------------

FLEET_SERVICES = (
    "frontend", "cart", "checkout", "currency", "payment", "email",
)
FLEET_TENANTS = {
    "frontend": "web", "cart": "web", "checkout": "web",
    "currency": "platform", "payment": "platform", "email": "platform",
}


def _fleet_records(rng: np.ndarray, service: str, n: int) -> list:
    """Deterministic per-service span records: the fleet shard and its
    witness twin are fed byte-identical streams."""
    from .tensorize import SpanRecord

    return [
        SpanRecord(
            service=service,
            duration_us=float(200.0 + 50.0 * rng.random()),
            trace_id=rng.bytes(8),
            is_error=bool(rng.random() < 0.02),
            attr=f"a{int(rng.integers(0, 8))}",
        )
        for _ in range(n)
    ]


class _Shard:
    """One in-proc fleet member: detector + pipeline with the SHARED
    pre-interned service table, plus a live replication primary so a
    mirror of its state exists to adopt after its death."""

    def __init__(self, name: str, config: DetectorConfig, batch: int,
                 interval_s: float):
        self.name = name
        self.detector = AnomalyDetector(config)
        self.pipe = DetectorPipeline(self.detector, batch_size=batch)
        for svc in FLEET_SERVICES:  # the shared-table contract
            self.pipe.tensorizer.service_id(svc)
        self.fence = EpochFence(0)
        self.primary = ReplicationPrimary(
            self._snapshot, self.fence, interval_s=interval_s
        )
        self.primary.start()

    def _snapshot(self):
        with self.pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in self.detector.state._asdict().items()
            }
            clock_t_prev = self.detector.clock._t_prev
        return arrays, {
            "offsets": {},
            "service_names": self.pipe.tensorizer.service_names,
            "clock_t_prev": clock_t_prev,
            "config": list(
                self.detector.config._replace(sketch_impl=None)
            ),
        }

    def arrays(self) -> dict:
        return self._snapshot()[0]

    def stop(self) -> None:
        self.primary.stop()


def _query_docs(arrays: dict, meta: dict, services) -> dict:
    """The /query/* answer set for a service list over one state —
    the bit-comparable unit the witness pin asserts on (same pure
    numpy read fns both the shard plane and a read replica run)."""
    from . import query as q

    return {
        svc: {
            "cardinality": q.cardinality(arrays, meta, svc),
            "topk": q.topk_heavy_hitters(arrays, meta, svc, k=5),
            "zscore": q.zscore_state(arrays, meta, svc),
        }
        for svc in services
    }


def measure_reshard(
    seconds: float = 1.5,
    batch: int = 256,
    interval_s: float = 0.05,
    dead_after_s: float = 0.35,
    pump_interval_s: float = 0.05,
    rows_per_service: int = 24,
    seed: int = 7,
    config: DetectorConfig | None = None,
) -> dict:
    """The in-proc shard-kill → reshard drill (module docstring).

    3 shards + a 3-shard WITNESS fleet fed byte-identical virtual-time
    streams; kill shard-1's replication abruptly, detect death through
    the membership guardrails, monoid-merge its mirror frame into the
    survivors, and pin the post-reshard answers for the victim's keys
    bit-exact against the witness fleet merged the same way.
    """
    from . import query as q  # noqa: F401 — via _query_docs
    from .fleet import (
        FleetMembership,
        HashRing,
        merge_shard_arrays,
        service_row_mask,
        shard_key,
        tenant_of,
    )

    config = config or DetectorConfig(
        num_services=8, hll_p=8, cms_width=512
    )
    shard_ids = ["shard-0", "shard-1", "shard-2"]
    victim_id = "shard-1"
    ring = HashRing(shard_ids, vnodes=128)
    owner_of = {
        svc: ring.owner(shard_key(svc, tenant_of(svc, FLEET_TENANTS)))
        for svc in FLEET_SERVICES
    }
    # The ring must actually give the victim a slice for the drill to
    # mean anything; with 6 keys × 128 vnodes it always does, but
    # assert rather than assume.
    victim_services = [s for s, o in owner_of.items() if o == victim_id]
    if not victim_services:
        raise RuntimeError("ring assigned the victim no keyspace")

    fleet = {s: _Shard(s, config, batch, interval_s) for s in shard_ids}
    witness = {s: _Shard(s, config, batch, interval_s) for s in shard_ids}
    # The victim's hot mirror: the frame the survivors adopt. (In the
    # deployed fleet every shard has one — its standby; the drill
    # mirrors only the shard it will kill.)
    mirror_fence = EpochFence(0)
    mirror = ReplicationStandby(
        f"127.0.0.1:{fleet[victim_id].primary.port}", mirror_fence,
        config_fingerprint=list(config._replace(sketch_impl=None)),
    )
    mirror.start()
    try:
        if not mirror.wait_for_state(10.0):
            raise RuntimeError("victim mirror never bootstrapped")

        # Virtual-time load, routed by ring ownership, fed IDENTICALLY
        # to fleet and witness (one rng stream per (service, step)).
        steps = max(int(seconds / pump_interval_s), 8)
        t = 0.0
        for i in range(steps):
            for svc in FLEET_SERVICES:
                rng = np.random.default_rng(
                    seed * 100003 + i * 131 + hash_stable(svc)
                )
                records = _fleet_records(rng, svc, rows_per_service)
                fleet[owner_of[svc]].pipe.submit(records)
                witness[owner_of[svc]].pipe.submit(records)
            for shard in (*fleet.values(), *witness.values()):
                shard.pipe.pump(t)
            t += pump_interval_s
        for shard in (*fleet.values(), *witness.values()):
            shard.pipe.drain()

        # Quiesce: the mirror must carry the victim's final state (the
        # documented replication bound — under live flow the adopted
        # frame lags by ≤ one interval; the BIT-EXACT pin needs the
        # acked frame to BE the final state, as in measure_failover).
        final_victim = fleet[victim_id].arrays()
        deadline = time.monotonic() + max(10 * interval_s, 2.0)
        while time.monotonic() < deadline:
            arrs, _m = mirror.snapshot()
            if arrs and (
                arrs["cms_bank"] == final_victim["cms_bank"]
            ).all() and (
                arrs["hll_bank"] == final_victim["hll_bank"]
            ).all():
                break
            time.sleep(interval_s / 2)

        # Membership over the fleet, with the health double-check the
        # chaos tests reuse (a serving shard is never declared dead).
        alive = {s: True for s in shard_ids}
        membership = FleetMembership(
            "shard-0", [s for s in shard_ids if s != "shard-0"],
            vnodes=128, dead_after_s=dead_after_s,
            rejoin_after_s=1.0, reshard_budget=4,
            reshard_refill_s=60.0,
            health_check=lambda s: alive[s],
        )
        for s in shard_ids[1:]:
            membership.observe(s)

        # KILL: RST every replication session + health goes dark — the
        # SIGKILL shape (the live-fire leg does the real SIGKILL).
        t_kill = time.monotonic()
        fleet[victim_id].primary.kill()
        alive[victim_id] = False
        events: list = []
        give_up = t_kill + dead_after_s * 20 + 5.0
        while time.monotonic() < give_up and not events:
            events = membership.tick()
            if not events:
                membership.observe("shard-2")  # survivor stays fresh
                time.sleep(0.02)
        if not any(
            e["op"] == "leave" and e["shard"] == victim_id
            for e in events
        ):
            raise RuntimeError("membership never declared the victim dead")

        # RESHARD: adopt the victim's last replicated frame into every
        # survivor (reads route by ownership, so the add-merge can
        # never double-count an answer), then answer for its keys.
        mirror_arrays, mirror_meta = mirror.snapshot()
        survivors = [s for s in shard_ids if s != victim_id]
        merged: dict[str, dict] = {}
        for s in survivors:
            dst = fleet[s].arrays()
            mask = service_row_mask(
                list(mirror_meta.get("service_names") or []),
                fleet[s].pipe.tensorizer.service_names,
                int(dst["lat_mean"].shape[0]),
                owned=victim_services,
            )
            merged[s] = merge_shard_arrays(dst, mirror_arrays, mask)
        # TTD: kill → a survivor answering the victim's keys.
        meta = {
            "service_names": list(FLEET_SERVICES),
            "config": list(config._replace(sketch_impl=None)),
        }
        post_owner = {
            svc: membership.ring.owner(
                shard_key(svc, tenant_of(svc, FLEET_TENANTS))
            )
            for svc in victim_services
        }
        answers = {
            svc: _query_docs(merged[post_owner[svc]], meta, [svc])[svc]
            for svc in victim_services
        }
        ttd_s = time.monotonic() - t_kill
        answered = all(
            max(a["cardinality"]["estimate"]) > 0.0
            for a in answers.values()
        )

        # WITNESS PIN: the unkilled witness fleet, merged identically,
        # must answer bit-exactly for every service on every survivor.
        witness_merged: dict[str, dict] = {}
        for s in survivors:
            dst = witness[s].arrays()
            mask = service_row_mask(
                witness[victim_id].pipe.tensorizer.service_names,
                witness[s].pipe.tensorizer.service_names,
                int(dst["lat_mean"].shape[0]),
                owned=victim_services,
            )
            witness_merged[s] = merge_shard_arrays(
                dst, witness[victim_id].arrays(), mask
            )
        bitexact = True
        for s in survivors:
            got = _query_docs(merged[s], meta, FLEET_SERVICES)
            want = _query_docs(witness_merged[s], meta, FLEET_SERVICES)
            if got != want:
                bitexact = False
            for name in ("hll_bank", "cms_bank"):
                if not (merged[s][name] == witness_merged[s][name]).all():
                    bitexact = False
    finally:
        mirror.stop()
        for shard in (*fleet.values(), *witness.values()):
            shard.stop()

    partial = _measure_partial_answer(config, batch)
    tenant = _measure_tenant_isolation(config, batch)
    fleet_ok = bool(
        answered and bitexact
        and partial["partial_answer_ok"]
        and tenant["noisy_tenant_isolated"]
    )
    return {
        "shard_reshard_ttd_s": round(ttd_s, 4),
        "fleet_shards": len(shard_ids),
        "victim": victim_id,
        "victim_services": victim_services,
        "reshards_applied": membership.reshards_total,
        "reshard_bitexact": bitexact,
        "survivor_answers_victim_keys": answered,
        "dead_after_s": dead_after_s,
        **partial,
        **tenant,
        "fleet_ok": fleet_ok,
    }


def hash_stable(s: str) -> int:
    """Deterministic small int from a string (NOT hash(): the drill's
    rng seeds must not change across processes)."""
    from .fleet import key_hash64

    return key_hash64(s) % 65521


def _measure_partial_answer(config: DetectorConfig, batch: int) -> dict:
    """Aggregator degradation leg: two real shard query planes, one
    BLACKHOLED via runtime.faultwire — the merged answer must come
    back 200, labeled partial, never 5xx."""
    from .aggregator import FleetAggregator
    from .faultwire import FaultWire
    from .query import QueryEngine, QueryService

    shards = {}
    services = []
    wire = None
    aggregator = None
    try:
        for name in ("shard-0", "shard-1"):
            det = AnomalyDetector(config)
            pipe = DetectorPipeline(det, batch_size=batch)
            for svc in FLEET_SERVICES:
                pipe.tensorizer.service_id(svc)
            rng = np.random.default_rng(11)
            pipe.submit_columns(make_columns(rng, batch))
            pipe.pump(0.0)
            pipe.drain()

            def snapshot(det=det, pipe=pipe):
                with pipe._dispatch_lock:
                    arrays = {
                        k: np.asarray(v)
                        for k, v in det.state._asdict().items()
                    }
                return arrays, {
                    "service_names": pipe.tensorizer.service_names,
                    "config": list(
                        det.config._replace(sketch_impl=None)
                    ),
                    "query": pipe.query_meta(),
                }

            engine = QueryEngine(snapshot_fn=snapshot)
            service = QueryService(engine, host="127.0.0.1", port=0)
            service.start()
            services.append(service)
            shards[name] = f"127.0.0.1:{service.port}"
        # Blackhole shard-1 behind a faultwire proxy: accepted
        # connections, every byte dropped — the half-open worst case.
        wire = FaultWire("127.0.0.1", services[1].port)
        wire.blackhole = True
        wire.start()
        shards["shard-1"] = f"127.0.0.1:{wire.port}"
        aggregator = FleetAggregator(shards, timeout_s=0.5)
        status, doc = aggregator.dispatch("/query/services", {})
        meta = doc.get("meta") or {}
        ok = (
            status == 200
            and meta.get("partial") is True
            and meta.get("shards_answered") == 1
            and meta.get("shards_total") == 2
            and not meta.get("shards", {}).get("shard-1", {}).get("ok")
            and (doc.get("data") or {}).get("services")
        )
        return {
            "partial_answer_ok": bool(ok),
            "partial_shards_answered": meta.get("shards_answered"),
        }
    finally:
        if aggregator is not None:
            aggregator.close()
        if wire is not None:
            wire.stop()
        for service in services:
            service.stop()


def _measure_tenant_isolation(config: DetectorConfig, batch: int) -> dict:
    """Noisy-tenant leg: one tenant floods far past its quota — ONLY
    its rows shed (anomaly_shed_rows_total{tenant=} isolated), the
    quiet tenant's rows all admitted."""
    from .fleet import tenant_of

    det = AnomalyDetector(config)
    pipe = DetectorPipeline(
        det, batch_size=batch,
        tenant_of=lambda name: tenant_of(name, FLEET_TENANTS),
        tenant_quota_rows_s=500.0,
    )
    for svc in FLEET_SERVICES:
        pipe.tensorizer.service_id(svc)
    rng = np.random.default_rng(3)
    # The web tenant floods (frontend), platform stays modest (payment).
    for _ in range(6):
        pipe.submit(_fleet_records(rng, "frontend", 400))
        pipe.submit(_fleet_records(rng, "payment", 40))
    shed = dict(pipe.stats.shed_rows_tenant)
    pipe.pump(0.0)
    pipe.drain()
    isolated = bool(
        shed.get("web", 0) > 0 and shed.get("platform", 0) == 0
    )
    return {
        "noisy_tenant_isolated": isolated,
        "tenant_shed_rows": shed,
    }


def measure_reshard_live(
    dead_after_s: float = 2.0,
    batch: int = 128,
) -> dict:
    """Live-fire reshard: the victim shard is a REAL daemon subprocess
    under live Kafka + OTLP load, SIGKILLed mid-stream; an in-proc
    survivor adopts its replicated frame once membership (heartbeating
    the victim's real /healthz, with the double-check) declares it
    dead. ``shard_reshard_ttd_s`` here is the deployment-shaped
    number: real process death, real health silence, real frame
    adoption."""
    import http.client
    import os
    import re
    import signal
    import subprocess
    import sys

    from .fleet import (
        FleetMembership,
        http_health_alive,
        merge_shard_arrays,
        service_row_mask,
    )
    from .kafka_broker import KafkaBroker
    from .kafka_orders import Order, encode_order
    from .otlp_export import encode_export_request

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    config = DetectorConfig(num_services=8, hll_p=8, cms_width=512)
    broker = KafkaBroker()
    broker.start()
    broker.ensure_topic("orders")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env.update({
        "ANOMALY_OTLP_PORT": "0",
        "ANOMALY_OTLP_GRPC_PORT": "-1",
        "ANOMALY_METRICS_PORT": "0",
        "ANOMALY_BATCH": str(batch),
        "ANOMALY_PUMP_INTERVAL_S": "0.05",
        "ANOMALY_NUM_SERVICES": "8",
        "ANOMALY_CMS_WIDTH": "512",
        "ANOMALY_HLL_P": "8",
        "ANOMALY_INGEST_WORKERS": "0",
        "ANOMALY_ROLE": "primary",
        "ANOMALY_REPLICATION_PORT": "0",
        "ANOMALY_REPLICATION_INTERVAL_S": "0.1",
        "ANOMALY_FLEET_SERVICES": ",".join(FLEET_SERVICES),
        "KAFKA_ADDR": f"127.0.0.1:{broker.port}",
    })
    env.pop("ANOMALY_CHECKPOINT", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "opentelemetry_demo_tpu.runtime.daemon"],
        cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    mirror = None
    try:
        line = None
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            out = proc.stdout.readline()
            if not out:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"victim shard exited rc={proc.returncode}"
                    )
                time.sleep(0.05)
                continue
            if "anomaly-detector:" in out:
                line = out
                break
        if not line:
            raise RuntimeError("victim shard never announced")
        otlp_port = int(re.search(r"otlp-http :(\d+)", line).group(1))
        repl_port = int(re.search(r"repl :(\d+)", line).group(1))
        metrics_port = int(
            re.search(r"metrics :(\d+)", line).group(1)
        )

        # Live load on both legs: orders into the broker + spans over
        # OTLP at the victim.
        for i in range(8):
            broker.append("orders", encode_order(Order(
                order_id=f"ord-{i}", tracking_id=f"trk-{i}",
                shipping_cost_units=5.0, item_count=1,
                product_ids=("EYE-PLO-25",), total_quantity=1,
            )))
        rng = np.random.default_rng(5)
        body = encode_export_request(
            _fleet_records(rng, "payment", 64)
            + _fleet_records(rng, "frontend", 64)
        )
        conn = http.client.HTTPConnection(
            "127.0.0.1", otlp_port, timeout=10.0
        )
        conn.request(
            "POST", "/v1/traces", body=body,
            headers={"Content-Type": "application/x-protobuf"},
        )
        if conn.getresponse().status != 200:
            raise RuntimeError("victim refused OTLP load")

        # The survivor's mirror of the victim (its standby, in-proc).
        mirror_fence = EpochFence(0)
        mirror = ReplicationStandby(
            f"127.0.0.1:{repl_port}", mirror_fence,
            config_fingerprint=list(
                config._replace(sketch_impl=None)
            ),
        )
        mirror.start()
        if not mirror.wait_for_state(60.0):
            raise RuntimeError("mirror never bootstrapped")
        # Wait until the mirror has actually absorbed the span load.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            arrs, _m = mirror.snapshot()
            if arrs and float(np.asarray(arrs["span_total"]).sum()) > 0:
                break
            time.sleep(0.1)

        # The survivor shard, in-proc, with the SHARED service table.
        survivor = _Shard("shard-0", config, batch, interval_s=0.1)
        membership = FleetMembership(
            "shard-0", ["shard-1"],
            dead_after_s=dead_after_s, rejoin_after_s=2.0,
            reshard_budget=4, reshard_refill_s=60.0,
            # The REAL double-check: the victim's live /healthz.
            health_check=lambda s: http_health_alive(
                f"127.0.0.1:{metrics_port}", timeout_s=2.0
            ),
        )
        membership.observe("shard-1")

        # SIGKILL, the real thing, mid-load.
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        t_kill = time.monotonic()
        events: list = []
        give_up = t_kill + dead_after_s * 20 + 30.0
        while time.monotonic() < give_up and not events:
            events = membership.tick()
            if not events:
                time.sleep(0.05)
        if not events:
            raise RuntimeError("membership never declared the victim dead")
        mirror_arrays, mirror_meta = mirror.snapshot()
        dst = survivor.arrays()
        mask = service_row_mask(
            list(mirror_meta.get("service_names") or []),
            survivor.pipe.tensorizer.service_names,
            int(dst["lat_mean"].shape[0]),
        )
        merged = merge_shard_arrays(dst, mirror_arrays, mask)
        meta = {
            "service_names": list(FLEET_SERVICES),
            "config": list(config._replace(sketch_impl=None)),
        }
        docs = _query_docs(merged, meta, ["payment", "frontend"])
        ttd_s = time.monotonic() - t_kill
        answered = all(
            max(d["cardinality"]["estimate"]) > 0.0
            for d in docs.values()
        )
        # Adoption exactness, pinned INDEPENDENTLY of the merge
        # implementation: the survivor ingested nothing in this leg,
        # so the post-merge answers for the victim's services must
        # equal the answers computed from the mirror frame ALONE —
        # the unkilled witness for the live leg. (Recomputing the
        # max/add here would just re-run merge_shard_arrays' own
        # arithmetic and could never fail.)
        witness_docs = _query_docs(
            mirror_arrays, meta, ["payment", "frontend"]
        )
        exact = docs == witness_docs
        survivor.stop()
        return {
            "live_sigkill_ttd_s": round(ttd_s, 4),
            "live_survivor_answers": answered,
            "live_adoption_exact": exact,
            "live_reshards_applied": membership.reshards_total,
        }
    finally:
        if mirror is not None:
            mirror.stop()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)
        broker.stop()


def main() -> None:
    import json
    import sys

    if "--fleet" in sys.argv[1:]:
        out = measure_reshard()
        # The live-fire SIGKILL leg (slow: a real daemon subprocess
        # boots + compiles); skip with --no-live for quick iterations.
        if "--no-live" not in sys.argv[1:]:
            out.update(measure_reshard_live())
            out["fleet_ok"] = bool(
                out["fleet_ok"]
                and out["live_survivor_answers"]
                and out["live_adoption_exact"]
            )
        print(json.dumps(out))
        return
    print(json.dumps(measure_failover()))


if __name__ == "__main__":
    main()
