"""Failover drill driver: one methodology, bench + tests.

The overloadbench/lagbench sibling for the replication fault class:
run a REAL detector as primary with a live replication link, a standby
applying deltas, then kill the primary abruptly (RST, the SIGKILL
shape from the standby's point of view) and measure the hot-standby
contract end to end:

- ``replication_lag_p99_ms`` — p99 of ship→ack round trips while the
  link is healthy (how stale the standby's mirror can be);
- ``failover_ttd_s`` — wall time from primary death to the standby
  PROMOTED (watchdog fire + epoch bump + state hydration), the blind
  window a host loss actually costs;
- convergence — the promoted state's HLL/CMS equal the primary's last
  acked state exactly (merge semantics, not replay).

``tests/test_replication.py`` asserts on this dict (the acceptance
bar); ``make replbench`` prints it as ONE json line, the bench.py
habit. ``bench.py`` lifts ``failover_ttd_s`` / ``replication_lag_p99_ms``
into the flagship artifact.
"""

from __future__ import annotations

import time

import numpy as np

from ..models import AnomalyDetector, DetectorConfig
from .lagbench import make_columns
from .pipeline import DetectorPipeline
from .replication import EpochFence, ReplicationPrimary, ReplicationStandby


def measure_failover(
    seconds: float = 2.0,
    batch: int = 256,
    interval_s: float = 0.05,
    failover_timeout_s: float = 0.5,
    pump_interval_s: float = 0.01,
    seed: int = 0,
    config: DetectorConfig | None = None,
) -> dict:
    """Drive a primary pipeline under load with a live standby, kill
    the primary, and time the standby's promotion decision + hydration.

    The watchdog here is the same rule the daemon's standby step runs
    (silence > ``failover_timeout_s`` after a completed bootstrap), so
    the number is the deployment's TTD floor, not a toy's.
    """
    config = config or DetectorConfig(
        num_services=8, hll_p=8, cms_width=512
    )
    detector = AnomalyDetector(config)
    pipe = DetectorPipeline(detector, batch_size=batch)
    offsets = {0: 0}

    def snapshot():
        with pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in detector.state._asdict().items()
            }
            clock_t_prev = detector.clock._t_prev
        return arrays, {
            "offsets": dict(offsets),
            "service_names": pipe.tensorizer.service_names,
            "clock_t_prev": clock_t_prev,
            "config": list(config._replace(sketch_impl=None)),
        }

    fence_p = EpochFence(0)
    primary = ReplicationPrimary(
        snapshot, fence_p, interval_s=interval_s
    )
    primary.start()
    fence_s = EpochFence(0)
    standby = ReplicationStandby(
        f"127.0.0.1:{primary.port}", fence_s,
        config_fingerprint=list(config._replace(sketch_impl=None)),
    )
    standby.start()
    if not standby.wait_for_state(10.0):
        raise RuntimeError("standby never bootstrapped")

    # Load: realistic columns at a steady cadence, offsets advancing
    # the way confirmed Kafka offsets would. One warmup dispatch first
    # so the jit compile doesn't eat the timed load window.
    rng = np.random.default_rng(seed)
    pipe.submit_columns(make_columns(rng, batch))
    pipe.pump(0.0)
    pipe.drain()
    offsets[0] += batch
    t_end = time.monotonic() + seconds
    t = pump_interval_s  # virtual clock continues past the warmup pump
    while time.monotonic() < t_end:
        pipe.submit_columns(make_columns(rng, batch))
        pipe.pump(t)
        offsets[0] += batch
        t += pump_interval_s
        time.sleep(pump_interval_s)
    pipe.drain()

    # Let the link quiesce so the standby's mirror reaches the final
    # state (one last delta + ack), then record the healthy-link lag.
    deadline = time.monotonic() + max(10 * interval_s, 2.0)
    final = snapshot()[0]
    while time.monotonic() < deadline:
        arrs, _meta = standby.snapshot()
        if arrs and (arrs["cms_bank"] == final["cms_bank"]).all():
            break
        time.sleep(interval_s / 2)
    stats = primary.stats()
    lag_p99_ms = stats["ack_lag_p99_ms"]

    # Death: RST every session — what a SIGKILLed host looks like.
    t_kill = time.monotonic()
    primary.kill()
    # The standby-side watchdog loop (the daemon's _standby_step rule).
    promoted_at = None
    give_up = t_kill + failover_timeout_s * 20 + 10.0
    while time.monotonic() < give_up:
        if (
            standby.seconds_since_frame() > failover_timeout_s
            and standby.applied_seq >= 0
        ):
            fence_s.bump()  # the promotion's first act
            promoted_at = time.monotonic()
            break
        time.sleep(0.005)
    if promoted_at is None:
        raise RuntimeError("standby never promoted")
    arrays, meta = standby.snapshot()
    standby.stop()
    converged = bool(
        arrays
        and (arrays["cms_bank"] == final["cms_bank"]).all()
        and (arrays["hll_bank"] == final["hll_bank"]).all()
    )
    return {
        "failover_ttd_s": round(promoted_at - t_kill, 4),
        "replication_lag_p99_ms": (
            round(lag_p99_ms, 3) if lag_p99_ms is not None else None
        ),
        "failover_timeout_s": failover_timeout_s,
        "replication_interval_s": interval_s,
        "deltas_shipped": stats["deltas_shipped"],
        "snapshots_shipped": stats["snapshots_shipped"],
        "converged_exact": converged,
        "promoted_epoch": fence_s.epoch,
        "replicated_offsets": meta.get("offsets"),
        "spans_fed": int(pipe.stats.spans),
    }


def main() -> None:
    import json

    print(json.dumps(measure_failover()))


if __name__ == "__main__":
    main()
