"""Failover + fleet-reshard drill driver: one methodology, bench + tests.

The overloadbench/lagbench sibling for the replication fault class:
run a REAL detector as primary with a live replication link, a standby
applying deltas, then kill the primary abruptly (RST, the SIGKILL
shape from the standby's point of view) and measure the hot-standby
contract end to end:

- ``replication_lag_p99_ms`` — p99 of ship→ack round trips while the
  link is healthy (how stale the standby's mirror can be);
- ``failover_ttd_s`` — wall time from primary death to the standby
  PROMOTED (watchdog fire + epoch bump + state hydration), the blind
  window a host loss actually costs;
- convergence — the promoted state's HLL/CMS equal the primary's last
  acked state exactly (merge semantics, not replay).

The FLEET drill (``--fleet`` / ``make fleetbench``; runtime.fleet +
runtime.aggregator) scales the same methodology to the N-way sharded
tier:

- :func:`measure_reshard` — an in-proc 3-shard fleet under
  deterministic virtual-time load beside an UNKILLED WITNESS fleet
  fed identically; kill one shard (RST, the SIGKILL shape), let
  membership declare it dead (health double-check + hysteresis),
  reshard its keyspace by monoid-merging its last replicated frame
  into the survivors, and pin every post-reshard ``/query/*`` answer
  for the victim's keys BIT-EXACT against the witness. Also drives
  the aggregator's partial-answer contract (one shard blackholed via
  runtime.faultwire → labeled partial 200, never 5xx) and the
  noisy-tenant quota isolation.
- :func:`measure_reshard_live` — the live-fire leg: the victim is a
  REAL daemon subprocess under live Kafka + OTLP load, SIGKILLed
  mid-stream; ``shard_reshard_ttd_s`` is kill → the survivor
  answering queries for the victim's keys from the adopted frame.

``tests/test_replication.py`` / ``tests/test_fleet.py`` assert on
these dicts (the acceptance bars); ``make replbench`` /
``make fleetbench`` print ONE json line each, the bench.py habit.
``bench.py`` lifts ``failover_ttd_s`` / ``replication_lag_p99_ms`` /
``shard_reshard_ttd_s`` / ``fleet_ok`` into the flagship artifact.
"""

from __future__ import annotations

import time

import numpy as np

from ..models import AnomalyDetector, DetectorConfig
from .lagbench import make_columns
from .pipeline import DetectorPipeline
from .replication import EpochFence, ReplicationPrimary, ReplicationStandby


def measure_failover(
    seconds: float = 2.0,
    batch: int = 256,
    interval_s: float = 0.05,
    failover_timeout_s: float = 0.5,
    pump_interval_s: float = 0.01,
    seed: int = 0,
    config: DetectorConfig | None = None,
) -> dict:
    """Drive a primary pipeline under load with a live standby, kill
    the primary, and time the standby's promotion decision + hydration.

    The watchdog here is the same rule the daemon's standby step runs
    (silence > ``failover_timeout_s`` after a completed bootstrap), so
    the number is the deployment's TTD floor, not a toy's.
    """
    config = config or DetectorConfig(
        num_services=8, hll_p=8, cms_width=512
    )
    detector = AnomalyDetector(config)
    pipe = DetectorPipeline(detector, batch_size=batch)
    offsets = {0: 0}

    def snapshot():
        with pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in detector.state._asdict().items()
            }
            clock_t_prev = detector.clock._t_prev
        return arrays, {
            "offsets": dict(offsets),
            "service_names": pipe.tensorizer.service_names,
            "clock_t_prev": clock_t_prev,
            "config": list(config._replace(sketch_impl=None)),
        }

    fence_p = EpochFence(0)
    primary = ReplicationPrimary(
        snapshot, fence_p, interval_s=interval_s
    )
    primary.start()
    fence_s = EpochFence(0)
    standby = ReplicationStandby(
        f"127.0.0.1:{primary.port}", fence_s,
        config_fingerprint=list(config._replace(sketch_impl=None)),
    )
    standby.start()
    if not standby.wait_for_state(10.0):
        raise RuntimeError("standby never bootstrapped")

    # Load: realistic columns at a steady cadence, offsets advancing
    # the way confirmed Kafka offsets would. One warmup dispatch first
    # so the jit compile doesn't eat the timed load window.
    rng = np.random.default_rng(seed)
    pipe.submit_columns(make_columns(rng, batch))
    pipe.pump(0.0)
    pipe.drain()
    offsets[0] += batch
    t_end = time.monotonic() + seconds
    t = pump_interval_s  # virtual clock continues past the warmup pump
    while time.monotonic() < t_end:
        pipe.submit_columns(make_columns(rng, batch))
        pipe.pump(t)
        offsets[0] += batch
        t += pump_interval_s
        time.sleep(pump_interval_s)
    pipe.drain()

    # Let the link quiesce so the standby's mirror reaches the final
    # state (one last delta + ack), then record the healthy-link lag.
    deadline = time.monotonic() + max(10 * interval_s, 2.0)
    final = snapshot()[0]
    while time.monotonic() < deadline:
        arrs, _meta = standby.snapshot()
        if arrs and (arrs["cms_bank"] == final["cms_bank"]).all():
            break
        time.sleep(interval_s / 2)
    stats = primary.stats()
    lag_p99_ms = stats["ack_lag_p99_ms"]

    # Death: RST every session — what a SIGKILLed host looks like.
    t_kill = time.monotonic()
    primary.kill()
    # The standby-side watchdog loop (the daemon's _standby_step rule).
    promoted_at = None
    give_up = t_kill + failover_timeout_s * 20 + 10.0
    while time.monotonic() < give_up:
        if (
            standby.seconds_since_frame() > failover_timeout_s
            and standby.applied_seq >= 0
        ):
            fence_s.bump()  # the promotion's first act
            promoted_at = time.monotonic()
            break
        time.sleep(0.005)
    if promoted_at is None:
        raise RuntimeError("standby never promoted")
    arrays, meta = standby.snapshot()
    standby.stop()
    converged = bool(
        arrays
        and (arrays["cms_bank"] == final["cms_bank"]).all()
        and (arrays["hll_bank"] == final["hll_bank"]).all()
    )
    return {
        "failover_ttd_s": round(promoted_at - t_kill, 4),
        "replication_lag_p99_ms": (
            round(lag_p99_ms, 3) if lag_p99_ms is not None else None
        ),
        "failover_timeout_s": failover_timeout_s,
        "replication_interval_s": interval_s,
        "deltas_shipped": stats["deltas_shipped"],
        "snapshots_shipped": stats["snapshots_shipped"],
        "converged_exact": converged,
        "promoted_epoch": fence_s.epoch,
        "replicated_offsets": meta.get("offsets"),
        "spans_fed": int(pipe.stats.spans),
    }


# -- the N-way fleet reshard drill (runtime.fleet) ----------------------

FLEET_SERVICES = (
    "frontend", "cart", "checkout", "currency", "payment", "email",
)
FLEET_TENANTS = {
    "frontend": "web", "cart": "web", "checkout": "web",
    "currency": "platform", "payment": "platform", "email": "platform",
}


def _fleet_records(rng: np.ndarray, service: str, n: int) -> list:
    """Deterministic per-service span records: the fleet shard and its
    witness twin are fed byte-identical streams."""
    from .tensorize import SpanRecord

    return [
        SpanRecord(
            service=service,
            duration_us=float(200.0 + 50.0 * rng.random()),
            trace_id=rng.bytes(8),
            is_error=bool(rng.random() < 0.02),
            attr=f"a{int(rng.integers(0, 8))}",
        )
        for _ in range(n)
    ]


class _Shard:
    """One in-proc fleet member: detector + pipeline with the SHARED
    pre-interned service table, plus a live replication primary so a
    mirror of its state exists to adopt after its death."""

    def __init__(self, name: str, config: DetectorConfig, batch: int,
                 interval_s: float):
        self.name = name
        self.detector = AnomalyDetector(config)
        self.pipe = DetectorPipeline(self.detector, batch_size=batch)
        for svc in FLEET_SERVICES:  # the shared-table contract
            self.pipe.tensorizer.service_id(svc)
        self.fence = EpochFence(0)
        self.primary = ReplicationPrimary(
            self._snapshot, self.fence, interval_s=interval_s
        )
        self.primary.start()

    def _snapshot(self):
        with self.pipe._dispatch_lock:
            arrays = {
                k: np.asarray(v)
                for k, v in self.detector.state._asdict().items()
            }
            clock_t_prev = self.detector.clock._t_prev
        return arrays, {
            "offsets": {},
            "service_names": self.pipe.tensorizer.service_names,
            "clock_t_prev": clock_t_prev,
            "config": list(
                self.detector.config._replace(sketch_impl=None)
            ),
        }

    def arrays(self) -> dict:
        return self._snapshot()[0]

    def stop(self) -> None:
        self.primary.stop()


def _query_docs(arrays: dict, meta: dict, services) -> dict:
    """The /query/* answer set for a service list over one state —
    the bit-comparable unit the witness pin asserts on (same pure
    numpy read fns both the shard plane and a read replica run)."""
    from . import query as q

    return {
        svc: {
            "cardinality": q.cardinality(arrays, meta, svc),
            "topk": q.topk_heavy_hitters(arrays, meta, svc, k=5),
            "zscore": q.zscore_state(arrays, meta, svc),
        }
        for svc in services
    }


def measure_reshard(
    seconds: float = 1.5,
    batch: int = 256,
    interval_s: float = 0.05,
    dead_after_s: float = 0.35,
    pump_interval_s: float = 0.05,
    rows_per_service: int = 24,
    seed: int = 7,
    config: DetectorConfig | None = None,
) -> dict:
    """The in-proc shard-kill → reshard drill (module docstring).

    3 shards + a 3-shard WITNESS fleet fed byte-identical virtual-time
    streams; kill shard-1's replication abruptly, detect death through
    the membership guardrails, monoid-merge its mirror frame into the
    survivors, and pin the post-reshard answers for the victim's keys
    bit-exact against the witness fleet merged the same way.
    """
    from . import query as q  # noqa: F401 — via _query_docs
    from .fleet import (
        FleetMembership,
        HashRing,
        merge_shard_arrays,
        service_row_mask,
        shard_key,
        tenant_of,
    )

    config = config or DetectorConfig(
        num_services=8, hll_p=8, cms_width=512
    )
    shard_ids = ["shard-0", "shard-1", "shard-2"]
    victim_id = "shard-1"
    ring = HashRing(shard_ids, vnodes=128)
    owner_of = {
        svc: ring.owner(shard_key(svc, tenant_of(svc, FLEET_TENANTS)))
        for svc in FLEET_SERVICES
    }
    # The ring must actually give the victim a slice for the drill to
    # mean anything; with 6 keys × 128 vnodes it always does, but
    # assert rather than assume.
    victim_services = [s for s, o in owner_of.items() if o == victim_id]
    if not victim_services:
        raise RuntimeError("ring assigned the victim no keyspace")

    fleet = {s: _Shard(s, config, batch, interval_s) for s in shard_ids}
    witness = {s: _Shard(s, config, batch, interval_s) for s in shard_ids}
    # The victim's hot mirror: the frame the survivors adopt. (In the
    # deployed fleet every shard has one — its standby; the drill
    # mirrors only the shard it will kill.)
    mirror_fence = EpochFence(0)
    mirror = ReplicationStandby(
        f"127.0.0.1:{fleet[victim_id].primary.port}", mirror_fence,
        config_fingerprint=list(config._replace(sketch_impl=None)),
    )
    mirror.start()
    try:
        if not mirror.wait_for_state(10.0):
            raise RuntimeError("victim mirror never bootstrapped")

        # Virtual-time load, routed by ring ownership, fed IDENTICALLY
        # to fleet and witness (one rng stream per (service, step)).
        steps = max(int(seconds / pump_interval_s), 8)
        t = 0.0
        for i in range(steps):
            for svc in FLEET_SERVICES:
                rng = np.random.default_rng(
                    seed * 100003 + i * 131 + hash_stable(svc)
                )
                records = _fleet_records(rng, svc, rows_per_service)
                fleet[owner_of[svc]].pipe.submit(records)
                witness[owner_of[svc]].pipe.submit(records)
            for shard in (*fleet.values(), *witness.values()):
                shard.pipe.pump(t)
            t += pump_interval_s
        for shard in (*fleet.values(), *witness.values()):
            shard.pipe.drain()

        # Quiesce: the mirror must carry the victim's final state (the
        # documented replication bound — under live flow the adopted
        # frame lags by ≤ one interval; the BIT-EXACT pin needs the
        # acked frame to BE the final state, as in measure_failover).
        final_victim = fleet[victim_id].arrays()
        deadline = time.monotonic() + max(10 * interval_s, 2.0)
        while time.monotonic() < deadline:
            arrs, _m = mirror.snapshot()
            if arrs and (
                arrs["cms_bank"] == final_victim["cms_bank"]
            ).all() and (
                arrs["hll_bank"] == final_victim["hll_bank"]
            ).all():
                break
            time.sleep(interval_s / 2)

        # Membership over the fleet, with the health double-check the
        # chaos tests reuse (a serving shard is never declared dead).
        alive = {s: True for s in shard_ids}
        membership = FleetMembership(
            "shard-0", [s for s in shard_ids if s != "shard-0"],
            vnodes=128, dead_after_s=dead_after_s,
            rejoin_after_s=1.0, reshard_budget=4,
            reshard_refill_s=60.0,
            health_check=lambda s: alive[s],
        )
        for s in shard_ids[1:]:
            membership.observe(s)

        # KILL: RST every replication session + health goes dark — the
        # SIGKILL shape (the live-fire leg does the real SIGKILL).
        t_kill = time.monotonic()
        fleet[victim_id].primary.kill()
        alive[victim_id] = False
        events: list = []
        give_up = t_kill + dead_after_s * 20 + 5.0
        while time.monotonic() < give_up and not events:
            events = membership.tick()
            if not events:
                membership.observe("shard-2")  # survivor stays fresh
                time.sleep(0.02)
        if not any(
            e["op"] == "leave" and e["shard"] == victim_id
            for e in events
        ):
            raise RuntimeError("membership never declared the victim dead")

        # RESHARD: adopt the victim's last replicated frame into every
        # survivor (reads route by ownership, so the add-merge can
        # never double-count an answer), then answer for its keys.
        mirror_arrays, mirror_meta = mirror.snapshot()
        survivors = [s for s in shard_ids if s != victim_id]
        merged: dict[str, dict] = {}
        for s in survivors:
            dst = fleet[s].arrays()
            mask = service_row_mask(
                list(mirror_meta.get("service_names") or []),
                fleet[s].pipe.tensorizer.service_names,
                int(dst["lat_mean"].shape[0]),
                owned=victim_services,
            )
            merged[s] = merge_shard_arrays(dst, mirror_arrays, mask)
        # TTD: kill → a survivor answering the victim's keys.
        meta = {
            "service_names": list(FLEET_SERVICES),
            "config": list(config._replace(sketch_impl=None)),
        }
        post_owner = {
            svc: membership.ring.owner(
                shard_key(svc, tenant_of(svc, FLEET_TENANTS))
            )
            for svc in victim_services
        }
        answers = {
            svc: _query_docs(merged[post_owner[svc]], meta, [svc])[svc]
            for svc in victim_services
        }
        ttd_s = time.monotonic() - t_kill
        answered = all(
            max(a["cardinality"]["estimate"]) > 0.0
            for a in answers.values()
        )

        # WITNESS PIN: the unkilled witness fleet, merged identically,
        # must answer bit-exactly for every service on every survivor.
        witness_merged: dict[str, dict] = {}
        for s in survivors:
            dst = witness[s].arrays()
            mask = service_row_mask(
                witness[victim_id].pipe.tensorizer.service_names,
                witness[s].pipe.tensorizer.service_names,
                int(dst["lat_mean"].shape[0]),
                owned=victim_services,
            )
            witness_merged[s] = merge_shard_arrays(
                dst, witness[victim_id].arrays(), mask
            )
        bitexact = True
        for s in survivors:
            got = _query_docs(merged[s], meta, FLEET_SERVICES)
            want = _query_docs(witness_merged[s], meta, FLEET_SERVICES)
            if got != want:
                bitexact = False
            for name in ("hll_bank", "cms_bank"):
                if not (merged[s][name] == witness_merged[s][name]).all():
                    bitexact = False
    finally:
        mirror.stop()
        for shard in (*fleet.values(), *witness.values()):
            shard.stop()

    partial = _measure_partial_answer(config, batch)
    tenant = _measure_tenant_isolation(config, batch)
    fleet_ok = bool(
        answered and bitexact
        and partial["partial_answer_ok"]
        and tenant["noisy_tenant_isolated"]
    )
    return {
        "shard_reshard_ttd_s": round(ttd_s, 4),
        "fleet_shards": len(shard_ids),
        "victim": victim_id,
        "victim_services": victim_services,
        "reshards_applied": membership.reshards_total,
        "reshard_bitexact": bitexact,
        "survivor_answers_victim_keys": answered,
        "dead_after_s": dead_after_s,
        **partial,
        **tenant,
        "fleet_ok": fleet_ok,
    }


def hash_stable(s: str) -> int:
    """Deterministic small int from a string (NOT hash(): the drill's
    rng seeds must not change across processes)."""
    from .fleet import key_hash64

    return key_hash64(s) % 65521


def _measure_partial_answer(config: DetectorConfig, batch: int) -> dict:
    """Aggregator degradation leg: two real shard query planes, one
    BLACKHOLED via runtime.faultwire — the merged answer must come
    back 200, labeled partial, never 5xx."""
    from .aggregator import FleetAggregator
    from .faultwire import FaultWire
    from .query import QueryEngine, QueryService

    shards = {}
    services = []
    wire = None
    aggregator = None
    try:
        for name in ("shard-0", "shard-1"):
            det = AnomalyDetector(config)
            pipe = DetectorPipeline(det, batch_size=batch)
            for svc in FLEET_SERVICES:
                pipe.tensorizer.service_id(svc)
            rng = np.random.default_rng(11)
            pipe.submit_columns(make_columns(rng, batch))
            pipe.pump(0.0)
            pipe.drain()

            def snapshot(det=det, pipe=pipe):
                with pipe._dispatch_lock:
                    arrays = {
                        k: np.asarray(v)
                        for k, v in det.state._asdict().items()
                    }
                return arrays, {
                    "service_names": pipe.tensorizer.service_names,
                    "config": list(
                        det.config._replace(sketch_impl=None)
                    ),
                    "query": pipe.query_meta(),
                }

            engine = QueryEngine(snapshot_fn=snapshot)
            service = QueryService(engine, host="127.0.0.1", port=0)
            service.start()
            services.append(service)
            shards[name] = f"127.0.0.1:{service.port}"
        # Blackhole shard-1 behind a faultwire proxy: accepted
        # connections, every byte dropped — the half-open worst case.
        wire = FaultWire("127.0.0.1", services[1].port)
        wire.blackhole = True
        wire.start()
        shards["shard-1"] = f"127.0.0.1:{wire.port}"
        aggregator = FleetAggregator(shards, timeout_s=0.5)
        status, doc = aggregator.dispatch("/query/services", {})
        meta = doc.get("meta") or {}
        ok = (
            status == 200
            and meta.get("partial") is True
            and meta.get("shards_answered") == 1
            and meta.get("shards_total") == 2
            and not meta.get("shards", {}).get("shard-1", {}).get("ok")
            and (doc.get("data") or {}).get("services")
        )
        return {
            "partial_answer_ok": bool(ok),
            "partial_shards_answered": meta.get("shards_answered"),
        }
    finally:
        if aggregator is not None:
            aggregator.close()
        if wire is not None:
            wire.stop()
        for service in services:
            service.stop()


def _measure_tenant_isolation(config: DetectorConfig, batch: int) -> dict:
    """Noisy-tenant leg: one tenant floods far past its quota — ONLY
    its rows shed (anomaly_shed_rows_total{tenant=} isolated), the
    quiet tenant's rows all admitted."""
    from .fleet import tenant_of

    det = AnomalyDetector(config)
    pipe = DetectorPipeline(
        det, batch_size=batch,
        tenant_of=lambda name: tenant_of(name, FLEET_TENANTS),
        tenant_quota_rows_s=500.0,
    )
    for svc in FLEET_SERVICES:
        pipe.tensorizer.service_id(svc)
    rng = np.random.default_rng(3)
    # The web tenant floods (frontend), platform stays modest (payment).
    for _ in range(6):
        pipe.submit(_fleet_records(rng, "frontend", 400))
        pipe.submit(_fleet_records(rng, "payment", 40))
    shed = dict(pipe.stats.shed_rows_tenant)
    pipe.pump(0.0)
    pipe.drain()
    isolated = bool(
        shed.get("web", 0) > 0 and shed.get("platform", 0) == 0
    )
    return {
        "noisy_tenant_isolated": isolated,
        "tenant_shed_rows": shed,
    }


def measure_reshard_live(
    dead_after_s: float = 2.0,
    batch: int = 128,
) -> dict:
    """Live-fire reshard: the victim shard is a REAL daemon subprocess
    under live Kafka + OTLP load, SIGKILLed mid-stream; an in-proc
    survivor adopts its replicated frame once membership (heartbeating
    the victim's real /healthz, with the double-check) declares it
    dead. ``shard_reshard_ttd_s`` here is the deployment-shaped
    number: real process death, real health silence, real frame
    adoption."""
    import http.client
    import os
    import re
    import signal
    import subprocess
    import sys

    from .fleet import (
        FleetMembership,
        http_health_alive,
        merge_shard_arrays,
        service_row_mask,
    )
    from .kafka_broker import KafkaBroker
    from .kafka_orders import Order, encode_order
    from .otlp_export import encode_export_request

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    config = DetectorConfig(num_services=8, hll_p=8, cms_width=512)
    broker = KafkaBroker()
    broker.start()
    broker.ensure_topic("orders")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env.update({
        "ANOMALY_OTLP_PORT": "0",
        "ANOMALY_OTLP_GRPC_PORT": "-1",
        "ANOMALY_METRICS_PORT": "0",
        "ANOMALY_BATCH": str(batch),
        "ANOMALY_PUMP_INTERVAL_S": "0.05",
        "ANOMALY_NUM_SERVICES": "8",
        "ANOMALY_CMS_WIDTH": "512",
        "ANOMALY_HLL_P": "8",
        "ANOMALY_INGEST_WORKERS": "0",
        "ANOMALY_ROLE": "primary",
        "ANOMALY_REPLICATION_PORT": "0",
        "ANOMALY_REPLICATION_INTERVAL_S": "0.1",
        "ANOMALY_FLEET_SERVICES": ",".join(FLEET_SERVICES),
        "KAFKA_ADDR": f"127.0.0.1:{broker.port}",
    })
    env.pop("ANOMALY_CHECKPOINT", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "opentelemetry_demo_tpu.runtime.daemon"],
        cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    mirror = None
    try:
        line = None
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            out = proc.stdout.readline()
            if not out:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"victim shard exited rc={proc.returncode}"
                    )
                time.sleep(0.05)
                continue
            if "anomaly-detector:" in out:
                line = out
                break
        if not line:
            raise RuntimeError("victim shard never announced")
        otlp_port = int(re.search(r"otlp-http :(\d+)", line).group(1))
        repl_port = int(re.search(r"repl :(\d+)", line).group(1))
        metrics_port = int(
            re.search(r"metrics :(\d+)", line).group(1)
        )

        # Live load on both legs: orders into the broker + spans over
        # OTLP at the victim.
        for i in range(8):
            broker.append("orders", encode_order(Order(
                order_id=f"ord-{i}", tracking_id=f"trk-{i}",
                shipping_cost_units=5.0, item_count=1,
                product_ids=("EYE-PLO-25",), total_quantity=1,
            )))
        rng = np.random.default_rng(5)
        body = encode_export_request(
            _fleet_records(rng, "payment", 64)
            + _fleet_records(rng, "frontend", 64)
        )
        conn = http.client.HTTPConnection(
            "127.0.0.1", otlp_port, timeout=10.0
        )
        conn.request(
            "POST", "/v1/traces", body=body,
            headers={"Content-Type": "application/x-protobuf"},
        )
        if conn.getresponse().status != 200:
            raise RuntimeError("victim refused OTLP load")

        # The survivor's mirror of the victim (its standby, in-proc).
        mirror_fence = EpochFence(0)
        mirror = ReplicationStandby(
            f"127.0.0.1:{repl_port}", mirror_fence,
            config_fingerprint=list(
                config._replace(sketch_impl=None)
            ),
        )
        mirror.start()
        if not mirror.wait_for_state(60.0):
            raise RuntimeError("mirror never bootstrapped")
        # Wait until the mirror has actually absorbed the span load.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            arrs, _m = mirror.snapshot()
            if arrs and float(np.asarray(arrs["span_total"]).sum()) > 0:
                break
            time.sleep(0.1)

        # The survivor shard, in-proc, with the SHARED service table.
        survivor = _Shard("shard-0", config, batch, interval_s=0.1)
        membership = FleetMembership(
            "shard-0", ["shard-1"],
            dead_after_s=dead_after_s, rejoin_after_s=2.0,
            reshard_budget=4, reshard_refill_s=60.0,
            # The REAL double-check: the victim's live /healthz.
            health_check=lambda s: http_health_alive(
                f"127.0.0.1:{metrics_port}", timeout_s=2.0
            ),
        )
        membership.observe("shard-1")

        # SIGKILL, the real thing, mid-load.
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        t_kill = time.monotonic()
        events: list = []
        give_up = t_kill + dead_after_s * 20 + 30.0
        while time.monotonic() < give_up and not events:
            events = membership.tick()
            if not events:
                time.sleep(0.05)
        if not events:
            raise RuntimeError("membership never declared the victim dead")
        mirror_arrays, mirror_meta = mirror.snapshot()
        dst = survivor.arrays()
        mask = service_row_mask(
            list(mirror_meta.get("service_names") or []),
            survivor.pipe.tensorizer.service_names,
            int(dst["lat_mean"].shape[0]),
        )
        merged = merge_shard_arrays(dst, mirror_arrays, mask)
        meta = {
            "service_names": list(FLEET_SERVICES),
            "config": list(config._replace(sketch_impl=None)),
        }
        docs = _query_docs(merged, meta, ["payment", "frontend"])
        ttd_s = time.monotonic() - t_kill
        answered = all(
            max(d["cardinality"]["estimate"]) > 0.0
            for d in docs.values()
        )
        # Adoption exactness, pinned INDEPENDENTLY of the merge
        # implementation: the survivor ingested nothing in this leg,
        # so the post-merge answers for the victim's services must
        # equal the answers computed from the mirror frame ALONE —
        # the unkilled witness for the live leg. (Recomputing the
        # max/add here would just re-run merge_shard_arrays' own
        # arithmetic and could never fail.)
        witness_docs = _query_docs(
            mirror_arrays, meta, ["payment", "frontend"]
        )
        exact = docs == witness_docs
        survivor.stop()
        return {
            "live_sigkill_ttd_s": round(ttd_s, 4),
            "live_survivor_answers": answered,
            "live_adoption_exact": exact,
            "live_reshards_applied": membership.reshards_total,
        }
    finally:
        if mirror is not None:
            mirror.stop()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)
        broker.stop()


def _free_port() -> int:
    """Reserve an ephemeral port number (bind/close: the usual bench
    race window, acceptable on a loopback-only drill)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http_json(port: int, path: str, timeout_s: float = 2.0):
    """GET 127.0.0.1:port/path as parsed JSON; None on any failure."""
    import http.client
    import json as _json

    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=timeout_s
        )
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        conn.close()
        if resp.status != 200:
            return None
        return _json.loads(body)
    except Exception:  # noqa: BLE001 — a dead/booting shard is "no"
        return None


def _read_announce(proc, deadline_s: float = 180.0) -> dict:
    """Block until a daemon subprocess prints its announce line; then
    keep DRAINING its stdout on a thread (an unread pipe would block
    the daemon's own prints mid-drill)."""
    import re
    import threading

    line = None
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        out = proc.stdout.readline()
        if not out:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"shard exited rc={proc.returncode} before announce"
                )
            time.sleep(0.05)
            continue
        if "anomaly-detector:" in out:
            line = out
            break
    if not line:
        raise RuntimeError("shard never announced")

    def _drain() -> None:
        for _ in proc.stdout:
            pass

    threading.Thread(target=_drain, daemon=True).start()
    return {
        "otlp": int(re.search(r"otlp-http :(\d+)", line).group(1)),
        "query": int(re.search(r"query :(\d+)", line).group(1)),
    }


def measure_adoption(
    dead_after_s: float = 2.0,
    batch: int = 256,
    quiet_s: float = 5.0,
) -> dict:
    """The ELASTIC-fleet live drill (`make autoscalebench`): two REAL
    daemon shards wired as an adoptive pair (each mirrors its
    ring-successor's replication stream) with the autoscaler enabled
    on the heir. Ramp OTLP load until the heir's admission saturates
    and the autoscaler proposes scale-out, then SIGKILL the victim
    shard mid-resize and watch the heir adopt its keyspace with ZERO
    operator action — membership double-check, in-daemon monoid merge
    under the dispatch lock, new ring version.

    - ``autoscale_tta_s`` — SIGKILL → the heir's /healthz reporting
      the adoption applied (the zero-operator time-to-adopt);
    - ``autoscale_ok`` — the whole contract: a split was proposed
      under real saturation, adoption happened automatically, the
      heir's post-settle /query/* answers for the victim's keys are
      BIT-EXACT against an unkilled witness (both shards' pre-kill
      mirror frames merged in-proc by the same monoid ops), and the
      controller stays quiet (no further proposals) for ``quiet_s``
      after the resize — no oscillation.
    """
    import http.client
    import json as _json
    import os
    import signal
    import subprocess
    import sys

    from .fleet import (
        HashRing,
        merge_shard_arrays,
        service_row_mask,
        shard_key,
        tenant_of,
    )
    from .otlp_export import encode_export_request

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    config = DetectorConfig(num_services=8, hll_p=8, cms_width=512)
    heartbeat_s = 0.25
    metrics_ports = [_free_port(), _free_port()]
    repl_ports = [_free_port(), _free_port()]
    peers = ",".join(f"127.0.0.1:{p}" for p in metrics_ports)
    repl_peers = ",".join(f"127.0.0.1:{p}" for p in repl_ports)

    base_env = dict(os.environ)
    base_env.pop("PALLAS_AXON_POOL_IPS", None)
    base_env.pop("ANOMALY_CHECKPOINT", None)
    base_env["JAX_PLATFORMS"] = "cpu"
    base_env["PYTHONPATH"] = repo + os.pathsep + base_env.get(
        "PYTHONPATH", ""
    )
    base_env["PYTHONUNBUFFERED"] = "1"
    base_env.update({
        "ANOMALY_OTLP_PORT": "0",
        "ANOMALY_OTLP_GRPC_PORT": "-1",
        "ANOMALY_QUERY_PORT": "0",
        "ANOMALY_BATCH": str(batch),
        "ANOMALY_PUMP_INTERVAL_S": "0.2",
        "ANOMALY_NUM_SERVICES": "8",
        "ANOMALY_CMS_WIDTH": "512",
        "ANOMALY_HLL_P": "8",
        "ANOMALY_INGEST_WORKERS": "0",
        "ANOMALY_ROLE": "primary",
        "ANOMALY_REPLICATION_INTERVAL_S": "0.1",
        # Selftrace spans would keep mutating the heir's sketches
        # after the witness snapshot — off for the bit-exact pin.
        "ANOMALY_SELFTRACE_ENABLE": "0",
        # Tight snapshot cache: the post-adoption /query/* pin must
        # not be answered from a pre-merge cached snapshot.
        "ANOMALY_QUERY_MAX_STALENESS_S": "0.25",
        "ANOMALY_FLEET_SHARDS": "2",
        "ANOMALY_FLEET_PEERS": peers,
        "ANOMALY_FLEET_REPL_PEERS": repl_peers,
        "ANOMALY_FLEET_SERVICES": ",".join(FLEET_SERVICES),
        "ANOMALY_FLEET_HEARTBEAT_S": str(heartbeat_s),
        "ANOMALY_FLEET_DEAD_AFTER_S": str(dead_after_s),
        "ANOMALY_FLEET_REJOIN_AFTER_S": "2.0",
        "KAFKA_ADDR": "",
    })
    heir_env = dict(base_env)
    heir_env.update({
        "ANOMALY_FLEET_SHARD_INDEX": "0",
        "ANOMALY_METRICS_PORT": str(metrics_ports[0]),
        "ANOMALY_REPLICATION_PORT": str(repl_ports[0]),
        # The elastic half under test: opt-in autoscaler on the heir,
        # with a small row budget so the ramp actually saturates.
        "ANOMALY_AUTOSCALE_ENABLE": "1",
        "ANOMALY_AUTOSCALE_ACT_BATCHES": "3",
        "ANOMALY_AUTOSCALE_CLEAR_BATCHES": "120",
        "ANOMALY_AUTOSCALE_BUDGET": "2",
        "ANOMALY_AUTOSCALE_REFILL_S": "300.0",
        "ANOMALY_QUEUE_MAX_ROWS": "1024",
    })
    victim_env = dict(base_env)
    victim_env.update({
        "ANOMALY_FLEET_SHARD_INDEX": "1",
        "ANOMALY_METRICS_PORT": str(metrics_ports[1]),
        "ANOMALY_REPLICATION_PORT": str(repl_ports[1]),
    })

    # Route load by the SAME ring the daemons build (member ids,
    # default vnodes, default tenant map).
    ring = HashRing(["shard-0", "shard-1"], vnodes=128)
    owner_of = {
        svc: ring.owner(shard_key(svc, tenant_of(svc, {})))
        for svc in FLEET_SERVICES
    }
    heir_services = [s for s, o in owner_of.items() if o == "shard-0"]
    victim_services = [s for s, o in owner_of.items() if o == "shard-1"]
    if not heir_services or not victim_services:
        raise RuntimeError("ring left one shard without keyspace")

    def spawn(env):
        return subprocess.Popen(
            [sys.executable, "-m", "opentelemetry_demo_tpu.runtime.daemon"],
            cwd=repo, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )

    def post_spans(otlp_port: int, services, rows: int, rng) -> None:
        body = encode_export_request([
            rec
            for svc in services
            for rec in _fleet_records(rng, svc, rows)
        ])
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", otlp_port, timeout=5.0
            )
            conn.request(
                "POST", "/v1/traces", body=body,
                headers={"Content-Type": "application/x-protobuf"},
            )
            conn.getresponse().read()
            conn.close()
        except Exception:  # noqa: BLE001 — 429/refused mid-saturation
            pass            # IS the drill working

    heir = spawn(heir_env)
    victim = spawn(victim_env)
    witness_victim = witness_heir = None
    try:
        heir_ports = _read_announce(heir)
        victim_ports = _read_announce(victim)

        # Membership must see the pair before anything can be adopted.
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            doc = _http_json(metrics_ports[0], "/healthz")
            fleet_doc = (doc or {}).get("fleet") or {}
            if fleet_doc.get("shards_live") == 2:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("heir never saw the victim alive")

        # The unkilled WITNESS: both shards' replication streams
        # mirrored in-proc — their pre-kill frames merged by the same
        # monoid ops are what the heir must serve after adopting.
        fingerprint = list(config._replace(sketch_impl=None))
        witness_victim = ReplicationStandby(
            f"127.0.0.1:{repl_ports[1]}", EpochFence(0),
            config_fingerprint=fingerprint, standby_id="witness-victim",
        )
        witness_heir = ReplicationStandby(
            f"127.0.0.1:{repl_ports[0]}", EpochFence(0),
            config_fingerprint=fingerprint, standby_id="witness-heir",
        )
        witness_victim.start()
        witness_heir.start()
        if not witness_victim.wait_for_state(60.0):
            raise RuntimeError("victim witness never bootstrapped")
        if not witness_heir.wait_for_state(60.0):
            raise RuntimeError("heir witness never bootstrapped")

        # RAMP until brownout: blast the heir's keyspace far past its
        # row budget until the saturation streak crosses the acting
        # edge and the autoscaler proposes scale-out. (The victim gets
        # a modest stream so its frame is worth adopting.)
        rng = np.random.default_rng(17)
        split_seen = False
        iters = 0
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            post_spans(heir_ports["otlp"], heir_services, 192, rng)
            if iters < 20:
                post_spans(victim_ports["otlp"], victim_services, 48, rng)
            iters += 1
            doc = _http_json(metrics_ports[0], "/healthz")
            auto = (doc or {}).get("autoscale") or {}
            if int(auto.get("proposals_split") or 0) >= 1:
                split_seen = True
                break
            time.sleep(0.05)
        if not split_seen:
            raise RuntimeError(
                "autoscaler never proposed scale-out under saturation"
            )

        # Quiesce: load OFF, wait for both witness mirrors to go
        # static (the daemons' own adoption mirrors ride the same
        # streams, so static witnesses mean static frames everywhere).
        def mirror_sum(standby) -> float | None:
            arrs, _m = standby.snapshot()
            if not arrs:
                return None
            return float(np.asarray(arrs["span_total"]).sum())

        stable_since = None
        last = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            now = (mirror_sum(witness_victim), mirror_sum(witness_heir))
            if None not in now and now == last:
                if stable_since is None:
                    stable_since = time.monotonic()
                elif time.monotonic() - stable_since >= 1.5:
                    break
            else:
                stable_since = None
            last = now
            time.sleep(0.25)
        else:
            raise RuntimeError("mirrors never quiesced after the ramp")

        v_arrays, v_meta = witness_victim.snapshot()
        h_arrays, h_meta = witness_heir.snapshot()
        mask = service_row_mask(
            list(v_meta.get("service_names") or []),
            list(h_meta.get("service_names") or []),
            int(h_arrays["lat_mean"].shape[0]),
            owned=victim_services,
        )
        witness_merged = merge_shard_arrays(h_arrays, v_arrays, mask)
        wmeta = {
            "service_names": list(h_meta.get("service_names") or []),
            "config": fingerprint,
        }
        # Pin the PURE state reads (cardinality + zscore): the top-k
        # candidate ring is host-side ingest bookkeeping, not sketch
        # state, so a witness merge cannot reproduce it over HTTP.
        from . import query as q

        witness_docs = _json.loads(_json.dumps({
            svc: {
                "cardinality": q.cardinality(
                    witness_merged, wmeta, svc
                ),
                "zscore": q.zscore_state(witness_merged, wmeta, svc),
            }
            for svc in victim_services
        }))

        # SIGKILL mid-resize: the proposal just landed, the victim
        # dies. Nobody calls a merge — the heir must do it alone.
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=30)
        t_kill = time.monotonic()
        adoptions: dict = {}
        give_up = t_kill + dead_after_s * 20 + 30.0
        while time.monotonic() < give_up:
            doc = _http_json(metrics_ports[0], "/healthz", timeout_s=1.0)
            adoptions = (
                (doc or {}).get("fleet") or {}
            ).get("adoptions") or {}
            if int(adoptions.get("total") or 0) >= 1:
                break
            time.sleep(0.02)
        tta_s = time.monotonic() - t_kill
        adopted = int(adoptions.get("total") or 0) >= 1
        if not adopted:
            raise RuntimeError("heir never adopted the victim's keyspace")

        # Post-settle /query/* pin: the heir's own query plane must
        # answer the victim's keys bit-exactly as the witness merge.
        # Retried briefly: the engine's snapshot cache may still hold
        # the last pre-merge state for one staleness window.
        def fetch_docs() -> dict:
            out: dict = {}
            for svc in victim_services:
                docs = {}
                for kind, path in (
                    ("cardinality", f"/query/cardinality?service={svc}"),
                    ("zscore", f"/query/zscore?service={svc}"),
                ):
                    answer = _http_json(heir_ports["query"], path)
                    data = (answer or {}).get("data") or {}
                    data.pop("timeline", None)  # engine-local, not state
                    docs[kind] = data
                out[svc] = docs
            return out

        got: dict = {}
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            got = fetch_docs()
            if got == witness_docs:
                break
            time.sleep(0.25)
        bitexact = got == witness_docs
        answered = all(
            max(d["cardinality"].get("estimate") or [0.0]) > 0.0
            for d in got.values()
        )

        # NO OSCILLATION: the controller must sit quiet after the
        # resize — no further proposals, no further ring changes.
        doc = _http_json(metrics_ports[0], "/healthz")
        auto0 = (doc or {}).get("autoscale") or {}
        time.sleep(quiet_s)
        doc = _http_json(metrics_ports[0], "/healthz")
        auto1 = (doc or {}).get("autoscale") or {}
        fleet1 = (doc or {}).get("fleet") or {}
        quiet = (
            auto1.get("proposals_split") == auto0.get("proposals_split")
            and auto1.get("proposals_join") == auto0.get("proposals_join")
            and int(
                (fleet1.get("adoptions") or {}).get("total") or 0
            ) == 1
        )
        mismatch = None
        if not bitexact:
            # Small enough to ride the json line; a failed pin without
            # the two answer sets is undebuggable after the fact.
            mismatch = {"got": got, "witness": witness_docs}
        return {
            "autoscale_tta_s": round(tta_s, 4),
            "autoscale_ok": bool(
                split_seen and adopted and bitexact and answered and quiet
            ),
            "adoption_mismatch": mismatch,
            "autoscale_proposals_split": auto1.get("proposals_split"),
            "autoscale_frozen": auto1.get("frozen"),
            "adoption_bitexact": bitexact,
            "adoption_answers_victim_keys": answered,
            "adoption_no_oscillation": quiet,
            "adoption_tta_internal_s": adoptions.get("last_tta_s"),
            "adoption_victim_services": victim_services,
            "adoption_dead_after_s": dead_after_s,
            "adoption_heartbeat_s": heartbeat_s,
        }
    finally:
        for standby in (witness_victim, witness_heir):
            if standby is not None:
                standby.stop()
        for proc in (heir, victim):
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=15)


def main() -> None:
    import json
    import sys

    if "--autoscale" in sys.argv[1:]:
        # The elastic-fleet live leg alone (`make autoscalebench`).
        print(json.dumps(measure_adoption()))
        return
    if "--fleet" in sys.argv[1:]:
        out = measure_reshard()
        # The live-fire SIGKILL legs (slow: real daemon subprocesses
        # boot + compile); skip with --no-live for quick iterations.
        if "--no-live" not in sys.argv[1:]:
            out.update(measure_reshard_live())
            out["fleet_ok"] = bool(
                out["fleet_ok"]
                and out["live_survivor_answers"]
                and out["live_adoption_exact"]
            )
            # The autoscalebench leg, folded in: saturation-driven
            # scale-out + SIGKILL mid-resize + automatic adoption.
            out.update(measure_adoption())
            out["fleet_ok"] = bool(
                out["fleet_ok"] and out["autoscale_ok"]
            )
        print(json.dumps(out))
        return
    print(json.dumps(measure_failover()))


if __name__ == "__main__":
    main()
