"""Checkpoint/resume: sketch snapshots keyed to stream offsets.

The reference system's only durability is Kafka consumer offsets
(auto-commit, /root/reference/src/accounting/Consumer.cs:79-80) — state
lost on restart is re-derived by replaying the topic. Sketch state makes
that cheap to improve on: the whole detector is a few MB of mergeable
integers/floats, so an atomic ``.npz`` snapshot stamped with the Kafka
offsets (and the tensorizer's intern table) gives exactly-once-ish
resume: restore the snapshot, seek the consumer to the stored offsets,
and the sketches continue as if never interrupted. Anything replayed
twice would double-count in CMS — seeking to the recorded offset is what
prevents that; HLL/EWMA are idempotent/robust to small overlaps anyway.

Format: one ``<path>.npz`` holding the state arrays plus the metadata
(offsets, intern table, config fingerprint) as an embedded JSON entry —
a single file so that state and offsets can never be torn apart by a
crash between two writes. The write goes through a temp file +
``os.replace`` so a crash mid-write leaves the previous snapshot intact
— the same torn-write discipline flagd-ui needs for its JSON file
(SURVEY.md §2.2).
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

import jax

from ..models.detector import AnomalyDetector, DetectorConfig, DetectorState


def save(
    path: str,
    detector: AnomalyDetector,
    offsets: dict[str, Any] | None = None,
    service_names: list[str] | None = None,
    metrics_feed=None,
) -> None:
    save_state(
        path, detector.state, detector.config,
        offsets=offsets, service_names=service_names,
        clock_t_prev=detector.clock._t_prev, metrics_feed=metrics_feed,
    )


def save_state(
    path: str,
    state: DetectorState,
    config: DetectorConfig,
    offsets: dict[str, Any] | None = None,
    service_names: list[str] | None = None,
    clock_t_prev: float | None = None,
    metrics_feed=None,
) -> None:
    """Snapshot any DetectorState — single-chip or MESH-SHARDED.

    ``np.asarray`` on a sharded ``jax.Array`` gathers the GLOBAL value
    (all shards are process-addressable in this deployment), so the
    on-disk format is topology-free: global shapes carry no device
    count, and the same snapshot restores onto one chip (:func:`load`)
    or any mesh (:func:`load_onto_mesh`). Monoid state is what makes
    this a placement problem rather than a retrain — HLL registers,
    CMS counters and EWMA heads mean the same thing wherever the
    service/depth axes land.
    """
    state_np = {k: np.asarray(v) for k, v in state._asdict().items()}
    # sketch_impl is an execution-backend knob, not state: a snapshot
    # written on TPU (pallas) must restore on a CPU box (xla) and vice
    # versa, so it is excluded from the persisted config fingerprint.
    meta = {
        "offsets": offsets or {},
        "service_names": service_names or [],
        "config": list(config._replace(sketch_impl=None)),
        "clock_t_prev": clock_t_prev,
    }
    if metrics_feed is not None:
        # The metrics-leg head warms in minutes, but a restart must not
        # forget which rate is "normal" — snapshot its EWMA state and
        # both intern tables beside the sketch state.
        head = metrics_feed.head
        for name, arr in head.state._asdict().items():
            state_np[f"metrics_{name}"] = np.asarray(arr)
        meta["metrics_config"] = list(head.config)
        meta["metrics_service_names"] = metrics_feed.service_names
        meta["metrics_metric_names"] = metrics_feed.metric_names
    # Metadata rides inside the npz (as a unicode scalar) so snapshot
    # and offsets commit in ONE os.replace — a crash can only ever leave
    # the previous complete (state, offsets) pair, never a mixed one.
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, __meta__=np.asarray(json.dumps(meta)), **state_np)
    os.replace(tmp, path + ".npz")
    # Clean up a sidecar left by the old two-file format so it can't
    # shadow or confuse a later inspection of the snapshot directory.
    try:
        os.remove(path + ".json")
    except OSError:
        pass


def _load_arrays(
    path: str, config: DetectorConfig | None
) -> tuple[dict, dict, DetectorConfig]:
    """Shared npz read + config validation → (arrays, meta, saved_cfg)."""
    with np.load(path + ".npz") as data:
        if "__meta__" not in data.files:
            raise ValueError(
                f"{path}.npz is not a self-contained checkpoint (missing "
                "__meta__); it was written by an incompatible version"
            )
        meta = json.loads(str(data["__meta__"][()]))
        arrays = {
            k: data[k]
            for k in data.files
            if k != "__meta__" and not k.startswith("metrics_")
        }
        metrics_arrays = {
            k[len("metrics_"):]: data[k]
            for k in data.files
            if k.startswith("metrics_")
        }
    meta["_metrics_arrays"] = metrics_arrays
    saved_cfg = DetectorConfig(
        *[tuple(v) if isinstance(v, list) else v for v in meta["config"]]
    )
    # Compare/restore ignoring the backend knob (see save()): the caller
    # keeps their own sketch_impl choice for this process.
    if config is not None:
        saved_cfg = saved_cfg._replace(sketch_impl=config.sketch_impl)
        if list(config) != list(saved_cfg):
            raise ValueError(
                f"checkpoint config {saved_cfg} does not match "
                f"requested {config}"
            )
    return arrays, meta, saved_cfg


def load(path: str, config: DetectorConfig | None = None) -> tuple[AnomalyDetector, dict]:
    """Restore a detector (state + clock) and return (detector, meta).

    Topology-elastic by format: the snapshot may have been written from
    a MESH-SHARDED run (save_state gathers global values) — restoring
    here places it on the process's default single device.
    """
    arrays, meta, saved_cfg = _load_arrays(path, config)
    detector = AnomalyDetector(saved_cfg)
    detector.state = DetectorState(
        **{k: jax.device_put(v) for k, v in arrays.items()}
    )
    detector.clock._t_prev = meta.get("clock_t_prev")
    return detector, meta


def load_onto_mesh(
    path: str,
    config: DetectorConfig | None,
    mesh,
) -> tuple[DetectorState, dict]:
    """Elastic restore: place a snapshot onto a device mesh.

    The inverse move of :func:`save_state`'s gather — a 1-chip snapshot
    resumes on an 8-device mesh (or 8→1, or 2-D→hybrid) because the
    on-disk state is global and monoid: ``device_put`` with the mesh's
    NamedShardings IS the whole migration (the offsets in ``meta`` then
    seek the consumers exactly as in the same-topology path — the
    Consumer.cs:79-80 resume semantics, now independent of topology).
    Pair with ``parallel.make_sharded_step(config, mesh)`` and replace
    its initial state with the returned one.
    """
    from ..parallel.spmd import place_state

    arrays, meta, _saved_cfg = _load_arrays(path, config)
    state = DetectorState(**arrays)
    return place_state(state, mesh), meta


def exists(path: str) -> bool:
    return os.path.exists(path + ".npz")


def restore_metrics_feed(meta: dict, feed) -> bool:
    """Hydrate a MetricsFeed from checkpoint meta (load() output).

    Returns False (feed untouched) when the snapshot has no metrics leg
    or its geometry doesn't match the feed's — a geometry change means
    the cells don't line up and warm state would be attributed to the
    wrong (service, metric)."""
    arrays = meta.get("_metrics_arrays") or {}
    if not arrays or meta.get("metrics_config") is None:
        return False
    from ..models.metrics_head import MetricsHeadConfig, MetricsHeadState

    saved_cfg = MetricsHeadConfig(
        *[tuple(v) if isinstance(v, list) else v
          for v in meta["metrics_config"]]
    )
    if list(saved_cfg) != list(feed.config):
        return False
    feed.head.state = MetricsHeadState(
        **{k: jax.device_put(v) for k, v in arrays.items()}
    )
    for name in meta.get("metrics_service_names", []):
        feed._intern_service(name)
    for name in meta.get("metrics_metric_names", []):
        feed.metric_id(name)
    return True
