"""Checkpoint/resume: sketch snapshots keyed to stream offsets.

The reference system's only durability is Kafka consumer offsets
(auto-commit, /root/reference/src/accounting/Consumer.cs:79-80) — state
lost on restart is re-derived by replaying the topic. Sketch state makes
that cheap to improve on: the whole detector is a few MB of mergeable
integers/floats, so an atomic one-file snapshot stamped with the Kafka
offsets (and the tensorizer's intern table) gives exactly-once-ish
resume: restore the snapshot, seek the consumer to the stored offsets,
and the sketches continue as if never interrupted. Anything replayed
twice would double-count in CMS — seeking to the recorded offset is what
prevents that; HLL/EWMA are idempotent/robust to small overlaps anyway.

Format: one ``<path>.ckpt`` file that IS a verified columnar frame
(``runtime.frame``: magic, format version, schema hash, per-column
CRC32C checksums, trailer checksum) — the SAME byte layout replication
ships over TCP and the ingest pool moves from decode scratch, so disk,
link and device feed all carry one format with zero re-encode. The
metadata (offsets, intern table, config fingerprint, fencing epoch)
rides in the frame's meta block beside the state columns — a single
file so that state and offsets can never be torn apart by a crash
between two writes. The write goes through a temp file + ``fsync`` +
``os.replace`` so a crash mid-write leaves the previous snapshot intact
— the same torn-write discipline flagd-ui needs for its JSON file
(SURVEY.md §2.2). The frame checksums replace the old sha256 sidecar
digest: truncation fails the trailer, bit rot fails a column CRC, and
either way :func:`load_resilient` quarantines the file and cold-starts.

Version skew: snapshots written by the pre-frame layout (an npz with an
embedded ``__meta__`` entry — "v0", at ``<path>.npz``) still restore
through the explicit migration shim in :func:`_load_arrays`; the next
save writes the current frame format and retires the legacy file, so a
rolling upgrade (or a rollback within the frame-version window via
``ANOMALY_FRAME_WRITE_VERSION``) never strands durable state.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
from typing import Any

import numpy as np

import jax

from ..models.detector import AnomalyDetector, DetectorConfig, DetectorState
from . import frame

log = logging.getLogger(__name__)

# Current snapshot files are frames; ``.npz`` is the pre-frame ("v0")
# layout the loader still migrates from.
SUFFIX = ".ckpt"
LEGACY_SUFFIX = ".npz"

# Stand-in context for lock-free callers of :func:`save` (tests and
# single-threaded harnesses with no dispatcher running).
_NULL_LOCK = contextlib.nullcontext()


class CheckpointCorrupt(Exception):
    """A snapshot file that cannot be trusted: truncated, unreadable,
    or failing its content digest. Distinct from a *config mismatch*
    (``ValueError``), which is an operator error that must refuse boot
    — corruption is an environment fault the boot path degrades
    through (cold start) instead of crashing on."""


class StaleEpochError(RuntimeError):
    """A write carrying an old fencing epoch was rejected.

    The storage half of split-brain prevention (runtime.replication):
    every snapshot is stamped with the writer's epoch, and
    :func:`save_state` refuses to replace a snapshot whose on-disk
    epoch is NEWER than the writer's — a resurrected stale primary
    sharing the checkpoint volume with its promoted successor must not
    clobber the successor's state. The same error fences Kafka offset
    commits (kafka_orders.OrdersSource.commit) and replication frames
    (replication.EpochFence)."""


def _content_digest(state_np: dict, meta_json: str) -> str:
    """sha256 over the meta JSON + every array's bytes (name-sorted).

    The zip container catches truncation; the digest catches what the
    container can't — bit rot inside a still-valid archive, or a
    partially-flushed entry on filesystems that reorder writes."""
    h = hashlib.sha256()
    h.update(meta_json.encode())
    for name in sorted(state_np):
        arr = np.ascontiguousarray(state_np[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def save(
    path: str,
    detector: AnomalyDetector,
    offsets: dict[str, Any] | None = None,
    service_names: list[str] | None = None,
    metrics_feed=None,
    epoch: int = 0,
    generation: int = 0,
    *,
    dispatch_lock,
) -> None:
    """Snapshot a live detector to disk.

    ``dispatch_lock`` is the owning pipeline's ``_dispatch_lock`` —
    live dispatch DONATES the state buffers, so an unlocked read can
    touch a just-deleted array. The argument is keyword-only and has
    NO default: a caller with a quiesced detector (tests,
    single-threaded harnesses) must write ``dispatch_lock=None``
    deliberately, so the unsafe path is never reached by omission.
    The lock is held ONLY for the host copy-out; the frame encode and
    the fsync'd write below run outside it, so a slow disk never
    stalls dispatch."""
    with dispatch_lock if dispatch_lock is not None else _NULL_LOCK:
        state_host = DetectorState(
            **{
                k: np.asarray(v)
                for k, v in detector.state._asdict().items()
            }
        )
        clock_t_prev = detector.clock._t_prev
    save_state(
        path, state_host, detector.config,
        offsets=offsets, service_names=service_names,
        clock_t_prev=clock_t_prev, metrics_feed=metrics_feed,
        epoch=epoch, generation=generation,
    )


def save_state(
    path: str,
    state: DetectorState,
    config: DetectorConfig,
    offsets: dict[str, Any] | None = None,
    service_names: list[str] | None = None,
    clock_t_prev: float | None = None,
    metrics_feed=None,
    epoch: int = 0,
    generation: int = 0,
) -> None:
    """Snapshot any DetectorState — single-chip or MESH-SHARDED.

    ``np.asarray`` on a sharded ``jax.Array`` gathers the GLOBAL value
    (all shards are process-addressable in this deployment), so the
    on-disk format is topology-free: global shapes carry no device
    count, and the same snapshot restores onto one chip (:func:`load`)
    or any mesh (:func:`load_onto_mesh`). Monoid state is what makes
    this a placement problem rather than a retrain — HLL registers,
    CMS counters and EWMA heads mean the same thing wherever the
    service/depth axes land.
    """
    # Save-time fencing (runtime.replication): a snapshot written at a
    # NEWER epoch than ours means another process promoted past us —
    # replacing it would be the stale half of a split brain overwriting
    # the live half's durable state. Checked before any serialization
    # work, and again implicitly by the atomic os.replace below (the
    # window between peek and replace is accepted: both writers sharing
    # a volume also share the replication fence, which learns epochs
    # faster than the checkpoint cadence).
    existing_epoch = peek_epoch(path)
    if existing_epoch is not None and existing_epoch > epoch:
        raise StaleEpochError(
            f"snapshot at {path} carries epoch {existing_epoch} > writer epoch "
            f"{epoch}: refusing a stale-primary checkpoint save"
        )
    state_np = {k: np.asarray(v) for k, v in state._asdict().items()}
    # sketch_impl is an execution-backend knob, not state: a snapshot
    # written on TPU (pallas) must restore on a CPU box (xla) and vice
    # versa, so it is excluded from the persisted config fingerprint.
    meta = {
        "offsets": offsets or {},
        "service_names": service_names or [],
        "config": list(config._replace(sketch_impl=None)),
        "clock_t_prev": clock_t_prev,
        "epoch": int(epoch),
        # Keyspace generation (runtime/keyspace.py): restore adopts it
        # positionally with the name table — EVICTED_SLOT tombstones in
        # service_names mark recycled-id holes — so a restored process
        # refuses generation-drifted frames exactly like the one that
        # wrote the snapshot.
        "generation": int(generation),
    }
    if metrics_feed is not None:
        # The metrics-leg head warms in minutes, but a restart must not
        # forget which rate is "normal" — snapshot its EWMA state and
        # both intern tables beside the sketch state.
        head = metrics_feed.head
        for name, arr in head.state._asdict().items():
            state_np[f"metrics_{name}"] = np.asarray(arr)
        meta["metrics_config"] = list(head.config)
        meta["metrics_service_names"] = metrics_feed.service_names
        meta["metrics_metric_names"] = metrics_feed.metric_names
    # Metadata rides inside the frame's meta block so snapshot and
    # offsets commit in ONE os.replace — a crash can only ever leave
    # the previous complete (state, offsets) pair, never a mixed one.
    # The frame's per-column CRCs + trailer are the content integrity
    # (the old sha256 sidecar digest retired), and fsync-before-rename
    # makes the replace itself crash-safe: without it a power cut can
    # leave the *renamed* file with zero-filled pages on journaled
    # filesystems.
    blob = frame.encode(state_np, meta=meta)
    tmp = path + ".tmp" + SUFFIX
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path + SUFFIX)
    # Retire artifacts of older layouts AFTER the new snapshot landed
    # (the crash window always leaves at least one complete snapshot):
    # the pre-frame npz ("v0" — just migrated from) and the ancient
    # two-file JSON sidecar, either of which could otherwise shadow or
    # confuse a later inspection of the snapshot directory.
    for stale in (path + LEGACY_SUFFIX, path + ".json"):
        try:
            os.remove(stale)
        except OSError:
            pass


def _snapshot_file(path: str) -> str | None:
    """The snapshot file for ``path``: the current frame layout wins;
    a legacy npz ("v0") is the migration source. None = cold."""
    for suffix in (SUFFIX, LEGACY_SUFFIX):
        if os.path.exists(path + suffix):
            return path + suffix
    return None


def _load_arrays(
    path: str, config: DetectorConfig | None
) -> tuple[dict, dict, DetectorConfig]:
    """Shared snapshot read + config validation → (arrays, meta, cfg).

    Reads the current frame layout, or migrates a pre-frame npz
    ("v0") through the explicit shim below. Anything the *file* can do
    wrong — truncation, a failed trailer/column checksum, a torn zip —
    raises :class:`CheckpointCorrupt`; only the post-read *semantic*
    checks (incompatible version, config mismatch) raise ``ValueError``.
    """
    file = _snapshot_file(path)
    if file is None:
        raise FileNotFoundError(f"no snapshot at {path}")
    if file.endswith(SUFFIX):
        arrays, metrics_arrays, meta = _read_frame_snapshot(file)
    else:
        arrays, metrics_arrays, meta = _read_legacy_snapshot(file)
    meta["_metrics_arrays"] = metrics_arrays
    saved_cfg = DetectorConfig(
        *[tuple(v) if isinstance(v, list) else v for v in meta["config"]]
    )
    # Compare/restore ignoring the backend knob (see save()): the caller
    # keeps their own sketch_impl choice for this process.
    if config is not None:
        saved_cfg = saved_cfg._replace(sketch_impl=config.sketch_impl)
        if list(config) != list(saved_cfg):
            raise ValueError(
                f"checkpoint config {saved_cfg} does not match "
                f"requested {config}"
            )
    return arrays, meta, saved_cfg


def _split_metric_arrays(all_arrays: dict) -> tuple[dict, dict]:
    arrays = {
        k: v for k, v in all_arrays.items()
        if not k.startswith("metrics_")
        and k not in ("__meta__", "__digest__")
    }
    metrics_arrays = {
        k[len("metrics_"):]: v
        for k, v in all_arrays.items()
        if k.startswith("metrics_")
    }
    return arrays, metrics_arrays


def _read_frame_snapshot(file: str) -> tuple[dict, dict, dict]:
    """Current layout: the file IS one verified columnar frame."""
    try:
        with open(file, "rb") as fh:
            blob = fh.read()
        fr = frame.decode(blob)
    except frame.FrameVersionError as e:
        # An upgrade-order problem (a frame version outside this
        # reader's window), not corruption: refuse loudly rather than
        # quarantining a perfectly intact newer snapshot.
        raise ValueError(f"{file}: {e}") from e
    except frame.FrameError as e:
        # File-content faults only: transient ENVIRONMENT errors
        # (PermissionError, EIO, MemoryError) propagate — a retry could
        # succeed, and mislabeling them corrupt would make
        # load_resilient move a perfectly good snapshot aside.
        raise CheckpointCorrupt(f"{file} unreadable: {e}") from e
    arrays, metrics_arrays = _split_metric_arrays(fr.arrays)
    if "config" not in fr.meta:
        raise ValueError(
            f"{file} carries no config fingerprint; it was written by "
            "an incompatible version"
        )
    return arrays, metrics_arrays, dict(fr.meta)


def _read_legacy_snapshot(file: str) -> tuple[dict, dict, dict]:
    """The pre-frame npz layout ("v0") — the migration shim. Verified
    by its embedded sha256 digest when present (older-still snapshots
    verify by the zip container alone); the next save rewrites the
    state as a frame and retires this file."""
    try:
        raw = frame.read_npz(file)
    except frame.FrameCorrupt as e:  # container faults (torn zip, …)
        raise CheckpointCorrupt(f"{file} unreadable: {e}") from e
    if "__meta__" not in raw:
        raise ValueError(
            f"{file} is not a self-contained checkpoint (missing "
            "__meta__); it was written by an incompatible version"
        )
    try:
        meta_json = str(raw["__meta__"][()])
        meta = json.loads(meta_json)
    except ValueError as e:
        raise CheckpointCorrupt(f"{file} meta unreadable: {e}") from e
    stored_digest = (
        str(raw["__digest__"][()]) if "__digest__" in raw else None
    )
    arrays, metrics_arrays = _split_metric_arrays(raw)
    if stored_digest is not None:
        all_arrays = dict(arrays)
        all_arrays.update(
            {f"metrics_{k}": v for k, v in metrics_arrays.items()}
        )
        actual = _content_digest(all_arrays, meta_json)
        if actual != stored_digest:
            raise CheckpointCorrupt(
                f"{file} content digest mismatch "
                f"(stored {stored_digest[:12]}…, computed {actual[:12]}…)"
            )
    return arrays, metrics_arrays, meta


def load(path: str, config: DetectorConfig | None = None) -> tuple[AnomalyDetector, dict]:
    """Restore a detector (state + clock) and return (detector, meta).

    Topology-elastic by format: the snapshot may have been written from
    a MESH-SHARDED run (save_state gathers global values) — restoring
    here places it on the process's default single device.
    """
    arrays, meta, saved_cfg = _load_arrays(path, config)
    detector = AnomalyDetector(saved_cfg)
    detector.state = DetectorState(  # staticcheck: ok[donation-race] fresh detector constructed one line up — no pipeline, no dispatcher thread can hold it yet
        **{k: jax.device_put(v) for k, v in arrays.items()}
    )
    detector.clock._t_prev = meta.get("clock_t_prev")
    return detector, meta


def load_resilient(
    path: str, config: DetectorConfig | None = None
) -> tuple[AnomalyDetector | None, dict | None, bool]:
    """Boot-path load: ``(detector, meta, corrupt)``.

    A truncated or bit-rotted snapshot — a failed frame trailer or
    column CRC, a torn legacy zip — degrades to a cold start
    (``(None, None, True)``) instead of crashing the daemon at boot:
    the snapshot is an *optimization* (skip topic replay / re-warmup),
    never a boot dependency. The bad file is QUARANTINED — moved aside
    to ``<file>.corrupt`` — so the evidence survives for inspection AND
    the next restart doesn't trip on it again. Config mismatch still
    raises (operator error, mustMapEnv discipline); a missing file is
    ``(None, None, False)`` — a plain cold start.
    """
    file = _snapshot_file(path)
    if file is None:
        return None, None, False
    try:
        detector, meta = load(path, config)
        return detector, meta, False
    except CheckpointCorrupt as e:
        log.error("checkpoint corrupt, falling back to cold start: %s", e)
        try:
            os.replace(file, file + ".corrupt")
        except OSError:
            pass
        return None, None, True


def load_onto_mesh(
    path: str,
    config: DetectorConfig | None,
    mesh,
) -> tuple[DetectorState, dict]:
    """Elastic restore: place a snapshot onto a device mesh.

    The inverse move of :func:`save_state`'s gather — a 1-chip snapshot
    resumes on an 8-device mesh (or 8→1, or 2-D→hybrid) because the
    on-disk state is global and monoid: ``device_put`` with the mesh's
    NamedShardings IS the whole migration (the offsets in ``meta`` then
    seek the consumers exactly as in the same-topology path — the
    Consumer.cs:79-80 resume semantics, now independent of topology).
    Pair with ``parallel.make_sharded_step(config, mesh)`` and replace
    its initial state with the returned one.

    Window-clock continuity: the sharded step has no host-side
    ``AnomalyDetector`` to hydrate, so the clock comes back through
    ``meta["clock_t_prev"]`` (always present, None for a pre-clock
    snapshot) — seed ``models.windows.WindowClock._t_prev`` with it
    before the first sharded tick, exactly what :func:`load` does for
    the single-chip path. Skipping this restarts the window phase and
    the first post-resume rotation fires at the wrong boundary.
    """
    from ..parallel.spmd import place_state

    arrays, meta, _saved_cfg = _load_arrays(path, config)
    meta.setdefault("clock_t_prev", None)
    state = DetectorState(**arrays)
    return place_state(state, mesh), meta


def exists(path: str) -> bool:
    return _snapshot_file(path) is not None


def peek_epoch(path: str) -> int | None:
    """Fencing epoch of the snapshot at ``path``, or None.

    None means "no fencing evidence": missing file, unreadable file, or
    a pre-epoch snapshot (treated as epoch 0 by ``meta.get``). Frame
    snapshots answer from a header-only read (fixed header + meta JSON,
    never the state payload — cheap enough for the save path to call
    every time); a legacy npz pays one full container read, once, on
    the save that retires it. When BOTH layouts are present (a crash
    between the frame replace and the legacy unlink), the LARGEST
    epoch wins — fencing must see the strongest evidence."""
    best: int | None = None
    for suffix in (SUFFIX, LEGACY_SUFFIX):
        file = path + suffix
        if not os.path.exists(file):
            continue
        try:
            if suffix == SUFFIX:
                # The shared header-only peek (frame.FramePeek) — the
                # same read the history store's time index uses; the
                # checkpoint-specific header-walking duplicate this
                # branch once carried is retired.
                meta = frame.peek_file_meta(file).meta
            else:
                raw = frame.read_npz(file)
                if "__meta__" not in raw:
                    continue
                meta = json.loads(str(raw["__meta__"][()]))
        except Exception:  # noqa: BLE001 — corruption is
            # load_resilient's problem; fencing only needs readable
            # evidence of a newer epoch
            continue
        epoch = int(meta.get("epoch", 0))
        best = epoch if best is None else max(best, epoch)
    return best


def restore_metrics_feed(meta: dict, feed) -> bool:
    """Hydrate a MetricsFeed from checkpoint meta (load() output).

    Returns False (feed untouched) when the snapshot has no metrics leg
    or its geometry doesn't match the feed's — a geometry change means
    the cells don't line up and warm state would be attributed to the
    wrong (service, metric). A mismatch is LOGGED with the offending
    key (a silent partial restore looks exactly like a warm one until
    the metrics head mis-flags), and the daemon exports each False
    return on a snapshot that HAD a metrics leg as
    ``anomaly_restore_partial_total``."""
    arrays = meta.get("_metrics_arrays") or {}
    if not arrays or meta.get("metrics_config") is None:
        if arrays or meta.get("metrics_config") is not None:
            # Half a metrics leg (arrays without config or vice versa)
            # is a torn snapshot shape worth naming; a snapshot with
            # neither is simply pre-metrics — silent.
            log.warning(
                "metrics-feed restore skipped: snapshot carries %s but "
                "not %s — metrics head cold-starts",
                "arrays" if arrays else "metrics_config",
                "metrics_config" if arrays else "arrays",
            )
        return False
    from ..models.metrics_head import MetricsHeadConfig, MetricsHeadState

    saved_cfg = MetricsHeadConfig(
        *[tuple(v) if isinstance(v, list) else v
          for v in meta["metrics_config"]]
    )
    if list(saved_cfg) != list(feed.config):
        mismatched = [
            name
            for name, saved, cur in zip(
                MetricsHeadConfig._fields, saved_cfg, feed.config
            )
            if (tuple(saved) if isinstance(saved, (list, tuple)) else saved)
            != (tuple(cur) if isinstance(cur, (list, tuple)) else cur)
        ]
        log.warning(
            "metrics-feed restore skipped: config mismatch on %s "
            "(snapshot %s vs running %s) — metrics head cold-starts, "
            "span-leg state restored normally",
            ", ".join(mismatched) or "<unknown field>",
            saved_cfg, feed.config,
        )
        return False
    feed.head.state = MetricsHeadState(
        **{k: jax.device_put(v) for k, v in arrays.items()}
    )
    for name in meta.get("metrics_service_names", []):
        feed._intern_service(name)
    for name in meta.get("metrics_metric_names", []):
        feed.metric_id(name)
    return True
