"""Kafka wire protocol: the minimal subset the orders leg speaks.

The reference's async tier is a real Kafka broker
(/root/reference/docker-compose.yml kafka service) with consumers
polling over TCP (src/fraud-detection/.../main.kt:54-69,
src/accounting/Consumer.cs:77-80). This image ships no Kafka client
library, so — in the same from-scratch spirit as ``runtime.wire`` for
protobuf — this module implements the Kafka protocol primitives
directly: size-prefixed request/response framing, the primitive codecs,
and the v0 MessageSet record format (magic 0, zlib CRC32).

Record formats: the v0 MessageSet (magic 0) for the legacy path, and
the **v2 RecordBatch** (magic 2: CRC-32C, varint-packed records, and
per-record HEADERS) used by Produce v3 / Fetch v4 — the headers slot is
how the reference's checkout injects W3C trace context into the orders
topic (/root/reference/src/checkout/main.go:631-637), so the batch
format is required for context to cross the async boundary the way the
reference's does. Modern brokers (Kafka ≥3.0) dropped Produce <v3 and
Fetch <v4, so the v3/v4 path is also what makes the client speak to the
compose overlay's real broker. Other APIs stay in the non-flexible era —
ListOffsets v0, Metadata v0, FindCoordinator v0, OffsetCommit v2,
OffsetFetch v1 — real Kafka wire format, without re-implementing
KIP-482 tagged fields. Interop scope: **Kafka 3.x brokers** — 4.0
removed these auxiliary API versions entirely (KIP-896), so a 4.x
broker would reject the Metadata/ListOffsets/FindCoordinator calls
even though the record path (Produce v3 / Fetch v4) would still speak.
The in-repo broker (``kafka_broker``) speaks the same subset, so client
and broker are interoperable test doubles for the compose topology's
real broker. The interop scope is FALSIFIABLE: ``make kafka-interop``
(tests/test_kafka_interop.py) runs the client-level suite against
whatever ``KAFKA_ADDR`` points at — green against the in-repo broker
here, runnable unchanged against a real Kafka 3.x.
"""

from __future__ import annotations

import struct
import zlib
from typing import NamedTuple

# API keys (the public protocol's).
PRODUCE = 0
FETCH = 1
LIST_OFFSETS = 2
METADATA = 3
OFFSET_COMMIT = 8
OFFSET_FETCH = 9
FIND_COORDINATOR = 10

# Error codes.
NO_ERROR = 0
OFFSET_OUT_OF_RANGE = 1
UNKNOWN_TOPIC_OR_PARTITION = 3
UNSUPPORTED_VERSION = 35


class KafkaWireError(ValueError):
    """Malformed Kafka wire data."""


class KafkaProduceError(KafkaWireError):
    """Broker answered the Produce but rejected the record (non-zero
    partition error code) — the transport is healthy, so retrying on a
    fresh connection cannot help; callers should bound retries and
    dead-letter instead of treating this as a broken broker."""

    def __init__(self, code: int, partition: int):
        super().__init__(f"produce error {code} on partition {partition}")
        self.code = code
        self.partition = partition


# --- primitive codecs --------------------------------------------------


class Reader:
    """Sequential reader over one request/response body."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise KafkaWireError("truncated message")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def int8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> str | None:
        n = self.int16()
        if n == -1:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.int32()
        if n == -1:
            return None
        return self._take(n)

    def array(self, fn):
        n = self.int32()
        if n < 0:
            return []
        return [fn() for _ in range(n)]

    def remaining(self) -> bytes:
        return self.buf[self.pos :]


def enc_int8(v: int) -> bytes:
    return struct.pack(">b", v)


def enc_int16(v: int) -> bytes:
    return struct.pack(">h", v)


def enc_int32(v: int) -> bytes:
    return struct.pack(">i", v)


def enc_int64(v: int) -> bytes:
    return struct.pack(">q", v)


def enc_string(v: str | None) -> bytes:
    if v is None:
        return enc_int16(-1)
    raw = v.encode("utf-8")
    return enc_int16(len(raw)) + raw


def enc_bytes(v: bytes | None) -> bytes:
    if v is None:
        return enc_int32(-1)
    return enc_int32(len(v)) + v


def enc_array(items, fn) -> bytes:
    return enc_int32(len(items)) + b"".join(fn(x) for x in items)


# --- request/response framing -----------------------------------------


def encode_request(
    api_key: int,
    api_version: int,
    correlation_id: int,
    client_id: str,
    body: bytes,
) -> bytes:
    """Size-prefixed request with the v1 (non-flexible) header."""
    payload = (
        enc_int16(api_key)
        + enc_int16(api_version)
        + enc_int32(correlation_id)
        + enc_string(client_id)
        + body
    )
    return enc_int32(len(payload)) + payload


class RequestHeader(NamedTuple):
    api_key: int
    api_version: int
    correlation_id: int
    client_id: str | None


def decode_request_header(reader: Reader) -> RequestHeader:
    return RequestHeader(
        api_key=reader.int16(),
        api_version=reader.int16(),
        correlation_id=reader.int32(),
        client_id=reader.string(),
    )


def encode_response(correlation_id: int, body: bytes) -> bytes:
    payload = enc_int32(correlation_id) + body
    return enc_int32(len(payload)) + payload


def read_frame(sock) -> bytes | None:
    """One size-prefixed frame off a socket; None on clean EOF."""
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (size,) = struct.unpack(">i", header)
    if size < 0 or size > 64 * 1024 * 1024:
        raise KafkaWireError(f"implausible frame size {size}")
    frame = _read_exact(sock, size)
    if frame is None:
        raise KafkaWireError("truncated frame")
    return frame


def _read_exact(sock, n: int) -> bytes | None:
    """Exactly n bytes; None on EOF at a frame boundary, error mid-frame."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if got == 0:
                return None
            raise KafkaWireError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# --- CRC-32C (Castagnoli) ---------------------------------------------
# RecordBatch v2 checksums with CRC-32C, NOT zlib's CRC-32/IEEE; the
# stdlib has no crc32c, so: reflected table-driven implementation of
# polynomial 0x1EDC6F41 (reflected form 0x82F63B78), the same algorithm
# every Kafka client ships.

def _crc32c_table() -> list[int]:
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC32C_TABLE = _crc32c_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# --- zigzag varints (RecordBatch v2 integer packing) ------------------


def enc_varint(v: int) -> bytes:
    """Signed zigzag varint (the only flavor the record format uses)."""
    zz = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    out = bytearray()
    while True:
        b = zz & 0x7F
        zz >>= 7
        if zz:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def dec_varint(buf: bytes, pos: int) -> tuple[int, int]:
    """(value, new_pos); signed zigzag."""
    shift = 0
    zz = 0
    while True:
        if pos >= len(buf):
            raise KafkaWireError("truncated varint")
        b = buf[pos]
        pos += 1
        zz |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 63:
            raise KafkaWireError("varint overflow")
    return (zz >> 1) ^ -(zz & 1), pos


# --- MessageSet v0 (magic 0) ------------------------------------------


class KafkaMessage(NamedTuple):
    offset: int
    key: bytes | None
    value: bytes | None


def encode_message(key: bytes | None, value: bytes | None) -> bytes:
    """One magic-0 message body (without the offset/size envelope)."""
    rest = enc_int8(0) + enc_int8(0) + enc_bytes(key) + enc_bytes(value)
    crc = zlib.crc32(rest) & 0xFFFFFFFF
    return struct.pack(">I", crc) + rest


def encode_message_set(messages, base_offset: int = 0) -> bytes:
    """[(key, value), ...] → on-wire MessageSet with assigned offsets."""
    out = b""
    for i, (key, value) in enumerate(messages):
        msg = encode_message(key, value)
        out += enc_int64(base_offset + i) + enc_int32(len(msg)) + msg
    return out


def decode_message_set(buf: bytes) -> list[KafkaMessage]:
    """On-wire MessageSet → messages; a trailing partial message (the
    protocol allows brokers to cut one at the fetch byte limit) is
    dropped, matching every real client's behavior."""
    out: list[KafkaMessage] = []
    pos = 0
    n = len(buf)
    while pos + 12 <= n:
        offset, size = struct.unpack(">qi", buf[pos : pos + 12])
        if pos + 12 + size > n:
            break  # partial trailing message
        body = buf[pos + 12 : pos + 12 + size]
        pos += 12 + size
        crc_stored = struct.unpack(">I", body[:4])[0]
        rest = body[4:]
        if zlib.crc32(rest) & 0xFFFFFFFF != crc_stored:
            raise KafkaWireError(f"bad message CRC at offset {offset}")
        r = Reader(rest)
        magic = r.int8()
        if magic != 0:
            raise KafkaWireError(f"unsupported message magic {magic}")
        r.int8()  # attributes (no compression in this subset)
        key = r.bytes_()
        value = r.bytes_()
        out.append(KafkaMessage(offset=offset, key=key, value=value))
    return out


# --- RecordBatch v2 (magic 2) -----------------------------------------
# The modern record format: one batch envelope (fixed-width header,
# CRC-32C over everything after the crc field) wrapping varint-packed
# records, each with an offset/timestamp delta and a HEADERS list —
# the slot trace context rides in (main.go:631-637).


class KafkaRecord(NamedTuple):
    offset: int
    key: bytes | None
    value: bytes | None
    headers: tuple  # ((str, bytes|None), ...)
    timestamp_ms: int = 0


def _enc_varbytes(v: bytes | None) -> bytes:
    if v is None:
        return enc_varint(-1)
    return enc_varint(len(v)) + v


def encode_record_batch(
    records,
    base_offset: int = 0,
    base_timestamp_ms: int = 0,
) -> bytes:
    """[(key, value, headers), ...] → one on-wire v2 RecordBatch.

    ``headers`` per record: iterable of (str, bytes|None) pairs (or a
    {str: bytes} mapping). Produced with producerId/epoch/sequence -1
    (idempotence/transactions are out of scope) and no compression.
    """
    recs = b""
    for i, (key, value, headers) in enumerate(records):
        if hasattr(headers, "items"):
            headers = list(headers.items())
        body = (
            b"\x00"  # record attributes (unused)
            + enc_varint(0)  # timestamp delta
            + enc_varint(i)  # offset delta
            + _enc_varbytes(key)
            + _enc_varbytes(value)
            + enc_varint(len(headers))
        )
        for hkey, hval in headers:
            raw = hkey.encode("utf-8")
            body += enc_varint(len(raw)) + raw + _enc_varbytes(hval)
        recs += enc_varint(len(body)) + body
    n = len(records)
    tail = (
        enc_int16(0)  # batch attributes: no compression, CREATE_TIME
        + enc_int32(max(n - 1, 0))  # lastOffsetDelta
        + enc_int64(base_timestamp_ms)
        + enc_int64(base_timestamp_ms)  # maxTimestamp
        + enc_int64(-1)  # producerId
        + enc_int16(-1)  # producerEpoch
        + enc_int32(-1)  # baseSequence
        + enc_int32(n)
        + recs
    )
    crc = crc32c(tail)
    after_length = (
        enc_int32(-1)  # partitionLeaderEpoch
        + enc_int8(2)  # magic
        + struct.pack(">I", crc)
        + tail
    )
    return enc_int64(base_offset) + enc_int32(len(after_length)) + after_length


def decode_record_batches(buf: bytes) -> list[KafkaRecord]:
    """On-wire record data → records with absolute offsets + headers.

    Handles multiple concatenated batches (a fetch may return several);
    a trailing partial batch — the protocol lets brokers cut one at the
    byte limit — is dropped, like every real client does. A magic-0/1
    segment in the same buffer raises: mixed-format logs don't occur in
    this subset.
    """
    out: list[KafkaRecord] = []
    pos = 0
    n = len(buf)
    while pos + 12 <= n:
        base_offset, batch_len = struct.unpack(">qi", buf[pos : pos + 12])
        if pos + 12 + batch_len > n:
            break  # partial trailing batch
        batch = buf[pos + 12 : pos + 12 + batch_len]
        pos += 12 + batch_len
        if len(batch) < 9:
            raise KafkaWireError("runt record batch")
        magic = batch[4]
        if magic != 2:
            raise KafkaWireError(f"unsupported batch magic {magic}")
        (crc_stored,) = struct.unpack(">I", batch[5:9])
        tail = batch[9:]
        if crc32c(tail) != crc_stored:
            raise KafkaWireError(f"bad batch CRC at offset {base_offset}")
        r = Reader(tail)
        r.int16()  # attributes (no compression in this subset)
        r.int32()  # lastOffsetDelta
        base_ts = r.int64()
        r.int64()  # maxTimestamp
        r.int64()  # producerId
        r.int16()  # producerEpoch
        r.int32()  # baseSequence
        num_records = r.int32()
        rest = tail[r.pos :]
        rpos = 0
        for _ in range(num_records):
            length, rpos = dec_varint(rest, rpos)
            end = rpos + length
            if length < 0 or end > len(rest):
                raise KafkaWireError("truncated record")
            rpos += 1  # record attributes
            ts_delta, rpos = dec_varint(rest, rpos)
            off_delta, rpos = dec_varint(rest, rpos)
            klen, rpos = dec_varint(rest, rpos)
            key = None
            if klen >= 0:
                key = rest[rpos : rpos + klen]
                rpos += klen
            vlen, rpos = dec_varint(rest, rpos)
            value = None
            if vlen >= 0:
                value = rest[rpos : rpos + vlen]
                rpos += vlen
            hcount, rpos = dec_varint(rest, rpos)
            headers = []
            for _h in range(max(hcount, 0)):
                hklen, rpos = dec_varint(rest, rpos)
                if hklen < 0 or rpos + hklen > len(rest):
                    raise KafkaWireError("truncated header key")
                hkey = rest[rpos : rpos + hklen].decode("utf-8")
                rpos += hklen
                hvlen, rpos = dec_varint(rest, rpos)
                hval = None
                if hvlen >= 0:
                    hval = rest[rpos : rpos + hvlen]
                    rpos += hvlen
                headers.append((hkey, hval))
            if rpos != end:
                rpos = end  # tolerate future per-record extensions
            out.append(
                KafkaRecord(
                    offset=base_offset + off_delta,
                    key=key,
                    value=value,
                    headers=tuple(headers),
                    timestamp_ms=base_ts + ts_delta,
                )
            )
    return out
