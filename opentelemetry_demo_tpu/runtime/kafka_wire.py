"""Kafka wire protocol: the minimal subset the orders leg speaks.

The reference's async tier is a real Kafka broker
(/root/reference/docker-compose.yml kafka service) with consumers
polling over TCP (src/fraud-detection/.../main.kt:54-69,
src/accounting/Consumer.cs:77-80). This image ships no Kafka client
library, so — in the same from-scratch spirit as ``runtime.wire`` for
protobuf — this module implements the Kafka protocol primitives
directly: size-prefixed request/response framing, the primitive codecs,
and the v0 MessageSet record format (magic 0, zlib CRC32).

Versions are pinned to the legacy (non-flexible) protocol era —
Produce v0, Fetch v0, ListOffsets v0, Metadata v0, FindCoordinator v0,
OffsetCommit v2, OffsetFetch v1 — which IS real Kafka wire format
(every broker accepted it for a decade); the point is consuming ordered
bytes over a real socket with consumer-group offset storage, not
re-implementing KIP-482 tagged fields. The in-repo broker
(``kafka_broker``) speaks the same subset, so client and broker are
interoperable test doubles for the compose topology's real broker.
"""

from __future__ import annotations

import struct
import zlib
from typing import NamedTuple

# API keys (the public protocol's).
PRODUCE = 0
FETCH = 1
LIST_OFFSETS = 2
METADATA = 3
OFFSET_COMMIT = 8
OFFSET_FETCH = 9
FIND_COORDINATOR = 10

# Error codes.
NO_ERROR = 0
OFFSET_OUT_OF_RANGE = 1
UNKNOWN_TOPIC_OR_PARTITION = 3
UNSUPPORTED_VERSION = 35


class KafkaWireError(ValueError):
    """Malformed Kafka wire data."""


# --- primitive codecs --------------------------------------------------


class Reader:
    """Sequential reader over one request/response body."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise KafkaWireError("truncated message")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def int8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> str | None:
        n = self.int16()
        if n == -1:
            return None
        return self._take(n).decode("utf-8")

    def bytes_(self) -> bytes | None:
        n = self.int32()
        if n == -1:
            return None
        return self._take(n)

    def array(self, fn):
        n = self.int32()
        if n < 0:
            return []
        return [fn() for _ in range(n)]

    def remaining(self) -> bytes:
        return self.buf[self.pos :]


def enc_int8(v: int) -> bytes:
    return struct.pack(">b", v)


def enc_int16(v: int) -> bytes:
    return struct.pack(">h", v)


def enc_int32(v: int) -> bytes:
    return struct.pack(">i", v)


def enc_int64(v: int) -> bytes:
    return struct.pack(">q", v)


def enc_string(v: str | None) -> bytes:
    if v is None:
        return enc_int16(-1)
    raw = v.encode("utf-8")
    return enc_int16(len(raw)) + raw


def enc_bytes(v: bytes | None) -> bytes:
    if v is None:
        return enc_int32(-1)
    return enc_int32(len(v)) + v


def enc_array(items, fn) -> bytes:
    return enc_int32(len(items)) + b"".join(fn(x) for x in items)


# --- request/response framing -----------------------------------------


def encode_request(
    api_key: int,
    api_version: int,
    correlation_id: int,
    client_id: str,
    body: bytes,
) -> bytes:
    """Size-prefixed request with the v1 (non-flexible) header."""
    payload = (
        enc_int16(api_key)
        + enc_int16(api_version)
        + enc_int32(correlation_id)
        + enc_string(client_id)
        + body
    )
    return enc_int32(len(payload)) + payload


class RequestHeader(NamedTuple):
    api_key: int
    api_version: int
    correlation_id: int
    client_id: str | None


def decode_request_header(reader: Reader) -> RequestHeader:
    return RequestHeader(
        api_key=reader.int16(),
        api_version=reader.int16(),
        correlation_id=reader.int32(),
        client_id=reader.string(),
    )


def encode_response(correlation_id: int, body: bytes) -> bytes:
    payload = enc_int32(correlation_id) + body
    return enc_int32(len(payload)) + payload


def read_frame(sock) -> bytes | None:
    """One size-prefixed frame off a socket; None on clean EOF."""
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (size,) = struct.unpack(">i", header)
    if size < 0 or size > 64 * 1024 * 1024:
        raise KafkaWireError(f"implausible frame size {size}")
    frame = _read_exact(sock, size)
    if frame is None:
        raise KafkaWireError("truncated frame")
    return frame


def _read_exact(sock, n: int) -> bytes | None:
    """Exactly n bytes; None on EOF at a frame boundary, error mid-frame."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            if got == 0:
                return None
            raise KafkaWireError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# --- MessageSet v0 (magic 0) ------------------------------------------


class KafkaMessage(NamedTuple):
    offset: int
    key: bytes | None
    value: bytes | None


def encode_message(key: bytes | None, value: bytes | None) -> bytes:
    """One magic-0 message body (without the offset/size envelope)."""
    rest = enc_int8(0) + enc_int8(0) + enc_bytes(key) + enc_bytes(value)
    crc = zlib.crc32(rest) & 0xFFFFFFFF
    return struct.pack(">I", crc) + rest


def encode_message_set(messages, base_offset: int = 0) -> bytes:
    """[(key, value), ...] → on-wire MessageSet with assigned offsets."""
    out = b""
    for i, (key, value) in enumerate(messages):
        msg = encode_message(key, value)
        out += enc_int64(base_offset + i) + enc_int32(len(msg)) + msg
    return out


def decode_message_set(buf: bytes) -> list[KafkaMessage]:
    """On-wire MessageSet → messages; a trailing partial message (the
    protocol allows brokers to cut one at the fetch byte limit) is
    dropped, matching every real client's behavior."""
    out: list[KafkaMessage] = []
    pos = 0
    n = len(buf)
    while pos + 12 <= n:
        offset, size = struct.unpack(">qi", buf[pos : pos + 12])
        if pos + 12 + size > n:
            break  # partial trailing message
        body = buf[pos + 12 : pos + 12 + size]
        pos += 12 + size
        crc_stored = struct.unpack(">I", body[:4])[0]
        rest = body[4:]
        if zlib.crc32(rest) & 0xFFFFFFFF != crc_stored:
            raise KafkaWireError(f"bad message CRC at offset {offset}")
        r = Reader(rest)
        magic = r.int8()
        if magic != 0:
            raise KafkaWireError(f"unsupported message magic {magic}")
        r.int8()  # attributes (no compression in this subset)
        key = r.bytes_()
        value = r.bytes_()
        out.append(KafkaMessage(offset=offset, key=key, value=value))
    return out
