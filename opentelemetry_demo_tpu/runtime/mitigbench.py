"""Closed-loop mitigation bench: time-to-mitigate beside time-to-detect.

qualbench measures how fast the detector *sees* each flagd failure
scenario; this harness measures how fast the remediation controller
*fixes* it, through the real control seam: the fault is gated by a live
flagd-schema flag (exactly how every shop service evaluates its fault
flags), the controller's :class:`~.remediation.FlagdActuator` disables
that flag through the store's atomic write path, and the loop then
VERIFIES recovery with the detector's own heads.

Per scenario (virtual time, ``DT_S`` per batch — the qualbench
timebase, so TTM and TTD are directly comparable):

- clean warmup → fault flag flips on → the fault shape from
  ``qualbench.fault_shapes`` mutates the stream **while the flag
  evaluates truthy**;
- the controller acts after its flagged-batch hysteresis; mitigation
  DISABLES the flag, the injector (reading the same store) stops, the
  heads clear, and the clean-streak verification records
  ``time_to_mitigate_s`` = fault-flagged → verified-recovery;
- the **rollback drill** runs one scenario whose injector IGNORES the
  mitigation (the flag flip does not heal the fault — a wrong runbook):
  the recovery deadline expires, the actuation rolls back to the exact
  prior flag state, and the service parks in ``MITIGATION_FAILED``;
- the **no-oscillation gate** runs a long clean stream with remediation
  ENABLED and every scenario flag present: zero actuator writes and
  zero flag flips is the pass condition (a controller that trims flags
  on quiet traffic is worse than no controller).

``main`` prints ONE json line (`make mitigbench`); bench.py runs it in
a CPU subprocess and lifts ``time_to_mitigate_s`` + the gates into the
flagship artifact.
"""

from __future__ import annotations

import numpy as np

from ..utils.flags import FlagEvaluator
from . import qualbench
from .qualbench import B, DT_S, S, WARM_STEPS, _batch, _quality_config
from .remediation import (
    STATE_FAILED,
    FlagdActuator,
    RemediationController,
    SamplingActuator,
)

# Mitigation loop horizon after fault onset: hysteresis + actuation +
# clean-streak verification all happen inside it (virtual batches).
MITIGATE_WINDOW_STEPS = 240
QUIET_STEPS = 600

# Bench-scale guardrails (virtual seconds): tight enough to finish in
# the window, shaped like production's (act hysteresis > 1 batch,
# verification clean-streak > 1 batch, deadline ≫ verify time).
ACT_BATCHES = 2
CLEAR_BATCHES = 4
DEADLINE_S = 10.0
ROLLBACK_DEADLINE_S = 4.0

# The ≥3 scenarios measured with verified recovery (names must exist in
# qualbench.fault_shapes), plus the rollback drill's scenario.
HEALING_SCENARIOS = (
    "paymentFailure", "recommendationCacheFailure", "kafkaQueueProblems",
)
ROLLBACK_SCENARIO = "paymentFailure"


def _scenario_store(flag_keys) -> FlagEvaluator:
    """A flagd-schema store carrying each scenario flag, fault OFF."""
    return FlagEvaluator({
        "flags": {
            key: {
                "state": "ENABLED",
                "variants": {"on": True, "off": False},
                "defaultVariant": "off",
            }
            for key in flag_keys
        }
    })


def _set_fault(store: FlagEvaluator, key: str, on: bool) -> None:
    doc = store.snapshot()
    doc["flags"][key]["defaultVariant"] = "on" if on else "off"
    store.replace(doc)


def run_scenario(
    name: str,
    heal: bool = True,
    seed: int = 0,
    deadline_s: float = DEADLINE_S,
) -> dict:
    """One closed-loop drill; ``heal=False`` is the rollback drill
    (the injector ignores the mitigation — flag flip does not heal)."""
    from .tensorize import SpanTensorizer
    from ..models import AnomalyDetector

    rng = np.random.default_rng(seed)
    shapes = qualbench.fault_shapes(rng)
    fault_svc, mutate = shapes[name]
    det = AnomalyDetector(_quality_config())
    tz = SpanTensorizer(num_services=S, batch_size=B)
    names = [f"svc{i}" for i in range(S)]
    for n in names:
        tz.service_id(n)
    store = _scenario_store([name])
    sampling_policy: dict = {}

    def publish(policy, seeds):
        sampling_policy.clear()
        sampling_policy.update(policy)

    flagd = FlagdActuator(
        store=store, policy={names[fault_svc]: (name,)}
    )
    sampler = SamplingActuator(
        publish=publish, base_policy={"*": 0.05},
        exemplar_fn=lambda svc: ["00deadbeef"],
    )
    ctrl = RemediationController(
        [flagd, sampler], enabled=True,
        act_batches=ACT_BATCHES, clear_batches=CLEAR_BATCHES,
        budget=4, budget_refill_s=1e9, deadline_s=deadline_s,
        rollback=True,
    )
    out: dict = {
        "ttd_s": None, "time_to_mitigate_s": None,
        "act_to_recover_s": None, "verified": False,
        "rolled_back": False, "sampling_promoted": False,
    }
    try:
        for step in range(WARM_STEPS):
            det.observe(_batch(rng, tz), step * DT_S)
        _set_fault(store, name, True)
        fault_steps = 0
        for k in range(MITIGATE_WINDOW_STEPS):
            t = (WARM_STEPS + k) * DT_S
            active = True if not heal else bool(
                store.evaluate(name, False)
            )
            if active:
                batch = _batch(rng, tz, mutate=mutate, step=fault_steps)
                fault_steps += 1
            else:
                batch = _batch(rng, tz)
            report = det.observe(batch, t)
            flags_np = np.asarray(report.flags)
            flagged = [names[i] for i in np.nonzero(flags_np)[0]]
            if out["ttd_s"] is None and flags_np[fault_svc]:
                out["ttd_s"] = round((k + 1) * DT_S, 3)
            ctrl.observe(t, flagged, services=names)
            ctrl.drain(5.0)  # serialize actuator writes per batch
            if sampling_policy.get(names[fault_svc]) == 1.0:
                out["sampling_promoted"] = True
            samples = ctrl.take_ttm_samples()
            if samples:
                ttm, act_to_recover = samples[0]
                # TTM on the onset basis: fault ONSET→verified, like
                # ttd_s is onset→first flag (the controller's own
                # sample runs first-flag→verified; onset adds the TTD).
                out["time_to_mitigate_s"] = round(
                    ttm + (out["ttd_s"] or 0.0) - DT_S, 3
                )
                out["act_to_recover_s"] = round(act_to_recover, 3)
                out["verified"] = True
                break
            if ctrl.state_of(names[fault_svc]) == STATE_FAILED:
                out["rolled_back"] = True
                break
        ctrl.drain(5.0)
        st = ctrl.stats()
        out.update({
            "flag_writes": flagd.writes,
            "actions": st["actions"],
            "failed": st["failed"],
            "rollbacks": st["rollbacks"],
            # The revert/rollback contract: the flag's spec is back to
            # its pre-mitigation state (ENABLED — the doc the operator
            # owns), proven, not assumed.
            "flag_state_end": (store.flag_spec(name) or {}).get("state"),
            "sampling_policy_end": dict(sampling_policy),
        })
    finally:
        ctrl.close()
    return out


def measure_no_oscillation(seed: int = 1) -> dict:
    """Long clean run with remediation ENABLED and every scenario flag
    present: the pass condition is ZERO actuator writes (no flag ever
    flips on quiet traffic) — the bench's anti-flap gate."""
    from .tensorize import SpanTensorizer
    from ..models import AnomalyDetector

    rng = np.random.default_rng(seed)
    det = AnomalyDetector(_quality_config())
    tz = SpanTensorizer(num_services=S, batch_size=B)
    names = [f"svc{i}" for i in range(S)]
    for n in names:
        tz.service_id(n)
    all_flags = list(qualbench.fault_shapes(rng))
    store = _scenario_store(all_flags)
    doc_before = store.snapshot()
    flagd = FlagdActuator(
        store=store,
        policy={names[i]: tuple(all_flags) for i in range(S)},
    )
    ctrl = RemediationController(
        [flagd], enabled=True, act_batches=ACT_BATCHES,
        clear_batches=CLEAR_BATCHES, budget=4, budget_refill_s=1e9,
        deadline_s=DEADLINE_S, rollback=True,
    )
    flagged_batches = 0
    try:
        for step in range(WARM_STEPS + QUIET_STEPS):
            t = step * DT_S
            report = det.observe(_batch(rng, tz), t)
            flags_np = np.asarray(report.flags)
            if step >= WARM_STEPS and flags_np.any():
                flagged_batches += 1
            ctrl.observe(
                t, [names[i] for i in np.nonzero(flags_np)[0]],
                services=names,
            )
        ctrl.drain(5.0)
    finally:
        ctrl.close()
    return {
        "quiet_batches": QUIET_STEPS,
        "flagged_batches": flagged_batches,
        "flag_writes": flagd.writes,
        "doc_unchanged": store.snapshot() == doc_before,
        "ok": flagd.writes == 0 and store.snapshot() == doc_before,
    }


def measure_mitigation(seed: int = 0) -> dict:
    scenarios = {}
    ttm = {}
    for name in HEALING_SCENARIOS:
        res = run_scenario(name, heal=True, seed=seed)
        scenarios[name] = res
        ttm[name] = res["time_to_mitigate_s"]
    rollback = run_scenario(
        ROLLBACK_SCENARIO, heal=False, seed=seed,
        deadline_s=ROLLBACK_DEADLINE_S,
    )
    no_osc = measure_no_oscillation(seed=seed + 1)
    verified_n = sum(1 for r in scenarios.values() if r["verified"])
    return {
        "dt_s": DT_S,
        "act_batches": ACT_BATCHES,
        "clear_batches": CLEAR_BATCHES,
        "time_to_mitigate_s": ttm,
        "scenarios": scenarios,
        "rollback_drill": rollback,
        "no_oscillation": no_osc,
        "mitigation_ok": bool(
            verified_n >= 3
            and rollback["rolled_back"]
            and rollback["flag_state_end"] == "ENABLED"
            and no_osc["ok"]
        ),
    }


def main() -> None:
    import json

    print(json.dumps(measure_mitigation()))


if __name__ == "__main__":
    main()
