"""Closed-loop mitigation bench: time-to-mitigate beside time-to-detect.

qualbench measures how fast the detector *sees* each flagd failure
scenario; this harness measures how fast the remediation controller
*fixes* it, through the real control seam: the fault is gated by a live
flagd-schema flag (exactly how every shop service evaluates its fault
flags), the controller's :class:`~.remediation.FlagdActuator` disables
that flag through the store's atomic write path, and the loop then
VERIFIES recovery with the detector's own heads.

Per scenario (virtual time, ``DT_S`` per batch — the qualbench
timebase, so TTM and TTD are directly comparable):

- clean warmup → fault flag flips on → the fault shape from
  ``qualbench.fault_shapes`` mutates the stream **while the flag
  evaluates truthy**;
- the controller acts after its flagged-batch hysteresis; mitigation
  DISABLES the flag, the injector (reading the same store) stops, the
  heads clear, and the clean-streak verification records
  ``time_to_mitigate_s`` = fault-flagged → verified-recovery;
- the **rollback drill** runs one scenario whose injector IGNORES the
  mitigation (the flag flip does not heal the fault — a wrong runbook):
  the recovery deadline expires, the actuation rolls back to the exact
  prior flag state, and the service parks in ``MITIGATION_FAILED``;
- the **no-oscillation gate** runs a long clean stream with remediation
  ENABLED and every scenario flag present: zero actuator writes and
  zero flag flips is the pass condition (a controller that trims flags
  on quiet traffic is worse than no controller).

The **shadow leg** (``--shadow`` / ``make shadowbench``, gated by
``BENCH_SHADOW``) proves the PR 17 counterfactual gate live, both
directions: a closed loop records its own span corpus through a REAL
``DetectorPipeline`` + ``HistoryWriter`` while a preflighted controller
replays it through ``runtime.shadow`` before every act — a would-help
mitigation is released (TTM within 2× the ungated baseline, the
act→verdict interval measured), a mitigation mapped to the WRONG
service is refused before any actuator write (zero flag-store
mutations, budget token refunded, ``preflight_refused`` flight
evidence + dump on disk) — plus the shadow-vs-replaybench bit-identity
/ ≥rate×wall pin and the collector-steering keep-ratio measurement
with exact-state revert.

``main`` prints ONE json line (`make mitigbench`); bench.py runs it in
a CPU subprocess and lifts ``time_to_mitigate_s`` + the gates into the
flagship artifact.
"""

from __future__ import annotations

import glob
import os
import tempfile
import time

import numpy as np

from ..utils.flags import FlagEvaluator
from . import history, qualbench, replaybench, shadow
from .flightrec import FlightRecorder
from .qualbench import B, DT_S, S, WARM_STEPS, _batch, _quality_config
from .remediation import (
    STATE_FAILED,
    CollectorActuator,
    FlagdActuator,
    RemediationController,
    SamplingActuator,
)

# Mitigation loop horizon after fault onset: hysteresis + actuation +
# clean-streak verification all happen inside it (virtual batches).
MITIGATE_WINDOW_STEPS = 240
QUIET_STEPS = 600

# Bench-scale guardrails (virtual seconds): tight enough to finish in
# the window, shaped like production's (act hysteresis > 1 batch,
# verification clean-streak > 1 batch, deadline ≫ verify time).
ACT_BATCHES = 2
CLEAR_BATCHES = 4
DEADLINE_S = 10.0
ROLLBACK_DEADLINE_S = 4.0

# The ≥3 scenarios measured with verified recovery (names must exist in
# qualbench.fault_shapes), plus the rollback drill's scenario.
HEALING_SCENARIOS = (
    "paymentFailure", "recommendationCacheFailure", "kafkaQueueProblems",
)
ROLLBACK_SCENARIO = "paymentFailure"


def _scenario_store(flag_keys) -> FlagEvaluator:
    """A flagd-schema store carrying each scenario flag, fault OFF."""
    return FlagEvaluator({
        "flags": {
            key: {
                "state": "ENABLED",
                "variants": {"on": True, "off": False},
                "defaultVariant": "off",
            }
            for key in flag_keys
        }
    })


def _set_fault(store: FlagEvaluator, key: str, on: bool) -> None:
    doc = store.snapshot()
    doc["flags"][key]["defaultVariant"] = "on" if on else "off"
    store.replace(doc)


def run_scenario(
    name: str,
    heal: bool = True,
    seed: int = 0,
    deadline_s: float = DEADLINE_S,
) -> dict:
    """One closed-loop drill; ``heal=False`` is the rollback drill
    (the injector ignores the mitigation — flag flip does not heal)."""
    from .tensorize import SpanTensorizer
    from ..models import AnomalyDetector

    rng = np.random.default_rng(seed)
    shapes = qualbench.fault_shapes(rng)
    fault_svc, mutate = shapes[name]
    det = AnomalyDetector(_quality_config())
    tz = SpanTensorizer(num_services=S, batch_size=B)
    names = [f"svc{i}" for i in range(S)]
    for n in names:
        tz.service_id(n)
    store = _scenario_store([name])
    sampling_policy: dict = {}

    def publish(policy, seeds):
        sampling_policy.clear()
        sampling_policy.update(policy)

    flagd = FlagdActuator(
        store=store, policy={names[fault_svc]: (name,)}
    )
    sampler = SamplingActuator(
        publish=publish, base_policy={"*": 0.05},
        exemplar_fn=lambda svc: ["00deadbeef"],
    )
    ctrl = RemediationController(
        [flagd, sampler], enabled=True,
        act_batches=ACT_BATCHES, clear_batches=CLEAR_BATCHES,
        budget=4, budget_refill_s=1e9, deadline_s=deadline_s,
        rollback=True,
    )
    out: dict = {
        "ttd_s": None, "time_to_mitigate_s": None,
        "act_to_recover_s": None, "verified": False,
        "rolled_back": False, "sampling_promoted": False,
    }
    try:
        for step in range(WARM_STEPS):
            det.observe(_batch(rng, tz), step * DT_S)
        _set_fault(store, name, True)
        fault_steps = 0
        for k in range(MITIGATE_WINDOW_STEPS):
            t = (WARM_STEPS + k) * DT_S
            active = True if not heal else bool(
                store.evaluate(name, False)
            )
            if active:
                batch = _batch(rng, tz, mutate=mutate, step=fault_steps)
                fault_steps += 1
            else:
                batch = _batch(rng, tz)
            report = det.observe(batch, t)
            flags_np = np.asarray(report.flags)
            flagged = [names[i] for i in np.nonzero(flags_np)[0]]
            if out["ttd_s"] is None and flags_np[fault_svc]:
                out["ttd_s"] = round((k + 1) * DT_S, 3)
            ctrl.observe(t, flagged, services=names)
            ctrl.drain(5.0)  # serialize actuator writes per batch
            if sampling_policy.get(names[fault_svc]) == 1.0:
                out["sampling_promoted"] = True
            samples = ctrl.take_ttm_samples()
            if samples:
                ttm, act_to_recover = samples[0]
                # TTM on the onset basis: fault ONSET→verified, like
                # ttd_s is onset→first flag (the controller's own
                # sample runs first-flag→verified; onset adds the TTD).
                out["time_to_mitigate_s"] = round(
                    ttm + (out["ttd_s"] or 0.0) - DT_S, 3
                )
                out["act_to_recover_s"] = round(act_to_recover, 3)
                out["verified"] = True
                break
            if ctrl.state_of(names[fault_svc]) == STATE_FAILED:
                out["rolled_back"] = True
                break
        ctrl.drain(5.0)
        st = ctrl.stats()
        out.update({
            "flag_writes": flagd.writes,
            "actions": st["actions"],
            "failed": st["failed"],
            "rollbacks": st["rollbacks"],
            # The revert/rollback contract: the flag's spec is back to
            # its pre-mitigation state (ENABLED — the doc the operator
            # owns), proven, not assumed.
            "flag_state_end": (store.flag_spec(name) or {}).get("state"),
            "sampling_policy_end": dict(sampling_policy),
        })
    finally:
        ctrl.close()
    return out


def measure_no_oscillation(seed: int = 1) -> dict:
    """Long clean run with remediation ENABLED and every scenario flag
    present: the pass condition is ZERO actuator writes (no flag ever
    flips on quiet traffic) — the bench's anti-flap gate."""
    from .tensorize import SpanTensorizer
    from ..models import AnomalyDetector

    rng = np.random.default_rng(seed)
    det = AnomalyDetector(_quality_config())
    tz = SpanTensorizer(num_services=S, batch_size=B)
    names = [f"svc{i}" for i in range(S)]
    for n in names:
        tz.service_id(n)
    all_flags = list(qualbench.fault_shapes(rng))
    store = _scenario_store(all_flags)
    doc_before = store.snapshot()
    flagd = FlagdActuator(
        store=store,
        policy={names[i]: tuple(all_flags) for i in range(S)},
    )
    ctrl = RemediationController(
        [flagd], enabled=True, act_batches=ACT_BATCHES,
        clear_batches=CLEAR_BATCHES, budget=4, budget_refill_s=1e9,
        deadline_s=DEADLINE_S, rollback=True,
    )
    flagged_batches = 0
    try:
        for step in range(WARM_STEPS + QUIET_STEPS):
            t = step * DT_S
            report = det.observe(_batch(rng, tz), t)
            flags_np = np.asarray(report.flags)
            if step >= WARM_STEPS and flags_np.any():
                flagged_batches += 1
            ctrl.observe(
                t, [names[i] for i in np.nonzero(flags_np)[0]],
                services=names,
            )
        ctrl.drain(5.0)
    finally:
        ctrl.close()
    return {
        "quiet_batches": QUIET_STEPS,
        "flagged_batches": flagged_batches,
        "flag_writes": flagd.writes,
        "doc_unchanged": store.snapshot() == doc_before,
        "ok": flagd.writes == 0 and store.snapshot() == doc_before,
    }


def measure_mitigation(seed: int = 0) -> dict:
    scenarios = {}
    ttm = {}
    for name in HEALING_SCENARIOS:
        res = run_scenario(name, heal=True, seed=seed)
        scenarios[name] = res
        ttm[name] = res["time_to_mitigate_s"]
    rollback = run_scenario(
        ROLLBACK_SCENARIO, heal=False, seed=seed,
        deadline_s=ROLLBACK_DEADLINE_S,
    )
    no_osc = measure_no_oscillation(seed=seed + 1)
    verified_n = sum(1 for r in scenarios.values() if r["verified"])
    return {
        "dt_s": DT_S,
        "act_batches": ACT_BATCHES,
        "clear_batches": CLEAR_BATCHES,
        "time_to_mitigate_s": ttm,
        "scenarios": scenarios,
        "rollback_drill": rollback,
        "no_oscillation": no_osc,
        "mitigation_ok": bool(
            verified_n >= 3
            and rollback["rolled_back"]
            and rollback["flag_state_end"] == "ENABLED"
            and no_osc["ok"]
        ),
    }


# -- the PR 17 shadow leg ----------------------------------------------

# The counterfactual drills run at the REPLAYBENCH geometry (S=8,
# B=256, dt=0.25 — the recorded-corpus protocol under test, not
# detection quality) with the paymentFailure-shaped flag gate.
PREFLIGHT_FLAG = "paymentFailure"
PREFLIGHT_WINDOW_STEPS = 160
PREFLIGHT_CLEAR_TAIL = 4


def _preflight_loop(
    preflight_wired: bool, refuse: bool = False, seed: int = 0,
) -> dict:
    """One closed loop at replaybench geometry: a REAL pipeline feeds
    a live detector AND records its span corpus (HistoryWriter), the
    fault is gated by a live flagd-schema flag, and — when wired — a
    ShadowVerifier replays the recorded window before every act.
    ``refuse=True`` models a mitigation mapped to the WRONG service:
    its counterfactual transform suppresses a healthy service, so the
    flagged service never clears in the shadow and the act is refused.
    ``preflight_wired=False`` is the PR 13 baseline the TTM gate
    compares against."""
    rng = np.random.default_rng(seed)
    names = [f"svc{i}" for i in range(replaybench.S)]
    fault_name = names[replaybench.FAULT_SVC]
    out: dict = {
        "ttd_s": None, "time_to_mitigate_s": None, "verified": False,
        "preflight_verdict_s": None, "refused": 0, "released": 0,
        "refused_reason": None, "flag_writes": 0,
        "doc_unchanged": None, "tokens_refunded": None,
        "flight_refused_events": 0, "flight_refused_dumps": 0,
    }
    with tempfile.TemporaryDirectory(prefix="shadowbench-") as directory:
        live: dict = {}
        det, pipe = shadow.build_shadow_pipeline(
            replaybench._replay_config(), replaybench.B, live
        )
        store_h = history.HistoryStore(
            directory, retention_s=(86400.0, 86400.0)
        )

        def snapshot():
            with pipe._dispatch_lock:
                arrays = {
                    k: np.asarray(v)
                    for k, v in det.state._asdict().items()
                }
                clock_t_prev = det.clock._t_prev
            return arrays, {
                "clock_t_prev": clock_t_prev,
                "service_names": pipe.tensorizer.service_names,
                "config": list(det.config._replace(sketch_impl=None)),
                "query": pipe.query_meta(),
            }

        writer = history.HistoryWriter(
            store_h, snapshot, rungs=(1.0, 60.0), capture_spans=True,
            span_queue_max=4 * (
                replaybench.WARM_STEPS + PREFLIGHT_WINDOW_STEPS
            ),
        )
        pipe.history_capture = writer.capture
        reader = history.HistoryReader(store_h, rungs=(1.0, 60.0))
        flag_store = _scenario_store([PREFLIGHT_FLAG])
        doc_before = flag_store.snapshot()
        wall0 = time.time()
        t_cur = [0.0]
        flight = FlightRecorder(dump_dir=directory)
        preflight_fn = None
        if preflight_wired:
            # The WRONG-mitigation drill suppresses a healthy service
            # in the counterfactual; the verdict still asks whether
            # the FLAGGED service clears.
            target = (
                (replaybench.FAULT_SVC + 1) % replaybench.S
                if refuse else replaybench.FAULT_SVC
            )
            verifier = shadow.ShadowVerifier(
                reader, replaybench._replay_config(),
                batch_size=replaybench.B, window_s=90.0,
                deadline_s=30.0, min_records=8,
                clear_tail=PREFLIGHT_CLEAR_TAIL, flight=flight,
                now_fn=lambda: wall0 + t_cur[0],
            )

            def preflight_fn(_svc):
                return verifier.verify(
                    replaybench.FAULT_SVC,
                    shadow.suppress_transform(target),
                )

        flagd = FlagdActuator(
            store=flag_store, policy={fault_name: (PREFLIGHT_FLAG,)}
        )
        ctrl = RemediationController(
            [flagd], enabled=True, act_batches=ACT_BATCHES,
            clear_batches=CLEAR_BATCHES, budget=2, budget_refill_s=1e9,
            deadline_s=DEADLINE_S, rollback=True, flight=flight,
            preflight=preflight_fn,
        )
        try:
            for step in range(
                replaybench.WARM_STEPS + PREFLIGHT_WINDOW_STEPS
            ):
                t = step * replaybench.DT_S
                t_cur[0] = t
                if step == replaybench.WARM_STEPS:
                    _set_fault(flag_store, PREFLIGHT_FLAG, True)
                    # The zero-mutation gate compares against the doc
                    # WITH the fault injected: only actuator writes
                    # may change it from here.
                    doc_before = flag_store.snapshot()
                faulted = step >= replaybench.WARM_STEPS and bool(
                    flag_store.evaluate(PREFLIGHT_FLAG, False)
                )
                pipe.submit_columns(
                    replaybench._make_cols(rng, step, faulted)
                )
                pipe.pump(t)
                pipe.drain()  # this batch's report, synchronously
                writer.tick(now=wall0 + t)
                flags = live.get(round(t, 6)) or ()
                flagged = [
                    names[i] for i, f in enumerate(flags) if f
                ]
                k = step - replaybench.WARM_STEPS
                if (
                    out["ttd_s"] is None and k >= 0
                    and replaybench.FAULT_SVC < len(flags)
                    and flags[replaybench.FAULT_SVC]
                ):
                    out["ttd_s"] = round((k + 1) * replaybench.DT_S, 3)
                ctrl.observe(t, flagged, services=names)
                # Serialize the worker (preflight replay + actuator
                # writes) inside this virtual batch, so TTM stays
                # comparable across gated and ungated runs.
                ctrl.drain(60.0)
                for verdict_s in ctrl.take_preflight_samples():
                    out["preflight_verdict_s"] = round(verdict_s, 4)
                samples = ctrl.take_ttm_samples()
                if samples:
                    ttm, _a2r = samples[0]
                    out["time_to_mitigate_s"] = round(
                        ttm + (out["ttd_s"] or 0.0) - replaybench.DT_S, 3
                    )
                    out["verified"] = True
                    break
                st = ctrl.stats()
                if refuse and st["preflight_verdicts"].get(
                    "refused", 0
                ) >= 2:
                    break  # two refusals prove the gate holds; stop
            ctrl.drain(60.0)
            st = ctrl.stats()
            out.update({
                "released": st["preflight_verdicts"].get("released", 0),
                "refused": st["preflight_verdicts"].get("refused", 0),
                "refused_reason": (
                    max(
                        st["preflight_refused"],
                        key=st["preflight_refused"].get,
                    )
                    if st["preflight_refused"] else None
                ),
                "flag_writes": flagd.writes,
                "doc_unchanged": flag_store.snapshot() == doc_before,
                "tokens_refunded": abs(ctrl.bucket.tokens - 2.0) < 1e-6,
                "flight_refused_events": flight.events_total.get(
                    "preflight_refused", 0
                ),
                "flight_refused_dumps": len(glob.glob(
                    os.path.join(directory, "flight-preflight-refused-*")
                )),
            })
        finally:
            ctrl.close()
            writer.close()
            pipe.close()
    return out


def measure_shadow_identity(seed: int = 0, rate_target: float = 10.0) -> dict:
    """Record an incident with replaybench's own recorder, replay it
    BOTH ways — ``replaybench.replay`` and a transform-less
    ``ShadowVerifier`` pass — and pin all three verdict maps equal
    (recording run, replaybench replay, shadow replay) at ≥ the rate
    target. One shared pipeline builder makes drift structurally
    impossible; this gate proves it stays that way."""
    with tempfile.TemporaryDirectory(prefix="shadowident-") as directory:
        recorded = replaybench.record_incident(directory, seed=seed)
        replayed, _virtual, _wall, _batches = replaybench.replay(directory)
        store = history.HistoryStore(directory)
        reader = history.HistoryReader(store, rungs=(1.0, 60.0))
        recs = reader.span_records()
        now = recs[-1].t_end + 1.0
        verifier = shadow.ShadowVerifier(
            reader, replaybench._replay_config(),
            batch_size=replaybench.B,
            window_s=now - recs[0].t_start + 1.0,
            deadline_s=300.0, rate_target=rate_target, min_records=1,
        )
        v = verifier.verify(replaybench.FAULT_SVC, None, now=now)
    identical = v.verdicts == recorded == replayed
    return {
        "shadow_identical": bool(identical),
        "shadow_speedup": v.speedup,
        "shadow_batches": v.batches,
        "shadow_wall_s": v.wall_s,
        "shadow_would_help": v.would_help,  # no transform: still flagged
    }


def measure_collector(seed: int = 0) -> dict:
    """The collector-steering leg: push a tail-sampling policy for the
    flagged service, MEASURE the row-level keep fraction the policy
    implies on a replaybench-shaped stream (promoted service keeps
    every row, quiet services head-sample deterministically by trace
    key), then prove the exact-state revert (the policy file did not
    exist before the first hold → it is GONE after the last release)."""
    names = [f"svc{i}" for i in range(replaybench.S)]
    promoted = names[replaybench.FAULT_SVC]
    with tempfile.TemporaryDirectory(prefix="collbench-") as directory:
        path = os.path.join(directory, "tail-sampling-policy.json")
        col = CollectorActuator(
            policy_path=path, base_keep=0.1,
            exemplar_fn=lambda svc: ["00deadbeef"],
            services_fn=lambda: names,
        )
        token = col.apply(promoted)
        pushed = os.path.exists(path)
        implied = col.keep_ratio()
        policy_names = [
            p["name"] for p in col.render_policy()["processors"][
                "tail_sampling/anomaly"
            ]["policies"]
        ]
        # Row-level measurement: apply the pushed policy's semantics
        # to the recorded-shape stream (keep-all on the promoted
        # service, threshold-by-trace-key at base_keep elsewhere —
        # all spans of one trace land or drop together).
        rng = np.random.default_rng(seed)
        kept = total = 0
        for step in range(60):
            cols = replaybench._make_cols(rng, step, step >= 30)
            svc = np.asarray(cols.svc)
            key = np.asarray(cols.trace_key, dtype=np.uint64)
            u = (
                (key * np.uint64(0x9E3779B97F4A7C15))
                >> np.uint64(40)
            ).astype(np.float64) / float(1 << 24)
            keep = (svc == replaybench.FAULT_SVC) | (u < 0.1)
            kept += int(keep.sum())
            total += int(svc.size)
        measured = kept / max(total, 1)
        col.revert(promoted, token)
        revert_exact = not os.path.exists(path)
    return {
        "collector_keep_ratio": round(measured, 4),
        "collector_keep_ratio_policy": round(implied, 4),
        "collector_storage_reduction": round(1.0 - measured, 4),
        "collector_pushed": bool(pushed),
        "collector_policy_names": policy_names,
        "collector_revert_exact": bool(revert_exact),
    }


def measure_shadow(seed: int = 0) -> dict:
    """The ``--shadow`` artifact block: both verdict directions live,
    bit-identity + speedup, and the collector keep/drop ratio."""
    from ..utils.config import SHADOW_KNOBS, env_float

    rate_target = env_float(
        "ANOMALY_SHADOW_RATE", SHADOW_KNOBS["ANOMALY_SHADOW_RATE"][1]
    )
    ident = measure_shadow_identity(seed=seed, rate_target=rate_target)
    baseline = _preflight_loop(False, seed=seed)
    released = _preflight_loop(True, refuse=False, seed=seed)
    refusal = _preflight_loop(True, refuse=True, seed=seed)
    base_ttm = baseline["time_to_mitigate_s"]
    gated_ttm = released["time_to_mitigate_s"]
    ttm_ratio = (
        round(gated_ttm / base_ttm, 3)
        if base_ttm and gated_ttm else None
    )
    refusal_ok = bool(
        refusal["refused"] >= 1
        and not refusal["verified"]
        and refusal["flag_writes"] == 0
        and refusal["doc_unchanged"]
        and refusal["tokens_refunded"]
        and refusal["flight_refused_events"] >= 1
        and refusal["flight_refused_dumps"] >= 1
    )
    released_ok = bool(
        released["verified"]
        and released["released"] >= 1
        and ttm_ratio is not None and ttm_ratio <= 2.0
    )
    return {
        **ident,
        "shadow_rate_target": rate_target,
        "preflight_baseline_ttm_s": base_ttm,
        "preflight_ttm_s": gated_ttm,
        "preflight_ttm_ratio": ttm_ratio,
        "preflight_verdict_s": released["preflight_verdict_s"],
        "preflight_released": released,
        "preflight_refusal": refusal,
        "preflight_refusal_ok": refusal_ok,
        **measure_collector(seed=seed),
        "shadow_ok": bool(
            ident["shadow_identical"]
            and ident["shadow_speedup"] >= rate_target
            and released_ok and refusal_ok
        ),
    }


def main() -> None:
    import json
    import sys

    from ..utils.config import BENCH_KNOBS, env_int

    shadow_only = "--shadow" in sys.argv[1:]
    out: dict = {}
    if not shadow_only:
        out.update(measure_mitigation())
    if shadow_only or env_int(
        "BENCH_SHADOW", BENCH_KNOBS["BENCH_SHADOW"][1]
    ):
        out.update(measure_shadow())
    print(json.dumps(out))


if __name__ == "__main__":
    main()
