"""Streaming pipeline: records → batches → device, without host syncs.

The latency budget (<100 ms p99 detection lag, BASELINE north_star)
shapes this module: JAX dispatch is asynchronous, so the pipeline keeps
exactly one report in flight — batch *k* is dispatched before batch
*k-1*'s report is fetched, overlapping host tensorization, host→device
transfer, and device compute the way the reference's async Kafka
producer overlaps order handling
(/root/reference/src/checkout/kafka/producer.go:15-43).

Flag gating per the north star: ``anomalyDetectorEnabled`` switches the
whole device path off (records are drained and dropped);
``anomalyDetectorZThreshold`` adjusts flagging at report time without
recompiling (the jitted step's threshold only feeds the report's
``flags`` bool — the z-scores themselves are always emitted).

Ingest seam: ``submit_columns`` is the ONE admission gate — the serial
receiver paths (``submit``/``submit_columnar``) and the parallel
ingest engine (``runtime.ingest_pool``, which coalesces many requests
into one columns batch per flush) all merge through it, so shed/
brownout/saturation semantics are identical regardless of which decode
architecture fed the queue, and the pipeline lock is taken once per
flush instead of once per request on the pooled path.

Overload protection (``queue_max_rows`` > 0): the pending queue is
row-budgeted with high/low watermarks — the reference collector's
``memory_limiter`` + ``sending_queue`` discipline rebuilt at the
pipeline seam. Over budget, the OLDEST OK-lane rows are shed first and
error/exception-lane rows are never shed (``SHED_LANES``); between the
watermarks a saturation flag (hysteresis) tells the OTLP receivers to
answer retryable 429/``RESOURCE_EXHAUSTED``; and under SUSTAINED
saturation a deterministic brownout ladder head-samples OK-lane rows
(1/2, 1/4, …) so detection stays live — degraded and counted — instead
of lagging unboundedly. tests/test_overload.py is the proof.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable

import jax
import numpy as np

from ..models.detector import AnomalyDetector, DetectorReport, report_unpack
from ..ops.hashing import splitmix64_np
from ..utils.flags import FlagEvaluator
from .provenance import (
    REASON_CARDINALITY,
    REASON_CUSUM,
    REASON_ERROR_RATE,
    REASON_LATENCY,
    REASON_THROUGHPUT,
)
from .selftrace import (
    PHASE_DISPATCH,
    PHASE_FLAG,
    PHASE_HARVEST,
    PHASE_HARVEST_LAG,
    PHASE_PUT_WAIT,
    PHASE_STAGE,
    SPAN_DISPATCH,
    SPAN_FLAG,
    SPAN_HARVEST,
    SPAN_PUT,
    SPAN_STAGE,
)
from .tensorize import SpanColumns, SpanRecord, SpanTensorizer

FLAG_ENABLED = "anomalyDetectorEnabled"
FLAG_THRESHOLD = "anomalyDetectorZThreshold"

# Admission contract: the lanes the shed policy is ALLOWED to drop.
# The error/exception lane is deliberately absent — under any overload
# the rows that explain an incident are the last ones a detector may
# throw away. scripts/sanitycheck.py pins this constant (and the tests
# assert the error-lane counter stays 0 under a 5x flood), so a future
# edit that widens it is a visible contract change, not a drive-by.
SHED_LANES = ("ok",)

# Keyspace degradation ladder (runtime/keyspace.py drives the clock;
# KEYSPACE_KNOBS is the registry). One rung per ANOMALY_KEYSPACE_HOLD_S
# of SUSTAINED pressure, two-edge hysteresis exactly like the brownout
# ladder — each rung degrades NEW-key admission harder while existing
# keys' detection stays untouched:
#   0 normal · 1 evict idle keys · 2 per-tenant new-key throttle ·
#   3 overflow-collapse all new keys · 4 shed ingest (429 Retry-After).
KEYSPACE_LEVEL_EVICT = 1
KEYSPACE_LEVEL_THROTTLE = 2
KEYSPACE_LEVEL_COLLAPSE = 3
KEYSPACE_LEVEL_SHED = 4
KEYSPACE_MAX_LEVEL = KEYSPACE_LEVEL_SHED


def _pow2_ceil(n: int) -> int:
    """Smallest power of two ≥ n — the width ladder's rounding rule
    (constructor cap AND escalation factor must agree, or a width
    leaves the precompiled ladder and compiles mid-incident)."""
    return 1 << max(n - 1, 0).bit_length()


@dataclass
class PipelineStats:
    batches: int = 0
    spans: int = 0
    dropped_disabled: int = 0
    flag_events: int = 0
    # Reports dropped unfetched under a harvest interval (their batches
    # still updated device state; only the host-side readback skipped).
    reports_skipped: int = 0
    # Reports whose host-side processing raised (async harvester only;
    # the sync path propagates to the caller).
    harvest_errors: int = 0
    # Bounded window: the exported p99 tracks *current* lag, and memory
    # stays constant in a sidecar that pumps for days.
    lag_ms: deque = field(default_factory=lambda: deque(maxlen=2048))
    # Paired per-harvest RTT probes (rtt_probe=True): sample i here rode
    # the tunnel CONCURRENTLY with lag sample i's report fetch — so
    # lag−rtt is an elementwise pairing under identical congestion, not
    # a subtraction of two unrelated medians.
    rtt_ms: deque = field(default_factory=lambda: deque(maxlen=2048))
    # Overload accounting (bounded admission): rows dropped by the
    # overflow shed, per lane. The "error" key exists so the
    # zero-error-lane-loss invariant is an asserted number, not an
    # absence — it must stay 0 (SHED_LANES).
    shed_rows: dict = field(default_factory=lambda: {"ok": 0, "error": 0})
    # Per-tenant quota shed (the fleet's noisy-tenant isolation,
    # ANOMALY_FLEET_TENANT_QUOTA_ROWS_S): OK-lane rows a tenant lost
    # to ITS OWN bucket, keyed by tenant — exported as
    # anomaly_shed_rows_total{tenant=}. Other tenants' admission is
    # untouched by construction (one bucket per tenant).
    shed_rows_tenant: dict = field(default_factory=dict)
    # OK-lane rows dropped by the brownout head-sampler (deliberate,
    # deterministic degradation — distinct from the overflow shed).
    brownout_rows: int = 0
    # Times the queue crossed the high watermark (one event per
    # saturation episode, not per refused request).
    saturation_events: int = 0
    # Keyspace ladder accounting (runtime/keyspace.py): NEW keys a
    # tenant's token bucket deferred to overflow at the throttle rung,
    # and new keys collapsed wholesale at the collapse rung — both
    # keyed by tenant, exported as
    # anomaly_keyspace_newkey_throttled_total{tenant=} /
    # anomaly_keyspace_overflow_keys_total{tenant=}.
    newkey_throttled_tenant: dict = field(default_factory=dict)
    overflow_keys_tenant: dict = field(default_factory=dict)
    # Times keyspace pressure crossed its high watermark (one event
    # per pressure episode, mirroring saturation_events).
    keyspace_pressure_events: int = 0

    def lag_p99_ms(self) -> float:
        if not self.lag_ms:
            return 0.0
        return float(np.percentile(np.asarray(self.lag_ms), 99))

    def lag_net_samples(self) -> np.ndarray:
        """Elementwise lag−RTT over the paired tail (empty w/o probes).

        The net lag approximates a locally attached chip: each harvest's
        device→host fetch pays one tunnel round trip that a local PCIe/
        ICI attach would not, and the probe measures THAT harvest's RTT,
        not a run-level median.
        """
        n = min(len(self.lag_ms), len(self.rtt_ms))
        if n == 0:
            return np.empty(0, np.float64)
        lag = np.asarray(self.lag_ms, dtype=np.float64)[-n:]
        rtt = np.asarray(self.rtt_ms, dtype=np.float64)[-n:]
        net = lag - rtt
        return net[~np.isnan(net)]


class DetectorPipeline:
    """Drives an :class:`AnomalyDetector` from a span-record source."""

    def __init__(
        self,
        detector: AnomalyDetector,
        flags: FlagEvaluator | None = None,
        on_report: Callable[[float, DetectorReport, list[str]], None] | None = None,
        batch_size: int = 2048,
        max_wait_s: float = 0.05,
        harvest_interval_s: float = 0.0,
        harvest_async: bool = False,
        rtt_probe: bool = False,
        adaptive_batching: bool = False,
        max_batch_growth: int = 8,
        queue_max_rows: int = 0,
        high_watermark: float = 0.85,
        low_watermark: float = 0.5,
        brownout_hold_s: float = 2.0,
        brownout_max_level: int = 4,
        retry_after_s: float = 1.0,
        exemplar_ring: int = 8,
        hh_candidates: int = 64,
        spine_ring: int = 0,
        spine_overlap: bool = True,
        spine_chunk_rows: int = 0,
        phase_observe: Callable[[str, float], None] | None = None,
        selftrace=None,
        history_capture: Callable[[object, float], None] | None = None,
        tenant_of: Callable[[str], str] | None = None,
        tenant_quota_rows_s: float = 0.0,
        provenance=None,
        explain_ring: int = 64,
        keyspace_enable: bool = False,
        keyspace_high_watermark: float = 0.85,
        keyspace_low_watermark: float = 0.70,
        keyspace_hold_s: float = 5.0,
        keyspace_newkey_rate: float = 64.0,
        keyspace_retry_after_s: float = 2.0,
    ):
        self.detector = detector
        # Time-travel span capture (runtime.history.HistoryWriter
        # .capture, or None): every dispatched batch's host columns +
        # virtual timebase, the replay corpus replaybench re-feeds.
        # The callee copies and enqueues (bounded, drop-oldest) — the
        # pump thread pays one memcpy, never an encode or a disk write.
        self.history_capture = history_capture
        # Self-telemetry (runtime.selftrace): ``phase_observe(phase,
        # seconds)`` feeds the promoted per-phase histograms (dispatch/
        # stage/put-wait/harvest/harvest-lag/flag) one sample per batch;
        # ``selftrace`` (a SelfTracer or None) samples whole batch
        # lifecycles into exported traces. Both default off and both
        # cost nothing when None — the hot path pays one None check.
        self.phase_observe = phase_observe
        self._selftrace = selftrace
        self.flags = flags or FlagEvaluator()
        self.on_report = on_report
        self.tensorizer = SpanTensorizer(
            num_services=detector.config.num_services, batch_size=batch_size
        )
        # Device-put spine (runtime.spine; knob registry:
        # utils.config.SPINE_KNOBS): pack+put move off the pump thread
        # onto a stager working a ring of ``spine_ring`` pre-allocated
        # host buffers, so batch k+1's host→device transfer overlaps
        # batch k's in-flight donated step. 0 = the classic in-tick
        # pack+put path. Dispatch itself NEVER moves: the spine owns no
        # detector state and every state touch stays on the pump thread
        # under _dispatch_lock (the donation-race contract).
        self._spine = None
        if spine_ring > 0:
            from .spine import DevicePutSpine

            self._spine = DevicePutSpine(
                self.tensorizer,
                depth=spine_ring,
                overlap=spine_overlap,
                chunk_rows=spine_chunk_rows,
            )
        self.max_wait_s = max_wait_s
        # Device→host readback cadence. 0 = harvest a report every pump
        # (max report fidelity). On topologies where readback RTT is the
        # bottleneck (tunneled/remote devices: ~110 ms/fetch measured,
        # vs ~6 ms to pack+dispatch a batch), a positive interval keeps
        # dispatch free-running and fetches only the newest report each
        # interval; skipped reports are counted, and nothing is lost on
        # device — CUSUM/z state evolves every batch regardless.
        self.harvest_interval_s = harvest_interval_s
        self._last_harvest = time.monotonic()
        # Optional background harvester: on topologies where readback
        # blocks for a full RTT, fetching on the pump thread steals a
        # fetch-worth of wall time from dispatch. The harvester thread
        # takes the newest in-flight report (skipping stale ones) and
        # does the blocking device_get off the dispatch path.
        self.harvest_async = harvest_async
        self._harvest_wake = threading.Event()
        self._harvest_idle = threading.Event()
        self._harvest_idle.set()
        self._harvest_stop = False
        self._harvest_flush = False  # drain() bypasses the cadence
        self._harvest_thread: threading.Thread | None = None
        if harvest_async:
            self._harvest_thread = threading.Thread(
                target=self._harvest_loop, name="report-harvester", daemon=True
            )
            self._harvest_thread.start()
        # Paired RTT probing: after each report fetch completes (lag
        # window closed), time one fresh 1-scalar device→host fetch.
        # The probe shares the harvest's tunnel conditions, so
        # lag[i]−rtt[i] isolates compute+transfer from topology RTT.
        # Off by default — it costs one extra round trip per harvest.
        self.rtt_probe = rtt_probe
        self._rtt_state = None
        self._rtt_bump = None
        # Adaptive batch growth (VERDICT r4 weak #1): when harvest can't
        # keep pace with dispatch (readback RTT > batch interval — the
        # 10× stress regime on tunneled topologies), reports get dropped
        # unfetched EXACTLY when the operator most wants them. The
        # controller widens the dispatch batch (powers of two up to
        # ``max_batch_growth``×) until dispatch rate ≤ harvest rate, so
        # every span still reaches device state AND ~every report is
        # fetched; when the skip pressure clears, the width decays back
        # for report granularity. Each ladder width is its own compiled
        # shape — ``warm_widths()`` precompiles them off the hot path.
        self.adaptive_batching = adaptive_batching
        self._width = batch_size
        # Round the growth cap UP to a power of two: the controller
        # moves in pow2 steps, so a non-pow2 cap would clamp the width
        # off the precompiled ladder (an unwarmed shape = a compile
        # mid-incident).
        self._max_width = batch_size * _pow2_ceil(max(int(max_batch_growth), 1))
        self._adapt_lock = threading.Lock()
        self._adapt_events = 0
        self._adapt_skips = 0
        self._adapt_clean = 0
        # Decay hysteresis: each decay that promptly re-escalates (the
        # operating point sits ON the boundary) doubles the clean
        # windows required before the next decay — damping oscillation
        # between a clean width and a skipping one.
        self._adapt_clean_needed = 2
        self._last_decay = 0.0
        self._last_dispatch = time.monotonic()
        self.stats = PipelineStats()
        # Pending work is columnar ((SpanColumns, enqueue_clock) chunks
        # + a total row count): both the per-record path and the native
        # decoder land here, and batch assembly is array slicing, not
        # object pops. The enqueue clock makes the lag metric honest
        # under the adaptive accumulate-hold — lag is measured from the
        # OLDEST row's arrival, so pre-dispatch queue time counts.
        # The lock covers queue+counter as a unit — producers are
        # receiver/consumer threads, the consumer is the pump thread,
        # and the row counter plus multi-chunk batch assembly are
        # read-modify-write sequences a bare deque can't make atomic.
        self._pending: deque = deque()
        self._pending_rows = 0
        self._pending_lock = threading.Lock()
        # Bounded admission (queue_max_rows > 0): the pending queue is
        # row-budgeted — the memory_limiter analogue for THIS process.
        # Above the budget the overflow shed drops the OLDEST OK-lane
        # rows (freshness beats completeness for telemetry, the
        # reference sending_queue's discipline) and NEVER error-lane
        # rows (SHED_LANES). Watermark hysteresis drives the saturation
        # signal the receivers propagate as 429/RESOURCE_EXHAUSTED:
        # saturated at >= high, admitting again only at <= low — so a
        # producer retrying on Retry-After doesn't flap the gate.
        if queue_max_rows:
            if not 0.0 < low_watermark < high_watermark <= 1.0:
                raise ValueError(
                    "watermarks must satisfy 0 < low < high <= 1 "
                    f"(got low={low_watermark}, high={high_watermark})"
                )
            if queue_max_rows < batch_size:
                raise ValueError(
                    f"queue_max_rows={queue_max_rows} below one batch "
                    f"({batch_size}): the pipeline could never dispatch"
                )
        self.queue_max_rows = int(queue_max_rows)
        self._high_rows = int(queue_max_rows * high_watermark)
        self._low_rows = int(queue_max_rows * low_watermark)
        self.brownout_hold_s = brownout_hold_s
        self.brownout_max_level = int(brownout_max_level)
        self.retry_after_s = retry_after_s
        self._saturated = False
        self._brownout_level = 0
        self._sat_since = 0.0
        self._unsat_since = time.monotonic()
        self._level_changed_at = 0.0
        # Guards the watermark/ladder read-modify-writes: updates come
        # from every receiver thread AND the pump; an unguarded race
        # could double-step the ladder inside one hold window.
        self._admission_lock = threading.Lock()
        # (t_batch, dispatch_clock, report, cols) — cols is the host
        # SpanColumns of the dispatched batch, kept for flag-time
        # exemplar capture (bounded: the deque holds ≤3 entries).
        self._inflight: deque = deque()
        self._inflight_lock = threading.Lock()
        # Serializes detector-state advancement: observe_packed is a
        # read-modify-write on detector.state, and warm_widths() may run
        # on a background thread beside the pump thread.
        self._dispatch_lock = threading.Lock()
        self._last_t: float | None = None
        # Query-plane capture (runtime.query): bounded per-service rings
        # of (a) exemplar trace ids taken AT FLAG TIME from the flagged
        # batch's trace-id column — every anomaly links to a concrete
        # Jaeger trace — and (b) recent attribute-CRC candidates, the
        # host-side candidate set a CMS top-k query needs (a CMS can
        # answer "how often?" but never enumerate its keys). Everything
        # here is JSON-able (query_meta) so it rides the replication
        # meta block and a read replica answers the same queries from
        # the same data. Guarded by its own lock: writers are the pump
        # thread (candidates) and the harvester (exemplars), readers
        # the replication/query snapshot threads.
        # Per-tenant sketch-namespace quota (the fleet tier's
        # noisy-tenant isolation; knob registry: utils.config
        # FLEET_KNOBS): one token bucket per tenant — capacity = one
        # second's quota, refill = quota rows/s — consulted in
        # submit_columns AHEAD of the global row budget, so a tenant
        # over quota sheds its OWN OK-lane rows (error lane always
        # passes, SHED_LANES discipline) while every other tenant's
        # admission, brownout state and TTD are untouched. tenant_of
        # maps a service NAME to its tenant (the ANOMALY_FLEET_TENANTS
        # map); quota 0 = the path costs one comparison.
        self._tenant_of = tenant_of
        self.tenant_quota_rows_s = float(tenant_quota_rows_s)
        self._tenant_buckets: dict[str, tuple[float, float]] = {}
        # Key lifecycle plane (runtime/keyspace.py; knob registry:
        # utils.config.KEYSPACE_KNOBS). The pipeline owns the per-key
        # last-seen clock (one vectorized scatter per admitted flush),
        # the keyspace degradation ladder (same two-edge hysteresis as
        # the brownout ladder, but clocked by the keyspace watchdog's
        # tick, not the queue depth), and the NEW-key admission gate
        # the tensorizer consults on a genuine intern miss. Eviction
        # itself — folding idle rows into history and retiring ids —
        # lives in keyspace.KeyspaceManager, which writes detector
        # state only under _dispatch_lock.
        self.keyspace_enable = bool(keyspace_enable)
        self.keyspace_high_watermark = float(keyspace_high_watermark)
        self.keyspace_low_watermark = float(keyspace_low_watermark)
        self.keyspace_hold_s = float(keyspace_hold_s)
        self.keyspace_newkey_rate = float(keyspace_newkey_rate)
        self.keyspace_retry_after_s = float(keyspace_retry_after_s)
        self._keyspace_level = 0
        self._ks_saturated = False
        self._ks_sat_since = 0.0
        self._ks_unsat_since = time.monotonic()
        self._ks_level_changed_at = 0.0
        self._ks_newkey_buckets: dict[str, tuple[float, float]] = {}
        self._last_seen = np.zeros(
            detector.config.num_services, np.float64
        )
        if self.keyspace_enable:
            self.tensorizer.new_key_gate = self.keyspace_newkey_gate
        self._exemplar_ring = int(exemplar_ring)
        self._hh_cand_max = int(hh_candidates)
        self._query_lock = threading.Lock()
        self._exemplars: dict[int, deque] = {}
        self._hh_cands: dict[int, deque] = {}
        self._anomaly_ring: deque = deque(maxlen=64)
        self.exemplars_captured = 0
        # Verdict provenance (runtime.provenance; knob registry:
        # utils.config.PROVENANCE_KNOBS): the evidence engine builds
        # one bundle per flagged service at flag time; the bundle RING
        # lives here beside the anomaly ring so it rides query_meta
        # replication and the read replica's /query/explain answers
        # bit-identically. The built counter follows the
        # exemplars_captured delta discipline (never restored).
        self._provenance = provenance
        self._explain_ring: deque = deque(maxlen=max(int(explain_ring), 1))
        self.explanations_built = 0
        # Bundles awaiting OTLP log export, drained by the daemon's
        # export tick (bounded, drop-oldest — freshness over
        # completeness, the exporter queue's own discipline).
        self._explain_export: deque = deque(maxlen=max(int(explain_ring), 1))

    # -- ingestion -----------------------------------------------------

    def submit(self, records: Iterable[SpanRecord]) -> None:
        """Queue records; called from receiver/consumer threads."""
        records = list(records)
        if records:
            self.submit_columns(self.tensorizer.columns_from_records(records))

    def submit_columnar(self, columnar) -> None:
        """Queue a native-decoder batch (runtime.native.ColumnarSpans)."""
        self.submit_columns(self.tensorizer.columns_from_columnar(columnar))

    def submit_columns(self, cols: SpanColumns) -> None:
        if not cols.rows:
            return
        # Per-key liveness clock: a key is "seen" when rows ARRIVE for
        # it, before any shed/brownout thins them — idleness means the
        # world stopped sending, not that we dropped what it sent.
        # Duplicate ids in one scatter are benign (same timestamp) and
        # cross-thread races are too (both write "now"). Ids past the
        # table (synthetic columns that bypassed the tensorizer) clip
        # to the overflow slot, exactly like the device scatter does.
        self._last_seen[
            np.minimum(cols.svc, self._last_seen.shape[0] - 1)
        ] = time.monotonic()
        if self.tenant_quota_rows_s > 0:
            cols = self._tenant_quota_sample(cols)
            if not cols.rows:
                return
        level = self._brownout_level
        if level:
            cols = self._brownout_sample(cols, level)
            if not cols.rows:
                return
        with self._pending_lock:
            self._pending.append((cols, time.monotonic()))
            self._pending_rows += cols.rows
            if self.queue_max_rows and self._pending_rows > self.queue_max_rows:
                self._shed_locked()
            rows = self._pending_rows
        self._admission_update(rows)

    # -- bounded admission / brownout ----------------------------------

    def _tenant_quota_sample(self, cols: SpanColumns) -> SpanColumns:
        """Per-tenant admission quota (token bucket, 1 s burst).

        Runs AHEAD of the global row budget and the brownout ladder so
        one noisy tenant is clipped to its quota BEFORE it can push
        the shared queue toward saturation — the isolation is per
        tenant by construction (one bucket each), so a quiet tenant's
        rows are admitted untouched whatever its neighbors do. Error-
        lane rows always pass (the SHED_LANES discipline: incident
        evidence is never droppable telemetry). Shed rows land in
        ``stats.shed_rows_tenant[tenant]``, exported as
        anomaly_shed_rows_total{tenant=}.
        """
        quota = self.tenant_quota_rows_s
        now = time.monotonic()
        names = self.tensorizer.service_names
        svc = cols.svc
        ok = ~(cols.is_error > 0.0)
        # Group the batch's service ids by tenant (a tenant may own
        # several services; the bucket is per TENANT).
        by_tenant: dict[str, list[int]] = {}
        for sid in np.unique(svc):
            sid = int(sid)
            name = names[sid] if sid < len(names) else f"svc-{sid}"
            tenant = (
                self._tenant_of(name)
                if self._tenant_of is not None else "default"
            )
            by_tenant.setdefault(tenant, []).append(sid)
        drop = np.zeros(cols.rows, dtype=bool)
        with self._admission_lock:  # buckets are shared across
            # receiver threads; refill+consume is read-modify-write
            for tenant, sids in by_tenant.items():
                tokens, t_last = self._tenant_buckets.get(
                    tenant, (quota, now)
                )
                tokens = min(tokens + (now - t_last) * quota, quota)
                mask = np.isin(svc, np.asarray(sids, svc.dtype)) & ok
                n = int(mask.sum())
                allow = min(n, int(tokens))
                if allow < n:
                    # Keep the OLDEST rows within the quota (rows are
                    # enqueue-ordered): head-of-line fairness, and a
                    # deterministic choice two replicas agree on.
                    rank = np.cumsum(mask)
                    drop |= mask & (rank > allow)
                    shed = self.stats.shed_rows_tenant
                    shed[tenant] = shed.get(tenant, 0) + (n - allow)
                self._tenant_buckets[tenant] = (tokens - allow, now)
        if not drop.any():
            return cols
        return cols.compress(~drop)

    def _brownout_sample(self, cols: SpanColumns, level: int) -> SpanColumns:
        """Deterministic head sampling: keep 1/2^level of OK-lane rows.

        The keep decision hashes the trace key (splitmix64) rather than
        using its raw low bits — Kafka-order keys are ASCII order ids
        whose low byte is constant, and a sampler biased by encoding
        would black-hole a whole source instead of thinning it. Hashing
        makes the decision uniform AND deterministic: the same trace is
        kept or dropped at every level crossing (head sampling, so a
        kept trace stays internally consistent), and two replicas
        sampling the same stream agree. Error-lane rows always pass —
        brownout degrades the OK lane only.
        """
        mask = np.uint64((1 << level) - 1)
        keep = (cols.is_error > 0.0) | (
            (splitmix64_np(cols.trace_key) & mask) == np.uint64(0)
        )
        dropped = int(cols.rows - keep.sum())
        if dropped == 0:
            return cols
        with self._admission_lock:  # += races across receiver threads
            self.stats.brownout_rows += dropped
        return cols.compress(keep)

    def _shed_locked(self) -> None:
        """Drop the oldest OK-lane rows until the queue fits its budget.

        Called under ``_pending_lock``. Error-lane rows are NEVER shed
        (SHED_LANES): a chunk keeps its error rows (and its original
        enqueue clock — partially-shed chunks still report honest lag)
        even when every OK row around them is dropped. If error rows
        alone exceed the budget (a pathological all-error flood) the
        queue holds them anyway — the bound is a promise about
        droppable telemetry, not a license to lose incident evidence —
        and the depth gauge makes the excursion visible.
        """
        need = self._pending_rows - self.queue_max_rows
        idx = 0
        shed = 0
        while need > 0 and idx < len(self._pending):
            cols, t_enq = self._pending[idx]
            err = cols.is_error > 0.0
            n_ok = int(cols.rows - err.sum())
            if n_ok == 0:
                idx += 1  # pure error-lane chunk: untouchable
                continue
            if n_ok <= need:
                kept = cols.compress(err)
                dropped = n_ok
            else:
                # Drop only the oldest `need` OK rows of this chunk
                # (rows are enqueue-ordered within a chunk).
                ok_rank = np.cumsum(~err)
                kept = cols.compress(err | (ok_rank > need))
                dropped = need
            if kept.rows:
                self._pending[idx] = (kept, t_enq)
                idx += 1
            else:
                del self._pending[idx]
            self._pending_rows -= dropped
            need -= dropped
            shed += dropped
        if shed:
            self.stats.shed_rows["ok"] += shed

    def _admission_update(self, rows: int, now: float | None = None) -> None:
        """Watermark hysteresis + brownout ladder (host wall clock).

        Saturation flips at the high watermark and clears only at the
        low one. The ladder moves one level per ``brownout_hold_s`` of
        SUSTAINED saturation (transient spikes never engage it) and
        relaxes one level per hold of sustained clearance — the same
        hysteresis in both directions, so an operating point near the
        boundary oscillates the gauge, not the sampling rate.
        """
        if not self.queue_max_rows:
            return
        now = time.monotonic() if now is None else now
        with self._admission_lock:
            if not self._saturated:
                if rows >= self._high_rows:
                    self._saturated = True
                    self._sat_since = now
                    self.stats.saturation_events += 1
            elif rows <= self._low_rows:
                self._saturated = False
                self._unsat_since = now
            if self._saturated:
                if (
                    self._brownout_level < self.brownout_max_level
                    and now - max(self._sat_since, self._level_changed_at)
                    >= self.brownout_hold_s
                ):
                    self._brownout_level += 1
                    self._level_changed_at = now
            elif self._brownout_level and (
                now - max(self._unsat_since, self._level_changed_at)
                >= self.brownout_hold_s
            ):
                self._brownout_level -= 1
                self._level_changed_at = now

    @property
    def saturated(self) -> bool:
        """True between the high-watermark crossing and the low one —
        what the OTLP receivers consult before admitting a request."""
        return self._saturated

    @property
    def brownout_level(self) -> int:
        """Current head-sampling level (0 = keep everything; level L
        keeps 1/2^L of OK-lane rows)."""
        return self._brownout_level

    def keyspace_update(
        self, fill: float, rss_over: bool = False,
        now: float | None = None,
    ) -> int:
        """Keyspace pressure hysteresis + degradation ladder.

        Driven by the keyspace watchdog's tick (runtime/keyspace.py)
        with the live-row fill fraction and the RSS-budget verdict.
        Pressure flips at the high watermark (or any RSS breach) and
        clears only at the low one; the ladder moves one rung per
        ``keyspace_hold_s`` of SUSTAINED state in either direction —
        identical discipline to :meth:`_admission_update`, so one fill
        spike never staircases straight to the 429 rung. Returns the
        post-update level.
        """
        now = time.monotonic() if now is None else now
        with self._admission_lock:
            if not self._ks_saturated:
                if fill >= self.keyspace_high_watermark or rss_over:
                    self._ks_saturated = True
                    self._ks_sat_since = now
                    self.stats.keyspace_pressure_events += 1
            elif fill <= self.keyspace_low_watermark and not rss_over:
                self._ks_saturated = False
                self._ks_unsat_since = now
            if self._ks_saturated:
                if (
                    self._keyspace_level < KEYSPACE_MAX_LEVEL
                    and now - max(
                        self._ks_sat_since, self._ks_level_changed_at
                    ) >= self.keyspace_hold_s
                ):
                    self._keyspace_level += 1
                    self._ks_level_changed_at = now
            elif self._keyspace_level and (
                now - max(
                    self._ks_unsat_since, self._ks_level_changed_at
                ) >= self.keyspace_hold_s
            ):
                self._keyspace_level -= 1
                self._ks_level_changed_at = now
            return self._keyspace_level

    @property
    def keyspace_level(self) -> int:
        """Current keyspace ladder rung (0 = normal; see the
        KEYSPACE_LEVEL_* constants)."""
        return self._keyspace_level

    def keyspace_newkey_gate(self, name: str) -> bool:
        """NEW-key admission gate, consulted by the tensorizer under
        its intern lock on a genuine miss (existing keys never reach
        it). Below the throttle rung every new key gets a slot; at the
        throttle rung each TENANT spends a token bucket refilled at
        ``keyspace_newkey_rate`` new keys/s (a UUID-spraying tenant
        exhausts its own bucket while a quiet tenant's first sighting
        still interns); at the collapse rung and above every new key
        folds to overflow. Refusals are counted per tenant — the key's
        ROWS are still admitted, they just share the overflow bucket.
        """
        level = self._keyspace_level
        if level < KEYSPACE_LEVEL_THROTTLE:
            return True
        tenant = (
            self._tenant_of(name)
            if self._tenant_of is not None else "default"
        )
        if level >= KEYSPACE_LEVEL_COLLAPSE:
            with self._admission_lock:
                d = self.stats.overflow_keys_tenant
                d[tenant] = d.get(tenant, 0) + 1
            return False
        rate = self.keyspace_newkey_rate
        if rate <= 0:
            return True
        now = time.monotonic()
        with self._admission_lock:
            tokens, t_last = self._ks_newkey_buckets.get(
                tenant, (rate, now)
            )
            tokens = min(tokens + (now - t_last) * rate, rate)
            if tokens >= 1.0:
                self._ks_newkey_buckets[tenant] = (tokens - 1.0, now)
                return True
            self._ks_newkey_buckets[tenant] = (tokens, now)
            d = self.stats.newkey_throttled_tenant
            d[tenant] = d.get(tenant, 0) + 1
        return False

    def admission_retry_after(self) -> float | None:
        """None while admitting; a Retry-After hint (seconds) while
        saturated — the receivers' single admission-control question.
        The keyspace ladder's shed rung answers here too, so ALL
        ingest doors (Python OTLP, gRPC, native front door) return
        429/RESOURCE_EXHAUSTED under a sustained cardinality bomb
        without any door-side change."""
        if self._saturated:
            return self.retry_after_s
        if self._keyspace_level >= KEYSPACE_LEVEL_SHED:
            return self.keyspace_retry_after_s
        return None

    def pending_rows(self) -> int:
        with self._pending_lock:
            return self._pending_rows

    def pump(self, t_now: float | None = None) -> None:
        """Form at most one batch and dispatch it (non-blocking).

        Callers drive either wall time or a virtual clock; when ``t_now``
        is omitted, reuse the caller's last timebase rather than mixing
        ``time.monotonic()`` into a virtual-time stream (which would
        poison dt/window rotation for the rest of the run).
        """
        if t_now is None:
            t_now = self._last_t if self._last_t is not None else time.monotonic()
        self._last_t = t_now
        if not self.flags.evaluate(FLAG_ENABLED, True):
            with self._pending_lock:
                self.stats.dropped_disabled += self._pending_rows
                self._pending.clear()
                self._pending_rows = 0
            if self._spine is not None:
                # Staged-but-undispatched batches are pending work too:
                # the off switch drops them with the queue.
                self.stats.dropped_disabled += self._spine.discard_pending()
            self._admission_update(0)
            return
        # Assemble up to one batch of rows from the columnar queue;
        # an oversized head chunk is split and its tail re-queued.
        width = self._width if self.adaptive_batching else self.tensorizer.batch_size
        with self._pending_lock:
            rows_avail = self._pending_rows
        # The consumer side of the admission loop: draining below the
        # low watermark reopens the gate, and an idle/paced pump is
        # what ticks the brownout ladder's relaxation clock.
        self._admission_update(rows_avail)
        # The accumulate-hold scales with the growth factor (at 8× it
        # is 8×max_wait_s — exactly the regime where a report every
        # ~0.4 s beats skipping half of them) and engages ONLY once the
        # controller has escalated: at base width a hold would spend up
        # to max_wait_s of the <100 ms lag budget for nothing — the
        # batch that would have dispatched now is the same batch either
        # way, just later.
        hold_s = self.max_wait_s * (width / self.tensorizer.batch_size)
        if (
            self.adaptive_batching
            and width > self.tensorizer.batch_size  # escalated regime only
            and not self._harvest_flush  # drain() must always dispatch
            and 0 < rows_avail < width
            and time.monotonic() - self._last_dispatch < hold_s
        ):
            # Widened regime: hold sub-width dispatches briefly so the
            # batch fills — max_wait_s bounds the added latency, and a
            # quiet stream still flushes on the next pump past it.
            self._maybe_sync_harvest(keep=0)
            return
        with self._pending_lock:
            budget = width
            parts: list[SpanColumns] = []
            t_oldest = None
            while self._pending and budget:
                head, t_enq = self._pending.popleft()
                if t_oldest is None:
                    t_oldest = t_enq  # FIFO: the head is the oldest
                if head.rows > budget:
                    parts.append(head.slice(0, budget))
                    # The requeued tail keeps its original enqueue time.
                    self._pending.appendleft(
                        (head.slice(budget, head.rows), t_enq)
                    )
                    budget = 0
                else:
                    parts.append(head)
                    budget -= head.rows
            self._pending_rows -= sum(p.rows for p in parts)
            rows_after = self._pending_rows
        # Re-check with the batch removed: a drain that just crossed
        # the low watermark must reopen the gate THIS pump, not next.
        self._admission_update(rows_after)
        if not parts:
            # Nothing new to assemble — but a staged batch from an
            # earlier pump may be ready now (its put rode behind the
            # previous step): dispatch it before the idle harvest.
            if self._spine is not None and self._pump_spine():
                pass
            else:
                # Nothing to dispatch — but an idle pump must still
                # fetch due in-flight reports (outside the pending
                # lock: the fetch blocks for an RTT and submitters
                # must not): a report that only ever harvests on the
                # NEXT batch's pump carries one extra batch interval
                # of detection lag.
                self._maybe_sync_harvest(keep=0)
                return
        else:
            cols = SpanColumns.concat(parts)
            self._capture_candidates(cols)
            # Batch-lifecycle sampling gate: one splitmix64 + compare
            # per batch; None rides the whole path for unsampled ones.
            trace = (
                self._selftrace.begin()
                if self._selftrace is not None else None
            )
            if self._spine is not None:
                # Spine path: hand the columns to the stager (pack +
                # async device put off the pump thread) and dispatch
                # whatever staged batch is ready — typically the one
                # whose transfer just overlapped the in-flight step.
                # Ring bound first: past `depth` undispatched batches
                # the pump wait-dispatches the head — the ring IS the
                # backpressure, and the pump is the only consumer.
                while self._spine.pending() >= self._spine.depth:
                    self._pump_spine(force_wait=True)
                self._spine.stage(cols, width, t_now, t_oldest, trace=trace)
                self._pump_spine()
            else:
                batch = self.tensorizer.pack_columns(cols, width=width)
                self._dispatch_batch(
                    batch, t_now, t_oldest, cols, batch.num_valid,
                    trace=trace,
                )
        if self.harvest_async:
            self._harvest_wake.set()
        else:
            # Adaptive overlap: with more batches queued, leave the
            # newest dispatch in flight (device compute overlaps the
            # fetch — the throughput regime); with the queue drained,
            # fetch everything now (the low-rate regime, where a kept
            # report would wait a whole batch interval).
            with self._pending_lock:
                keep = 1 if self._pending else 0
            self._maybe_sync_harvest(keep=keep)

    def _dispatch_batch(
        self, batch, t_now, t_oldest, cols, n_valid: int, trace=None
    ) -> None:
        """Dispatch ONE packed batch (host- or device-resident) into
        the donated step — the single place detector state advances
        from the pump path, always under ``_dispatch_lock``."""
        self._last_dispatch = time.monotonic()
        if self.history_capture is not None and cols is not None:
            self.history_capture(cols, t_now)
        t0 = time.perf_counter()
        # Packed dispatch: the report comes back as ONE device vector so
        # harvest is a single transfer instead of one per report leaf.
        with self._dispatch_lock:
            report = self.detector.observe_packed(batch, t_now)  # async dispatch
        try:
            # Start the device→host copy now; by harvest time the bytes
            # are (mostly) on host and device_get degenerates to a wait.
            report.copy_to_host_async()
        except AttributeError:  # non-jax.Array stand-ins in tests
            pass
        dispatch_dt = time.perf_counter() - t0
        if self.phase_observe is not None:
            self.phase_observe(PHASE_DISPATCH, dispatch_dt)
        if trace is not None:
            trace.span(SPAN_DISPATCH, dispatch_dt)
            trace.attrs.append(("batch.rows", str(int(n_valid))))
        self.stats.batches += 1
        self.stats.spans += n_valid
        with self._inflight_lock:
            # Lag clock = the oldest row's enqueue time, not dispatch
            # time: under the adaptive accumulate-hold rows can wait up
            # to hold_s before dispatch, and that wait IS detection lag.
            # The host-side columns ride along so the harvester can
            # capture exemplar trace ids AT FLAG TIME from the exact
            # batch that flagged (bounded: ≤3 batches in flight). The
            # batch's sampled self-trace (or None) rides too — the
            # harvester finishes it after the flag decision.
            self._inflight.append((t_now, t_oldest, report, cols, trace))
            # Bound the in-flight window: stale reports are dropped
            # unfetched (their batches already updated device state) so
            # readback RTT never throttles dispatch.
            while len(self._inflight) > 2:
                self._inflight.popleft()
                self.stats.reports_skipped += 1
                self._note_outcome(skipped=True)

    def _pump_spine(self, force_wait: bool = False) -> bool:
        """Dispatch the oldest staged batch if available (spine path).

        Overlap discipline: with a step already in flight the pump
        takes only a batch whose put has COMPLETED (a not-ready batch
        dispatches next tick — its transfer keeps riding behind the
        running step, which is the whole point); with the device idle,
        under drain, at the ring bound, or with overlap disabled it
        waits — the low-rate regime must not defer a lone batch a
        whole pump interval."""
        with self._inflight_lock:
            idle = not self._inflight
        must_wait = (
            force_wait
            or not self._spine.overlap
            or self._harvest_flush
            or idle
        )
        staged = self._spine.take(wait=must_wait)
        if staged is None:
            return False
        if self.phase_observe is not None:
            self.phase_observe(PHASE_STAGE, staged.stage_dur)
            self.phase_observe(PHASE_PUT_WAIT, staged.wait_s)
        if staged.trace is not None:
            staged.trace.span(SPAN_STAGE, staged.stage_dur)
            staged.trace.span(
                SPAN_PUT, staged.wait_s,
                attrs=(("overlap.hit", str(int(staged.wait_s == 0.0))),),
            )
        # n_valid from the host row count: the device batch's own
        # valid.sum() would force a device sync on the dispatch path.
        self._dispatch_batch(
            staged.batch,
            staged.t_now,
            staged.t_oldest,
            staged.cols,
            staged.cols.rows,
            trace=staged.trace,
        )
        return True

    def _maybe_sync_harvest(self, keep: int) -> None:
        """One due-cadence synchronous harvest (no-op in async mode)."""
        if self.harvest_async:
            return
        if time.monotonic() - self._last_harvest >= self.harvest_interval_s:
            if self._harvest_one(keep=keep):
                self._last_harvest = time.monotonic()

    def drain(self) -> None:
        """Harvest all in-flight reports (end of stream / shutdown)."""
        # Raise the flush flag BEFORE pumping the backlog: the async
        # harvester must not cadence-skip reports dispatched during the
        # drain itself.
        self._harvest_flush = True
        try:
            while self._pending or (
                self._spine is not None and self._spine.pending()
            ):
                self.pump()
            if self.harvest_async:
                self._drain_async()
            else:
                while self._harvest_one(keep=0):
                    pass
        finally:
            self._harvest_flush = False

    def _drain_async(self) -> None:
        while True:
            with self._inflight_lock:
                empty = not self._inflight
            if empty and self._harvest_idle.is_set():
                break
            if (
                self._harvest_thread is None
                or not self._harvest_thread.is_alive()
            ):
                # Dead harvester (should be impossible — the loop
                # swallows processing errors — but never spin against
                # it): fall back to synchronous harvest.
                while self._harvest_one(keep=0):
                    pass
                break
            self._harvest_wake.set()
            time.sleep(0.005)

    def close(self) -> None:
        """Stop the background harvester (if any) after a final drain."""
        self.drain()
        if self._spine is not None:
            self._spine.close()
        if self._harvest_thread is not None:
            self._harvest_stop = True
            self._harvest_wake.set()
            self._harvest_thread.join(timeout=5.0)
            self._harvest_thread = None

    def spine_stats(self) -> dict | None:
        """The spine's put/overlap counters (None when the spine is
        off) — the daemon's anomaly_spine_* export reads this."""
        return None if self._spine is None else self._spine.stats()

    # -- supervision hooks --------------------------------------------

    def harvester_alive(self) -> bool:
        """True while the async harvester runs (or isn't configured).
        The supervisor's probe: a dead harvester means in-flight
        reports pile up to the skip cap and nothing reaches
        ``on_report`` — silent from the outside."""
        if not self.harvest_async:
            return True
        return self._harvest_thread is not None and self._harvest_thread.is_alive()

    def restart_harvester(self) -> None:
        """Respawn a dead async harvester (the supervisor's restart).
        Safe to call when it's healthy (no-op) or after close()."""
        if not self.harvest_async or self.harvester_alive():
            return
        self._harvest_stop = False
        self._harvest_idle.set()
        self._harvest_thread = threading.Thread(
            target=self._harvest_loop, name="report-harvester", daemon=True
        )
        self._harvest_thread.start()

    # -- adaptive width controller ------------------------------------

    @property
    def batch_width(self) -> int:
        """Current dispatch width (== batch_size unless adaptive grew it)."""
        return self._width if self.adaptive_batching else self.tensorizer.batch_size

    def warm_widths(self) -> None:
        """Precompile every ladder width (adaptive mode only).

        A width change is a new compiled shape; on TPU that is tens of
        seconds the first time — paid here, off the streaming path, not
        mid-incident when the controller escalates. The warm steps run
        on a COPY of the detector state through the same jitted
        callable (same compile cache as live dispatch), so live pumping
        is never blocked behind a compile and neither the state nor the
        window clock is touched."""
        if not self.adaptive_batching:
            return
        import jax.numpy as jnp

        width = self.tensorizer.batch_size
        while width <= self._max_width:
            # All-invalid batch: every lane hits the kernels' monoid
            # identities — and the step consumes a throwaway state copy
            # (the jit donates its argument; donating the live state
            # would invalidate it under the pump thread).
            cols = SpanColumns(
                svc=np.zeros(0, np.int32),
                lat_us=np.zeros(0, np.float32),
                is_error=np.zeros(0, np.float32),
                trace_key=np.zeros(0, np.uint64),
                attr_crc=np.zeros(0, np.uint64),
            )
            batch = self.tensorizer.pack_columns(cols, width=width)
            # Snapshot under the dispatch lock: live dispatch DONATES
            # the state buffers (the jit deletes them Python-side the
            # moment it dispatches), so an unlocked tree_map(copy)
            # could read a just-deleted array mid-snapshot. The lock is
            # held only for the (async-dispatched) copies — never for
            # the compile below.
            with self._dispatch_lock:
                state_copy = jax.tree_util.tree_map(
                    jnp.copy, self.detector.state
                )
            # Args mirror AnomalyDetector._args dtype-for-dtype (same
            # compile-cache key) but bypass the clock tick — warming
            # must not advance window rotation.
            _, report = self.detector._step_packed(
                state_copy,
                jnp.asarray(batch.svc),
                jnp.asarray(batch.lat_us),
                jnp.asarray(batch.is_error),
                jnp.asarray(batch.trace_hi),
                jnp.asarray(batch.trace_lo),
                jnp.asarray(batch.attr_hi),
                jnp.asarray(batch.attr_lo),
                jnp.asarray(batch.valid),
                jnp.float32(0.0),
                jnp.asarray((False,) * len(self.detector.config.windows_s)),
            )
            jax.device_get(report)  # force the compile + execute
            width *= 2

    def _note_outcome(self, skipped: bool) -> None:
        """Feed the width controller one report outcome.

        Escalation is jump-to-target: over a 4-outcome window,
        dispatched/harvested ≈ dispatch-rate/harvest-rate, and that
        ratio IS the width factor that balances the two — so one window
        at 3-skips-to-1 jumps straight to 4×, instead of doubling three
        times while reports keep dropping. Decay: two consecutive
        all-clean 8-outcome windows halve the width, returning report
        granularity once the pressure clears. Counters are
        lock-guarded — outcomes arrive from the pump thread AND the
        harvester (lock order: _inflight_lock → _adapt_lock, never the
        reverse)."""
        if not self.adaptive_batching:
            return
        with self._adapt_lock:
            self._adapt_events += 1
            if skipped:
                self._adapt_skips += 1
            window = 4 if self._adapt_skips else 8
            if self._adapt_events < window:
                return
            skips = self._adapt_skips
            events = self._adapt_events
            self._adapt_events = 0
            self._adapt_skips = 0
            if (
                skips == 0
                and self._adapt_clean_needed > 2
                and time.monotonic() - self._last_decay >= 10.0
            ):
                # The last decay survived its 10 s re-escalation window
                # (or pressure cleared long ago): earn the hysteresis
                # back down toward the initial requirement, so a
                # transient oscillation doesn't leave a long-running
                # daemon permanently width-elevated behind a 32-window
                # decay price.
                self._adapt_clean_needed = max(
                    self._adapt_clean_needed // 2, 2
                )
            if skips > events // 4:
                self._adapt_clean = 0
                if time.monotonic() - self._last_decay < 10.0:
                    # The decay we just made re-skipped: the clean
                    # width is the one ABOVE the boundary — make the
                    # next decay much harder to earn.
                    self._adapt_clean_needed = min(
                        self._adapt_clean_needed * 2, 32
                    )
                harvested = max(events - skips, 1)
                factor = max(2, -(-events // harvested))  # ceil div
                # Pow2 rounding keeps the width on the precompiled
                # ladder (same rule as the constructor cap).
                self._width = min(
                    self._width * _pow2_ceil(factor), self._max_width
                )
            elif skips == 0 and self._width > self.tensorizer.batch_size:
                self._adapt_clean += 1
                if self._adapt_clean >= self._adapt_clean_needed:
                    # Floor at base: the width must never leave the
                    # [batch_size, max] ladder.
                    self._width = max(
                        self._width // 2, self.tensorizer.batch_size
                    )
                    self._adapt_clean = 0
                    self._last_decay = time.monotonic()
            else:
                self._adapt_clean = 0

    # -- report handling ----------------------------------------------

    def _harvest_loop(self) -> None:
        """Background harvester: blocking readback off the pump thread.

        On the cadence path, takes the NEWEST in-flight report (older
        ones are superseded — device state already includes them; CUSUM
        keeps persistent anomalies sticky across skipped readbacks).
        Under drain() (``_harvest_flush``), processes every remaining
        report oldest-first — end-of-stream must not lose finals."""
        while True:
            self._harvest_wake.wait(timeout=0.05)
            self._harvest_wake.clear()
            # The interval knob composes with async mode: between due
            # times the harvester idles (stale reports keep being
            # dropped at append time), so a tunnel isn't saturated with
            # back-to-back readbacks the interval was set to avoid.
            # drain() bypasses the cadence via _harvest_flush; close()
            # via _harvest_stop.
            if (
                not self._harvest_stop
                and not self._harvest_flush
                and time.monotonic() - self._last_harvest < self.harvest_interval_s
            ):
                continue
            with self._inflight_lock:
                if not self._inflight:
                    if self._harvest_stop:
                        return
                    continue
                # Cadence path: an older report whose device→host copy
                # (started at dispatch, copy_to_host_async) has already
                # COMPLETED costs ~nothing to fetch — process it instead
                # of skipping. Only a genuinely-behind report (copy
                # still in flight; fetching it would block the fresher
                # one for an RTT) is dropped as superseded — device
                # state already includes it. The drain path must NOT
                # skip: end-of-stream harvests every remaining report
                # oldest-first, matching sync-mode drain semantics.
                if not self._harvest_flush:
                    while len(self._inflight) > 1:
                        is_ready = getattr(
                            self._inflight[0][2], "is_ready", None
                        )
                        try:
                            if is_ready is not None and is_ready():
                                break  # oldest is free to fetch
                        except Exception:  # noqa: BLE001 — treat as not ready
                            pass
                        self._inflight.popleft()
                        self.stats.reports_skipped += 1
                        self._note_outcome(skipped=True)
                item = self._inflight.popleft()
                self._harvest_idle.clear()
            self._last_harvest = time.monotonic()
            try:
                self._process_report(item)
            except Exception:  # noqa: BLE001 — a raising on_report must
                # not kill the harvester: the thread is the only
                # consumer of _inflight, and drain()/close() would spin
                # forever against a dead one.
                self.stats.harvest_errors += 1
            finally:
                self._harvest_idle.set()

    def _start_rtt_probe(self) -> dict:
        """Launch a 1-scalar device→host fetch CONCURRENT with the
        report fetch it pairs with.

        Concurrency is the point: both round trips ride the tunnel at
        the same moment, so congestion/jitter hits both and cancels in
        lag−rtt (measured: sequential probes leave ~40 ms of unpaired
        jitter in the net p99; concurrent probes cut it to <5 ms even
        when the tunnel itself swings 100→400 ms). Each probe bumps a
        device counter so the fetched array is fresh — jax.Array caches
        its host copy, so re-fetching the same array would time a dict
        lookup, not the wire.
        """
        import jax.numpy as jnp

        if self._rtt_bump is None:
            self._rtt_bump = jax.jit(lambda s: s + 1)
            self._rtt_state = jnp.zeros((), jnp.int32)
        self._rtt_state = self._rtt_bump(self._rtt_state)
        arr = self._rtt_state
        res: dict = {}

        def run():
            t0 = time.perf_counter()
            _ = int(np.asarray(arr))
            res["rtt"] = (time.perf_counter() - t0) * 1e3

        th = threading.Thread(target=run, name="rtt-probe", daemon=True)
        th.start()
        return {"thread": th, "res": res}

    def _harvest_one(self, keep: int = 1) -> bool:
        """Synchronous harvest of the oldest in-flight report beyond
        ``keep`` (keep=1 leaves one dispatch in flight for overlap)."""
        with self._inflight_lock:
            if len(self._inflight) <= keep:
                return False
            item = self._inflight.popleft()
        self._process_report(item)
        return True

    # -- query-plane capture ------------------------------------------

    def _capture_candidates(self, cols: SpanColumns) -> None:
        """Remember recent per-service attribute keys (pump thread).

        The CMS absorbs every span but can never list its keys; a
        top-k query therefore needs candidates. Heavy hitters are, by
        definition, frequent — any attr with real share appears in the
        recent stream, so a bounded ring of recently-seen distinct
        CRCs per service IS the candidate set (counts stay exact: they
        come from the full table at query time)."""
        if not self._hh_cand_max:
            return
        svcs = np.unique(cols.svc)
        # The O(services × rows) mask/unique pass runs lock-free:
        # query_meta() and exemplar capture contend on _query_lock
        # every refresh/snapshot, so only the ring mutation may hold
        # it — not per-batch numpy work.
        tails = []
        for s in svcs:
            vals = cols.attr_crc[cols.svc == s]
            # Distinct values in ARRIVAL order (np.unique sorts by
            # value — slicing that would keep the numerically
            # largest CRCs forever, not the recent ones): sort the
            # first-appearance indices back into stream order,
            # then keep the tail.
            _u, first = np.unique(vals, return_index=True)
            ordered = vals[np.sort(first)]
            tails.append(
                (int(s), [int(v) for v in ordered[-self._hh_cand_max:]])
            )
        with self._query_lock:
            for s, tail in tails:
                ring = self._hh_cands.get(s)
                if ring is None:
                    ring = self._hh_cands[s] = deque(
                        maxlen=self._hh_cand_max
                    )
                ring.extend(tail)

    def _provenance_snapshot(self) -> dict | None:
        """Flag-time device→host fetch of the baseline/sketch state the
        evidence bundles cite (EWMA means/vars, CUSUM accumulators, the
        live CMS/HLL banks). Harvester thread, under ``_dispatch_lock``
        — the donation-race contract: ``detector.state`` may be donated
        away mid-read otherwise. Flags are rare and the fetch is the
        same order of work as one replication snapshot; a failed fetch
        costs the bundle its state block, never the report path."""
        try:
            with self._dispatch_lock:
                state = self.detector.state
                return jax.device_get({
                    "lat_mean": state.lat_mean,
                    "lat_var": state.lat_var,
                    "err_mean": state.err_mean,
                    "rate_mean": state.rate_mean,
                    "rate_var": state.rate_var,
                    "card_mean": state.card_mean,
                    "card_var": state.card_var,
                    "cusum": state.cusum,
                    "cms_bank": state.cms_bank,
                    "hll_bank": state.hll_bank,
                    "span_total": state.span_total,
                    "step_idx": state.step_idx,
                })
        except Exception:  # noqa: BLE001 — evidence is advisory; the
            # report (and the anomaly event) must land regardless.
            return None

    def _capture_exemplars(
        self, t_batch, cols, report, flags_np, threshold,
        prov_state: dict | None = None, trace_id: str | None = None,
    ) -> list[str]:
        """At flag time: link each flagged service to concrete trace
        ids from the batch that flagged it (harvester thread).

        The exemplar is the first 8 bytes of the OTLP trace id (the
        tensorizer's ``trace_key``, little-endian) rendered as hex —
        exactly the prefix a Jaeger UI search matches on. A flag whose
        evidence is windowed (CUSUM/cardinality, no row of the service
        in THIS batch) still records the anomaly event; the ring keeps
        the service's most recent exemplars from earlier batches.

        ``exemplar_ring=0`` disables only the trace-id capture (the
        privacy knob) — anomaly EVENTS still land in the ring, or
        /query/anomalies and the Grafana annotations would go dark.

        Returns every trace-id hex captured across the flagged
        services — the span links a sampled batch trace's flag span
        carries (runtime.selftrace)."""
        if not flags_np.any():
            return []
        captured: list[str] = []
        cusum_thr = np.asarray(
            self.detector.config.cusum_thresholds, np.float32
        )
        now = time.time()
        with self._query_lock:
            for i in np.nonzero(flags_np)[0]:
                i = int(i)
                # Signal names come from the runtime.provenance
                # REASON_* table (the provenance-vocabulary staticcheck
                # pass fences this set — bundles, anomaly events and
                # dashboards all speak it).
                signals = [
                    name
                    for name, z in (
                        (REASON_LATENCY, report.lat_z[i]),
                        (REASON_ERROR_RATE, report.err_z[i]),
                        (REASON_THROUGHPUT, report.rate_z[i]),
                        (REASON_CARDINALITY, report.card_z[i]),
                    )
                    if np.abs(z).max() > threshold
                ] + (
                    [REASON_CUSUM]
                    if (report.cusum[i] > cusum_thr).any()
                    else []
                )
                traces: list[str] = []
                if self._exemplar_ring and cols is not None:
                    keys = cols.trace_key[cols.svc == i]
                    for v in keys[-self._exemplar_ring:]:
                        traces.append(int(v).to_bytes(8, "little").hex())
                if self._exemplar_ring:
                    ring = self._exemplars.get(i)
                    if ring is None:
                        ring = self._exemplars[i] = deque(
                            maxlen=self._exemplar_ring
                        )
                    sig = signals[0] if signals else "flag"
                    for tid in traces:
                        ring.append(
                            {"trace_id": tid, "t": now, "signal": sig}
                        )
                self.exemplars_captured += len(traces)
                captured.extend(traces)
                bundle_ref = None
                if self._provenance is not None:
                    # Evidence bundle per flagged service: candidates
                    # come from the same ring the top-k query reads
                    # (already under _query_lock here); seq is the
                    # detector step from the dispatch-lock snapshot so
                    # the id is a pure function of replicated
                    # coordinates.
                    seq = (
                        int(prov_state["step_idx"])
                        if prov_state is not None
                        and "step_idx" in prov_state
                        else self.stats.flag_events
                    )
                    names = self.tensorizer.service_names
                    cands = list(dict.fromkeys(
                        reversed(self._hh_cands.get(i) or ())
                    ))[: self._hh_cand_max]
                    bundle = self._provenance.build(
                        t_batch=float(t_batch),
                        seq=seq,
                        service=i,
                        label=(
                            names[i] if i < len(names) else f"svc-{i}"
                        ),
                        signals=signals,
                        exemplars=traces,
                        state=prov_state,
                        hh_candidates=cands,
                        trace_id=trace_id,
                    )
                    self._explain_ring.append(bundle)
                    self._explain_export.append(bundle)
                    self.explanations_built += 1
                    bundle_ref = bundle["id"]
                self._anomaly_ring.append({
                    "t": now,
                    "t_batch": float(t_batch),
                    "service": i,
                    "signals": signals,
                    "exemplars": traces,
                    "bundle": bundle_ref,
                })
        return captured

    def query_meta(self) -> dict:
        """JSON-able query-plane block: exemplar rings, recent anomaly
        events, and top-k candidate keys. Shipped inside the
        replication meta so a read replica answers exemplar/anomaly/
        top-k queries from the same data the primary would — the
        bit-consistency contract runtime.query is built on."""
        with self._query_lock:
            return {
                "exemplars": {
                    str(svc): [dict(e) for e in ring]
                    for svc, ring in self._exemplars.items()
                },
                "anomalies": [dict(ev) for ev in self._anomaly_ring],
                "hh_candidates": {
                    # Most-recent-first distinct CRCs (the ring keeps
                    # arrival order; dict.fromkeys dedups stably).
                    str(svc): list(
                        dict.fromkeys(reversed(ring))
                    )[: self._hh_cand_max]
                    for svc, ring in self._hh_cands.items()
                },
                "exemplars_captured": self.exemplars_captured,
                # Evidence bundles are built once (on the primary, at
                # flag time) and ride here verbatim — the replica's
                # /query/explain answers from the SAME dicts, which is
                # what makes the parity pin bit-identical.
                "explains": [dict(b) for b in self._explain_ring],
                "explanations_built": self.explanations_built,
            }

    def restore_query_meta(self, block: dict) -> None:
        """Promotion hydration: refill the query-plane rings from a
        replicated :meth:`query_meta` block, so exemplar/anomaly/top-k
        answers survive the role flip — the mirror is the ONLY copy a
        promoting standby has, and without this the history would
        vanish the moment its snapshot cache expires post-promotion.

        ``exemplars_captured`` is deliberately NOT restored: it backs
        this process's Prometheus counter delta, and importing the dead
        primary's lifetime total would spike the promoted daemon's
        ``anomaly_exemplars_captured_total`` by traffic it never saw."""
        if not block:
            return
        with self._query_lock:
            if self._exemplar_ring:
                for svc, events in (block.get("exemplars") or {}).items():
                    ring = self._exemplars.get(int(svc))
                    if ring is None:
                        ring = self._exemplars[int(svc)] = deque(
                            maxlen=self._exemplar_ring
                        )
                    ring.extend(
                        dict(e) for e in events[-self._exemplar_ring:]
                    )
            for ev in (block.get("anomalies") or [])[
                -self._anomaly_ring.maxlen:
            ]:
                self._anomaly_ring.append(dict(ev))
            if self._hh_cand_max:
                for svc, crcs in (
                    block.get("hh_candidates") or {}
                ).items():
                    ring = self._hh_cands.get(int(svc))
                    if ring is None:
                        ring = self._hh_cands[int(svc)] = deque(
                            maxlen=self._hh_cand_max
                        )
                    # query_meta lists most-recent-FIRST; the rings
                    # keep arrival order (most recent at the right).
                    ring.extend(int(c) for c in reversed(crcs))
            # Bundle ring: restored (the mirror is the only copy), but
            # explanations_built is NOT — it backs this process's
            # Prometheus counter delta, same rule as
            # exemplars_captured above.
            for b in (block.get("explains") or [])[
                -self._explain_ring.maxlen:
            ]:
                self._explain_ring.append(dict(b))

    def take_explain_exports(self) -> list[dict]:
        """Drain bundles awaiting OTLP log export (daemon export
        tick). Bounded drop-oldest upstream, so a stalled exporter
        never grows this queue."""
        with self._query_lock:
            out = list(self._explain_export)
            self._explain_export.clear()
        return out

    # -- report processing --------------------------------------------

    def _process_report(self, item) -> None:
        t_batch, t_dispatch, dev_report, cols, trace = item
        self._note_outcome(skipped=False)
        probe = self._start_rtt_probe() if self.rtt_probe else None
        # Single-array fetch + host-side unpack (see pump()).
        t_fetch = time.perf_counter()
        report = report_unpack(jax.device_get(dev_report), self.detector.config)
        fetch_dt = time.perf_counter() - t_fetch
        flags_np = report.flags
        if self._provenance is not None:
            # Ring the head trajectories on EVERY harvested report —
            # already host numpy, so the K-window evidence history
            # costs an append, never a device round trip.
            self._provenance.observe_report(float(t_batch), report)
        lag_ms = (time.monotonic() - t_dispatch) * 1e3
        self.stats.lag_ms.append(lag_ms)
        if self.phase_observe is not None:
            self.phase_observe(PHASE_HARVEST, fetch_dt)
            # Submit→harvest lag is its own histogram (the detection-lag
            # SLO's distribution), distinct from the fetch cost above.
            self.phase_observe(PHASE_HARVEST_LAG, lag_ms / 1e3)
        if trace is not None:
            trace.span(SPAN_HARVEST, fetch_dt)
        if probe is not None:
            probe["thread"].join(timeout=10.0)
            self.stats.rtt_ms.append(probe["res"].get("rtt", float("nan")))
        threshold = float(
            self.flags.evaluate(FLAG_THRESHOLD, self.detector.config.z_threshold)
        )
        if threshold != self.detector.config.z_threshold:
            # Re-derive flags from raw z-scores at the flagd-driven
            # threshold — no recompile, the report carries the scores.
            # The CUSUM alarms keep their own (unchanged) threshold; the
            # flag only tunes the instantaneous-z sensitivity.
            z = np.maximum.reduce(
                [
                    np.abs(report.lat_z).max(axis=1),
                    np.abs(report.err_z).max(axis=1),
                    np.abs(report.rate_z).max(axis=1),
                    np.abs(report.card_z).max(axis=1),
                ]
            )
            cusum_thr = np.asarray(
                self.detector.config.cusum_thresholds, np.float32
            )
            cusum_alarm = (report.cusum > cusum_thr[None, :]).any(axis=1)
            flags_np = (z > threshold) | cusum_alarm
        if flags_np.any():
            t_flag = time.perf_counter()
            self.stats.flag_events += 1
            names = self.tensorizer.service_names
            flagged = [
                names[i] if i < len(names) else f"svc-{i}"
                for i in np.nonzero(flags_np)[0]
            ]
            prov_state = (
                self._provenance_snapshot()
                if self._provenance is not None
                else None
            )
            links = self._capture_exemplars(
                t_batch, cols, report, flags_np, threshold,
                prov_state=prov_state,
                trace_id=(
                    trace.trace_id.hex() if trace is not None else None
                ),
            )
            flag_dt = time.perf_counter() - t_flag
            if self.phase_observe is not None:
                self.phase_observe(PHASE_FLAG, flag_dt)
            if trace is not None:
                # The flag span carries span LINKS to the exemplar shop
                # traces captured from THIS batch — a detector batch
                # trace in Jaeger jumps straight to the evidence.
                trace.span(
                    SPAN_FLAG, flag_dt,
                    attrs=(("flagged.services", ",".join(flagged)),),
                    links=tuple(links),
                )
        else:
            flagged = []
        if trace is not None:
            try:
                self._selftrace.finish(trace)
            except Exception:  # noqa: BLE001 — self-telemetry export
                # must never fail the report path it observes: the
                # trace is advisory, the report is the product.
                pass
        if self.on_report is not None:
            self.on_report(t_batch, report, flagged)
