"""Span records → fixed-width tensor batches (the host hot path).

Contract mirrors what the reference's checkout service attaches to its
spans and Kafka messages (/root/reference/src/checkout/main.go:248-315:
order id, currency, shipping cost, item products; and the OTLP span
fields every SDK emits: service.name resource attr, duration, trace_id,
status). A span record here is the minimal tuple the detector consumes:

    (service, duration_us, trace_id, is_error, attr)

Tensorization policy — everything the device needs is *hashes and
numbers*, so strings die at this boundary:

- ``service`` → small int id via an intern table (the service set is
  bounded — the shop has ~20; overflow routes to a reserved "other" id
  so shapes never change).
- ``trace_id`` (16 random bytes in OTLP) → first 8 bytes as uint64, then
  splitmix64 → (hi, lo) uint32 lanes. Random ids are already uniform but
  re-hashing is ~free and protects against structured ids.
- ``attr`` (the monitored attribute value, e.g. product id in an order)
  → CRC32 of the string, mixed with the service id, then splitmix64 —
  giving the (service, attr) folded CMS key (see ops.cms docstring).
- ``duration_us``, ``is_error`` → float32 lanes.

Batches are fixed width ``B`` with a validity mask (masked lanes hit the
monoid identities in the kernels), so every step reuses one compiled
program. The per-record Python path below is the portable fallback; the
C++ tensorizer (runtime/native) does the same transform vectorised for
the ≥200k spans/sec target.
"""

from __future__ import annotations

import threading
import zlib
from dataclasses import dataclass
from typing import Callable, Iterable, NamedTuple

import numpy as np

from ..ops.hashing import split_hi_lo_np, splitmix64_np

# Positional placeholder for an id slot the keyspace evictor freed and
# nothing has reclaimed yet. It must round-trip through every surface
# that carries the name table positionally (checkpoint meta,
# replication meta, fleet merge masks) without ever colliding with a
# real service name — OTLP service.name values are printable strings,
# so a NUL-prefixed sentinel cannot be interned from the wire.
EVICTED_SLOT = "\x00evicted"


class SpanEvent(NamedTuple):
    """One span event — the reference narrates spans with these
    (checkout's prepared/charged/shipped,
    /root/reference/src/checkout/main.go:270-294; product-catalog's
    "Product Found", main.go:296-315; email's record_exception,
    email_server.rb:32). ``ts_offset_us`` is the event time relative to
    span START (SpanRecords carry duration, not absolute start; the
    OTLP codecs convert to/from absolute time_unix_nano). ``attrs`` is
    a tuple of (key, value) pairs so the record stays hashable.
    """

    name: str
    ts_offset_us: float = 0.0
    attrs: tuple = ()

    @property
    def attr_dict(self) -> dict:
        return dict(self.attrs)


# Event names that carry error-cause evidence: the OTel semconv
# record_exception name, the reference checkout's deferred "error"
# event (main.go:257 — AddEvent("error", exception.message)), and the
# ad service's capitalized "Error" (AdService.java:219). Spans
# carrying one feed the detector's error lane even when their status
# is unset (email's Sinatra handler records the exception; the span
# status is whatever the framework set). Kept as an exact-name tuple —
# the native decoder (ingest.cc) matches the same three literals.
EXCEPTION_EVENT_NAMES = ("exception", "error", "Error")


def has_exception_event(events) -> bool:
    return any(e.name in EXCEPTION_EVENT_NAMES for e in events)


class SpanRecord(NamedTuple):
    """One ingested span (or order event projected onto span shape)."""

    service: str
    duration_us: float
    trace_id: bytes | int
    is_error: bool = False
    attr: str | None = None
    # Operation name — carried for trace-based assertions (the tracetest
    # harness selects spans by it); the tensorizer ignores it.
    name: str | None = None
    # Span events (SpanEvent tuple) — trace narration; the tensorizer
    # folds exception-shaped events into the error lane and ignores the
    # rest (strings die at the tensor boundary, evidence does not).
    events: tuple = ()


class SpanColumns(NamedTuple):
    """Interned columnar records — the pipeline's pending currency.

    The service axis is already resolved to small int ids; the attr key
    is the bare value CRC (the service fold and splitmix happen at pack
    time, in ``pack_arrays``). Both decode paths produce this shape: the
    per-record Python loop (``columns_from_records``) and the native C++
    decoder (``columns_from_columnar``), so batching, padding and
    device feed are one code path regardless of origin.
    """

    svc: np.ndarray  # int32 — interned service ids
    lat_us: np.ndarray  # float32
    is_error: np.ndarray  # float32
    trace_key: np.ndarray  # uint64 — first 8 bytes of trace id, LE
    attr_crc: np.ndarray  # uint64 — CRC32 of the monitored attr value

    @property
    def rows(self) -> int:
        return self.svc.shape[0]

    def slice(self, start: int, stop: int) -> "SpanColumns":
        return SpanColumns(*(a[start:stop] for a in self))

    def compress(self, keep: np.ndarray) -> "SpanColumns":
        """Rows where ``keep`` (bool mask) is True, order preserved —
        the shed/brownout paths' row-selection primitive."""
        return SpanColumns(*(a[keep] for a in self))

    @staticmethod
    def concat(parts: list["SpanColumns"]) -> "SpanColumns":
        if len(parts) == 1:
            return parts[0]
        return SpanColumns(
            *(np.concatenate(cols) for cols in zip(*parts))
        )


class TensorBatch(NamedTuple):
    """Fixed-width device-ready batch; all arrays length ``B``."""

    svc: np.ndarray  # int32 — service id
    lat_us: np.ndarray  # float32 — span duration
    is_error: np.ndarray  # float32 — 0/1 status flag
    trace_hi: np.ndarray  # uint32 — trace-id hash hi lane
    trace_lo: np.ndarray  # uint32
    attr_hi: np.ndarray  # uint32 — folded (service, attr) key hash
    attr_lo: np.ndarray  # uint32
    valid: np.ndarray  # bool

    @property
    def batch_size(self) -> int:
        return self.svc.shape[0]

    @property
    def num_valid(self) -> int:
        return int(self.valid.sum())


class InternArena:
    """Per-worker intern arena over a shared :class:`SpanTensorizer`.

    Each decode worker owns one. Lookups resolve against the arena's
    private dict — no shared snapshot read, no lock, no cache-line
    traffic with sibling workers — and only a flush whose batch carries
    a name this worker has NEVER seen reconciles against the global
    table, via ONE batched ``intern_many`` call (at most one lock
    acquisition per flush). Ids are global and immutable once assigned,
    which is what makes caching them worker-locally safe; bit-identity
    with the serial ``service_id`` path is pinned by
    tests/test_ingest_pool.py.
    """

    __slots__ = ("_tz", "_local", "_gen")

    def __init__(self, tensorizer: "SpanTensorizer"):
        self._tz = tensorizer
        self._local: dict[str, int] = {}
        self._gen = tensorizer.generation

    def lookup(self, names: list[str]) -> list[int]:
        """Resolve ``names`` (first-appearance document order) to ids."""
        if self._gen != self._tz.generation:
            # The evictor retired ids since this arena last synced —
            # cached name→id pairs may now point at RECYCLED slots
            # owned by different services. Drop the whole cache; one
            # cold flush per worker per generation is the entire cost.
            self._local = {}
            self._gen = self._tz.generation
        local = self._local
        try:
            return [local[n] for n in names]  # pure-local hot path
        except KeyError:
            pass
        ids = self._tz.intern_many(names)
        ov = self._tz.num_services - 1
        for n, sid in zip(names, ids):
            # Never cache the overflow id: a key parked there by a
            # full table or the keyspace new-key gate must re-consult
            # the global table later, when pressure clears and a slot
            # frees — a cached overflow hit would pin it forever.
            if sid != ov:
                local[n] = sid
        return ids


@dataclass
class SpanTensorizer:
    """Stateful interner + vectorised hasher; one per ingest stream.

    ``num_services`` bounds the service axis of every sketch; the last id
    is reserved for overflow ("other") so an unexpected service never
    changes tensor shapes — it just shares the overflow bucket, exactly
    the trade a streaming sketch makes everywhere else.
    """

    num_services: int = 32
    batch_size: int = 2048

    def __post_init__(self) -> None:
        self._svc_ids: dict[str, int] = {}
        # Interning is check-then-act; decode now happens on receiver
        # AND ingest-pool worker threads, so two concurrent
        # first-sightings of different names must not race to the same
        # id. Read-mostly design: the hot path reads an IMMUTABLE
        # snapshot dict (published wholesale under the lock, read
        # lock-free — dict reads are atomic under the GIL and the
        # snapshot object is never mutated after publication), so
        # workers interning a KNOWN service — every request after the
        # first sighting, i.e. essentially all of them — never touch
        # the lock. Only a genuine miss takes the lock, re-checks the
        # writable table, assigns, and publishes a fresh snapshot.
        self._intern_lock = threading.Lock()
        self._svc_snapshot: dict[str, int] = {}
        # Key lifecycle plane (runtime/keyspace.py). The table is
        # BOUNDED: names map to at most num_services-1 real slots; a
        # name that can't get one folds into the overflow bucket and
        # is NOT memorized (an unbounded name dict is exactly the
        # cardinality-bomb leak this plane exists to stop).
        # ``_names_by_id`` is the id-ordered mirror of _svc_ids (None =
        # never assigned, EVICTED_SLOT = freed, awaiting reuse);
        # ``service_names`` reads it so positional order survives id
        # recycling — dict insertion order stops being id order the
        # moment one id is reused.
        self._names_by_id: list[str | None] = []
        self._free_ids: list[int] = []  # retired ids, ascending reuse
        self._next_id = 0  # next never-used dense slot
        # Generation epoch: bumped once per retirement sweep. Frames,
        # checkpoints and fleet merges carry it so recycled ids are
        # never merged across the retirement boundary (ShardMergeError
        # drift-refusal contract); InternArena caches key on it.
        self.generation = 0
        # Optional admission gate consulted under the intern lock on a
        # GENUINE miss only: return False to park the new key in the
        # overflow bucket instead of granting a slot (the keyspace
        # ladder's throttle/collapse rungs). Existing keys never pass
        # through it.
        self.new_key_gate: Callable[[str], bool] | None = None
        self.evicted_total = 0  # ids retired over process lifetime
        self.overflow_assigns_total = 0  # misses parked in overflow

    @property
    def service_names(self) -> list[str]:
        """Positional name table: index i is the name owning id i
        (EVICTED_SLOT marks freed slots). Bit-identical to the old
        insertion-order list until the first eviction, after which
        only this ordering is correct."""
        out = list(self._names_by_id)
        return [EVICTED_SLOT if n is None else n for n in out]

    @property
    def capacity(self) -> int:
        """Real (non-overflow) id slots."""
        return self.num_services - 1

    @property
    def live_keys(self) -> int:
        return len(self._svc_ids)

    @property
    def free_ids(self) -> int:
        return len(self._free_ids)

    def service_id(self, name: str) -> int:
        sid = self._svc_snapshot.get(name)  # lock-free: hit is immutable
        if sid is None:
            with self._intern_lock:
                sid = self._assign_locked(name)
        return sid

    def _assign_locked(self, name: str, publish: bool = True) -> int:
        """Assign (or find) ``name``'s id; caller holds the intern
        lock. The ONE assignment rule both the per-name path and the
        batched path share — recycled ids first (ascending), then
        dense first-appearance ranks, with the last id reserved as the
        overflow bucket. ``publish=False`` defers the snapshot
        publication to the caller (the batched path publishes ONCE per
        batch instead of once per new name)."""
        sid = self._svc_ids.get(name)
        if sid is None:
            gate = self.new_key_gate
            if gate is not None and not gate(name):
                # Keyspace ladder refused the slot: overflow, and do
                # NOT memorize the name — the key re-applies on its
                # next sighting, when pressure may have cleared.
                self.overflow_assigns_total += 1
                return self.num_services - 1
            if self._free_ids:
                sid = self._free_ids.pop(0)
            elif self._next_id < self.num_services - 1:
                sid = self._next_id
                self._next_id += 1
            else:
                # Table saturated: overflow, unmemorized (bounded
                # memory beats a lock-free re-hit for a key the
                # sketches can't tell apart anyway).
                self.overflow_assigns_total += 1
                return self.num_services - 1
            self._svc_ids[name] = sid
            while len(self._names_by_id) <= sid:
                self._names_by_id.append(None)
            self._names_by_id[sid] = name
            if publish:
                # Publish a NEW snapshot object — readers holding the
                # old one still see consistent (if stale) hits and
                # fall through to the lock on miss.
                self._svc_snapshot = dict(self._svc_ids)
        return sid

    def intern_many(self, names: list[str]) -> list[int]:
        """Batched intern: every name resolved with AT MOST one lock
        acquisition for the whole batch (the flush-granular
        reconciliation the per-worker arenas ride).

        Misses are assigned in first-appearance order of ``names``, so
        a caller passing names in document order produces ids
        bit-identical to a serial ``service_id`` loop — the intern-id
        bit-exactness contract (tests/test_ingest_pool.py). Names the
        table refused (saturation or the new-key gate) resolve to the
        overflow id without being memorized.
        """
        snap = self._svc_snapshot  # immutable: consistent for the batch
        if all(n in snap for n in names):
            return [snap[n] for n in names]
        ov = self.num_services - 1
        with self._intern_lock:
            before = len(self._svc_ids)
            for n in names:
                if n not in self._svc_ids:
                    self._assign_locked(n, publish=False)
            if len(self._svc_ids) != before:
                # ONE snapshot publication for the whole batch — k new
                # names cost one O(N) copy, not k of them. An all-
                # overflow batch memorizes nothing and republishes
                # nothing (a recurring overflow name must not cost a
                # table copy per flush).
                self._svc_snapshot = dict(self._svc_ids)
            snap = self._svc_snapshot
        return [snap.get(n, ov) for n in names]

    def retire_services(self, names: list[str]) -> list[int]:
        """Retire ``names`` from the live table: their ids join the
        free list (ascending) for reuse and the generation epoch bumps
        ONCE for the whole sweep. Returns the freed ids.

        CONTRACT: the caller must hold the pipeline dispatch lock (the
        eviction-lock staticcheck pass pins this) and must have folded
        the retired rows out of detector state BEFORE calling — after
        the snapshot republish below, a recycled id can be assigned to
        a brand-new service on the very next flush, and any residue in
        its sketch rows would mis-attribute history to the newcomer.
        """
        freed: list[int] = []
        with self._intern_lock:
            for name in names:
                sid = self._svc_ids.pop(name, None)
                if sid is None or sid >= self.num_services - 1:
                    continue  # unknown, or the overflow bucket
                self._names_by_id[sid] = EVICTED_SLOT
                freed.append(sid)
            if freed:
                self._free_ids.extend(freed)
                self._free_ids.sort()
                self.evicted_total += len(freed)
                self.generation += 1
                self._svc_snapshot = dict(self._svc_ids)
        return freed

    def adopt_names(self, names: list[str]) -> None:
        """Rebuild the table POSITIONALLY from a checkpoint/snapshot
        name list (index = id), honoring EVICTED_SLOT tombstones as
        free slots. A plain ``service_id`` replay can't restore a
        post-eviction table — it would re-densify around the holes and
        shift every id after the first tombstone.
        """
        with self._intern_lock:
            self._svc_ids = {}
            self._names_by_id = []
            self._free_ids = []
            for sid, name in enumerate(names[: self.num_services - 1]):
                if name is None or name == EVICTED_SLOT:
                    self._names_by_id.append(EVICTED_SLOT)
                    self._free_ids.append(sid)
                else:
                    self._names_by_id.append(name)
                    self._svc_ids[name] = sid
            self._next_id = len(self._names_by_id)
            self._svc_snapshot = dict(self._svc_ids)

    def tensorize(self, records: Iterable[SpanRecord]) -> list[TensorBatch]:
        """Pack records into one or more fixed-width batches."""
        cols = self.columns_from_records(list(records))
        out: list[TensorBatch] = []
        for start in range(0, max(cols.rows, 1), self.batch_size):
            out.append(self.pack_columns(cols.slice(start, start + self.batch_size)))
        return out

    def columns_from_records(self, records: list[SpanRecord]) -> SpanColumns:
        """Python record path (portable fallback; see module doc).

        Vectorised: one ``np.fromiter`` per numeric lane instead of
        per-row scalar array stores, and ALL trace ids batched through
        ONE ``np.frombuffer`` over a joined byte buffer (the per-row
        ``np.frombuffer`` of the old loop was ~1 µs/row of pure call
        overhead — 100× the native decoder's whole span budget). Same
        outputs bit-for-bit: tests/test_ingest_pool.py pins this
        against a reference per-row loop.
        """
        n = len(records)
        svc = np.fromiter(
            (self.service_id(r.service) for r in records), np.int32, count=n
        )
        lat = np.fromiter(
            (r.duration_us for r in records), np.float32, count=n
        )
        # Exception events are error-cause evidence even on spans
        # whose status was never set to ERROR (see SpanEvent doc).
        err = np.fromiter(
            (
                1.0 if (r.is_error or has_exception_event(r.events)) else 0.0
                for r in records
            ),
            np.float32, count=n,
        )
        # Trace ids: first 8 bytes little-endian, zero-padded — joined
        # into one contiguous buffer so a single frombuffer reads every
        # key (int ids serialize through the same 8-byte LE layout).
        joined = b"".join(
            bytes(r.trace_id[:8]).ljust(8, b"\0")
            if isinstance(r.trace_id, (bytes, bytearray))
            else (r.trace_id & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
            for r in records
        )
        tid = np.frombuffer(joined, dtype=np.uint64, count=n).copy()
        crc = np.fromiter(
            (
                zlib.crc32((r.attr if r.attr is not None else "").encode())
                for r in records
            ),
            np.uint64, count=n,
        )
        return SpanColumns(svc, lat, err, tid, crc)

    def columns_from_columnar(
        self, cols, copy: bool = False, arena: "InternArena | None" = None
    ) -> SpanColumns:
        """Adopt a native-decoder batch (runtime.native.ColumnarSpans).

        Interns the handful of per-request service names (``None`` —
        no service.name attribute — becomes the record decoder's
        "unknown"; a present-but-empty name interns as ``""``, exactly
        as the record path does) and maps the per-row resource indices
        through — the only per-string work left on the Python side of
        the native path. Only names actually referenced by a span are
        interned (a span-less resource block must not claim a service
        id the record path would never assign); ``svc_idx`` is monotone
        in document order, so ``np.unique``'s sorted order IS
        first-appearance order.

        ``arena`` (a per-worker :class:`InternArena`) resolves the
        names against worker-LOCAL memory first, touching the shared
        snapshot/lock at most once per flush — the decode workers'
        contention-free path. Ids are bit-identical either way.

        ``copy=True`` forces every output lane to own fresh memory —
        required when ``cols`` is views into a reusable decode scratch
        (the ingest pool's buffer freelist), whose next decode would
        otherwise scribble over rows still queued in the pipeline.
        """
        ids = np.zeros(max(len(cols.services), 1), np.int32)
        # O(rows) presence scan instead of np.unique's O(rows log rows)
        # sort — ascending index order IS first-appearance order
        # (svc_idx is monotone in document order).
        seen = np.zeros(max(len(cols.services), 1), bool)
        seen[cols.svc_idx] = True
        live = np.nonzero(seen)[0]
        if arena is not None:
            names = [
                "unknown" if cols.services[i] is None else cols.services[i]
                for i in live
            ]
            ids[live] = arena.lookup(names)
        else:
            for i in live:
                name = cols.services[i]
                ids[i] = self.service_id(
                    "unknown" if name is None else name
                )
        return SpanColumns(
            svc=ids[cols.svc_idx],
            lat_us=cols.duration_us.astype(np.float32, copy=copy),
            # Same exception-event fold as the record path: the native
            # decoder surfaces a has_exception flag per span.
            is_error=np.maximum(
                cols.is_error, cols.has_exception
            ).astype(np.float32),
            trace_key=cols.trace_key.copy() if copy else cols.trace_key,
            attr_crc=cols.attr_crc.astype(np.uint64),
        )

    def pack_columns(
        self, cols: SpanColumns, width: int | None = None
    ) -> TensorBatch:
        """Columns → one padded, hashed, device-ready batch."""
        return self.pack_arrays(
            cols.svc,
            cols.lat_us,
            cols.trace_key,
            cols.is_error,
            cols.attr_crc,
            width=width,
        )

    def alloc_batch(self, width: int | None = None) -> TensorBatch:
        """Pre-allocated width-sized host arrays for
        :meth:`pack_columns_into` — one spine ring slot. Allocated once
        per (slot, width) and reused for every staged batch, so the
        steady-state pack performs zero numpy allocations and the
        device put always reads from stable host memory."""
        b = width if width is not None else self.batch_size
        return TensorBatch(
            np.zeros(b, np.int32),
            np.zeros(b, np.float32),
            np.zeros(b, np.float32),
            np.zeros(b, np.uint32),
            np.zeros(b, np.uint32),
            np.zeros(b, np.uint32),
            np.zeros(b, np.uint32),
            np.zeros(b, bool),
        )

    def pack_columns_into(
        self, out: TensorBatch, cols: SpanColumns, chunk_rows: int = 0
    ) -> TensorBatch:
        """:meth:`pack_columns` into PRE-ALLOCATED arrays, bit-for-bit.

        The spine's staging pack: rows are hashed + copied into the
        ring slot ``out`` (optionally in ``chunk_rows`` blocks — cache
        blocking for the copy loop), the tail is padded exactly as
        :meth:`pack_arrays` pads (masked lanes carry the hash of the
        zero key, valid=False), and no width-sized array is allocated.
        tests/test_spine.py pins equality against pack_columns.
        """
        n = cols.rows
        b = out.svc.shape[0]
        if n > b:
            raise ValueError(f"chunk of {n} exceeds batch width {b}")
        step = int(chunk_rows) if chunk_rows and chunk_rows > 0 else max(n, 1)
        for s0 in range(0, n, step):
            sl = slice(s0, min(s0 + step, n))
            out.svc[sl] = cols.svc[sl]
            out.lat_us[sl] = cols.lat_us[sl]
            out.is_error[sl] = cols.is_error[sl]
            key = cols.attr_crc[sl].astype(np.uint64) | (
                cols.svc[sl].astype(np.uint64) << np.uint64(32)
            )
            t_hi, t_lo = split_hi_lo_np(splitmix64_np(cols.trace_key[sl]))
            a_hi, a_lo = split_hi_lo_np(splitmix64_np(key))
            out.trace_hi[sl] = t_hi
            out.trace_lo[sl] = t_lo
            out.attr_hi[sl] = a_hi
            out.attr_lo[sl] = a_lo
            out.valid[sl] = True
        # Pad tail: numeric lanes zero; hash lanes carry the zero-key
        # hash (pack_arrays hashes AFTER padding, so parity demands it).
        tail = slice(n, b)
        out.svc[tail] = 0
        out.lat_us[tail] = 0.0
        out.is_error[tail] = 0.0
        z_hi, z_lo = split_hi_lo_np(
            splitmix64_np(np.zeros(1, np.uint64))
        )
        out.trace_hi[tail] = z_hi[0]
        out.trace_lo[tail] = z_lo[0]
        out.attr_hi[tail] = z_hi[0]
        out.attr_lo[tail] = z_lo[0]
        out.valid[tail] = False
        return out

    def pack_arrays(
        self,
        svc: np.ndarray,
        lat_us: np.ndarray,
        trace_id: np.ndarray,
        is_error: np.ndarray | None = None,
        attr_key: np.ndarray | None = None,
        width: int | None = None,
    ) -> TensorBatch:
        """Vectorised packing for callers that already hold columnar data
        (the simulator, the C++ decoder, benchmark generators). ``svc``
        must already be int ids; ``trace_id``/``attr_key`` uint64 keys.
        Pads (or rejects overflow beyond) ``width`` (default
        ``batch_size`` — the adaptive pipeline passes its grown width).
        """
        n = svc.shape[0]
        b = width if width is not None else self.batch_size
        if n > b:
            raise ValueError(f"chunk of {n} exceeds batch width {b}")

        def pad(x, dtype):
            out = np.zeros(b, dtype)
            out[:n] = x
            return out

        if is_error is None:
            is_error = np.zeros(n, np.float32)
        if attr_key is None:
            attr_key = trace_id
        attr_key = attr_key.astype(np.uint64) | (
            svc.astype(np.uint64) << np.uint64(32)
        )
        t_hi, t_lo = split_hi_lo_np(splitmix64_np(pad(trace_id, np.uint64)))
        a_hi, a_lo = split_hi_lo_np(splitmix64_np(pad(attr_key, np.uint64)))
        valid = np.zeros(b, bool)
        valid[:n] = True
        return TensorBatch(
            pad(svc, np.int32),
            pad(lat_us, np.float32),
            pad(is_error, np.float32),
            t_hi,
            t_lo,
            a_hi,
            a_lo,
            valid,
        )
