"""End-to-end ingest-spine benchmark: OTLP payload → flagged report.

The bench trajectory had two ISOLATED numbers — pooled host ingest
(~8M spans/s, ingestbench) and the device sketch kernel (~66M spans/s,
bench.py's matrix) — and an 8× gap between them that ROADMAP item 1
exists to close. This module measures the number that actually matters:
sustained spans/sec from raw OTLP protobuf bytes, through the parallel
decode pool (zero-copy ticketed scratch), the pipeline's bounded
admission, the device-put spine's staged ring, the donated one-pass
device step, and back out as harvested detector reports. One
methodology, two callers: ``make spinebench`` (the standalone sweep:
workers × ring depth) and ``bench.py`` (the ``e2e_spans_per_sec`` /
``e2e_ok`` artifact fields, gated at ≥90% of
``min(host_ingest, kernel)`` — transfer provably hidden behind
compute, not just asserted).

The pump runs on its own thread at a tight cadence while the driver
thread offers payloads as fast as admission accepts them — the daemon
loop's shape, minus the sockets (the receivers' HTTP/gRPC framing is
measured elsewhere; this is the Kafka/collector-facing span path).
"""

from __future__ import annotations

import threading
import time

from ..models.detector import AnomalyDetector, DetectorConfig
from .ingest_pool import IngestPool, IngestPoolSaturated
from .ingestbench import make_payloads
from .pipeline import DetectorPipeline


def measure_kernel_ref(
    config: DetectorConfig, batch: int, steps: int = 120
) -> float:
    """Device-only spans/s at the SAME geometry and batch width the
    e2e run dispatches — the matched-basis denominator for
    ``e2e_vs_kernel`` (bench.py's headline kernel runs default
    geometry at BENCH_BATCH; comparing e2e against THAT would mix
    apples and oranges). Slope-of-two-regions with a terminating
    fetch, the repo's honest-timing rule (bench.py module doc)."""
    import numpy as np

    from .lagbench import make_columns

    det = AnomalyDetector(config)
    rng = np.random.default_rng(0)
    from .tensorize import SpanTensorizer

    tens = SpanTensorizer(
        num_services=config.num_services, batch_size=batch
    )
    packed = tens.pack_columns(make_columns(rng, batch), width=batch)
    t = 0.0
    det.observe_packed(packed, t)  # compile

    def region(k: int, t0: float) -> tuple[float, float]:
        start = time.perf_counter()
        t_local = t0
        for _ in range(k):
            t_local += 0.05
            rep = det.observe_packed(packed, t_local)
        import jax

        jax.device_get(rep)  # fetch forces the chain
        return time.perf_counter() - start, t_local

    k1 = max(steps // 4, 8)
    k2 = 3 * k1
    t1, t = region(k1, t)
    t2, t = region(k2, t)
    per_step = max((t2 - t1) / (k2 - k1), 1e-9)
    return batch / per_step


def measure_e2e(
    workers: int = 2,
    ring: int = 2,
    seconds: float = 4.0,
    batch: int = 2048,
    overlap: bool = True,
    num_services: int = 16,
    hll_p: int = 8,
    cms_width: int = 1024,
    n_requests: int = 32,
    spans_per_request: int = 256,
    payloads: list[bytes] | None = None,
    kernel_ref: bool = True,
    selftrace: bool = False,
    selftrace_sample: float = 0.05,
    provenance: bool = False,
) -> dict | None:
    """One configuration's e2e rate, or None without the native decoder.

    Geometry defaults are CI-friendly (the protocol and overlap, not
    the kernel plateau, are under test here — bench.py reports the
    kernel's own rate beside this); on a real TPU pass the production
    geometry. Returns spans/sec measured payload-submit → report-
    harvest (everything drained before the clock stops), plus the
    attribution the spine's win is judged by: pool phase shares and
    the put-overlap ratio.
    """
    from . import native

    if not native.available():
        return None
    if payloads is None:
        payloads = make_payloads(n_requests, spans_per_request)
    config = DetectorConfig(
        num_services=num_services, hll_p=hll_p, cms_width=cms_width
    )
    det = AnomalyDetector(config)
    reports = [0]
    # Self-telemetry A/B leg (bench.py's selftrace_overhead_ratio):
    # the FULL production wiring — sampled tracer + phase histograms
    # into a real MetricRegistry — so the measured cost is what the
    # daemon actually pays, not a strawman.
    tracer = None
    phase_observe = None
    if selftrace:
        from ..telemetry.metrics import (
            ANOMALY_HARVEST_LAG,
            ANOMALY_PHASE_SECONDS,
            ANOMALY_SPINE_PUT_WAIT,
            MetricRegistry,
        )
        from .selftrace import (
            PHASE_BUCKETS,
            PHASE_HARVEST_LAG,
            PHASE_PUT_WAIT,
            SelfTracer,
        )

        registry = MetricRegistry()
        sink = {"n": 0, "bytes": 0}

        def _submit(body: bytes) -> None:
            sink["n"] += 1
            sink["bytes"] += len(body)

        tracer = SelfTracer(submit=_submit, sample=selftrace_sample)

        def phase_observe(phase: str, seconds_: float) -> None:
            metric = (
                ANOMALY_HARVEST_LAG if phase == PHASE_HARVEST_LAG
                else ANOMALY_SPINE_PUT_WAIT if phase == PHASE_PUT_WAIT
                else ANOMALY_PHASE_SECONDS
            )
            if metric is ANOMALY_PHASE_SECONDS:
                registry.histogram_observe(
                    metric, seconds_, PHASE_BUCKETS, phase=phase
                )
            else:
                registry.histogram_observe(metric, seconds_, PHASE_BUCKETS)

    # Provenance A/B leg (bench.py's explain_overhead_ratio): the real
    # engine wired the way the daemon wires it, so the steady-state
    # cost under measurement is the per-report trajectory ring — the
    # only provenance work that runs on every batch (bundle assembly
    # only fires on flags, which synthetic steady load rarely raises;
    # same sampled-measurement philosophy as the selftrace arm).
    prov = None
    if provenance:
        from .provenance import ProvenanceEngine

        prov = ProvenanceEngine(config)
    pipe = DetectorPipeline(
        det,
        on_report=lambda t, r, flagged: reports.__setitem__(
            0, reports[0] + 1
        ),
        batch_size=batch,
        spine_ring=ring,
        spine_overlap=overlap,
        phase_observe=phase_observe,
        selftrace=tracer,
        provenance=prov,
    )
    pool = IngestPool(
        pipe.submit_columns,
        pipe.tensorizer,
        workers=workers,
        coalesce_max=64,
        max_pending=max(4 * n_requests, 256),
        phase_observe=phase_observe,
        selftrace=tracer,
    )
    stop = threading.Event()

    def pump_loop() -> None:
        while not stop.is_set():
            pipe.pump()
            time.sleep(0.0005)

    pump = threading.Thread(target=pump_loop, name="e2e-pump", daemon=True)
    try:
        # Warmup: size the scratch + compile the step off the clock.
        pool.submit(payloads[0]).result()
        pool.drain()
        pipe.pump()
        pipe.drain()
        pump.start()
        t0 = time.perf_counter()
        deadline = t0 + seconds
        while time.perf_counter() < deadline:
            for p in payloads:
                try:
                    pool.submit(p)
                except IngestPoolSaturated:
                    time.sleep(0.001)  # bounded admission: back off
        pool.drain()
        stop.set()
        pump.join(timeout=10.0)
        pipe.drain()
        elapsed = time.perf_counter() - t0
    finally:
        stop.set()
        pool.close()
        pipe.close()
    st = pool.stats()
    from .ingest_pool import TOP_PHASES

    # TOP-level phases only: scan/extract are sub-phases INSIDE the
    # decode envelope (the two-pass scanner's split) — summing them
    # into the denominator would double-count decode time.
    phase = {k: st["phase_s"].get(k, 0.0) for k in TOP_PHASES}
    phase_total = sum(phase.values()) or 1.0
    spine = pipe.spine_stats()
    # Matched-basis kernel reference: device-only rate at THIS
    # geometry and batch width (measured after the e2e run so the
    # timed region never shares the machine with the pool threads).
    kernel_rate = (
        measure_kernel_ref(config, batch) if kernel_ref else None
    )
    return {
        "spans_per_sec": pipe.stats.spans / max(elapsed, 1e-9),
        "kernel_spans_per_sec": (
            round(kernel_rate, 1) if kernel_rate else None
        ),
        "spans": pipe.stats.spans,
        "batches": pipe.stats.batches,
        "reports": reports[0],
        "elapsed_s": round(elapsed, 3),
        "workers": workers,
        "ring": ring,
        "overlap_ratio": (
            round(spine["overlap_ratio"], 4) if spine else None
        ),
        "tickets_parked": st["tickets_parked"],
        "tickets_recycled": st["tickets_recycled"],
        "frames_corrupt": st["frames_corrupt"],
        # Flush-time attribution (fractions of pool wall time): the
        # zero-copy win shows as decode dominating; a fat tensorize/
        # submit share means the host glue is the bottleneck again.
        "phase_share": {
            k: round(v / phase_total, 4) for k, v in phase.items()
        },
        "selftrace_traces": (
            tracer.traces_exported if tracer is not None else None
        ),
        "explanations_built": (
            pipe.explanations_built if prov is not None else None
        ),
    }


def measure_selftrace_overhead(
    seconds: float = 2.0, rounds: int = 2, **kw
) -> dict | None:
    """Tracer-on vs tracer-off spinebench A/B — the overhead canary.

    Interleaved OFF/ON rounds on the SAME payload set (ABAB, so CPU
    drift hits both arms), full production wiring on the ON arm
    (sampled tracer + phase histograms into a real registry). Returns
    ``ratio`` = off_rate / on_rate — 1.0 means free, and bench.py
    gates it at ≤ 1.03. None without the native decoder."""
    payloads = kw.pop("payloads", None) or make_payloads(
        kw.get("n_requests", 32), kw.get("spans_per_request", 256)
    )
    rates = {True: [], False: []}
    traces = 0
    for _ in range(max(int(rounds), 1)):
        for on in (False, True):
            got = measure_e2e(
                seconds=seconds, payloads=payloads, kernel_ref=False,
                selftrace=on, **kw,
            )
            if got is None:
                return None
            rates[on].append(got["spans_per_sec"])
            if on:
                traces += got.get("selftrace_traces") or 0
    rate_off = sum(rates[False]) / len(rates[False])
    rate_on = sum(rates[True]) / len(rates[True])
    return {
        "ratio": round(rate_off / max(rate_on, 1e-9), 4),
        "spans_per_sec_on": round(rate_on, 1),
        "spans_per_sec_off": round(rate_off, 1),
        "traces_exported": traces,
    }


def measure_explain_overhead(
    seconds: float = 2.0, rounds: int = 2, **kw
) -> dict | None:
    """Provenance-on vs provenance-off spinebench A/B.

    Same ABAB discipline as ``measure_selftrace_overhead``: interleaved
    OFF/ON rounds over one payload set so CPU drift hits both arms,
    the real ``ProvenanceEngine`` on the ON arm. ``ratio`` =
    off_rate / on_rate; bench.py gates it at ≤ 1.03 — the evidence
    plane must ride the harvester for free. None without the native
    decoder."""
    payloads = kw.pop("payloads", None) or make_payloads(
        kw.get("n_requests", 32), kw.get("spans_per_request", 256)
    )
    rates = {True: [], False: []}
    built = 0
    for _ in range(max(int(rounds), 1)):
        for on in (False, True):
            got = measure_e2e(
                seconds=seconds, payloads=payloads, kernel_ref=False,
                provenance=on, **kw,
            )
            if got is None:
                return None
            rates[on].append(got["spans_per_sec"])
            if on:
                built += got.get("explanations_built") or 0
    rate_off = sum(rates[False]) / len(rates[False])
    rate_on = sum(rates[True]) / len(rates[True])
    return {
        "ratio": round(rate_off / max(rate_on, 1e-9), 4),
        "spans_per_sec_on": round(rate_on, 1),
        "spans_per_sec_off": round(rate_off, 1),
        "explanations_built": built,
    }


def measure_sweep(
    workers_list=(1, 2), rings=(0, 2, 4), seconds: float = 2.0,
    **kw,
) -> dict[str, float]:
    """workers × ring-depth grid of e2e rates ({} without native) —
    the ``make spinebench`` matrix: ring 0 is the inline pack+put
    BEFORE number, so the spine's delta is in the same artifact."""
    payloads = kw.pop("payloads", None) or make_payloads(
        kw.get("n_requests", 32), kw.get("spans_per_request", 256)
    )
    out: dict[str, float] = {}
    for w in workers_list:
        for r in rings:
            got = measure_e2e(
                workers=w, ring=r, seconds=seconds, payloads=payloads,
                kernel_ref=False, **kw,
            )
            if got is None:
                return {}
            out[f"w{w}r{r}"] = round(got["spans_per_sec"], 1)
    return out


def main() -> None:
    import json
    import sys

    from ..utils.config import BENCH_KNOBS, env_float

    seconds = env_float(
        "BENCH_SPINE_SECONDS", BENCH_KNOBS["BENCH_SPINE_SECONDS"][1]
    )
    if "--explain" in sys.argv[1:]:
        # `make explainbench`: the provenance canary alone — the A/B
        # overhead ratio (gated ≤1.03 in bench.py) plus the explain
        # endpoint's own p99 from the querybench hammer.
        from .querybench import measure_query

        explain_ab = measure_explain_overhead(
            seconds=max(seconds / 3, 1.0)
        )
        queryq = measure_query()
        print(
            json.dumps(
                {
                    "metric": "explain_overhead",
                    "explain_overhead_ratio": (
                        explain_ab.get("ratio") if explain_ab else None
                    ),
                    "explain_overhead": explain_ab or None,
                    "explain_p99_ms": queryq.get("explain_p99_ms"),
                    "explain_queries": queryq.get("explain_queries"),
                    "query_p99_ms": queryq.get("query_p99_ms"),
                }
            )
        )
        return
    headline = measure_e2e(seconds=seconds)
    sweep = measure_sweep(seconds=max(seconds / 3, 1.0))
    selftrace_ab = measure_selftrace_overhead(
        seconds=max(seconds / 3, 1.0)
    )
    print(
        json.dumps(
            {
                "metric": "e2e_ingest_spine",
                "e2e_spans_per_sec": (
                    round(headline["spans_per_sec"], 1) if headline else None
                ),
                "unit": "spans/sec",
                "e2e_overlap_ratio": (
                    headline.get("overlap_ratio") if headline else None
                ),
                "e2e_phase_share": (
                    headline.get("phase_share") if headline else None
                ),
                "e2e_reports": headline.get("reports") if headline else None,
                "sweep": sweep or None,
                "selftrace_overhead_ratio": (
                    selftrace_ab.get("ratio") if selftrace_ab else None
                ),
                "selftrace_overhead": selftrace_ab or None,
            }
        )
    )


if __name__ == "__main__":
    main()
