"""opentelemetry_demo_tpu — TPU-native streaming-sketch analytics framework.

A ground-up, TPU-first rebuild of the capability surface of the
OpenTelemetry Astronomy Shop demo (`antimetal/opentelemetry-demo`, mounted
read-only at /root/reference), centred on the system's north star: a
streaming anomaly-detection sidecar that consumes the shop's Kafka
`orders` topic (reference: src/fraud-detection/src/main/kotlin/frauddetection/main.kt:54-69)
and OTLP span/metric streams (reference: src/otel-collector/otelcol-config.yml:4-143)
and runs HyperLogLog / Count-Min / EWMA z-score sketch kernels in
JAX/Pallas on batched span tensors.

Package layout
--------------
- ``ops``       pure, stateless sketch kernels (HLL, CMS, EWMA, hashing,
                fused Pallas) on packed tensor state — the MXU/VPU path.
- ``models``    the AnomalyDetector "model": multi-window sketch-bank state
                pytree + a single jitted, donated update step.
- ``parallel``  device meshes, shard_map sketch-merge collectives (ICI),
                ring/DCN replay — the distributed backend.
- ``runtime``   host streaming runtime: tensorization, double-buffered
                device feed, Kafka/OTLP ingest, checkpoint/resume.
- ``services``  the Astronomy Shop capability layer (checkout orchestration,
                cart, currency, payment, …) as in-process services driving
                realistic span streams for tests and load generation.
- ``telemetry`` OTel-style span/metric emission and Prometheus export.
- ``utils``     config (env contract), flagd-style feature flags, helpers.
"""

__version__ = "0.1.0"
