// Native ingest: protobuf wire → columnar span tensors, C ABI.
//
// The host side of the ≥200k spans/sec target (SURVEY.md §7 hard part
// (a)): protobuf decode and attribute hashing must not be a per-record
// Python loop. This library decodes the two ingest seams directly into
// columnar arrays the tensorizer turns into device batches.
//
// **Two-pass structural decode** (the r15 decode-wall rework,
// simdjson-style): pass 1 (`scan_request`) is a boundary sweep that
// validates the structural levels — top-level fields, ResourceSpans
// including the resource's KeyValues, ScopeSpans, span headers — and
// records one (ptr, len, svc) entry per span WITHOUT parsing span
// interiors (their bytes are skipped by length). Pass 2
// (`extract_span`) consumes that structural index and extracts the
// columns, one independent span at a time, with no re-parsing of the
// framing. The split buys three things:
//
//   - exact capacity up front: pass 1 knows the span/resource/name
//     totals before a single column row is written, so -2/-3 are
//     decided once instead of mid-parse;
//   - **intra-call sharding**: `otd_decode_otlp_many` splits the
//     combined span index across `n_threads` worker threads at span-
//     record boundaries (including MID-payload — one oversized OTLP
//     export no longer serializes on one core), each thread writing a
//     disjoint row range of the shared output columns;
//   - attributable phases: the call reports scan vs extract wall time
//     (`scan_s` / `extract_s`), which runtime/ingest_pool.py feeds to
//     the anomaly_phase_seconds{phase=scan|extract} histograms.
//
// Verdict parity with the single-pass decoder is by construction: the
// two passes together check exactly the constraint set the old
// interleaved walk checked (pass 1 the framing, pass 2 the span
// interiors), and a payload is malformed iff either pass says so —
// order of discovery never changes a per-payload verdict. A pass-2
// failure marks its payload bad; a single-threaded epilogue compacts
// the bad payload's rows/services back out (append-only writes make
// the compaction a handful of memmoves), so batchmates keep their
// rows and `payload_rows` keeps the old -1-per-bad-payload contract.
//
// The decoded seams:
//
//   - OTLP ExportTraceServiceRequest (the collector-export seam; field
//     numbers per opentelemetry-proto trace/v1, mirrored from
//     runtime/otlp.py which mirrors the reference collector config
//     /root/reference/src/otel-collector/otelcol-config.yml:120-123).
//   - OrderResult from the Kafka `orders` topic (field numbers per
//     /root/reference/pb/demo.proto:203-214, same contract as the
//     reference consumers Consumer.cs:59-70 / main.kt:64).
//
// Parity contract with runtime/wire.py + runtime/otlp.py +
// runtime/kafka_orders.py (enforced by tests/test_native_ingest.py):
// identical columns on well-formed payloads AND identical error
// verdicts on malformed ones — the HTTP receiver answers 400 where the
// Python path would, never 200-and-drop. The Python decoders' field
// semantics fall into a few categories, modelled explicitly below:
//
//   submessage-list  — every occurrence descended, any non-LEN value
//                      is an error (Python: scan_fields(int) raises).
//   submessage-first — first occurrence claims the slot; LEN descends,
//                      numeric 0 is "absent" (falsy), numeric nonzero
//                      is an error (truthy int hits scan_fields).
//   bytes-first      — first occurrence claims the slot; LEN is the
//                      value, numeric 0 falls to the default, numeric
//                      nonzero is an error (int.decode()).
//   numeric-first    — first occurrence claims the slot; any numeric
//                      wire type is the value (wire.py decodes varint/
//                      fixed alike), empty LEN is falsy-skip, nonempty
//                      LEN is an error (int(bytes) raises).
//
// Strings are hashed with zlib-compatible CRC32 exactly as the Python
// tensorizer does.
//
// Build: g++ -O3 -shared -fPIC (no dependencies). Loaded via ctypes by
// opentelemetry_demo_tpu/runtime/native.py.

#include <atomic>
#include <chrono>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32
// IEEE CRC-32 (zlib/zip polynomial 0xEDB88320), table-driven; must
// match Python's zlib.crc32 bit-for-bit (tensorize.py attr keys).
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const Crc32Table kCrc;

uint32_t crc32(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = kCrc.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// --------------------------------------------------------------- crc32c
// CRC-32C (Castagnoli polynomial 0x82F63B78, reflected) — the frame
// checksum (runtime/frame.py); slicing-by-8 so verify runs at memory
// bandwidth rather than per-byte table speed. Must match frame.py's
// portable _py_crc32c bit-for-bit (pinned by tests/test_frame.py).
struct Crc32cTable {
  uint32_t t[8][256];
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = t[0][t[s - 1][i] & 0xFF] ^ (t[s - 1][i] >> 8);
  }
};
const Crc32cTable kCrc32c;

uint32_t crc32c_sw(uint32_t seed, const uint8_t* p, size_t n) {
  uint32_t c = ~seed;
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    c ^= lo;
    c = kCrc32c.t[7][c & 0xFF] ^ kCrc32c.t[6][(c >> 8) & 0xFF] ^
        kCrc32c.t[5][(c >> 16) & 0xFF] ^ kCrc32c.t[4][c >> 24] ^
        kCrc32c.t[3][hi & 0xFF] ^ kCrc32c.t[2][(hi >> 8) & 0xFF] ^
        kCrc32c.t[1][(hi >> 16) & 0xFF] ^ kCrc32c.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) c = kCrc32c.t[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return ~c;
}

// CRC-32C in hardware where the ISA offers it: the Castagnoli
// polynomial IS x86 SSE4.2's crc32 instruction (and AArch64's CRC32C
// extension), so the hardware path is bit-identical to the sliced
// table walk by definition of the instruction — the ingest-hop verify,
// the parked-scratch recycle re-check and every frame trailer run at
// instruction speed (~3 bytes/cycle) instead of table speed. Runtime-
// detected once; the portable slicing-by-8 path stays the fallback
// (and the only path on other ISAs).
#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(uint32_t seed,
                                                     const uint8_t* p,
                                                     size_t n) {
  uint32_t c = ~seed;
#if defined(__x86_64__)
  uint64_t c64 = c;
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    c64 = __builtin_ia32_crc32di(c64, v);
    p += 8;
    n -= 8;
  }
  c = uint32_t(c64);
#endif
  while (n--) c = __builtin_ia32_crc32qi(c, *p++);
  return ~c;
}
bool crc32c_hw_available() {
  return __builtin_cpu_supports("sse4.2");
}
#else
uint32_t crc32c_hw(uint32_t seed, const uint8_t* p, size_t n) {
  return crc32c_sw(seed, p, n);
}
bool crc32c_hw_available() { return false; }
#endif

const bool kCrc32cHw = crc32c_hw_available();

uint32_t crc32c_update(uint32_t seed, const uint8_t* p, size_t n) {
  return kCrc32cHw ? crc32c_hw(seed, p, n) : crc32c_sw(seed, p, n);
}

// ------------------------------------------------------------ wire scan
constexpr int kVarint = 0;
constexpr int kFixed64 = 1;
constexpr int kLen = 2;
constexpr int kFixed32 = 5;

struct Slice {
  const uint8_t* p;
  size_t n;
  size_t pos = 0;
  bool done() const { return pos >= n; }
};

// Decode one base-128 varint; false on truncation/overlength (parity
// with wire.read_varint's 64-bit cap).
bool read_varint(Slice& s, uint64_t& out) {
  uint64_t result = 0;
  int shift = 0;
  while (true) {
    if (s.pos >= s.n) return false;
    uint8_t b = s.p[s.pos++];
    result |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      out = result;
      return true;
    }
    shift += 7;
    if (shift > 63) return false;
  }
}

// One field header + payload. For LEN fields `val`/`len` hold the bytes;
// for varint/fixed the numeric value lands in `num`. Returns false on
// malformed input (the caller surfaces it as a WireError analogue).
struct Field {
  uint32_t no;
  int wt;
  uint64_t num;
  const uint8_t* val;
  size_t len;
};

bool next_field(Slice& s, Field& f) {
  uint64_t tag;
  if (!read_varint(s, tag)) return false;
  f.no = uint32_t(tag >> 3);
  f.wt = int(tag & 0x7);
  if (f.no == 0) return false;
  switch (f.wt) {
    case kVarint:
      return read_varint(s, f.num);
    case kFixed64:
      if (s.pos + 8 > s.n) return false;
      std::memcpy(&f.num, s.p + s.pos, 8);  // little-endian hosts only
      s.pos += 8;
      return true;
    case kFixed32: {
      if (s.pos + 4 > s.n) return false;
      uint32_t v;
      std::memcpy(&v, s.p + s.pos, 4);
      s.pos += 4;
      f.num = v;
      return true;
    }
    case kLen: {
      uint64_t ln;
      if (!read_varint(s, ln)) return false;
      if (ln > s.n - s.pos) return false;
      f.val = s.p + s.pos;
      f.len = size_t(ln);
      s.pos += size_t(ln);
      return true;
    }
    default:
      return false;  // SGROUP/EGROUP etc: wire.py raises on these
  }
}

bool numeric(const Field& f) {
  return f.wt == kVarint || f.wt == kFixed64 || f.wt == kFixed32;
}

struct Str {
  const uint8_t* p = nullptr;
  size_t n = 0;
  bool set = false;
};

// --- the Python decoders' field-slot semantics (see file header) -----

// submessage-list: every occurrence must be LEN. ok=false ⇒ caller
// errors; descend=true ⇒ this occurrence is a submessage to parse.
bool sub_list(const Field& f, bool& descend) {
  descend = (f.wt == kLen);
  return f.wt == kLen;
}

// submessage-first: `claimed` is the slot. Sets descend for a LEN first
// occurrence; numeric 0 claims the slot as "absent"; numeric nonzero
// is an error.
bool sub_first(const Field& f, bool& claimed, bool& descend) {
  descend = false;
  if (claimed) return true;
  claimed = true;
  if (f.wt == kLen) {
    descend = true;
    return true;
  }
  return numeric(f) && f.num == 0;
}

// bytes-first: LEN claims with the value; numeric 0 claims with the
// default; numeric nonzero errors.
bool bytes_first(const Field& f, Str& out) {
  if (out.set) return true;
  if (f.wt == kLen) {
    out.p = f.val;
    out.n = f.len;
    out.set = true;
    return true;
  }
  if (numeric(f) && f.num == 0) {
    out.set = true;  // claimed, stays at default (empty)
    return true;
  }
  return false;
}

// numeric-first: numeric claims with the value; nonempty LEN errors
// (int(bytes) of non-digits raises). Empty LEN depends on the Python
// call-site shape: `int(first(...) or 0)` treats b"" as falsy → default
// (empty_len_ok), while bare `float(first(...))` raises on b"" —
// callers pass empty_len_ok=false to model the latter.
bool numeric_first(const Field& f, bool& claimed, uint64_t& out,
                   bool empty_len_ok = true) {
  if (claimed) return true;
  if (numeric(f)) {
    claimed = true;
    out = f.num;
    return true;
  }
  if (empty_len_ok && f.wt == kLen && f.len == 0) {
    claimed = true;
    return true;
  }
  return false;
}

bool str_eq(const Str& s, const char* lit) {
  size_t n = std::strlen(lit);
  return s.set && s.n == n && std::memcmp(s.p, lit, n) == 0;
}

// Length-precomputed variant for the monitored-key compares in the
// span hot loop (strlen per attribute per key was measurable at the
// flush scale the pool runs).
inline bool str_eq_n(const Str& s, const char* lit, size_t n) {
  return s.set && s.n == n && std::memcmp(s.p, lit, n) == 0;
}

// AnyValue{string_value=1}: first occurrence of a LEN field 1 is the
// string; any other type/field is ignored (otlp._anyvalue_str returns
// None for non-string values, raising nothing).
bool anyvalue_str(const uint8_t* p, size_t n, Str& out) {
  Slice s{p, n};
  Field f;
  while (!s.done()) {
    if (!next_field(s, f)) return false;
    if (f.no == 1 && f.wt == kLen && !out.set) {
      out.p = f.val;
      out.n = f.len;
      out.set = true;
    }
  }
  return true;
}

// KeyValue{key=1, value=2}. Mirrors otlp._attrs_to_dict exactly: the
// pair only materialises when the key is truthy, the value is LEN, and
// the AnyValue holds a string; a truthy *numeric* key is an error only
// in that same case (Python reaches key.decode() only then).
bool keyvalue(const uint8_t* p, size_t n, Str& key, Str& val) {
  Slice s{p, n};
  Field f;
  Str raw_val;
  bool key_numeric_bad = false;
  bool key_claimed = false;
  while (!s.done()) {
    if (!next_field(s, f)) return false;
    if (f.no == 1 && !key_claimed) {
      key_claimed = true;
      if (f.wt == kLen) {
        key.p = f.val;
        key.n = f.len;
        key.set = true;
      } else if (numeric(f) && f.num != 0) {
        key_numeric_bad = true;  // only fatal if a string value exists
      }
    } else if (f.no == 2 && f.wt == kLen && !raw_val.set) {
      raw_val.p = f.val;
      raw_val.n = f.len;
      raw_val.set = true;
    }
  }
  if (raw_val.set && !anyvalue_str(raw_val.p, raw_val.n, val)) return false;
  if (val.set && key_numeric_bad) return false;  // int.decode() analogue
  if (!(key.set && key.n > 0)) val.set = false;  // falsy key: pair skipped
  return true;
}

// First 8 bytes little-endian, zero-padded — matches
// tensorize._pack's `bytes(trace_id[:8]).ljust(8, b"\0")`.
uint64_t key8(const uint8_t* p, size_t n) {
  uint64_t v = 0;
  std::memcpy(&v, p, n < 8 ? n : 8);
  return v;
}

constexpr int kMaxAttrKeys = 16;

}  // namespace

namespace {

// ---------------------------------------------------------- pass 1: scan
// One structural-index entry per span record (the pass-1 product).
struct SpanRef {
  const uint8_t* p;  // span submessage bytes
  uint32_t len;
  int32_t svc;      // batch-wide resource-spans entry index
  int32_t payload;  // payload index within the batch (verdict mapping)
};

// Structural sweep of one ExportTraceServiceRequest: validates the
// framing levels (top-level fields, ResourceSpans incl. the resource's
// KeyValues, ScopeSpans, span headers), APPENDS service names to the
// shared name buffer, and emits one boundary record per span WITHOUT
// descending into span interiors — pass 2's job. The sweep is branch-
// light on purpose: span bodies (the bulk of the bytes) are skipped by
// their LEN header, so scan throughput is set by varint-walk speed,
// not field semantics. Returns the new total span count or a negative
// error code (-1 malformed framing, -2 span capacity, -3 name/entry
// capacity).
template <typename EmitSpan>
int scan_request(const uint8_t* buf, size_t len, int payload_idx,  //
                 char* svc_buf, size_t svc_buf_cap,                //
                 int32_t* svc_len, int rs_cap,                     //
                 int* n_svc_io, size_t* svc_pos_io,                //
                 int n_spans, int span_cap, EmitSpan&& emit) {
  int n_svc = *n_svc_io;
  size_t svc_pos = *svc_pos_io;
  Slice top{buf, len};
  Field rs_f;
  bool descend;
  while (!top.done()) {
    if (!next_field(top, rs_f)) return -1;
    if (rs_f.no != 1) continue;  // unknown top-level fields: skipped
    if (!sub_list(rs_f, descend)) return -1;

    // ResourceSpans{resource=1 (first), scope_spans=2 (repeated)}.
    // Sweep A: the resource can appear after scope_spans on the wire;
    // the Python decoder's two-phase scan is order-independent, so
    // resolve the service name before emitting this block's spans.
    Str svc_name;
    bool have_name = false;
    bool resource_claimed = false;
    Slice rs{rs_f.val, rs_f.len};
    Field f;
    while (!rs.done()) {
      if (!next_field(rs, f)) return -1;
      if (f.no == 1) {
        if (!sub_first(f, resource_claimed, descend)) return -1;
        if (!descend) continue;
        Slice res{f.val, f.len};
        Field rf;
        while (!res.done()) {
          if (!next_field(res, rf)) return -1;
          if (rf.no == 1) {  // repeated KeyValue (submessage-list)
            if (!sub_list(rf, descend)) return -1;
            Str key, val;
            if (!keyvalue(rf.val, rf.len, key, val)) return -1;
            // Last occurrence wins (dict-assignment semantics).
            if (val.set && str_eq(key, "service.name")) {
              svc_name = val;
              have_name = true;
            }
          }
        }
      }
    }
    if (n_svc >= rs_cap) return -3;
    if (svc_pos + svc_name.n > svc_buf_cap) return -3;
    if (svc_name.n) std::memcpy(svc_buf + svc_pos, svc_name.p, svc_name.n);
    svc_pos += svc_name.n;
    svc_len[n_svc++] = have_name ? int32_t(svc_name.n) : -1;

    // Sweep B: record span-record boundaries (no interior parse).
    rs = Slice{rs_f.val, rs_f.len};
    while (!rs.done()) {
      if (!next_field(rs, f)) return -1;
      if (f.no != 2) continue;  // ScopeSpans (submessage-list)
      if (!sub_list(f, descend)) return -1;
      Slice ss{f.val, f.len};
      Field sf;
      while (!ss.done()) {
        if (!next_field(ss, sf)) return -1;
        if (sf.no != 2) continue;  // Span (submessage-list)
        if (!sub_list(sf, descend)) return -1;
        if (n_spans >= span_cap) return -2;
        emit(sf.val, sf.len, n_svc - 1, payload_idx, n_spans);
        ++n_spans;
      }
    }
  }
  *n_svc_io = n_svc;
  *svc_pos_io = svc_pos;
  return n_spans;
}

// ------------------------------------------------------- pass 2: extract
// Extract ONE pass-1 span record into output row `r`. Field slot
// semantics are identical to the retired single-pass walk (the file
// header's four categories); rows are independent, which is what makes
// the extraction shardable across threads. Returns false on a
// malformed span interior (the caller maps it to the owning payload's
// -1 verdict).
bool extract_span(const uint8_t* p, size_t n, int32_t svc, int r,  //
                  const char* const* attr_keys,                    //
                  const size_t* key_lens, int n_keys,              //
                  Str* attr_val,                                   //
                  float* duration_us, uint64_t* trace_key,         //
                  uint8_t* is_error, uint32_t* attr_crc,           //
                  uint8_t* attr_present, int32_t* svc_idx,         //
                  int32_t* event_count, uint8_t* has_exception) {
  Str tid;
  uint64_t tid_num = 0;
  bool tid_is_num = false;
  uint64_t start = 0, end = 0;
  bool start_claimed = false, end_claimed = false;
  bool err = false;
  bool status_claimed = false;
  int32_t n_events = 0;
  bool exc = false;
  // attr_val is the CALLER's per-thread slot array (hoisted out of
  // the span loop: value-initializing all kMaxAttrKeys Str slots per
  // span costs more memory traffic than scanning the span itself);
  // only the first n_keys slots are live and reset here.
  for (int k = 0; k < n_keys; ++k) attr_val[k] = Str{};
  bool descend;

  Slice sp{p, n};
  Field pf;
  while (!sp.done()) {
    if (!next_field(sp, pf)) return false;
    switch (pf.no) {
      case 1:  // trace_id: first; bytes OR numeric both accepted
               // (SpanRecord.trace_id is bytes | int)
        if (!tid.set && !tid_is_num) {
          if (pf.wt == kLen) {
            tid.p = pf.val;
            tid.n = pf.len;
            tid.set = true;
          } else if (numeric(pf)) {
            tid_num = pf.num;
            tid_is_num = true;
          }
        }
        break;
      case 7:  // start_time_unix_nano (numeric-first)
        if (!numeric_first(pf, start_claimed, start)) return false;
        break;
      case 8:  // end_time_unix_nano (numeric-first)
        if (!numeric_first(pf, end_claimed, end)) return false;
        break;
      case 9: {  // attributes: repeated KeyValue (submessage-list)
        if (!sub_list(pf, descend)) return false;
        Str key, val;
        if (!keyvalue(pf.val, pf.len, key, val)) return false;
        if (val.set)
          for (int k = 0; k < n_keys; ++k)
            if (str_eq_n(key, attr_keys[k], key_lens[k])) attr_val[k] = val;
        break;
      }
      case 11: {  // events: repeated Event{time_unix_nano=1,
                  // name=2, attributes=3} (submessage-list).
        if (!sub_list(pf, descend)) return false;
        Slice ev{pf.val, pf.len};
        Field ef;
        Str ev_name;
        bool name_claimed = false;
        bool t_claimed = false;
        uint64_t t_ns = 0;
        while (!ev.done()) {
          if (!next_field(ev, ef)) return false;
          if (ef.no == 1) {  // time (numeric-first, empty-LEN ok)
            if (!numeric_first(ef, t_claimed, t_ns)) return false;
          } else if (ef.no == 2 && !name_claimed) {
            // Python: wire.first(ev, 2) then isinstance(bytes) —
            // a numeric first occurrence claims the slot with an
            // EMPTY name, never an error.
            name_claimed = true;
            if (ef.wt == kLen) {
              ev_name.p = ef.val;
              ev_name.n = ef.len;
              ev_name.set = true;
            }
          } else if (ef.no == 3) {  // attributes (submessage-list)
            if (!sub_list(ef, descend)) return false;
            Str key, val;
            if (!keyvalue(ef.val, ef.len, key, val)) return false;
          }
        }
        ++n_events;
        // tensorize.EXCEPTION_EVENT_NAMES, exact literals: the
        // semconv name, checkout's "error", ad's "Error".
        if (str_eq(ev_name, "exception") || str_eq(ev_name, "error") ||
            str_eq(ev_name, "Error"))
          exc = true;
        break;
      }
      case 15: {  // Status{code=3} (submessage-first)
        if (!sub_first(pf, status_claimed, descend)) return false;
        if (!descend) break;
        Slice st{pf.val, pf.len};
        Field stf;
        bool code_claimed = false;
        uint64_t code = 0;
        while (!st.done()) {
          if (!next_field(st, stf)) return false;
          if (stf.no == 3 && !numeric_first(stf, code_claimed, code))
            return false;
        }
        err = (code == 2);  // STATUS_CODE_ERROR
        break;
      }
      default:
        break;  // unknown: skipped, not descended
    }
  }

  duration_us[r] = end > start ? float(double(end - start) / 1000.0) : 0.0f;
  trace_key[r] = tid_is_num ? tid_num : key8(tid.p, tid.n);
  is_error[r] = err ? 1 : 0;
  uint32_t crc = 0;
  uint8_t present = 0;
  for (int k = 0; k < n_keys; ++k)
    if (attr_val[k].set) {  // priority order: first hit wins
      crc = crc32(attr_val[k].p, attr_val[k].n);
      present = 1;
      break;
    }
  attr_crc[r] = crc;
  attr_present[r] = present;
  svc_idx[r] = svc;
  event_count[r] = n_events;
  has_exception[r] = exc ? 1 : 0;
  return true;
}

void key_lengths(const char* const* attr_keys, int n_keys, size_t* out) {
  for (int k = 0; k < n_keys; ++k) out[k] = std::strlen(attr_keys[k]);
}

// Minimum spans per extraction shard: below this the std::thread
// spawn/join overhead exceeds the parse work a shard would cover.
constexpr int kMinShardSpans = 512;

double seconds_since(std::chrono::steady_clock::time_point t0) {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now() - t0)
      .count();
}

}  // namespace

extern "C" {

// Error codes (negative returns).
// -1 malformed wire data; -2 record capacity exceeded; -3 service-name
// buffer exceeded; -4 too many monitored keys.

// Decode an ExportTraceServiceRequest into columns. One output row per
// span, in document order. `svc_idx[i]` indexes the i-th record's
// resource-spans entry; service names are written back-to-back into
// `svc_buf` with per-entry byte lengths in `svc_len` (length -1 ⇒ the
// resource had no service.name — distinct from a present-but-empty
// name, which the record path interns as ""). Monitored attribute keys
// come in priority order; the chosen value's CRC32 goes to attr_crc
// with attr_present=1. Span events (field 11; the reference services
// narrate spans with them — checkout main.go:270-294) surface as a
// per-span count plus a has_exception flag (event named "exception",
// "error", or "Error" — all three literals of
// tensorize.EXCEPTION_EVENT_NAMES: the OTel semconv name, checkout's
// lowercase variant, and the ad service's capitalized one), the
// error-cause evidence the detector folds into its error lane.
int otd_decode_otlp(const uint8_t* buf, size_t len,              //
                    const char* const* attr_keys, int n_keys,    //
                    int cap,                                     //
                    float* duration_us, uint64_t* trace_key,     //
                    uint8_t* is_error, uint32_t* attr_crc,       //
                    uint8_t* attr_present, int32_t* svc_idx,     //
                    int32_t* event_count, uint8_t* has_exception,  //
                    char* svc_buf, size_t svc_buf_cap,           //
                    int32_t* svc_len, int rs_cap,                //
                    int32_t* n_services) {
  if (n_keys > kMaxAttrKeys) return -4;
  int n_svc = 0;
  size_t svc_pos = 0;
  std::vector<SpanRef> spans;
  spans.reserve(len / 64 + 16);
  int n_rec = scan_request(
      buf, len, 0, svc_buf, svc_buf_cap, svc_len, rs_cap, &n_svc, &svc_pos,
      0, cap,
      [&](const uint8_t* p, size_t n, int svc, int payload, int row) {
        (void)payload;
        (void)row;
        spans.push_back(SpanRef{p, uint32_t(n), int32_t(svc), 0});
      });
  if (n_rec < 0) return n_rec;
  size_t key_lens[kMaxAttrKeys];
  key_lengths(attr_keys, n_keys, key_lens);
  Str attr_val[kMaxAttrKeys];
  for (int r = 0; r < n_rec; ++r) {
    const SpanRef& s = spans[r];
    if (!extract_span(s.p, s.len, s.svc, r, attr_keys, key_lens, n_keys,
                      attr_val, duration_us, trace_key, is_error, attr_crc,
                      attr_present, svc_idx, event_count, has_exception))
      return -1;
  }
  *n_services = n_svc;
  return n_rec;
}

// Pass 1 alone: structural scan of one ExportTraceServiceRequest into
// a caller-owned span index (`span_off`/`span_len` relative to `buf`,
// `span_svc` into the resource-spans list) — the raw-scanner surface
// `make decodebench` isolates, and the boundary oracle the fuzz suite
// truncates against. Returns the span count or -1/-2/-3.
int otd_scan_otlp(const uint8_t* buf, size_t len,                //
                  int32_t* span_off, int32_t* span_len,          //
                  int32_t* span_svc, int span_cap,               //
                  char* svc_buf, size_t svc_buf_cap,             //
                  int32_t* svc_len, int rs_cap,                  //
                  int32_t* n_services) {
  int n_svc = 0;
  size_t svc_pos = 0;
  int n = scan_request(
      buf, len, 0, svc_buf, svc_buf_cap, svc_len, rs_cap, &n_svc, &svc_pos,
      0, span_cap,
      [&](const uint8_t* p, size_t sn, int svc, int payload, int row) {
        (void)payload;
        span_off[row] = int32_t(p - buf);
        span_len[row] = int32_t(sn);
        span_svc[row] = int32_t(svc);
      });
  if (n < 0) return n;
  *n_services = n_svc;
  return n;
}

// Pass 2 alone: extract a caller-provided span index (from
// `otd_scan_otlp`) into columns — the other half of the raw-scanner
// microbench. Index bounds are re-validated against `len` so a stale
// or corrupted index can never read outside the payload. Returns
// `n_spans` or -1.
int otd_extract_otlp(const uint8_t* buf, size_t len,             //
                     const int32_t* span_off, const int32_t* span_len,
                     const int32_t* span_svc, int n_spans,       //
                     const char* const* attr_keys, int n_keys,   //
                     float* duration_us, uint64_t* trace_key,    //
                     uint8_t* is_error, uint32_t* attr_crc,      //
                     uint8_t* attr_present, int32_t* svc_idx,    //
                     int32_t* event_count, uint8_t* has_exception) {
  if (n_keys > kMaxAttrKeys) return -4;
  size_t key_lens[kMaxAttrKeys];
  key_lengths(attr_keys, n_keys, key_lens);
  Str attr_val[kMaxAttrKeys];
  for (int r = 0; r < n_spans; ++r) {
    size_t off = size_t(span_off[r]);
    size_t sn = size_t(span_len[r]);
    if (span_off[r] < 0 || span_len[r] < 0 || off + sn > len) return -1;
    if (!extract_span(buf + off, sn, span_svc[r], r, attr_keys, key_lens,
                      n_keys, attr_val, duration_us, trace_key, is_error,
                      attr_crc, attr_present, svc_idx, event_count,
                      has_exception))
      return -1;
  }
  return n_spans;
}

// Batched two-pass decode: `n_payloads` independent
// ExportTraceServiceRequests into ONE set of output columns (rows
// append across payloads in argument order; `svc_idx` indexes the
// shared, batch-wide resource-spans list). One ctypes round trip —
// during which ctypes has dropped the GIL — amortizes over the whole
// coalesced flush, which is the ingest pool's (runtime/ingest_pool.py)
// per-flush cost model.
//
// Pass 1 scans every payload serially (boundary work only), building
// the combined span index + service table; pass 2 extracts the index
// into the columns — sharded across up to `n_threads` OS threads at
// span-record boundaries (including mid-payload) whenever the batch
// carries at least `shard_min_bytes` of payload and enough spans to
// amortize a thread spawn. Because pass 1 fixed every row/service slot
// up front, shard writes are disjoint and need no synchronization.
//
// Per-payload verdicts land in `payload_rows`: the row count this
// payload contributed, or -1 when IT was malformed — a poison request
// never fails its batchmates (each receiver still answers 400 for
// exactly the bad request, the serial path's verdict). A pass-1
// failure contributes nothing (its partial index rolls back); a pass-2
// failure is compacted out by the single-threaded epilogue. Capacity
// exhaustion (-2/-3) aborts the whole call: the caller regrows its
// pooled buffers and retries everything. `scan_s`/`extract_s` (either
// may be null) report per-pass wall seconds for the phase histograms.
int otd_decode_otlp_many(const uint8_t* const* bufs, const size_t* lens,
                         int n_payloads,                          //
                         const char* const* attr_keys, int n_keys,  //
                         int cap,                                  //
                         float* duration_us, uint64_t* trace_key,  //
                         uint8_t* is_error, uint32_t* attr_crc,    //
                         uint8_t* attr_present, int32_t* svc_idx,  //
                         int32_t* event_count, uint8_t* has_exception,  //
                         char* svc_buf, size_t svc_buf_cap,        //
                         int32_t* svc_len, int rs_cap,             //
                         int32_t* n_services, int32_t* payload_rows,
                         int n_threads, long long shard_min_bytes,
                         double* scan_s, double* extract_s) {
  if (n_keys > kMaxAttrKeys) return -4;
  auto t0 = std::chrono::steady_clock::now();

  // ---- pass 1: structural scan, batch-wide index --------------------
  // The index rides a thread_local vector: each pool worker's calls
  // reuse one high-watermark allocation instead of paying a
  // payload-sized malloc/free per flush (the same retention policy as
  // the Python-side DecodeScratch freelist). clear() keeps capacity.
  static thread_local std::vector<SpanRef> spans_tls;
  std::vector<SpanRef>& spans = spans_tls;
  spans.clear();
  size_t total_bytes = 0;
  for (int i = 0; i < n_payloads; ++i) total_bytes += lens[i];
  if (spans.capacity() < total_bytes / 64 + 16)
    spans.reserve(total_bytes / 64 + 16);
  // Per-payload bookkeeping for the epilogue: row/service/name-byte
  // ranges as committed by pass 1 (rolled-back payloads collapse to
  // empty ranges).
  std::vector<int> row0(n_payloads + 1), svc0(n_payloads + 1);
  std::vector<size_t> pos0(n_payloads + 1);
  int n_svc = 0;
  size_t svc_pos = 0;
  bool any_bad = false;
  auto emit = [&](const uint8_t* p, size_t n, int svc, int payload,
                  int row) {
    (void)row;
    spans.push_back(SpanRef{p, uint32_t(n), int32_t(svc), int32_t(payload)});
  };
  for (int i = 0; i < n_payloads; ++i) {
    row0[i] = int(spans.size());
    svc0[i] = n_svc;
    pos0[i] = svc_pos;
    int r = scan_request(bufs[i], lens[i], i, svc_buf, svc_buf_cap,
                         svc_len, rs_cap, &n_svc, &svc_pos,
                         int(spans.size()), cap, emit);
    if (r == -2 || r == -3) return r;  // shared capacity: retry all
    if (r < 0) {
      // Malformed framing: roll back this payload's partial appends
      // (append-only writes — restoring the counters IS the rollback).
      payload_rows[i] = -1;
      spans.resize(size_t(row0[i]));
      n_svc = svc0[i];
      svc_pos = pos0[i];
      any_bad = true;
    } else {
      payload_rows[i] = r - row0[i];
    }
  }
  row0[n_payloads] = int(spans.size());
  svc0[n_payloads] = n_svc;
  pos0[n_payloads] = svc_pos;
  int n_rec = int(spans.size());
  if (scan_s) *scan_s = seconds_since(t0);
  auto t1 = std::chrono::steady_clock::now();

  // ---- pass 2: extraction, sharded at span-record boundaries --------
  size_t key_lens[kMaxAttrKeys];
  key_lengths(attr_keys, n_keys, key_lens);
  const size_t n_pl = size_t(n_payloads);
  std::vector<std::atomic<int>> bad(n_pl);
  for (auto& b : bad) b.store(0, std::memory_order_relaxed);
  std::atomic<bool> bad_seen{false};
  auto extract_range = [&](int lo, int hi) {
    Str attr_val[kMaxAttrKeys];  // per-thread: shards never share it
    for (int k = lo; k < hi; ++k) {
      const SpanRef& s = spans[size_t(k)];
      if (bad[size_t(s.payload)].load(std::memory_order_relaxed))
        continue;  // owning payload already condemned: skip the work
      if (!extract_span(s.p, s.len, s.svc, k, attr_keys, key_lens, n_keys,
                        attr_val, duration_us, trace_key, is_error,
                        attr_crc, attr_present, svc_idx, event_count,
                        has_exception)) {
        bad[size_t(s.payload)].store(1, std::memory_order_relaxed);
        bad_seen.store(true, std::memory_order_relaxed);
      }
    }
  };
  int shards = 1;
  if (n_threads > 1 && (long long)total_bytes >= shard_min_bytes)
    shards = n_threads;
  if (shards > n_rec / kMinShardSpans)
    shards = n_rec / kMinShardSpans;  // don't spawn for trivial work
  if (shards <= 1) {
    extract_range(0, n_rec);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(size_t(shards - 1));
    int per = (n_rec + shards - 1) / shards;
    for (int t = 1; t < shards; ++t)
      pool.emplace_back(extract_range, t * per,
                        t * per + per < n_rec ? t * per + per : n_rec);
    extract_range(0, per < n_rec ? per : n_rec);
    for (auto& th : pool) th.join();
  }

  // ---- epilogue: compact condemned payloads back out ----------------
  if (bad_seen.load(std::memory_order_relaxed)) any_bad = true;
  if (any_bad && n_rec) {
    int wr = 0;        // write row
    int wsvc = 0;      // write service entry
    size_t wpos = 0;   // write name byte
    for (int i = 0; i < n_payloads; ++i) {
      int r0 = row0[i], cnt = row0[i + 1] - row0[i];
      int s0 = svc0[i], scnt = svc0[i + 1] - svc0[i];
      size_t p0 = pos0[i], pbytes = pos0[i + 1] - pos0[i];
      if (payload_rows[i] < 0) continue;  // pass-1 bad: empty ranges
      if (bad[size_t(i)].load(std::memory_order_relaxed)) {
        payload_rows[i] = -1;  // pass-2 bad: drop rows + services
        continue;
      }
      payload_rows[i] = cnt;
      int svc_shift = s0 - wsvc;
      if (wr != r0 || svc_shift) {
        std::memmove(duration_us + wr, duration_us + r0,
                     size_t(cnt) * sizeof(float));
        std::memmove(trace_key + wr, trace_key + r0,
                     size_t(cnt) * sizeof(uint64_t));
        std::memmove(is_error + wr, is_error + r0, size_t(cnt));
        std::memmove(attr_crc + wr, attr_crc + r0,
                     size_t(cnt) * sizeof(uint32_t));
        std::memmove(attr_present + wr, attr_present + r0, size_t(cnt));
        for (int k = 0; k < cnt; ++k)
          svc_idx[wr + k] = svc_idx[r0 + k] - svc_shift;
        std::memmove(event_count + wr, event_count + r0,
                     size_t(cnt) * sizeof(int32_t));
        std::memmove(has_exception + wr, has_exception + r0, size_t(cnt));
        std::memmove(svc_len + wsvc, svc_len + s0,
                     size_t(scnt) * sizeof(int32_t));
        std::memmove(svc_buf + wpos, svc_buf + p0, pbytes);
      }
      wr += cnt;
      wsvc += scnt;
      wpos += pbytes;
    }
    n_rec = wr;
    n_svc = wsvc;
  }
  if (extract_s) *extract_s = seconds_since(t1);
  *n_services = n_svc;
  return n_rec;
}

// USD-normalization table for the order value lane, installed from
// Python (currency_data.EUR_RATES) via otd_set_order_rates. Codes are
// fixed 8-byte NUL-padded entries; unknown codes pass through at 1.0
// (kafka_orders.to_usd_factor contract).
static struct OrderRate {
  char code[8];
  double factor;
} g_order_rates[64];
static int g_n_order_rates = 0;

void otd_set_order_rates(const char* codes, const double* factors, int n) {
  if (n > 64) n = 64;
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < 8; ++j) g_order_rates[i].code[j] = codes[i * 8 + j];
    g_order_rates[i].factor = factors[i];
  }
  g_n_order_rates = n;
}

static double order_rate_lookup(const uint8_t* p, size_t len) {
  if (len == 0 || len > 8) return 1.0;
  for (int i = 0; i < g_n_order_rates; ++i) {
    const char* c = g_order_rates[i].code;
    size_t clen = 0;
    while (clen < 8 && c[clen]) ++clen;
    if (clen != len) continue;
    bool eq = true;
    for (size_t j = 0; j < len; ++j)
      if ((uint8_t)c[j] != p[j]) { eq = false; break; }
    if (eq) return g_order_rates[i].factor;
  }
  return 1.0;
}

// Decode a batch of OrderResult payloads (one Kafka message each) into
// the detector's order-record columns: order-id key (first 8 bytes of
// the id string), shipping cost USD-normalized via the installed rate
// table (the value lane), and the CRC of the first *non-empty* product
// id (heavy-hitter attribute — kafka_orders.decode_order skips falsy
// ids). Mirrors decode_order + order_to_record, including error
// verdicts.
int otd_decode_orders(const uint8_t* const* bufs, const size_t* lens,
                      int n,                                     //
                      float* value_units, uint64_t* order_key,   //
                      uint32_t* attr_crc) {
  for (int i = 0; i < n; ++i) {
    Slice top{bufs[i], lens[i]};
    Field f;
    bool descend;
    Str order_id, tracking, first_product, currency;
    bool money_claimed = false;
    uint64_t units = 0, nanos = 0;
    bool units_claimed = false, nanos_claimed = false;
    while (!top.done()) {
      if (!next_field(top, f)) return -1;
      switch (f.no) {
        case 1:  // order_id (bytes-first)
          if (!bytes_first(f, order_id)) return -1;
          break;
        case 2:  // shipping_tracking_id (bytes-first; decoded by Python
                 // even though unused here, so verdicts must match)
          if (!bytes_first(f, tracking)) return -1;
          break;
        case 3: {  // shipping_cost Money{units=2, nanos=3}
          if (!sub_first(f, money_claimed, descend)) return -1;
          if (!descend) break;
          Slice m{f.val, f.len};
          Field mf;
          while (!m.done()) {
            if (!next_field(m, mf)) return -1;
            if (mf.no == 1) {
              // currency_code: bytes-first, EXCEPT Python's
              // isinstance(code, bytes) guard (_money_units) maps a
              // numeric value to the USD default instead of raising —
              // so a nonzero varint claims-with-default here, unlike
              // every other bytes field in this decoder.
              if (!bytes_first(mf, currency)) {
                if (!numeric(mf)) return -1;
                currency.set = true;  // claimed, empty → USD factor
              }
            } else if (mf.no == 2) {
              // float(first(...)) raises on b"" — no empty-LEN default.
              if (!numeric_first(mf, units_claimed, units, false))
                return -1;
            } else if (mf.no == 3) {
              if (!numeric_first(mf, nanos_claimed, nanos, false))
                return -1;
            }
          }
          break;
        }
        case 5: {  // items: OrderItem{item=1 CartItem{product_id=1,
                   // quantity=2}} (submessage-list)
          if (!sub_list(f, descend)) return -1;
          Slice it{f.val, f.len};
          Field itf;
          bool cart_claimed = false;
          while (!it.done()) {
            if (!next_field(it, itf)) return -1;
            if (itf.no != 1) continue;
            if (!sub_first(itf, cart_claimed, descend)) return -1;
            if (!descend) continue;
            Slice cart{itf.val, itf.len};
            Field cf;
            Str pid;
            bool qty_claimed = false;
            uint64_t qty = 0;
            while (!cart.done()) {
              if (!next_field(cart, cf)) return -1;
              if (cf.no == 1) {
                if (!bytes_first(cf, pid)) return -1;
              } else if (cf.no == 2) {
                if (!numeric_first(cf, qty_claimed, qty)) return -1;
              }
            }
            // decode_order: `if pid: products.append(...)` — empty ids
            // are skipped, so the first NON-empty product wins.
            if (pid.set && pid.n > 0 && !first_product.set)
              first_product = pid;
          }
          break;
        }
        default:
          break;
      }
    }
    // Parity with wire.py: varints decode unsigned, and _money_units
    // floats the raw value (negative money is producer error; both
    // sides treat it identically). USD normalization matches
    // order_to_record: float32(float64 value × float64 factor).
    double factor = currency.set ? order_rate_lookup(currency.p, currency.n)
                                 : order_rate_lookup((const uint8_t*)"USD", 3);
    value_units[i] = float((double(units) + double(nanos) * 1e-9) * factor);
    order_key[i] =
        order_id.set && order_id.n ? key8(order_id.p, order_id.n) : 0;
    attr_crc[i] =
        first_product.set ? crc32(first_product.p, first_product.n) : 0;
  }
  return n;
}

// CRC32 of one buffer — exposed so Python-side fallbacks/tests can
// assert the hash contract without zlib.
uint32_t otd_crc32(const uint8_t* p, size_t n) { return crc32(p, n); }

// CRC-32C with a running seed (0 to start): the frame checksum
// (runtime/frame.py). Called with the GIL released like every foreign
// call here — column verify overlaps other workers' Python.
uint32_t otd_crc32c(const uint8_t* p, size_t n, uint32_t seed) {
  return crc32c_update(seed, p, n);
}

}  // extern "C"
