// Native shipping kernel: quote money math + tracking-id generation.
//
// The reference keeps shipping native (its shipping service is Rust —
// /root/reference/src/shipping/src/shipping_service/quote.rs:15-46
// builds a Quote from the quote service's float; tracking.rs:8-10 mints
// tracking ids); this framework keeps the same polyglot contract:
// services/shipping.py is the facade, the arithmetic lives here, and a
// pure-Python fallback keeps the capability dependency-free.
//
// Semantics pinned to services/shipping.py + services/money.py by
// tests/test_native_shipping.py:
//   - quote total = round(per_item * count, 2) — Python round():
//     ties-to-even at 2 decimal places, via scaling to cents;
//   - Money split = Money.from_float: units = trunc, nanos =
//     round((value-units)*1e9) with carry normalisation;
//   - tracking id = RFC 4122 UUID v5 (SHA-1, URL namespace) over the
//     trace-id hex string — byte-identical to Python's
//     uuid.uuid5(uuid.NAMESPACE_URL, name).
//
// Build: g++ -O3 -shared -fPIC (no dependencies); loaded via ctypes by
// runtime/native.py.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

constexpr int64_t kNanosPerUnit = 1000000000;

// ---- minimal SHA-1 (RFC 3174) for UUID v5 ---------------------------

struct Sha1 {
  uint32_t h[5] = {0x67452301u, 0xEFCDAB89u, 0x98BADCFEu, 0x10325476u,
                   0xC3D2E1F0u};
  uint8_t block[64];
  uint64_t total = 0;
  size_t fill = 0;

  static uint32_t rol(uint32_t v, int s) { return (v << s) | (v >> (32 - s)); }

  void process(const uint8_t* p) {
    uint32_t w[80];
    for (int i = 0; i < 16; ++i) {
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    }
    for (int i = 16; i < 80; ++i) {
      w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4];
    for (int i = 0; i < 80; ++i) {
      uint32_t f, k;
      if (i < 20) {
        f = (b & c) | (~b & d);
        k = 0x5A827999u;
      } else if (i < 40) {
        f = b ^ c ^ d;
        k = 0x6ED9EBA1u;
      } else if (i < 60) {
        f = (b & c) | (b & d) | (c & d);
        k = 0x8F1BBCDCu;
      } else {
        f = b ^ c ^ d;
        k = 0xCA62C1D6u;
      }
      uint32_t t = rol(a, 5) + f + e + k + w[i];
      e = d;
      d = c;
      c = rol(b, 30);
      b = a;
      a = t;
    }
    h[0] += a;
    h[1] += b;
    h[2] += c;
    h[3] += d;
    h[4] += e;
  }

  void update(const uint8_t* data, size_t len) {
    total += len;
    while (len) {
      size_t take = 64 - fill < len ? 64 - fill : len;
      std::memcpy(block + fill, data, take);
      fill += take;
      data += take;
      len -= take;
      if (fill == 64) {
        process(block);
        fill = 0;
      }
    }
  }

  void digest(uint8_t out[20]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (fill != 56) update(&zero, 1);
    uint8_t len_be[8];
    for (int i = 0; i < 8; ++i) len_be[i] = uint8_t(bits >> (56 - 8 * i));
    update(len_be, 8);
    for (int i = 0; i < 5; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

// RFC 4122 URL namespace: 6ba7b811-9dad-11d1-80b4-00c04fd430c8.
constexpr uint8_t kUrlNamespace[16] = {0x6b, 0xa7, 0xb8, 0x11, 0x9d, 0xad,
                                       0x11, 0xd1, 0x80, 0xb4, 0x00, 0xc0,
                                       0x4f, 0xd4, 0x30, 0xc8};

void split(int64_t total_nanos, int64_t* out_units, int32_t* out_nanos) {
  int64_t a = total_nanos < 0 ? -total_nanos : total_nanos;
  int64_t u = a / kNanosPerUnit;
  int64_t n = a % kNanosPerUnit;
  if (total_nanos < 0) {
    u = -u;
    n = -n;
  }
  *out_units = u;
  *out_nanos = int32_t(n);
}

}  // namespace

extern "C" {

// Quote total for `count` items at `per_item` cost: round(per_item *
// count, 2) (llrint under round-to-nearest-even == Python round()'s
// 2-dp behaviour via cent scaling), split Money.from_float-style.
// Returns 0, or -1 for invalid count, or -3 when the product leaves
// the safely representable domain.
int otd_quote_money(double per_item, int32_t count, int64_t* out_units,
                    int32_t* out_nanos) {
  if (count < 0) return -1;
  double total = per_item * double(count);
  // The nanos domain is int64: |total| * 1e9 must stay below ~9.22e18,
  // so the guard is on 9.0e9 units (cents * 1e7 is the overflow site).
  if (!(total >= -9.0e9 && total <= 9.0e9)) return -3;
  double cents = total * 100.0;
  int64_t c = llrint(cents);  // ties-to-even, like Python round(x, 2)
  split(c * (kNanosPerUnit / 100), out_units, out_nanos);
  return 0;
}

// RFC 4122 UUID v5 over the URL namespace — byte-identical to Python's
// uuid.uuid5(uuid.NAMESPACE_URL, name). Writes the canonical 36-char
// form (no NUL) into out36. Returns 0.
int otd_tracking_id(const uint8_t* name, int32_t name_len, char* out36) {
  Sha1 sha;
  sha.update(kUrlNamespace, sizeof(kUrlNamespace));
  sha.update(name, size_t(name_len));
  uint8_t d[20];
  sha.digest(d);
  d[6] = uint8_t((d[6] & 0x0F) | 0x50);  // version 5
  d[8] = uint8_t((d[8] & 0x3F) | 0x80);  // RFC 4122 variant
  static const char* hex = "0123456789abcdef";
  int pos = 0;
  for (int i = 0; i < 16; ++i) {
    if (i == 4 || i == 6 || i == 8 || i == 10) out36[pos++] = '-';
    out36[pos++] = hex[d[i] >> 4];
    out36[pos++] = hex[d[i] & 0x0F];
  }
  return 0;
}

}  // extern "C"
