// Native OTLP/HTTP front door — the zero-Python per-payload ingest
// acceptor (ISSUE 19 tentpole; ROADMAP item 3's "front end is the
// wall" seam).
//
// BENCH_r06 showed the pooled decode engine flat at ~6.1-6.2M spans/s
// across 1-4 workers because every byte still traversed the Python
// http.server receiver before on_payload could hand it to the pool.
// This translation unit owns the whole socket→scan path natively:
//
//   accept → HTTP/1.1 framing (Content-Length, 413 oversize cap,
//   chunked rejection) → recv() DIRECTLY into a recycled native body
//   buffer → enqueue an (id, ptr, len) ticket for the Python pump →
//   verdict comes back via otd_fd_respond → canned response bytes on
//   the wire → buffer recycled for the connection's next request.
//
// No Python object is created, copied or touched per payload on this
// path: the pump (runtime/frontdoor.py) drains tickets in BATCHES
// (one GIL-released otd_fd_next call per batch) and the decode scans
// the buffers in place via the existing otd_decode_otlp_many pointer
// ABI. Python keeps only the control plane — the 429/413/400 verdict
// taxonomy decisions that need pipeline state (saturation hints, the
// DecodeTicket per-request decode verdicts), /healthz wiring, metrics
// and graceful drain — exactly the split runtime/otlp.py documents.
//
// Concurrency model: one acceptor thread + one thread per live
// connection (capped; a keep-alive OTLP exporter holds few
// connections, so thread-per-conn buys simplicity without an epoll
// state machine). A connection has AT MOST one request in flight —
// pipelined bytes wait buffered until the current verdict is written,
// which also keeps responses in request order as HTTP/1.1 requires.
//
// Buffer ownership rule (the safety contract with the pump): once a
// ticket is handed out by otd_fd_next, the body buffer belongs to
// Python until otd_fd_respond(id) — the connection thread blocks on
// the verdict condvar and never touches (or recycles) the buffer in
// between. Tickets still queued at stop time are answered 503
// natively, so no buffer is ever abandoned while borrowed.
//
// Thread/GIL contract matches ingest.cc: every export here is called
// through ctypes.CDLL (GIL released for the call's duration), touches
// only raw C memory, and the server's own threads never see a Python
// object.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// Signal kinds a ticket carries (the pump routes on these: traces go
// to the decode pool's pointer path, metrics/logs — scrape-cadence
// traffic, exempt from the saturation gate like runtime/otlp.py —
// take the Python decoders).
constexpr int32_t kKindTraces = 0;
constexpr int32_t kKindMetrics = 1;
constexpr int32_t kKindLogs = 2;

constexpr size_t kMaxHeaderBytes = 8192;
constexpr size_t kReadChunk = 65536;
// Body buffers larger than this shrink back after a small request so
// one fat export doesn't pin its size onto an idle keep-alive conn.
constexpr size_t kShrinkAbove = 1 << 20;

// Native reject counters (the natively-decided verdicts; Python
// counts the pool-verdict rejects itself). Indices are the
// otd_fd_stats layout — keep in sync with runtime/native.py.
enum StatIdx {
  kStatAccepted = 0,
  kStatLiveConns = 1,
  kStatEnqueued = 2,
  kStatPending = 3,
  kStatBadLength = 4,
  kStatOversized = 5,
  kStatChunked = 6,
  kStatTruncated = 7,
  kStatDisconnect = 8,
  kStatOvercap = 9,
  kStatHealth = 10,
  kStatNotFound = 11,
  kStatBytesIn = 12,
  kStatResponded = 13,
  kStatCount = 14,
};

struct Server;

struct Conn {
  Server* srv = nullptr;
  // Atomic because the acceptor's reaper partitions on fd != -1 with
  // no lock; teardown (exchange → shutdown → close) and otd_fd_stop's
  // wake-up shutdown additionally serialize under verdict_mu so stop
  // can never shutdown() an fd number the kernel already recycled.
  std::atomic<int> fd{-1};
  std::thread thread;
  // Buffered reader state: bytes recv'd but not yet consumed (the
  // pipelining holdover).
  std::string rbuf;
  size_t rpos = 0;
  // The connection's single in-flight request.
  std::vector<uint8_t> body;
  int64_t req_id = -1;
  std::mutex verdict_mu;
  std::condition_variable verdict_cv;
  int32_t status = 0;  // 0 = pending
  int32_t retry_after = 0;
  bool done = false;
};

struct Ticket {
  int64_t id;
  int32_t kind;
  const uint8_t* ptr;
  int64_t len;
};

struct Server {
  int listen_fd = -1;
  int port = 0;
  int64_t max_body = 16 << 20;
  int32_t max_conns = 64;
  int64_t header_timeout_ms = 10000;
  std::thread acceptor;

  std::atomic<bool> quiesced{false};
  std::atomic<bool> stopping{false};
  std::atomic<int64_t> next_id{1};
  std::atomic<int64_t> stats[kStatCount]{};

  std::mutex mu;  // guards conns, ready, by_id
  std::condition_variable ready_cv;
  std::vector<Conn*> conns;
  std::deque<Ticket> ready;
  std::map<int64_t, Conn*> by_id;
};

std::mutex g_servers_mu;
std::map<int64_t, Server*> g_servers;
int64_t g_next_handle = 1;

Server* find_server(int64_t h) {
  std::lock_guard<std::mutex> lk(g_servers_mu);
  auto it = g_servers.find(h);
  return it == g_servers.end() ? nullptr : it->second;
}

bool send_all(int fd, const char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = ::send(fd, buf + off, n - off, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    off += static_cast<size_t>(w);
  }
  return true;
}

const char* reason_phrase(int status) {
  switch (status) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 413: return "Content Too Large";
    case 429: return "Too Many Requests";
    case 500: return "Internal Server Error";
    case 503: return "Service Unavailable";
    default: return "OK";
  }
}

// Canned response writer. 200 carries the empty-protobuf success body
// the Python receiver sends (Content-Type + zero-length body); every
// other status is a bare status + Content-Length: 0 (+ optional
// Retry-After / Connection: close) — clients compare status codes,
// not server vanity headers.
bool write_response(int fd, int status, int retry_after, bool close_conn) {
  char buf[256];
  int n = snprintf(buf, sizeof(buf), "HTTP/1.1 %d %s\r\n", status,
                   reason_phrase(status));
  if (status == 200) {
    n += snprintf(buf + n, sizeof(buf) - n,
                  "Content-Type: application/x-protobuf\r\n");
  }
  if (retry_after > 0) {
    n += snprintf(buf + n, sizeof(buf) - n, "Retry-After: %d\r\n",
                  retry_after);
  }
  if (close_conn) {
    n += snprintf(buf + n, sizeof(buf) - n, "Connection: close\r\n");
  }
  n += snprintf(buf + n, sizeof(buf) - n, "Content-Length: 0\r\n\r\n");
  return send_all(fd, buf, static_cast<size_t>(n));
}

// recv() more bytes into the connection's read buffer. Returns >0 on
// progress, 0 on orderly EOF, <0 on error/timeout. `deadline` bounds
// the TOTAL wait (the slowloris guard: SO_RCVTIMEO alone resets per
// byte trickled).
int fill_rbuf(Conn* c, Clock::time_point deadline) {
  if (Clock::now() >= deadline) return -1;
  char tmp[kReadChunk];
  ssize_t r = ::recv(c->fd.load(), tmp, sizeof(tmp), 0);
  if (r > 0) {
    c->rbuf.append(tmp, static_cast<size_t>(r));
    c->srv->stats[kStatBytesIn] += r;
    return static_cast<int>(r);
  }
  if (r == 0) return 0;
  if (errno == EINTR) return 1;  // retryable, counts as progress-less ok
  return -1;
}

// Case-insensitive header lookup inside the raw header block
// [hdr_begin, hdr_end). Returns the trimmed value or "".
std::string header_value(const std::string& head, const char* name) {
  size_t nlen = strlen(name);
  size_t pos = 0;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos) eol = head.size();
    if (eol - pos > nlen && head[pos + nlen] == ':') {
      bool match = true;
      for (size_t i = 0; i < nlen; i++) {
        if (tolower(static_cast<unsigned char>(head[pos + i])) !=
            tolower(static_cast<unsigned char>(name[i]))) {
          match = false;
          break;
        }
      }
      if (match) {
        size_t v = pos + nlen + 1;
        while (v < eol && (head[v] == ' ' || head[v] == '\t')) v++;
        size_t e = eol;
        while (e > v && (head[e - 1] == ' ' || head[e - 1] == '\t')) e--;
        return head.substr(v, e - v);
      }
    }
    pos = eol + 2;
  }
  return "";
}

bool iequals(const std::string& a, const char* b) {
  size_t n = strlen(b);
  if (a.size() != n) return false;
  for (size_t i = 0; i < n; i++) {
    if (tolower(static_cast<unsigned char>(a[i])) !=
        tolower(static_cast<unsigned char>(b[i]))) {
      return false;
    }
  }
  return true;
}

// Parse a non-negative decimal. Returns -1 on malformed (the Python
// receiver's int(...) ValueError → 400 bad_length verdict).
int64_t parse_length(const std::string& s) {
  if (s.empty() || s.size() > 18) return -1;
  int64_t v = 0;
  for (char ch : s) {
    if (ch < '0' || ch > '9') return -1;
    v = v * 10 + (ch - '0');
  }
  return v;
}

// One request → verdict cycle. Returns false when the connection must
// close (error, Connection: close, or drain).
bool serve_one(Conn* c) {
  Server* s = c->srv;
  // Only this thread ever changes c->fd, so one load is stable for
  // the whole request cycle.
  const int fd = c->fd.load();
  auto deadline =
      Clock::now() + std::chrono::milliseconds(s->header_timeout_ms);

  // -- read the header block -------------------------------------------
  size_t hdr_end;
  for (;;) {
    hdr_end = c->rbuf.find("\r\n\r\n", c->rpos);
    if (hdr_end != std::string::npos) break;
    if (c->rbuf.size() - c->rpos > kMaxHeaderBytes) {
      s->stats[kStatBadLength]++;
      write_response(fd, 400, 0, true);
      return false;
    }
    int r = fill_rbuf(c, deadline);
    if (r < 0) {
      // Timeout (slowloris header trickle) or reset mid-headers: the
      // client is gone or hostile — release the thread, no response.
      if (c->rbuf.size() > c->rpos) s->stats[kStatDisconnect]++;
      return false;
    }
    if (r == 0) {
      // Orderly EOF. Between requests this is a clean keep-alive
      // close; mid-headers it is a disconnect.
      if (c->rbuf.size() > c->rpos) s->stats[kStatDisconnect]++;
      return false;
    }
  }
  std::string head = c->rbuf.substr(c->rpos, hdr_end - c->rpos);
  size_t body_start = hdr_end + 4;

  // -- request line ----------------------------------------------------
  size_t line_end = head.find("\r\n");
  std::string line =
      head.substr(0, line_end == std::string::npos ? head.size() : line_end);
  size_t sp1 = line.find(' ');
  size_t sp2 = line.find(' ', sp1 == std::string::npos ? 0 : sp1 + 1);
  if (sp1 == std::string::npos || sp2 == std::string::npos) {
    s->stats[kStatBadLength]++;
    write_response(fd, 400, 0, true);
    return false;
  }
  std::string method = line.substr(0, sp1);
  std::string path = line.substr(sp1 + 1, sp2 - sp1 - 1);
  size_t q = path.find('?');
  if (q != std::string::npos) path.resize(q);
  bool keep_alive = true;
  std::string conn_hdr = header_value(head, "Connection");
  if (iequals(conn_hdr, "close")) keep_alive = false;

  if (method == "GET") {
    // Erase the consumed request like the POST path does — advancing
    // rpos alone would let a keep-alive /healthz prober grow rbuf
    // without bound.
    c->rbuf.erase(0, body_start);
    c->rpos = 0;
    if (path == "/healthz") {
      s->stats[kStatHealth]++;
      write_response(fd, 200, 0, !keep_alive);
    } else {
      s->stats[kStatNotFound]++;
      write_response(fd, 404, 0, !keep_alive);
    }
    return keep_alive;
  }
  if (method != "POST") {
    s->stats[kStatNotFound]++;
    write_response(fd, 404, 0, true);
    return false;
  }

  // -- framing verdicts (native; zero Python) --------------------------
  std::string te = header_value(head, "Transfer-Encoding");
  if (!te.empty() && !iequals(te, "identity")) {
    // Chunked (or any exotic coding) is refused outright: the framing
    // the zero-copy body read depends on is Content-Length. 400 with
    // close — the chunked body bytes must not be parsed as a next
    // request.
    s->stats[kStatChunked]++;
    write_response(fd, 400, 0, true);
    return false;
  }
  std::string cl = header_value(head, "Content-Length");
  int64_t length = cl.empty() ? 0 : parse_length(cl);
  if (length < 0) {
    s->stats[kStatBadLength]++;
    write_response(fd, 400, 0, true);
    return false;
  }
  if (length > s->max_body) {
    // Oversized: refuse WITHOUT reading the body (runtime/otlp.py's
    // exact contract — draining a multi-GB body to politely answer
    // 413 is itself a resource fault) and close so the unread
    // remainder can't be parsed as a next request.
    s->stats[kStatOversized]++;
    write_response(fd, 413, 0, true);
    return false;
  }

  int32_t kind = kKindTraces;
  if (path.size() >= 11 &&
      path.compare(path.size() - 11, 11, "/v1/metrics") == 0) {
    kind = kKindMetrics;
  } else if (path.size() >= 8 &&
             path.compare(path.size() - 8, 8, "/v1/logs") == 0) {
    kind = kKindLogs;
  }

  // -- body straight into the recycled native buffer -------------------
  c->body.resize(static_cast<size_t>(length));
  size_t have = std::min(c->rbuf.size() - body_start,
                         static_cast<size_t>(length));
  memcpy(c->body.data(), c->rbuf.data() + body_start, have);
  // Consume header + the body prefix; keep any pipelined tail.
  c->rbuf.erase(0, body_start + have);
  c->rpos = 0;
  size_t filled = have;
  // Total-deadline for the body too (SO_RCVTIMEO alone resets per
  // trickled byte — the slowloris guard must cover both phases):
  // header-timeout grace plus a floor transfer rate of ~8 KiB/s, so a
  // one-byte-per-9s trickler is bounded while a slow legitimate
  // exporter on a thin link is not cut off.
  auto body_deadline = Clock::now() + std::chrono::milliseconds(
                           s->header_timeout_ms + length / 8);
  while (filled < static_cast<size_t>(length)) {
    if (Clock::now() >= body_deadline) {
      s->stats[kStatDisconnect]++;
      return false;
    }
    ssize_t r = ::recv(fd, c->body.data() + filled,
                       static_cast<size_t>(length) - filled, 0);
    if (r > 0) {
      filled += static_cast<size_t>(r);
      s->stats[kStatBytesIn] += r;
      continue;
    }
    if (r < 0 && errno == EINTR) continue;
    if (r == 0) {
      // Truncated frame: the client promised more bytes than it sent
      // (died mid-upload). 4xx, not a crash — otlp.py's verdict.
      s->stats[kStatTruncated]++;
      write_response(fd, 400, 0, true);
    } else {
      // Timeout or reset mid-body: nothing to answer.
      s->stats[kStatDisconnect]++;
    }
    return false;
  }

  if (s->quiesced.load() || s->stopping.load()) {
    // Draining: no new work enters the pump. 503 is the OTLP
    // retryable status — the exporter resends to the successor.
    write_response(fd, 503, 1, true);
    return false;
  }

  // -- enqueue the ticket and wait for the pump's verdict --------------
  int64_t id = s->next_id.fetch_add(1);
  {
    std::lock_guard<std::mutex> lk(c->verdict_mu);
    c->req_id = id;
    c->status = 0;
    c->retry_after = 0;
    c->done = false;
  }
  {
    // The stopping re-check MUST happen under s->mu: otd_fd_stop sets
    // stopping before taking s->mu for its ready/by_id 503 flush, so a
    // ticket either lands before the flush (and is flushed) or the
    // check here observes stopping and refuses — no ticket can be
    // enqueued after the flush with nobody left to answer it (which
    // would strand this thread on verdict_cv and hang stop's join).
    std::unique_lock<std::mutex> lk(s->mu);
    if (s->stopping.load()) {
      lk.unlock();
      write_response(fd, 503, 1, true);
      return false;
    }
    s->by_id[id] = c;
    s->ready.push_back(Ticket{id, kind, c->body.data(),
                              static_cast<int64_t>(length)});
  }
  s->stats[kStatEnqueued]++;
  s->stats[kStatPending]++;
  s->ready_cv.notify_one();

  int32_t status, retry_after;
  {
    // The buffer is Python's until the verdict lands: wait without a
    // deadline (otd_fd_stop answers every queued ticket 503, so this
    // cannot outlive the server).
    std::unique_lock<std::mutex> lk(c->verdict_mu);
    c->verdict_cv.wait(lk, [c] { return c->done; });
    status = c->status;
    retry_after = c->retry_after;
  }
  s->stats[kStatPending]--;
  s->stats[kStatResponded]++;

  if (c->body.capacity() > kShrinkAbove &&
      static_cast<size_t>(length) < kShrinkAbove / 16) {
    std::vector<uint8_t>().swap(c->body);
  }
  bool close_now = !keep_alive || s->stopping.load();
  if (!write_response(fd, status, retry_after, close_now)) {
    s->stats[kStatDisconnect]++;
    return false;
  }
  return !close_now;
}

void conn_loop(Conn* c) {
  // Per-recv bound so a dead peer can't pin the thread; the overall
  // header deadline in serve_one handles the trickle case.
  const int fd = c->fd.load();
  struct timeval tv;
  tv.tv_sec = static_cast<time_t>(c->srv->header_timeout_ms / 1000);
  tv.tv_usec =
      static_cast<suseconds_t>((c->srv->header_timeout_ms % 1000) * 1000);
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  while (!c->srv->stopping.load()) {
    if (!serve_one(c)) break;
  }
  {
    // Publish -1 and close under verdict_mu: otd_fd_stop's wake-up
    // shutdown() takes the same mutex, so it can never race this
    // close and hit a kernel-recycled fd number.
    std::lock_guard<std::mutex> lk(c->verdict_mu);
    c->fd.store(-1);
    ::shutdown(fd, SHUT_RDWR);
    ::close(fd);
  }
  c->srv->stats[kStatLiveConns]--;
}

void accept_loop(Server* s) {
  for (;;) {
    struct sockaddr_in addr;
    socklen_t alen = sizeof(addr);
    int fd = ::accept(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                      &alen);
    if (fd < 0) {
      if (errno == EINTR) continue;
      return;  // listener closed: quiesce/stop
    }
    if (s->stopping.load() || s->quiesced.load()) {
      ::close(fd);
      return;
    }
    if (s->stats[kStatLiveConns].load() >= s->max_conns) {
      // Connection cap: retryable refusal, never an accept backlog
      // that turns into unbounded thread growth.
      s->stats[kStatOvercap]++;
      write_response(fd, 503, 1, true);
      ::close(fd);
      continue;
    }
    s->stats[kStatAccepted]++;
    s->stats[kStatLiveConns]++;
    // Reap finished connections (fd already -1): join + delete here so
    // a long-lived server doesn't accumulate dead Conn objects.
    {
      std::vector<Conn*> dead;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        auto alive_end = std::partition(
            s->conns.begin(), s->conns.end(),
            [](Conn* c) { return c->fd != -1; });
        dead.assign(alive_end, s->conns.end());
        s->conns.erase(alive_end, s->conns.end());
      }
      for (Conn* c : dead) {
        if (c->thread.joinable()) c->thread.join();
        delete c;
      }
    }
    Conn* c = new Conn();
    c->srv = s;
    c->fd = fd;
    {
      std::lock_guard<std::mutex> lk(s->mu);
      s->conns.push_back(c);
    }
    c->thread = std::thread(conn_loop, c);
  }
}

void respond_locked_ticket(Server* s, const Ticket& t, int32_t status,
                           int32_t retry_after) {
  Conn* c;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->by_id.find(t.id);
    if (it == s->by_id.end()) return;
    c = it->second;
    s->by_id.erase(it);
  }
  {
    std::lock_guard<std::mutex> lk(c->verdict_mu);
    c->status = status;
    c->retry_after = retry_after;
    c->done = true;
  }
  c->verdict_cv.notify_one();
}

}  // namespace

extern "C" {

// Start a front door on `port` (0 = ephemeral). Returns a handle
// (>0), or -1 when the socket could not be bound.
int64_t otd_fd_start(int32_t port, int64_t max_body, int32_t max_conns,
                     int64_t header_timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);

  Server* s = new Server();
  s->listen_fd = fd;
  s->port = ntohs(addr.sin_port);
  s->max_body = max_body > 0 ? max_body : (16 << 20);
  s->max_conns = max_conns > 0 ? max_conns : 64;
  s->header_timeout_ms = header_timeout_ms > 0 ? header_timeout_ms : 10000;
  s->acceptor = std::thread(accept_loop, s);

  std::lock_guard<std::mutex> lk(g_servers_mu);
  int64_t h = g_next_handle++;
  g_servers[h] = s;
  return h;
}

int32_t otd_fd_port(int64_t h) {
  Server* s = find_server(h);
  return s ? s->port : -1;
}

// Pop up to `max_n` complete request tickets, blocking up to
// `timeout_ms`. Fills ids/kinds/ptrs/lens. Returns the count (0 on
// timeout) or -1 once the server is stopping and the queue is empty —
// the pump's exit signal. Called with the GIL released (ctypes.CDLL).
int64_t otd_fd_next(int64_t h, int64_t* ids, int32_t* kinds,
                    const uint8_t** ptrs, int64_t* lens, int64_t max_n,
                    int64_t timeout_ms) {
  Server* s = find_server(h);
  if (s == nullptr || max_n <= 0) return -1;
  std::unique_lock<std::mutex> lk(s->mu);
  if (s->ready.empty()) {
    s->ready_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                         [s] { return !s->ready.empty() ||
                                      s->stopping.load(); });
  }
  if (s->ready.empty()) return s->stopping.load() ? -1 : 0;
  int64_t n = 0;
  while (n < max_n && !s->ready.empty()) {
    const Ticket& t = s->ready.front();
    ids[n] = t.id;
    kinds[n] = t.kind;
    ptrs[n] = t.ptr;
    lens[n] = t.len;
    s->ready.pop_front();
    n++;
  }
  return n;
}

// Deliver the verdict for ticket `id`: the connection thread writes
// the canned response and recycles the buffer. retry_after <= 0
// omits the header. Returns 0 (unknown ids are a no-op: the conn may
// have died — its close path already counted the disconnect).
int32_t otd_fd_respond(int64_t h, int64_t id, int32_t status,
                       int32_t retry_after) {
  Server* s = find_server(h);
  if (s == nullptr) return -1;
  respond_locked_ticket(s, Ticket{id, 0, nullptr, 0}, status, retry_after);
  return 0;
}

void otd_fd_stats(int64_t h, int64_t* out) {
  Server* s = find_server(h);
  for (int i = 0; i < kStatCount; i++) {
    out[i] = s ? s->stats[i].load() : 0;
  }
}

// Stop accepting new connections/requests (graceful drain, phase 1).
// Already-enqueued tickets keep flowing to the pump; new requests on
// live connections answer 503.
void otd_fd_quiesce(int64_t h) {
  Server* s = find_server(h);
  if (s == nullptr) return;
  s->quiesced.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
}

// Full stop (phase 2): answer every still-queued ticket 503, wake the
// pump (otd_fd_next returns -1), shut every connection down and join
// all threads. The handle stays valid for stats reads; call after the
// Python pumps have drained their in-flight batches.
void otd_fd_stop(int64_t h) {
  Server* s = find_server(h);
  if (s == nullptr) return;
  s->quiesced.store(true);
  s->stopping.store(true);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  s->ready_cv.notify_all();
  // Flush the ready queue with 503s so no connection thread waits on
  // a verdict that will never come (and no buffer stays borrowed);
  // the conn threads do the pending/responded accounting as usual.
  std::deque<Ticket> leftover;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    leftover.swap(s->ready);
  }
  for (const Ticket& t : leftover) {
    respond_locked_ticket(s, t, 503, 1);
  }
  if (s->acceptor.joinable()) s->acceptor.join();
  ::close(s->listen_fd);
  // Any ticket the pump popped but never answered (a dead pump) gets
  // its 503 here — same lock order as respond (s->mu, then verdict).
  std::vector<int64_t> orphans;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    for (const auto& kv : s->by_id) orphans.push_back(kv.first);
  }
  for (int64_t id : orphans) {
    respond_locked_ticket(s, Ticket{id, 0, nullptr, 0}, 503, 1);
  }
  std::vector<Conn*> conns;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    conns.swap(s->conns);
  }
  for (Conn* c : conns) {
    std::lock_guard<std::mutex> lk(c->verdict_mu);
    // Under verdict_mu the conn thread's exchange(-1)+close teardown
    // cannot interleave, so this shutdown() can never hit an fd number
    // the kernel already recycled for another descriptor.
    int fd = c->fd.load();
    if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
    // Belt-and-suspenders vs a stranded waiter: resolve, don't just
    // notify — a bare notify_all leaves the wait predicate (done)
    // false and the join below would hang forever. The enqueue-time
    // stopping re-check makes this unreachable in practice, but a
    // verdict the pump popped-and-dropped still lands here.
    if (!c->done) {
      c->status = 503;
      c->retry_after = 1;
      c->done = true;
    }
    c->verdict_cv.notify_all();
  }
  for (Conn* c : conns) {
    if (c->thread.joinable()) c->thread.join();
    delete c;
  }
}

}  // extern "C"
