// Native currency kernel: Money conversion with exact carry, C ABI.
//
// The reference keeps currency conversion native (its currency service
// is C++ — /root/reference/src/currency/src/server.cpp:103-120, rate
// table :48-84); this framework keeps the same polyglot contract: the
// conversion arithmetic lives here and services/currency.py is the
// facade (with a pure-Python fallback for compiler-less environments).
//
// Semantics pinned to services/money.py + services/currency.py by
// tests/test_native_currency.py:
//   - validation: |nanos| < 1e9 and units/nanos signs must agree
//   - conversion: total_nanos = units*1e9 + nanos; multiply by the
//     EUR-cross rate in double (same precision path as Python's
//     int*float); round ties-to-even (Python round()); split with
//     divmod-on-absolute carry.
// The rate table itself stays in Python (one source of truth; the rate
// arrives here as the already-divided cross rate).
//
// Build: g++ -O3 -shared -fPIC (no dependencies); loaded via ctypes by
// runtime/native.py.

#include <cmath>
#include <cstdint>
#include <cstdlib>

namespace {

constexpr int64_t kNanosPerUnit = 1000000000;

// money.validate(): nanos range and sign agreement.
bool valid(int64_t units, int64_t nanos) {
  if (nanos <= -kNanosPerUnit || nanos >= kNanosPerUnit) return false;
  if ((units > 0 && nanos < 0) || (units < 0 && nanos > 0)) return false;
  return true;
}

// divmod-on-absolute + reapplied sign (the carry split both services
// use for every Money result).
void split(int64_t total_nanos, int64_t* out_units, int32_t* out_nanos) {
  int64_t a = total_nanos < 0 ? -total_nanos : total_nanos;
  int64_t u = a / kNanosPerUnit;
  int64_t n = a % kNanosPerUnit;
  if (total_nanos < 0) {
    u = -u;
    n = -n;
  }
  *out_units = u;
  *out_nanos = int32_t(n);
}

}  // namespace

extern "C" {

// Convert (units, nanos) by `rate` (to-rate / from-rate, computed by
// the caller from its table). Returns 0, or -2 for invalid money, or
// -3 when the product overflows the int64 nanos domain (Python's
// arbitrary-precision ints would keep going; the facade falls back).
int otd_money_convert(double rate, int64_t units, int32_t nanos,
                      int64_t* out_units, int32_t* out_nanos) {
  if (!valid(units, nanos)) return -2;
  // The double product mirrors Python's `total_nanos * rate` (int →
  // float conversion, one rounding); llrint under the default
  // round-to-nearest-even mode mirrors Python's round().
  double total = double(__int128(units) * kNanosPerUnit + nanos);
  double product = total * rate;
  if (!(product >= -9.2e18 && product <= 9.2e18)) return -3;
  split(llrint(product), out_units, out_nanos);
  return 0;
}

// Sum two Money values of the same (caller-checked) currency with
// exact carry. Returns 0, -2 for invalid input, -3 on int64 overflow.
int otd_money_sum(int64_t u1, int32_t n1, int64_t u2, int32_t n2,
                  int64_t* out_units, int32_t* out_nanos) {
  if (!valid(u1, n1) || !valid(u2, n2)) return -2;
  __int128 total = (__int128(u1) + u2) * kNanosPerUnit + n1 + n2;
  if (total > __int128(INT64_MAX) || total < __int128(INT64_MIN)) return -3;
  split(int64_t(total), out_units, out_nanos);
  return 0;
}

}  // extern "C"
