"""Flag editor UI: the flagd-ui analogue, mountable behind the edge.

The reference ships a Next.js app (/root/reference/src/flagd-ui/) whose
whole job is rewriting the flagd JSON file the services evaluate:
a "basic" page toggling each flag's ``defaultVariant``
(src/app/page.tsx), an "advanced" raw-JSON editor
(src/app/advanced/page.tsx), and two API routes doing the file IO
(src/app/api/{read-file,write-to-file}). :class:`FlagEditorUI` is that
surface as one handler object the :class:`~..services.gateway.ShopGateway`
mounts at ``/feature`` (the same path Envoy routes to flagd-ui,
/root/reference/src/frontend-proxy/envoy.tmpl.yaml:39-54).

Works against either flag store flavour:

- :class:`~.flags.FlagFileStore` — writes go to the JSON file
  atomically; every service sharing the file hot-reloads (the
  reference's mounted-volume pattern, docker-compose.yml:651-652);
- plain :class:`~.flags.FlagEvaluator` — writes replace the in-memory
  doc (the in-proc Shop case).
"""

from __future__ import annotations

import json
from html import escape

from .flags import FlagEvaluator, FlagFileStore, atomic_write_doc


class FlagValidationError(ValueError):
    pass


def validate_flag_doc(doc) -> dict:
    """Schema-check a flagd document the way flagd-ui's save path does."""
    if not isinstance(doc, dict) or not isinstance(doc.get("flags"), dict):
        raise FlagValidationError('document must be {"flags": {...}}')
    for key, flag in doc["flags"].items():
        if not isinstance(flag, dict):
            raise FlagValidationError(f"flag {key!r} must be an object")
        variants = flag.get("variants")
        if not isinstance(variants, dict) or not variants:
            raise FlagValidationError(f"flag {key!r} needs non-empty variants")
        default = flag.get("defaultVariant")
        if default not in variants:
            raise FlagValidationError(
                f"flag {key!r}: defaultVariant {default!r} not in variants"
            )
        if flag.get("state") not in ("ENABLED", "DISABLED"):
            raise FlagValidationError(f"flag {key!r}: state must be ENABLED|DISABLED")
    return doc


class FlagEditorUI:
    """handle(method, path, body) -> (status, content_type, bytes)."""

    def __init__(self, store: FlagEvaluator):
        self.store = store

    # -- store IO ------------------------------------------------------

    def _read_doc(self) -> dict:
        if isinstance(self.store, FlagFileStore):
            with open(self.store.path) as f:
                return json.load(f)
        # Deep copy: handlers mutate the returned doc before validation,
        # and a rejected write must never corrupt the live store.
        return self.store.snapshot()

    def _write_doc(self, doc: dict) -> None:
        validate_flag_doc(doc)
        if isinstance(self.store, FlagFileStore):
            # Atomic replace (flags.atomic_write_doc — the ONE
            # flag-file write primitive, shared with the remediation
            # actuator): services hot-reload on mtime and must never
            # observe a torn write.
            atomic_write_doc(self.store.path, doc)
            self.store._maybe_reload(force=True)
        else:
            self.store.replace(doc)

    # -- routing -------------------------------------------------------

    def handle(self, method: str, path: str, body: bytes):
        try:
            if path in ("/", "") and method == "GET":
                return 200, "text/html", self._page_basic()
            if path == "/advanced" and method == "GET":
                return 200, "text/html", self._page_advanced()
            if path == "/api/read-file" and method == "GET":
                return 200, "application/json", json.dumps(self._read_doc()).encode()
            if path == "/api/write-to-file" and method == "POST":
                payload = json.loads(body or b"{}")
                self._write_doc(payload.get("data", payload))
                return 200, "application/json", b'{"status":"saved"}'
            if path == "/api/set-variant" and method == "POST":
                # Basic-page action: flip one flag's defaultVariant.
                req = json.loads(body or b"{}")
                doc = self._read_doc()
                flag = doc.get("flags", {}).get(req.get("flag"))
                if flag is None:
                    return 404, "application/json", b'{"error":"no such flag"}'
                flag["defaultVariant"] = req.get("variant")
                self._write_doc(doc)
                return 200, "application/json", b'{"status":"saved"}'
            return 404, "text/plain", b"no route"
        except (FlagValidationError, json.JSONDecodeError) as e:
            return 400, "application/json", json.dumps({"error": str(e)}).encode()

    # -- pages ---------------------------------------------------------

    def _page_basic(self) -> bytes:
        doc = self._read_doc()
        rows = []
        for key, flag in sorted(doc.get("flags", {}).items()):
            opts = "".join(
                f'<option value="{escape(str(v))}"'
                f'{" selected" if v == flag.get("defaultVariant") else ""}>'
                f"{escape(str(v))}</option>"
                for v in flag.get("variants", {})
            )
            rows.append(
                f"<tr><td><code>{escape(key)}</code></td>"
                f"<td>{escape(flag.get('state', ''))}</td>"
                f'<td><select onchange="setVariant(\'{escape(key)}\', this.value)">'
                f"{opts}</select></td></tr>"
            )
        return (
            "<!doctype html><title>Flags</title>"
            "<h1>Feature Flags</h1>"
            '<p><a href="/feature/advanced">advanced (raw JSON)</a></p>'
            "<table border=1 cellpadding=4><tr><th>flag</th><th>state</th>"
            "<th>defaultVariant</th></tr>" + "".join(rows) + "</table>"
            "<script>function setVariant(flag, variant) {"
            "fetch('/feature/api/set-variant', {method: 'POST',"
            "headers: {'Content-Type': 'application/json'},"
            "body: JSON.stringify({flag, variant})}).then(() => location.reload());"
            "}</script>"
        ).encode()

    def _page_advanced(self) -> bytes:
        raw = json.dumps(self._read_doc(), indent=2)
        return (
            "<!doctype html><title>Flags (advanced)</title>"
            "<h1>Raw flag JSON</h1>"
            f'<textarea id="doc" rows="30" cols="100">{escape(raw)}</textarea><br>'
            '<button onclick="save()">Save</button> <span id="msg"></span>'
            "<script>function save() {"
            "fetch('/feature/api/write-to-file', {method: 'POST',"
            "headers: {'Content-Type': 'application/json'},"
            "body: JSON.stringify({data: JSON.parse("
            "document.getElementById('doc').value)})})"
            ".then(r => r.json()).then(d => {"
            "document.getElementById('msg').textContent = "
            "d.status || d.error;});}</script>"
        ).encode()
