"""Config (env contract) and flagd-style feature flags."""

from .config import ConfigError, env_float, env_int, env_str, must_map_env
from .flags import FlagEvaluator, FlagFileStore, OfrepClient

__all__ = [
    "ConfigError",
    "env_float",
    "env_int",
    "env_str",
    "must_map_env",
    "FlagEvaluator",
    "FlagFileStore",
    "OfrepClient",
]
