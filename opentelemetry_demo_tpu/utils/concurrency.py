"""Concurrency primitives for serving the shop graph.

The reference's services are separate processes, each concurrent by
construction (gRPC thread pools, Go goroutines); this framework's shop
is ONE object graph, so its edge servers need explicit discipline:

- :class:`RWLock` — writer-preference readers-writer lock. Exclusive
  mode is a drop-in for ``threading.Lock`` (``with lock:``), so the
  HTTP gateway's single-writer pump keeps its exact semantics, while
  the gRPC edge runs read-only RPCs (GetProduct, Convert, GetQuote, …)
  concurrently under ``lock.shared()``.
- :class:`LockedRng` — a thread-safe facade over one
  ``numpy.random.Generator``. Every service draw (latency jitter, ad
  choice, quote cost) is a read-modify-write of shared generator state;
  under concurrent readers an unlocked Generator corrupts silently.
  Single-threaded draws keep their exact order, so seeded tests stay
  deterministic.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class RWLock:
    """Writer-preference readers-writer lock.

    Writer preference: once a writer waits, new readers queue behind
    it — a read-heavy gRPC edge can then never starve the gateway's
    pump (which holds exclusive for every span flush).
    Not reentrant in either mode (``threading.Lock`` discipline).
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    # -- exclusive (threading.Lock drop-in) ----------------------------

    def acquire(self) -> bool:
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True
        return True

    def release(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    def __enter__(self) -> "RWLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- shared --------------------------------------------------------

    @contextmanager
    def shared(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield self
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()


class LockedRng:
    """Thread-safe proxy over a ``numpy.random.Generator``.

    Method calls run under one mutex; attribute reads pass through.
    Bound methods are cached so the hot path costs one dict hit + one
    lock, not a ``getattr`` chain per draw.
    """

    def __init__(self, rng):
        self._rng = rng
        self._lock = threading.Lock()
        self._cache: dict = {}

    def __getattr__(self, name):
        cached = self._cache.get(name)
        if cached is not None:
            return cached
        attr = getattr(self._rng, name)
        if not callable(attr):
            return attr

        def locked(*args, **kwargs):
            with self._lock:
                return attr(*args, **kwargs)

        self._cache[name] = locked
        return locked
