"""Environment config contract: hard-fail on missing keys.

Mirrors the reference's two-tier config system (SURVEY.md §5 "Config /
flag system"): static configuration arrives exclusively through
environment variables and a service must refuse to boot when a required
key is absent — the behaviour every reference service implements
(Go ``mustMapEnv`` /root/reference/src/checkout/main.go:230-236, Python
``must_map_env`` /root/reference/src/recommendation/recommendation_server.py:116-120,
Kotlin /root/reference/src/fraud-detection/src/main/kotlin/frauddetection/main.kt:42-46).
Failing fast at boot beats a half-configured service discovered at 3am.
"""

from __future__ import annotations

import os


class ConfigError(RuntimeError):
    """A required environment key is missing or malformed."""


def must_map_env(target: dict, key: str, env_name: str) -> None:
    """Fetch ``env_name`` into ``target[key]`` or refuse to boot."""
    value = os.environ.get(env_name, "")
    if not value:
        raise ConfigError(f"environment variable {env_name} not set")
    target[key] = value


def env_str(env_name: str, default: str | None = None) -> str:
    value = os.environ.get(env_name, "")
    if value:
        return value
    if default is None:
        raise ConfigError(f"environment variable {env_name} not set")
    return default


def env_int(env_name: str, default: int | None = None) -> int:
    raw = os.environ.get(env_name, "")
    if not raw:
        if default is None:
            raise ConfigError(f"environment variable {env_name} not set")
        return default
    try:
        return int(raw)
    except ValueError as e:
        raise ConfigError(f"{env_name}={raw!r} is not an integer") from e


def env_float(env_name: str, default: float | None = None) -> float:
    raw = os.environ.get(env_name, "")
    if not raw:
        if default is None:
            raise ConfigError(f"environment variable {env_name} not set")
        return default
    try:
        return float(raw)
    except ValueError as e:
        raise ConfigError(f"{env_name}={raw!r} is not a number") from e
