"""Environment config contract: hard-fail on missing keys.

Mirrors the reference's two-tier config system (SURVEY.md §5 "Config /
flag system"): static configuration arrives exclusively through
environment variables and a service must refuse to boot when a required
key is absent — the behaviour every reference service implements
(Go ``mustMapEnv`` /root/reference/src/checkout/main.go:230-236, Python
``must_map_env`` /root/reference/src/recommendation/recommendation_server.py:116-120,
Kotlin /root/reference/src/fraud-detection/src/main/kotlin/frauddetection/main.kt:42-46).
Failing fast at boot beats a half-configured service discovered at 3am.
"""

from __future__ import annotations

import os


class ConfigError(RuntimeError):
    """A required environment key is missing or malformed."""


def must_map_env(target: dict, key: str, env_name: str) -> None:
    """Fetch ``env_name`` into ``target[key]`` or refuse to boot."""
    value = os.environ.get(env_name, "")
    if not value:
        raise ConfigError(f"environment variable {env_name} not set")
    target[key] = value


def env_str(env_name: str, default: str | None = None) -> str:
    value = os.environ.get(env_name, "")
    if value:
        return value
    if default is None:
        raise ConfigError(f"environment variable {env_name} not set")
    return default


def env_int(env_name: str, default: int | None = None) -> int:
    raw = os.environ.get(env_name, "")
    if not raw:
        if default is None:
            raise ConfigError(f"environment variable {env_name} not set")
        return default
    try:
        return int(raw)
    except ValueError as e:
        raise ConfigError(f"{env_name}={raw!r} is not an integer") from e


def env_float(env_name: str, default: float | None = None) -> float:
    raw = os.environ.get(env_name, "")
    if not raw:
        if default is None:
            raise ConfigError(f"environment variable {env_name} not set")
        return default
    try:
        return float(raw)
    except ValueError as e:
        raise ConfigError(f"{env_name}={raw!r} is not a number") from e


# Overload-protection knobs (runtime.pipeline bounded admission /
# brownout; runtime/daemon.py threads them into the pipeline). ONE
# declarative registry — env name → (type, default, meaning) — so the
# daemon, the compose overlay, the k8s generator and sanitycheck.py can
# never disagree about the knob set: scripts/sanitycheck.py asserts
# every key here appears in deploy/docker-compose.anomaly.yml,
# utils/k8s.py and runtime/daemon.py. Values must stay literals
# (sanitycheck reads this dict via ast.literal_eval, without importing
# jax).
OVERLOAD_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_QUEUE_MAX_ROWS": (
        "int", 65536,
        "pending-queue row budget (0 = unbounded; the memory_limiter "
        "analogue for the span pipeline)",
    ),
    "ANOMALY_QUEUE_HIGH_WATERMARK": (
        "float", 0.85,
        "fraction of the row budget at which admission saturates "
        "(OTLP answers 429/RESOURCE_EXHAUSTED)",
    ),
    "ANOMALY_QUEUE_LOW_WATERMARK": (
        "float", 0.5,
        "fraction of the row budget at which admission resumes "
        "(hysteresis: must be below the high watermark)",
    ),
    "ANOMALY_BROWNOUT_HOLD_S": (
        "float", 2.0,
        "sustained-saturation seconds before the brownout ladder moves "
        "one level (and sustained-clear seconds before it relaxes one)",
    ),
    "ANOMALY_BROWNOUT_MAX_LEVEL": (
        "int", 4,
        "deepest head-sampling level: level L keeps 1/2^L of OK-lane "
        "spans (error-lane spans always pass)",
    ),
    "ANOMALY_RETRY_AFTER_S": (
        "float", 1.0,
        "Retry-After hint (seconds) handed to throttled OTLP producers",
    ),
}


# Parallel host-ingest knobs (runtime.ingest_pool: the sharded decode
# pool between the OTLP/Kafka receivers and the pipeline). Same ONE-
# registry discipline as OVERLOAD_KNOBS — the daemon, the compose
# overlay, the k8s generator and sanitycheck.py all consume this dict,
# so the knob set can never drift between them. Values must stay
# literals (sanitycheck reads via ast.literal_eval, without importing
# jax).
INGEST_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_INGEST_WORKERS": (
        "int", 2,
        "decode-pool worker threads (0 = no pool: serial in-thread "
        "decode on the receiver threads, the pre-pool path)",
    ),
    "ANOMALY_INGEST_COALESCE": (
        "int", 64,
        "max export requests folded into ONE native batch decode + "
        "tensorize + pipeline merge (a worker drains up to this many "
        "queued requests per flush; coalescing is opportunistic, so an "
        "idle stream still sees single-request latency)",
    ),
    "ANOMALY_INGEST_MAX_PENDING": (
        "int", 512,
        "bounded request queue ahead of the decode pool; a full queue "
        "answers retryable 429/RESOURCE_EXHAUSTED (no unbounded buffer "
        "ever forms before the pipeline's row-budgeted admission)",
    ),
    "ANOMALY_INGEST_NATIVE_THREADS": (
        "int", 2,
        "native extraction threads PER batched decode call (the "
        "two-pass scanner's pass-2 sharding: one oversized flush "
        "splits across cores at span-record boundaries); <=1 keeps "
        "extraction serial per call",
    ),
    "ANOMALY_INGEST_SHARD_MIN_BYTES": (
        "int", 262144,
        "payload-byte floor below which a batched decode never shards "
        "across native threads (thread spawn/join would cost more "
        "than the extraction it hides)",
    ),
}


# Hot-standby replication knobs (runtime.replication: epoch-fenced
# primary→standby state streaming; runtime/daemon.py role state
# machine). Same ONE-registry discipline as OVERLOAD_KNOBS/INGEST_KNOBS
# — daemon, compose overlay, k8s generator and sanitycheck.py all
# consume this dict. Values must stay literals (sanitycheck reads via
# ast.literal_eval, without importing jax).
REPLICATION_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_ROLE": (
        "str", "primary",
        "boot role: 'primary' serves + ships deltas, 'standby' applies "
        "them and promotes itself when the primary goes quiet",
    ),
    "ANOMALY_REPLICATION_PORT": (
        "int", -1,
        "primary-side replication listener port (-1 disables "
        "replication; a promoted standby opens the same listener so "
        "the NEXT standby can attach)",
    ),
    "ANOMALY_REPLICATION_TARGET": (
        "str", "",
        "standby-side primary address host:port (the primary's "
        "ANOMALY_REPLICATION_PORT listener); empty = no replication",
    ),
    "ANOMALY_REPLICATION_INTERVAL_S": (
        "float", 1.0,
        "delta ship cadence seconds — replicated state lags the "
        "primary by at most this much (the failover data-loss bound "
        "for the replace-latest EWMA block; HLL/CMS converge exactly "
        "by merge regardless)",
    ),
    "ANOMALY_FAILOVER_TIMEOUT_S": (
        "float", 5.0,
        "standby watchdog: seconds without a replication frame before "
        "the standby promotes itself (epoch bump + Kafka resume from "
        "the replicated offset map + OTLP ingest up)",
    ),
    "ANOMALY_PRIMARY_HEALTH_ADDR": (
        "str", "",
        "optional grpc.health.v1 address of the primary; when set, the "
        "standby double-checks it before promoting (a SERVING primary "
        "behind a broken replication link must not cause split-brain)",
    ),
    "ANOMALY_OFFSET_DEFER_MAX": (
        "int", 64,
        "cap on the deferred-confirmation offset list (orders flushes "
        "whose pool ticket hasn't resolved); over it the oldest entry "
        "is shed (anomaly_offset_defer_dropped_total — its records "
        "replay on restart, at-least-once preserved) and a checkpoint "
        "barrier is forced",
    ),
}


# Verified-frame knobs (runtime.frame: the ONE columnar wire format —
# checksummed, versioned — that ingest scratch→pipeline, replication
# payloads and checkpoint files all move; runtime/daemon.py threads
# these into frame.configure() at boot). Same ONE-registry discipline
# as the other knob families — daemon, compose overlay, k8s generator
# and sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
FRAME_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_FRAME_VERIFY": (
        "int", 1,
        "verify frame checksums (per-column CRC32C + trailer) at every "
        "hop before state merges (0 = trust the bytes — benchmarking "
        "only; corruption then merges undetected, the pre-frame "
        "behavior)",
    ),
    "ANOMALY_FRAME_WRITE_VERSION": (
        "int", 2,
        "frame format version this process WRITES (readers always "
        "accept the full window, currently 1..2): pin to the old "
        "version while a rolling primary/standby upgrade is in flight "
        "so the not-yet-upgraded side keeps reading every payload",
    ),
    "ANOMALY_FRAME_QUARANTINE_DIR": (
        "str", "",
        "directory where frames that fail verification are written "
        "aside for forensics (empty = count + drop for in-memory hops; "
        "corrupt checkpoint FILES always move aside to <file>.corrupt "
        "regardless)",
    ),
}


# Live-query-plane knobs (runtime.query: the HTTP/gRPC read API over
# live sketch state, the Grafana JSON datasource, and read-replica
# serving on a standby; runtime/daemon.py wires them). Same ONE-
# registry discipline as the other knob families — daemon, compose
# overlay, k8s generator and sanitycheck.py all consume this dict.
# Values must stay literals (sanitycheck reads via ast.literal_eval,
# without importing jax).
QUERY_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_QUERY_PORT": (
        "int", 0,
        "HTTP/JSON query port (also the Grafana simple-JSON datasource "
        "surface); 0 binds an ephemeral port (announced at boot), -1 "
        "disables the query plane entirely",
    ),
    "ANOMALY_QUERY_GRPC_PORT": (
        "int", -1,
        "gRPC query port (same documents over "
        "/otdtpu.query.v1.QueryService/Query); -1 disables, 0 binds an "
        "ephemeral port; silently skipped when grpcio is absent",
    ),
    "ANOMALY_QUERY_TOPK": (
        "int", 10,
        "default k for /query/topk heavy-hitter answers (per-request "
        "?k= overrides)",
    ),
    "ANOMALY_QUERY_EXEMPLARS": (
        "int", 8,
        "per-service exemplar-ring size: trace ids captured at flag "
        "time from the flagged batch, linking every anomaly to a "
        "concrete Jaeger trace (0 disables capture)",
    ),
    "ANOMALY_QUERY_CANDIDATES": (
        "int", 64,
        "per-service ring of recently-seen attribute keys — the "
        "candidate set /query/topk scores against the live CMS (a CMS "
        "cannot enumerate its keys); bounds how many distinct keys a "
        "top-k answer can rank, so keep it >= the largest k queried",
    ),
    "ANOMALY_QUERY_TIMELINE": (
        "int", 120,
        "snapshot-timeline ring depth: per-interval cardinality/CUSUM "
        "samples backing Grafana timeseries targets and "
        "/query/cardinality timelines",
    ),
    "ANOMALY_QUERY_READ_REPLICA": (
        "int", 1,
        "1 = a STANDBY serves the query API from its replicated mirror "
        "(staleness-bounded by replication lag, reported per response) "
        "while remaining promotable; 0 = standby serves no queries "
        "until promotion",
    ),
    "ANOMALY_QUERY_MAX_STALENESS_S": (
        "float", 2.0,
        "snapshot cache budget: a query re-snapshots state when the "
        "cached copy is older than this, so every answer is at most "
        "this stale (plus replication lag on a read replica)",
    ),
    "ANOMALY_QUERY_EVICTED_LOOKBACK_S": (
        "float", 3600.0,
        "how far back /query/* searches history for a service the "
        "keyspace evictor retired from the live table; answers found "
        "there are labeled source:\"evicted\" (0 disables the "
        "evicted-key fallback)",
    ),
}


# Daemon-core knobs (runtime/daemon.py boot contract): ports, batch
# geometry, harvest/adaptive cadence, checkpoint path/cadence, body
# cap, and the flag/Kafka wiring env. Historically these were ad-hoc
# ``os.environ`` reads scattered through daemon.__init__ — outside any
# registry, invisible to the deploy surfaces and the checkers. Same
# ONE-registry discipline as every other family; scripts/staticcheck's
# knob-discipline pass (and sanitycheck's literal pins) enforce the
# correspondence. Values must stay literals (read via ast.literal_eval,
# without importing jax). The -1 geometry defaults mean "use the
# model's DetectorConfig default" (this module must stay jax-free, so
# it cannot name those defaults directly).
DAEMON_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_OTLP_PORT": (
        "int", 4318,
        "OTLP/HTTP listen port (the collector's otlphttp exporter "
        "target); 0 binds an ephemeral port",
    ),
    "ANOMALY_OTLP_GRPC_PORT": (
        "int", 4317,
        "OTLP/gRPC listen port (the collector's primary ingress); "
        "-1 disables the gRPC leg",
    ),
    "ANOMALY_METRICS_PORT": (
        "int", 9464,
        "Prometheus /metrics + /healthz listen port",
    ),
    "ANOMALY_BATCH": (
        "int", 2048,
        "device batch size (rows per dispatched step)",
    ),
    "ANOMALY_PUMP_INTERVAL_S": (
        "float", 0.05,
        "batch cadence seconds (the <100ms detection-lag budget "
        "spends half on batching)",
    ),
    "ANOMALY_HARVEST_INTERVAL": (
        "float", 0.0,
        "report readback cadence seconds (0 = harvest every batch; "
        "set on tunneled/remote devices where readback RTT dominates)",
    ),
    "ANOMALY_HARVEST_ASYNC": (
        "int", 0,
        "1 = fetch reports on a background harvester thread so "
        "dispatch never waits on a device->host round trip",
    ),
    "ANOMALY_ADAPTIVE_BATCH": (
        "int", 1,
        "adaptive dispatch-width controller (1 = on): widens batches "
        "in pow2 steps when readback can't keep pace; the width "
        "ladder precompiles in the background at boot",
    ),
    "ANOMALY_NUM_SERVICES": (
        "int", -1,
        "detector service-axis size (-1 = DetectorConfig default); "
        "smaller geometry shrinks compile time on small deployments",
    ),
    "ANOMALY_CMS_WIDTH": (
        "int", -1,
        "CMS sketch width (-1 = DetectorConfig default)",
    ),
    "ANOMALY_HLL_P": (
        "int", -1,
        "HLL precision p (-1 = DetectorConfig default)",
    ),
    "ANOMALY_WARMUP_BATCHES": (
        "float", -1.0,
        "EWMA warmup batches before z-scores count (-1 = "
        "DetectorConfig default)",
    ),
    "ANOMALY_Z_WARMUP_BATCHES": (
        "float", -1.0,
        "z-score suppression window in batches (-1 = DetectorConfig "
        "default)",
    ),
    "ANOMALY_CHECKPOINT": (
        "str", "",
        "snapshot path prefix (enables offset-keyed checkpoint/resume; "
        "empty = stateless)",
    ),
    "ANOMALY_CHECKPOINT_INTERVAL_S": (
        "float", 30.0,
        "snapshot cadence seconds",
    ),
    "ANOMALY_OTLP_MAX_BODY": (
        "int", 16777216,
        "ingest body-size cap in bytes (oversized exports answer "
        "413/RESOURCE_EXHAUSTED)",
    ),
    "FLAGD_FILE": (
        "str", "",
        "flagd-schema JSON path (hot-reloaded flag store; wins over "
        "OFREP_URL)",
    ),
    "OFREP_URL": (
        "str", "",
        "OFREP flag endpoint (used when FLAGD_FILE is unset)",
    ),
    "KAFKA_ADDR": (
        "str", "",
        "Kafka bootstrap for the orders topic (empty = no Kafka leg)",
    ),
}


# Device-put spine knobs (runtime.spine: the staging ring between the
# pipeline's batch assembly and the donated device step — pack + async
# device puts on a stager thread, overlapping batch k+1's host→device
# transfer with batch k's in-flight compute; runtime/daemon.py threads
# these into the pipeline). Same ONE-registry discipline as every
# other family — daemon, compose overlay, k8s generator and
# sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
SPINE_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_SPINE_RING": (
        "int", 2,
        "device-put staging ring depth: pre-allocated host batch "
        "buffers a stager thread packs + asynchronously puts through, "
        "so batch k+1's transfer rides behind batch k's in-flight "
        "donated step (2 = classic double buffering; 0 = spine off — "
        "pack+put inline on the pump thread, the pre-spine path)",
    ),
    "ANOMALY_SPINE_OVERLAP": (
        "int", 1,
        "1 = with a step in flight, dispatch only batches whose put "
        "already completed (transfer hidden behind compute; "
        "anomaly_spine_put_overlap_ratio tracks the hit rate); 0 = "
        "always wait for the put synchronously (A/B debugging)",
    ),
    "ANOMALY_SPINE_CHUNK_ROWS": (
        "int", 0,
        "rows per copy block when packing into a staging slot (cache "
        "blocking for the host pack loop); 0 = whole batch in one pass",
    ),
}


# Detector self-telemetry knobs (runtime.selftrace: the batch-lifecycle
# tracer exporting the daemon's OWN traces into the telemetry stack it
# monitors; runtime.flightrec: the flight-recorder event ring dumped as
# evidence on health/role transitions). Same ONE-registry discipline as
# every other family — daemon, compose overlay, k8s generator and
# sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
SELFTRACE_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_SELFTRACE_ENABLE": (
        "int", 1,
        "1 = trace sampled batch lifecycles (decode→…→flag) and export "
        "them through the background OTLP poster; 0 = tracer off "
        "(phase histograms and the flight recorder stay on — they are "
        "metrics/forensics, not traces)",
    ),
    "ANOMALY_SELFTRACE_SAMPLE": (
        "float", 0.01,
        "head-sampling rate in [0,1]: batch seq is hashed with "
        "splitmix64 and sampled below rate*2^64 — deterministic, so "
        "every replica and restart agrees which batches carry traces",
    ),
    "ANOMALY_SELFTRACE_ENDPOINT": (
        "str", "",
        "OTLP endpoint the detector's own traces export to "
        "(http(s)://host:4318 or grpc://host:4317 — the collector the "
        "shop already feeds, so detector batch traces land in the same "
        "Jaeger); empty = encode-only (tests/bench read the bytes)",
    ),
    "ANOMALY_SELFTRACE_FLIGHT_RING": (
        "int", 512,
        "flight-recorder ring size (structured runtime events: role/"
        "epoch moves, shed/brownout steps, fence hits, quarantines, "
        "phase snapshots); the ring is the /query/flight body and the "
        "dump payload",
    ),
    "ANOMALY_SELFTRACE_FLIGHT_DIR": (
        "str", "",
        "directory for flight-recorder evidence dumps written on every "
        "DEGRADED/SATURATED/FENCED/PROMOTING transition "
        "(flight-<reason>-<ms>.json, per-reason cooldown); empty = "
        "ring-only, nothing written",
    ),
}


# Time-travel history tier knobs (runtime.history: the compaction
# thread folding expiring window banks into an on-disk retention
# ladder of verified frames, the range-query read path, and the span
# capture leg runtime.replaybench replays; runtime/daemon.py threads
# them). Same ONE-registry discipline as every other family — daemon,
# compose overlay, k8s generator and sanitycheck.py all consume this
# dict. Values must stay literals (sanitycheck reads via
# ast.literal_eval, without importing jax).
HISTORY_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_HISTORY_DIR": (
        "str", "",
        "segment-log directory for the frame-native history store "
        "(empty = time-travel tier off: no compaction thread, range "
        "queries answer 404)",
    ),
    "ANOMALY_HISTORY_RUNGS": (
        "str", "1,60,3600",
        "retention-ladder rung spans in seconds, finest first; each "
        "rung folds the previous one's records by the sketch monoids "
        "(HLL max-merge, CMS add-merge; EWMA/CUSUM heads keep "
        "last-value-per-rung), so every rung must divide the next",
    ),
    "ANOMALY_HISTORY_RETENTION_S": (
        "str", "3600,86400,604800",
        "per-rung retention caps in seconds (one entry per rung): "
        "sealed segments whose newest record ages past the cap are "
        "deleted oldest-first; span-capture records share rung 0's cap",
    ),
    "ANOMALY_HISTORY_COMPACT_INTERVAL_S": (
        "float", 0.5,
        "compaction-thread tick seconds: how often the writer "
        "snapshots state (under the dispatch lock, same discipline as "
        "replication) looking for a completed shortest-window bank to "
        "fold into the ladder; keep below the shortest rung span or "
        "completed windows are missed (counted, never mis-merged)",
    ),
    "ANOMALY_HISTORY_SEGMENT_MB": (
        "int", 8,
        "segment roll size in MiB: the active segment seals "
        "(flush+fsync+rename, the checkpoint crash-safety discipline) "
        "and a new one opens once it grows past this",
    ),
    "ANOMALY_HISTORY_SPANS": (
        "str", "0",
        "span-batch capture policy for the replay corpus "
        "runtime.replaybench re-feeds through the real pipeline: "
        "'0' = off, '1' = capture every dispatched batch (one "
        "host-side column copy per batch plus rung-0-retention disk), "
        "or a per-service sample-rate map 'svc:rate,svc2:rate[,*:rate]' "
        "(rates in [0,1]; '*' is the default for unlisted services, 0 "
        "when absent) — record a mitigation drill's flagged service at "
        "100% without capturing the full firehose; rows sample "
        "deterministically by trace key, so reruns keep the same spans",
    ),
    "ANOMALY_HISTORY_REPLAY_RATE": (
        "float", 10.0,
        "target wall-clock speedup for replaybench (virtual-time "
        "clock injection re-feeds recorded frames at N x real time); "
        "bench.py gates replay_speedup against this",
    ),
}


# Closed-loop auto-mitigation knobs (runtime.remediation: the
# supervised controller that subscribes to the pipeline's per-service
# anomaly verdicts and — ONLY when opted in — drives the flagd
# mitigation flags and the sampling policy, then verifies its own
# action recovered the system). Same ONE-registry discipline as every
# other family — daemon, compose overlay, k8s generator and
# sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
REMEDIATION_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_REMEDIATION_ENABLE": (
        "int", 0,
        "1 = the controller ACTS (flips mitigation flags / promotes "
        "sampling) on a PRIMARY; 0 (the default — auto-mitigation is "
        "strictly opt-in) = observe-only: the controller tracks "
        "episodes and exports metrics but never writes an actuator",
    ),
    "ANOMALY_REMEDIATION_ACT_BATCHES": (
        "int", 3,
        "hysteresis, acting half: consecutive flagged batches a "
        "service must accrue before the controller actuates (one "
        "noisy batch must never flip a production flag)",
    ),
    "ANOMALY_REMEDIATION_CLEAR_BATCHES": (
        "int", 8,
        "hysteresis, clearing half: consecutive clean batches after "
        "an actuation before recovery is VERIFIED and the actuation "
        "reverts (also how long a MITIGATION_FAILED service stays "
        "sticky before the episode resets)",
    ),
    "ANOMALY_REMEDIATION_BUDGET": (
        "int", 4,
        "token-bucket capacity: maximum actuations in flight-window "
        "burst; a flapping detector exhausts the bucket and the flags "
        "STAY in their last state instead of oscillating",
    ),
    "ANOMALY_REMEDIATION_BUDGET_REFILL_S": (
        "float", 60.0,
        "seconds per token refill (observed-timebase): the sustained "
        "actuation rate ceiling, 1 action per this many seconds",
    ),
    "ANOMALY_REMEDIATION_DEADLINE_S": (
        "float", 30.0,
        "verified-recovery deadline (observed-timebase seconds after "
        "acting): no clean-streak verification within it rolls the "
        "actuation back and parks the service in MITIGATION_FAILED",
    ),
    "ANOMALY_REMEDIATION_ROLLBACK": (
        "int", 1,
        "1 = automatically roll the actuation back when the recovery "
        "deadline expires (restore the flag's prior state); 0 = leave "
        "the mitigation in place and only mark MITIGATION_FAILED "
        "(for mitigations an operator prefers sticky, e.g. load shed)",
    ),
    "ANOMALY_REMEDIATION_FLAG_URL": (
        "str", "",
        "remote flag-write base URL (the flag editor mounted on the "
        "shop gateway, e.g. http://gateway:8080/feature — the "
        "actuator calls its GET /api/read-file + POST "
        "/api/write-to-file routes); when set it wins over the local "
        "FLAGD_FILE store — every write is bounded-timeout with "
        "capped jittered retry, and a dead/slow endpoint queues or "
        "fails the ACTION, never the ingest path",
    ),
    "ANOMALY_REMEDIATION_TIMEOUT_S": (
        "float", 1.0,
        "per-actuator-write transport bound (connect/read); with the "
        "bounded retry count this caps what one sick flagd write can "
        "cost the worker thread",
    ),
    "ANOMALY_REMEDIATION_SAMPLING": (
        "int", 1,
        "1 = the sampling-policy actuator runs beside the flagd one: "
        "a flagged service is promoted to keep-100% span capture "
        "(seeded with its flag-time exemplar trace ids) while quiet "
        "services keep the configured ANOMALY_HISTORY_SPANS policy; "
        "0 = flagd actuator only",
    ),
    "ANOMALY_REMEDIATION_COLLECTOR_PATH": (
        "str", "",
        "collector-steering leg, file transport: path the "
        "CollectorActuator atomically writes its rendered "
        "tail-sampling policy document to (an otelcol config "
        "reloader/sidecar watches it — see "
        "deploy/otelcol-config-anomaly.yml); empty AND no URL = the "
        "collector actuator is off (the default)",
    ),
    "ANOMALY_REMEDIATION_COLLECTOR_URL": (
        "str", "",
        "collector-steering leg, HTTP transport: base URL whose POST "
        "/api/sampling-policy receives the rendered tail-sampling "
        "policy (bounded timeout + the worker's capped jittered "
        "retry); wins over the file path when both are set",
    ),
    "ANOMALY_REMEDIATION_COLLECTOR_BASE_KEEP": (
        "float", 0.1,
        "head-sampling keep fraction [0,1] for QUIET services in the "
        "pushed collector policy (flagged services always keep 1.0); "
        "the policy-implied storage fraction is exported as "
        "anomaly_collector_keep_ratio",
    ),
}


# Sharded-fleet knobs (runtime.fleet: consistent-hash keyspace
# partitioning over (service × tenant) keys, heartbeat membership with
# guardrailed reshard; runtime.aggregator: the scatter-gather read tier
# behind the existing /query/* API). Same ONE-registry discipline as
# every other family — daemon, compose overlay, k8s generator and
# sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
FLEET_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_FLEET_SHARDS": (
        "int", 0,
        "detector shard count N (0/1 = fleet off: the classic single "
        "primary + hot standby deployment); each shard is a FULL "
        "daemon — its own epoch fence, standby, history, remediation "
        "gating — consuming only its assigned Kafka partitions / "
        "OTLP-routed slice of the keyspace",
    ),
    "ANOMALY_FLEET_SHARD_INDEX": (
        "int", 0,
        "this shard's index in 0..N-1 (its ring member id is "
        "shard-<index>); Kafka partition assignment and the "
        "collector's OTLP routing key off the same index",
    ),
    "ANOMALY_FLEET_PEERS": (
        "str", "",
        "comma list of PEER health addresses host:metrics_port, "
        "index-aligned with the shard indices (this shard's own entry "
        "may be present and is skipped): the membership heartbeat "
        "polls each peer's /healthz on this address",
    ),
    "ANOMALY_FLEET_QUERY_PEERS": (
        "str", "",
        "comma list of shard QUERY-plane addresses host:query_port, "
        "index-aligned like ANOMALY_FLEET_PEERS: the aggregator tier "
        "fans /query/* out to these and merges the shard frames",
    ),
    "ANOMALY_FLEET_VNODES": (
        "int", 128,
        "virtual nodes per shard on the consistent-hash ring: more "
        "vnodes = tighter balance (the fleet suite pins the balance "
        "bound at this default) at O(N*vnodes) ring-build cost",
    ),
    "ANOMALY_FLEET_SERVICES": (
        "str", "",
        "comma list of service names PRE-INTERNED in this exact order "
        "on every shard at boot — the shared service-id table that "
        "makes cross-shard monoid merges (reshard frame adoption) "
        "bit-exact: CMS cells fold the service id into the key hash, "
        "so shards whose intern tables drift cannot exchange frames; "
        "empty = dynamic interning (single-shard behavior)",
    ),
    "ANOMALY_FLEET_HEARTBEAT_S": (
        "float", 1.0,
        "membership heartbeat cadence seconds (one /healthz poll per "
        "peer per tick)",
    ),
    "ANOMALY_FLEET_DEAD_AFTER_S": (
        "float", 3.0,
        "hysteresis, down edge: heartbeat silence seconds before a "
        "peer is DECLARED dead and its key range reassigned — but "
        "only after the health double-check below also fails (a "
        "compile-stalled-but-serving shard is not dead)",
    ),
    "ANOMALY_FLEET_REJOIN_AFTER_S": (
        "float", 5.0,
        "hysteresis, up edge: a dead peer must answer heartbeats "
        "continuously for this long before it REJOINS the ring (a "
        "flapping shard cannot thrash the keyspace on every blip)",
    ),
    "ANOMALY_FLEET_RESHARD_BUDGET": (
        "int", 4,
        "token-bucket capacity on ring membership changes: a flapping "
        "shard exhausts the bucket and the ring FREEZES in its last "
        "state (reshards refused + counted) instead of thrashing — "
        "the PR 2 brownout-ladder / PR 13 actuation-budget guardrail "
        "construction",
    ),
    "ANOMALY_FLEET_RESHARD_REFILL_S": (
        "float", 60.0,
        "seconds per reshard-budget token refill: the sustained "
        "membership-change rate ceiling, 1 reshard per this many "
        "seconds",
    ),
    "ANOMALY_FLEET_TENANTS": (
        "str", "",
        "per-tenant sketch namespaces: a comma map "
        "'service:tenant[,*:tenant]' assigning every service to a "
        "tenant ('*' is the default for unlisted services; absent = "
        "tenant 'default') — ring keys are tenant/service, and the "
        "per-tenant quota below sheds one noisy tenant's rows alone "
        "(anomaly_shed_rows_total{tenant=})",
    ),
    "ANOMALY_FLEET_TENANT_QUOTA_ROWS_S": (
        "float", 0.0,
        "per-tenant admission quota in rows/second (token bucket, 1 s "
        "burst), folded into the backpressure ladder AHEAD of the "
        "global row budget: a tenant over quota has its OK-lane rows "
        "shed (error lane always passes) while other tenants' "
        "admission and TTD are untouched; 0 = no per-tenant quota",
    ),
    "ANOMALY_AGGREGATOR_PORT": (
        "int", -1,
        "scatter-gather aggregator HTTP port (the fleet-global "
        "/query/* surface; runtime.aggregator main): -1 = this "
        "process serves no aggregator, 0 = ephemeral",
    ),
    "ANOMALY_AGGREGATOR_TIMEOUT_S": (
        "float", 1.0,
        "per-shard fan-out timeout seconds: a shard that cannot "
        "answer within this is annotated missing and the merged "
        "answer degrades to a labeled PARTIAL result "
        "(shards_answered/shards_total) — never a crashed query",
    ),
    "ANOMALY_FLEET_REPL_PEERS": (
        "str", "",
        "comma list of per-shard REPLICATION-stream addresses "
        "host:repl_port, index-aligned like ANOMALY_FLEET_PEERS: each "
        "shard subscribes a standby mirror to its ring-successor's "
        "stream so a declared-dead pair's keyspace is ADOPTED by the "
        "survivor automatically (merge under the dispatch lock, new "
        "ring version, flight-recorded) with zero operator action; "
        "empty = no adoption mirrors (the PR 14 operator-merge "
        "behavior)",
    ),
}


# Fleet autoscaler knobs (runtime.autoscale: the supervised,
# STRICTLY OPT-IN controller that proposes shard split on sustained
# brownout and join on sustained idle, behind the same token-bucket +
# two-edge-hysteresis guardrails as remediation and reshard — a
# flapping load shape exhausts the budget and FREEZES the ring instead
# of oscillating it; every decision is epoch-fenced, the sixth fenced
# path, and evidence-dumped). Same ONE-registry discipline as every
# other family — daemon, compose overlay, k8s generator and
# sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
AUTOSCALE_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_AUTOSCALE_ENABLE": (
        "int", 0,
        "1 = the autoscaler PROPOSES ring resizes (split/join) on a "
        "PRIMARY; 0 (the default — elastic scaling is strictly "
        "opt-in) = observe-only: the controller tracks saturation "
        "streaks and exports metrics but never proposes",
    ),
    "ANOMALY_AUTOSCALE_ACT_BATCHES": (
        "int", 5,
        "hysteresis, acting half: consecutive observation windows at "
        "or above the high watermark before a SPLIT is proposed (one "
        "noisy window must never resize a production ring)",
    ),
    "ANOMALY_AUTOSCALE_CLEAR_BATCHES": (
        "int", 30,
        "hysteresis, clearing half: consecutive windows at or below "
        "the low watermark before a JOIN is proposed — deliberately "
        "much longer than the acting half (scaling in is cheap to "
        "defer, expensive to regret)",
    ),
    "ANOMALY_AUTOSCALE_BUDGET": (
        "int", 2,
        "token-bucket capacity on resize proposals: a flapping load "
        "shape exhausts the bucket and the ring FREEZES in its last "
        "shape (proposals refused + counted) instead of oscillating",
    ),
    "ANOMALY_AUTOSCALE_REFILL_S": (
        "float", 300.0,
        "seconds per proposal-budget token refill (observed "
        "timebase): the sustained resize rate ceiling, 1 proposal "
        "per this many seconds",
    ),
    "ANOMALY_AUTOSCALE_HIGH_WATER": (
        "float", 0.75,
        "two-edge hysteresis, upper edge: saturation score (max of "
        "admission watermark fraction, shed activity, brownout "
        "level) at or above which a window counts toward the split "
        "streak",
    ),
    "ANOMALY_AUTOSCALE_LOW_WATER": (
        "float", 0.15,
        "two-edge hysteresis, lower edge: saturation score at or "
        "below which a window counts toward the join streak; scores "
        "between the edges reset BOTH streaks (the dead band that "
        "makes a flapping shape freeze instead of oscillate)",
    ),
    "ANOMALY_AUTOSCALE_MIN_SHARDS": (
        "int", 2,
        "floor on the proposed fleet size: join proposals below it "
        "are refused (counted) — the fleet never scales itself back "
        "to a single point of failure",
    ),
    "ANOMALY_AUTOSCALE_MAX_SHARDS": (
        "int", 8,
        "ceiling on the proposed fleet size: split proposals above "
        "it are refused (counted) — a runaway load shape cannot "
        "demand unbounded hardware",
    ),
}


# Counterfactual pre-flight knobs (runtime.shadow: before the
# remediation controller releases an actuator write, replay the last
# WINDOW_S of recorded span frames through a fresh shadow pipeline
# with the proposed mitigation applied, and refuse acts whose shadow
# heads do not clear). Same ONE-registry discipline as every other
# family — daemon, compose overlay, k8s generator and sanitycheck.py
# all consume this dict. Values must stay literals (sanitycheck reads
# via ast.literal_eval, without importing jax).
SHADOW_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_SHADOW_ENABLE": (
        "int", 0,
        "1 = every remediation act is pre-flighted on a shadow replay "
        "of recorded history before any actuator write (requires "
        "ANOMALY_HISTORY_DIR + ANOMALY_HISTORY_SPANS span capture); "
        "0 (the default — the gate is strictly opt-in like every "
        "controller tier) = PR 13 behavior, act on hysteresis alone",
    ),
    "ANOMALY_SHADOW_WINDOW_S": (
        "float", 120.0,
        "how far back the counterfactual replay reaches: the recorded "
        "span window (seconds, recorded timebase) re-fed through the "
        "shadow pipeline with the mitigation transform applied",
    ),
    "ANOMALY_SHADOW_RATE": (
        "float", 10.0,
        "minimum recorded-seconds-per-wall-second the shadow replay "
        "must sustain (the replaybench >=10x discipline) — gated by "
        "the mitigbench shadow leg, measured on every verdict",
    ),
    "ANOMALY_SHADOW_DEADLINE_S": (
        "float", 5.0,
        "verification deadline (wall seconds): a shadow replay still "
        "running past it REFUSES the act (fail closed, "
        "reason=deadline) — a slow verifier must delay mitigation, "
        "never release an unproven one",
    ),
    "ANOMALY_SHADOW_MIN_RECORDS": (
        "int", 20,
        "minimum recorded span batches inside the window for a "
        "verdict; fewer = the counterfactual is unprovable and the "
        "act is refused (fail closed, reason=insufficient_records)",
    ),
}


# Verdict provenance knobs (runtime.provenance: the per-verdict
# evidence engine — at flag time a bounded JSON-able bundle is built
# per flagged service: firing head, head trajectories over the last K
# windows, CMS top-k contributors, HLL cardinality delta, exemplar +
# selftrace ids — served live by /query/explain, replicated in the
# query_meta block, persisted through the history retention ladder and
# exported as OTLP log records). Same ONE-registry discipline as every
# other family — daemon, compose overlay, k8s generator and
# sanitycheck.py all consume this dict. Values must stay literals
# (sanitycheck reads via ast.literal_eval, without importing jax).
PROVENANCE_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_PROVENANCE_ENABLE": (
        "int", 1,
        "1 = build an evidence bundle per flagged service at flag "
        "time (harvester thread, beside exemplar capture) and serve "
        "it on /query/explain; 0 = provenance off (flags and "
        "exemplars still capture — bundles are explanation, not "
        "detection)",
    ),
    "ANOMALY_PROVENANCE_RING": (
        "int", 64,
        "bounded bundle ring size (newest wins): the live "
        "/query/explain depth, and — because the ring rides the "
        "replicated query_meta block — the replica's too",
    ),
    "ANOMALY_PROVENANCE_TOPK": (
        "int", 5,
        "heavy-hitter contributors per bundle: the top-k candidate "
        "attribute CRCs folded through the CMS under the dispatch "
        "lock at flag time (the /query/topk fold, snapshotted into "
        "evidence)",
    ),
    "ANOMALY_PROVENANCE_TRAJECTORY_WINDOWS": (
        "int", 16,
        "per-service head-trajectory depth (reports): how many "
        "recent harvested windows of z/CUSUM/cardinality each "
        "bundle replays — ring-buffered host-side from stats "
        "already fetched, never an extra device round trip",
    ),
}


# Native front-door knobs (runtime/frontdoor.py + native/frontdoor.cc:
# the zero-Python OTLP/HTTP acceptor that recv's request bodies into
# native buffers and tickets them straight to the decode pool).
# Strictly OPT-IN: enable defaults to 0 and the Python receiver stays
# the default path — the front door is a second, faster door into the
# SAME bounded admission queue, never a replacement contract. Values
# must stay literals (sanitycheck reads via ast.literal_eval).
FRONTDOOR_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_FRONTDOOR_ENABLE": (
        "int", 0,
        "1 = start the native OTLP/HTTP front door (socket→scratch→"
        "scan, zero Python per payload); 0 (default) = Python "
        "receiver only — opt-in, never implicit",
    ),
    "ANOMALY_FRONTDOOR_PORT": (
        "int", 4316,
        "front-door listen port (distinct from the Python receiver's "
        "4318 — both can serve at once during migration; 0 = ephemeral "
        "for tests)",
    ),
    "ANOMALY_FRONTDOOR_PUMPS": (
        "int", 1,
        "verdict-pump threads draining native tickets into the decode "
        "pool (each drains whole batches per GIL-released call; 1 is "
        "enough below ~10 Gb/s of OTLP)",
    ),
    "ANOMALY_FRONTDOOR_BATCH": (
        "int", 64,
        "max tickets one pump drain hands the decode pool before "
        "resolving verdicts (mirrors ANOMALY_INGEST_COALESCE: an idle "
        "stream still sees single-request latency)",
    ),
    "ANOMALY_FRONTDOOR_MAX_CONNS": (
        "int", 64,
        "concurrent front-door connections; the acceptor answers 503 "
        "past the cap instead of queueing unbounded sockets",
    ),
}


# Key lifecycle knobs (runtime/keyspace.py: the memory-budgeted
# keyspace plane — idle-key eviction folding sketch rows into history
# under the dispatch lock, intern-id recycling behind a generation
# epoch, and the keyspace degradation ladder: evict → per-tenant
# new-key throttle → overflow-collapse → 429). The watchdog gauges
# (anomaly_process_rss_bytes + row/interner fill) export regardless of
# the enable bit, so a cardinality bomb is visible even with the
# ladder off. Values must stay literals (sanitycheck reads via
# ast.literal_eval).
KEYSPACE_KNOBS: dict[str, tuple[str, object, str]] = {
    "ANOMALY_KEYSPACE_ENABLE": (
        "int", 1,
        "1 = keyspace lifecycle plane on (idle eviction + degradation "
        "ladder under pressure); 0 = watchdog gauges only — the table "
        "reverts to append-only-then-overflow",
    ),
    "ANOMALY_KEYSPACE_HIGH_WATERMARK": (
        "float", 0.85,
        "live-row fill fraction (interned keys / table capacity) above "
        "which the keyspace ladder counts pressure; two-edge "
        "hysteresis against the low watermark",
    ),
    "ANOMALY_KEYSPACE_LOW_WATERMARK": (
        "float", 0.70,
        "fill fraction the ladder must fall below before de-escalating "
        "(the hysteresis lower edge; must be < high watermark)",
    ),
    "ANOMALY_KEYSPACE_IDLE_S": (
        "float", 300.0,
        "a key with no rows admitted for this long is eviction-"
        "eligible under pressure; its sketch/head rows fold into a "
        "history record before the id recycles",
    ),
    "ANOMALY_KEYSPACE_HOLD_S": (
        "float", 5.0,
        "seconds of SUSTAINED pressure (or relief) per ladder edge — "
        "the same two-edge hysteresis hold the brownout ladder uses, "
        "so one fill spike never staircases straight to 429",
    ),
    "ANOMALY_KEYSPACE_EVICT_BATCH": (
        "int", 64,
        "max idle keys folded out per evictor sweep; bounds how long "
        "one sweep holds the dispatch lock",
    ),
    "ANOMALY_KEYSPACE_RSS_MB": (
        "float", 0.0,
        "process RSS budget in MB; above it the watchdog counts "
        "pressure even when the intern table has room (0 = no RSS "
        "budget — fill-fraction pressure only)",
    ),
    "ANOMALY_KEYSPACE_NEWKEY_RATE": (
        "float", 64.0,
        "per-tenant NEW-key admissions per second once the ladder "
        "reaches the throttle rung; keys past the budget collapse to "
        "the overflow bucket (counted per tenant)",
    ),
    "ANOMALY_KEYSPACE_RETRY_AFTER_S": (
        "float", 2.0,
        "Retry-After hint (seconds) the ingest doors return with 429 "
        "once the keyspace ladder reaches its shed rung",
    ),
}


# Registries whose knobs ride the DEPLOY surfaces: every knob in these
# must be threaded through runtime/daemon.py, the compose overlay and
# the k8s generator (scripts/staticcheck knob-discipline pass +
# scripts/sanitycheck.py both assert the correspondence). The harness
# registries below this tuple only legitimize env reads — a chaos
# proxy or a bench driver has no business in the fleet compose file.
DEPLOYED_KNOB_REGISTRIES: tuple[str, ...] = (
    "DAEMON_KNOBS", "OVERLOAD_KNOBS", "INGEST_KNOBS",
    "REPLICATION_KNOBS", "FRAME_KNOBS", "QUERY_KNOBS", "SPINE_KNOBS",
    "SELFTRACE_KNOBS", "HISTORY_KNOBS", "REMEDIATION_KNOBS",
    "FLEET_KNOBS", "AUTOSCALE_KNOBS", "SHADOW_KNOBS",
    "PROVENANCE_KNOBS", "FRONTDOOR_KNOBS", "KEYSPACE_KNOBS",
)


# Chaos-harness knobs (runtime/faultwire.py: the fault-injection TCP
# proxy tests/test_chaos.py and test_frame.py drive). Registered so
# the knob-discipline pass can resolve the proxy's env reads; NOT a
# deployed registry — faults are injected by test harnesses, not by
# the fleet config.
FAULTWIRE_KNOBS: dict[str, tuple[str, object, str]] = {
    "FAULTWIRE_DELAY_MS": ("float", 0.0, "per-direction added latency"),
    "FAULTWIRE_TRUNCATE_AFTER": (
        "str", "", "close each connection after N relayed bytes",
    ),
    "FAULTWIRE_RST": ("int", 0, "1 = RST every connect immediately"),
    "FAULTWIRE_BLACKHOLE": (
        "int", 0, "1 = accept then drop all bytes (half-open link)",
    ),
    "FAULTWIRE_CORRUPT_RATE": (
        "float", 0.0, "per-byte bit-flip probability (seeded)",
    ),
    "FAULTWIRE_CORRUPT_SEED": ("int", 0, "bit-flip plan seed"),
    "FAULTWIRE_CORRUPT_OFFSET": (
        "int", 0, "absolute stream offset where corruption starts",
    ),
}


# Dev-harness knobs (scripts/serve_shop.py, scripts/serve_kafka.py and
# the in-proc load generator): CLI-default conveniences for the local
# shop stack. Registered, not deployed.
SHOP_KNOBS: dict[str, tuple[str, object, str]] = {
    "SHOP_PORT": ("int", 8080, "gateway listen port"),
    "SHOP_USERS": ("int", 0, "simulated browsing users"),
    "SHOP_MINIMAL": ("str", "", "non-empty = reduced profile"),
    "SHOP_GRPC_PORT": ("int", -1, "gRPC edge port (-1 off)"),
    "KAFKA_PORT": ("int", 9092, "in-repo broker listen port"),
    "OTEL_EXPORTER_OTLP_ENDPOINT": (
        "str", "", "where the shop exports OTLP (reference env name)",
    ),
    "LOCUST_BROWSER_TRAFFIC_ENABLED": (
        "str", "",
        "truthy = the load generator adds browser-shaped traffic "
        "(reference locustfile env name)",
    ),
}


# Benchmark scaffolding knobs (bench.py): section toggles and load
# shapes for the flagship benchmark line. Registered, not deployed.
BENCH_KNOBS: dict[str, tuple[str, object, str]] = {
    "BENCH_BATCH": ("int", 2097152, "device sketch benchmark batch"),
    "BENCH_MATRIX": ("int", 1, "0 skips the sketch impl matrix"),
    "BENCH_INGEST": ("int", 1, "0 skips host-ingest benches"),
    "BENCH_REPL": ("int", 1, "0 skips the replication/failover drill"),
    "BENCH_QUERY": ("int", 1, "0 skips the query-plane bench"),
    "BENCH_QUALITY": ("int", 1, "0 skips detection-quality scenarios"),
    "BENCH_LAG_STRESS": ("int", 1, "0 skips the lag stress leg"),
    "BENCH_LAG_RATE": ("float", 2000.0, "lag bench offered spans/s"),
    "BENCH_LAG_SECONDS": ("float", 12.0, "lag bench duration"),
    "BENCH_SPINE": ("int", 1, "0 skips the e2e ingest-spine bench"),
    "BENCH_SELFTRACE": (
        "int", 1,
        "0 skips the self-telemetry overhead A/B (tracer-on vs "
        "tracer-off spinebench, gated <= 1.03)",
    ),
    "BENCH_EXPLAIN": (
        "int", 1,
        "0 skips the provenance overhead A/B (evidence-engine-on vs "
        "off spinebench, gated <= 1.03) and the /query/explain "
        "latency leg",
    ),
    "BENCH_SPINE_SECONDS": (
        "float", 6.0, "e2e spine bench duration per configuration",
    ),
    "BENCH_REPLAY": (
        "int", 1,
        "0 skips the history replay bench (record a synthetic "
        "incident, replay the recorded frames through the real "
        "pipeline at N x wall clock, pin bit-identical verdicts)",
    ),
    "BENCH_MITIG": (
        "int", 1,
        "0 skips the closed-loop mitigation bench (runtime.mitigbench:"
        " time-to-mitigate beside time-to-detect per flagd scenario, "
        "rollback drill, no-oscillation gate over a long clean run)",
    ),
    "BENCH_FLEET": (
        "int", 1,
        "0 skips the sharded-fleet reshard drill (runtime.replbench "
        "measure_reshard: kill a shard beside an unkilled witness "
        "fleet, reshard TTD, witness-pinned bit-exact answers, "
        "blackholed-shard partial answers, noisy-tenant isolation)",
    ),
    "BENCH_AUTOSCALE": (
        "int", 1,
        "0 skips the elastic-fleet drill (runtime.replbench "
        "measure_adoption: ramp load to brownout, watch the "
        "autoscaler propose scale-out, SIGKILL a shard mid-resize, "
        "pin the automatic adoption bit-exact against an unkilled "
        "witness; lifts autoscale_tta_s and autoscale_ok)",
    ),
    "BENCH_SHADOW": (
        "int", 1,
        "0 skips the counterfactual pre-flight leg of the mitigation "
        "bench (runtime.mitigbench --shadow: released + refused "
        "verdict drills through a preflighted controller, "
        "shadow-vs-replaybench bit-identity at >= ANOMALY_SHADOW_RATE "
        "x wall, collector keep-ratio measurement; lifts "
        "preflight_refusal_ok and preflight_verdict_s)",
    ),
    "BENCH_FRONTDOOR": (
        "int", 1,
        "0 skips the native front-door leg (runtime.frontdoorbench: "
        "front-door spans/s vs the in-process pool at matched "
        "workers + the >=1M-distinct-key cardinality soak; lifts "
        "frontdoor_ok and frontdoor_soak_ok)",
    ),
    "BENCH_FRONTDOOR_WORKERS": (
        "int", 2, "front-door bench decode workers per side",
    ),
    "BENCH_FRONTDOOR_SECONDS": (
        "float", 4.0, "front-door vs pool timed-run duration",
    ),
    "BENCH_FRONTDOOR_KEYS": (
        "int", 1048576,
        "distinct (tenant x service) keys the cardinality soak must "
        "push through ingest->sketch->query",
    ),
    "BENCH_CHURN_WAVES": (
        "int", 8,
        "churn-soak waves (each wave: a fresh churn cohort past the "
        "key budget, an eviction sweep, a live-cohort liveness + "
        "evicted-query + generation-refusal probe; lifts churn_ok)",
    ),
}


# Native-build knobs (runtime/native.py's on-demand kernel compile)
# and check-pipeline plumbing (scripts/sanitycheck.py).
BUILD_KNOBS: dict[str, tuple[str, object, str]] = {
    "CXX": ("str", "g++", "C++ compiler for the native kernels"),
    "SANITYCHECK_SKIP_STATICCHECK": (
        "int", 0,
        "1 = the caller (make check) already ran the full staticcheck; "
        "sanitycheck skips its delegated frame-monopoly re-run instead "
        "of parsing the tree twice",
    ),
}


def _resolve(registry: dict) -> dict[str, int | float | str]:
    out: dict[str, int | float | str] = {}
    for env_name, (kind, default, _help) in registry.items():
        out[env_name] = (
            env_int(env_name, default) if kind == "int"
            else env_float(env_name, default) if kind == "float"
            else env_str(env_name, default)
        )
    return out


def overload_config() -> dict[str, int | float]:
    """Resolve every OVERLOAD_KNOBS entry from the environment (typed,
    defaulted, hard-fail on malformed values — mustMapEnv discipline)."""
    return _resolve(OVERLOAD_KNOBS)


def ingest_config() -> dict[str, int | float]:
    """Resolve every INGEST_KNOBS entry from the environment (same
    contract as :func:`overload_config`)."""
    return _resolve(INGEST_KNOBS)


def frame_config() -> dict[str, int | float | str]:
    """Resolve every FRAME_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the write version
    against the reader window — a version nobody could read back must
    refuse to boot, not corrupt-by-construction."""
    out = _resolve(FRAME_KNOBS)
    # Literal window bounds (not an import of runtime.frame: this
    # module stays jax/numpy-free for sanitycheck's AST read); the
    # correspondence with frame.MIN_READ_VERSION..FRAME_VERSION is
    # asserted by tests/test_frame.py.
    if not 1 <= int(out["ANOMALY_FRAME_WRITE_VERSION"]) <= 2:
        raise ConfigError(
            f"ANOMALY_FRAME_WRITE_VERSION="
            f"{out['ANOMALY_FRAME_WRITE_VERSION']} outside the readable "
            "window 1..2"
        )
    return out


def query_config() -> dict[str, int | float]:
    """Resolve every QUERY_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the shape knobs —
    a query plane with a zero-deep timeline or a negative staleness
    budget must refuse to boot, not serve nonsense."""
    out = _resolve(QUERY_KNOBS)
    if int(out["ANOMALY_QUERY_TOPK"]) < 1:
        raise ConfigError(
            f"ANOMALY_QUERY_TOPK={out['ANOMALY_QUERY_TOPK']} must be >= 1"
        )
    if int(out["ANOMALY_QUERY_TIMELINE"]) < 1:
        raise ConfigError(
            f"ANOMALY_QUERY_TIMELINE={out['ANOMALY_QUERY_TIMELINE']} "
            "must be >= 1"
        )
    if int(out["ANOMALY_QUERY_CANDIDATES"]) < int(out["ANOMALY_QUERY_TOPK"]):
        raise ConfigError(
            f"ANOMALY_QUERY_CANDIDATES={out['ANOMALY_QUERY_CANDIDATES']} "
            f"below ANOMALY_QUERY_TOPK={out['ANOMALY_QUERY_TOPK']}: "
            "top-k could never rank k candidates"
        )
    if float(out["ANOMALY_QUERY_MAX_STALENESS_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_QUERY_MAX_STALENESS_S="
            f"{out['ANOMALY_QUERY_MAX_STALENESS_S']} must be > 0"
        )
    return out


def spine_config() -> dict[str, int | float]:
    """Resolve every SPINE_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the shapes — a
    negative ring depth or copy block must refuse to boot."""
    out = _resolve(SPINE_KNOBS)
    if int(out["ANOMALY_SPINE_RING"]) < 0:
        raise ConfigError(
            f"ANOMALY_SPINE_RING={out['ANOMALY_SPINE_RING']} must be "
            ">= 0 (0 disables the spine)"
        )
    if int(out["ANOMALY_SPINE_CHUNK_ROWS"]) < 0:
        raise ConfigError(
            "ANOMALY_SPINE_CHUNK_ROWS="
            f"{out['ANOMALY_SPINE_CHUNK_ROWS']} must be >= 0"
        )
    return out


def daemon_config() -> dict[str, int | float | str]:
    """Resolve every DAEMON_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the shape knobs —
    a daemon with a zero batch or a non-positive pump cadence must
    refuse to boot, not spin."""
    out = _resolve(DAEMON_KNOBS)
    if int(out["ANOMALY_BATCH"]) < 1:
        raise ConfigError(
            f"ANOMALY_BATCH={out['ANOMALY_BATCH']} must be >= 1"
        )
    if float(out["ANOMALY_PUMP_INTERVAL_S"]) <= 0:
        raise ConfigError(
            f"ANOMALY_PUMP_INTERVAL_S={out['ANOMALY_PUMP_INTERVAL_S']} "
            "must be > 0"
        )
    if int(out["ANOMALY_OTLP_MAX_BODY"]) < 1:
        raise ConfigError(
            f"ANOMALY_OTLP_MAX_BODY={out['ANOMALY_OTLP_MAX_BODY']} "
            "must be >= 1"
        )
    if float(out["ANOMALY_CHECKPOINT_INTERVAL_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_CHECKPOINT_INTERVAL_S="
            f"{out['ANOMALY_CHECKPOINT_INTERVAL_S']} must be > 0"
        )
    return out


def selftrace_config() -> dict[str, int | float | str]:
    """Resolve every SELFTRACE_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the shapes — a
    sampling rate outside [0,1] or a zero flight ring must refuse to
    boot, not mis-sample silently."""
    out = _resolve(SELFTRACE_KNOBS)
    sample = float(out["ANOMALY_SELFTRACE_SAMPLE"])
    if not 0.0 <= sample <= 1.0:
        raise ConfigError(
            f"ANOMALY_SELFTRACE_SAMPLE={sample} outside [0, 1]"
        )
    if int(out["ANOMALY_SELFTRACE_FLIGHT_RING"]) < 1:
        raise ConfigError(
            "ANOMALY_SELFTRACE_FLIGHT_RING="
            f"{out['ANOMALY_SELFTRACE_FLIGHT_RING']} must be >= 1"
        )
    return out


def history_ladder(
    rungs_raw, retention_raw
) -> tuple[tuple[float, ...], tuple[float, ...]]:
    """Parsed ``(rung_spans_s, retention_s)`` from the two raw
    comma-separated ladder knob values — the ONE parse, shared by the
    validator below and the daemon (two copies of the split/float
    could drift, and then the values validated would not be the
    values used). Callers pass the knob values themselves so the
    consuming subscripts stay visible to the knob-discipline pass."""
    rungs = tuple(
        float(r) for r in str(rungs_raw).split(",") if r.strip()
    )
    retention = tuple(
        float(r) for r in str(retention_raw).split(",") if r.strip()
    )
    return rungs, retention


def history_spans_policy(raw) -> tuple[bool, dict[str, float]]:
    """Parsed ``(capture_on, {service: rate})`` from the raw
    ``ANOMALY_HISTORY_SPANS`` knob value — the ONE parse, shared by
    :func:`history_config`'s validator and the daemon (the same
    no-drift rule as :func:`history_ladder`).

    ``'0'``/``''`` → off; ``'1'`` → capture everything
    (``{'*': 1.0}``); otherwise a comma map ``svc:rate[,*:rate]`` with
    rates in [0, 1] (``'*'`` is the default rate for unlisted
    services; absent = 0, so a map names exactly what it records).
    Raises ``ConfigError`` on malformed entries or out-of-range rates.
    """
    text = str(raw).strip()
    if text in ("", "0"):
        return False, {}
    if text == "1":
        return True, {"*": 1.0}
    rates: dict[str, float] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ConfigError(
                f"ANOMALY_HISTORY_SPANS entry {part!r} is not "
                "'service:rate' (or the literal '0'/'1')"
            )
        name, rate_raw = part.rsplit(":", 1)
        name = name.strip()
        try:
            rate = float(rate_raw)
        except ValueError as e:
            raise ConfigError(
                f"ANOMALY_HISTORY_SPANS rate {rate_raw!r} for "
                f"{name!r} is not a number"
            ) from e
        if not 0.0 <= rate <= 1.0:
            raise ConfigError(
                f"ANOMALY_HISTORY_SPANS rate {rate} for {name!r} "
                "outside [0, 1]"
            )
        if not name:
            raise ConfigError(
                "ANOMALY_HISTORY_SPANS has an empty service name"
            )
        rates[name] = rate
    if not rates:
        raise ConfigError(
            f"ANOMALY_HISTORY_SPANS={text!r} parsed to an empty map"
        )
    return True, rates


def history_config() -> dict[str, int | float | str]:
    """Resolve every HISTORY_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the ladder shape —
    rungs must be positive, ascending, and each must divide the next
    (a rung that doesn't divide its parent can never fold exactly N
    child records into one parent record), with one retention cap per
    rung. A ladder nobody can fold must refuse to boot."""
    out = _resolve(HISTORY_KNOBS)
    try:
        rungs, retention = history_ladder(
            out["ANOMALY_HISTORY_RUNGS"],
            out["ANOMALY_HISTORY_RETENTION_S"],
        )
    except ValueError as e:
        raise ConfigError(
            "ANOMALY_HISTORY_RUNGS/RETENTION_S must be comma-separated "
            f"numbers: {e}"
        ) from e
    if not rungs or any(r <= 0 for r in rungs):
        raise ConfigError(
            f"ANOMALY_HISTORY_RUNGS={out['ANOMALY_HISTORY_RUNGS']!r} "
            "needs at least one positive rung span"
        )
    for fine, coarse in zip(rungs, rungs[1:]):
        if coarse <= fine or (coarse / fine) != int(coarse / fine):
            raise ConfigError(
                f"ANOMALY_HISTORY_RUNGS={out['ANOMALY_HISTORY_RUNGS']!r}"
                ": rungs must ascend and each must divide the next "
                f"({fine} -> {coarse})"
            )
    if len(retention) != len(rungs):
        raise ConfigError(
            "ANOMALY_HISTORY_RETENTION_S needs one cap per rung "
            f"({len(retention)} caps for {len(rungs)} rungs)"
        )
    if float(out["ANOMALY_HISTORY_COMPACT_INTERVAL_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_HISTORY_COMPACT_INTERVAL_S="
            f"{out['ANOMALY_HISTORY_COMPACT_INTERVAL_S']} must be > 0"
        )
    if int(out["ANOMALY_HISTORY_SEGMENT_MB"]) < 1:
        raise ConfigError(
            f"ANOMALY_HISTORY_SEGMENT_MB={out['ANOMALY_HISTORY_SEGMENT_MB']}"
            " must be >= 1"
        )
    if float(out["ANOMALY_HISTORY_REPLAY_RATE"]) <= 0:
        raise ConfigError(
            "ANOMALY_HISTORY_REPLAY_RATE="
            f"{out['ANOMALY_HISTORY_REPLAY_RATE']} must be > 0"
        )
    # Span-capture policy: validate the map shape here (the parse the
    # daemon reuses) — a policy nobody can apply must refuse to boot.
    history_spans_policy(out["ANOMALY_HISTORY_SPANS"])
    return out


def remediation_config() -> dict[str, int | float | str]:
    """Resolve every REMEDIATION_KNOBS entry from the environment
    (same contract as :func:`overload_config`); validates the
    guardrail shapes — a controller with zero hysteresis, a zero
    budget or a non-positive deadline could flip production flags on
    one noisy batch, and must refuse to boot instead."""
    out = _resolve(REMEDIATION_KNOBS)
    if int(out["ANOMALY_REMEDIATION_ACT_BATCHES"]) < 1:
        raise ConfigError(
            "ANOMALY_REMEDIATION_ACT_BATCHES="
            f"{out['ANOMALY_REMEDIATION_ACT_BATCHES']} must be >= 1"
        )
    if int(out["ANOMALY_REMEDIATION_CLEAR_BATCHES"]) < 1:
        raise ConfigError(
            "ANOMALY_REMEDIATION_CLEAR_BATCHES="
            f"{out['ANOMALY_REMEDIATION_CLEAR_BATCHES']} must be >= 1"
        )
    if int(out["ANOMALY_REMEDIATION_BUDGET"]) < 1:
        raise ConfigError(
            f"ANOMALY_REMEDIATION_BUDGET="
            f"{out['ANOMALY_REMEDIATION_BUDGET']} must be >= 1"
        )
    if float(out["ANOMALY_REMEDIATION_BUDGET_REFILL_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_REMEDIATION_BUDGET_REFILL_S="
            f"{out['ANOMALY_REMEDIATION_BUDGET_REFILL_S']} must be > 0"
        )
    if float(out["ANOMALY_REMEDIATION_DEADLINE_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_REMEDIATION_DEADLINE_S="
            f"{out['ANOMALY_REMEDIATION_DEADLINE_S']} must be > 0"
        )
    if float(out["ANOMALY_REMEDIATION_TIMEOUT_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_REMEDIATION_TIMEOUT_S="
            f"{out['ANOMALY_REMEDIATION_TIMEOUT_S']} must be > 0"
        )
    keep = float(out["ANOMALY_REMEDIATION_COLLECTOR_BASE_KEEP"])
    if not 0.0 <= keep <= 1.0:
        raise ConfigError(
            f"ANOMALY_REMEDIATION_COLLECTOR_BASE_KEEP={keep} must be "
            "a keep fraction in [0, 1]"
        )
    return out


def fleet_tenant_map(raw) -> dict[str, str]:
    """Parsed ``{service: tenant}`` from the raw
    ``ANOMALY_FLEET_TENANTS`` knob value — the ONE parse, shared by
    :func:`fleet_config`'s validator, the daemon and the fleet/
    aggregator tiers (the same no-drift rule as
    :func:`history_ladder`). ``'*'`` names the default tenant for
    unlisted services; an empty knob means every service is tenant
    ``'default'``. Raises ``ConfigError`` on malformed entries."""
    text = str(raw).strip()
    out: dict[str, str] = {}
    if not text:
        return out
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ConfigError(
                f"ANOMALY_FLEET_TENANTS entry {part!r} is not "
                "'service:tenant'"
            )
        name, tenant = part.rsplit(":", 1)
        name, tenant = name.strip(), tenant.strip()
        if not name or not tenant:
            raise ConfigError(
                f"ANOMALY_FLEET_TENANTS entry {part!r} has an empty "
                "service or tenant name"
            )
        if "/" in tenant or "/" in name:
            # '/' is the ring-key separator (tenant/service): letting
            # it into either side would let two different (tenant,
            # service) pairs collide on one ring key.
            raise ConfigError(
                f"ANOMALY_FLEET_TENANTS entry {part!r} contains '/' "
                "(reserved as the ring-key separator)"
            )
        out[name] = tenant
    if not out:
        raise ConfigError(
            f"ANOMALY_FLEET_TENANTS={text!r} parsed to an empty map"
        )
    return out


def fleet_config() -> dict[str, int | float | str]:
    """Resolve every FLEET_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the fleet shape —
    an index outside 0..N-1, a zero heartbeat, inverted hysteresis
    edges or an empty reshard budget could thrash or split the ring
    and must refuse to boot instead."""
    out = _resolve(FLEET_KNOBS)
    shards = int(out["ANOMALY_FLEET_SHARDS"])
    if shards < 0:
        raise ConfigError(
            f"ANOMALY_FLEET_SHARDS={shards} must be >= 0"
        )
    if shards > 1:
        index = int(out["ANOMALY_FLEET_SHARD_INDEX"])
        if not 0 <= index < shards:
            raise ConfigError(
                f"ANOMALY_FLEET_SHARD_INDEX={index} outside "
                f"0..{shards - 1}"
            )
        # The peer lists are index-aligned: fewer entries than shards
        # means some member can never be heartbeated (or queried) —
        # every shard would build a partial ring and believe it owns
        # keyspace it doesn't: a SILENT permanent ring split, the one
        # failure mode this validator exists to refuse.
        peers = [
            a for a in str(out["ANOMALY_FLEET_PEERS"]).split(",")
            if a.strip()
        ]
        if len(peers) < shards:
            raise ConfigError(
                f"ANOMALY_FLEET_PEERS lists {len(peers)} address(es) "
                f"for ANOMALY_FLEET_SHARDS={shards}: every shard "
                "index needs its health address (index-aligned)"
            )
        if int(out["ANOMALY_AGGREGATOR_PORT"]) >= 0:
            qpeers = [
                a
                for a in str(out["ANOMALY_FLEET_QUERY_PEERS"]).split(",")
                if a.strip()
            ]
            if len(qpeers) < shards:
                raise ConfigError(
                    "ANOMALY_FLEET_QUERY_PEERS lists "
                    f"{len(qpeers)} address(es) for "
                    f"ANOMALY_FLEET_SHARDS={shards}: the aggregator "
                    "needs every shard's query address (index-aligned)"
                )
    if int(out["ANOMALY_FLEET_VNODES"]) < 1:
        raise ConfigError(
            f"ANOMALY_FLEET_VNODES={out['ANOMALY_FLEET_VNODES']} "
            "must be >= 1"
        )
    if float(out["ANOMALY_FLEET_HEARTBEAT_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_FLEET_HEARTBEAT_S="
            f"{out['ANOMALY_FLEET_HEARTBEAT_S']} must be > 0"
        )
    if float(out["ANOMALY_FLEET_DEAD_AFTER_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_FLEET_DEAD_AFTER_S="
            f"{out['ANOMALY_FLEET_DEAD_AFTER_S']} must be > 0"
        )
    if float(out["ANOMALY_FLEET_REJOIN_AFTER_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_FLEET_REJOIN_AFTER_S="
            f"{out['ANOMALY_FLEET_REJOIN_AFTER_S']} must be > 0"
        )
    if int(out["ANOMALY_FLEET_RESHARD_BUDGET"]) < 1:
        raise ConfigError(
            "ANOMALY_FLEET_RESHARD_BUDGET="
            f"{out['ANOMALY_FLEET_RESHARD_BUDGET']} must be >= 1"
        )
    if float(out["ANOMALY_FLEET_RESHARD_REFILL_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_FLEET_RESHARD_REFILL_S="
            f"{out['ANOMALY_FLEET_RESHARD_REFILL_S']} must be > 0"
        )
    if float(out["ANOMALY_FLEET_TENANT_QUOTA_ROWS_S"]) < 0:
        raise ConfigError(
            "ANOMALY_FLEET_TENANT_QUOTA_ROWS_S="
            f"{out['ANOMALY_FLEET_TENANT_QUOTA_ROWS_S']} must be >= 0"
        )
    if float(out["ANOMALY_AGGREGATOR_TIMEOUT_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_AGGREGATOR_TIMEOUT_S="
            f"{out['ANOMALY_AGGREGATOR_TIMEOUT_S']} must be > 0"
        )
    # Tenant map: validate the shape here (the parse the daemon and
    # the fleet tier reuse) — a map nobody can apply must refuse to
    # boot.
    fleet_tenant_map(out["ANOMALY_FLEET_TENANTS"])
    return out


def autoscale_config() -> dict[str, int | float | str]:
    """Resolve every AUTOSCALE_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the guardrail
    shapes — a controller with zero hysteresis, a zero budget,
    inverted watermark edges or an inverted shard range could resize
    a production ring on one noisy window, and must refuse to boot
    instead."""
    out = _resolve(AUTOSCALE_KNOBS)
    if int(out["ANOMALY_AUTOSCALE_ACT_BATCHES"]) < 1:
        raise ConfigError(
            "ANOMALY_AUTOSCALE_ACT_BATCHES="
            f"{out['ANOMALY_AUTOSCALE_ACT_BATCHES']} must be >= 1"
        )
    if int(out["ANOMALY_AUTOSCALE_CLEAR_BATCHES"]) < 1:
        raise ConfigError(
            "ANOMALY_AUTOSCALE_CLEAR_BATCHES="
            f"{out['ANOMALY_AUTOSCALE_CLEAR_BATCHES']} must be >= 1"
        )
    if int(out["ANOMALY_AUTOSCALE_BUDGET"]) < 1:
        raise ConfigError(
            f"ANOMALY_AUTOSCALE_BUDGET="
            f"{out['ANOMALY_AUTOSCALE_BUDGET']} must be >= 1"
        )
    if float(out["ANOMALY_AUTOSCALE_REFILL_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_AUTOSCALE_REFILL_S="
            f"{out['ANOMALY_AUTOSCALE_REFILL_S']} must be > 0"
        )
    high = float(out["ANOMALY_AUTOSCALE_HIGH_WATER"])
    low = float(out["ANOMALY_AUTOSCALE_LOW_WATER"])
    if not 0.0 <= low < high <= 1.0:
        raise ConfigError(
            f"ANOMALY_AUTOSCALE_LOW_WATER={low} / HIGH_WATER={high}: "
            "the two-edge hysteresis needs 0 <= low < high <= 1 (the "
            "dead band between the edges is what prevents oscillation)"
        )
    lo_n = int(out["ANOMALY_AUTOSCALE_MIN_SHARDS"])
    hi_n = int(out["ANOMALY_AUTOSCALE_MAX_SHARDS"])
    if not 1 <= lo_n <= hi_n:
        raise ConfigError(
            f"ANOMALY_AUTOSCALE_MIN_SHARDS={lo_n} / MAX_SHARDS={hi_n}: "
            "need 1 <= min <= max"
        )
    return out


def shadow_config() -> dict[str, int | float | str]:
    """Resolve every SHADOW_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the fail-closed
    shapes — a zero window, rate, deadline or record floor would turn
    the counterfactual gate into a rubber stamp (or a wedge), and must
    refuse to boot instead."""
    out = _resolve(SHADOW_KNOBS)
    if float(out["ANOMALY_SHADOW_WINDOW_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_SHADOW_WINDOW_S="
            f"{out['ANOMALY_SHADOW_WINDOW_S']} must be > 0"
        )
    if float(out["ANOMALY_SHADOW_RATE"]) <= 0:
        raise ConfigError(
            f"ANOMALY_SHADOW_RATE={out['ANOMALY_SHADOW_RATE']} "
            "must be > 0"
        )
    if float(out["ANOMALY_SHADOW_DEADLINE_S"]) <= 0:
        raise ConfigError(
            "ANOMALY_SHADOW_DEADLINE_S="
            f"{out['ANOMALY_SHADOW_DEADLINE_S']} must be > 0"
        )
    if int(out["ANOMALY_SHADOW_MIN_RECORDS"]) < 1:
        raise ConfigError(
            "ANOMALY_SHADOW_MIN_RECORDS="
            f"{out['ANOMALY_SHADOW_MIN_RECORDS']} must be >= 1"
        )
    return out


def provenance_config() -> dict[str, int | float | str]:
    """Resolve every PROVENANCE_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the bundle shapes —
    a zero ring or trajectory depth would silently build empty
    evidence, and must refuse to boot instead."""
    out = _resolve(PROVENANCE_KNOBS)
    if int(out["ANOMALY_PROVENANCE_RING"]) < 1:
        raise ConfigError(
            "ANOMALY_PROVENANCE_RING="
            f"{out['ANOMALY_PROVENANCE_RING']} must be >= 1"
        )
    if int(out["ANOMALY_PROVENANCE_TOPK"]) < 1:
        raise ConfigError(
            "ANOMALY_PROVENANCE_TOPK="
            f"{out['ANOMALY_PROVENANCE_TOPK']} must be >= 1"
        )
    if int(out["ANOMALY_PROVENANCE_TRAJECTORY_WINDOWS"]) < 1:
        raise ConfigError(
            "ANOMALY_PROVENANCE_TRAJECTORY_WINDOWS="
            f"{out['ANOMALY_PROVENANCE_TRAJECTORY_WINDOWS']} "
            "must be >= 1"
        )
    return out


def frontdoor_config() -> dict[str, int | float | str]:
    """Resolve every FRONTDOOR_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the pump/batch
    shapes — a zero pump count would accept connections whose tickets
    nobody ever drains, and must refuse to boot instead."""
    out = _resolve(FRONTDOOR_KNOBS)
    if int(out["ANOMALY_FRONTDOOR_PUMPS"]) < 1:
        raise ConfigError(
            "ANOMALY_FRONTDOOR_PUMPS="
            f"{out['ANOMALY_FRONTDOOR_PUMPS']} must be >= 1"
        )
    if int(out["ANOMALY_FRONTDOOR_BATCH"]) < 1:
        raise ConfigError(
            "ANOMALY_FRONTDOOR_BATCH="
            f"{out['ANOMALY_FRONTDOOR_BATCH']} must be >= 1"
        )
    if int(out["ANOMALY_FRONTDOOR_MAX_CONNS"]) < 1:
        raise ConfigError(
            "ANOMALY_FRONTDOOR_MAX_CONNS="
            f"{out['ANOMALY_FRONTDOOR_MAX_CONNS']} must be >= 1"
        )
    return out


def keyspace_config() -> dict[str, int | float | str]:
    """Resolve every KEYSPACE_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the watermark
    ordering and the per-edge shapes — an inverted hysteresis band
    would flap the ladder on every sweep, and must refuse to boot
    instead."""
    out = _resolve(KEYSPACE_KNOBS)
    hi = float(out["ANOMALY_KEYSPACE_HIGH_WATERMARK"])
    lo = float(out["ANOMALY_KEYSPACE_LOW_WATERMARK"])
    if not (0.0 < lo < hi <= 1.0):
        raise ConfigError(
            "keyspace watermarks must satisfy 0 < "
            f"ANOMALY_KEYSPACE_LOW_WATERMARK ({lo}) < "
            f"ANOMALY_KEYSPACE_HIGH_WATERMARK ({hi}) <= 1"
        )
    if int(out["ANOMALY_KEYSPACE_EVICT_BATCH"]) < 1:
        raise ConfigError(
            "ANOMALY_KEYSPACE_EVICT_BATCH="
            f"{out['ANOMALY_KEYSPACE_EVICT_BATCH']} must be >= 1"
        )
    if float(out["ANOMALY_KEYSPACE_HOLD_S"]) < 0:
        raise ConfigError(
            "ANOMALY_KEYSPACE_HOLD_S="
            f"{out['ANOMALY_KEYSPACE_HOLD_S']} must be >= 0"
        )
    if float(out["ANOMALY_KEYSPACE_IDLE_S"]) < 0:
        raise ConfigError(
            "ANOMALY_KEYSPACE_IDLE_S="
            f"{out['ANOMALY_KEYSPACE_IDLE_S']} must be >= 0"
        )
    return out


def replication_config() -> dict[str, int | float | str]:
    """Resolve every REPLICATION_KNOBS entry from the environment (same
    contract as :func:`overload_config`); validates the role name —
    a typo'd role must refuse to boot, not silently run as primary."""
    out = _resolve(REPLICATION_KNOBS)
    if out["ANOMALY_ROLE"] not in ("primary", "standby"):
        raise ConfigError(
            f"ANOMALY_ROLE={out['ANOMALY_ROLE']!r} is not a role "
            "(expected 'primary' or 'standby')"
        )
    return out
