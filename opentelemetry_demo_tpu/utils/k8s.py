"""Kubernetes deployment surface: the Helm-manifest analogue.

The reference ships a 12,848-line Helm-generated manifest
(/root/reference/kubernetes/opentelemetry-demo.yaml: 23 Deployments,
25 Services, 7 ConfigMaps, 1 StatefulSet, RBAC + PodDisruptionBudget;
regenerated via /root/reference/Makefile:163-176). This framework's
deployable units are fewer — the in-proc shop collapses the storefront
tier into one gateway process — so the generator emits exactly what a
cluster needs, from code rather than templates:

- **standalone stack**: shop-gateway (edge :8080 incl. flag editor +
  in-proc telemetry backend), anomaly-detector (OTLP :4318, metrics
  :9464, checkpoint PVC, PodDisruptionBudget), http load-generator.
- **sidecar overlay**: just the detector, wired to an *existing*
  reference-shop deployment the way deploy/docker-compose.anomaly.yml
  does for compose (same env shape as the reference's fraud-detection
  consumer, /root/reference/docker-compose.yml:226-256).

Memory limits follow the reference's budget style (load-gen 1500M,
detector sized like load-gen; docker-compose.yml resource limits).

Regenerate with ``make gen-k8s`` (writes deploy/k8s/*.yaml).
"""

from __future__ import annotations

import argparse
import os

APP_LABEL = "app.kubernetes.io/name"
PART_OF = "app.kubernetes.io/part-of"
STACK = "opentelemetry-demo-tpu"
IMAGE_DETECTOR = "opentelemetry-demo-tpu:anomaly-detector"
IMAGE_GATEWAY = "opentelemetry-demo-tpu:gateway"


def _labels(name: str) -> dict:
    return {APP_LABEL: name, PART_OF: STACK}


def deployment(
    name: str,
    image: str,
    *,
    env: dict[str, str] | None = None,
    ports: list[int] | None = None,
    memory: str = "300Mi",
    command: list[str] | None = None,
    volume_mounts: list[dict] | None = None,
    volumes: list[dict] | None = None,
    readiness_http: tuple[str, int] | None = None,
    liveness_http: tuple[str, int] | None = None,
    grpc_health_port: int | None = None,
    tcp_probe_port: int | None = None,
    replicas: int = 1,
    strategy: str | None = None,
) -> dict:
    container: dict = {
        "name": name,
        "image": image,
        "imagePullPolicy": "IfNotPresent",
        "resources": {"limits": {"memory": memory}},
    }
    if command:
        container["command"] = command
    if env:
        container["env"] = [{"name": k, "value": str(v)} for k, v in sorted(env.items())]
    if ports:
        container["ports"] = [{"containerPort": p} for p in ports]
    if volume_mounts:
        container["volumeMounts"] = volume_mounts
    # One probe FAMILY per deployment: grpc/tcp set BOTH readiness and
    # liveness, so mixing them with each other or with the http pair
    # would silently overwrite one of the probes.
    probe_kinds = [
        k for k, v in (
            ("http", readiness_http or liveness_http),
            ("grpc", grpc_health_port),
            ("tcp", tcp_probe_port),
        ) if v
    ]
    if len(probe_kinds) > 1:
        raise ValueError(
            f"multiple probe kinds {probe_kinds}: one would silently "
            "replace the other — pick one family per deployment"
        )
    if readiness_http:
        path, port = readiness_http
        container["readinessProbe"] = {
            "httpGet": {"path": path, "port": port},
            "initialDelaySeconds": 5,
            "periodSeconds": 10,
        }
    if liveness_http:
        # Liveness gets a longer grace than readiness: a slow boot must
        # gate traffic, not trigger a restart loop.
        path, port = liveness_http
        container["livenessProbe"] = {
            "httpGet": {"path": path, "port": port},
            "initialDelaySeconds": 30,
            "periodSeconds": 20,
            "failureThreshold": 3,
        }
    if grpc_health_port:
        # Native kubelet gRPC probe (k8s ≥1.24): queries the same
        # grpc.health.v1 service the reference's containers register
        # (main.go:223-224); liveness uses it too, with a longer grace.
        container["readinessProbe"] = {
            "grpc": {"port": grpc_health_port},
            "initialDelaySeconds": 5,
            "periodSeconds": 10,
        }
        container["livenessProbe"] = {
            "grpc": {"port": grpc_health_port},
            "initialDelaySeconds": 30,
            "periodSeconds": 20,
            "failureThreshold": 3,
        }
    if tcp_probe_port:
        # Raw socket-accept probes for wire-protocol servers with no
        # HTTP/gRPC surface (the broker) — the shape the reference's
        # kafka healthcheck takes (docker-compose.yml:681-687).
        container["readinessProbe"] = {
            "tcpSocket": {"port": tcp_probe_port},
            "initialDelaySeconds": 5,
            "periodSeconds": 10,
        }
        container["livenessProbe"] = {
            "tcpSocket": {"port": tcp_probe_port},
            "initialDelaySeconds": 30,
            "periodSeconds": 20,
            "failureThreshold": 3,
        }
    spec: dict = {
        "replicas": replicas,
        "selector": {"matchLabels": {APP_LABEL: name}},
        "template": {
            "metadata": {"labels": _labels(name)},
            "spec": {
                # RBAC posture (the reference manifest ships per-service
                # ServiceAccounts): a dedicated identity per component,
                # with API credentials NOT mounted — nothing in this
                # stack talks to the kube API.
                "serviceAccountName": name,
                "containers": [container],
            },
        },
    }
    if volumes:
        spec["template"]["spec"]["volumes"] = volumes
    if strategy:
        spec["strategy"] = {"type": strategy}
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "labels": _labels(name)},
        "spec": spec,
    }


def service_account(name: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {"name": name, "labels": _labels(name)},
        "automountServiceAccountToken": False,
    }


def service(name: str, ports: list[int]) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "labels": _labels(name)},
        "spec": {
            "selector": {APP_LABEL: name},
            "ports": [{"name": f"port-{p}", "port": p, "targetPort": p} for p in ports],
        },
    }


def configmap(name: str, data: dict[str, str]) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "labels": _labels(name)},
        "data": data,
    }


def pvc(name: str, size: str = "1Gi") -> dict:
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "labels": _labels(name)},
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": size}},
        },
    }


def pod_disruption_budget(name: str) -> dict:
    return {
        "apiVersion": "policy/v1",
        "kind": "PodDisruptionBudget",
        "metadata": {"name": name, "labels": _labels(name)},
        "spec": {
            # maxUnavailable (not minAvailable): with replicas=1,
            # minAvailable:1 would deadlock `kubectl drain` forever.
            "maxUnavailable": 1,
            "selector": {"matchLabels": {APP_LABEL: name}},
        },
    }


def _detector_resources(kafka_addr: str | None) -> list[dict]:
    """Detector Deployment + Service + PVC + PDB (shared by both bundles)."""
    env = {
        "ANOMALY_OTLP_PORT": "4318",
        "ANOMALY_OTLP_GRPC_PORT": "4317",
        "ANOMALY_METRICS_PORT": "9464",
        "ANOMALY_BATCH": "2048",
        "ANOMALY_CHECKPOINT": "/var/lib/anomaly/detector",
        "FLAGD_FILE": "/app/flagd/demo.flagd.json",
    }
    if kafka_addr:
        env["KAFKA_ADDR"] = kafka_addr
    return [
        service_account("anomaly-detector"),
        deployment(
            "anomaly-detector",
            IMAGE_DETECTOR,
            env=env,
            ports=[4317, 4318, 9464],
            grpc_health_port=4317,
            memory="1500Mi",
            # Recreate: the RWO checkpoint PVC can't be attached by old
            # and new pods at once; RollingUpdate would wedge on
            # Multi-Attach when the replacement lands on another node.
            strategy="Recreate",
            volume_mounts=[
                {"name": "anomaly-state", "mountPath": "/var/lib/anomaly"},
                {"name": "flagd-config", "mountPath": "/app/flagd", "readOnly": True},
            ],
            volumes=[
                {
                    "name": "anomaly-state",
                    "persistentVolumeClaim": {"claimName": "anomaly-state"},
                },
                {
                    "name": "flagd-config",
                    "configMap": {"name": "flagd-config"},
                },
            ],
        ),
        service("anomaly-detector", [4317, 4318, 9464]),
        pvc("anomaly-state"),
        pod_disruption_budget("anomaly-detector"),
    ]


def _flagd_configmap() -> dict:
    """flagd file ConfigMap; content sourced from the deploy dir."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(here, "deploy", "demo.flagd.anomaly.json")
    try:
        with open(path) as f:
            flags = f.read()
    except OSError:
        flags = '{"flags": {}}\n'
    return configmap("flagd-config", {"demo.flagd.json": flags})


def kafka_resources() -> list[dict]:
    """The async tier as its own component, like the reference's kafka
    container (docker-compose.yml kafka service): the in-repo broker
    process with socket-accept probes and a drain budget."""
    return [
        service_account("kafka"),
        deployment(
            "kafka",
            IMAGE_GATEWAY,
            command=["python", "scripts/serve_kafka.py"],
            env={"KAFKA_PORT": "9092"},
            ports=[9092],
            tcp_probe_port=9092,
            memory="620Mi",  # the reference's kafka budget
        ),
        service("kafka", [9092]),
        pod_disruption_budget("kafka"),
    ]


def shop_resources() -> list[dict]:
    """Edge + shop tier: gateway (HTTP :8080 incl. /jaeger + /grafana
    observability surfaces), gRPC edge :8443, wired to the broker and
    exporting all three OTLP signals to the detector service."""
    return [
        service_account("shop-gateway"),
        deployment(
            "shop-gateway",
            IMAGE_GATEWAY,
            command=[
                "python", "scripts/serve_shop.py",
                "--kafka", "kafka:9092",
                "--otlp-endpoint", "http://anomaly-detector:4318",
            ],
            env={
                "SHOP_PORT": "8080",
                "SHOP_GRPC_PORT": "8443",
                "SHOP_USERS": "0",
            },
            ports=[8080, 8443],
            memory="500Mi",
            readiness_http=("/health", 8080),
            liveness_http=("/health", 8080),
        ),
        service("shop-gateway", [8080, 8443]),
        pod_disruption_budget("shop-gateway"),
    ]


def loadgen_resources() -> list[dict]:
    return [
        service_account("load-generator"),
        deployment(
            "load-generator",
            IMAGE_GATEWAY,
            command=["python", "scripts/serve_shop.py", "--load-only",
                     "--target", "http://shop-gateway:8080", "--users", "5"],
            memory="1500Mi",
        ),
    ]


def component_bundles() -> dict[str, list[dict]]:
    """Per-component resource bundles — the reference manifest's
    per-service breakout, generated instead of Helm-templated."""
    return {
        "kafka": kafka_resources(),
        "shop-gateway": shop_resources(),
        "load-generator": loadgen_resources(),
        "anomaly-detector": [_flagd_configmap()]
        + _detector_resources(kafka_addr="kafka:9092"),
    }


def standalone_stack() -> list[dict]:
    """The whole framework stack as cluster resources."""
    docs: list[dict] = []
    for bundle in component_bundles().values():
        docs.extend(bundle)
    return docs


def sidecar_overlay(kafka_addr: str = "kafka:9092") -> list[dict]:
    """Detector-only bundle for an existing reference-shop cluster."""
    return [_flagd_configmap()] + _detector_resources(kafka_addr=kafka_addr)


def to_yaml(docs: list[dict]) -> str:
    import yaml

    return yaml.safe_dump_all(docs, sort_keys=False, default_flow_style=False)


def write_manifests(outdir: str) -> list[str]:
    os.makedirs(outdir, exist_ok=True)
    targets = [
        ("opentelemetry-demo-tpu.yaml", standalone_stack()),
        ("anomaly-detector-sidecar.yaml", sidecar_overlay()),
    ]
    # Per-component breakout beside the aggregates (operate one tier at
    # a time, the way the reference's per-service Helm values allow).
    comp_dir = os.path.join(outdir, "components")
    os.makedirs(comp_dir, exist_ok=True)
    bundles = component_bundles()
    # Prune stale generations: a renamed/removed component must not
    # leave a "do not edit" file behind that `kubectl apply -f` would
    # still create.
    keep = {f"{name}.yaml" for name in bundles}
    for fname in os.listdir(comp_dir):
        if fname.endswith(".yaml") and fname not in keep:
            os.remove(os.path.join(comp_dir, fname))
    for name, docs in bundles.items():
        targets.append((os.path.join("components", f"{name}.yaml"), docs))
    written = []
    for fname, docs in targets:
        path = os.path.join(outdir, fname)
        with open(path, "w") as f:
            f.write("# Generated by opentelemetry_demo_tpu.utils.k8s — do not edit.\n")
            f.write(to_yaml(docs))
        written.append(path)
    return written


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="deploy/k8s")
    args = parser.parse_args()
    for path in write_manifests(args.out):
        print(path)


if __name__ == "__main__":
    main()
