"""flagd-style feature flags: file-backed evaluation + OFREP client.

The reference's entire fault-injection surface is a flagd JSON file
(/root/reference/src/flagd/demo.flagd.json) evaluated by OpenFeature SDKs
in every service, editable live via flagd-ui (SURVEY.md §5). This module
implements the same control plane for the TPU framework:

- :class:`FlagFileStore` — watches a flagd-schema JSON file and reloads
  on mtime change (flagd's own file-backed mode;
  /root/reference/docker-compose.yml:614-623 mounts the file the same way).
- :class:`FlagEvaluator` — evaluates ``state``/``variants``/
  ``defaultVariant`` plus the ``fractional`` targeting rule (weighted
  bucket on a targeting key, e.g. session id) — the subset the demo's
  flags actually use (percentage paymentFailure variants etc.).
- :class:`OfrepClient` — OpenFeature REST (OFREP) evaluation against a
  live flagd, for deployments where the detector sidecar shares the
  shop's flagd instead of a local file (the reference's load generator
  uses OFREP the same way,
  /root/reference/src/load-generator/locustfile.py:72-74).

The detector reads its own switches through this layer:
``anomalyDetectorEnabled``, ``anomalyDetectorZThreshold`` — per the
north-star requirement that the sidecar is gated by a flagd flag.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import time
import urllib.error
import urllib.request
import zlib
from typing import Any


def capped_jitter_backoff(attempt: int, base_s: float, cap_s: float) -> float:
    """Capped exponential backoff with full jitter — the
    ``otlp_export`` sender discipline as ONE shared formula:
    ``min(base * 2^attempt, cap) * uniform[0.5, 1.5)``. Used by the
    OFREP client's transient retries and the remediation worker's
    actuator retries, so the flag plane's retry shape cannot drift
    between its two transports."""
    base = min(base_s * (2.0 ** attempt), cap_s)
    return base * (0.5 + random.random())


def atomic_write_doc(path: str, doc: dict) -> None:
    """THE flag-file write primitive: tmp file + ``os.replace``.

    Services hot-reload the flagd file on mtime and must never observe
    a torn write (``FlagFileStore`` *tolerates* one — it keeps serving
    the previous snapshot — but no writer in this repo may produce one
    in the first place). Every flag-store writer goes through here:
    the flag editor UI (``flag_ui.py``) and the remediation
    controller's flagd actuator (``runtime/remediation.py``) — and
    scripts/sanitycheck.py pins that closed set, so a third writer is
    a reviewed decision, not drift."""
    dir_ = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=dir_, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class FlagEvaluator:
    """Evaluate flags from a flagd-schema dict ``{"flags": {...}}``."""

    def __init__(self, doc: dict | None = None):
        self._doc = doc or {"flags": {}}
        # Bumped on every replace(): the change signal flagd's
        # EventStream pushes as configuration_change events.
        self.version = 0

    def replace(self, doc: dict) -> None:
        self._doc = doc or {"flags": {}}
        self.version += 1

    def _refresh(self) -> None:
        """Pre-read hook; file-backed subclasses hot-reload here so
        EVERY public read path (resolve/evaluate/keys/specs/snapshot)
        sees the current document, not just evaluate()."""

    def poll_version(self) -> int:
        """Refresh, then return the document version — THE way to watch
        for changes (flagd EventStream et al). Reading the bare
        ``version`` attribute skips the file-store reload hook and
        misses file-only writes."""
        self._refresh()
        return self.version

    def snapshot(self) -> dict:
        """Deep copy of the live flagd document — THE public read /
        copy-for-write surface (callers mutate the copy and
        :meth:`replace` it back; nobody reaches into ``_doc``).
        JSON round-trip: the document is JSON by contract (flagd file
        schema), and this also catches non-JSON values early."""
        self._refresh()
        return json.loads(json.dumps(self._doc))

    def flag_keys(self) -> list[str]:
        self._refresh()
        return list(self._doc.get("flags", {}))

    def flag_spec(self, key: str) -> dict | None:
        """READ-ONLY view of one flag's live spec (no copy) — callers
        must not mutate; use :meth:`snapshot` + :meth:`replace` to
        write. Safe concurrently: ``replace`` swaps the whole document
        reference atomically."""
        self._refresh()
        spec = self._doc.get("flags", {}).get(key)
        return spec if isinstance(spec, dict) else None

    def flag_specs(self) -> dict:
        """READ-ONLY view of the live flags mapping (same contract as
        :meth:`flag_spec`)."""
        self._refresh()
        return self._doc.get("flags", {})

    def evaluate(self, key: str, default: Any, targeting_key: str = "") -> Any:
        """Return the flag's value, or ``default`` if absent/disabled."""
        try:
            value, _variant, _reason = self.resolve(key, targeting_key)
        except KeyError:
            return default
        return value

    def resolve(self, key: str, targeting_key: str = "") -> tuple:
        """Full resolution: ``(value, variant_name, reason)``.

        The flagd evaluation contract (schemas.flagd.dev): raises
        ``KeyError`` for a flag that is absent, DISABLED, or whose
        selected variant does not exist — the cases flagd answers with
        FLAG_NOT_FOUND. Reason is ``TARGETING_MATCH`` when a fractional
        rule picked the variant, ``STATIC`` otherwise.
        """
        self._refresh()
        flag = self._doc.get("flags", {}).get(key)
        if not isinstance(flag, dict):
            raise KeyError(key)
        if str(flag.get("state", "ENABLED")).upper() == "DISABLED":
            raise KeyError(key)
        variants = flag.get("variants", {})
        variant = flag.get("defaultVariant")
        reason = "STATIC"
        targeting = flag.get("targeting") or {}
        frac = targeting.get("fractional")
        if isinstance(frac, list) and frac:
            variant = self._fractional(key, frac, targeting_key, variant)
            reason = "TARGETING_MATCH"
        if variant not in variants:
            raise KeyError(key)
        return variants[variant], str(variant), reason

    @staticmethod
    def _fractional(
        key: str, rule: list, targeting_key: str, fallback: Any
    ) -> Any:
        """Weighted variant pick, sticky per targeting key.

        flagd buckets ``hash(flagKey + targetingKey)`` over the weight
        sum; we use crc32 for the same stable-bucket property (the exact
        hash need not match flagd's murmur3 — stickiness and weighting
        are the contract that matters to the demo's percentage flags).
        """
        pairs = []
        for entry in rule:
            if isinstance(entry, list) and len(entry) == 2:
                pairs.append((str(entry[0]), float(entry[1])))
        total = sum(w for _, w in pairs)
        if total <= 0:
            return fallback
        bucket = zlib.crc32(f"{key}{targeting_key}".encode()) % int(total)
        acc = 0.0
        for name, weight in pairs:
            acc += weight
            if bucket < acc:
                return name
        return fallback


class FlagFileStore(FlagEvaluator):
    """File-backed evaluator with mtime-based hot reload."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._mtime = -1.0
        self._maybe_reload(force=True)

    def _maybe_reload(self, force: bool = False) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return
        if force or mtime != self._mtime:
            try:
                with open(self.path) as f:
                    self.replace(json.load(f))
                self._mtime = mtime
            except (OSError, json.JSONDecodeError):
                # Keep serving the previous snapshot on a torn write —
                # flagd-ui rewrites the file in place.
                pass

    def _refresh(self) -> None:
        # The base class calls this before EVERY public read
        # (resolve/evaluate/keys/specs/snapshot), so a file edit is
        # visible on the next read of any kind, not just evaluate().
        self._maybe_reload()


class OfrepClient:
    """Minimal OFREP client (stdlib-only; gated by reachability).

    ``evaluate`` degrades to the default on any transport error so the
    detector never hard-depends on the flag service being up — matching
    the OpenFeature SDK's error-default semantics.

    Transport hardening (the remediation controller evaluates through
    this client on its verification path, so a sick flagd must cost a
    bounded, known amount): every request carries a bounded
    connect/read timeout, and TRANSIENT failures (connection refused /
    reset / timeout / 5xx / 429) are retried up to ``retries`` times
    with capped exponential backoff and full jitter — the
    ``otlp_export`` sender discipline. Definitive answers (404 — flag
    genuinely absent — and other 4xx) return the default immediately:
    retrying a NOT_FOUND would only triple the latency of a correct
    answer.

    Circuit half: the pipeline pump evaluates the detector's gating
    flag through this client ONCE PER BATCH, so a sustained outage
    must not pay the retry burst on every call. After an evaluate
    fails all its attempts the client enters a ``failure_cooldown_s``
    window in which each evaluate makes a SINGLE bounded attempt (the
    pre-hardening per-call cost); the first success closes the
    window. Worst case per call is therefore one timeout during an
    outage, and ``retries`` × timeout + capped backoff only at the
    outage's first detection — never an unbounded hang.
    """

    BACKOFF_BASE_S = 0.05
    BACKOFF_CAP_S = 0.5

    def __init__(self, base_url: str, timeout_s: float = 1.0,
                 retries: int = 2, failure_cooldown_s: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.retries = max(int(retries), 0)
        self.failure_cooldown_s = float(failure_cooldown_s)
        self.transient_failures = 0  # retried transport faults, lifetime
        self._down_until = 0.0  # monotonic: single-attempt mode window

    def _backoff_s(self, attempt: int) -> float:
        return capped_jitter_backoff(
            attempt, self.BACKOFF_BASE_S, self.BACKOFF_CAP_S
        )

    def evaluate(self, key: str, default: Any, targeting_key: str = "") -> Any:
        url = f"{self.base_url}/ofrep/v1/evaluate/flags/{key}"
        body = json.dumps({"context": {"targetingKey": targeting_key}}).encode()
        attempts = (
            1 if time.monotonic() < self._down_until
            else self.retries + 1
        )
        for attempt in range(attempts):
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as resp:
                    payload = json.load(resp)
                self._down_until = 0.0  # circuit closes on success
                return payload.get("value", default)
            except urllib.error.HTTPError as e:
                if e.code < 500 and e.code != 429:
                    # Definitive refusal (404 flag-not-found et al):
                    # the default IS the answer, retrying buys nothing.
                    self._down_until = 0.0
                    return default
                self.transient_failures += 1
            except Exception:  # noqa: BLE001 — transport fault
                # (refused/reset/timeout/DNS): the OpenFeature
                # error-default contract — degrade, never raise into
                # the evaluating service.
                self.transient_failures += 1
            if attempt + 1 < attempts:
                time.sleep(self._backoff_s(attempt))
        self._down_until = time.monotonic() + self.failure_cooldown_s
        return default
