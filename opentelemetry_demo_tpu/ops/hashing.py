"""Hashing primitives for sketch keys, host-side (NumPy) and device-side (JAX).

Design: all sketch kernels consume a single 64-bit hash per key, carried as
two ``uint32`` lanes ``(hi, lo)``. TPUs have no native 64-bit integer path
(and we deliberately avoid ``jax_enable_x64``), so the 64-bit hash is either

- computed on the **host** with vectorised NumPy ``uint64`` splitmix64
  (the real ingest path — trace-ids arrive as 16 raw bytes, attribute
  strings are interned/CRC'd; see ``runtime.tensorize``), or
- synthesised on **device** from counters with two independent murmur3
  fmix32 finalisers (the benchmark path, so throughput benchmarks measure
  sketch updates, not host→device transfer).

HLL needs (index bits ⊥ rank bits) and CMS derives its ``d`` row hashes via
the Kirsch–Mitzenmacher construction ``g_i = lo + i*hi``, so one 64-bit
hash per key serves every sketch.

Reference parity note: the reference system keys everything by OTel
trace/span ids (16/8 random bytes, e.g. produced by the Go SDK used in
/root/reference/src/checkout/main.go:92-106) — random ids are already
uniform, but we re-hash through splitmix64 so that adversarial or
low-entropy keys (attribute strings) are safe too.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

_SPLIT_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SPLIT_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SPLIT_M2 = np.uint64(0x94D049BB133111EB)


def splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Vectorised splitmix64 over a ``uint64`` NumPy array (host path).

    Wrapping arithmetic is numpy's native behaviour for unsigned dtypes, so
    this runs at memory bandwidth on the host — it is the scalariser-free
    hash for the 200k spans/sec ingest target (BASELINE north_star).
    """
    x = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x += _SPLIT_GAMMA
        z = x.copy()
        z ^= z >> np.uint64(30)
        z *= _SPLIT_M1
        z ^= z >> np.uint64(27)
        z *= _SPLIT_M2
        z ^= z >> np.uint64(31)
    return z


def split_hi_lo_np(h64: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split host uint64 hashes into device-friendly ``(hi, lo)`` uint32."""
    hi = (h64 >> np.uint64(32)).astype(np.uint32)
    lo = (h64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo


def fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """Murmur3 32-bit finaliser (device path, uint32 lanes, VPU-only)."""
    h = h.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def hash_u32_pair(x: jnp.ndarray, seed: int = 0) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Expand uint32 keys into a pseudo-64-bit hash as two uint32 lanes.

    Two fmix32 passes with distinct seeds give two independent 32-bit
    hashes — exactly what HLL (index ⊥ rank) and Kirsch–Mitzenmacher CMS
    rows require.
    """
    x = x.astype(jnp.uint32)
    hi = fmix32(x ^ jnp.uint32((0x9E3779B9 + seed) & 0xFFFFFFFF))
    lo = fmix32(x ^ jnp.uint32((0x85EBCA77 + 2 * seed) & 0xFFFFFFFF))
    return hi, lo


def hash_spans_synthetic(
    start: jnp.ndarray, batch: int, seed: int = 0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Device-side synthetic span-key generator for benchmarks.

    Produces ``batch`` hash pairs for the counter range
    ``[start, start+batch)`` entirely on device, so benchmark loops never
    touch the host. ``start`` may be a traced scalar.
    """
    # TPU requires >=2D iota; broadcasted_iota over a (batch, 1) frame.
    ctr = jax.lax.broadcasted_iota(jnp.uint32, (batch, 1), 0).squeeze(-1)
    x = ctr + jnp.uint32(start)
    return hash_u32_pair(x, seed=seed)
