"""Pure sketch kernels on packed tensor state.

Every kernel here is a stateless function ``state -> state`` or
``state -> measurement`` with static shapes, safe under ``jax.jit``,
``jax.vmap`` and ``shard_map``. Sketch states are associative monoids
(HLL registers merge by elementwise max, CMS tables by elementwise add),
which is what makes the multi-chip story trivial: shard the span batch,
sketch locally, merge with one collective.
"""

from .hashing import fmix32, hash_spans_synthetic, splitmix64_np
from .hll import (
    HLL_P,
    hll_estimate,
    hll_indices,
    hll_init,
    hll_merge,
    hll_update,
)
from .cms import (
    CMS_DEPTH,
    CMS_WIDTH,
    cms_indices,
    cms_init,
    cms_merge,
    cms_query,
    cms_update,
)
from .ewma import ewma_init, ewma_update, segment_stats
from .fused import SketchDelta, resolve_impl, sketch_batch_delta

__all__ = [
    "SketchDelta",
    "sketch_batch_delta",
    "resolve_impl",
    "fmix32",
    "hash_spans_synthetic",
    "splitmix64_np",
    "HLL_P",
    "hll_init",
    "hll_indices",
    "hll_update",
    "hll_estimate",
    "hll_merge",
    "CMS_DEPTH",
    "CMS_WIDTH",
    "cms_init",
    "cms_indices",
    "cms_update",
    "cms_query",
    "cms_merge",
    "ewma_init",
    "ewma_update",
    "segment_stats",
]
