"""Fused batch→delta sketch op: one pass, three sketches, no scatters.

The hot loop of the detector absorbs a span batch into HLL registers
(scatter-max), a Count-Min table (scatter-add), and per-service moment
stats (segment sum). This module collapses all three into one
delta-producing program (BASELINE config #4, "fused HLL+CMS+EWMA Pallas
kernel"):

- The batch's effect on each sketch is first reduced to a **delta
  sketch**: ``hll_delta[S,R]`` (max rank per cell), ``cms_delta[D,W]``
  (count per counter), ``stats[4,S]`` (count / Σlog-lat / Σlog-lat² /
  Σerr per service). Deltas are tiny monoid elements: the caller merges
  them into every tumbling-window bank with one broadcast max/add, and
  on a mesh they — not the banks — ride the ICI collectives.
- Inside the Pallas kernel the "scatter" is a dense one-hot
  compare-reduction: for each tile of sketch cells, compare the batch's
  cell ids against a lane iota and max/sum over the batch axis. That is
  embarrassingly parallel VPU work with perfect lane utilisation —
  the TPU answer to what CUDA builds do with HBM atomics (SURVEY.md §7
  hard part (b)) — and the whole working set (delta tiles + batch
  vectors) stays VMEM-resident.
- The segment stats ride the MXU as a ``[4,B] @ [B,S]`` one-hot matmul.

An ``impl="xla"`` reference path (the scatter formulation built from
``ops.hll`` / ``ops.cms`` / ``ops.ewma``) defines the semantics; the
Pallas path is property-tested against it (interpret mode on CPU, native
on TPU). Honest fetch-synchronized timing single-chip (S=32, p=12,
4×8192 CMS; r5, see the calibration table above ``expected_rates``):
the dense kernel owns the small-batch low-latency regime through
B≈16k (5.8M vs ~2.3M full-step at 8192); the XLA path wins from ~24k
up (47M at 65536, 105M at 512k, 123M plateau at 2M) — its CMS count
rides the scatter-free histogram engines in ``cms.cms_update_hist``
(the transposed-int8 MXU outer-product Pallas kernel at
tile-divisible geometries, sort+searchsorted elsewhere; TPU scatters
serialize on duplicate indices, and a CMS batch is nothing but
duplicates). ``resolve_impl`` auto-selects by batch size; the HYBRID
is deliberate — the dense formulation's O(B·cells) sweep is a ceiling
no layout removes (see the bound argument in the calibration comment
and PARITY.md), so BASELINE config #4's "fused kernel" answer at
large B is the histogram formulation, whose hot engine is itself a
Pallas kernel. The dense kernel's further wins are determinism (fixed
VPU/MXU schedule, no batch-order dependence) and keeping the whole
delta VMEM-resident.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import cms, ewma, hll


class SketchDelta(NamedTuple):
    """One batch's mergeable effect on the sketch bank."""

    hll: jnp.ndarray  # int32[S, R] — max HLL rank per (service, bucket)
    cms: jnp.ndarray  # int32[D, W] — count per CMS counter
    stats: jnp.ndarray  # float32[4, S] — cnt, Σlog-lat, Σlog-lat², Σerr


class HeadState(NamedTuple):
    """The EWMA/CUSUM detection-head memory one batch advances — the
    slice of ``models.detector.DetectorState`` the fused one-pass
    update owns when the head fold is enabled (NO_COMM path)."""

    lat_mean: jnp.ndarray  # float32[S, T]
    lat_var: jnp.ndarray  # float32[S, T]
    err_mean: jnp.ndarray  # float32[S, T]
    rate_mean: jnp.ndarray  # float32[S, T]
    rate_var: jnp.ndarray  # float32[S, T]
    cusum: jnp.ndarray  # float32[S, 3] — {lat↑, err↑, rate↓}
    obs_batches: jnp.ndarray  # float32[S]


def head_update(
    stats: jnp.ndarray,  # float32[4, S] — cnt, Σlog-lat, Σlog-lat², Σerr
    heads: HeadState,
    dt: jnp.ndarray,  # float32[] — seconds since previous batch
    step_pos: jnp.ndarray,  # bool[] — True past step 0 (rate gate)
    *,
    taus_s: tuple,
    warmup_batches: float,
    z_warmup_batches: float,
    cusum_k: float,
    cusum_cap: float,
    err_slack: float,
) -> tuple[HeadState, tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """One batch's EWMA/CUSUM head advance: ``(heads', (lat_z, err_z,
    rate_z))`` — the 3b/CUSUM math of ``models.detector.detector_step``,
    hoisted HERE so the NO_COMM spine folds it into the one-pass
    ``sketch_batch_update`` program (the last delta→HBM round trip PR 9
    left) while the mesh path applies the SAME function to its
    collective-merged stats. Formulas are verbatim from the detector
    step — bit-identical by construction; tests/test_fused.py pins the
    folded path against this two-step form.

    Count-aware scaling (why each z looks the way it does):
    latency x̄ of n spans → z=(x̄-μ)/sqrt(σ²/n); error binomial;
    throughput Poisson with empirically learned burstiness — see the
    detector-step docstring for the full rationale.
    """
    # Per-τ smoothing weights, built from traced SCALARS (a
    # jnp.asarray(taus_s) constant would be a captured const inside
    # the Pallas kernel); elementwise-identical to 1-exp(-dt/taus).
    alphas = jnp.stack(
        [1.0 - jnp.exp(-dt / jnp.float32(t)) for t in taus_s]
    )  # [T]
    cnt, lat_sum, lat_sumsq, err_sum = stats
    seen = cnt > 0  # [S]
    obs2d = seen[:, None]
    warm = (heads.obs_batches < warmup_batches)[:, None]  # [S,1]
    z_warm = (heads.obs_batches < z_warmup_batches)[:, None]  # [S,1]
    n = jnp.maximum(cnt, 1.0)[:, None]  # [S,1]
    # Bias-corrected smoothing (Adam-style debias via max, not divide).
    alphas = jnp.maximum(
        alphas, 1.0 / (heads.obs_batches[:, None] + 1.0)
    )  # [S,T]
    # Variance gets its own (slow) smoothing — the per-span variance is
    # a property of the service, not of the detection timescale.
    alpha_var = jnp.maximum(
        1.0 - jnp.exp(-dt / jnp.float32(max(taus_s))),
        1.0 / (heads.obs_batches[:, None] + 1.0),
    )  # [S,1]

    # Latency: per-span mean μ and per-span variance σ² per timescale,
    # with a σ floor (≈15% latency noise in log space).
    mu = heads.lat_mean
    sigma2 = heads.lat_var
    floor2 = jnp.float32(0.15 * 0.15)
    xbar = (lat_sum / jnp.maximum(cnt, 1.0))[:, None]  # [S,1]
    lat_z = (xbar - mu) / jnp.sqrt(sigma2 / n + floor2)
    lat_z_cusum = jnp.where(obs2d & ~warm, lat_z, 0.0)
    lat_z = jnp.where(obs2d & ~z_warm, lat_z, 0.0)
    lat_mean = jnp.where(obs2d, mu + alphas * (xbar - mu), mu)
    # E[(x-μ)²] against the *updated* mean.
    v_obs = (
        (lat_sumsq / jnp.maximum(cnt, 1.0))[:, None]
        - 2.0 * lat_mean * xbar
        + lat_mean * lat_mean
    )
    lat_var = jnp.where(
        obs2d, sigma2 + alpha_var * (jnp.maximum(v_obs, 0.0) - sigma2), sigma2
    )

    # Error rate: EWMA of p, binomial z on this batch's error count.
    p = heads.err_mean
    err_cnt = err_sum[:, None]  # [S,1]
    err_z = (err_cnt - n * p) / jnp.sqrt(n * p * (1.0 - p) + 1.0)
    err_z = jnp.where(obs2d & ~z_warm, err_z, 0.0)
    phat = err_cnt / n
    err_mean = jnp.where(obs2d, p + alphas * (phat - p), p)

    # Throughput: EWMA of spans/sec; Poisson-floored variance with the
    # learned burstiness. step 0 carries a meaningless dt — gated out.
    lam = heads.rate_mean
    dt_c = jnp.maximum(dt, 1e-3)
    expected = lam * dt_c
    emp_var = heads.rate_var * dt_c * dt_c  # (spans/s)² → count²
    rate_obs = (seen | (heads.obs_batches > 0))[:, None] & step_pos
    rate_z = (cnt[:, None] - expected) / jnp.sqrt(
        jnp.maximum(expected, emp_var) + 1.0
    )
    rate_z_cusum = jnp.where(rate_obs & ~warm, rate_z, 0.0)
    rate_z = jnp.where(rate_obs & ~z_warm, rate_z, 0.0)
    rate_x = (cnt / jnp.maximum(dt, 1e-3))[:, None]
    rate_mean = jnp.where(rate_obs, lam + alphas * (rate_x - lam), lam)
    rate_var = jnp.where(
        rate_obs,
        heads.rate_var + alpha_var * ((rate_x - lam) ** 2 - heads.rate_var),
        heads.rate_var,
    )

    obs_batches = heads.obs_batches + seen.astype(jnp.float32)

    # CUSUM layer: sustained small shifts, standardized scores against
    # the slowest-τ baseline; sparse services HOLD their accumulators.
    k = jnp.float32(cusum_k)
    active = seen & ~warm[:, 0]
    s_lat = jnp.where(active, lat_z_cusum[:, -1] - k, 0.0)
    p_ref = err_mean[:, -1]
    err_sigma = jnp.sqrt(n[:, 0] * p_ref * (1.0 - p_ref) + 1.0)
    s_err = jnp.where(
        active,
        (err_cnt[:, 0] - n[:, 0] * (p_ref + err_slack)) / err_sigma - k,
        0.0,
    )
    s_rate = jnp.where(
        rate_obs[:, 0] & ~warm[:, 0], -rate_z_cusum[:, -1] - k, 0.0
    )
    scores = jnp.stack([s_lat, s_err, s_rate], axis=1)  # [S,3]
    cusum = jnp.clip(heads.cusum + scores, 0.0, cusum_cap)

    new_heads = HeadState(
        lat_mean=lat_mean,
        lat_var=lat_var,
        err_mean=err_mean,
        rate_mean=rate_mean,
        rate_var=rate_var,
        cusum=cusum,
        obs_batches=obs_batches,
    )
    return new_heads, (lat_z, err_z, rate_z)


def _cell_chunk(total_cells: int, batch: int, wide: bool = False) -> int:
    """Lane-chunk size: biggest power-of-two tile dividing the cell count.

    Two regimes, measured on v5e-1 (S=32, p=12, 4×8192 CMS):

    - ``wide`` (multi-tile grids, large B): chunks up to 2048 lanes.
      The kernel's cost is pure compare-reduce sweeps (O(B·cells)
      total), so its throughput is set by how much of each sweep runs
      per loop iteration — C=128 leaves ~1300 tiny sequential
      fori_loop steps per grid step and 1.7M spans/s; C=2048 cuts the
      loop overhead ~16× and reaches 7.6M spans/s (the dense-compare
      VPU roofline for this geometry), for [TB, 2048] int32 compare
      intermediates of 32 MiB inside the raised VMEM grant.
    - narrow (single-tile grids, small B — the low-latency pipeline
      regime): big chunks measurably HURT (1.67M → 1.02M at B=2048);
      without grid pipelining the wide intermediates only add VMEM
      pressure. Keep the [B, chunk] intermediate ≲4 MiB.
    """
    if wide:
        cap = max(128, (1 << 24) // max(batch, 1))
        limit = 2048
    else:
        cap = max(128, (1 << 20) // max(batch, 1))
        limit = 512
    c = 128
    while c * 2 <= min(limit, cap) and total_cells % (c * 2) == 0:
        c *= 2
    if total_cells % c:
        raise ValueError(f"cell count {total_cells} not divisible by {c}")
    return c


def _delta_kernel(
    flat_ref,  # int32[TB, 1] — svc*R + bucket (rank 0 ⇒ no-op)
    rank_ref,  # int32[TB, 1] — HLL rank, 0 for masked lanes
    cidx_ref,  # int32[TB, D] — CMS row indices
    weight_ref,  # int32[TB, 1] — CMS increment (0 for masked lanes)
    svc_ref,  # int32[TB, 1] — local service id, >=S for masked lanes
    feats_ref,  # float32[4, TB] — premasked [1, loglat, loglat², err]
    hll_ref,  # out int32[SR/C, C] — same block every grid step
    cms_ref,  # out int32[D, W] — same block every grid step
    stats_ref,  # out float32[4, S] — same block every grid step
    *,
    wide: bool,  # multi-tile grid → wide cell chunks (see _cell_chunk)
):
    """One grid step absorbs one batch tile into the delta.

    The grid runs sequentially over batch tiles (TPU grids iterate in
    order), each step revisiting the SAME output block: the first step
    initialises, later steps max/sum-accumulate. This keeps only a
    [TB, chunk] compare intermediate in VMEM regardless of total B —
    the scoped-VMEM ceiling that capped the single-block kernel at
    B=16384 no longer binds."""
    b = flat_ref.shape[0]
    n_hll, c_hll = hll_ref.shape
    d, w = cms_ref.shape
    s = stats_ref.shape[1]
    first = pl.program_id(0) == 0
    flat = flat_ref[:]  # [TB, 1]
    rank = rank_ref[:]

    # HLL delta: per cell tile, max rank over the batch where the flat
    # (service, bucket) id hits the lane's cell id.
    def hll_body(i, _):
        cell = i * c_hll + jax.lax.broadcasted_iota(jnp.int32, (1, c_hll), 1)
        contrib = jnp.where(flat == cell, rank, 0)  # [TB, C]
        tile_max = jnp.max(contrib, axis=0, keepdims=True)
        prev = jnp.where(first, 0, hll_ref[pl.ds(i, 1), :])
        hll_ref[pl.ds(i, 1), :] = jnp.maximum(prev, tile_max)
        return 0

    jax.lax.fori_loop(0, n_hll, hll_body, 0)

    # CMS delta: per row and cell tile, sum weights over the batch where
    # the row hash hits the lane's counter id.
    weight = weight_ref[:]  # [TB, 1] int32
    # 2*b: the grid pipeline double-buffers blocks, so budget the
    # [TB, chunk] intermediates as if two tiles were resident.
    c_cms = _cell_chunk(w, 2 * b, wide=wide)
    for di in range(d):  # depth is small and static — unrolled
        col = cidx_ref[:, pl.ds(di, 1)]  # [TB, 1]

        def cms_body(i, _, col=col, di=di):
            cell = i * c_cms + jax.lax.broadcasted_iota(
                jnp.int32, (1, c_cms), 1
            )
            contrib = jnp.where(col == cell, weight, 0)  # [TB, C]
            tile_sum = jnp.sum(contrib, axis=0, keepdims=True)
            prev = jnp.where(
                first, 0, cms_ref[pl.ds(di, 1), pl.ds(i * c_cms, c_cms)]
            )
            cms_ref[pl.ds(di, 1), pl.ds(i * c_cms, c_cms)] = prev + tile_sum
            return 0

        jax.lax.fori_loop(0, w // c_cms, cms_body, 0)

    # Segment stats: one-hot matmul on the MXU.
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
    onehot = (cols == svc_ref[:]).astype(jnp.float32)  # [TB, S]
    tile_stats = jnp.dot(
        feats_ref[:], onehot, preferred_element_type=jnp.float32
    )
    prev = jnp.where(first, 0.0, stats_ref[:])
    stats_ref[:] = prev + tile_stats


def _update_kernel(
    *refs,
    wide: bool,
    n_windows: int,
    fold_heads: bool = False,
    head_statics: dict | None = None,
):
    """One grid step absorbs one batch tile DIRECTLY into every window
    bank — the single-pass form of :func:`_delta_kernel`.

    The delta kernel materializes a [S,R]/[D,W] delta that the caller
    then broadcast-merges into each of the W tumbling banks: one extra
    HBM round trip for the delta plus a separate merge computation. Here
    the accumulation is seeded from the INCOMING banks (first grid step)
    instead of zero, and each cell tile's batch contribution — computed
    once — is folded into all W banks while it is still VMEM-resident.
    Integer max/add monoids make this bit-identical to delta-then-merge.
    Only the single-chip path may use it: on a mesh the DELTA (not the
    merged bank) must cross the batch-axis collectives.

    Positional refs (``fold_heads=False``)::

        flat[TB,1] rank[TB,1] cidx[TB,D] weight[TB,1] svc[TB,1]
        feats[4,TB] hll_in cms_in → hll_out cms_out stats[4,S]

    With ``fold_heads=True`` the EWMA/CUSUM head state rides along —
    inputs gain ``lat_mean/lat_var/err_mean/rate_mean/rate_var[S,T]
    cusum[S,3] obs[1,S] params[1,2]`` (params = dt, step_pos) and
    outputs gain the advanced heads plus ``lat_z/err_z/rate_z[S,T]``.
    The head math (:func:`head_update`, shared verbatim with the xla
    impl and the mesh path) runs ONCE, on the LAST grid step, consuming
    the fully-accumulated stats straight from VMEM — the stats delta
    never round-trips to HBM between sketch fold and head advance,
    which is what makes the NO_COMM path truly one program.
    """
    if fold_heads:
        (flat_ref, rank_ref, cidx_ref, weight_ref, svc_ref, feats_ref,
         hll_in_ref, cms_in_ref, lat_mean_ref, lat_var_ref, err_mean_ref,
         rate_mean_ref, rate_var_ref, cusum_ref, obs_ref, params_ref,
         hll_ref, cms_ref, stats_ref, lat_mean_o, lat_var_o, err_mean_o,
         rate_mean_o, rate_var_o, cusum_o, obs_o, lat_z_o, err_z_o,
         rate_z_o) = refs
    else:
        (flat_ref, rank_ref, cidx_ref, weight_ref, svc_ref, feats_ref,
         hll_in_ref, cms_in_ref, hll_ref, cms_ref, stats_ref) = refs
    b = flat_ref.shape[0]
    rows_hll, c_hll = hll_ref.shape
    n_hll = rows_hll // n_windows
    rows_cms, w = cms_ref.shape
    d = rows_cms // n_windows
    s = stats_ref.shape[1]
    first = pl.program_id(0) == 0
    flat = flat_ref[:]  # [TB, 1]
    rank = rank_ref[:]

    # HLL: per cell tile, max rank over the batch — folded into every
    # window's bank row (the windows are row-stacked, stride n_hll).
    def hll_body(i, _):
        cell = i * c_hll + jax.lax.broadcasted_iota(jnp.int32, (1, c_hll), 1)
        contrib = jnp.where(flat == cell, rank, 0)  # [TB, C]
        tile_max = jnp.max(contrib, axis=0, keepdims=True)
        for wi in range(n_windows):
            row = wi * n_hll + i
            prev = jnp.where(
                first,
                hll_in_ref[pl.ds(row, 1), :],
                hll_ref[pl.ds(row, 1), :],
            )
            hll_ref[pl.ds(row, 1), :] = jnp.maximum(prev, tile_max)
        return 0

    jax.lax.fori_loop(0, n_hll, hll_body, 0)

    # CMS: per row and cell tile, sum weights over the batch — added
    # into every window's matching bank row.
    weight = weight_ref[:]  # [TB, 1] int32
    c_cms = _cell_chunk(w, 2 * b, wide=wide)
    for di in range(d):  # depth is small and static — unrolled
        col = cidx_ref[:, pl.ds(di, 1)]  # [TB, 1]

        def cms_body(i, _, col=col, di=di):
            cell = i * c_cms + jax.lax.broadcasted_iota(
                jnp.int32, (1, c_cms), 1
            )
            contrib = jnp.where(col == cell, weight, 0)  # [TB, C]
            tile_sum = jnp.sum(contrib, axis=0, keepdims=True)
            for wi in range(n_windows):
                row = wi * d + di
                prev = jnp.where(
                    first,
                    cms_in_ref[pl.ds(row, 1), pl.ds(i * c_cms, c_cms)],
                    cms_ref[pl.ds(row, 1), pl.ds(i * c_cms, c_cms)],
                )
                cms_ref[pl.ds(row, 1), pl.ds(i * c_cms, c_cms)] = (
                    prev + tile_sum
                )
            return 0

        jax.lax.fori_loop(0, w // c_cms, cms_body, 0)

    # Segment stats: one-hot matmul on the MXU (identical to the delta
    # kernel — stats feed the EWMA fold, which is not window-banked).
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
    onehot = (cols == svc_ref[:]).astype(jnp.float32)  # [TB, S]
    tile_stats = jnp.dot(
        feats_ref[:], onehot, preferred_element_type=jnp.float32
    )
    new_stats = jnp.where(first, 0.0, stats_ref[:]) + tile_stats
    stats_ref[:] = new_stats

    if fold_heads:
        # EWMA/CUSUM head advance, ONCE, on the last grid step — the
        # accumulated stats are consumed from VMEM (new_stats), never
        # re-read from HBM. Same head_update the xla impl and the mesh
        # path run, so every impl is bit-identical by shared code.
        @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
        def _fold_heads():
            heads = HeadState(
                lat_mean=lat_mean_ref[:],
                lat_var=lat_var_ref[:],
                err_mean=err_mean_ref[:],
                rate_mean=rate_mean_ref[:],
                rate_var=rate_var_ref[:],
                cusum=cusum_ref[:],
                obs_batches=obs_ref[0, :],
            )
            dt = params_ref[0, 0]
            step_pos = params_ref[0, 1] > 0.5
            new_heads, (lat_z, err_z, rate_z) = head_update(
                new_stats, heads, dt, step_pos, **head_statics
            )
            lat_mean_o[:] = new_heads.lat_mean
            lat_var_o[:] = new_heads.lat_var
            err_mean_o[:] = new_heads.err_mean
            rate_mean_o[:] = new_heads.rate_mean
            rate_var_o[:] = new_heads.rate_var
            cusum_o[:] = new_heads.cusum
            obs_o[0, :] = new_heads.obs_batches
            lat_z_o[:] = lat_z
            err_z_o[:] = err_z
            rate_z_o[:] = rate_z


def _out_structs(
    shapes_dtypes: list[tuple[tuple[int, ...], jnp.dtype]],
    inputs: tuple,
) -> tuple:
    """ShapeDtypeStructs carrying the inputs' vma union when this jax
    can express it. Under shard_map the per-shard result varies across
    every mesh axis any input varies across (batch-sharded lanes,
    sketch-localised ids); pallas_call can't infer that, so propagate
    the union. Older jax (no ``jax.typeof``/``vma``) tracks no varying
    manual axes — plain structs are then exactly right, and gating here
    keeps the kernels runnable (interpret mode included) across the
    version window instead of failing on an AttributeError."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in inputs))
        return tuple(
            jax.ShapeDtypeStruct(s, d, vma=vma) for s, d in shapes_dtypes
        )
    except (AttributeError, TypeError):
        return tuple(jax.ShapeDtypeStruct(s, d) for s, d in shapes_dtypes)


def _batch_tiling(b: int, batch_tile: int | None) -> tuple[int, int]:
    """(grid steps, tile rows) for the batch axis.

    Tile the batch axis so VMEM holds one tile, not the whole batch;
    the grid accumulates tiles into one delta/bank (see the kernels).
    4096 keeps the [TB, chunk] compare intermediates comfortably under
    the 16M scoped-VMEM limit at any total B (8192 tiles sat at
    16.04M — over by 40K — once the grid's double buffering counted).
    Picks the LARGEST divisor tile ≤ target (fewest grid steps), not a
    power-of-two shrink: every grid step re-sweeps all sketch cell
    tiles, so a degenerate tile (e.g. 16 for b=6000) would be a
    silent orders-of-magnitude cliff. Refuses instead of degrading.
    """
    target = min(b, batch_tile or 4096)
    nb = -(-b // target)  # ceil
    while nb <= b and b % nb:
        nb += 1
    tb = b // nb
    if tb < min(target, 256):
        hint = (
            f"pick a batch_tile that divides {b}"
            if batch_tile
            else "use a batch size that is a multiple of 4096 (or ≤ 4096)"
        )
        raise ValueError(
            f"batch size {b} has no usable tile divisor near {target} "
            f"for the pallas impl; {hint}"
        )
    return nb, tb


def _delta_pallas(
    flat: jnp.ndarray,
    rank: jnp.ndarray,
    cidx_t: jnp.ndarray,
    weight: jnp.ndarray,
    svc: jnp.ndarray,
    feats: jnp.ndarray,
    *,
    num_services: int,
    hll_regs: int,
    cms_depth: int,
    cms_width: int,
    interpret: bool = False,
    batch_tile: int | None = None,
) -> SketchDelta:
    b = flat.shape[0]
    nb, tb = _batch_tiling(b, batch_tile)
    sr = num_services * hll_regs
    wide = nb > 1  # multi-tile grid: pipelined sweeps want wide chunks
    c_hll = _cell_chunk(sr, 2 * tb, wide=wide)  # 2*: double-buffer headroom
    out_shape = _out_structs(
        [
            ((sr // c_hll, c_hll), jnp.int32),
            ((cms_depth, cms_width), jnp.int32),
            ((4, num_services), jnp.float32),
        ],
        (flat, rank, cidx_t, weight, svc, feats),
    )
    d = cidx_t.shape[1]

    def col_tile(i):  # [B, k] inputs: tile the batch (row) axis
        return (i, 0)

    def feats_tile(i):  # [4, B] input: tile the lane (col) axis
        return (0, i)

    def whole(i):  # outputs: same full block every grid step
        return (0, 0)

    hll_d, cms_d, stats = pl.pallas_call(
        functools.partial(_delta_kernel, wide=wide),
        grid=(nb,),
        # The compiler's default scoped-VMEM budget (16 MiB) sits ~36 KiB
        # under what the grid pipeline requests at very large B; v5e has
        # 128 MiB physical VMEM, so grant headroom explicitly.
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, d), col_tile, memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
            pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
            pl.BlockSpec((4, tb), feats_tile, memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((sr // c_hll, c_hll), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((cms_depth, cms_width), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((4, num_services), whole, memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(
        flat.reshape(b, 1),
        rank.reshape(b, 1),
        cidx_t,
        weight.reshape(b, 1),
        svc.reshape(b, 1),
        feats,
    )
    return SketchDelta(
        hll=hll_d.reshape(num_services, hll_regs), cms=cms_d, stats=stats
    )


def sketch_batch_delta(
    svc: jnp.ndarray,  # int32[B] — local service ids (may be out of range)
    log_lat: jnp.ndarray,  # float32[B]
    is_error: jnp.ndarray,  # float32[B]
    trace_hi: jnp.ndarray,  # uint32[B]
    trace_lo: jnp.ndarray,  # uint32[B]
    cidx: jnp.ndarray,  # int32[D, B] — CMS row indices (global hashes)
    valid: jnp.ndarray,  # bool[B]
    *,
    num_services: int,
    hll_p: int = hll.HLL_P,
    cms_width: int = cms.CMS_WIDTH,
    impl: str = "xla",  # "xla" | "pallas" | "interpret"
    batch_tile: int | None = None,  # pallas batch-grid tile (default 4096)
) -> SketchDelta:
    """Reduce one span batch to its mergeable sketch delta.

    Semantics (both impls):
    - HLL counts only lanes that are valid *and* in the local service
      slice ``[0, num_services)`` (out-of-slice ids belong to another
      shard on the sketch mesh axis).
    - CMS counts every valid lane (the table is global; service is
      folded into the key hash upstream).
    - stats rows are (count, Σlog-lat, Σlog-lat², Σerr) per service.
    """
    r = 1 << hll_p
    svc = svc.astype(jnp.int32)
    in_slice = (svc >= 0) & (svc < num_services)
    bucket, rank = hll.hll_indices(trace_hi, trace_lo, p=hll_p)
    rank = jnp.where(valid & in_slice, rank, 0)
    flat = jnp.where(in_slice, svc, 0) * r + bucket
    d = cidx.shape[0]

    if impl == "xla":
        hll_d = hll.hll_update(
            jnp.zeros((num_services, r), jnp.int32),
            jnp.where(in_slice, svc, num_services),
            bucket,
            rank,
            valid,
        )
        # Unit weights → the scatter-free histogram. cms_update_hist
        # auto-selects its engine: at production geometries on TPU
        # (tile-divisible key counts) that is the MXU one-hot
        # outer-product Pallas kernel — so the "xla" impl embeds a
        # Pallas hist — with sort+searchsorted elsewhere; both are
        # bit-exact and ~2-4× over the duplicate-heavy scatter.
        cms_d = cms.cms_update_hist(
            jnp.zeros((d, cms_width), jnp.int32), cidx, valid
        )
        cnt, lat_sum, lat_sumsq = ewma.segment_stats(
            log_lat, svc, num_services, valid=valid
        )
        _, err_sum, _ = ewma.segment_stats(
            is_error, svc, num_services, valid=valid
        )
        stats = jnp.stack([cnt, lat_sum, lat_sumsq, err_sum], axis=0)
        return SketchDelta(hll=hll_d, cms=cms_d, stats=stats)

    valid_f = valid.astype(jnp.float32)
    log_lat = log_lat.astype(jnp.float32) * valid_f
    feats = jnp.stack(
        [valid_f, log_lat, log_lat * log_lat, is_error.astype(jnp.float32) * valid_f],
        axis=0,
    )  # [4, B]
    return _delta_pallas(
        flat,
        rank,
        cidx.T,
        valid.astype(jnp.int32),
        jnp.where(valid & in_slice, svc, num_services),
        feats,
        num_services=num_services,
        hll_regs=r,
        cms_depth=d,
        cms_width=cms_width,
        interpret=(impl == "interpret"),
        batch_tile=batch_tile,
    )


def _update_pallas(
    flat: jnp.ndarray,
    rank: jnp.ndarray,
    cidx_t: jnp.ndarray,
    weight: jnp.ndarray,
    svc: jnp.ndarray,
    feats: jnp.ndarray,
    hll_cur: jnp.ndarray,  # int32[W, S, R]
    cms_cur: jnp.ndarray,  # int32[W, D, Wc]
    *,
    num_services: int,
    hll_regs: int,
    cms_depth: int,
    cms_width: int,
    interpret: bool = False,
    batch_tile: int | None = None,
    heads: HeadState | None = None,
    dt: jnp.ndarray | None = None,
    step_pos: jnp.ndarray | None = None,
    head_statics: dict | None = None,
):
    b = flat.shape[0]
    nb, tb = _batch_tiling(b, batch_tile)
    sr = num_services * hll_regs
    n_windows = hll_cur.shape[0]
    wide = nb > 1
    c_hll = _cell_chunk(sr, 2 * tb, wide=wide)
    # Row-stack the window banks into 2D blocks (same [rows, lanes]
    # shape discipline as the delta kernel — 3D blocks would force the
    # mosaic tiler onto an untested layout for no bandwidth gain).
    hll2 = hll_cur.reshape(n_windows * (sr // c_hll), c_hll)
    cms2 = cms_cur.reshape(n_windows * cms_depth, cms_width)
    fold = heads is not None
    out_dims: list[tuple[tuple[int, ...], jnp.dtype]] = [
        (hll2.shape, jnp.int32),
        (cms2.shape, jnp.int32),
        ((4, num_services), jnp.float32),
    ]
    head_ins: tuple = ()
    if fold:
        params = jnp.stack(
            [
                jnp.asarray(dt, jnp.float32),
                jnp.asarray(step_pos, jnp.float32),
            ]
        ).reshape(1, 2)
        head_ins = (
            heads.lat_mean, heads.lat_var, heads.err_mean,
            heads.rate_mean, heads.rate_var, heads.cusum,
            heads.obs_batches.reshape(1, num_services), params,
        )
        n_taus = heads.lat_mean.shape[1]
        out_dims += [
            ((num_services, n_taus), jnp.float32),  # lat_mean'
            ((num_services, n_taus), jnp.float32),  # lat_var'
            ((num_services, n_taus), jnp.float32),  # err_mean'
            ((num_services, n_taus), jnp.float32),  # rate_mean'
            ((num_services, n_taus), jnp.float32),  # rate_var'
            ((num_services, 3), jnp.float32),       # cusum'
            ((1, num_services), jnp.float32),       # obs_batches'
            ((num_services, n_taus), jnp.float32),  # lat_z
            ((num_services, n_taus), jnp.float32),  # err_z
            ((num_services, n_taus), jnp.float32),  # rate_z
        ]
    out_shape = _out_structs(
        out_dims,
        (flat, rank, cidx_t, weight, svc, feats, hll2, cms2) + head_ins,
    )
    d = cidx_t.shape[1]

    def col_tile(i):  # [B, k] inputs: tile the batch (row) axis
        return (i, 0)

    def feats_tile(i):  # [4, B] input: tile the lane (col) axis
        return (0, i)

    def whole(i):  # banks/heads/outputs: same full block every step
        return (0, 0)

    def whole_spec(shape):
        return pl.BlockSpec(shape, whole, memory_space=pltpu.VMEM)

    in_specs = [
        pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
        pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
        pl.BlockSpec((tb, d), col_tile, memory_space=pltpu.VMEM),
        pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
        pl.BlockSpec((tb, 1), col_tile, memory_space=pltpu.VMEM),
        pl.BlockSpec((4, tb), feats_tile, memory_space=pltpu.VMEM),
        whole_spec(hll2.shape),
        whole_spec(cms2.shape),
    ] + [whole_spec(tuple(x.shape)) for x in head_ins]
    out_specs = tuple(whole_spec(shape) for shape, _dtype in out_dims)

    got = pl.pallas_call(
        functools.partial(
            _update_kernel, wide=wide, n_windows=n_windows,
            fold_heads=fold, head_statics=head_statics,
        ),
        grid=(nb,),
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=out_specs,
        interpret=interpret,
    )(
        flat.reshape(b, 1),
        rank.reshape(b, 1),
        cidx_t,
        weight.reshape(b, 1),
        svc.reshape(b, 1),
        feats,
        hll2,
        cms2,
        *head_ins,
    )
    hll_new = got[0].reshape(n_windows, num_services, hll_regs)
    cms_new = got[1].reshape(n_windows, cms_depth, cms_width)
    stats = got[2]
    if not fold:
        return hll_new, cms_new, stats
    new_heads = HeadState(
        lat_mean=got[3], lat_var=got[4], err_mean=got[5],
        rate_mean=got[6], rate_var=got[7], cusum=got[8],
        obs_batches=got[9].reshape(num_services),
    )
    return hll_new, cms_new, stats, new_heads, (got[10], got[11], got[12])


def sketch_batch_update(
    hll_cur: jnp.ndarray,  # int32[W, S, R] — current window banks
    cms_cur: jnp.ndarray,  # int32[W, D, Wc] — current window banks
    svc: jnp.ndarray,  # int32[B] — local service ids (may be out of range)
    log_lat: jnp.ndarray,  # float32[B]
    is_error: jnp.ndarray,  # float32[B]
    trace_hi: jnp.ndarray,  # uint32[B]
    trace_lo: jnp.ndarray,  # uint32[B]
    cidx: jnp.ndarray,  # int32[D, B] — CMS row indices (global hashes)
    valid: jnp.ndarray,  # bool[B]
    *,
    num_services: int,
    hll_p: int = hll.HLL_P,
    cms_width: int = cms.CMS_WIDTH,
    impl: str = "xla",  # "xla" | "pallas" | "interpret"
    batch_tile: int | None = None,
    heads: HeadState | None = None,
    dt: jnp.ndarray | None = None,
    step_pos: jnp.ndarray | None = None,
    # Head constants: REQUIRED whenever ``heads`` is passed (no
    # defaults here — they live in DetectorConfig, and a stale copy
    # would silently detune the folded path).
    taus_s: tuple | None = None,
    warmup_batches: float | None = None,
    z_warmup_batches: float | None = None,
    cusum_k: float | None = None,
    cusum_cap: float | None = None,
    err_slack: float | None = None,
):
    """One-pass batch absorption: ``(hll_banks', cms_banks', stats)``.

    The single-chip fast path of the ingest spine: instead of
    materializing a :class:`SketchDelta` and broadcast-merging it into
    every tumbling window bank as a second step, the batch's effect is
    folded into ALL ``W`` current banks inside one program — the Pallas
    kernel keeps banks + batch tile VMEM-resident and never writes the
    intermediate delta to HBM; the ``xla`` reference expresses the same
    fold as delta+merge in one traced scope (XLA fuses the broadcast
    into the delta's epilogue). Integer monoids (HLL max, CMS add) make
    every impl bit-identical to the two-step form — pinned by
    tests/test_fused.py.

    **Fused head update** (the r15 close of PR 9's last round trip):
    pass ``heads`` (+ ``dt``, ``step_pos`` and the head constants) and
    the EWMA/CUSUM head advance folds into the SAME program — the
    return grows to ``(hll', cms', stats, heads',
    (lat_z, err_z, rate_z))``. In the Pallas impl the head math runs on
    the last grid step against the VMEM-resident stats accumulator, so
    no stats delta round-trips to HBM between sketch fold and head
    advance; every impl shares :func:`head_update` verbatim, making the
    folded form bit-identical to calling it separately (pinned by
    tests/test_fused.py).

    NOT for the mesh path: under ``shard_map`` the per-shard DELTA must
    cross the batch-axis collectives before any bank merge, so
    ``detector_step`` uses this only when ``comm is NO_COMM`` (the mesh
    path applies :func:`head_update` to the psum-merged stats instead).
    """
    r = 1 << hll_p
    svc = svc.astype(jnp.int32)
    in_slice = (svc >= 0) & (svc < num_services)
    bucket, rank = hll.hll_indices(trace_hi, trace_lo, p=hll_p)
    rank = jnp.where(valid & in_slice, rank, 0)
    flat = jnp.where(in_slice, svc, 0) * r + bucket
    head_statics = None
    if heads is not None:
        required = dict(
            taus_s=taus_s, warmup_batches=warmup_batches,
            z_warmup_batches=z_warmup_batches, cusum_k=cusum_k,
            cusum_cap=cusum_cap, err_slack=err_slack, dt=dt,
            step_pos=step_pos,
        )
        missing = [k for k, v in required.items() if v is None]
        if missing:
            raise TypeError(
                f"sketch_batch_update(heads=...) requires {missing} "
                "(the head constants come from DetectorConfig — no "
                "defaults here)"
            )
        head_statics = dict(
            taus_s=tuple(taus_s),
            warmup_batches=warmup_batches,
            z_warmup_batches=z_warmup_batches,
            cusum_k=cusum_k,
            cusum_cap=cusum_cap,
            err_slack=err_slack,
        )

    if impl == "xla":
        delta = sketch_batch_delta(
            svc, log_lat, is_error, trace_hi, trace_lo, cidx, valid,
            num_services=num_services, hll_p=hll_p, cms_width=cms_width,
            impl="xla",
        )
        merged = (
            jnp.maximum(hll_cur, delta.hll[None]),
            cms_cur + delta.cms[None],
            delta.stats,
        )
        if heads is None:
            return merged
        new_heads, zs = head_update(
            delta.stats, heads, dt, step_pos, **head_statics
        )
        return merged + (new_heads, zs)

    valid_f = valid.astype(jnp.float32)
    log_lat = log_lat.astype(jnp.float32) * valid_f
    feats = jnp.stack(
        [
            valid_f,
            log_lat,
            log_lat * log_lat,
            is_error.astype(jnp.float32) * valid_f,
        ],
        axis=0,
    )  # [4, B]
    return _update_pallas(
        flat,
        rank,
        cidx.T,
        valid.astype(jnp.int32),
        jnp.where(valid & in_slice, svc, num_services),
        feats,
        hll_cur,
        cms_cur,
        num_services=num_services,
        hll_regs=r,
        cms_depth=cidx.shape[0],
        cms_width=cms_width,
        interpret=(impl == "interpret"),
        batch_tile=batch_tile,
        heads=heads,
        dt=dt,
        step_pos=step_pos,
        head_statics=head_statics,
    )


# --- impl auto-select: geometry-derived rate model -----------------------
#
# Calibration anchors, measured single-chip at the REFERENCE geometry
# (S=32, p=12, D=4, W=8192; fetch-synchronized slope timing of the FULL
# detector step, r5 after the transposed-int8 MXU histogram landed —
# its geometry gate now engages from B=2048, n_keys multiple of 8192):
#
#     B        pallas      xla        winner
#     2048     1.1M/s      0.6M/s     pallas (narrow chunks)
#     8192     5.8M/s      ~2.3M/s    pallas (wide chunks)
#     16384    6.2M/s      ~4.2M/s    pallas
#     32768    6.7M/s     ~12M/s      xla
#     65536    6.6M/s     47.2M/s     xla
#     524288   7.2M/s    104.8M/s     xla
#     2097152     —       123.1M/s    xla (plateau)
#
# Mid-size xla numbers (8k-32k) carry real run-to-run variance on the
# tunneled topology (32768 measured 8-23M across trials — per-step
# FIXED costs dominate that band and RTT jitter leaks into short
# regions); anchors are tight-floor medians, and _TIE_MARGIN absorbs
# the slack. The r4 table's 42.7M@16384 did not reproduce after the
# r5 rework (today's tight-floor runs put 16384 at ~4M either round).
#
# The router must not hard-code the conclusions of that table (r3 did:
# fixed crossovers at 8192/32768, stale the moment cms_width or hll_p
# changed). Instead it scales both sides by geometry:
#
# - The dense kernel's work is O(B·cells) compares BY CONSTRUCTION
#   (every batch tile sweeps every sketch cell tile), so its rate
#   scales as 1/cells — the one scaling law in this file that is
#   exact, not fitted; the B-shape comes from the measured curve
#   (_PALLAS_CURVE: narrow-chunk ramp through 4096, wide plateau
#   7.2M/s at cells_ref). That law is
#   also the kernel's CEILING: at the reference geometry no layout can
#   push the dense formulation past ~12M spans/s (B·cells compares at
#   the VPU's element rate), which is why the large-B regime belongs
#   to the histogram formulation by construction, not by tuning — see
#   PARITY.md "config #4".
# - The xla path's rate comes from the measured curves above
#   (log-interpolated in B, engine chosen by the REAL geometry gate),
#   derated by bins growth: its large-B cost is the CMS histogram,
#   whose work scales with the bin count. Bins below the reference cap
#   at the measured rate (never extrapolate faster than measured).

_REF_CELLS = 32 * (1 << 12) + 4 * 8192  # 163840
_REF_BINS = 4 * 8192
# Dense-kernel full-step curve at the reference geometry (the narrow→
# wide chunk transition sits at 8192, see _cell_chunk); rates scale as
# 1/cells, the kernel's exact O(B·cells) law. A flat narrow anchor
# (the r5-initial model) misrouted 4096-6144 to the slower xla path —
# the measured curve keeps routing monotone through the ramp.
_PALLAS_CURVE = (
    (2048, 1.14e6), (4096, 1.54e6), (8192, 5.8e6), (16384, 6.2e6),
    (65536, 6.6e6), (524288, 7.2e6),
)
# (batch, spans/s) at the reference geometry, per histogram engine.
_XLA_MXU_CURVE = (
    (2048, 0.62e6), (8192, 2.3e6), (16384, 4.2e6), (32768, 12.0e6),
    (65536, 47.2e6), (524288, 104.8e6), (2097152, 123.1e6),
)
_XLA_SORT_CURVE = ((2048, 0.63e6), (4096, 1.2e6), (8192, 1.7e6), (32768, 7.0e6))
# Prefer xla inside this band: the pallas side is its best-case plateau
# K, while the sort numbers are full-step measurements — at the pre-MXU
# ~32k tie (6.7 vs 7.0) the dense kernel's model slightly overshoots.
_TIE_MARGIN = 0.9


def _interp_rate(curve, batch: float) -> float:
    """Piecewise log-log interpolation, clamped at the curve's ends."""
    import math

    if batch <= curve[0][0]:
        return curve[0][1]
    if batch >= curve[-1][0]:
        return curve[-1][1]
    for (b0, r0), (b1, r1) in zip(curve, curve[1:]):
        if b0 <= batch <= b1:
            f = math.log(batch / b0) / math.log(b1 / b0)
            return r0 * (r1 / r0) ** f
    return curve[-1][1]  # unreachable


def expected_rates(
    batch: int,
    cms_depth: int = cms.CMS_DEPTH,
    cms_width: int = cms.CMS_WIDTH,
    num_services: int = 32,
    hll_p: int = hll.HLL_P,
) -> tuple[float, float]:
    """(pallas, xla) expected spans/s at this batch AND geometry."""
    cells = num_services * (1 << hll_p) + cms_depth * cms_width
    bins = cms_depth * cms_width
    pallas_rate = _interp_rate(_PALLAS_CURVE, batch) * (
        _REF_CELLS / max(cells, 1)
    )
    mxu = cms.mxu_hist_geometry_ok(bins, cms_depth * batch)
    if mxu:
        # Bins growth derates the MXU estimate only: the one-hot
        # contraction's FLOPs scale with the bin count. The sort
        # engine's cost is O(keys·log) — bins touch nothing but the
        # searchsorted log factor, so its curve stays as measured.
        xla_rate = _interp_rate(_XLA_MXU_CURVE, batch) * min(
            1.0, _REF_BINS / max(bins, 1)
        )
    else:
        xla_rate = _interp_rate(_XLA_SORT_CURVE, batch)
    return pallas_rate, xla_rate


def resolve_impl(
    requested: str | None,
    batch: int | None = None,
    cms_depth: int = cms.CMS_DEPTH,
    cms_width: int = cms.CMS_WIDTH,
    num_services: int = 32,
    hll_p: int = hll.HLL_P,
) -> str:
    """Map a config's ``sketch_impl`` field to a concrete impl name.

    ``None`` auto-selects by backend, batch size AND sketch geometry:
    the expected-rate model above picks whichever side wins at the
    configured (cells, bins, batch) — e.g. a large sketch (S=64, p=14)
    sinks the dense kernel's K/cells rate enough that xla wins at every
    batch, where the r3 fixed-crossover table would have silently kept
    pallas. CPU interpret mode is for tests, not production CPU runs.
    """
    if requested is None:
        if jax.default_backend() != "tpu":
            return "xla"
        if batch is None:
            return "pallas"  # no batch hint: the low-latency default
        pallas_rate, xla_rate = expected_rates(
            batch, cms_depth, cms_width, num_services, hll_p
        )
        return "xla" if xla_rate >= _TIE_MARGIN * pallas_rate else "pallas"
    if requested not in ("xla", "pallas", "interpret"):
        raise ValueError(f"unknown sketch impl {requested!r}")
    return requested
