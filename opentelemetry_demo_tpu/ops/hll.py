"""HyperLogLog on packed register tensors, TPU-first.

State layout: ``int32[..., S, R]`` — any number of leading window/bank
axes, then a *keyed* axis ``S`` (one sub-sketch per service, mirroring the
per-service cardinality question the reference system answers with Jaeger
queries over trace ids; see SURVEY.md §2.3 and BASELINE config #3
"HyperLogLog distinct trace_id per service") and ``R = 2**p`` registers.

Registers hold the HLL rank (leading-zero count + 1) of the best hash seen
per bucket; ``int32`` rather than ``uint8`` because TPU vector lanes are
32-bit anyway and int32 scatter-max lowers cleanly; HBM cost is trivial
(S=64, p=12 → 1 MiB per bank).

Everything here is monoid algebra:
- update  = elementwise max of scattered ranks,
- merge   = elementwise max across shards (``lax.pmax`` over the batch
  mesh axis — the ICI collective; see ``parallel.merge``),
- query   = the classic bias-corrected harmonic estimator.

No data-dependent shapes: invalid lanes are masked to rank 0, which is the
monoid identity, so fixed-width batches need no compaction.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

# p=12 → 4096 registers, standard error 1.04/sqrt(4096) ≈ 1.6% — plenty for
# anomaly *detection* (we look for multi-sigma cardinality swings, not
# billing-grade counts), and small enough that a full multi-window bank of
# per-service sketches stays VMEM-resident for the fused Pallas kernel.
HLL_P = 12


def hll_init(num_keys: int, p: int = HLL_P, leading: tuple[int, ...] = ()) -> jnp.ndarray:
    """Zeroed register bank ``int32[*leading, num_keys, 2**p]``."""
    return jnp.zeros((*leading, num_keys, 1 << p), dtype=jnp.int32)


def hll_indices(
    hash_hi: jnp.ndarray, hash_lo: jnp.ndarray, p: int = HLL_P
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Split 64-bit hashes (as uint32 hi/lo lanes) into (bucket, rank).

    Bucket = low ``p`` bits of ``lo``. Rank = leading-zero count of the
    remaining 64-p bits + 1 (range [1, 65-p]); computed with ``lax.clz``
    on the two 32-bit lanes of ``w = h64 >> p`` — no 64-bit integers
    anywhere, so this maps directly onto TPU VPU ops.
    """
    hash_hi = hash_hi.astype(jnp.uint32)
    hash_lo = hash_lo.astype(jnp.uint32)
    r_mask = jnp.uint32((1 << p) - 1)
    bucket = (hash_lo & r_mask).astype(jnp.int32)

    # w = h64 >> p, in two lanes. w_hi has (32-p) significant bits.
    w_lo = (hash_lo >> p) | (hash_hi << (32 - p))
    w_hi = hash_hi >> p

    clz_hi = jax.lax.clz(w_hi).astype(jnp.int32)  # 32 when w_hi == 0
    clz_lo = jax.lax.clz(w_lo).astype(jnp.int32)
    # Leading zeros of w within its (64-p)-bit frame.
    lz = jnp.where(w_hi != 0, clz_hi - p, (32 - p) + clz_lo)
    rank = lz + 1
    return bucket, rank


def hll_update(
    regs: jnp.ndarray,
    key: jnp.ndarray,
    bucket: jnp.ndarray,
    rank: jnp.ndarray,
    valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Scatter-max a batch of (key, bucket, rank) into ``regs[S, R]``.

    ``key`` is the sub-sketch selector (service id). Invalid lanes are
    masked to rank 0 — the max-monoid identity — so the scatter is always
    full-width and shape-static. Flattening (key, bucket) into one index
    lets XLA emit a single 1-D scatter-max, the cheapest scatter form on
    TPU.
    """
    s, r = regs.shape[-2], regs.shape[-1]
    rank = rank.astype(jnp.int32)
    if valid is not None:
        rank = jnp.where(valid, rank, 0)
    flat_idx = key.astype(jnp.int32) * r + bucket.astype(jnp.int32)
    flat = regs.reshape(*regs.shape[:-2], s * r)
    flat = flat.at[..., flat_idx].max(rank, mode="drop")
    return flat.reshape(regs.shape)


def hll_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """HLL union: registers merge by elementwise max (exact, order-free)."""
    return jnp.maximum(a, b)


@jax.jit
def hll_estimate(regs: jnp.ndarray) -> jnp.ndarray:
    """Bias-corrected cardinality estimate over the last axis.

    Standard Flajolet et al. HLL estimator with the small-range
    linear-counting correction; the large-range correction is unnecessary
    with 64-bit hashes. Vectorises over all leading axes (windows ×
    services) in one fused VPU pass — querying the full sketch bank every
    step is cheap enough to feed the cardinality EWMA each batch.
    ``m`` comes from the register axis itself, so banks of any precision
    query correctly without plumbing ``p``.
    """
    m = jnp.float32(regs.shape[-1])
    regs_f = regs.astype(jnp.float32)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv_sum = jnp.sum(jnp.exp2(-regs_f), axis=-1)
    raw = alpha * m * m / inv_sum
    zeros = jnp.sum((regs == 0).astype(jnp.float32), axis=-1)
    # Linear counting when raw <= 2.5m and empty registers exist.
    lc = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lc = (raw <= 2.5 * m) & (zeros > 0)
    return jnp.where(use_lc, lc, raw)


def hll_estimate_np(regs: "object"):
    """Host twin of :func:`hll_estimate` for the query plane.

    Runs the same estimator (same float32 arithmetic, same
    linear-counting switch) over a host numpy register snapshot — the
    pipeline's dispatch-lock snapshot on a primary, the replication
    mirror on a read replica. Both roles answering a cardinality query
    through THIS function is what makes their answers bit-identical at
    the same replicated state (runtime.query's consistency contract)."""
    import numpy as np

    regs = np.asarray(regs)
    m = np.float32(regs.shape[-1])
    regs_f = regs.astype(np.float32)
    alpha = np.float32(0.7213) / (np.float32(1.0) + np.float32(1.079) / m)
    inv_sum = np.sum(np.exp2(-regs_f, dtype=np.float32), axis=-1, dtype=np.float32)
    raw = alpha * m * m / inv_sum
    zeros = np.sum((regs == 0), axis=-1).astype(np.float32)
    lc = m * np.log(m / np.maximum(zeros, np.float32(1.0)), dtype=np.float32)
    use_lc = (raw <= np.float32(2.5) * m) & (zeros > 0)
    return np.where(use_lc, lc, raw)
