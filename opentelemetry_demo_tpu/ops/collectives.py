"""Named-axis communicator: the detector's entire comm surface.

``detector_step`` is written against this four-method interface; with
``NO_COMM`` every method is the identity and the step is the single-chip
program. Inside ``shard_map`` the same code runs per-shard and these
methods become XLA collectives — the whole distributed design is "insert
four reductions", which is what mergeable sketch monoids buy.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax


class Comm(NamedTuple):
    """Axis names; ``None`` means that axis is not sharded.

    ``batch_axis`` may be a TUPLE of names on hybrid multi-host meshes
    (``("dcn", "batch")``) — ``lax.psum``/``pmax`` reduce over all of
    them at once, merging deltas across hosts and chips in one
    collective.

    ``merge_impl`` selects the delta-merge algorithm:

    - ``"direct"`` (default): one-shot ``lax.psum``/``pmax`` — XLA
      lowers these near-optimally onto ICI; the single-pod choice.
    - ``"ring"``: the chunked ``ppermute`` ring all-reduce
      (``parallel.ring``) on the LONG-HAUL axis — on a hybrid mesh the
      outer ``dcn`` axis rides the ring (chunked + overlapped, the
      bandwidth-scarce hop) while inner axes stay direct on ICI; on a
      2-D mesh the whole batch axis rides the ring.
    """

    batch_axis: str | tuple[str, ...] | None = None
    sketch_axis: str | None = None
    merge_impl: str = "direct"

    def _check_impl(self) -> None:
        # Validate BEFORE any early return, not only in
        # make_sharded_step: a typo'd impl on a directly-built Comm
        # must raise, not silently run direct and let ring-vs-direct
        # comparisons pass without exercising the ring.
        if self.merge_impl not in ("direct", "ring"):
            raise ValueError(f"unknown merge_impl {self.merge_impl!r}")

    def _merge_batch(self, x: jnp.ndarray, direct_op, ring_name: str) -> jnp.ndarray:
        self._check_impl()
        if not self.batch_axis:
            return x
        # Chunked ring hops only pay off on the KB-scale sketch banks;
        # scalars and tiny stats merges (fewer elements than ring
        # chunks) would become 2(n-1) latency-bound hops replacing one
        # collective — keep them direct.
        if self.merge_impl != "ring" or x.size < 256:
            return direct_op(x, self.batch_axis)
        # Lazy import: parallel.ring only depends on jax, but importing
        # it at module scope would cycle through the parallel package
        # (parallel → spmd → models → ops). By the time a ring Comm
        # traces, the package is fully loaded.
        from ..parallel import ring as ring_mod

        ring_op = getattr(ring_mod, ring_name)
        if isinstance(self.batch_axis, tuple):
            outer, inner = self.batch_axis[0], self.batch_axis[1:]
            if inner:
                x = direct_op(x, inner)
            return ring_op(x, outer)
        return ring_op(x, self.batch_axis)

    def psum_batch(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._merge_batch(x, lax.psum, "ring_merge_sum")

    def psum_batch_f32(self, x: jnp.ndarray) -> jnp.ndarray:
        """Float sums stay DIRECT in every merge_impl: ring chunking
        reorders the f32 reduction, so EWMA inputs (and every score
        downstream) would differ between ring and direct runs. Integer
        sketch monoids (exact in any order) are what rides the ring;
        the float stats tensor is KB-scale anyway."""
        self._check_impl()
        return lax.psum(x, self.batch_axis) if self.batch_axis else x

    def pmax_batch(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._merge_batch(x, lax.pmax, "ring_merge_max")

    def pmin_sketch(self, x: jnp.ndarray) -> jnp.ndarray:
        return lax.pmin(x, self.sketch_axis) if self.sketch_axis else x

    def sketch_index(self) -> jnp.ndarray:
        if self.sketch_axis is None:
            return jnp.int32(0)
        return lax.axis_index(self.sketch_axis).astype(jnp.int32)


NO_COMM = Comm(None, None)
