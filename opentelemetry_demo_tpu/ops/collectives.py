"""Named-axis communicator: the detector's entire comm surface.

``detector_step`` is written against this four-method interface; with
``NO_COMM`` every method is the identity and the step is the single-chip
program. Inside ``shard_map`` the same code runs per-shard and these
methods become XLA collectives — the whole distributed design is "insert
four reductions", which is what mergeable sketch monoids buy.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax


class Comm(NamedTuple):
    """Axis names; ``None`` means that axis is not sharded.

    ``batch_axis`` may be a TUPLE of names on hybrid multi-host meshes
    (``("dcn", "batch")``) — ``lax.psum``/``pmax`` reduce over all of
    them at once, merging deltas across hosts and chips in one
    collective."""

    batch_axis: str | tuple[str, ...] | None = None
    sketch_axis: str | None = None

    def psum_batch(self, x: jnp.ndarray) -> jnp.ndarray:
        return lax.psum(x, self.batch_axis) if self.batch_axis else x

    def pmax_batch(self, x: jnp.ndarray) -> jnp.ndarray:
        return lax.pmax(x, self.batch_axis) if self.batch_axis else x

    def pmin_sketch(self, x: jnp.ndarray) -> jnp.ndarray:
        return lax.pmin(x, self.sketch_axis) if self.sketch_axis else x

    def sketch_index(self) -> jnp.ndarray:
        if self.sketch_axis is None:
            return jnp.int32(0)
        return lax.axis_index(self.sketch_axis).astype(jnp.int32)


NO_COMM = Comm(None, None)
