"""Count-Min sketch on packed count tensors, TPU-first.

State layout: ``int32[..., D, W]`` — leading window axes, then ``D`` hash
rows × ``W`` counters. The sketch is *global* with the service id folded
into the key hash (keys are (service, attribute) pairs hashed together on
the host / in ``models.detector``): point queries always name a service,
so folding loses nothing and keeps the scatter one flat 1-D op instead of
a per-service loop — the shape XLA lowers best on TPU.

Row hashes use the Kirsch–Mitzenmacher construction ``g_i = lo + i·hi``
(two independent 32-bit hashes generate d pairwise-usable row hashes),
so the device never needs more than the one 64-bit key hash produced by
``ops.hashing``.

Monoid algebra: update = scatter-add, merge = elementwise add (``lax.psum``
over the batch mesh axis on multi-chip), query = min over rows.

Answers BASELINE config #2 ("Count-Min heavy-hitter attrs across all
services") — the reference system surfaces the same question as Grafana
top-k panels over spanmetrics
(/root/reference/src/grafana/provisioning/dashboards/demo/spanmetrics-dashboard.json).
"""

from __future__ import annotations

import jax.numpy as jnp

CMS_DEPTH = 4
# Width 8192: for counts over a 1-60s window of ~thousands of spans the
# over-estimate bound e·N/W is single-digit counts — negligible against the
# heavy-hitter thresholds we flag on. int32[4, 8192] = 128 KiB per window.
CMS_WIDTH = 8192


def cms_init(
    depth: int = CMS_DEPTH, width: int = CMS_WIDTH, leading: tuple[int, ...] = ()
) -> jnp.ndarray:
    """Zeroed count table ``int32[*leading, depth, width]``."""
    return jnp.zeros((*leading, depth, width), dtype=jnp.int32)


def cms_indices(
    hash_hi: jnp.ndarray,
    hash_lo: jnp.ndarray,
    depth: int = CMS_DEPTH,
    width: int = CMS_WIDTH,
) -> jnp.ndarray:
    """Row indices ``int32[depth, B]`` via Kirsch–Mitzenmacher.

    ``width`` must be a power of two so the modulo is a mask (VPU and-op,
    no integer division anywhere on device).
    """
    assert width & (width - 1) == 0, "CMS width must be a power of two"
    hi = hash_hi.astype(jnp.uint32)
    lo = hash_lo.astype(jnp.uint32)
    rows = []
    for i in range(depth):
        g = lo + jnp.uint32(i) * hi  # wrapping uint32 arithmetic
        rows.append((g & jnp.uint32(width - 1)).astype(jnp.int32))
    return jnp.stack(rows, axis=0)


def cms_update(
    table: jnp.ndarray,
    idx: jnp.ndarray,
    weight: jnp.ndarray | None = None,
    valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Scatter-add a batch into ``table[D, W]``.

    ``idx`` is ``[D, B]`` from :func:`cms_indices`. Invalid lanes add 0
    (the monoid identity) so batches stay fixed-width. One flat scatter of
    D·B elements.
    """
    d, w = table.shape[-2], table.shape[-1]
    b = idx.shape[-1]
    if weight is None:
        weight = jnp.ones((b,), dtype=table.dtype)
    weight = jnp.broadcast_to(weight.astype(table.dtype), (d, b))
    if valid is not None:
        weight = jnp.where(valid[None, :], weight, 0)
    row_offset = jnp.arange(d, dtype=jnp.int32)[:, None] * w
    flat_idx = (idx + row_offset).reshape(-1)
    flat = table.reshape(*table.shape[:-2], d * w)
    flat = flat.at[..., flat_idx].add(weight.reshape(-1), mode="drop")
    return flat.reshape(table.shape)


def cms_update_hist(
    table: jnp.ndarray,
    idx: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    impl: str | None = None,
) -> jnp.ndarray:
    """Scatter-free unit-weight batch count.

    Semantically identical to :func:`cms_update` with ``weight=None``.
    TPU scatters serialize on duplicate indices, and a CMS batch is
    nothing but duplicates (B ≫ W), so the histogram is computed
    scatter-free. Two interchangeable engines (bit-exact, both tested):

    - ``"mxu"`` (TPU default when the table fits 16-bit keys and the
      batch tiles evenly): the one-hot OUTER-PRODUCT histogram — each
      flat key splits into (hi, lo) bytes, a Pallas kernel builds
      TRANSPOSED int8 one-hots ([HI, TB] and [256, TB], keys riding the
      LANE axis so the equality-compare broadcasts across sublanes —
      the cheap direction; the r4 row-major layout broadcast the key
      column across lanes, a relayout that dominated the kernel) and
      contracts them on the MXU with int8×int8→int32 accumulation into
      the [HI, 256] count matrix
      (``count[hi, lo] = Σ_b 1[hi_b=hi]·1[lo_b=lo]``). int32
      accumulation is exact for any key count below 2³¹ — the r4 f32
      engine's 2²⁴ cap is gone. Measured single-chip, D=4 W=8192
      B=512k, 200-rep slope: **~0.49 ms vs 3.3 ms** for the r4 bf16
      row-major kernel and 7.9 ms for the sort engine — BELOW the old
      bf16 MXU FLOP bound (~0.7 ms), because int8 runs the MXU at 2×
      the bf16 rate (new int8 bound ~0.35 ms; the remaining 1.4× is
      one-hot construction, now minor).
    - ``"sort"``: ``diff(searchsorted(sort(ids), edges))`` — the
      engine everywhere the kernel can't run (CPU tests, odd
      geometries), and itself ~2× over the scatter at large B.

    2-D tables only (the delta path); invalid lanes carry key ``d·w``,
    one past the counted range, and fall out of either engine.
    """
    d, w = table.shape
    row_offset = jnp.arange(d, dtype=jnp.int32)[:, None] * w
    flat_idx = idx + row_offset
    if valid is not None:
        # Invalid lanes take key d·w — one past the counted range: the
        # sort engine's edge sweep stops before it, and the mxu engine
        # folds it onto the last bin pre-kernel and subtracts the exact
        # sentinel count afterwards (see _hist_mxu's sentinel-FOLD note).
        flat_idx = jnp.where(valid[None, :], flat_idx, d * w)
    flat = flat_idx.reshape(-1)
    if impl is None:
        impl = "mxu" if _mxu_hist_usable(d * w, flat.shape[0]) else "sort"
    if impl == "mxu":
        counts = _hist_mxu(flat, d * w).astype(table.dtype)
    else:
        s = jnp.sort(flat)
        edges = jnp.arange(d * w + 1, dtype=s.dtype)
        cuts = jnp.searchsorted(s, edges)
        counts = (cuts[1:] - cuts[:-1]).astype(table.dtype)
    return table + counts.reshape(d, w)


_HIST_TILE = 8192  # keys per MXU-histogram grid step (VMEM-resident)


def mxu_hist_geometry_ok(n_bins: int, n_keys: int) -> bool:
    """Pure-geometry gate for the MXU histogram engine (no backend
    check — also used by ``fused.resolve_impl`` to predict whether the
    xla path will get the fast engine at a given batch size)."""
    return (
        # (hi, lo) byte split: bins must fit 16-bit keys (sentinels are
        # folded onto the last bin pre-kernel, so they need no slot of
        # their own) and fill whole 256-wide lo rows.
        n_bins <= 65536
        and n_bins % 256 == 0
        # the kernel tiles the key axis; a partial tile would need a
        # second masked pass — keys are D·B with B a power of two in
        # every real config, so just fall back otherwise.
        and n_keys > 0
        and n_keys % _HIST_TILE == 0
        # the MXU accumulates bin counts in int32, exact below 2^31;
        # counts are bounded by the key count, so gate on it and let
        # larger batches take the sort engine.
        and n_keys < (1 << 31)
    )


def _mxu_hist_usable(n_bins: int, n_keys: int) -> bool:
    import jax

    return jax.default_backend() == "tpu" and mxu_hist_geometry_ok(
        n_bins, n_keys
    )


def _hist_mxu_kernel(keys_ref, out_ref):
    """One grid step: [1, TB] keys → TRANSPOSED int8 one-hots → MXU
    int8 contraction accumulated into the [HI, 256] int32 count block.

    Layout is the whole trick (r5): keys ride the LANE axis ([1, TB]
    row), so ``(k >> 8) == iota`` broadcasts the key vector across
    SUBLANES — the cheap broadcast direction. The r4 kernel held keys
    as a [TB, 1] column and broadcast across lanes, a per-element
    relayout that cost ~5× the matmul itself. int8 one-hots halve the
    VMEM footprint and run the MXU at 2× the bf16 rate with EXACT int32
    accumulation (no 2²⁴ cap). Keys arrive pre-clamped to [0, n_bins):
    sentinels are folded onto the last bin by the caller (see the
    sentinel-FOLD note in ``_hist_mxu``) and corrected after."""
    from jax import lax
    from jax.experimental import pallas as pl

    first = pl.program_id(0) == 0
    k = keys_ref[:]  # [1, TB] int32
    n_hi = out_ref.shape[0]
    iota_hi = lax.broadcasted_iota(jnp.int32, (n_hi, 1), 0)
    iota_lo = lax.broadcasted_iota(jnp.int32, (256, 1), 0)
    oh_hi = ((k >> 8) == iota_hi).astype(jnp.int8)  # [HI, TB]
    oh_lo = ((k & 255) == iota_lo).astype(jnp.int8)  # [256, TB]
    tile = lax.dot_general(
        oh_hi, oh_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [HI, 256]
    prev = jnp.where(first, 0, out_ref[:])
    out_ref[:] = prev + tile


def _hist_mxu(flat: jnp.ndarray, n_bins: int) -> jnp.ndarray:
    """Exact histogram of int32 keys in [0, n_bins] → counts[n_bins].

    Keys equal to ``n_bins`` (the invalid-lane sentinel) are clamped
    onto the last bin before the kernel and their exact count is
    subtracted afterwards — see the sentinel-FOLD note below. See
    cms_update_hist for engine selection."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = flat.shape[0]
    if n == 0 or n % _HIST_TILE:
        # The grid would silently truncate (or never write the output
        # block): a forced impl="mxu" at a non-tileable key count must
        # be an error, not wrong counts. Auto-select gates on this same
        # condition (_mxu_hist_usable).
        raise ValueError(
            f"mxu histogram needs a key count that is a nonzero "
            f"multiple of {_HIST_TILE}; got {n} (use impl='sort')"
        )
    if n_bins % 256:
        # Same must-be-an-error philosophy as the key-count guard: the
        # fold keeps exactly n_bins//256 hi rows, so a partial lo row
        # would silently drop keys past the last whole row (the pre-r4
        # +1-row variant tolerated this; the fold does not).
        raise ValueError(
            f"mxu histogram needs a bin count that is a multiple of "
            f"256; got {n_bins} (use impl='sort')"
        )
    if n >= 1 << 31:
        # int32 accumulation is exact only below 2^31 (counts are
        # bounded by the key count) — a forced impl="mxu" past that
        # must be an error, not silently wrapped counts, same
        # philosophy as the tile/bin guards above. Auto-select gates on
        # this condition too (mxu_hist_geometry_ok).
        raise ValueError(
            f"mxu histogram is int32-exact only below 2^31 keys; got {n} "
            f"(use impl='sort')"
        )
    # Sentinel FOLD (r4): the invalid-lane key ``n_bins`` used to ride
    # its own hi row, making HI = n_bins//256 + 1 — 129 at the
    # production table — and the MXU pads output rows to 128-row
    # tiles, so that single extra row DOUBLED the contraction passes
    # (measured: ~2x hist wall time). Clamp sentinels onto the last
    # real bin and subtract their exact count afterwards: HI stays a
    # whole number of MXU tiles and the result is bit-identical.
    sentinel_count = jnp.sum((flat >= n_bins).astype(jnp.int32))
    flat = jnp.minimum(flat, n_bins - 1)
    n_hi = n_bins // 256
    try:
        # Propagate the keys' varying-manual-axes under shard_map; on
        # older jax (no typeof/vma) a plain struct is exactly right.
        out_struct = jax.ShapeDtypeStruct(
            (n_hi, 256), jnp.int32, vma=jax.typeof(flat).vma
        )
    except (AttributeError, TypeError):
        out_struct = jax.ShapeDtypeStruct((n_hi, 256), jnp.int32)

    # Keys as ONE [1, n] row, blocked along the lane axis: the block's
    # leading dim (1) equals the array's, satisfying the Pallas TPU
    # block-divisibility rule, and the kernel sees each tile lane-major
    # (the layout the transposed construction needs).
    counts2d = pl.pallas_call(
        _hist_mxu_kernel,
        grid=(n // _HIST_TILE,),
        # int8 one-hots: [HI, TB]+[256, TB] ≈ 3 MiB at TB=8k —
        # comfortably inside the default scoped-VMEM budget (the r4
        # bf16 row-major tiles needed a 96 MiB override).
        out_shape=out_struct,
        in_specs=[
            pl.BlockSpec(
                (1, _HIST_TILE), lambda i: (0, i),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (n_hi, 256), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
    )(flat.reshape(1, n))
    counts = counts2d.reshape(-1)
    return counts.at[n_bins - 1].add(-sentinel_count)


def cms_query(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Point-query counts for a batch: ``min`` over the D rows.

    Returns ``int32[..., B]`` for ``table[..., D, W]`` and ``idx[D, B]``.
    Gathers vectorise over leading window axes.
    """
    gathered = jnp.take_along_axis(
        table, jnp.broadcast_to(idx, (*table.shape[:-2], *idx.shape)), axis=-1
    )
    return jnp.min(gathered, axis=-2)


def cms_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """CMS union: tables merge by elementwise addition (exact)."""
    return a + b


# -- host-side read helpers (the query plane) --------------------------
#
# The live query service (runtime.query) answers point queries from a
# SNAPSHOT of sketch state — host numpy arrays taken under the
# pipeline's dispatch lock on the primary, or the replication mirror on
# a read replica (which has no device at all). These numpy twins of
# cms_indices/cms_query exist so both roles run the IDENTICAL read
# path: same dtypes, same wrapping arithmetic, bit-identical answers
# from bit-identical state (the read-replica consistency bar
# tests/test_query.py asserts).


def cms_indices_np(
    hash_hi: "np.ndarray",
    hash_lo: "np.ndarray",
    depth: int = CMS_DEPTH,
    width: int = CMS_WIDTH,
) -> "np.ndarray":
    """Host twin of :func:`cms_indices`: ``int32[depth, B]`` rows via
    the same Kirsch–Mitzenmacher construction in wrapping uint32."""
    import numpy as np

    assert width & (width - 1) == 0, "CMS width must be a power of two"
    hi = hash_hi.astype(np.uint32)
    lo = hash_lo.astype(np.uint32)
    rows = []
    with np.errstate(over="ignore"):
        for i in range(depth):
            g = lo + np.uint32(i) * hi
            rows.append((g & np.uint32(width - 1)).astype(np.int32))
    return np.stack(rows, axis=0)


def cms_query_np(table: "np.ndarray", idx: "np.ndarray") -> "np.ndarray":
    """Host twin of :func:`cms_query`: min over the D rows of a host
    table snapshot. ``table[..., D, W]``, ``idx[D, B]`` →
    ``int32[..., B]``."""
    import numpy as np

    gathered = np.take_along_axis(
        table, np.broadcast_to(idx, (*table.shape[:-2], *idx.shape)), axis=-1
    )
    return np.min(gathered, axis=-2)
