"""Count-Min sketch on packed count tensors, TPU-first.

State layout: ``int32[..., D, W]`` — leading window axes, then ``D`` hash
rows × ``W`` counters. The sketch is *global* with the service id folded
into the key hash (keys are (service, attribute) pairs hashed together on
the host / in ``models.detector``): point queries always name a service,
so folding loses nothing and keeps the scatter one flat 1-D op instead of
a per-service loop — the shape XLA lowers best on TPU.

Row hashes use the Kirsch–Mitzenmacher construction ``g_i = lo + i·hi``
(two independent 32-bit hashes generate d pairwise-usable row hashes),
so the device never needs more than the one 64-bit key hash produced by
``ops.hashing``.

Monoid algebra: update = scatter-add, merge = elementwise add (``lax.psum``
over the batch mesh axis on multi-chip), query = min over rows.

Answers BASELINE config #2 ("Count-Min heavy-hitter attrs across all
services") — the reference system surfaces the same question as Grafana
top-k panels over spanmetrics
(/root/reference/src/grafana/provisioning/dashboards/demo/spanmetrics-dashboard.json).
"""

from __future__ import annotations

import jax.numpy as jnp

CMS_DEPTH = 4
# Width 8192: for counts over a 1-60s window of ~thousands of spans the
# over-estimate bound e·N/W is single-digit counts — negligible against the
# heavy-hitter thresholds we flag on. int32[4, 8192] = 128 KiB per window.
CMS_WIDTH = 8192


def cms_init(
    depth: int = CMS_DEPTH, width: int = CMS_WIDTH, leading: tuple[int, ...] = ()
) -> jnp.ndarray:
    """Zeroed count table ``int32[*leading, depth, width]``."""
    return jnp.zeros((*leading, depth, width), dtype=jnp.int32)


def cms_indices(
    hash_hi: jnp.ndarray,
    hash_lo: jnp.ndarray,
    depth: int = CMS_DEPTH,
    width: int = CMS_WIDTH,
) -> jnp.ndarray:
    """Row indices ``int32[depth, B]`` via Kirsch–Mitzenmacher.

    ``width`` must be a power of two so the modulo is a mask (VPU and-op,
    no integer division anywhere on device).
    """
    assert width & (width - 1) == 0, "CMS width must be a power of two"
    hi = hash_hi.astype(jnp.uint32)
    lo = hash_lo.astype(jnp.uint32)
    rows = []
    for i in range(depth):
        g = lo + jnp.uint32(i) * hi  # wrapping uint32 arithmetic
        rows.append((g & jnp.uint32(width - 1)).astype(jnp.int32))
    return jnp.stack(rows, axis=0)


def cms_update(
    table: jnp.ndarray,
    idx: jnp.ndarray,
    weight: jnp.ndarray | None = None,
    valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Scatter-add a batch into ``table[D, W]``.

    ``idx`` is ``[D, B]`` from :func:`cms_indices`. Invalid lanes add 0
    (the monoid identity) so batches stay fixed-width. One flat scatter of
    D·B elements.
    """
    d, w = table.shape[-2], table.shape[-1]
    b = idx.shape[-1]
    if weight is None:
        weight = jnp.ones((b,), dtype=table.dtype)
    weight = jnp.broadcast_to(weight.astype(table.dtype), (d, b))
    if valid is not None:
        weight = jnp.where(valid[None, :], weight, 0)
    row_offset = jnp.arange(d, dtype=jnp.int32)[:, None] * w
    flat_idx = (idx + row_offset).reshape(-1)
    flat = table.reshape(*table.shape[:-2], d * w)
    flat = flat.at[..., flat_idx].add(weight.reshape(-1), mode="drop")
    return flat.reshape(table.shape)


def cms_update_hist(
    table: jnp.ndarray,
    idx: jnp.ndarray,
    valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Scatter-free unit-weight batch count: sort + searchsorted.

    Semantically identical to :func:`cms_update` with ``weight=None``.
    TPU scatters serialize on duplicate indices, and a CMS batch is
    nothing but duplicates (B ≫ W); a histogram computed as
    ``diff(searchsorted(sort(ids), bin_edges))`` avoids scatters
    entirely — measured ~2× faster at B=512k, D=4, W=8192 on v5e-1
    (7.3 ms vs 14.2 ms), which matters because the CMS update dominates
    the large-batch detector step. 2-D tables only (the delta path);
    invalid lanes sort past the last edge and fall out of the counts.
    """
    d, w = table.shape
    row_offset = jnp.arange(d, dtype=jnp.int32)[:, None] * w
    flat_idx = idx + row_offset
    if valid is not None:
        flat_idx = jnp.where(valid[None, :], flat_idx, d * w)
    s = jnp.sort(flat_idx.reshape(-1))
    edges = jnp.arange(d * w + 1, dtype=flat_idx.dtype)
    cuts = jnp.searchsorted(s, edges)
    counts = (cuts[1:] - cuts[:-1]).astype(table.dtype)
    return table + counts.reshape(d, w)


def cms_query(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Point-query counts for a batch: ``min`` over the D rows.

    Returns ``int32[..., B]`` for ``table[..., D, W]`` and ``idx[D, B]``.
    Gathers vectorise over leading window axes.
    """
    gathered = jnp.take_along_axis(
        table, jnp.broadcast_to(idx, (*table.shape[:-2], *idx.shape)), axis=-1
    )
    return jnp.min(gathered, axis=-2)


def cms_merge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """CMS union: tables merge by elementwise addition (exact)."""
    return a + b
