"""EWMA mean/variance tracking and z-scores, vectorised over keyed axes.

This is the detection head (BASELINE config #1: "EWMA z-score on
checkoutservice span latency"). State is a pair of ``float32[..., S, T]``
tensors (mean, var) for S services × T timescales; each update folds one
batch observation per service into all timescales at once.

Timescales replace tumbling windows for the latency/error-rate signals:
an EWMA with time constant τ *is* a continuously-sliding window of width
≈τ, with none of the reset discontinuities — ideal for <100 ms detection
lag because every batch moves the estimate. (Distinct-count signals can't
be EWMA'd that way — cardinality is not an average — so HLL banks keep
real tumbling windows; see ``models.windows``.)

The per-service batch reduction is a one-hot matmul (``segment_stats``):
(B,S) one-hot against the value vector rides the MXU, turning the only
"segmented" operation in the hot path into dense linear algebra — the
TPU-first answer to what a CUDA build would do with atomics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ewma_init(num_keys: int, num_scales: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Zeroed (mean, var) state ``float32[num_keys, num_scales]``."""
    shape = (num_keys, num_scales)
    return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)


def segment_stats(
    values: jnp.ndarray,
    seg: jnp.ndarray,
    num_segments: int,
    valid: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-segment (count, sum, sum-of-squares) via one-hot matmul.

    ``values: float32[B]``, ``seg: int32[B]`` → three ``float32[S]``.
    The (B,S) one-hot is built with a broadcasted-iota compare (no 1-D
    iota — TPU constraint) and contracted on the MXU with
    ``preferred_element_type=float32``.
    """
    b = values.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (b, num_segments), 1)
    onehot = (col == seg.astype(jnp.int32)[:, None]).astype(jnp.float32)
    if valid is not None:
        onehot = onehot * valid.astype(jnp.float32)[:, None]
    values = values.astype(jnp.float32)
    stacked = jnp.stack(
        [jnp.ones_like(values), values, values * values], axis=0
    )  # [3, B]
    out = jnp.dot(stacked, onehot, preferred_element_type=jnp.float32)  # [3, S]
    return out[0], out[1], out[2]


def ewma_update(
    mean: jnp.ndarray,
    var: jnp.ndarray,
    x: jnp.ndarray,
    alpha: jnp.ndarray,
    observed: jnp.ndarray | None = None,
    warmup: jnp.ndarray | None = None,
    eps: float = 1e-6,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One EWMA step; returns (mean', var', z).

    ``x`` broadcasts against ``mean``/``var`` (typically ``[S, 1]`` vs
    ``[S, T]``). ``alpha`` is the per-timescale smoothing weight,
    ``1 - exp(-dt/τ)`` for batch gap ``dt`` — passed in by the caller so
    the kernel stays shape-static while the cadence varies.

    The z-score is computed against the *prior* state (the anomaly question
    is "is this batch surprising given history so far"), then the state
    absorbs the observation: West's incremental update
    ``var' = (1-α)(var + α·δ²)``.

    ``observed`` masks keys with no data this batch (state frozen, z=0).
    ``warmup`` (same shape semantics) suppresses z until a key has seen
    enough history to make "surprise" meaningful.
    """
    x = x.astype(jnp.float32)
    delta = x - mean
    z = delta / jnp.sqrt(var + eps)
    new_mean = mean + alpha * delta
    new_var = (1.0 - alpha) * (var + alpha * delta * delta)
    if observed is not None:
        obs = observed.astype(jnp.bool_)
        new_mean = jnp.where(obs, new_mean, mean)
        new_var = jnp.where(obs, new_var, var)
        z = jnp.where(obs, z, 0.0)
    if warmup is not None:
        z = jnp.where(warmup.astype(jnp.bool_), 0.0, z)
    return new_mean, new_var, z
