"""The Astronomy Shop capability layer, in-process.

Behavioural re-implementations of the reference's business services
(SURVEY.md §2.1) as one-process Python components wired by
:class:`~.shop.Shop` — the docker-compose analogue — emitting spans
through ``telemetry.Tracer`` into the anomaly-detector pipeline. Each
module's docstring cites the reference service whose observable
behaviour it mirrors (APIs, failure flags, latency profiles); none of
them translate reference code — the stack here is Python-in-proc +
the framework's native/TPU components, not Go/C#/Java/PHP/Ruby ports.

Failure injection parity (SURVEY.md §5): every reference flagd flag has
an equivalent here and flips real behaviour the detector must catch.
"""

from .gateway import ShopGateway
from .shop import Shop, ShopConfig

__all__ = ["Shop", "ShopConfig", "ShopGateway"]
