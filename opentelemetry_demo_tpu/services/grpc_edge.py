"""gRPC edge: the reference's 9-service gRPC surface over the shop.

The reference's business services ARE gRPC servers (pb/demo.proto
services; e.g. checkout serves CheckoutService, cart CartService). This
framework's services are in-proc objects behind the HTTP gateway; the
gRPC edge exposes the same wire surface — method paths
``/oteldemo.<Service>/<Method>`` with the reference's field numbers
(proto/demo.proto) — so a client built against the reference's stubs
talks to this shop unchanged.

Transport is grpcio generic raw-bytes handlers (the ``otlp_grpc``
pattern): requests decode by field number through the wire scanner,
responses encode with the wire helpers — no generated stubs anywhere in
the runtime. Interop with REAL protoc stubs is pinned by
tests/test_grpc_edge.py.

Concurrency: mutating RPCs take the shop lock exclusively (the graph is
single-writer by design — the HTTP gateway serializes the same way),
but read-only RPCs (:data:`READ_METHODS`) run CONCURRENTLY under the
shared side of a :class:`~..utils.concurrency.RWLock` — a product-
catalog fan-out no longer queues behind a PlaceOrder. The health
service (``grpc.health.v1``, the registration every reference service
performs — /root/reference/src/checkout/main.go:223-224,
src/currency/src/server.cpp:92-102) answers entirely outside the lock.
"""

from __future__ import annotations

import functools
import struct as _struct
import threading

from ..runtime import structpb, wire
from ..runtime.kafka_orders import encode_placed_order
from ..telemetry.tracer import TraceContext
from ..utils.concurrency import RWLock
from .base import ServiceError
from .money import Money

PKG = "oteldemo"
FLAGD_PKG = "flagd.evaluation.v1"

# RPCs with no shop-graph writes: safe under the shared lock. Span
# emission, metrics, and rng draws inside them are individually
# thread-safe (atomic list append / MetricRegistry mutex / LockedRng).
READ_METHODS = frozenset({
    f"/{PKG}.CartService/GetCart",
    f"/{PKG}.RecommendationService/ListRecommendations",
    f"/{PKG}.ProductCatalogService/ListProducts",
    f"/{PKG}.ProductCatalogService/GetProduct",
    f"/{PKG}.ProductCatalogService/SearchProducts",
    f"/{PKG}.ShippingService/GetQuote",
    f"/{PKG}.CurrencyService/GetSupportedCurrencies",
    f"/{PKG}.CurrencyService/Convert",
    f"/{PKG}.AdService/GetAds",
    f"/{PKG}.FeatureFlagService/GetFlag",
    f"/{PKG}.FeatureFlagService/ListFlags",
    f"/{FLAGD_PKG}.Service/ResolveBoolean",
    f"/{FLAGD_PKG}.Service/ResolveString",
    f"/{FLAGD_PKG}.Service/ResolveFloat",
    f"/{FLAGD_PKG}.Service/ResolveInt",
    f"/{FLAGD_PKG}.Service/ResolveObject",
    f"/{FLAGD_PKG}.Service/ResolveAll",
})



# -- message codecs (field numbers = proto/demo.proto) ------------------


def _enc_money(m: Money) -> bytes:
    out = wire.encode_len(1, m.currency.encode())
    if m.units:
        out += wire.encode_int(2, m.units)
    if m.nanos:
        out += wire.encode_int(3, m.nanos)
    return out


def _dec_money(buf: bytes) -> Money:
    f = wire.scan_fields(buf)
    code = wire.first(f, 1, b"USD")
    # int64/int32 varints need sign extension — a negative Money (a
    # refund) arrives as 64-bit two's complement on the wire.
    return Money(
        code.decode() if isinstance(code, bytes) else "USD",
        wire.to_int64(int(wire.first(f, 2, 0) or 0)),
        wire.to_int64(int(wire.first(f, 3, 0) or 0)),
    )


def _dec_str(fields: dict, n: int, default: str = "") -> str:
    raw = wire.first(fields, n, None)
    return raw.decode("utf-8", "replace") if isinstance(raw, bytes) else default


def _enc_cart_item(product_id: str, qty: int) -> bytes:
    return wire.encode_len(1, product_id.encode()) + wire.encode_int(2, qty)


def _enc_product(p: dict) -> bytes:
    out = wire.encode_len(1, p["id"].encode())
    out += wire.encode_len(2, p.get("name", "").encode())
    if p.get("description"):
        out += wire.encode_len(3, p["description"].encode())
    out += wire.encode_len(4, f"/images/{p['id']}.svg".encode())
    out += wire.encode_len(5, _enc_money(Money.from_float("USD", p["priceUsd"])))
    for cat in p.get("categories", []):
        out += wire.encode_len(6, cat.encode())
    return out


class GrpcShopEdge:
    """Serves the oteldemo gRPC surface; delegates into a Shop."""

    def __init__(self, shop, host: str = "0.0.0.0", port: int = 0,
                 lock: threading.Lock | RWLock | None = None,
                 max_workers: int = 8):
        import grpc
        from concurrent import futures

        self.shop = shop
        # An RWLock (default, and what the gateway shares) runs read
        # RPCs concurrently; a plain Lock (legacy callers) degrades to
        # exclusive-for-everything.
        self._lock = lock if lock is not None else RWLock()
        self._shared = getattr(self._lock, "shared", None)
        self._stop_event = threading.Event()
        edge = self

        handlers = {
            f"/{PKG}.CartService/AddItem": self._add_item,
            f"/{PKG}.CartService/GetCart": self._get_cart,
            f"/{PKG}.CartService/EmptyCart": self._empty_cart,
            f"/{PKG}.RecommendationService/ListRecommendations":
                self._list_recommendations,
            f"/{PKG}.ProductCatalogService/ListProducts": self._list_products,
            f"/{PKG}.ProductCatalogService/GetProduct": self._get_product,
            f"/{PKG}.ProductCatalogService/SearchProducts": self._search_products,
            f"/{PKG}.ShippingService/GetQuote": self._get_quote,
            f"/{PKG}.ShippingService/ShipOrder": self._ship_order,
            f"/{PKG}.CurrencyService/GetSupportedCurrencies":
                self._supported_currencies,
            f"/{PKG}.CurrencyService/Convert": self._convert,
            f"/{PKG}.PaymentService/Charge": self._charge,
            f"/{PKG}.EmailService/SendOrderConfirmation": self._send_confirmation,
            f"/{PKG}.CheckoutService/PlaceOrder": self._place_order,
            f"/{PKG}.AdService/GetAds": self._get_ads,
            f"/{PKG}.FeatureFlagService/GetFlag": self._get_flag,
            f"/{PKG}.FeatureFlagService/CreateFlag": self._create_flag,
            f"/{PKG}.FeatureFlagService/UpdateFlag": self._update_flag,
            f"/{PKG}.FeatureFlagService/ListFlags": self._list_flags,
            f"/{PKG}.FeatureFlagService/DeleteFlag": self._delete_flag,
            # flagd's own gRPC evaluation protocol (the :8013 surface
            # every OpenFeature flagd provider dials — schemas.flagd.dev;
            # SURVEY §1 "flagd gRPC :8013"). Typed resolvers + ResolveAll;
            # EventStream is registered as a streaming method below.
            f"/{FLAGD_PKG}.Service/ResolveBoolean":
                functools.partial(self._resolve_typed, bool),
            f"/{FLAGD_PKG}.Service/ResolveString":
                functools.partial(self._resolve_typed, str),
            f"/{FLAGD_PKG}.Service/ResolveFloat":
                functools.partial(self._resolve_typed, float),
            f"/{FLAGD_PKG}.Service/ResolveInt":
                functools.partial(self._resolve_typed, int),
            f"/{FLAGD_PKG}.Service/ResolveObject":
                functools.partial(self._resolve_typed, dict),
            f"/{FLAGD_PKG}.Service/ResolveAll": self._resolve_all,
        }

        # grpc.health.v1 (shared implementation, runtime.grpc_health):
        # answers for the oteldemo services plus "" (overall server
        # health, the probe every reference healthcheck queries).
        from ..runtime.grpc_health import HealthService

        self._health = HealthService(
            {m.split("/")[1] for m in handlers},
            self._stop_event,
            watcher_slots=2,
        )

        # flagd EventStream watchers share the health-watch thread
        # budget rationale: slot-bounded so parked streams can't starve
        # the executor pool.
        self._event_watchers = threading.Semaphore(2)

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                health = edge._health.add_to_generic_handlers(
                    grpc, details.method
                )
                if health is not None:
                    return health
                if details.method == f"/{FLAGD_PKG}.Service/EventStream":
                    return grpc.unary_stream_rpc_method_handler(
                        edge._event_stream_rpc,
                        request_deserializer=None, response_serializer=None,
                    )
                fn = handlers.get(details.method)
                if fn is None:
                    return None
                read_only = details.method in READ_METHODS
                is_flagd = details.method.startswith(f"/{FLAGD_PKG}.")

                def call(request: bytes, context) -> bytes:
                    # W3C context rides gRPC metadata (every reference
                    # SDK propagates traceparent/baggage this way);
                    # from_headers handles absence (fresh trace id) and
                    # parses baggage either way.
                    meta = {
                        k: v for k, v in (context.invocation_metadata() or [])
                        if isinstance(v, str)
                    }
                    ctx = TraceContext.from_headers(meta)
                    try:
                        if read_only and edge._shared is not None:
                            with edge._shared():
                                return fn(ctx, request)
                        with edge._lock:
                            return fn(ctx, request)
                    except ServiceError as e:
                        context.abort(grpc.StatusCode.INTERNAL, str(e))
                    except KeyError as e:
                        if not is_flagd:
                            # A KeyError in a business handler is a
                            # server bug, not a missing flag — let the
                            # framework surface INTERNAL, never a
                            # plausible-looking NOT_FOUND.
                            raise
                        # flagd contract: unknown/disabled flag =
                        # FLAG_NOT_FOUND → gRPC NOT_FOUND.
                        context.abort(
                            grpc.StatusCode.NOT_FOUND,
                            f"flag not found: {e.args[0] if e.args else e}",
                        )
                    except (wire.WireError, ValueError) as e:
                        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

                return grpc.unary_unary_rpc_method_handler(
                    call, request_deserializer=None, response_serializer=None
                )

        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="grpc-edge"
            )
        )
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise OSError(f"gRPC edge failed to bind {host}:{port}")

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 1.0) -> None:
        # Flip health to NOT_SERVING first so Watch streams deliver the
        # transition before the server tears down (the drain order
        # health-gated load balancers rely on).
        self._stop_event.set()
        self._server.stop(grace).wait()

    # -- cart ----------------------------------------------------------

    def _add_item(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        user_id = _dec_str(f, 1)
        item = wire.scan_fields(wire.first(f, 2, b"") or b"")
        self.shop.cart.add_item(
            ctx, user_id, _dec_str(item, 1), int(wire.first(item, 2, 1) or 1)
        )
        return b""

    def _get_cart(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        user_id = _dec_str(f, 1)
        items = self.shop.cart.get_cart(ctx, user_id)
        out = wire.encode_len(1, user_id.encode())
        for pid, qty in items.items():
            out += wire.encode_len(2, _enc_cart_item(pid, qty))
        return out

    def _empty_cart(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        self.shop.cart.empty_cart(ctx, _dec_str(f, 1))
        return b""

    # -- recommendation / catalog --------------------------------------

    def _list_recommendations(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        exclude = [b.decode("utf-8", "replace") for b in f.get(2, [])]
        recs = self.shop.recommendation.list_recommendations(ctx, exclude)
        return b"".join(wire.encode_len(1, r.encode()) for r in recs)

    def _list_products(self, ctx, request: bytes) -> bytes:
        products = self.shop.catalog.list_products(ctx)
        return b"".join(
            wire.encode_len(1, _enc_product(p)) for p in products
        )

    def _get_product(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        return _enc_product(self.shop.catalog.get_product(ctx, _dec_str(f, 1)))

    def _search_products(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        hits = self.shop.catalog.search_products(ctx, _dec_str(f, 1))
        return b"".join(wire.encode_len(1, _enc_product(p)) for p in hits)

    # -- shipping ------------------------------------------------------

    @staticmethod
    def _item_count(f: dict) -> int:
        count = 0
        for item_buf in f.get(2, []):
            item = wire.scan_fields(item_buf)
            count += int(wire.first(item, 2, 1) or 1)
        return count

    def _get_quote(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        cost = self.shop.shipping.get_quote(ctx, self._item_count(f))
        return wire.encode_len(1, _enc_money(cost))

    def _ship_order(self, ctx, request: bytes) -> bytes:
        tracking = self.shop.shipping.ship_order(ctx)
        return wire.encode_len(1, tracking.encode())

    # -- currency / payment --------------------------------------------

    def _supported_currencies(self, ctx, request: bytes) -> bytes:
        codes = self.shop.currency.supported_currencies(ctx)
        return b"".join(wire.encode_len(1, c.encode()) for c in codes)

    def _convert(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        money = _dec_money(wire.first(f, 1, b"") or b"")
        converted = self.shop.currency.convert(ctx, money, _dec_str(f, 2))
        return _enc_money(converted)

    def _charge(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        amount = _dec_money(wire.first(f, 1, b"") or b"")
        card = wire.scan_fields(wire.first(f, 2, b"") or b"")
        txid = self.shop.payment.charge(
            ctx,
            amount,
            _dec_str(card, 1),
            int(wire.first(card, 3, 2030) or 2030),
            int(wire.first(card, 4, 1) or 1),
        )
        return wire.encode_len(1, txid.encode())

    # -- email / checkout / ad -----------------------------------------

    def _send_confirmation(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        order = wire.scan_fields(wire.first(f, 2, b"") or b"")
        self.shop.email.send_order_confirmation(
            ctx, _dec_str(f, 1), _dec_str(order, 1)
        )
        return b""

    def _place_order(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        card = wire.scan_fields(wire.first(f, 6, b"") or b"")
        kwargs = {}
        if _dec_str(card, 1):
            kwargs = {
                "card_number": _dec_str(card, 1),
                "expiry_year": int(wire.first(card, 3, 2030) or 2030),
                "expiry_month": int(wire.first(card, 4, 1) or 1),
            }
        placed = self.shop.checkout.place_order(
            ctx,
            _dec_str(f, 1),
            _dec_str(f, 2, "USD"),
            _dec_str(f, 5),
            **kwargs,
        )
        # OrderResult field 3 is shipping_cost (proto/demo.proto:202),
        # NOT the grand total — marshalled by the SAME helper checkout's
        # Kafka publish uses, so the two transports cannot diverge.
        return wire.encode_len(1, encode_placed_order(placed))

    def _get_ads(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        keys = [b.decode("utf-8", "replace") for b in f.get(1, [])]
        ads = self.shop.ad.get_ads(ctx, keys)
        out = b""
        for ad_text in ads:
            ad = wire.encode_len(1, b"/") + wire.encode_len(2, ad_text.encode())
            out += wire.encode_len(1, ad)
        return out

    # -- feature flags (the flagd-analogue store over gRPC) ------------
    #
    # The wire Flag{name, description, enabled} projects onto the flagd
    # document: enabled = state ENABLED with a truthy defaultVariant;
    # Create/Update write boolean on/off flags (richer variants stay
    # editable through the flag-editor UI, which shares the store).

    def _flags_copy(self) -> dict:
        """Copy-for-write via the store's public snapshot API; the edge
        lock serialises mutation (snapshot → edit → replace)."""
        doc = self.shop.flags.snapshot()
        doc.setdefault("flags", {})
        return doc

    def _enc_flag(self, name: str, spec: dict) -> bytes:
        enabled = (
            spec.get("state", "ENABLED") == "ENABLED"
            and bool(spec.get("variants", {}).get(spec.get("defaultVariant")))
        )
        out = wire.encode_len(1, name.encode())
        desc = spec.get("description", "")
        if desc:
            out += wire.encode_len(2, desc.encode())
        if enabled:
            out += wire.encode_int(3, 1)
        return out

    def _get_flag(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        name = _dec_str(f, 1)
        spec = self.shop.flags.flag_spec(name)  # read-only live view
        if spec is None:
            raise ValueError(f"no such flag {name!r}")
        return wire.encode_len(1, self._enc_flag(name, spec))

    def _create_flag(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        name = _dec_str(f, 1)
        enabled = bool(wire.first(f, 3, 0) or 0)
        doc = self._flags_copy()
        doc["flags"][name] = {
            "state": "ENABLED",
            "description": _dec_str(f, 2),
            "variants": {"on": True, "off": False},
            "defaultVariant": "on" if enabled else "off",
        }
        self.shop.flags.replace(doc)
        return wire.encode_len(
            1, self._enc_flag(name, doc["flags"][name])
        )

    def _update_flag(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        name = _dec_str(f, 1)
        enabled = bool(wire.first(f, 2, 0) or 0)
        doc = self._flags_copy()
        spec = doc["flags"].get(name)
        if spec is None:
            raise ValueError(f"no such flag {name!r}")
        if not enabled:
            # Prefer flipping to a falsy variant (the flag then
            # evaluates False for every caller); a variants map with no
            # falsy member (percentage flags) disables via state, and a
            # DISABLED flag evaluates to the caller's default.
            variants = spec.get("variants", {})
            off = next((k for k, v in variants.items() if not v), None)
            if off is not None:
                spec["state"] = "ENABLED"
                spec["defaultVariant"] = off
            else:
                spec["state"] = "DISABLED"
        else:
            spec["state"] = "ENABLED"
            variants = dict(spec.get("variants", {}))
            if not variants.get(spec.get("defaultVariant")):
                on = next(
                    (k for k, v in variants.items() if v), None
                )
                if on is None:
                    variants["on"] = True
                    spec["variants"] = variants
                    on = "on"
                spec["defaultVariant"] = on
        self.shop.flags.replace(doc)
        return b""

    def _list_flags(self, ctx, request: bytes) -> bytes:
        flags = self.shop.flags.flag_specs()  # read-only live view
        return b"".join(
            wire.encode_len(1, self._enc_flag(name, spec))
            for name, spec in sorted(flags.items())
        )

    def _delete_flag(self, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        name = _dec_str(f, 1)
        doc = self._flags_copy()
        doc["flags"].pop(name, None)
        self.shop.flags.replace(doc)
        return b""

    # -- flagd.evaluation.v1 (the :8013 protocol, schemas.flagd.dev) ----
    #
    # Request: {flag_key=1, context=2 Struct}; response: {value=1 typed,
    # reason=2, variant=3}. targetingKey comes from the evaluation
    # context Struct (falling back to session.id baggage, the key the
    # demo's fractional flags bucket on). Unknown/disabled flags raise
    # KeyError → NOT_FOUND (flagd's FLAG_NOT_FOUND); a value of the
    # wrong type raises ValueError → INVALID_ARGUMENT (TYPE_MISMATCH).

    def _resolve_typed(self, want: type, ctx, request: bytes) -> bytes:
        f = wire.scan_fields(request)
        key = _dec_str(f, 1)
        raw_ctx = wire.first(f, 2, b"")
        ectx = structpb.decode_struct(raw_ctx) if isinstance(raw_ctx, bytes) else {}
        targeting = str(
            ectx.get("targetingKey") or ctx.baggage.get("session.id", "")
        )
        value, variant, reason = self.shop.flags.resolve(key, targeting)
        out = self._enc_resolved_value(want, key, value)
        out += wire.encode_len(2, reason.encode())
        out += wire.encode_len(3, variant.encode())
        return out

    @staticmethod
    def _enc_resolved_value(want: type, key: str, value) -> bytes:
        def mismatch():
            return ValueError(
                f"flag {key!r}: variant value {value!r} is not "
                f"{want.__name__} (TYPE_MISMATCH)"
            )

        if want is bool:
            if not isinstance(value, bool):
                raise mismatch()
            return wire.encode_int(1, 1) if value else b""  # proto3 default
        if want is str:
            if not isinstance(value, str):
                raise mismatch()
            return wire.encode_len(1, value.encode())
        if want is int:
            if isinstance(value, bool) or not isinstance(value, int):
                raise mismatch()
            return wire.encode_int(1, value) if value else b""
        if want is float:
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                raise mismatch()
            v = float(value)
            # Plain (non-oneof) field: +0.0 is the proto3 default and
            # is omitted; -0.0 has nonzero bits and must be emitted.
            if _struct.pack("<d", v) == bytes(8):
                return b""
            return wire.encode_double(1, v)
        # object: Struct value
        if not isinstance(value, dict):
            raise mismatch()
        return wire.encode_len(1, structpb.encode_struct(value))

    def _resolve_all(self, ctx, request: bytes) -> bytes:
        """ResolveAll: every enabled flag as an AnyFlag{reason=1,
        variant=2, bool=3|string=4|double=5|object=6} map entry (the
        flagd schema has no int lane — numbers ride the double, exactly
        like flagd itself)."""
        f = wire.scan_fields(request)
        raw_ctx = wire.first(f, 1, b"")
        ectx = structpb.decode_struct(raw_ctx) if isinstance(raw_ctx, bytes) else {}
        targeting = str(
            ectx.get("targetingKey") or ctx.baggage.get("session.id", "")
        )
        out = b""
        for key in sorted(self.shop.flags.flag_keys()):
            try:
                value, variant, reason = self.shop.flags.resolve(key, targeting)
            except KeyError:
                continue  # DISABLED flags are omitted, like flagd
            af = wire.encode_len(1, reason.encode())
            af += wire.encode_len(2, variant.encode())
            # The value lanes live in a proto3 ONEOF: presence is
            # tracked, so the chosen lane is serialized even at its
            # default (False/0.0/"") — an off-state flag must not
            # vanish from WhichOneof("value").
            if isinstance(value, bool):
                af += wire.encode_int(3, 1 if value else 0)
            elif isinstance(value, str):
                af += wire.encode_len(4, value.encode())
            elif isinstance(value, (int, float)):
                af += wire.encode_double(5, float(value))
            elif isinstance(value, dict):
                af += wire.encode_len(6, structpb.encode_struct(value))
            else:
                continue  # unmappable variant value: skip the flag
            entry = wire.encode_len(1, key.encode()) + wire.encode_len(2, af)
            out += wire.encode_len(1, entry)
        return out

    def _event_stream_rpc(self, request: bytes, context):
        """flagd EventStream: provider_ready immediately, then a
        configuration_change event per flag-store version bump (the
        push channel OpenFeature providers re-evaluate on). Slot-
        bounded like health Watch — an over-budget watcher gets
        provider_ready and the stream ends (providers reconnect)."""
        del request
        yield self._enc_event("provider_ready", {})
        if not self._event_watchers.acquire(blocking=False):
            return
        try:
            last = self.shop.flags.poll_version()
            while context.is_active() and not self._stop_event.wait(0.2):
                version = self.shop.flags.poll_version()
                if version != last:
                    last = version
                    yield self._enc_event("configuration_change", {})
        finally:
            self._event_watchers.release()

    @staticmethod
    def _enc_event(event_type: str, data: dict) -> bytes:
        out = wire.encode_len(1, event_type.encode())
        if data:
            out += wire.encode_len(2, structpb.encode_struct(data))
        return out
