"""Shop: the whole Astronomy Shop wired in one process.

The docker-compose analogue (/root/reference/docker-compose.yml wires 20+
containers; SURVEY.md §1): builds every service with shared telemetry,
flags, and the orders bus; attaches the two reference consumers; drives
the Locust-profile load generator on a virtual clock; and streams every
emitted span into the anomaly-detector pipeline. One object, fully
deterministic under a seed — the "run the real system, assert on traces"
test philosophy (SURVEY.md §4) without a container runtime.

Flag control works live mid-run exactly like flipping flags in flagd-ui:
``shop.set_flag("paymentFailure", 0.5)`` changes behaviour of the next
simulated request.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

from .ad import AdService
from .base import ServiceEnv
from .bus import Bus
from .cart import CartService
from .catalog import ProductCatalog
from .checkout import CheckoutService
from .consumers import AccountingService, FraudDetectionService
from .currency import CurrencyService
from .email import EmailService
from .frontend import Frontend
from .loadgen import LoadGenerator
from .payment import PaymentService
from .recommendation import RecommendationService
from .shipping import QuoteService, ShippingService
from ..runtime.tensorize import SpanRecord
from ..telemetry.collector import Collector
from ..telemetry.metrics import MetricRegistry
from ..telemetry.tracer import Tracer
from ..utils.flags import FlagEvaluator


@dataclass
class ShopConfig:
    users: int = 5
    seed: int = 0
    pump_interval_s: float = 0.25  # how often spans flush downstream
    # Network broker address ("host:port"). Unset = in-proc Bus; set =
    # orders cross a real TCP broker exactly like the reference's full
    # compose (checkout → Produce v3 with trace headers → accounting /
    # fraud-detection consumer groups polling over the socket).
    kafka_bootstrap: str | None = None
    # Minimal profile (/root/reference/docker-compose.minimal.yml:16):
    # drops accounting, fraud-detection and the async tier entirely —
    # checkout skips the Kafka publish the way the reference's
    # `if cs.kafkaBrokerSvcAddr != ""` does (main.go:324-327). The
    # flagd tier stays (the reference minimal keeps flagd, dropping
    # only flagd-UI — the serving layer handles that).
    minimal: bool = False


class Shop:
    def __init__(self, config: ShopConfig | None = None):
        self.config = config or ShopConfig()
        self._t = 0.0
        self._span_buffer: list[SpanRecord] = []
        # memory_limiter backoff (telemetry.collector.SpanAdmission):
        # while the collector refuses spans, the buffer holds and
        # re-sends after the hint instead of hammering a full pipeline.
        self._export_resume_at = 0.0
        self.spans_dropped_backpressure = 0
        self.flags = FlagEvaluator({"flags": {}})
        self.metrics = MetricRegistry()
        self.tracer = Tracer(self._span_buffer.append)
        # The telemetry backend tier (SURVEY.md §3.2): every flushed
        # span batch also enters the collector, which fans out to the
        # Jaeger/Prometheus/OpenSearch-analogue stores and to any
        # subscribed exporters (the anomaly-detector seam).
        self.collector = Collector(clock=lambda: self._t)
        # docker_stats analogue (otelcol-config.yml:18-19): this
        # process's container_* self stats ride the shop registry, so
        # they reach BOTH the TSDB (collector scrape) and the /metrics
        # exposition the compose Prometheus scrapes.
        from ..telemetry.receivers import ProcessStatsReceiver

        proc_stats = ProcessStatsReceiver("shop", registry=self.metrics)
        self.collector.add_scrape_target(
            "shop", self.metrics, before=proc_stats.scrape
        )
        self.collector.attach_hostmetrics()
        # Receiver family parity (otelcol-config.yml:15-23): cart-store
        # stats (redis receiver analogue) + httpcheck wired after the
        # services exist (see below).
        # One sim rng behind a mutex: the gRPC edge runs read RPCs
        # concurrently, and every service draw (latency jitter, ad
        # choice) is a read-modify-write of generator state. Draw ORDER
        # is unchanged single-threaded, so seeded runs stay exact.
        from ..utils.concurrency import LockedRng

        rng = LockedRng(np.random.default_rng(self.config.seed))
        env = ServiceEnv(
            tracer=self.tracer,
            flags=self.flags,
            rng=rng,
            clock=lambda: self._t,
            metrics=self.metrics,
            logger=self.collector.receive_log,
        )
        self.env = env

        if self.config.minimal:
            if self.config.kafka_bootstrap:
                raise ValueError(
                    "minimal profile drops the async tier; kafka_bootstrap "
                    "and minimal are mutually exclusive"
                )
            self.bus = None
        elif self.config.kafka_bootstrap:
            from .kafka_bus import KafkaBus

            self.bus = KafkaBus(self.config.kafka_bootstrap)
        else:
            self.bus = Bus()
        self.catalog = ProductCatalog(env)
        self.currency = CurrencyService(env)
        self.cart = CartService(env)
        self.payment = PaymentService(env)
        self.quote = QuoteService(env)
        self.shipping = ShippingService(env, self.quote)
        self.email = EmailService(env)
        self.recommendation = RecommendationService(env, self.catalog)
        self.ad = AdService(env)
        self.checkout = CheckoutService(
            env, self.cart, self.catalog, self.currency, self.payment,
            self.shipping, self.email, self.bus,
        )
        self.frontend = Frontend(
            env, self.catalog, self.cart, self.checkout, self.currency,
            self.recommendation, self.ad, self.shipping,
        )
        if self.bus is not None:
            self.accounting = AccountingService(env, self.bus)
            self.fraud = FraudDetectionService(env, self.bus)
        else:  # minimal: no consumers to attach (and nothing publishes)
            self.accounting = None
            self.fraud = None
        self.loadgen = LoadGenerator(self.frontend, rng, users=self.config.users)

        # Pull receivers on the scrape cadence (SURVEY.md §5 Profiling):
        # cart-store stats = the redis receiver; an in-proc httpcheck
        # probe = the frontend-proxy health check.
        from ..telemetry.receivers import HttpCheckReceiver, StoreStatsReceiver

        store_stats = StoreStatsReceiver(self.cart.store)
        self.collector.add_scrape_target(
            "valkey-cart", store_stats.registry, before=store_stats.scrape
        )
        httpcheck = HttpCheckReceiver()

        def probe_frontend() -> int:
            # Liveness probe with NO telemetry/rng side effects (probe
            # spans would pollute the deterministic traffic stream the
            # detector tests rely on): verify the catalog's data path
            # serves — goes red if the product table is gone/corrupt,
            # like the reference's /health probes (liveness, not deep
            # app-fault health).
            try:
                self.catalog.price_of(self.catalog.list_ids()[0])
                return 200
            except Exception:
                return 500

        httpcheck.add_target("frontend-proxy", probe_frontend)
        self.collector.add_scrape_target(
            "httpcheck", httpcheck.registry, before=httpcheck.scrape
        )

    # -- flag control (flagd-ui analogue) ------------------------------

    def set_flag(self, key: str, value, variants: dict | None = None) -> None:
        doc = self.flags.snapshot()
        variants = variants or {"on": value}
        doc.setdefault("flags", {})[key] = {
            "state": "ENABLED",
            "variants": variants,
            "defaultVariant": next(iter(variants)),
        }
        self.flags.replace(doc)

    def clear_flag(self, key: str) -> None:
        doc = self.flags.snapshot()
        doc.get("flags", {}).pop(key, None)
        self.flags.replace(doc)

    # -- simulation loop ----------------------------------------------

    @property
    def now(self) -> float:
        return self._t

    def pump(self, t_now: float, on_spans=None) -> None:
        """Advance the clock to ``t_now`` without load generation.

        The gateway's mode of driving the shop: external (HTTP) callers
        make the requests; this just moves virtual time forward, lets
        the bus deliver to consumers, and flushes accumulated spans.
        """
        if t_now > self._t:
            self._t = t_now
        if self.bus is not None:
            self.bus.pump()
        if self._span_buffer and self._t >= self._export_resume_at:
            # Copy-and-clear, never rebind: the tracer holds a reference
            # to this exact list's append method.
            spans = list(self._span_buffer)
            self._span_buffer.clear()
            adm = self.collector.receive_spans(spans)
            if adm.refused:
                # The in-proc SDK honors the memory_limiter's retryable
                # refusal: the refused TAIL (refusal is suffix-aligned,
                # see SpanAdmission) goes back to the buffer and export
                # holds for the hint — no re-sending into a full
                # collector. The held backlog stays bounded by the same
                # budget: beyond it, oldest held spans are dropped and
                # counted (the SDK-side sending_queue discipline).
                kept = spans[len(spans) - adm.refused:]
                self._span_buffer[:0] = kept
                overflow = (
                    len(self._span_buffer)
                    - self.collector.config.memory_limit_spans
                )
                if overflow > 0:
                    del self._span_buffer[:overflow]
                    self.spans_dropped_backpressure += overflow
                self._export_resume_at = self._t + (
                    adm.retry_after_s
                    or self.collector.config.batch_timeout_s
                )
                spans = spans[: len(spans) - adm.refused]
            if on_spans is not None and spans:
                # Downstream subscribers see the ADMITTED spans only —
                # the refused tail will reach them on its retry.
                on_spans(self._t, spans)
        self.collector.pump(self._t)

    def run(
        self,
        seconds: float,
        on_spans: Callable[[float, list[SpanRecord]], None] | None = None,
    ) -> None:
        """Advance the shop ``seconds`` of virtual time.

        Every ``pump_interval_s`` the bus delivers to consumers and the
        accumulated spans flush to ``on_spans`` (typically
        ``pipeline.submit`` + ``pipeline.pump``).
        """
        end = self._t + seconds
        step = self.config.pump_interval_s
        while self._t < end:
            self._t = min(self._t + step, end)
            self.loadgen.run_until(self._t)
            self.pump(self._t, on_spans)
