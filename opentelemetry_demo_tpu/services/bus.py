"""In-proc event bus: the Kafka ``orders`` topic analogue.

A single-partition append-only log with independent consumer-group
cursors — the exact consumption model the reference demonstrates with
``accounting`` and ``fraud-detection`` as two groups on one topic
(/root/reference/src/accounting/Consumer.cs:77,
/root/reference/src/fraud-detection/.../main.kt:27). Offsets are
first-class (``group_offset``) so checkpointing can key sketch snapshots
to them just like real Kafka offsets. Values are wire-compatible
OrderResult bytes (``runtime.kafka_orders``) with trace headers attached
the way the reference injects context into Kafka headers
(/root/reference/src/checkout/main.go:631-637).
"""

from __future__ import annotations

from typing import Callable, NamedTuple


class BusMessage(NamedTuple):
    offset: int
    key: bytes
    value: bytes
    headers: dict[str, str]


class Topic:
    def __init__(self, name: str):
        self.name = name
        self._log: list[BusMessage] = []
        self._cursors: dict[str, int] = {}

    def produce(self, key: bytes, value: bytes, headers: dict[str, str] | None = None) -> int:
        offset = len(self._log)
        self._log.append(BusMessage(offset, key, value, dict(headers or {})))
        return offset

    def poll(self, group: str, max_messages: int = 64) -> list[BusMessage]:
        cursor = self._cursors.get(group, 0)
        out = self._log[cursor : cursor + max_messages]
        self._cursors[group] = cursor + len(out)
        return out

    def group_offset(self, group: str) -> int:
        return self._cursors.get(group, 0)

    def seek(self, group: str, offset: int) -> None:
        self._cursors[group] = max(0, min(offset, len(self._log)))

    @property
    def end_offset(self) -> int:
        return len(self._log)

    def lag(self, group: str) -> int:
        return self.end_offset - self.group_offset(group)


class Bus:
    """Topic registry + pump for subscribed consumers."""

    def __init__(self):
        self._topics: dict[str, Topic] = {}
        self._consumers: list[tuple[str, str, Callable[[BusMessage], None]]] = []

    def topic(self, name: str) -> Topic:
        if name not in self._topics:
            self._topics[name] = Topic(name)
        return self._topics[name]

    def subscribe(self, topic: str, group: str, handler: Callable[[BusMessage], None]) -> None:
        self.topic(topic)
        self._consumers.append((topic, group, handler))

    def pump(self, max_messages: int = 64) -> int:
        """Deliver pending messages to all consumer groups; returns count."""
        delivered = 0
        for topic, group, handler in self._consumers:
            for msg in self._topics[topic].poll(group, max_messages):
                handler(msg)
                delivered += 1
        return delivered
