"""Currency conversion: EUR-based rate table with units/nanos carry math.

Mirrors the reference's C++ currency service behaviour
(/root/reference/src/currency/src/server.cpp:48-84 hardcoded EUR-based
rates; conversion via double arithmetic with carry): supported-currency
listing and ``convert``. The rate values here are this framework's own
plausible table, not the reference's numbers. The conversion math is
exact integer carry on (units, nanos) — the part worth being careful
about, per the Money proto contract (demo.proto:146-160).

This is also the Python facade over the framework's **native C++
currency kernel** (native/currency.cc via runtime.native) — conversion
is the shop hot path the reference keeps native, so ours does too; the
pure-Python fallback keeps the capability dependency-free.
"""

from __future__ import annotations

from .base import ServiceBase, ServiceError
from .money import NANOS_PER_UNIT, Money, MoneyError
from ..currency_data import EUR_RATES  # noqa: F401 — canonical location
from ..runtime import native
from ..telemetry.tracer import TraceContext


class CurrencyService(ServiceBase):
    name = "currency"
    base_latency_us = 200.0

    def supported_currencies(self, ctx: TraceContext) -> list[str]:
        self.span("GetSupportedCurrencies", ctx)
        return sorted(EUR_RATES)

    def convert(self, ctx: TraceContext, money: Money, to_code: str) -> Money:
        # Validate before emitting: one span per request, its error bit
        # reflecting the outcome — a success span followed by an error
        # span would dilute the error rate the detector measures.
        invalid: MoneyError | None = None
        try:
            money.validate()
        except MoneyError as e:
            invalid = e
        unsupported = money.currency not in EUR_RATES or to_code not in EUR_RATES
        self.span("Convert", ctx, error=unsupported or invalid is not None)
        if invalid is not None:
            raise invalid
        if unsupported:
            raise ServiceError(
                self.name, f"unsupported currency {money.currency}->{to_code}"
            )
        if money.currency == to_code:
            return money
        # to-EUR then EUR-to-target, carrying nanos exactly. The native
        # C++ kernel does the arithmetic when built (same validation,
        # same double product, same ties-to-even rounding — pinned by
        # tests/test_native_currency.py); -3 (int64 overflow) falls back
        # to Python's arbitrary-precision path.
        rate = EUR_RATES[to_code] / EUR_RATES[money.currency]
        if native.currency_available():
            code, units, nanos = native.money_convert(
                rate, money.units, money.nanos
            )
            if code == 0:
                return Money(to_code, units, nanos)
            if code == -2:  # unreachable: validate() ran above
                raise MoneyError("invalid money")
        total_nanos = money.units * NANOS_PER_UNIT + money.nanos
        converted = int(round(total_nanos * rate))
        units, nanos = divmod(abs(converted), NANOS_PER_UNIT)
        sign = -1 if converted < 0 else 1
        return Money(to_code, sign * units, sign * nanos)
