"""Email service: order-confirmation rendering (no real delivery).

Mirrors the reference Ruby service
(/root/reference/src/email/email_server.rb:18-53): one endpoint that
renders a confirmation and "sends" it to a test sink, with a manual
send_email child span.
"""

from __future__ import annotations

from .base import ServiceBase
from ..telemetry.tracer import TraceContext


class EmailService(ServiceBase):
    name = "email"
    base_latency_us = 1200.0

    def __init__(self, env):
        super().__init__(env)
        self.sent: int = 0

    def send_order_confirmation(
        self, ctx: TraceContext, email: str, order_id: str
    ) -> str:
        body = (
            f"To: {email}\nSubject: Your order {order_id}\n\n"
            "Clear skies! Your astronomy gear is on its way."
        )
        self.sent += 1
        self.span("send_order_confirmation", ctx)
        self.span("send_email", ctx, scale=0.5, attr=order_id)
        return body
