"""Email service: order-confirmation rendering (no real delivery).

Mirrors the reference Ruby service
(/root/reference/src/email/email_server.rb:18-53): one endpoint that
renders a confirmation and "sends" it to a test sink, with a manual
send_email child span. A delivery failure records the exception on the
current span — the Sinatra ``error do ... record_exception`` handler at
email_server.rb:31-33 — so the trace carries the CAUSE, not just an
error status.
"""

from __future__ import annotations

from .base import ServiceBase, ServiceError
from ..telemetry.tracer import TraceContext, exception_event


class InvalidRecipientError(ValueError):
    """The mail library's reject (Pony raises on a bad address)."""


class EmailService(ServiceBase):
    name = "email"
    base_latency_us = 1200.0

    def __init__(self, env):
        super().__init__(env)
        self.sent: int = 0

    def send_order_confirmation(
        self, ctx: TraceContext, email: str, order_id: str
    ) -> str:
        try:
            body = self._send(email, order_id)
        except InvalidRecipientError as exc:
            # record_exception analogue: error span + exception event
            # (email_server.rb:31-33), then propagate as the service
            # failure checkout observes.
            self.span(
                "send_order_confirmation", ctx, error=True,
                events=(exception_event(exc),),
            )
            raise ServiceError(self.name, str(exc)) from exc
        self.span("send_order_confirmation", ctx)
        self.span("send_email", ctx, scale=0.5, attr=order_id)
        return body

    def _send(self, email: str, order_id: str) -> str:
        if "@" not in email:
            raise InvalidRecipientError(f"invalid recipient {email!r}")
        self.sent += 1
        return (
            f"To: {email}\nSubject: Your order {order_id}\n\n"
            "Clear skies! Your astronomy gear is on its way."
        )
