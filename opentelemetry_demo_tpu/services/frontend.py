"""Frontend: the API gateway tier in front of the business services.

Mirrors the reference Next.js frontend's API routes
(/root/reference/src/frontend/pages/api/{products,cart,checkout,
recommendations,data}.ts and the gRPC gateways in gateways/rpc/*): each
route fans out to the owning service, wraps the request in a span, and
counts ``app.frontend.requests`` the way InstrumentationMiddleware does
(/root/reference/src/frontend/utils/telemetry/InstrumentationMiddleware.ts:10,30).
The ``imageSlowLoad`` Envoy fault-filter flag
(/root/reference/src/frontend-proxy/envoy.tmpl.yaml:57-64) is modelled on
the image route, the hop where the reference injects the delay.
"""

from __future__ import annotations

from .ad import AdService
from .base import ServiceBase, ServiceError
from .cart import CartService
from .catalog import ProductCatalog
from .checkout import CheckoutService, PlacedOrder
from .currency import CurrencyService
from .recommendation import RecommendationService
from .money import Money
from .shipping import ShippingService
from ..telemetry.tracer import TraceContext

FLAG_IMAGE_SLOW_LOAD = "imageSlowLoad"


class Frontend(ServiceBase):
    name = "frontend"
    base_latency_us = 1500.0

    def __init__(
        self,
        env,
        catalog: ProductCatalog,
        cart: CartService,
        checkout: CheckoutService,
        currency: CurrencyService,
        recommendation: RecommendationService,
        ad: AdService,
        shipping: ShippingService | None = None,
    ):
        super().__init__(env)
        self.catalog = catalog
        self.cart = cart
        self.checkout = checkout
        self.currency = currency
        self.recommendation = recommendation
        self.ad = ad
        self.shipping = shipping

    def _count(self):
        if self.env.metrics is not None:
            self.env.metrics.counter_add("app_frontend_requests_total", 1.0)

    # -- API routes (pages/api/*) --------------------------------------

    def api_products(self, ctx: TraceContext) -> list[dict]:
        self._count()
        products = self.catalog.list_products(ctx)
        self.span("GET /api/products", ctx)
        return products

    def api_product(self, ctx: TraceContext, product_id: str) -> dict:
        self._count()
        try:
            product = self.catalog.get_product(ctx, product_id)
        except ServiceError:
            self.span("GET /api/products/[id]", ctx, error=True, attr=product_id)
            raise
        self.span("GET /api/products/[id]", ctx, attr=product_id)
        return product

    def api_image(self, ctx: TraceContext, product_id: str) -> None:
        """Static product image via the proxy tier (image-provider)."""
        self._count()
        extra_us = 0.0
        if bool(self.flag(FLAG_IMAGE_SLOW_LOAD, False, ctx)):
            extra_us = float(self.env.rng.uniform(3_000_000.0, 5_000_000.0))
        self.env.tracer.emit(
            "image-provider", "GET /images", ctx,
            self._latency(0.2) + extra_us, attr=product_id,
        )

    def api_currency(self, ctx: TraceContext) -> list[str]:
        self._count()
        codes = self.currency.supported_currencies(ctx)
        self.span("GET /api/currency", ctx)
        return codes

    def api_cart_add(self, ctx: TraceContext, user_id: str, product_id: str, qty: int) -> None:
        self._count()
        try:
            self.cart.add_item(ctx, user_id, product_id, qty)
        except ServiceError:
            self.span("POST /api/cart", ctx, error=True)
            raise
        self.span("POST /api/cart", ctx)

    def api_cart_empty(self, ctx: TraceContext, user_id: str) -> None:
        self._count()
        self.cart.empty_cart(ctx, user_id)
        self.span("DELETE /api/cart", ctx)

    def api_cart_get(self, ctx: TraceContext, user_id: str) -> dict[str, int]:
        self._count()
        items = self.cart.get_cart(ctx, user_id)
        self.span("GET /api/cart", ctx)
        return items

    def api_recommendations(self, ctx: TraceContext, exclude: list[str]) -> list[str]:
        self._count()
        recs = self.recommendation.list_recommendations(ctx, exclude)
        self.span("GET /api/recommendations", ctx)
        return recs

    def api_ads(self, ctx: TraceContext, context_keys: list[str]) -> list[str]:
        self._count()
        try:
            ads = self.ad.get_ads(ctx, context_keys)
        except ServiceError:
            self.span("GET /api/data", ctx, error=True)
            raise
        self.span("GET /api/data", ctx)
        return ads

    def api_shipping(
        self, ctx: TraceContext, item_count: int, currency_code: str = "USD"
    ) -> Money:
        """Shipping quote via the HTTP gateway leg (pages/api/shipping.ts:
        frontend → shipping /get-quote → quote, then currency convert)."""
        self._count()
        if self.shipping is None:
            raise ServiceError(self.name, "shipping gateway not wired")
        try:
            cost = self.shipping.get_quote(ctx, item_count)
            if currency_code and currency_code != cost.currency:
                cost = self.currency.convert(ctx, cost, currency_code)
        except ServiceError:
            self.span("GET /api/shipping", ctx, error=True)
            raise
        self.span("GET /api/shipping", ctx)
        return cost

    def api_checkout(self, ctx: TraceContext, user_id: str, currency: str, email: str) -> PlacedOrder:
        self._count()
        try:
            order = self.checkout.place_order(ctx, user_id, currency, email)
        except ServiceError:
            self.span("POST /api/checkout", ctx, scale=2.0, error=True)
            raise
        self.span("POST /api/checkout", ctx)
        return order

    def index(self, ctx: TraceContext) -> None:
        """SSR home page: products + ads + currency fan-out."""
        self._count()
        self.catalog.list_products(ctx)
        self.currency.supported_currencies(ctx)
        self.span("GET /", ctx)
