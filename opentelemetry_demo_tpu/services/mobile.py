"""Mobile shop client: the react-native-app analogue.

The reference ships a React Native storefront (~5,600 LoC,
/root/reference/src/react-native-app/): tab screens
``app/(tabs)/{index,cart}.tsx``, an API gateway
(``gateways/Api.gateway.ts``) that calls the frontend's ``/api/*``
routes, a session gateway (``gateways/Session.gateway.ts``) minting a
per-install session id, and OTel JS client telemetry with a
``SessionIdProcessor`` stamping every span
(``utils/SessionIdProcessor.ts``). It is built beside the stack
(Makefile:284-285), not inside compose.

This module keeps that capability: a session-scoped client whose
"screens" issue the same API sequence the RN screens do, emitting
client-side spans (service ``react-native-app``) with the session id on
baggage — a second client class beside the load generator, usable
against the in-proc :class:`~.frontend.Frontend` or a live HTTP
gateway.
"""

from __future__ import annotations

import json
import urllib.request
import uuid
from dataclasses import dataclass

from .checkout import placed_order_json
from .frontend import Frontend
from ..telemetry.tracer import TraceContext, Tracer


@dataclass
class CheckoutForm:
    """The RN CheckoutForm's IFormData shape
    (src/react-native-app/components/CheckoutForm/CheckoutForm.tsx,
    consumed by cart.tsx onPlaceOrder): email + shipping address +
    credit card, defaults matching the form's prefilled demo values.
    The RN app hard-codes the currency to USD (cart.tsx comment)."""

    email: str = "someone@example.com"
    street_address: str = "1600 Amphitheatre Parkway"
    city: str = "Mountain View"
    state: str = "CA"
    country: str = "United States"
    zip_code: str = "94043"
    credit_card_number: str = "4432-8015-6152-0454"
    credit_card_cvv: str = "672"
    credit_card_expiration_month: int = 1
    credit_card_expiration_year: int = 2030
    currency: str = "USD"


class MobileSession:
    """Session gateway analogue: one id per app install/launch."""

    def __init__(self, session_id: str | None = None):
        self.session_id = session_id or str(uuid.uuid4())

    def new_context(self) -> TraceContext:
        """Every screen interaction starts a trace carrying the session
        id + synthetic marker on baggage (SessionIdProcessor behavior:
        the id rides every span/export)."""
        return TraceContext.new({
            "session.id": self.session_id,
            "synthetic_request": "true",
        })


class InProcTransport:
    """Api.gateway analogue over the in-proc frontend (test/sim path)."""

    def __init__(self, frontend: Frontend):
        self.frontend = frontend

    def products(self, ctx):
        return self.frontend.api_products(ctx)

    def product(self, ctx, product_id):
        return self.frontend.api_product(ctx, product_id)

    def recommendations(self, ctx, exclude):
        return self.frontend.api_recommendations(ctx, exclude)

    def cart_add(self, ctx, user_id, product_id, qty):
        self.frontend.api_cart_add(ctx, user_id, product_id, qty)

    def cart_get(self, ctx, user_id):
        items = self.frontend.api_cart_get(ctx, user_id)
        # Same wire shape the gateway's /api/cart returns.
        return [{"productId": p, "quantity": q} for p, q in items.items()]

    def cart_empty(self, ctx, user_id):
        self.frontend.api_cart_empty(ctx, user_id)

    def checkout(self, ctx, user_id, currency, email):
        order = self.frontend.api_checkout(ctx, user_id, currency, email)
        # One serializer with the gateway's /api/checkout route, so the
        # two transports cannot desynchronize.
        return placed_order_json(order)


class HttpTransport:
    """Api.gateway analogue over a live gateway (the RN app's real mode:
    fetch against the frontend's /api routes through the edge proxy)."""

    def __init__(self, base_url: str, timeout_s: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s

    def _call(self, ctx, method, path, body=None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            method=method,
            headers={**ctx.to_headers(), "Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read() or b"null")

    def products(self, ctx):
        return self._call(ctx, "GET", "/api/products")["products"]

    def product(self, ctx, product_id):
        return self._call(ctx, "GET", f"/api/products/{product_id}")

    def recommendations(self, ctx, exclude):
        q = ",".join(exclude)
        return self._call(ctx, "GET", f"/api/recommendations?productIds={q}")["productIds"]

    def cart_add(self, ctx, user_id, product_id, qty):
        self._call(ctx, "POST", "/api/cart", {
            "userId": user_id, "item": {"productId": product_id, "quantity": qty},
        })

    def cart_get(self, ctx, user_id):
        return self._call(ctx, "GET", f"/api/cart?sessionId={user_id}")["items"]

    def cart_empty(self, ctx, user_id):
        self._call(ctx, "DELETE", f"/api/cart?sessionId={user_id}")

    def checkout(self, ctx, user_id, currency, email):
        return self._call(ctx, "POST", "/api/checkout", {
            "userId": user_id, "currencyCode": currency, "email": email,
        })


class MobileApp:
    """The RN app's screens as driveable flows.

    Client-side telemetry: each screen method emits one span under
    service ``react-native-app`` (the WebTracerProvider analogue —
    browser/app spans reaching the collector through the edge's
    /otlp-http route in the reference, FrontendTracer.ts:22-71).
    """

    SERVICE = "react-native-app"

    def __init__(
        self,
        transport,
        tracer: Tracer | None = None,
        session: MobileSession | None = None,
        email: str = "mobile.user@example.com",
    ):
        self.transport = transport
        self.tracer = tracer
        self.session = session or MobileSession()
        self.email = email
        self.orders: list[dict] = []

    # -- client span helper -------------------------------------------

    def _span(self, name: str, ctx: TraceContext, error: bool = False) -> None:
        if self.tracer is not None:
            # Client-side latency is negligible in sim; 100µs nominal.
            self.tracer.emit(self.SERVICE, name, ctx, 100.0, is_error=error)

    # -- screens ------------------------------------------------------

    def _screen(self, name: str, ctx: TraceContext, thunk):
        """Run one screen interaction; the client span records success
        or failure either way (error spans must be visible in the trace
        store for every screen, not just list/checkout)."""
        try:
            result = thunk()
        except Exception:
            self._span(name, ctx, error=True)
            raise
        self._span(name, ctx)
        return result

    def product_list_screen(self) -> list[dict]:
        """Tab ``index``: ProductList fetches all products."""
        ctx = self.session.new_context()
        return self._screen(
            "GET /api/products", ctx, lambda: self.transport.products(ctx)
        )

    def product_detail_screen(self, product_id: str) -> dict:
        """ProductCard tap: detail + recommendations."""
        ctx = self.session.new_context()

        def go():
            detail = self.transport.product(ctx, product_id)
            self.transport.recommendations(ctx, [product_id])
            return detail

        return self._screen("GET /api/products/{id}", ctx, go)

    def add_to_cart(self, product_id: str, qty: int = 1) -> None:
        ctx = self.session.new_context()
        self._screen(
            "POST /api/cart", ctx,
            lambda: self.transport.cart_add(
                ctx, self.session.session_id, product_id, qty
            ),
        )

    def cart_screen(self) -> dict:
        """Tab ``cart`` RENDERED (cart.tsx): the items resolved to full
        product rows (each row is a ProductCard over the cart item),
        the tab badge (total quantity), per-line and cart totals, and
        the EmptyCart state when nothing is held."""
        ctx = self.session.new_context()

        def go():
            items = self.transport.cart_get(ctx, self.session.session_id)
            rows = []
            for item in items:
                product = self.transport.product(ctx, item["productId"])
                price = float(product.get("priceUsd", 0.0))
                rows.append({
                    "productId": item["productId"],
                    "name": product.get("name"),
                    "priceUsd": price,
                    "quantity": item["quantity"],
                    "lineTotalUsd": round(price * item["quantity"], 2),
                })
            return {
                "empty": not rows,  # EmptyCart component state
                "badge": sum(r["quantity"] for r in rows),
                "rows": rows,
                "subtotalUsd": round(sum(r["lineTotalUsd"] for r in rows), 2),
            }

        return self._screen("GET /api/cart", ctx, go)

    def empty_cart(self) -> dict:
        """cart.tsx onEmptyCart: DELETE then a success toast."""
        ctx = self.session.new_context()
        self._screen(
            "DELETE /api/cart", ctx,
            lambda: self.transport.cart_empty(ctx, self.session.session_id),
        )
        return {"toast": "Your cart was emptied"}

    def checkout_flow(
        self, currency: str | None = None, form: CheckoutForm | None = None
    ) -> dict:
        """cart.tsx onPlaceOrder: submit the CheckoutForm, render the
        confirmation state (success toast + order fields + redirect
        home), mirroring the RN flow's Toast.show + router.replace."""
        form = form or CheckoutForm(email=self.email)
        ctx = self.session.new_context()
        order = self._screen(
            "POST /api/checkout", ctx,
            lambda: self.transport.checkout(
                ctx, self.session.session_id,
                currency or form.currency, form.email,
            ),
        )
        self.orders.append(order)
        total = order.get("total", {})
        return {
            "toast": "Your order is Complete!",
            "toastDetail": "We've sent you a confirmation email.",
            "orderId": order["orderId"],
            "shippingTrackingId": order["shippingTrackingId"],
            "itemCount": sum(
                line["item"]["quantity"] for line in order.get("items", [])
            ),
            "totalUsd": (
                float(total.get("units", 0)) + total.get("nanos", 0) / 1e9
            ),
            "currencyCode": total.get("currencyCode"),
            "order": order,
            "redirect": "/",  # router.replace("/") after the toast
        }

    # -- a full shopping journey (the RN demo's happy path) -----------

    def shopping_journey(self, rng, n_items: int = 2) -> dict:
        products = self.product_list_screen()
        ids = [p["id"] for p in products]
        for _ in range(n_items):
            pid = ids[int(rng.integers(0, len(ids)))]
            self.product_detail_screen(pid)
            self.add_to_cart(pid, int(rng.integers(1, 4)))
        self.cart_screen()
        confirmation = self.checkout_flow()
        return confirmation["order"]
