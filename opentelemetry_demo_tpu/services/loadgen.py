"""Load generator: the Locust profile as a deterministic simulator.

Mirrors the reference's Locust task mix and user model
(/root/reference/src/load-generator/locustfile.py:107-220): weighted
tasks — browse×10, recommendations×3, ads×3, view-cart×3, add-to-cart×2,
checkout×1, checkout-multi×1, flood-home×5 (gated by the
``loadGeneratorFloodHomepage`` flag), index×1 — users with 1–10 s waits,
session-id + synthetic_request baggage attached at session start
(:175-179). Time is virtual: the generator advances a simulated clock,
so "a minute of 5-user traffic" runs in milliseconds while producing the
same span stream shape.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .base import ServiceError
from .frontend import Frontend
from ..telemetry.tracer import TraceContext

FLAG_FLOOD_HOMEPAGE = "loadGeneratorFloodHomepage"

TASK_WEIGHTS = [
    ("browse_product", 10),
    ("get_recommendations", 3),
    ("get_ads", 3),
    ("view_cart", 3),
    ("add_to_cart", 2),
    ("checkout", 1),
    ("checkout_multi", 1),
    ("flood_home", 5),
    ("index", 1),
]


@dataclass
class VirtualUser:
    session_id: str
    next_at: float
    user_id: str


class LoadGenerator:
    """Drives the frontend with the Locust profile on a virtual clock."""

    def __init__(self, frontend: Frontend, rng: np.random.Generator, users: int = 5):
        self.frontend = frontend
        self.rng = rng
        self.users = [
            VirtualUser(
                session_id=f"session-{i}",
                next_at=float(rng.uniform(0.0, 1.0)),
                user_id=f"user-{i}",
            )
            for i in range(users)
        ]
        names, weights = zip(*TASK_WEIGHTS)
        self._tasks = list(names)
        self._probs = np.asarray(weights, float) / sum(weights)
        self.requests = 0
        self.errors = 0

    def _ctx(self, user: VirtualUser) -> TraceContext:
        return TraceContext.new(
            {"session.id": user.session_id, "synthetic_request": "true"}
        )

    def run_until(self, t_end: float) -> None:
        """Advance all users' schedules up to virtual time ``t_end``."""
        if not self.users:  # users=0: external clients drive the shop
            return
        while True:
            user = min(self.users, key=lambda u: u.next_at)
            if user.next_at >= t_end:
                return
            self._run_task(user)
            # Locust wait_time = between(1, 10) (locustfile.py:108).
            user.next_at += float(self.rng.uniform(1.0, 10.0))

    # -- tasks ---------------------------------------------------------

    def _pick_product(self, ctx) -> str:
        products = self.frontend.catalog.list_products(ctx)
        return products[int(self.rng.integers(0, len(products)))]["id"]

    def _run_task(self, user: VirtualUser) -> None:
        task = self._tasks[int(self.rng.choice(len(self._tasks), p=self._probs))]
        ctx = self._ctx(user)
        self.requests += 1
        try:
            if task == "browse_product":
                pid = self._pick_product(ctx)
                self.frontend.api_product(ctx, pid)
                self.frontend.api_image(ctx, pid)
            elif task == "get_recommendations":
                self.frontend.api_recommendations(ctx, [self._pick_product(ctx)])
            elif task == "get_ads":
                cats = ["telescopes", "accessories"]
                self.frontend.api_ads(ctx, [cats[int(self.rng.integers(0, 2))]])
            elif task == "view_cart":
                self.frontend.api_cart_get(ctx, user.user_id)
            elif task == "add_to_cart":
                pid = self._pick_product(ctx)
                self.frontend.api_product(ctx, pid)
                self.frontend.api_cart_add(ctx, user.user_id, pid, 1)
            elif task == "checkout":
                self._checkout(ctx, user, n_items=1)
            elif task == "checkout_multi":
                self._checkout(ctx, user, n_items=int(self.rng.integers(2, 5)))
            elif task == "flood_home":
                n_flood = int(
                    self.frontend.env.flags.evaluate(
                        FLAG_FLOOD_HOMEPAGE, 0, user.session_id
                    )
                )
                for _ in range(n_flood):
                    self.frontend.index(self._ctx(user))
            elif task == "index":
                self.frontend.index(ctx)
        except ServiceError:
            self.errors += 1

    def _checkout(self, ctx, user: VirtualUser, n_items: int) -> None:
        for _ in range(n_items):
            self.frontend.api_cart_add(ctx, user.user_id, self._pick_product(ctx), 1)
        self.frontend.api_checkout(
            ctx, user.user_id, "USD", f"{user.user_id}@example.com"
        )
