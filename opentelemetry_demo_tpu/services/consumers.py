"""Orders-topic consumers: accounting + fraud detection.

Mirrors the reference's two independent consumer groups on the same
topic (SURVEY.md §2.1): accounting totals order value by product
(/root/reference/src/accounting/Consumer.cs:59-70) and fraud-detection
scores each order (/root/reference/src/fraud-detection/.../main.kt:54-88,
including the ``kafkaQueueProblems`` consumer-side slowdown :60-63).
Both parse the same wire-compatible OrderResult bytes and extract trace
context from message headers — the async-boundary propagation the
reference demonstrates.
"""

from __future__ import annotations

from .base import ServiceBase
from .bus import Bus, BusMessage
from ..runtime.kafka_orders import decode_order
from ..telemetry.tracer import TraceContext

FLAG_KAFKA_PROBLEMS = "kafkaQueueProblems"


class AccountingService(ServiceBase):
    name = "accounting"
    base_latency_us = 300.0
    GROUP = "accounting"

    def __init__(self, env, bus: Bus):
        super().__init__(env)
        self.totals_by_product: dict[str, int] = {}
        self.orders_seen = 0
        bus.subscribe("orders", self.GROUP, self.handle)

    def handle(self, msg: BusMessage) -> None:
        ctx = TraceContext.from_headers(msg.headers)
        order = decode_order(msg.value)
        self.orders_seen += 1
        for pid in order.product_ids:
            self.totals_by_product[pid] = self.totals_by_product.get(pid, 0) + 1
        self.span("orders process", ctx, attr=order.order_id)


class FraudDetectionService(ServiceBase):
    name = "fraud-detection"
    base_latency_us = 400.0
    GROUP = "fraud-detection"

    def __init__(self, env, bus: Bus):
        super().__init__(env)
        self.orders_checked = 0
        self.suspicious: list[str] = []
        bus.subscribe("orders", self.GROUP, self.handle)

    def handle(self, msg: BusMessage) -> None:
        ctx = TraceContext.from_headers(msg.headers)
        order = decode_order(msg.value)
        self.orders_checked += 1
        # Consumer-side slowdown under kafkaQueueProblems (main.kt:60-63):
        # surfaces as longer processing spans while the topic floods.
        extra_us = 0.0
        if int(self.flag(FLAG_KAFKA_PROBLEMS, 0, ctx)) > 0:
            extra_us = float(self.env.rng.gamma(4.0, 25_000.0))
        # A toy score: many units of one product in one order is "fraud".
        if order.total_quantity >= 9:
            self.suspicious.append(order.order_id)
            self.log(
                "WARN", "suspicious order", ctx,
                order_id=order.order_id, quantity=order.total_quantity,
            )
        self.span("orders consume", ctx, extra_us=extra_us, attr=order.order_id)
