"""Shared service scaffolding: env wiring + simulated latency model.

Every shop service boots from a :class:`ServiceEnv` (tracer, flag
evaluator, RNG, virtual clock) — the analogue of the reference's shared
boot shape (SURVEY.md §3.5: env config → tracer/meter → OpenFeature →
server). Latencies are drawn from a gamma distribution around each
service's base (long right tail, like real RPC latency) and stretched by
fault flags, so every injected failure has the observable signature the
detector is supposed to catch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..telemetry.tracer import TraceContext, Tracer
from ..utils.flags import FlagEvaluator


@dataclass
class ServiceEnv:
    tracer: Tracer
    flags: FlagEvaluator
    rng: np.random.Generator
    clock: Callable[[], float]
    metrics: object | None = None
    # Structured-log sink: (service, severity, body, attrs, trace_id) →
    # the collector's logs pipeline (OpenSearch-analogue index "otel").
    logger: Callable | None = None
    extra: dict = field(default_factory=dict)


def _place_events(events: tuple, duration: float) -> tuple:
    """Resolve negative (auto) event offsets — see ServiceBase.span."""
    out = list(events)
    i = 0
    while i < len(out):
        if out[i].ts_offset_us >= 0:
            i += 1
            continue
        j = i  # [i, j) is a run of autos; find its explicit anchors
        while j < len(out) and out[j].ts_offset_us < 0:
            j += 1
        lo = out[i - 1].ts_offset_us if i > 0 else 0.0
        hi = out[j].ts_offset_us if j < len(out) else duration
        hi = max(hi, lo)  # a decreasing explicit anchor clamps, not reverses
        for k in range(i, j):
            out[k] = out[k]._replace(
                ts_offset_us=lo + (hi - lo) * (k - i + 1) / (j - i + 1)
            )
        i = j
    return tuple(out)


class ServiceBase:
    """A shop service: named span source with a latency profile."""

    name = "service"
    base_latency_us = 500.0

    def __init__(self, env: ServiceEnv):
        self.env = env

    # -- latency / span helpers ---------------------------------------

    def _latency(self, scale: float = 1.0) -> float:
        # gamma(k=4) ⇒ mean 4θ; θ chosen so mean = base_latency_us.
        theta = self.base_latency_us * scale / 4.0
        return float(self.env.rng.gamma(4.0, theta))

    def span(
        self,
        op: str,
        ctx: TraceContext,
        scale: float = 1.0,
        extra_us: float = 0.0,
        error: bool = False,
        attr: str | None = None,
        events: tuple = (),
    ) -> float:
        """Emit one server span with simulated duration; returns µs.

        ``events`` narrate the span the way the reference's AddEvent
        calls do. Events with a negative ``ts_offset_us`` are auto-
        placed inside the simulated duration (callers know the ORDER of
        their milestones, not the simulated clock) — an event with an
        explicit non-negative offset keeps it, and autos interpolate
        evenly between their neighbouring explicit anchors (span start
        and end when none), so timestamps stay monotone in milestone
        order even when explicit and auto offsets mix.
        """
        duration = self._latency(scale) + extra_us
        if events:
            events = _place_events(events, duration)
        self.env.tracer.emit(
            self.name, op, ctx, duration, is_error=error, attr=attr,
            events=events,
        )
        return duration

    def log(
        self,
        severity: str,
        body: str,
        ctx: TraceContext | None = None,
        **attrs,
    ) -> None:
        """Structured log → collector logs pipeline (if wired).

        The analogue of the reference's per-service structured JSON
        logging shipped over OTLP (e.g. checkout's zap-style logger,
        /root/reference/src/checkout/main.go:61-73)."""
        if self.env.logger is not None:
            self.env.logger(
                self.name,
                severity,
                body,
                attrs or None,
                ctx.trace_id if ctx is not None else None,
            )

    def flag(self, key: str, default, ctx: TraceContext | None = None):
        targeting = ""
        if ctx is not None:
            targeting = ctx.baggage.get("session.id", "")
        return self.env.flags.evaluate(key, default, targeting)


class ServiceError(RuntimeError):
    """A service-level failure (maps to span status ERROR upstream)."""

    def __init__(self, service: str, message: str):
        super().__init__(f"{service}: {message}")
        self.service = service
