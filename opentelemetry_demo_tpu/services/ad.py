"""Ad service: category-targeted ads + CPU/GC/error fault flags.

Mirrors the reference Java AdService's observable behaviour
(/root/reference/src/ad/src/main/java/.../AdService.java:135-213 and
problempattern/*): ads served by category keyword with a random
fallback; session-id baggage drives targeting (AdService.java:160-168);
``adFailure`` errors 1-in-10 requests, ``adHighCpu`` burns latency,
``adManualGc`` injects long stop-the-world pauses.
"""

from __future__ import annotations

from .base import ServiceBase, ServiceError
from ..runtime.tensorize import SpanEvent
from ..telemetry.tracer import TraceContext

FLAG_AD_FAILURE = "adFailure"
FLAG_AD_HIGH_CPU = "adHighCpu"
FLAG_AD_MANUAL_GC = "adManualGc"

ADS = {
    "telescopes": ["Aperture fever sale: 10% off Dobsonians"],
    "eyepieces": ["Sharper views: premium Plossl set"],
    "filters": ["See the veil: OIII filters in stock"],
    "mounts": ["Track perfectly: Go-To mounts"],
    "cameras": ["Image the sky: cooled astro cams"],
    "binoculars": ["Grab-and-go: big binoculars"],
    "books": ["Navigate the deep sky: laminated atlas"],
    "accessories": ["Never lose a target: red dot finders"],
    "power": ["All-night power in the field"],
}


class AdService(ServiceBase):
    name = "ad"
    base_latency_us = 700.0

    def get_ads(self, ctx: TraceContext, context_keys: list[str]) -> list[str]:
        if self.env.metrics is not None:
            self.env.metrics.counter_add(
                "app_ads_requests_total", 1.0,
                targeted=str(bool(context_keys)).lower(),
            )
        # Fault flags, in the order the reference applies them.
        if bool(self.flag(FLAG_AD_FAILURE, False, ctx)):
            if self.env.rng.random() < 0.1:  # 1-in-10, AdService.java:172
                # "Error" event with the cause (AdService.java:219-220).
                self.span("GetAds", ctx, error=True, events=(SpanEvent(
                    "Error", -1.0,
                    (("exception.message", "flagged ad failure"),),
                ),))
                raise ServiceError(self.name, "flagged ad failure")
        extra_us = 0.0
        if bool(self.flag(FLAG_AD_HIGH_CPU, False, ctx)):
            extra_us += float(self.env.rng.gamma(4.0, 2000.0))
        if bool(self.flag(FLAG_AD_MANUAL_GC, False, ctx)):
            # Full-GC pause: rare but enormous.
            if self.env.rng.random() < 0.05:
                extra_us += 300_000.0

        picks: list[str] = []
        for key in context_keys:
            picks.extend(ADS.get(key, []))
        if not picks:
            flat = [a for ads in ADS.values() for a in ads]
            idx = self.env.rng.integers(0, len(flat), size=2)
            picks = [flat[i] for i in idx]
        self.span(
            "GetAds", ctx, extra_us=extra_us,
            attr=ctx.baggage.get("session.id"),
        )
        return picks
