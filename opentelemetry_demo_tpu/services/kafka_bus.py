"""The live shop's async tier over a real network broker.

Drop-in for :class:`services.bus.Bus` backed by the Kafka wire client:
``checkout`` publishes OrderResult bytes with trace headers via a real
Produce (/root/reference/src/checkout/kafka/producer.go:11-43), and the
``accounting`` / ``fraud-detection`` consumer groups poll the broker
over the socket (Consumer.cs:77-80, main.kt:54-69) — the path the
reference runs continuously, now the repo's own topology when
``serve_shop --kafka`` is up (pointing at ``runtime.kafka_broker`` or a
real Kafka 3.x broker; same protocol either way — see the interop
scope note in ``runtime.kafka_wire``).

Connection model: everything is lazy with backoff — compose starts
services in parallel, so a broker that isn't up yet means "retry", not
a boot crash. Until the producer connects, publishes buffer in memory
(bounded) the way sarama's async producer queues; consumers simply see
the messages later, preserving ordered delivery.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable

from .bus import BusMessage
from ..runtime.kafka_client import KafkaConsumer, KafkaProducer, _parse_bootstrap
from ..runtime.kafka_wire import KafkaProduceError, KafkaWireError

# What "the broker is unavailable / the connection is broken" looks
# like from the wire client: socket errors, OR KafkaWireError (a
# ValueError) for half-open connections ("broker closed connection")
# and malformed frames mid-restart. Catching only OSError would let a
# broker bounce crash checkout.place_order. NOTE: KafkaProduceError —
# the broker answering but REJECTING a record — subclasses
# KafkaWireError, so it must be caught FIRST wherever the handling
# differs (keep the producer, bounded retry, dead-letter; see
# _sender_loop).
_TRANSPORT_ERRORS = (OSError, KafkaWireError)

log = logging.getLogger(__name__)

RECONNECT_BACKOFF_S = 1.0
PENDING_MAX = 4096  # producer-side buffer while the broker is down
# A record the broker REJECTS (produce error code, healthy transport)
# is retried this many times, then dead-lettered — otherwise one
# poisoned head record (e.g. topic rejection with auto-create off)
# head-of-line blocks every later publish until the buffer drops orders.
MAX_HEAD_ATTEMPTS = 5


class _TopicHandle:
    """What ``checkout`` sees: ``bus.topic(name).produce(...)``."""

    def __init__(self, bus: "KafkaBus", name: str):
        self._bus = bus
        self.name = name

    def produce(self, key: bytes, value: bytes,
                headers: dict[str, str] | None = None) -> int:
        """Returns the broker-assigned base offset, or **-1** when the
        record was buffered instead (broker down / record rejected on
        the fast path) — callers must not treat -1 as a real offset;
        the sender loop delivers buffered records later, in order."""
        return self._bus._produce(self.name, key, value, headers or {})


class _Subscription:
    def __init__(self, topic: str, group: str,
                 handler: Callable[[BusMessage], None]):
        self.topic = topic
        self.group = group
        self.handler = handler
        self.consumer: KafkaConsumer | None = None
        self.next_connect = 0.0


class KafkaBus:
    """Bus facade over the wire client (one producer, one consumer per
    subscribed group — each group is its own real connection, like the
    reference's separate consumer containers)."""

    def __init__(self, bootstrap: str):
        # Validate now: a malformed address is a config error and must
        # refuse to boot (mustMapEnv discipline), unlike a broker that
        # is merely not up yet.
        _parse_bootstrap(bootstrap)
        self.bootstrap = bootstrap
        self._producer: KafkaProducer | None = None
        self._producer_next_connect = 0.0
        self._pending: deque = deque(maxlen=PENDING_MAX)
        self._pending_dropped = 0
        self._head_attempts = 0  # sender-thread only
        self._head_record = None  # identity of the record being retried
        self._dead_lettered = 0
        self._subs: list[_Subscription] = []
        self._lock = threading.Lock()
        self._last_send_error: str | None = None
        self._closed = False
        # Background sender (sarama's async-producer shape,
        # producer.go:15-43): connects and drains the buffer OFF the
        # caller's thread. checkout.place_order runs under the shop's
        # exclusive lock — a blocking connect there (5 s socket timeout
        # against a blackholed broker) would stall the whole site, so
        # _produce never connects: it fast-paths on an already-open
        # producer or enqueues and wakes this thread.
        self._send_wake = threading.Event()
        self._sender = threading.Thread(
            target=self._sender_loop, name="kafka-bus-sender", daemon=True
        )
        self._sender.start()

    # -- producer side --------------------------------------------------

    def topic(self, name: str) -> _TopicHandle:
        return _TopicHandle(self, name)

    def _note_send_error(self, e: Exception) -> None:
        """Log once per distinct failure — produce errors from a live
        broker (e.g. UNKNOWN_TOPIC with auto-create off) would otherwise
        loop silently while orders drain into the void."""
        msg = f"{type(e).__name__}: {e}"
        if msg != self._last_send_error:
            log.warning("Kafka produce to %s failing (%s); buffering "
                        "(%d queued, %d dropped)", self.bootstrap, msg,
                        len(self._pending), self._pending_dropped)
            self._last_send_error = msg

    def _produce(self, topic: str, key: bytes, value: bytes,
                 headers: dict[str, str]) -> int:
        wire_headers = [(k, v.encode("utf-8")) for k, v in headers.items()]
        with self._lock:
            producer = self._producer
            fast = producer is not None and not self._pending
        if fast:
            # Already-connected send: synchronous acks=1, broker offset
            # back to the caller (the common healthy-path case).
            try:
                return producer.send(topic, value, key=key,
                                     headers=wire_headers)
            except KafkaProduceError as e:
                # Record rejected, transport healthy: keep the producer,
                # queue for the sender loop's bounded retry.
                self._note_send_error(e)
            except _TRANSPORT_ERRORS as e:
                self._note_send_error(e)
                with self._lock:
                    self._drop_producer()
        with self._lock:
            if len(self._pending) == self._pending.maxlen:
                self._pending_dropped += 1
                if self._pending_dropped == 1 or self._pending_dropped % 500 == 0:
                    log.error(
                        "Kafka pending buffer full (%d): %d publishes "
                        "dropped so far (broker down too long?)",
                        self._pending.maxlen, self._pending_dropped,
                    )
            self._pending.append((topic, key, value, wire_headers))
        self._send_wake.set()
        return -1  # buffered: no broker offset yet

    def _sender_loop(self) -> None:
        while True:
            self._send_wake.wait(timeout=0.5)
            self._send_wake.clear()
            if self._closed:
                return
            # Consumer connects also live here: pump() must never block
            # a site-wide lock for a 5 s connect timeout.
            for sub in self._subs:
                if sub.consumer is None:
                    self._ensure_consumer(sub)
            if not self._pending:
                continue
            producer = self._ensure_producer()  # blocking connect OK here
            if producer is None:
                continue
            while not self._closed:
                with self._lock:
                    if not self._pending:
                        break
                    head = self._pending[0]
                    # Head identity, not position: a full deque evicts
                    # its head on caller-side appends, so both the
                    # rejection tally and the post-send pop must be
                    # charged to the exact record object we read —
                    # never to whatever sits at index 0 later.
                    if head is not self._head_record:
                        self._head_record = head
                        self._head_attempts = 0
                    t, k, v, h = head
                try:
                    producer.send(t, v, key=k, headers=h)
                except KafkaProduceError as e:
                    # Broker rejected THIS record over a healthy
                    # transport — reconnecting can't fix it. Bound the
                    # retries, then dead-letter the head so it can't
                    # block every later publish (ordered delivery
                    # resumes with the next record).
                    self._note_send_error(e)
                    self._head_attempts += 1
                    if self._head_attempts < MAX_HEAD_ATTEMPTS:
                        break  # retry this head on the next wake
                    with self._lock:
                        if self._pending and self._pending[0] is head:
                            self._pending.popleft()
                    self._dead_lettered += 1
                    self._head_record = None
                    self._head_attempts = 0
                    log.error(
                        "Kafka record to %s dead-lettered after %d broker "
                        "rejections (%s); %d dead-lettered total",
                        t, MAX_HEAD_ATTEMPTS, e, self._dead_lettered,
                    )
                    continue
                except _TRANSPORT_ERRORS as e:
                    self._note_send_error(e)
                    with self._lock:
                        self._drop_producer()
                    break
                self._head_record = None
                self._head_attempts = 0
                with self._lock:
                    # Pop the record we actually sent; if a full-buffer
                    # eviction already removed it, there is nothing to
                    # pop (the eviction was counted as a drop).
                    if self._pending and self._pending[0] is head:
                        self._pending.popleft()

    def _ensure_producer(self) -> KafkaProducer | None:
        """Sender-thread only (blocking connect)."""
        with self._lock:
            if self._producer is not None:
                return self._producer
        if time.monotonic() < self._producer_next_connect:
            return None
        try:
            producer = KafkaProducer(self.bootstrap)
        except _TRANSPORT_ERRORS as e:
            log.warning("Kafka producer connect to %s failed (%s); retrying",
                        self.bootstrap, e)
            return None
        finally:
            # Arm the backoff from attempt COMPLETION: a blackholed
            # address makes connect block for its full socket timeout
            # (5 s); arming from the start would expire the window
            # mid-attempt and retry back-to-back.
            self._producer_next_connect = time.monotonic() + RECONNECT_BACKOFF_S
        with self._lock:
            self._producer = producer
        return producer

    def _drop_producer(self) -> None:
        if self._producer is not None:
            try:
                self._producer.close()
            finally:
                self._producer = None

    # -- consumer side --------------------------------------------------

    def subscribe(self, topic: str, group: str,
                  handler: Callable[[BusMessage], None]) -> None:
        self._subs.append(_Subscription(topic, group, handler))

    def pump(self, max_messages: int = 64) -> int:
        """Poll every subscribed group once; returns delivered count.

        EVERY fetched message is delivered — the consumer's position
        and auto-commit already advanced past them, so dropping a tail
        here would be silent, unrecoverable loss (``max_messages`` is
        accepted for Bus-signature compatibility; the fetch size itself
        is bounded by the consumer's ``max_bytes``). A handler exception
        skips that message (it is already consumed and auto-committed —
        reference consumers log and poll on, main.kt:64) rather than
        wedging the subscription.
        """
        del max_messages
        delivered = 0
        for sub in self._subs:
            consumer = sub.consumer
            if consumer is None:
                # Connects happen on the sender thread (a 5 s connect
                # timeout must never run under the caller's shop lock).
                self._send_wake.set()
                continue
            try:
                msgs = consumer.poll(max_wait_ms=0)
            except Exception:
                try:
                    consumer.close()
                finally:
                    sub.consumer = None
                continue
            for msg in msgs:
                headers = {
                    k: (v.decode("utf-8", "replace") if v is not None else "")
                    for k, v in msg.headers
                }
                try:
                    sub.handler(
                        BusMessage(msg.offset, msg.key, msg.value, headers)
                    )
                except Exception:
                    log.exception(
                        "%s handler failed on %s@%s; skipping",
                        sub.group, sub.topic, msg.offset,
                    )
                delivered += 1
        return delivered

    def _ensure_consumer(self, sub: _Subscription) -> KafkaConsumer | None:
        """Sender-thread only (blocking connect)."""
        if sub.consumer is not None:
            return sub.consumer
        if time.monotonic() < sub.next_connect:
            return None
        try:
            sub.consumer = KafkaConsumer(self.bootstrap, sub.group, sub.topic)
        except _TRANSPORT_ERRORS as e:
            log.warning("Kafka consumer %s connect to %s failed (%s); retrying",
                        sub.group, self.bootstrap, e)
            return None
        finally:
            # From completion, not start — see _ensure_producer.
            sub.next_connect = time.monotonic() + RECONNECT_BACKOFF_S
        return sub.consumer

    def close(self) -> None:
        self._closed = True
        self._send_wake.set()
        self._sender.join(timeout=10.0)
        with self._lock:
            self._drop_producer()
        for sub in self._subs:
            if sub.consumer is not None:
                try:
                    sub.consumer.close()
                finally:
                    sub.consumer = None
