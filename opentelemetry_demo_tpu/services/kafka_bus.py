"""The live shop's async tier over a real network broker.

Drop-in for :class:`services.bus.Bus` backed by the Kafka wire client:
``checkout`` publishes OrderResult bytes with trace headers via a real
Produce (/root/reference/src/checkout/kafka/producer.go:11-43), and the
``accounting`` / ``fraud-detection`` consumer groups poll the broker
over the socket (Consumer.cs:77-80, main.kt:54-69) — the path the
reference runs continuously, now the repo's own topology when
``serve_shop --kafka`` is up (pointing at ``runtime.kafka_broker`` or a
real Kafka ≥3.0; same protocol either way).

Connection model: everything is lazy with backoff — compose starts
services in parallel, so a broker that isn't up yet means "retry", not
a boot crash. Until the producer connects, publishes buffer in memory
(bounded) the way sarama's async producer queues; consumers simply see
the messages later, preserving ordered delivery.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable

from .bus import BusMessage
from ..runtime.kafka_client import KafkaConsumer, KafkaProducer, _parse_bootstrap
from ..runtime.kafka_wire import KafkaWireError

# What "the broker is unavailable / the connection is broken" looks
# like from the wire client: socket errors, OR KafkaWireError (a
# ValueError) for half-open connections ("broker closed connection"),
# produce error codes, and malformed frames mid-restart. Catching only
# OSError would let a broker bounce crash checkout.place_order.
_TRANSPORT_ERRORS = (OSError, KafkaWireError)

log = logging.getLogger(__name__)

RECONNECT_BACKOFF_S = 1.0
PENDING_MAX = 4096  # producer-side buffer while the broker is down


class _TopicHandle:
    """What ``checkout`` sees: ``bus.topic(name).produce(...)``."""

    def __init__(self, bus: "KafkaBus", name: str):
        self._bus = bus
        self.name = name

    def produce(self, key: bytes, value: bytes,
                headers: dict[str, str] | None = None) -> int:
        return self._bus._produce(self.name, key, value, headers or {})


class _Subscription:
    def __init__(self, topic: str, group: str,
                 handler: Callable[[BusMessage], None]):
        self.topic = topic
        self.group = group
        self.handler = handler
        self.consumer: KafkaConsumer | None = None
        self.next_connect = 0.0


class KafkaBus:
    """Bus facade over the wire client (one producer, one consumer per
    subscribed group — each group is its own real connection, like the
    reference's separate consumer containers)."""

    def __init__(self, bootstrap: str):
        # Validate now: a malformed address is a config error and must
        # refuse to boot (mustMapEnv discipline), unlike a broker that
        # is merely not up yet.
        _parse_bootstrap(bootstrap)
        self.bootstrap = bootstrap
        self._producer: KafkaProducer | None = None
        self._producer_next_connect = 0.0
        self._pending: deque = deque(maxlen=PENDING_MAX)
        self._pending_dropped = 0
        self._subs: list[_Subscription] = []
        self._lock = threading.Lock()

    # -- producer side --------------------------------------------------

    def topic(self, name: str) -> _TopicHandle:
        return _TopicHandle(self, name)

    def _ensure_producer(self) -> KafkaProducer | None:
        if self._producer is not None:
            return self._producer
        if time.monotonic() < self._producer_next_connect:
            return None
        try:
            self._producer = KafkaProducer(self.bootstrap)
        except _TRANSPORT_ERRORS as e:
            log.warning("Kafka producer connect to %s failed (%s); retrying",
                        self.bootstrap, e)
            return None
        finally:
            # Arm the backoff from attempt COMPLETION: a blackholed
            # address makes connect block for its full socket timeout
            # (5 s) — arming from the start would expire the window
            # mid-attempt and turn every order into a fresh 5 s stall.
            self._producer_next_connect = time.monotonic() + RECONNECT_BACKOFF_S
        return self._producer

    def _produce(self, topic: str, key: bytes, value: bytes,
                 headers: dict[str, str]) -> int:
        wire_headers = [(k, v.encode("utf-8")) for k, v in headers.items()]
        with self._lock:
            # Drain any buffered publishes first so ordering holds.
            producer = self._ensure_producer()
            if producer is not None and self._pending:
                try:
                    while self._pending:
                        t, k, v, h = self._pending[0]
                        producer.send(t, v, key=k, headers=h)
                        self._pending.popleft()
                except _TRANSPORT_ERRORS:
                    self._drop_producer()
                    producer = None
            if producer is not None:
                try:
                    return producer.send(
                        topic, value, key=key, headers=wire_headers
                    )
                except _TRANSPORT_ERRORS:
                    self._drop_producer()
            if len(self._pending) == self._pending.maxlen:
                self._pending_dropped += 1
            self._pending.append((topic, key, value, wire_headers))
            return -1  # buffered: no broker offset yet

    def _drop_producer(self) -> None:
        if self._producer is not None:
            try:
                self._producer.close()
            finally:
                self._producer = None

    # -- consumer side --------------------------------------------------

    def subscribe(self, topic: str, group: str,
                  handler: Callable[[BusMessage], None]) -> None:
        self._subs.append(_Subscription(topic, group, handler))

    def pump(self, max_messages: int = 64) -> int:
        """Poll every subscribed group once; returns delivered count.

        EVERY fetched message is delivered — the consumer's position
        and auto-commit already advanced past them, so dropping a tail
        here would be silent, unrecoverable loss (``max_messages`` is
        accepted for Bus-signature compatibility; the fetch size itself
        is bounded by the consumer's ``max_bytes``). A handler exception
        skips that message (it is already consumed and auto-committed —
        reference consumers log and poll on, main.kt:64) rather than
        wedging the subscription.
        """
        del max_messages
        delivered = 0
        for sub in self._subs:
            consumer = self._ensure_consumer(sub)
            if consumer is None:
                continue
            try:
                msgs = consumer.poll(max_wait_ms=0)
            except Exception:
                try:
                    consumer.close()
                finally:
                    sub.consumer = None
                continue
            for msg in msgs:
                headers = {
                    k: (v.decode("utf-8", "replace") if v is not None else "")
                    for k, v in msg.headers
                }
                try:
                    sub.handler(
                        BusMessage(msg.offset, msg.key, msg.value, headers)
                    )
                except Exception:
                    log.exception(
                        "%s handler failed on %s@%s; skipping",
                        sub.group, sub.topic, msg.offset,
                    )
                delivered += 1
        return delivered

    def _ensure_consumer(self, sub: _Subscription) -> KafkaConsumer | None:
        if sub.consumer is not None:
            return sub.consumer
        if time.monotonic() < sub.next_connect:
            return None
        try:
            sub.consumer = KafkaConsumer(self.bootstrap, sub.group, sub.topic)
        except _TRANSPORT_ERRORS as e:
            log.warning("Kafka consumer %s connect to %s failed (%s); retrying",
                        sub.group, self.bootstrap, e)
            return None
        finally:
            # From completion, not start — see _ensure_producer.
            sub.next_connect = time.monotonic() + RECONNECT_BACKOFF_S
        return sub.consumer

    def close(self) -> None:
        with self._lock:
            self._drop_producer()
        for sub in self._subs:
            if sub.consumer is not None:
                try:
                    sub.consumer.close()
                finally:
                    sub.consumer = None
