"""Money arithmetic: (currency, units, nanos) with carry/borrow.

Mirrors the semantics of the reference's money package
(/root/reference/src/checkout/money/money.go: validation, signs must
agree, nanos in ±1e9, Sum with carry) and the proto Money shape
(/root/reference/pb/demo.proto:146-160). Implemented from the documented
invariants, not the Go code.
"""

from __future__ import annotations

from typing import NamedTuple

NANOS_PER_UNIT = 1_000_000_000


class MoneyError(ValueError):
    pass


class Money(NamedTuple):
    currency: str
    units: int
    nanos: int

    def validate(self) -> "Money":
        if abs(self.nanos) >= NANOS_PER_UNIT:
            raise MoneyError(f"nanos out of range: {self.nanos}")
        if self.units > 0 and self.nanos < 0 or self.units < 0 and self.nanos > 0:
            raise MoneyError("units and nanos signs disagree")
        if not self.currency:
            raise MoneyError("missing currency code")
        return self

    @classmethod
    def from_float(cls, currency: str, value: float) -> "Money":
        units = int(value)
        nanos = int(round((value - units) * NANOS_PER_UNIT))
        if nanos == NANOS_PER_UNIT or nanos == -NANOS_PER_UNIT:
            units += 1 if nanos > 0 else -1
            nanos = 0
        return cls(currency, units, nanos).validate()

    def to_float(self) -> float:
        return self.units + self.nanos / NANOS_PER_UNIT

    def add(self, other: "Money") -> "Money":
        self.validate()
        other.validate()
        if self.currency != other.currency:
            raise MoneyError(
                f"currency mismatch: {self.currency} != {other.currency}"
            )
        # Stays pure Python deliberately: two big-int ops beat a ctypes
        # round trip ~7x (measured 0.63 vs 4.4 µs/add), and exactness is
        # free. The native kernel's otd_money_sum mirrors this for
        # native-side consumers and is parity-pinned by tests.
        total = (self.units + other.units) * NANOS_PER_UNIT + self.nanos + other.nanos
        units, nanos = divmod(abs(total), NANOS_PER_UNIT)
        sign = -1 if total < 0 else 1
        return Money(self.currency, sign * units, sign * nanos)

    def multiply(self, factor: int) -> "Money":
        self.validate()
        total = (self.units * NANOS_PER_UNIT + self.nanos) * factor
        units, nanos = divmod(abs(total), NANOS_PER_UNIT)
        sign = -1 if total < 0 else 1
        return Money(self.currency, sign * units, sign * nanos)
