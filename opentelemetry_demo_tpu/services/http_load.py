"""HTTP load generator: the Locust profile over real sockets.

Drives a :class:`~.gateway.ShopGateway` address with the reference's
Locust user model (/root/reference/src/load-generator/locustfile.py:
107-220): N concurrent users, 1-10 s waits, the weighted task mix
(browse×10, recommendations×3, ads×3, view-cart×3, add-to-cart×2,
checkout×1, checkout-multi×1, flood-home×5 when enabled, index×1), and
``session.id`` + ``synthetic_request=true`` baggage attached per session
(:175-179) so payment/ad targeting sees the same keys.

The in-proc :class:`~.loadgen.LoadGenerator` is the deterministic
virtual-clock simulator for tests; this one exists to exercise the real
network edge (serialization, trace-header propagation, fault filters,
concurrent request interleaving) exactly as the reference's load
generator exercises Envoy.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import uuid

import numpy as np

from .loadgen import TASK_WEIGHTS
from ..telemetry.tracer import TraceContext


class HttpLoadGenerator:
    """N user threads issuing the Locust task mix against a base URL."""

    def __init__(
        self,
        base_url: str,
        users: int = 5,
        wait_range_s: tuple[float, float] = (1.0, 10.0),
        seed: int = 0,
        flood_enabled: bool = False,
        timeout_s: float = 10.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.users = users
        self.wait_range_s = wait_range_s
        self.flood_enabled = flood_enabled
        self.timeout_s = timeout_s
        self._seed = seed
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.requests_sent = 0
        self.errors = 0
        self._count_lock = threading.Lock()

    # -- plumbing ------------------------------------------------------

    def _headers(self, session_id: str) -> dict[str, str]:
        ctx = TraceContext.new({
            "session.id": session_id, "synthetic_request": "true",
        })
        return {**ctx.to_headers(), "Content-Type": "application/json"}

    def _request(self, method: str, path: str, session_id: str, body: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers=self._headers(session_id),
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                payload = resp.read()
            with self._count_lock:
                self.requests_sent += 1
            return json.loads(payload) if payload[:1] in (b"{", b"[") else None
        except Exception:
            with self._count_lock:
                self.requests_sent += 1
                self.errors += 1
            return None

    def _products(self, session_id: str) -> list[str]:
        doc = self._request("GET", "/api/products", session_id) or {}
        return [p["id"] for p in doc.get("products", [])]

    # -- the Locust tasks ----------------------------------------------

    def _run_task(self, rng: np.random.Generator, task: str, session_id: str, products: list[str]):
        pick = lambda: products[int(rng.integers(len(products)))]  # noqa: E731
        if task == "browse_product" and products:
            pid = pick()
            self._request("GET", f"/api/products/{pid}", session_id)
            self._request("GET", f"/images/{pid}.svg", session_id)
        elif task == "get_recommendations" and products:
            self._request("GET", f"/api/recommendations?productIds={pick()}", session_id)
        elif task == "get_ads":
            self._request("GET", "/api/data?contextKeys=telescopes", session_id)
        elif task == "view_cart":
            self._request("GET", f"/api/cart?sessionId={session_id}", session_id)
        elif task == "add_to_cart" and products:
            self._request("POST", "/api/cart", session_id, {
                "userId": session_id,
                "item": {"productId": pick(), "quantity": int(rng.integers(1, 4))},
            })
        elif task in ("checkout", "checkout_multi") and products:
            n = 1 if task == "checkout" else int(rng.integers(2, 5))
            for _ in range(n):
                self._request("POST", "/api/cart", session_id, {
                    "userId": session_id,
                    "item": {"productId": pick(), "quantity": 1},
                })
            self._request("POST", "/api/checkout", session_id, {
                "userId": session_id,
                "email": f"{session_id[:8]}@example.com",
                "currencyCode": "USD",
            })
        elif task == "flood_home":
            if self.flood_enabled:
                for _ in range(10):
                    self._request("GET", "/", session_id)
        else:  # index
            self._request("GET", "/", session_id)

    def _user_loop(self, user_idx: int):
        rng = np.random.default_rng(self._seed + user_idx)
        session_id = str(uuid.UUID(int=int(rng.integers(0, 2**63)) << 64))
        products = self._products(session_id)
        names = [n for n, _ in TASK_WEIGHTS]
        weights = np.array([w for _, w in TASK_WEIGHTS], dtype=np.float64)
        weights /= weights.sum()
        lo, hi = self.wait_range_s
        while not self._stop.is_set():
            task = names[int(rng.choice(len(names), p=weights))]
            self._run_task(rng, task, session_id, products)
            self._stop.wait(float(rng.uniform(lo, hi)))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        for i in range(self.users):
            t = threading.Thread(
                target=self._user_loop, args=(i,),
                name=f"http-loadgen-{i}", daemon=True,
            )
            self._threads.append(t)
            t.start()

    def stop(self, timeout_s: float = 15.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout_s)

    def run_for(self, seconds: float) -> None:
        self.start()
        time.sleep(seconds)
        self.stop()
