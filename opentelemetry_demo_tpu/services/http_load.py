"""HTTP load generator: the Locust profile over real sockets.

Drives a :class:`~.gateway.ShopGateway` address with the reference's
Locust user model (/root/reference/src/load-generator/locustfile.py:
107-220): N concurrent users, 1-10 s waits, the weighted task mix
(browse×10, recommendations×3, ads×3, view-cart×3, add-to-cart×2,
checkout×1, checkout-multi×1, flood-home×5 when enabled, index×1), and
``session.id`` + ``synthetic_request=true`` baggage attached per session
(:175-179) so payment/ad targeting sees the same keys.

The in-proc :class:`~.loadgen.LoadGenerator` is the deterministic
virtual-clock simulator for tests; this one exists to exercise the real
network edge (serialization, trace-header propagation, fault filters,
concurrent request interleaving) exactly as the reference's load
generator exercises Envoy.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import uuid

import numpy as np

from .loadgen import TASK_WEIGHTS
from ..telemetry.tracer import TraceContext


class _UserPool:
    """Per-user threads with RUNTIME resize — the Locust web-UI contract
    (users / spawn rate editable while the swarm runs, the surface the
    reference exposes through Envoy at /loadgen, envoy.tmpl.yaml:46).

    Each user thread owns a stop event; ``set_users`` retires excess
    users (their event fires, they exit at the next wait) or spawns
    missing ones — immediately, or paced at ``spawn_rate`` users/s by a
    spawner thread (Locust's ramp). ``_user_loop(idx, stop_ev)`` is the
    subclass's task loop.
    """

    def _pool_init(self, thread_prefix: str) -> None:
        self._thread_prefix = thread_prefix
        self._pool_lock = threading.Lock()
        self._pool: list[tuple[threading.Thread, threading.Event]] = []
        self._next_user_idx = 0
        self._spawn_cancel = threading.Event()
        self._spawner: threading.Thread | None = None
        self._resume_users = 0  # stop() parks the target here for start()

    def _spawn_one_locked(self) -> None:
        ev = threading.Event()
        idx = self._next_user_idx
        self._next_user_idx += 1
        t = threading.Thread(
            target=self._user_loop, args=(idx, ev),
            name=f"{self._thread_prefix}-{idx}", daemon=True,
        )
        self._pool.append((t, ev))
        t.start()

    def running_users(self) -> int:
        with self._pool_lock:
            return sum(
                1 for t, ev in self._pool if t.is_alive() and not ev.is_set()
            )

    def set_users(self, n: int, spawn_rate: float = 0.0) -> None:
        """Resize the swarm to ``n`` users; growth paced at
        ``spawn_rate`` users/s when positive, immediate otherwise."""
        n = max(int(n), 0)
        # Cancel any in-flight ramp: the newest target wins.
        self._spawn_cancel.set()
        spawner = self._spawner
        if spawner is not None:
            spawner.join(timeout=5.0)
        self._spawn_cancel = threading.Event()
        with self._pool_lock:
            self._pool = [
                (t, ev) for t, ev in self._pool
                if t.is_alive() and not ev.is_set()
            ]
            current = len(self._pool)
            self.users = n
            if n <= current:
                for _t, ev in self._pool[n:]:
                    ev.set()
                self._pool = self._pool[:n]
                return
            missing = n - current
            if spawn_rate <= 0:
                for _ in range(missing):
                    self._spawn_one_locked()
                return
        cancel = self._spawn_cancel

        def ramp():
            for _ in range(missing):
                if cancel.wait(1.0 / spawn_rate):
                    return
                with self._pool_lock:
                    if cancel.is_set():
                        return
                    self._spawn_one_locked()

        self._spawner = threading.Thread(
            target=ramp, name=f"{self._thread_prefix}-spawner", daemon=True
        )
        self._spawner.start()

    def start(self) -> None:
        # Locust stop→start semantics: resume with the pre-stop target
        # (stop() zeroes the advertised target, parking it aside).
        self.set_users(self.users or self._resume_users)

    def stop(self, timeout_s: float = 15.0) -> None:
        self._spawn_cancel.set()
        spawner = self._spawner
        if spawner is not None:
            spawner.join(timeout=timeout_s)
        with self._pool_lock:
            pool = list(self._pool)
            self._pool = []
            # Status surfaces report this as the target — a stopped
            # pool with a stale nonzero target would read as "running".
            if self.users:
                self._resume_users = self.users
            self.users = 0
        for _t, ev in pool:
            ev.set()
        for t, _ev in pool:
            t.join(timeout=timeout_s)

    def run_for(self, seconds: float) -> None:
        self.start()
        time.sleep(seconds)
        self.stop()


class HttpLoadGenerator(_UserPool):
    """N user threads issuing the Locust task mix against a base URL."""

    def __init__(
        self,
        base_url: str,
        users: int = 5,
        wait_range_s: tuple[float, float] = (1.0, 10.0),
        seed: int = 0,
        flood_enabled: bool = False,
        timeout_s: float = 10.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.users = users
        self.wait_range_s = wait_range_s
        self.flood_enabled = flood_enabled
        self.timeout_s = timeout_s
        self._seed = seed
        self.requests_sent = 0
        self.errors = 0
        self._count_lock = threading.Lock()
        self._pool_init("http-loadgen")

    # -- plumbing ------------------------------------------------------

    def _headers(self, session_id: str) -> dict[str, str]:
        ctx = TraceContext.new({
            "session.id": session_id, "synthetic_request": "true",
        })
        return {**ctx.to_headers(), "Content-Type": "application/json"}

    def _request(self, method: str, path: str, session_id: str, body: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path,
            data=data,
            headers=self._headers(session_id),
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                payload = resp.read()
            with self._count_lock:
                self.requests_sent += 1
            return json.loads(payload) if payload[:1] in (b"{", b"[") else None
        except Exception:
            with self._count_lock:
                self.requests_sent += 1
                self.errors += 1
            return None

    def _products(self, session_id: str) -> list[str]:
        doc = self._request("GET", "/api/products", session_id) or {}
        return [p["id"] for p in doc.get("products", [])]

    # -- the Locust tasks ----------------------------------------------

    def _run_task(self, rng: np.random.Generator, task: str, session_id: str, products: list[str]):
        pick = lambda: products[int(rng.integers(len(products)))]  # noqa: E731
        if task == "browse_product" and products:
            pid = pick()
            self._request("GET", f"/api/products/{pid}", session_id)
            self._request("GET", f"/images/{pid}.svg", session_id)
        elif task == "get_recommendations" and products:
            self._request("GET", f"/api/recommendations?productIds={pick()}", session_id)
        elif task == "get_ads":
            self._request("GET", "/api/data?contextKeys=telescopes", session_id)
        elif task == "view_cart":
            self._request("GET", f"/api/cart?sessionId={session_id}", session_id)
        elif task == "add_to_cart" and products:
            self._request("POST", "/api/cart", session_id, {
                "userId": session_id,
                "item": {"productId": pick(), "quantity": int(rng.integers(1, 4))},
            })
        elif task in ("checkout", "checkout_multi") and products:
            n = 1 if task == "checkout" else int(rng.integers(2, 5))
            for _ in range(n):
                self._request("POST", "/api/cart", session_id, {
                    "userId": session_id,
                    "item": {"productId": pick(), "quantity": 1},
                })
            self._request("POST", "/api/checkout", session_id, {
                "userId": session_id,
                "email": f"{session_id[:8]}@example.com",
                "currencyCode": "USD",
            })
        elif task == "flood_home":
            if self.flood_enabled:
                for _ in range(10):
                    self._request("GET", "/", session_id)
        else:  # index
            self._request("GET", "/", session_id)

    def _user_loop(self, user_idx: int, stop_ev: threading.Event):
        rng = np.random.default_rng(self._seed + user_idx)
        session_id = str(uuid.UUID(int=int(rng.integers(0, 2**63)) << 64))
        products = self._products(session_id)
        names = [n for n, _ in TASK_WEIGHTS]
        weights = np.array([w for _, w in TASK_WEIGHTS], dtype=np.float64)
        weights /= weights.sum()
        lo, hi = self.wait_range_s
        while not stop_ev.is_set():
            task = names[int(rng.choice(len(names), p=weights))]
            self._run_task(rng, task, session_id, products)
            stop_ev.wait(float(rng.uniform(lo, hi)))


def browser_traffic_enabled() -> bool:
    """The reference's gate, same env var (locustfile.py:180-181)."""
    import os

    return os.environ.get("LOCUST_BROWSER_TRAFFIC_ENABLED", "").lower() in (
        "true", "yes", "on",
    )


class BrowserLoadGenerator(_UserPool):
    """WebsiteBrowserUser analogue: drives the RENDERED storefront.

    The reference's browser users (locustfile.py:184-211, Playwright,
    gated by ``LOCUST_BROWSER_TRAFFIC_ENABLED``) differ from its HTTP
    users in three observable ways, all reproduced here without a real
    browser engine:

    - they load *pages* and then their referenced resources (images),
      carrying the session cookie a browser would;
    - they interact — change currency on the cart page, click a product,
      submit the add-to-cart form, follow the 303 redirect;
    - they emit *browser-side* spans (documentLoad + resource fetches,
      service ``frontend-web``) through the gateway's ``/otlp-http``
      seam, with ``synthetic_request=true`` baggage injected into every
      request (the add_baggage_header route hook).
    """

    SERVICE = "frontend-web"

    def __init__(
        self,
        base_url: str,
        users: int = 1,
        wait_range_s: tuple[float, float] = (1.0, 3.0),
        seed: int = 0,
        timeout_s: float = 10.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.users = users
        self.wait_range_s = wait_range_s
        self.timeout_s = timeout_s
        self._seed = seed
        self._count_lock = threading.Lock()
        self.pages_loaded = 0
        self.images_loaded = 0
        self.spans_exported = 0
        self.errors = 0
        self._pool_init("browser-loadgen")

    # -- a minimal browser ---------------------------------------------

    def _fetch(self, path: str, cookies: dict[str, str],
               form: dict[str, str] | None = None) -> tuple[int, str, float]:
        """One navigation: returns (status, html, duration_s); follows
        one 303 (the add-to-cart redirect) like a browser would."""
        headers = {
            "baggage": "synthetic_request=true",
            "Cookie": "; ".join(f"{k}={v}" for k, v in cookies.items()),
        }
        data = None
        if form is not None:
            from urllib.parse import urlencode

            data = urlencode(form).encode()
            headers["Content-Type"] = "application/x-www-form-urlencoded"
        req = urllib.request.Request(
            self.base_url + path, data=data, headers=headers,
            method="POST" if form is not None else "GET",
        )
        t0 = time.time()
        try:
            # A browser follows the 303 itself; urllib turns the POST
            # into a GET on redirect, which is exactly the behavior.
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                for header, value in resp.headers.items():
                    if header.lower() == "set-cookie":
                        name, _, rest = value.partition("=")
                        cookies[name.strip()] = rest.split(";", 1)[0]
                html = resp.read().decode("utf-8", "replace")
                return resp.status, html, time.time() - t0
        except Exception:
            with self._count_lock:
                self.errors += 1
            return 0, "", time.time() - t0

    def _load_page(self, path: str, cookies: dict[str, str],
                   form: dict[str, str] | None = None) -> str:
        """Navigate, then fetch every referenced image; export the
        documentLoad + resource spans the browser SDK would."""
        import re

        t_start = time.time()
        status, html, dur = self._fetch(path, cookies, form)
        spans = [("documentLoad " + path, t_start, dur, status == 0)]
        for src in re.findall(r'src="(/images/[^"]+)"', html):
            t_img = time.time()
            img_status, _, img_dur = self._fetch(src, cookies)
            spans.append(("resourceFetch " + src, t_img, img_dur, img_status == 0))
            with self._count_lock:
                self.images_loaded += 1
        with self._count_lock:
            self.pages_loaded += 1
        self._export_spans(spans, cookies)
        return html

    def _export_spans(self, spans, cookies: dict[str, str]) -> None:
        """Browser-side OTLP/JSON export through the /otlp-http seam."""
        session = cookies.get("shop_session", "")
        doc = {
            "resourceSpans": [{
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": self.SERVICE}},
                ]},
                "scopeSpans": [{"spans": [
                    {
                        "traceId": uuid.uuid4().hex,
                        "name": name,
                        "startTimeUnixNano": str(int(t0 * 1e9)),
                        "endTimeUnixNano": str(int((t0 + dur) * 1e9)),
                        "status": {"code": 2 if failed else 0},
                        "attributes": [
                            {"key": "session.id",
                             "value": {"stringValue": session}},
                        ],
                    }
                    for name, t0, dur, failed in spans
                ]}],
            }]
        }
        req = urllib.request.Request(
            self.base_url + "/otlp-http/v1/traces",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            with self._count_lock:
                self.spans_exported += len(spans)
        except Exception:
            with self._count_lock:
                self.errors += 1

    # -- the two reference browser tasks --------------------------------

    def open_cart_page_and_change_currency(self, cookies) -> None:
        self._load_page("/cart", cookies)
        self._load_page("/cart?currency=CHF", cookies)

    def add_product_to_cart(self, rng, cookies) -> None:
        import re

        html = self._load_page("/", cookies)
        links = re.findall(r'href="/product/([^"]+)"', html)
        if not links:
            return
        pid = links[int(rng.integers(len(links)))]
        self._load_page(f"/product/{pid}", cookies)
        # Submitting the add-to-cart form 303s to /cart; _fetch follows.
        self._load_page("/cart/add", cookies,
                        form={"productId": pid, "quantity": "1"})

    def _user_loop(self, user_idx: int, stop_ev: threading.Event) -> None:
        rng = np.random.default_rng(self._seed + 1000 + user_idx)
        cookies: dict[str, str] = {}
        lo, hi = self.wait_range_s
        while not stop_ev.is_set():
            if int(rng.integers(2)):
                self.add_product_to_cart(rng, cookies)
            else:
                self.open_cart_page_and_change_currency(cookies)
            stop_ev.wait(float(rng.uniform(lo, hi)))
