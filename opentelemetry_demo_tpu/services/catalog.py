"""Product catalog: list/get/search over a JSON-loadable product set.

Mirrors the reference service's observable behaviour
(/root/reference/src/product-catalog/main.go:277-349): products served
from data files reloadable on an interval; search is substring match;
the ``productCatalogFailure`` flag fails ``get_product`` for exactly one
featured product id (the reference fails only ``OLJCESPC7Z``,
main.go:339-349 — here the first catalog entry plays that role).

The product data is this framework's own astronomy-shop set (original
content, same *shape* as the reference's JSON: id, name, categories,
price) — see ``DEFAULT_PRODUCTS``.
"""

from __future__ import annotations

import json
import os

from .base import ServiceBase, ServiceError
from .money import Money
from ..runtime.tensorize import SpanEvent
from ..telemetry.tracer import TraceContext

FLAG_CATALOG_FAILURE = "productCatalogFailure"

DEFAULT_PRODUCTS = [
    {"id": "TEL-DOB-10", "name": "10-inch Dobsonian Telescope",
     "categories": ["telescopes"], "priceUsd": 649.99},
    {"id": "TEL-REF-80", "name": "80mm Apochromatic Refractor",
     "categories": ["telescopes"], "priceUsd": 929.00},
    {"id": "EYE-PLO-25", "name": "25mm Plossl Eyepiece",
     "categories": ["eyepieces", "accessories"], "priceUsd": 54.50},
    {"id": "FIL-OIII-2", "name": "2-inch OIII Nebula Filter",
     "categories": ["filters", "accessories"], "priceUsd": 129.95},
    {"id": "MNT-EQ6-GT", "name": "EQ6 Go-To Equatorial Mount",
     "categories": ["mounts"], "priceUsd": 1799.00},
    {"id": "CAM-ASI-294", "name": "Cooled Astro Camera IMX294",
     "categories": ["cameras"], "priceUsd": 1080.00},
    {"id": "BIN-15X70", "name": "15x70 Astronomy Binoculars",
     "categories": ["binoculars"], "priceUsd": 159.00},
    {"id": "RED-DOT-F", "name": "Red Dot Finder",
     "categories": ["accessories"], "priceUsd": 34.90},
    {"id": "CHA-ATLAS", "name": "Deep Sky Atlas (Laminated)",
     "categories": ["books"], "priceUsd": 42.00},
    {"id": "PWR-TANK-12", "name": "12V Field Power Tank",
     "categories": ["accessories", "power"], "priceUsd": 119.00},
]


class ProductCatalog(ServiceBase):
    name = "product-catalog"
    base_latency_us = 300.0

    def __init__(self, env, products_path: str | None = None):
        super().__init__(env)
        self._path = products_path
        self._mtime = -1.0
        self._products: list[dict] = []
        self._reload(force=True)
        # The flag-failure target: the catalog's featured product.
        self.failure_product_id = self._products[0]["id"]

    # -- data loading (reference reloads on a ticker, main.go:183-205) --

    def _reload(self, force: bool = False) -> None:
        if self._path is None:
            if force:
                self._products = [dict(p) for p in DEFAULT_PRODUCTS]
            return
        try:
            mtime = os.stat(self._path).st_mtime
            if force or mtime != self._mtime:
                with open(self._path) as f:
                    self._products = json.load(f)["products"]
                self._mtime = mtime
        except (OSError, json.JSONDecodeError, KeyError):
            if force:
                self._products = [dict(p) for p in DEFAULT_PRODUCTS]

    # -- API -----------------------------------------------------------

    def list_products(self, ctx: TraceContext) -> list[dict]:
        self._reload()
        self.span("ListProducts", ctx)
        return list(self._products)

    def get_product(self, ctx: TraceContext, product_id: str) -> dict:
        self._reload()
        fail = (
            bool(self.flag(FLAG_CATALOG_FAILURE, False, ctx))
            and product_id == self.failure_product_id
        )
        found = next((p for p in self._products if p["id"] == product_id), None)
        # Span events narrate the outcome the way the reference does
        # (main.go:294-315: error message as the event on both failure
        # paths, "Product Found" on success).
        if fail:
            event = SpanEvent(
                "Error: Product Catalog Fail Feature Flag Enabled", -1.0
            )
        elif found is None:
            event = SpanEvent(f"Product Not Found: {product_id}", -1.0)
        else:
            event = SpanEvent("Product Found", -1.0)
        # Exactly one span per request — a second error span would halve
        # the error rate the detector sees for this service.
        self.span(
            "GetProduct", ctx, error=fail or found is None,
            attr=product_id, events=(event,),
        )
        if fail:
            raise ServiceError(self.name, f"flagged failure for {product_id}")
        if found is None:
            raise ServiceError(self.name, f"no product {product_id}")
        return dict(found)

    def search_products(self, ctx: TraceContext, query: str) -> list[dict]:
        self._reload()
        self.span("SearchProducts", ctx)
        q = query.lower()
        return [p for p in self._products if q in p["name"].lower()]

    def list_ids(self) -> list[str]:
        """Product ids without a span — internal/probe surface."""
        return [p["id"] for p in self._products]

    def price_of(self, product_id: str) -> Money:
        for p in self._products:
            if p["id"] == product_id:
                return Money.from_float("USD", p["priceUsd"])
        raise ServiceError(self.name, f"no product {product_id}")
