"""Web storefront: server-rendered HTML shop (the Next.js tier analogue).

The reference's web tier is a Next.js storefront (~8,100 LoC,
/root/reference/src/frontend/): SSR pages + 20 components
(ProductCard, ProductList, CartDropdown, CheckoutForm, Ad, …), a
session cookie, currency switcher, and Cypress e2e specs driving Home /
ProductDetail / Checkout journeys
(/root/reference/src/frontend/cypress/e2e/*.cy.ts). This module renders
the same journeys server-side over the in-proc frontend API — product
grid with ads, product detail with recommendations, cart with checkout
form, order confirmation — with the session id held in a cookie and
every page view emitting the same API-call spans the reference's SSR
handlers do.

Mounted on the gateway at ``/`` (HTML lives beside the JSON ``/api/*``
routes, like Next.js pages beside ``pages/api``).
"""

from __future__ import annotations

import uuid
from html import escape

from .base import ServiceError
from .frontend import Frontend
from ..telemetry.tracer import TraceContext

SESSION_COOKIE = "shop_session"

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title} · Astronomy Shop (TPU)</title>
<style>
body{{font-family:system-ui,sans-serif;margin:0;background:#f6f6f8;color:#1a1a2e}}
header{{background:#0b1026;color:#fff;padding:12px 24px;display:flex;gap:24px;align-items:center}}
header a{{color:#9fc2ff;text-decoration:none}}
main{{max-width:960px;margin:24px auto;padding:0 16px}}
.grid{{display:grid;grid-template-columns:repeat(auto-fill,minmax(200px,1fr));gap:16px}}
.card{{background:#fff;border-radius:8px;padding:12px;box-shadow:0 1px 3px rgba(0,0,0,.12)}}
.card img{{width:100%;height:120px;object-fit:contain}}
.ad{{background:#fff6d6;border:1px solid #e8d48a;border-radius:8px;padding:8px 12px;margin:12px 0}}
.error{{background:#ffe3e3;border:1px solid #d88;border-radius:8px;padding:12px}}
button,input,select{{padding:6px 10px;margin:2px 0}}
table{{border-collapse:collapse;width:100%}}td,th{{padding:6px;border-bottom:1px solid #ddd;text-align:left}}
</style></head>
<body><header><a href="/">Astronomy Shop</a><a href="/cart">Cart ({cart_n})</a>
<span style="margin-left:auto;font-size:12px">session {session}</span></header>
<main>{body}</main></body></html>"""


def _money_str(m) -> str:
    return f"{m.currency} {m.units + m.nanos / 1e9:.2f}"


class WebStorefront:
    """HTML routes over the in-proc frontend (SSR-handler analogue)."""

    def __init__(self, frontend: Frontend):
        self.frontend = frontend

    # -- session ------------------------------------------------------

    def _session(self, cookies: dict[str, str]) -> tuple[str, bool]:
        sid = cookies.get(SESSION_COOKIE, "")
        if sid:
            return sid, False
        return str(uuid.uuid4()), True

    def _page(
        self, ctx, title: str, body: str, session_id: str, cart_n: int | None = None
    ) -> bytes:
        if cart_n is None:
            try:
                cart_n = sum(self.frontend.api_cart_get(ctx, session_id).values())
            except ServiceError:
                cart_n = 0  # cartFailure must not take the whole page down
        return _PAGE.format(
            title=escape(title),
            body=body,
            cart_n=cart_n,
            session=escape(session_id[:8]),
        ).encode()

    # -- dispatch ------------------------------------------------------

    def handle(
        self,
        method: str,
        route: str,
        query: dict[str, str],
        form: dict[str, str],
        cookies: dict[str, str],
        ctx: TraceContext,
    ):
        """Returns (status, content_type, body, extra_headers)."""
        session_id, fresh = self._session(cookies)
        ctx.baggage.setdefault("session.id", session_id)
        extra = (
            {"Set-Cookie": f"{SESSION_COOKIE}={session_id}; Path=/; HttpOnly"}
            if fresh
            else {}
        )
        currency = query.get("currency", "USD")
        cart_n = None  # /cart computes it itself; other pages fetch in _page
        try:
            if route == "/" and method == "GET":
                body = self._home(ctx, currency)
            elif route.startswith("/product/") and method == "GET":
                body = self._product(ctx, route.split("/product/", 1)[1], currency)
            elif route == "/cart" and method == "GET":
                body, cart_n = self._cart(ctx, session_id, currency)
            elif route == "/cart/add" and method == "POST":
                pid = form.get("productId", "")
                self.frontend.api_cart_add(ctx, session_id, pid, int(form.get("quantity", "1")))
                return 303, "text/html", b"", {**extra, "Location": "/cart"}
            elif route == "/cart/checkout" and method == "POST":
                body = self._checkout(ctx, session_id, form)
            else:
                return 404, "text/html", b"<h1>404</h1>", extra
        except ServiceError as err:
            body = (
                f'<div class="error"><h2>Something went wrong</h2>'
                f"<p>{escape(str(err))}</p><a href='/'>back to shop</a></div>"
            )
            return 500, "text/html", self._page(ctx, "Error", body, session_id), extra
        return (
            200, "text/html",
            self._page(ctx, "Shop", body, session_id, cart_n), extra,
        )

    # -- pages ---------------------------------------------------------

    def _home(self, ctx, currency: str) -> str:
        products = self.frontend.api_products(ctx)
        try:
            ads = self.frontend.api_ads(
                ctx,
                [p["categories"][0] for p in products[:3] if p.get("categories")],
            )
        except ServiceError:
            ads = []  # adFailure degrades the banner, never the page
        codes = self.frontend.api_currency(ctx)
        cur = escape(currency, quote=True)
        cur_opts = "".join(
            f'<option value="{escape(c, quote=True)}"'
            f'{" selected" if c == currency else ""}>{escape(c)}</option>'
            for c in codes
        )
        ad_html = (
            f'<div class="ad">Ad: {escape(ads[0])}</div>' if ads else ""
        )
        cards = "".join(
            f'<div class="card"><a href="/product/{escape(p["id"], quote=True)}'
            f'?currency={cur}">'
            f'<img src="/images/{escape(p["id"], quote=True)}.svg" alt="">'
            f'<h3>{escape(p["name"])}</h3></a>'
            f'<p>{escape(_price_str(p))}</p></div>'
            for p in products
        )
        return (
            f'<form method="GET" action="/">currency '
            f'<select name="currency" onchange="this.form.submit()">{cur_opts}</select></form>'
            f"{ad_html}<div class=\"grid\">{cards}</div>"
        )

    def _product(self, ctx, product_id: str, currency: str) -> str:
        p = self.frontend.api_product(ctx, product_id)
        recs = self.frontend.api_recommendations(ctx, [product_id])
        rec_html = "".join(
            f'<a class="card" href="/product/{escape(r, quote=True)}">{escape(r)}</a>'
            for r in recs[:4]
        )
        pid = escape(p["id"], quote=True)
        return (
            f'<div class="card"><img src="/images/{pid}.svg" style="max-width:300px">'
            f'<h2>{escape(p["name"])}</h2><p>{escape(p.get("description", ""))}</p>'
            f"<p><b>{escape(_price_str(p))}</b></p>"
            f'<form method="POST" action="/cart/add">'
            f'<input type="hidden" name="productId" value="{pid}">'
            f'<input type="number" name="quantity" value="1" min="1" max="10">'
            f"<button>Add to cart</button></form></div>"
            f"<h3>You may also like</h3><div class=\"grid\">{rec_html}</div>"
        )

    def _cart(self, ctx, session_id: str, currency: str) -> tuple[str, int]:
        """Returns (body, item count) — the count also feeds the header
        badge so the page renders with ONE GetCart call."""
        items = self.frontend.api_cart_get(ctx, session_id)
        if not items:
            return "<h2>Your cart is empty</h2><a href='/'>keep shopping</a>", 0
        rows = "".join(
            f"<tr><td><a href='/product/{escape(pid, quote=True)}'>"
            f"{escape(pid)}</a></td><td>{qty}</td></tr>"
            for pid, qty in items.items()
        )
        ship = self.frontend.api_shipping(ctx, sum(items.values()), currency)
        body = (
            f"<h2>Your cart</h2><table><tr><th>product</th><th>qty</th></tr>{rows}</table>"
            f"<p>shipping: {escape(_money_str(ship))}</p>"
            f'<form method="POST" action="/cart/checkout"><h3>Checkout</h3>'
            f'<input name="email" value="someone@example.com"> '
            f'<input name="currencyCode" value="{escape(currency, quote=True)}" size="4"> '
            f'<input name="cardNumber" value="4432801561520454" size="20">'
            f"<button>Place order</button></form>"
        )
        return body, sum(items.values())

    def _checkout(self, ctx, session_id: str, form: dict[str, str]) -> str:
        order = self.frontend.api_checkout(
            ctx,
            session_id,
            form.get("currencyCode", "USD"),
            form.get("email", "someone@example.com"),
        )
        return (
            f'<div class="card"><h2>Order placed 🎉</h2>'
            f"<p>order id: <b>{escape(order.order_id)}</b></p>"
            f"<p>tracking: {escape(order.tracking_id)}</p>"
            f"<p>total: {escape(_money_str(order.total))}</p>"
            f"<a href='/'>continue shopping</a></div>"
        )


def _price_str(p: dict) -> str:
    # Catalog serves priceUsd as a plain float (catalog.py product table).
    return f"USD {float(p.get('priceUsd', 0.0)):.2f}"
