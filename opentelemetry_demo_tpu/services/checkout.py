"""Checkout: the order orchestrator — the shop's money path.

Mirrors the reference Go checkout's PlaceOrder flow
(/root/reference/src/checkout/main.go:246-331 and §3.1 of SURVEY.md):
get cart → per-item product lookup + currency convert → shipping quote
→ convert → charge card → ship → empty cart → email confirmation →
publish OrderResult to the orders topic with trace context in headers
(main.go:549-637). The ``kafkaQueueProblems`` flag floods the topic with
extra messages the way the reference's producer loop does
(main.go:603-613).

Every failure path emits an error span *and* propagates, so the detector
sees exactly what Jaeger would show a human.
"""

from __future__ import annotations

import uuid
from typing import NamedTuple

from .base import ServiceBase, ServiceError
from .bus import Bus
from .cart import CartService
from .catalog import ProductCatalog
from .currency import CurrencyService
from .email import EmailService
from .money import Money
from .payment import PaymentService
from .shipping import ShippingService
from ..runtime.kafka_orders import encode_placed_order
from ..runtime.tensorize import SpanEvent
from ..telemetry.tracer import TraceContext

FLAG_KAFKA_PROBLEMS = "kafkaQueueProblems"
ORDERS_TOPIC = "orders"


class OrderLine(NamedTuple):
    """One cart line as it appears in OrderResult.items
    (proto OrderItem: CartItem + per-line cost in the user currency)."""

    product_id: str
    quantity: int
    cost: Money


class PlacedOrder(NamedTuple):
    order_id: str
    tracking_id: str
    total: Money
    shipping: Money  # the shipping quote, converted to the user currency
    items: tuple[OrderLine, ...]


def money_json(m: Money) -> dict:
    """Money → the proto-JSON shape the reference APIs use."""
    return {"currencyCode": m.currency, "units": m.units, "nanos": m.nanos}


def placed_order_json(order: PlacedOrder) -> dict:
    """PlacedOrder → the /api/checkout response shape.

    The ONE serializer for every transport that returns an order to a
    client (gateway HTTP route, in-proc mobile transport), mirroring the
    reference's proto-JSON of OrderResult
    (/root/reference/pb/demo.proto:207-214) — a field added here reaches
    all transports at once instead of desynchronizing hand-kept copies.
    """
    return {
        "orderId": order.order_id,
        "shippingTrackingId": order.tracking_id,
        "shippingCost": money_json(order.shipping),
        "total": money_json(order.total),
        "items": [
            {
                "item": {
                    "productId": line.product_id,
                    "quantity": line.quantity,
                },
                "cost": money_json(line.cost),
            }
            for line in order.items
        ],
    }


class CheckoutService(ServiceBase):
    name = "checkout"
    base_latency_us = 1000.0

    def __init__(
        self,
        env,
        cart: CartService,
        catalog: ProductCatalog,
        currency: CurrencyService,
        payment: PaymentService,
        shipping: ShippingService,
        email: EmailService,
        bus: Bus,
    ):
        super().__init__(env)
        self.cart = cart
        self.catalog = catalog
        self.currency = currency
        self.payment = payment
        self.shipping = shipping
        self.email = email
        self.bus = bus

    def place_order(
        self,
        ctx: TraceContext,
        user_id: str,
        user_currency: str,
        email: str,
        card_number: str = "4432801561520454",
        expiry_year: int = 2030,
        expiry_month: int = 1,
    ) -> PlacedOrder:
        # PlaceOrder narrates its milestones as span events exactly like
        # the reference (main.go:270 "prepared", :286-287 "charged" with
        # the transaction id, :292-294 "shipped" with the tracking id;
        # the deferred error event with exception.message at :255-259).
        # Offsets are auto-placed by ServiceBase.span (negative = "in
        # milestone order inside the simulated duration").
        events: list[SpanEvent] = []
        try:
            items = self.cart.get_cart(ctx, user_id)
            if not items:
                raise ServiceError(self.name, "empty cart")

            total = Money(user_currency, 0, 0)
            lines: list[OrderLine] = []
            for product_id, qty in items.items():
                self.catalog.get_product(ctx, product_id)
                usd = self.catalog.price_of(product_id).multiply(qty)
                line_cost = self.currency.convert(ctx, usd, user_currency)
                total = total.add(line_cost)
                lines.append(OrderLine(product_id, qty, line_cost))
            product_ids = [line.product_id for line in lines]

            ship_usd = self.shipping.get_quote(ctx, sum(items.values()))
            ship_cost = self.currency.convert(ctx, ship_usd, user_currency)
            total = total.add(ship_cost)
            events.append(SpanEvent("prepared", -1.0))

            tx_id = self.payment.charge(
                ctx, total, card_number, expiry_year, expiry_month
            )
            events.append(SpanEvent(
                "charged", -1.0, (("app.payment.transaction.id", tx_id),)
            ))
            tracking_id = self.shipping.ship_order(ctx)
            events.append(SpanEvent(
                "shipped", -1.0, (("app.shipping.tracking.id", tracking_id),)
            ))
            self.cart.empty_cart(ctx, user_id)

            order_id = str(uuid.uuid5(uuid.NAMESPACE_DNS, ctx.trace_id.hex()))
            # Email failure is non-fatal — the card is already charged
            # and the shipment created, so the reference logs a warning
            # and returns the order anyway (main.go:317-321). The email
            # span still records the exception (detector evidence).
            try:
                self.email.send_order_confirmation(ctx, email, order_id)
            except ServiceError as mail_err:
                self.log(
                    "WARN",
                    f"failed to send order confirmation to {email!r}: {mail_err}",
                    ctx,
                )

            placed = PlacedOrder(
                order_id, tracking_id, total, ship_cost, tuple(lines)
            )
            self._publish(ctx, placed)
            self.span(
                "PlaceOrder", ctx,
                attr=product_ids[0] if product_ids else None,
                events=tuple(events),
            )
            self.log(
                "INFO", "order placed", ctx,
                order_id=order_id, items=len(product_ids),
                total=f"{total.currency} {total.to_float():.2f}",
            )
            return placed
        except ServiceError as err:
            # Deferred error event (main.go:255-259): milestones reached
            # before the failure stay on the span, the error event ends
            # it with the cause message.
            events.append(SpanEvent(
                "error", -1.0, (("exception.message", str(err)),)
            ))
            self.span("PlaceOrder", ctx, scale=1.5, error=True,
                      events=tuple(events))
            self.log("ERROR", f"order failed: {err}", ctx, user=user_id)
            raise

    def _publish(self, ctx: TraceContext, placed: PlacedOrder) -> None:
        """Async post-processing boundary (main.go:549-614). The Kafka
        payload goes through the same OrderResult encoder as the gRPC
        PlaceOrder response — real quantities and per-line costs, never
        a diverging second encoding of the same proto message.

        No bus = the minimal profile: the reference checkout publishes
        only `if cs.kafkaBrokerSvcAddr != ""` (main.go:324-327), so no
        publish span is emitted either."""
        if self.bus is None:
            return
        topic = self.bus.topic(ORDERS_TOPIC)
        value = encode_placed_order(placed)
        headers = ctx.to_headers()  # context over the async boundary
        topic.produce(placed.order_id.encode(), value, headers)
        self.span("orders publish", ctx, scale=0.3)
        # kafkaQueueProblems: flood the topic so consumers lag.
        flood = int(self.flag(FLAG_KAFKA_PROBLEMS, 0, ctx))
        for _ in range(max(flood, 0)):
            topic.produce(placed.order_id.encode(), value, headers)
