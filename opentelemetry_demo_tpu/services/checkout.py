"""Checkout: the order orchestrator — the shop's money path.

Mirrors the reference Go checkout's PlaceOrder flow
(/root/reference/src/checkout/main.go:246-331 and §3.1 of SURVEY.md):
get cart → per-item product lookup + currency convert → shipping quote
→ convert → charge card → ship → empty cart → email confirmation →
publish OrderResult to the orders topic with trace context in headers
(main.go:549-637). The ``kafkaQueueProblems`` flag floods the topic with
extra messages the way the reference's producer loop does
(main.go:603-613).

Every failure path emits an error span *and* propagates, so the detector
sees exactly what Jaeger would show a human.
"""

from __future__ import annotations

import uuid
from typing import NamedTuple

from .base import ServiceBase, ServiceError
from .bus import Bus
from .cart import CartService
from .catalog import ProductCatalog
from .currency import CurrencyService
from .email import EmailService
from .money import Money
from .payment import PaymentService
from .shipping import ShippingService
from ..runtime.kafka_orders import Order, encode_order
from ..telemetry.tracer import TraceContext

FLAG_KAFKA_PROBLEMS = "kafkaQueueProblems"
ORDERS_TOPIC = "orders"


class PlacedOrder(NamedTuple):
    order_id: str
    tracking_id: str
    total: Money
    items: tuple[str, ...]


class CheckoutService(ServiceBase):
    name = "checkout"
    base_latency_us = 1000.0

    def __init__(
        self,
        env,
        cart: CartService,
        catalog: ProductCatalog,
        currency: CurrencyService,
        payment: PaymentService,
        shipping: ShippingService,
        email: EmailService,
        bus: Bus,
    ):
        super().__init__(env)
        self.cart = cart
        self.catalog = catalog
        self.currency = currency
        self.payment = payment
        self.shipping = shipping
        self.email = email
        self.bus = bus

    def place_order(
        self,
        ctx: TraceContext,
        user_id: str,
        user_currency: str,
        email: str,
        card_number: str = "4432801561520454",
        expiry_year: int = 2030,
        expiry_month: int = 1,
    ) -> PlacedOrder:
        try:
            items = self.cart.get_cart(ctx, user_id)
            if not items:
                raise ServiceError(self.name, "empty cart")

            total = Money(user_currency, 0, 0)
            product_ids = []
            for product_id, qty in items.items():
                self.catalog.get_product(ctx, product_id)
                usd = self.catalog.price_of(product_id).multiply(qty)
                total = total.add(self.currency.convert(ctx, usd, user_currency))
                product_ids.append(product_id)

            ship_usd = self.shipping.get_quote(ctx, sum(items.values()))
            total = total.add(self.currency.convert(ctx, ship_usd, user_currency))

            self.payment.charge(ctx, total, card_number, expiry_year, expiry_month)
            tracking_id = self.shipping.ship_order(ctx)
            self.cart.empty_cart(ctx, user_id)

            order_id = str(uuid.uuid5(uuid.NAMESPACE_DNS, ctx.trace_id.hex()))
            self.email.send_order_confirmation(ctx, email, order_id)

            order = Order(
                order_id=order_id,
                tracking_id=tracking_id,
                shipping_cost_units=ship_usd.to_float(),
                item_count=len(product_ids),
                product_ids=tuple(product_ids),
                total_quantity=sum(items.values()),
            )
            self._publish(ctx, order)
            self.span("PlaceOrder", ctx, attr=product_ids[0] if product_ids else None)
            self.log(
                "INFO", "order placed", ctx,
                order_id=order_id, items=len(product_ids),
                total=f"{total.currency} {total.to_float():.2f}",
            )
            return PlacedOrder(order_id, tracking_id, total, tuple(product_ids))
        except ServiceError as err:
            self.span("PlaceOrder", ctx, scale=1.5, error=True)
            self.log("ERROR", f"order failed: {err}", ctx, user=user_id)
            raise

    def _publish(self, ctx: TraceContext, order: Order) -> None:
        """Async post-processing boundary (main.go:549-614)."""
        topic = self.bus.topic(ORDERS_TOPIC)
        value = encode_order(order)
        headers = ctx.to_headers()  # context over the async boundary
        topic.produce(order.order_id.encode(), value, headers)
        self.span("orders publish", ctx, scale=0.3)
        # kafkaQueueProblems: flood the topic so consumers lag.
        flood = int(self.flag(FLAG_KAFKA_PROBLEMS, 0, ctx))
        for _ in range(max(flood, 0)):
            topic.produce(order.order_id.encode(), value, headers)
