"""Payment service: card validation + flag-driven failure injection.

Mirrors the reference Node payment service's observable behaviour
(/root/reference/src/payment/charge.js:25-91): cards are validated
(Luhn + type by prefix + expiry), only visa/mastercard are accepted,
``paymentFailure`` fails a configurable fraction of charges
(demo.flagd.json percentage variants), and ``synthetic_request`` baggage
marks the charge unfunded (charge.js:77-82) — the loadgen's traffic is
test traffic, after all. A transaction counter mirrors
``app.payment.transactions`` (charge.js:15).
"""

from __future__ import annotations

import uuid

from .base import ServiceBase, ServiceError
from .money import Money
from ..telemetry.tracer import TraceContext, exception_event

FLAG_PAYMENT_FAILURE = "paymentFailure"
FLAG_PAYMENT_UNREACHABLE = "paymentUnreachable"


def luhn_valid(number: str) -> bool:
    digits = [int(c) for c in number if c.isdigit()]
    if len(digits) < 12:
        return False
    checksum = 0
    for i, d in enumerate(reversed(digits)):
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        checksum += d
    return checksum % 10 == 0


def card_type(number: str) -> str:
    if number.startswith("4"):
        return "visa"
    if number[:2] in {"51", "52", "53", "54", "55"}:
        return "mastercard"
    if number[:2] in {"34", "37"}:
        return "amex"
    return "unknown"


class PaymentService(ServiceBase):
    name = "payment"
    base_latency_us = 800.0

    def charge(
        self,
        ctx: TraceContext,
        amount: Money,
        card_number: str,
        expiry_year: int,
        expiry_month: int,
        now_year: int = 2026,
        now_month: int = 1,
    ) -> str:
        # flagd-driven probabilistic failure (percentage variants).
        fail_rate = float(self.flag(FLAG_PAYMENT_FAILURE, 0.0, ctx))
        if self.flag(FLAG_PAYMENT_UNREACHABLE, False, ctx):
            self.span("Charge", ctx, scale=5.0, error=True)
            raise ServiceError(self.name, "payment service unreachable")
        if fail_rate > 0 and self.env.rng.random() < fail_rate:
            err = ServiceError(self.name, "charge failed (paymentFailure active)")
            self.span("Charge", ctx, scale=1.5, error=True,
                      events=(exception_event(err),))
            self.log("WARN", "charge failed (paymentFailure active)", ctx)
            raise err

        ctype = card_type(card_number)
        # Card rejects record the exception on the span (index.js:27's
        # recordException in the charge error handler).
        if not luhn_valid(card_number):
            err = ServiceError(self.name, "invalid card number")
            self.span("Charge", ctx, error=True,
                      events=(exception_event(err),))
            raise err
        if ctype not in ("visa", "mastercard"):
            err = ServiceError(self.name, f"{ctype} not accepted")
            self.span("Charge", ctx, error=True,
                      events=(exception_event(err),))
            raise err
        if (expiry_year, expiry_month) < (now_year, now_month):
            err = ServiceError(
                self.name, f"card expired {expiry_month}/{expiry_year}"
            )
            self.span("Charge", ctx, error=True,
                      events=(exception_event(err),))
            raise err

        charged = ctx.baggage.get("synthetic_request") != "true"
        if self.env.metrics is not None:
            self.env.metrics.counter_add(
                "app_payment_transactions_total", 1.0,
                currency=amount.currency, charged=str(charged).lower(),
            )
        self.span("Charge", ctx, attr=ctype)
        self.log(
            "INFO", "transaction processed", ctx,
            card_type=ctype, amount=f"{amount.currency} {amount.to_float():.2f}",
            charged=charged,
        )
        return str(uuid.uuid5(uuid.NAMESPACE_OID, ctx.trace_id.hex()))
