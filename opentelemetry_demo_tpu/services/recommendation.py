"""Recommendation service: random product picks + cache-leak flag.

Mirrors the reference Python service
(/root/reference/src/recommendation/recommendation_server.py:67-114):
returns up to 5 random catalog products excluding the ones in the
request, and under ``recommendationCacheFailure`` simulates an unbounded
cache whose growth degrades latency (the reference leaks a growing list
and re-reads the full catalog, :79-93) — observable as a slow latency
ramp, the kind of creeping degradation the EWMA's long timescale exists
to catch.
"""

from __future__ import annotations

import threading

from .base import ServiceBase
from .catalog import ProductCatalog
from ..telemetry.tracer import TraceContext

FLAG_RECO_CACHE = "recommendationCacheFailure"


class RecommendationService(ServiceBase):
    name = "recommendation"
    base_latency_us = 900.0

    def __init__(self, env, catalog: ProductCatalog):
        super().__init__(env)
        self.catalog = catalog
        self._cache_entries = 0  # simulated leak size
        # The gRPC edge runs ListRecommendations under the SHARED lock
        # (concurrent readers), and the leak counter is read-modify-
        # write: unlocked increments would lose counts and flatten the
        # very latency ramp the leak scenario exists to produce.
        self._cache_lock = threading.Lock()

    def list_recommendations(
        self, ctx: TraceContext, exclude_ids: list[str]
    ) -> list[str]:
        leak = bool(self.flag(FLAG_RECO_CACHE, False, ctx))
        extra_us = 0.0
        with self._cache_lock:
            if leak:
                # Each hit grows the "cache"; latency grows with it. The
                # reference's leak re-caches the whole catalog per
                # request (recommendation_server.py:79-93), so growth is
                # steep: a few dozen hits multiply the base latency.
                self._cache_entries += 1
                extra_us = min(self._cache_entries * 150.0, 50_000.0)
            else:
                self._cache_entries = 0
        products = self.catalog.list_products(ctx)
        pool = [p["id"] for p in products if p["id"] not in set(exclude_ids)]
        k = min(5, len(pool))
        picks = list(self.env.rng.choice(pool, size=k, replace=False)) if k else []
        if self.env.metrics is not None:
            self.env.metrics.counter_add("app_recommendations_total", float(k))
        self.span("ListRecommendations", ctx, extra_us=extra_us)
        return [str(p) for p in picks]
