"""Shipping + quote: the HTTP-JSON leg of the order path.

Mirrors the reference pair: the Rust shipping service
(/root/reference/src/shipping/src/shipping_service/quote.rs:15-69
delegates cost to the PHP quote service via HTTP POST /getquote;
tracking.rs issues tracking ids) and the PHP quote's per-item random
cost (/root/reference/src/quote/app/routes.php:16-74). Here shipping is
one hop (quote is a separate service object, same call structure), with
the quote cost = per-item uniform cost — the same observable shape.

Like the reference — whose shipping is its second NATIVE service — the
arithmetic lives in a native C++ kernel (native/shipping.cc via
runtime.native): quote money math (2-dp rounding + units/nanos split)
and tracking-id generation (RFC 4122 UUID v5). The pure-Python fallback
keeps the capability dependency-free; parity is pinned by
tests/test_native_shipping.py.
"""

from __future__ import annotations

import uuid

from .base import ServiceBase
from .money import Money
from ..runtime import native
from ..runtime.tensorize import SpanEvent
from ..telemetry.tracer import TraceContext


def quote_money(per_item: float, item_count: int) -> Money:
    """round(per_item × count, 2) as USD Money — native kernel when
    available, Python arithmetic otherwise (identical results)."""
    if native.shipping_available():
        code, units, nanos = native.quote_money(per_item, item_count)
        if code == 0:
            return Money("USD", units, nanos)
    return Money.from_float("USD", round(per_item * item_count, 2))


def tracking_id(trace_id: bytes) -> str:
    """Deterministic tracking id: UUID v5 (URL namespace) of the trace
    id hex — native SHA-1 kernel when available (uuid.uuid5 parity)."""
    name = trace_id.hex().encode()
    if native.shipping_available():
        return native.tracking_id(name)
    return str(uuid.uuid5(uuid.NAMESPACE_URL, name.decode()))


class QuoteService(ServiceBase):
    name = "quote"
    base_latency_us = 600.0

    def get_quote(self, ctx: TraceContext, item_count: int) -> Money:
        # The PHP quote span narrates both phases (routes.php:22,35).
        self.span("getquote", ctx, events=(
            SpanEvent("Calculating quote", -1.0),
            SpanEvent("Quote calculated, returning its value", -1.0),
        ))
        if self.env.metrics is not None:
            self.env.metrics.counter_add("app_quotes_total", 1.0)
        if item_count <= 0:
            return Money("USD", 0, 0)
        per_item = float(self.env.rng.uniform(8.0, 12.5))
        return quote_money(per_item, item_count)


class ShippingService(ServiceBase):
    name = "shipping"
    base_latency_us = 500.0

    def __init__(self, env, quote: QuoteService):
        super().__init__(env)
        self.quote = quote

    def get_quote(self, ctx: TraceContext, item_count: int) -> Money:
        cost = self.quote.get_quote(ctx, item_count)
        self.span("get-quote", ctx)
        return cost

    def ship_order(self, ctx: TraceContext) -> str:
        self.span("ship-order", ctx)
        return tracking_id(ctx.trace_id)
