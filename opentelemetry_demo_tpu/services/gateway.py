"""HTTP edge: the Envoy frontend-proxy + Next.js API surface, over real sockets.

The reference exposes the whole shop through one Envoy listener
(/root/reference/src/frontend-proxy/envoy.tmpl.yaml:39-54 routes ``/``,
``/images/``, ``/otlp-http/``, ``/feature``, ``/loadgen``, ``/metrics`` …)
in front of the Next.js API routes
(/root/reference/src/frontend/pages/api/{products,cart,checkout,currency,
data,recommendations,shipping}.ts). :class:`ShopGateway` is both tiers in
one threaded server:

- the Envoy behaviours: route table, W3C trace-context extraction,
  an access-log span per request tagged ``frontend-proxy`` (the
  ``spawn_upstream_span`` analogue, envoy.tmpl.yaml:18-31), and the
  fault-injection HTTP filter — header-triggered delay
  (``x-fault-delay-ms``, envoy.tmpl.yaml:57-64) plus the
  ``imageSlowLoad`` flag on the image route;
- the frontend behaviours: JSON API routes fanning out to the business
  services, ``app_frontend_requests_total`` counting via
  :class:`~.frontend.Frontend`;
- the image-provider tier (/root/reference/src/image-provider/
  nginx.conf.template): ``/images/<product-id>`` serves a deterministic
  per-product SVG with its own ``image-provider`` span;
- the browser-telemetry seam: ``POST /otlp-http/v1/traces`` accepts OTLP
  (protobuf or JSON) exactly like the collector route the reference
  rewrites for the browser tracer
  (/root/reference/src/frontend/utils/telemetry/FrontendTracer.ts:36-41),
  feeding decoded spans into the same sink as the shop's own.

The wrapped :class:`~.shop.Shop` stays single-threaded: a lock
serializes service calls, and the gateway drives the shop's virtual
clock from wall time (``Shop.pump``) so bus consumers and span flushes
happen between requests, not inside them.
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse
from xml.sax.saxutils import escape as _xml_escape

from .base import ServiceError
from .checkout import money_json as _money_json, placed_order_json
from ..utils.concurrency import RWLock
from .frontend import FLAG_IMAGE_SLOW_LOAD
from .shop import Shop
from .webui import WebStorefront
from ..runtime import otlp
from ..telemetry.obsui import GrafanaUI, JaegerUI
from ..telemetry.tracer import TraceContext

MAX_FAULT_DELAY_S = 10.0  # cap on header-triggered fault delays

# HTTP/2 prior-knowledge connection preface — what a gRPC client sends
# first on an h2c (cleartext) channel. The reference exposes the flag
# gRPC service through the single :8080 entry ("/flagservice/" →
# flagd :8013, envoy.tmpl.yaml:50-51); this edge is an HTTP/1 server,
# so gRPC rides a TCP splice instead: a connection opening with this
# preface is piped verbatim to the gRPC edge (which serves
# flagd.evaluation.v1 AND the oteldemo services — a superset of the
# reference's /flagservice/ upstream).
_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


def _product_image_svg(product_id: str) -> bytes:
    """Deterministic placeholder artwork, one color per product id."""
    # crc32, not hash(): str hashes are salted per process, and the color
    # must be stable across server restarts.
    hue = zlib.crc32(product_id.encode()) % 360
    label = _xml_escape(product_id)
    return (
        '<svg xmlns="http://www.w3.org/2000/svg" width="320" height="320">'
        f'<rect width="320" height="320" fill="hsl({hue},45%,35%)"/>'
        f'<circle cx="160" cy="140" r="70" fill="hsl({hue},60%,70%)"/>'
        f'<text x="160" y="280" text-anchor="middle" fill="#fff" '
        f'font-family="monospace" font-size="20">{label}</text></svg>'
    ).encode()


class ShopGateway:
    """Threaded HTTP server exposing a Shop at one edge address."""

    def __init__(
        self,
        shop: Shop,
        host: str = "0.0.0.0",
        port: int = 8080,
        on_spans=None,
    ):
        self.shop = shop
        self.on_spans = on_spans  # Callable[[float, list[SpanRecord]], None]
        # Writer-preference RW lock: the gateway itself always takes
        # exclusive (every route pumps/flushes shared state), but the
        # gRPC edge shares this lock and runs its read-only RPCs
        # concurrently under .shared() (grpc_edge.READ_METHODS).
        self._lock = RWLock()
        self._t0 = time.monotonic()
        self.requests_served = 0
        # Mount point for the flag editor (flagd-ui analogue): an object
        # with handle(method, path, body) -> (status, content_type, bytes).
        self.feature_ui = None
        self.loadgen_ui = None  # LoadControl, mounted at /loadgen
        # ("host", port) of a GrpcShopEdge over the SAME shop: enables
        # the h2c passthrough (the /flagservice/-at-the-edge analogue).
        # None = h2 connections are refused, like Envoy with the route
        # absent.
        self.grpc_target = None
        # Observability backends at the edge — the reference's Envoy
        # routes /jaeger and /grafana to the query UIs
        # (envoy.tmpl.yaml:44-47); here the analogues are served over
        # the shop's own collector backends.
        self.jaeger_ui = JaegerUI(shop.collector.trace_store)
        self.grafana_ui = GrafanaUI(shop.collector)
        # Server-rendered storefront at "/" (the Next.js tier analogue);
        # HTML pages live beside the JSON /api routes.
        self.web_ui = WebStorefront(shop.frontend)

        gateway = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _respond(
                self,
                status: int,
                body: bytes,
                ctype: str = "application/json",
                extra: dict | None = None,
            ):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _handle(self, method: str):
                t_start = time.monotonic()
                parsed = urlparse(self.path)
                route = parsed.path
                ctx = None
                extra = None
                try:
                    # Header/body parsing is inside the guard: a
                    # malformed traceparent or Content-Length is client
                    # input too, and must produce a 400 + an access-log
                    # span, never a dropped connection.
                    query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    ctx = TraceContext.from_headers(
                        {k.lower(): v for k, v in self.headers.items()}
                    )
                    # Envoy-style fault filter: header-triggered delay.
                    delay_ms = self.headers.get("x-fault-delay-ms")
                    if delay_ms:
                        try:
                            time.sleep(
                                min(max(float(delay_ms), 0.0) / 1000.0, MAX_FAULT_DELAY_S)
                            )
                        except ValueError:
                            pass
                    cookies = {}
                    for part in (self.headers.get("Cookie") or "").split(";"):
                        if "=" in part:
                            k, v = part.split("=", 1)
                            cookies[k.strip()] = v.strip()
                    result = gateway._route(
                        method, route, query, body, ctx,
                        self.headers.get("Content-Type") or "",
                        cookies,
                    )
                    status, ctype, payload = result[:3]
                    extra = result[3] if len(result) > 3 else None
                except ServiceError as e:
                    status, ctype = 500, "application/json"
                    payload = json.dumps({"error": str(e)}).encode()
                except (json.JSONDecodeError, ValueError, KeyError) as e:
                    # Malformed client input (bad JSON body, non-numeric
                    # query params) is the client's fault: 4xx, so it
                    # doesn't inflate the edge error rate the detector
                    # watches (is_error tracks status >= 500).
                    status, ctype = 400, "application/json"
                    payload = json.dumps({"error": f"bad request: {e}"}).encode()
                except Exception as e:  # route bug ≠ connection abort
                    status, ctype = 500, "application/json"
                    payload = json.dumps({"error": f"internal: {e}"}).encode()
                if ctx is None:  # header parse failed before extraction
                    ctx = TraceContext.new()
                # Log before writing the response: once the client sees
                # the reply, the edge span is already in the sink (tests
                # and the pipeline may pump immediately after).
                gateway._access_log(
                    method, route, ctx, status,
                    (time.monotonic() - t_start) * 1e6,
                )
                self._respond(status, payload, ctype, extra)

            def handle(self):
                # h2c prior-knowledge sniff BEFORE the HTTP/1 parser:
                # nothing has read from the socket yet (setup() only
                # wraps it), so MSG_PEEK is safe. A gRPC client's first
                # bytes are always the full 24-byte preface; loop while
                # we hold a strict prefix (TCP may fragment). The sniff
                # runs under a SHORT socket timeout: a blocking
                # MSG_PEEK against a half-open connection that never
                # sends a byte would otherwise pin this handler thread
                # forever. The previous timeout is restored before
                # either handoff — the h2 splice and the HTTP/1 parser
                # own their own read policies.
                import socket as _socket

                prev_timeout = self.connection.gettimeout()
                self.connection.settimeout(2.0)
                try:
                    deadline = time.monotonic() + 2.0
                    while True:
                        try:
                            head = self.connection.recv(
                                len(_H2_PREFACE), _socket.MSG_PEEK
                            )
                        except OSError:
                            # Timeout (half-open peer) or reset: either
                            # way no preface is coming.
                            head = b""
                        if head == _H2_PREFACE:
                            self.connection.settimeout(prev_timeout)
                            gateway._splice_h2(self.connection)
                            self.close_connection = True
                            return
                        if (head and _H2_PREFACE.startswith(head)
                                and time.monotonic() < deadline):
                            # Strict prefix: the rest of the preface is
                            # in flight. MSG_PEEK returns the same
                            # bytes immediately, so pace the re-peek.
                            time.sleep(0.005)
                            continue
                        break  # plain HTTP (or EOF): the normal parser
                finally:
                    # The splice path may have CLOSED the socket (e.g.
                    # no upstream): restoring a timeout on a closed fd
                    # raises EBADF, which must not escape handle().
                    try:
                        self.connection.settimeout(prev_timeout)
                    except OSError:
                        pass
                super().handle()

            def do_GET(self):  # noqa: N802 (http.server API)
                self._handle("GET")

            def do_POST(self):  # noqa: N802
                self._handle("POST")

            def do_DELETE(self):  # noqa: N802
                self._handle("DELETE")

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="shop-gateway", daemon=True
        )

    # -- lifecycle -----------------------------------------------------

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        # BaseServer.shutdown() blocks on an event only serve_forever
        # sets; calling it on a never-started server would wait forever.
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()

    # -- plumbing ------------------------------------------------------

    def _splice_h2(self, client_sock) -> None:
        """Bidirectional TCP splice: gRPC-over-h2c at the HTTP edge.

        The Envoy-route analogue of /flagservice/ (envoy.tmpl.yaml:50-51)
        — the whole connection is piped to the gRPC edge, so any
        flagd.evaluation.v1 / oteldemo call works against the single
        :8080 entry. No h2 frames are parsed here: prior-knowledge h2c
        means the preface identifies the protocol and the edge's job is
        transport, exactly what Envoy's TCP-proxying does for h2c
        upstreams. Runs on the handler's own thread (one per
        connection under ThreadingHTTPServer) plus one pump thread for
        the upstream→client direction.
        """
        import socket as _socket

        if self.grpc_target is None:
            client_sock.close()  # connection refused: route absent
            return
        try:
            upstream = _socket.create_connection(self.grpc_target, timeout=5)
        except OSError:
            client_sock.close()
            return
        upstream.settimeout(None)
        client_sock.settimeout(None)

        def pump(src, dst):
            try:
                while True:
                    data = src.recv(65536)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                # Half-close so the peer's pump sees EOF and drains.
                try:
                    dst.shutdown(_socket.SHUT_WR)
                except OSError:
                    pass

        back = threading.Thread(
            target=pump, args=(upstream, client_sock),
            name="h2c-splice", daemon=True,
        )
        back.start()
        pump(client_sock, upstream)
        back.join(timeout=30)
        upstream.close()

    def _access_log(self, method, route, ctx, status, duration_us):
        """Edge span per request — Envoy's access-log/upstream span."""
        with self._lock:
            self.requests_served += 1
            self.shop.tracer.emit(
                "frontend-proxy",
                f"{method} {route}",
                ctx,
                duration_us,
                is_error=status >= 500,
            )

    def _pump_locked(self):
        """Advance the shop clock to wall elapsed; flush bus + spans."""
        self.shop.pump(time.monotonic() - self._t0, on_spans=self.on_spans)

    WEB_ROUTES = ("/", "/cart", "/cart/add", "/cart/checkout")

    def _route(self, method, route, query, body, ctx, req_ctype, cookies=None):
        """Dispatch one request; returns (status, content_type, bytes)
        or (status, content_type, bytes, extra_headers)."""
        if route == "/health":
            return 200, "application/json", b'{"status":"ok"}'

        if self.web_ui is not None and (
            route in self.WEB_ROUTES or route.startswith("/product/")
        ):
            # Server-rendered storefront (Next.js-page analogue).
            form = {}
            if body and "json" not in req_ctype:
                form = {k: v[0] for k, v in parse_qs(body.decode()).items()}
            with self._lock:
                self._pump_locked()
                return self.web_ui.handle(
                    method, route, query, form, cookies or {}, ctx
                )

        if route.startswith("/otlp-http/"):
            # Browser-telemetry seam. The decode is pure, but the fan-out
            # mutates the same Collector state and detector pipeline that
            # every other route touches under the lock — concurrent OTLP
            # POSTs (ThreadingHTTPServer) would otherwise race the
            # collector's flush-list swap and the pipeline's donated
            # device buffers.
            if "json" in req_ctype:
                records = otlp.decode_export_request_json(body)
            else:
                records = otlp.decode_export_request(body)
            if records:
                # Same fan-out as server-side spans: detector feed AND
                # the telemetry backend (trace store / spanmetrics).
                with self._lock:
                    if self.on_spans is not None:
                        self.on_spans(time.monotonic() - self._t0, records)
                    self.shop.collector.receive_spans(records)
            return 200, "application/json", b"{}"

        if route.startswith("/ofrep/v1/evaluate/flags/"):
            if method != "POST":  # OFREP evaluation is POST-only
                return 405, "application/json", b'{"error":"method not allowed"}'
            # OFREP surface: flagd serves OFREP over HTTP (:8016 in the
            # reference, consumed by the Python load generator via the
            # OpenFeature OFREP provider, locustfile.py:72-74). Shape
            # matches utils.flags.OfrepClient — client and server round
            # trip against each other.
            key = route.rsplit("/", 1)[1]
            doc = json.loads(body or b"{}")
            if not isinstance(doc, dict):
                raise ValueError("OFREP body must be a JSON object")
            context = doc.get("context") or {}
            if not isinstance(context, dict):
                raise ValueError("OFREP context must be a JSON object")
            targeting = context.get("targetingKey", "")
            flags = self.shop.flags
            # Sentinel default: a DISABLED or unresolvable flag must get
            # the FLAG_NOT_FOUND treatment so OFREP clients fall back to
            # their own defaults (returning 200 {"value": null} would
            # override the caller's default with None).
            missing = object()
            value = (
                flags.evaluate(key, missing, targeting)
                if key in flags.flag_keys()
                else missing
            )
            if value is missing:
                return 404, "application/json", json.dumps(
                    {"key": key, "errorCode": "FLAG_NOT_FOUND"}
                ).encode()
            return 200, "application/json", json.dumps(
                {"key": key, "value": value, "reason": "STATIC"}
            ).encode()

        if route == "/jaeger" or route.startswith("/jaeger/"):
            # Trace query surface (envoy.tmpl.yaml:44-45 analogue).
            # Pump first (exclusive, brief) so spans the client just
            # generated have had their batch-timeout chance to reach
            # the trace store; then query/render under the SHARED side
            # of the RW lock — observability polling must not serialize
            # the data plane, only exclude writers while reading
            # (same discipline as the gRPC edge's read-only RPCs).
            sub = route[len("/jaeger"):] or "/"
            with self._lock:
                self._pump_locked()
                self.shop.collector.force_flush(scrape=False)
            with self._lock.shared():
                return self.jaeger_ui.handle(method, sub, query)

        if route == "/grafana" or route.startswith("/grafana/"):
            # Dashboard surface (envoy.tmpl.yaml:46-47 analogue).
            sub = route[len("/grafana"):] or "/"
            # Only the routes that evaluate live panels need a fresh
            # TSDB sample; the dashboard list and static model JSON
            # never read the TSDB.
            live = sub.startswith(("/api/eval/", "/d/"))
            with self._lock:
                self._pump_locked()
                self.shop.collector.force_flush(scrape=live)
            with self._lock.shared():
                return self.grafana_ui.handle(method, sub, query)

        if route.startswith("/feature"):
            if self.feature_ui is None:
                return 503, "text/plain", b"flag UI not mounted"
            sub = route[len("/feature"):] or "/"
            return self.feature_ui.handle(method, sub, body)

        if route.startswith("/loadgen"):
            # The Locust web UI behind the edge (envoy.tmpl.yaml:46):
            # view/set users + spawn rate at runtime. Deliberately
            # OUTSIDE the shop lock — the control plane must answer
            # while the load it controls saturates the data plane.
            if self.loadgen_ui is None:
                return 503, "text/plain", b"loadgen UI not mounted"
            sub = route[len("/loadgen"):] or "/"
            return self.loadgen_ui.handle(method, sub, body)

        if route.startswith("/images/"):
            product_id = route[len("/images/"):].removesuffix(".svg")
            with self._lock:
                self._pump_locked()
                fe = self.shop.frontend
                fe.api_image(ctx, product_id)  # emits image-provider span
                slow = bool(fe.flag(FLAG_IMAGE_SLOW_LOAD, False, ctx))
            if slow:
                # The envoy fault filter delays the *real* response too;
                # the span already carries the full simulated 3-5s, so
                # cap the wall-clock stall at 1s — outside the shop lock,
                # other routes keep flowing (Envoy only stalls this one).
                time.sleep(1.0)
            return 200, "image/svg+xml", _product_image_svg(product_id)

        with self._lock:
            self._pump_locked()
            return self._route_shop(method, route, query, body, ctx)

    def _route_shop(self, method, route, query, body, ctx):
        fe = self.shop.frontend
        ok = 200, "application/json"

        if route == "/" or route == "/index":
            fe.index(ctx)
            return (*ok, b'{"page":"home"}')

        if route == "/metrics":
            return 200, "text/plain; version=0.0.4", self.shop.metrics.render().encode()

        if route == "/api/products" and method == "GET":
            return (*ok, json.dumps({"products": fe.api_products(ctx)}).encode())

        if route.startswith("/api/products/") and method == "GET":
            product_id = route[len("/api/products/"):]
            return (*ok, json.dumps(fe.api_product(ctx, product_id)).encode())

        if route == "/api/currency" and method == "GET":
            return (*ok, json.dumps({"currencyCodes": fe.api_currency(ctx)}).encode())

        if route == "/api/cart":
            user = query.get("sessionId") or ctx.baggage.get("session.id", "anon")
            if method == "GET":
                items = fe.api_cart_get(ctx, user)
                return (*ok, json.dumps({
                    "userId": user,
                    "items": [
                        {"productId": p, "quantity": q} for p, q in items.items()
                    ],
                }).encode())
            if method == "POST":
                doc = json.loads(body or b"{}")
                item = doc.get("item", {})
                fe.api_cart_add(
                    ctx,
                    doc.get("userId", user),
                    item.get("productId", ""),
                    int(item.get("quantity", 1)),
                )
                return (*ok, b'{"status":"ok"}')
            if method == "DELETE":
                fe.api_cart_empty(ctx, user)
                return (*ok, b'{"status":"ok"}')

        if route == "/api/recommendations" and method == "GET":
            exclude = [p for p in query.get("productIds", "").split(",") if p]
            recs = fe.api_recommendations(ctx, exclude)
            return (*ok, json.dumps({"productIds": recs}).encode())

        if route == "/api/data" and method == "GET":
            keys = [k for k in query.get("contextKeys", "").split(",") if k]
            ads = fe.api_ads(ctx, keys)
            return (*ok, json.dumps({"ads": ads}).encode())

        if route == "/api/shipping" and method == "GET":
            count = int(query.get("itemCount", 1))
            cost = fe.api_shipping(ctx, count, query.get("currencyCode", "USD"))
            return (*ok, json.dumps({"costUsd": _money_json(cost)}).encode())

        if route == "/api/checkout" and method == "POST":
            doc = json.loads(body or b"{}")
            user = doc.get("userId") or ctx.baggage.get("session.id", "anon")
            order = fe.api_checkout(
                ctx, user,
                doc.get("currencyCode", "USD"),
                doc.get("email", "someone@example.com"),
            )
            return (*ok, json.dumps(placed_order_json(order)).encode())

        return 404, "application/json", b'{"error":"no route"}'
