"""Cart service: add/get/empty over a pluggable KV store.

Mirrors the reference C# cart
(/root/reference/src/cart/src/services/CartService.cs:13-101 over Valkey,
ValkeyCartStore.cs): per-user item dict, quantity accumulation on
re-add, and the ``cartFailure`` flag swapping in a store whose writes
fail (CartService.cs:83-90). Latency histograms per op mirror the
custom ``app.cart.*.latency`` metrics (ValkeyCartStore.cs:30-43).
"""

from __future__ import annotations

from .base import ServiceBase, ServiceError
from ..runtime.tensorize import SpanEvent
from ..telemetry.tracer import TraceContext

FLAG_CART_FAILURE = "cartFailure"

# Bucket advice for the cart latency histograms — the explicit-bounds
# hint the reference attaches to app.cart.{add_item,get_cart}.latency
# (ValkeyCartStore.cs:30-43), in milliseconds here.
CART_LATENCY_BUCKETS_MS = (0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 50.0, 200.0, 1000.0)


class InMemoryCartStore:
    """Valkey-analogue KV store: user id → {product id: quantity}."""

    def __init__(self):
        self._data: dict[str, dict[str, int]] = {}

    def add(self, user_id: str, product_id: str, quantity: int) -> None:
        cart = self._data.setdefault(user_id, {})
        cart[product_id] = cart.get(product_id, 0) + quantity

    def get(self, user_id: str) -> dict[str, int]:
        return dict(self._data.get(user_id, {}))

    def empty(self, user_id: str) -> None:
        self._data.pop(user_id, None)

    def stats(self) -> tuple[int, int]:
        """(key count, total items) — the server-stats surface the
        redis-receiver analogue scrapes (telemetry.receivers)."""
        return len(self._data), sum(
            sum(cart.values()) for cart in self._data.values()
        )


class FailingCartStore(InMemoryCartStore):
    """The cartFailure stand-in: every write raises."""

    def add(self, user_id: str, product_id: str, quantity: int) -> None:
        raise ServiceError("cart", "bad cart store (cartFailure active)")

    def empty(self, user_id: str) -> None:
        raise ServiceError("cart", "bad cart store (cartFailure active)")


class CartService(ServiceBase):
    name = "cart"
    base_latency_us = 400.0

    def __init__(self, env):
        super().__init__(env)
        self._store = InMemoryCartStore()
        self._bad_store = FailingCartStore()

    @property
    def store(self) -> InMemoryCartStore:
        """The real (healthy) backing store — the stats-scrape surface."""
        return self._store

    def _active_store(self, ctx: TraceContext):
        if bool(self.flag(FLAG_CART_FAILURE, False, ctx)):
            return self._bad_store
        return self._store

    def _observe(self, op: str, duration_us: float) -> None:
        if self.env.metrics is not None:
            self.env.metrics.histogram_observe(
                f"app_cart_{op}_latency_ms",
                duration_us / 1000.0,
                CART_LATENCY_BUCKETS_MS,
            )

    def add_item(self, ctx: TraceContext, user_id: str, product_id: str, quantity: int) -> None:
        store = self._active_store(ctx)
        try:
            store.add(user_id, product_id, quantity)
        except ServiceError:
            self.span("AddItem", ctx, scale=2.0, error=True, attr=product_id)
            raise
        if self.env.metrics is not None:
            self.env.metrics.counter_add("app_cart_add_item_total", 1.0)
        self._observe("add_item", self.span("AddItem", ctx, attr=product_id))

    def get_cart(self, ctx: TraceContext, user_id: str) -> dict[str, int]:
        # "Fetch cart" narration (CartService.cs:53).
        self._observe("get_cart", self.span(
            "GetCart", ctx, events=(SpanEvent("Fetch cart", -1.0),)
        ))
        return self._active_store(ctx).get(user_id)

    def empty_cart(self, ctx: TraceContext, user_id: str) -> None:
        store = self._active_store(ctx)
        # "Empty cart" narration rides BOTH outcomes: the reference
        # adds the event before the store call (CartService.cs:79), so
        # a failing span carries it too.
        narration = (SpanEvent("Empty cart", -1.0),)
        try:
            store.empty(user_id)
        except ServiceError:
            self.span("EmptyCart", ctx, scale=2.0, error=True,
                      events=narration)
            raise
        self.span("EmptyCart", ctx, events=narration)
