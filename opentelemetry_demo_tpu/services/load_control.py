"""Loadgen control surface: the Locust web UI behind the edge.

The reference routes ``/loadgen`` through Envoy to Locust's web UI
(/root/reference/src/frontend-proxy/envoy.tmpl.yaml:46), where an
operator watches request counters and changes user count / spawn rate
at runtime (autostart defaults from ``.env:97-101``). This module is
that surface for the framework's load tiers: a JSON API + minimal HTML
page the gateway mounts at ``/loadgen``, controlling the HTTP-user tier
and the browser tier (``services.http_load``) live.

API (all JSON):
  GET  /loadgen/api/status           counters + current swarm state
  POST /loadgen/api/start            {"users": N, "spawnRate": R,
                                      "browserUsers": M}
  POST /loadgen/api/users            same body — runtime resize
  POST /loadgen/api/stop             retire every user
"""

from __future__ import annotations

import json
import threading

from .http_load import BrowserLoadGenerator, HttpLoadGenerator


class LoadControl:
    """Owns the load tiers for one target URL; thread-safe."""

    def __init__(self, target_url: str, seed: int = 0):
        self.target_url = target_url
        self.seed = seed
        self.http: HttpLoadGenerator | None = None
        self.browser: BrowserLoadGenerator | None = None
        self._lock = threading.Lock()

    # -- control -------------------------------------------------------

    def set_users(self, users: int, spawn_rate: float = 0.0,
                  browser_users: int | None = None) -> dict:
        with self._lock:
            if self.http is None:
                self.http = HttpLoadGenerator(
                    self.target_url, users=0, seed=self.seed
                )
            self.http.set_users(users, spawn_rate)
            if browser_users is not None:
                if self.browser is None:
                    self.browser = BrowserLoadGenerator(
                        self.target_url, users=0, seed=self.seed
                    )
                self.browser.set_users(browser_users, spawn_rate)
        return self.status()

    def stop(self) -> dict:
        with self._lock:
            for tier in (self.http, self.browser):
                if tier is not None:
                    tier.stop(timeout_s=0.0)  # signal; threads drain async
        return self.status()

    def status(self) -> dict:
        http, browser = self.http, self.browser
        return {
            "target": self.target_url,
            "httpUsers": http.running_users() if http else 0,
            "httpUsersTarget": http.users if http else 0,
            "requestsSent": http.requests_sent if http else 0,
            "requestErrors": http.errors if http else 0,
            "browserUsers": browser.running_users() if browser else 0,
            "pagesLoaded": browser.pages_loaded if browser else 0,
            "browserSpansExported": browser.spans_exported if browser else 0,
        }

    # -- HTTP surface (mounted by the gateway at /loadgen) --------------

    def handle(self, method: str, sub: str, body: bytes):
        """(status, content_type, payload) for a /loadgen request."""
        if sub in ("/", "") and method == "GET":
            return 200, "text/html; charset=utf-8", self._page().encode()
        if sub == "/api/status" and method == "GET":
            return 200, "application/json", json.dumps(self.status()).encode()
        if method == "POST" and sub in ("/api/start", "/api/users"):
            try:
                doc = json.loads(body or b"{}")
                if not isinstance(doc, dict):
                    raise TypeError("body must be a JSON object")
                users = int(doc.get("users", 0))
                spawn_rate = float(doc.get("spawnRate", 0.0))
                browser = doc.get("browserUsers")
                browser_users = None if browser is None else int(browser)
            except (ValueError, TypeError) as e:
                return 400, "application/json", json.dumps(
                    {"error": f"bad request: {e}"}
                ).encode()
            out = self.set_users(users, spawn_rate, browser_users)
            return 200, "application/json", json.dumps(out).encode()
        if method == "POST" and sub == "/api/stop":
            return 200, "application/json", json.dumps(self.stop()).encode()
        return 404, "application/json", b'{"error":"no such loadgen route"}'

    def _page(self) -> str:
        s = self.status()
        return f"""<!doctype html><html><head><title>Load generator</title>
<style>body{{font-family:monospace;margin:2rem}}input{{width:5rem}}</style>
</head><body>
<h1>Load generator</h1>
<p>target: {s['target']}</p>
<table border=1 cellpadding=6>
<tr><th>tier</th><th>running</th><th>counters</th></tr>
<tr><td>http users</td><td>{s['httpUsers']} / {s['httpUsersTarget']}</td>
<td>{s['requestsSent']} requests, {s['requestErrors']} errors</td></tr>
<tr><td>browser users</td><td>{s['browserUsers']}</td>
<td>{s['pagesLoaded']} pages, {s['browserSpansExported']} spans</td></tr>
</table>
<form onsubmit="event.preventDefault();
fetch('/loadgen/api/users',{{method:'POST',
body:JSON.stringify({{users:+u.value,spawnRate:+r.value,
browserUsers:+b.value}})}}).then(()=>location.reload())">
<p>users <input id=u value={s['httpUsersTarget']}>
spawn/s <input id=r value=1>
browser <input id=b value={s['browserUsers']}>
<button>apply</button>
<button type=button onclick="fetch('/loadgen/api/stop',
{{method:'POST'}}).then(()=>location.reload())">stop all</button></p>
</form></body></html>"""
