"""Telemetry collector: the in-proc OTel Collector analogue.

Mirrors the reference collector's pipeline graph
(/root/reference/src/otel-collector/otelcol-config.yml):

    receivers (otlp :5-13, hostmetrics :24-81)
      → processors: memory_limiter → transform (span-name
        normalization :106-113) → batch
      → traces fan-out :120-123 → trace store (Jaeger analogue)
                                 + spanmetrics connector :115-116
      → spanmetrics re-enters the metrics pipeline :125 → TSDB
        (Prometheus analogue, the otlphttp/prometheus exporter :89-92)
      → logs pipeline :128-131 → log store (OpenSearch analogue,
        index "otel" :93-98)

plus collector self-telemetry at detailed level, 10 s cadence
(:132-142). Extra trace exporters can subscribe — that is the seam the
anomaly-detector taps (deploy/otelcol-config-anomaly.yml adds exactly
such an exporter), the pattern of the Jaeger exporter at :85-88.

Everything runs on an injectable virtual clock so pipelines are
deterministic under test.
"""

from __future__ import annotations

import re
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from .logstore import LogDoc, LogStore
from .metrics import MetricRegistry
from .tracestore import TraceStore
from .tsdb import MetricTSDB, Scraper
from ..runtime.tensorize import SpanRecord

# Default spanmetrics explicit duration buckets, in milliseconds — the
# connector's default histogram layout the spanmetrics dashboard's
# histogram_quantile queries ride on.
SPANMETRICS_BUCKETS_MS: tuple[float, ...] = (
    0.1, 0.25, 0.5, 1, 2, 4, 6, 8, 10, 50, 100, 200, 400, 800,
    1000, 1400, 2000, 5000, 15000,
)

CALLS_TOTAL = "traces_span_metrics_calls_total"
DURATION_MS = "traces_span_metrics_duration_milliseconds"


@dataclass
class Exemplar:
    """Metric→trace link: Prometheus exemplar semantics.

    The reference enables exemplar storage
    (--enable-feature=exemplar-storage, docker-compose.yml:793) and
    provisions an exemplars dashboard; spanmetrics attaches the trace id
    of an observation to the histogram so a latency spike on a panel
    clicks through to the exact trace in Jaeger."""

    trace_id: bytes
    value_ms: float
    ts: float

# Span-name normalization: the reference's transform processor rewrites
# high-cardinality span names (otelcol-config.yml:106-113). Same intent
# here: collapse id-looking path segments so span_name stays a bounded
# metric dimension.
_ID_SEGMENT = re.compile(
    r"/(?:[0-9a-f]{8,}|[0-9]+|[A-Z0-9]{8,})(?=/|\?|$)"
)


def normalize_span_name(name: str) -> str:
    """Collapse id-like path segments: ``GET /api/products/OLJCESPC7Z``
    → ``GET /api/products/{id}``."""
    return _ID_SEGMENT.sub("/{id}", name)


@dataclass
class SpanAdmission:
    """What the memory_limiter did with one receive_spans call.

    The reference's memory_limiter REFUSES data (it doesn't drop it
    silently) and the OTLP contract makes that refusal retryable —
    this is the in-proc edition of the same signal, so SDK-side
    exporters can hold their batch and back off instead of re-sending
    into a full collector. Refusal is suffix-aligned: within one call
    the pending buffer only grows, so the refused records are exactly
    the LAST ``refused`` of the submitted list — a caller re-buffers
    ``records[-refused:]`` and retries after ``retry_after_s``.
    """

    accepted: int
    refused: int
    retry_after_s: float | None = None


@dataclass
class CollectorConfig:
    batch_max_spans: int = 512          # batch processor send_batch_size
    batch_timeout_s: float = 0.2        # batch processor timeout
    memory_limit_spans: int = 50_000    # memory_limiter as a span budget
    spanmetrics_buckets_ms: tuple[float, ...] = SPANMETRICS_BUCKETS_MS
    scrape_interval_s: float = 5.0      # prometheus-config.yaml:5
    self_telemetry_interval_s: float = 10.0  # otelcol-config.yml:133-141
    retention_s: float = 3600.0         # prometheus 1h retention


class Collector:
    """Receiver → processors → connector/exporters, on a virtual clock."""

    def __init__(self, clock: Callable[[], float], config: CollectorConfig | None = None):
        self.clock = clock
        self.config = config or CollectorConfig()
        # Backends (the exporters' destinations).
        self.trace_store = TraceStore()
        self.log_store = LogStore()
        self.tsdb = MetricTSDB(retention_s=self.config.retention_s)
        # The spanmetrics connector writes RED metrics here; the scraper
        # pulls this registry into the TSDB like any other job.
        self.spanmetrics = MetricRegistry()
        # Collector self-telemetry (otelcol_* family).
        self.self_metrics = MetricRegistry()
        self.scraper = Scraper(self.tsdb, interval_s=self.config.scrape_interval_s)
        self.scraper.add_target("spanmetrics", self.spanmetrics)
        self.scraper.add_target("otel-collector", self.self_metrics)
        # Exemplar store: (service_name, span_name) → recent exemplars
        # (bounded ring; latest-wins like Prometheus exemplar storage).
        self.exemplars: dict[tuple[str, str], deque[Exemplar]] = {}
        # Extra trace-batch subscribers — the anomaly-detector seam.
        self.trace_exporters: list[Callable[[float, list[SpanRecord]], None]] = []
        # Metrics-pipeline subscribers, invoked after each scrape cycle
        # with the scraped (job, registry) pairs — the otlphttp metrics
        # exporter seam (otelcol-config.yml:124-126): the anomaly
        # sidecar's /v1/metrics leg subscribes here, in-proc or over
        # HTTP via runtime.otlp_metrics.OtlpHttpMetricsExporter.
        self.metrics_exporters: list[Callable[[float, list], None]] = []
        # Logs-pipeline subscribers (the third signal,
        # otelcol-config.yml:128-131): invoked per received log with
        # (now, [LogDoc]) — runtime.otlp_export.OtlpHttpLogsExporter
        # extends this flow across process boundaries to the sidecar.
        self.log_exporters: list[Callable[[float, list], None]] = []
        self._pending_spans: list[SpanRecord] = []
        self._pending_logs: list[LogDoc] = []
        self._last_batch_flush: float | None = None
        self._last_self_report: float | None = None
        # Per-ATTEMPT memory_limiter refusals (the reference's
        # otelcol_processor_refused_spans semantics): a span the SDK
        # retries into a still-full collector counts again, and a span
        # eventually admitted stays counted. This is refusal pressure,
        # NOT terminal loss — SDK-side loss is the sender's own ledger
        # (services.shop.Shop.spans_dropped_backpressure).
        self.dropped_spans = 0

    # -- receivers ----------------------------------------------------

    def add_scrape_target(self, job: str, registry: MetricRegistry, before=None) -> None:
        """Register a service registry for the 5 s scrape cycle."""
        self.scraper.add_target(job, registry, before)

    def attach_hostmetrics(self, receiver=None):
        """Enable the hostmetrics receiver on the scrape cadence
        (otelcol-config.yml:24-81 scrapers → metrics pipeline)."""
        from .hostmetrics import HostMetricsReceiver

        receiver = receiver or HostMetricsReceiver()
        self.add_scrape_target("hostmetrics", receiver.registry, before=receiver.scrape)
        return receiver

    def receive_spans(self, records: list[SpanRecord]) -> "SpanAdmission":
        """OTLP trace receiver → memory_limiter → transform → batch.

        Returns a :class:`SpanAdmission`: a refusal carries a
        retryable hint (one batch-flush interval — the soonest the
        budget can free) so in-proc SDK exporters back off the way a
        remote one honors 429/Retry-After.
        """
        now = self.clock()
        accepted = 0
        refused = 0
        for record in records:
            # memory_limiter: above the budget the collector refuses
            # data rather than OOMing (otelcol-config.yml:100-104).
            if len(self._pending_spans) >= self.config.memory_limit_spans:
                self.dropped_spans += 1
                refused += 1
                self.self_metrics.counter_add(
                    "otelcol_processor_refused_spans", 1.0, processor="memory_limiter"
                )
                continue
            if record.name:
                normalized = normalize_span_name(record.name)
                if normalized != record.name:
                    record = record._replace(name=normalized)
            self._pending_spans.append(record)
            accepted += 1
        if accepted:
            self.self_metrics.counter_add(
                "otelcol_receiver_accepted_spans", float(accepted), receiver="otlp"
            )
        if len(self._pending_spans) >= self.config.batch_max_spans:
            self._flush_spans(now)
        return SpanAdmission(
            accepted=accepted,
            refused=refused,
            retry_after_s=self.config.batch_timeout_s if refused else None,
        )

    def receive_log(
        self,
        service: str,
        severity: str,
        body: str,
        attrs: dict | None = None,
        trace_id: bytes | None = None,
    ) -> None:
        """Logs pipeline → OpenSearch-analogue index ``otel``."""
        now = self.clock()
        doc = LogDoc(
            ts=now,
            service=service,
            severity=severity,
            body=body,
            attrs=dict(attrs or {}),
            trace_id=trace_id,
        )
        self.log_store.add(doc)
        if self.log_exporters:
            # Export rides the span batch timer (one request per flush
            # interval, like _flush_spans) — per-record POSTs would
            # saturate the background sender exactly during the error
            # bursts the sidecar's log lane exists to detect. Local
            # indexing above stays immediate.
            self._pending_logs.append(doc)
        self.self_metrics.counter_add(
            "otelcol_receiver_accepted_log_records", 1.0, receiver="otlp"
        )

    # -- pipeline pump ------------------------------------------------

    def pump(self, now: float | None = None) -> None:
        """Advance timers: batch timeout, scrape cycle, self-telemetry."""
        now = self.clock() if now is None else now
        # Sample queue depth BEFORE the flush below drains it, so the
        # gauge reflects backlog rather than always reading zero.
        if (
            self._last_self_report is None
            or now - self._last_self_report >= self.config.self_telemetry_interval_s
        ):
            self._last_self_report = now
            self.self_metrics.gauge_set(
                "otelcol_exporter_queue_size", float(len(self._pending_spans))
            )
        if (self._pending_spans or self._pending_logs) and (
            self._last_batch_flush is None
            or now - self._last_batch_flush >= self.config.batch_timeout_s
        ):
            self._flush_spans(now)
        if self.scraper.maybe_scrape(now) and self.metrics_exporters:
            jobs = self.scraper.targets()
            for exporter in self.metrics_exporters:
                exporter(now, jobs)

    def force_flush(self, now: float | None = None, *, scrape: bool = True) -> None:
        """OTel-SDK-style ForceFlush: drain the batch processor and
        (optionally) take a scrape sample immediately, without waiting
        out the batch / scrape timers. The observability query surfaces
        (the Jaeger and Grafana UIs at the edge) call this so a read
        issued right after traffic sees that traffic — refresh-button
        semantics. Forced samples never advance the scrape cadence
        clock, so metrics exporters keep firing on schedule; pass
        ``scrape=False`` for trace-only surfaces that don't read the
        TSDB at all."""
        now = self.clock() if now is None else now
        if self._pending_spans or self._pending_logs:
            self._flush_spans(now)
        if scrape:
            self.scraper.scrape(now)

    def _flush_spans(self, now: float) -> None:
        batch, self._pending_spans = self._pending_spans, []
        self._last_batch_flush = now
        # Exporter fan-out: trace store + spanmetrics + subscribers.
        for record in batch:
            self.trace_store.add_span(now, record)
            self._spanmetrics_update(record, now)
        for exporter in self.trace_exporters:
            exporter(now, batch)
        self.self_metrics.counter_add(
            "otelcol_exporter_sent_spans", float(len(batch)), exporter="traces"
        )
        if self._pending_logs:
            log_batch, self._pending_logs = self._pending_logs, []
            for exporter in self.log_exporters:
                exporter(now, log_batch)
            self.self_metrics.counter_add(
                "otelcol_exporter_sent_log_records",
                float(len(log_batch)),
                exporter="logs",
            )

    def slowest_exemplars(self, limit: int = 10) -> list[tuple[str, str, "Exemplar"]]:
        """Across all series: the slowest recent exemplar observations,
        each resolvable to a full trace in the trace store — the
        exemplars-dashboard drill-down.

        Exemplars whose trace has been FIFO-evicted from the bounded
        store are dropped here (a dead click-through is worse than a
        missing row) and pruned from their ring so slow-but-stale
        entries can't dominate the panel forever."""
        rows = []
        for (svc, name), ring in self.exemplars.items():
            live = [ex for ex in ring if self.trace_store.get_trace(ex.trace_id)]
            if len(live) != len(ring):
                ring.clear()
                ring.extend(live)
            rows.extend((svc, name, ex) for ex in live)
        rows.sort(key=lambda r: r[2].value_ms, reverse=True)
        return rows[:limit]

    # -- spanmetrics connector ----------------------------------------

    def _spanmetrics_update(self, record: SpanRecord, now: float) -> None:
        labels = {
            "service_name": record.service,
            "span_name": record.name or "unknown",
            "status_code": "STATUS_CODE_ERROR" if record.is_error else "STATUS_CODE_UNSET",
        }
        self.spanmetrics.counter_add(CALLS_TOTAL, 1.0, **labels)
        self.spanmetrics.histogram_observe(
            DURATION_MS,
            record.duration_us / 1000.0,
            self.config.spanmetrics_buckets_ms,
            **labels,
        )
        # Exemplar: latest observations per (service, span) keep their
        # trace id so dashboards can click through to the trace store.
        if isinstance(record.trace_id, bytes):
            key = (record.service, record.name or "unknown")
            ring = self.exemplars.get(key)
            if ring is None:
                ring = self.exemplars[key] = deque(maxlen=8)
            ring.append(
                Exemplar(
                    trace_id=record.trace_id,
                    value_ms=record.duration_us / 1000.0,
                    ts=now,
                )
            )
