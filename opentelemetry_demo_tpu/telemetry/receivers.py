"""Pull receivers: httpcheck, store-stats, and per-service resource
stats (the redis + docker_stats receiver analogues).

The reference collector scrapes three more receiver families beyond
hostmetrics (/root/reference/src/otel-collector/otelcol-config.yml):
``httpcheck`` probing the frontend-proxy (:15-17), ``redis`` reading
the cart store's server stats (:20-23), and ``docker_stats`` (:18-19)
reporting per-container cpu/memory/etc. Same capabilities here as
scrape-cadence pull receivers on a :class:`~.metrics.MetricRegistry`
(register via ``Collector.add_scrape_target(..., before=recv.scrape)``).
"""

from __future__ import annotations

import os
import threading
import time
import urllib.error
import urllib.request
from typing import Callable

from .hostmetrics import self_rss_bytes
from .metrics import MetricRegistry


class HttpCheckReceiver:
    """Probes HTTP endpoints; emits httpcheck.* metrics.

    ``targets`` maps a name to either a URL (real HTTP GET, used when
    the gateway serves on a socket) or a zero-arg callable returning an
    HTTP status int (in-proc probing on the virtual clock).

    URL targets are probed on a background thread and ``scrape()``
    publishes the last completed result: the scraper runs inside
    ``Shop.pump`` while the gateway holds its request lock, so a
    blocking GET against an unreachable target would stall every locked
    HTTP route for up to ``timeout_s`` per cycle. Callable targets stay
    synchronous (in-proc, no network).
    """

    def __init__(self, registry: MetricRegistry | None = None, timeout_s: float = 5.0):
        self.registry = registry or MetricRegistry()
        self.timeout_s = timeout_s
        self._targets: dict[str, str | Callable[[], int]] = {}
        self._url_lock = threading.Lock()
        self._url_results: dict[str, tuple[int, float]] = {}
        self._url_inflight: set[str] = set()

    def add_target(self, name: str, target: str | Callable[[], int]) -> None:
        self._targets[name] = target

    def _probe(self, target) -> tuple[int, float]:
        t0 = time.monotonic()
        if callable(target):
            status = int(target())
        else:
            try:
                with urllib.request.urlopen(target, timeout=self.timeout_s) as r:
                    status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
            except Exception:
                status = 0  # unreachable
        return status, (time.monotonic() - t0) * 1000.0

    def _probe_url_async(self, name: str, target: str) -> None:
        def run():
            result = self._probe(target)
            with self._url_lock:
                self._url_results[name] = result
                self._url_inflight.discard(name)

        threading.Thread(
            target=run, name=f"httpcheck-{name}", daemon=True
        ).start()

    def scrape(self) -> None:
        for name, target in self._targets.items():
            if callable(target):
                status, ms = self._probe(target)
            else:
                with self._url_lock:
                    last = self._url_results.get(name)
                    kick = name not in self._url_inflight
                    if kick:
                        self._url_inflight.add(name)
                if kick:
                    try:
                        self._probe_url_async(name, target)
                    except Exception:
                        # A failed thread start must not wedge the
                        # target in the inflight set forever.
                        with self._url_lock:
                            self._url_inflight.discard(name)
                        raise
                if last is None:
                    continue  # first probe still in flight
                status, ms = last
            ok = 1.0 if 200 <= status < 400 else 0.0
            # Status code is a VALUE, not a label: gauges keyed by a
            # changing code would leave the stale series (old code, old
            # up/down value) exported forever beside the new one.
            self.registry.gauge_set("httpcheck_status", ok, endpoint=name)
            self.registry.gauge_set(
                "httpcheck_http_status_code", float(status), endpoint=name
            )
            self.registry.gauge_set("httpcheck_duration_ms", ms, endpoint=name)
            if not ok:
                self.registry.counter_add("httpcheck_error_total", 1.0, endpoint=name)


class StoreStatsReceiver:
    """Cart-store stats: the redis receiver analogue.

    The reference scrapes Valkey server stats (keys, memory, ops) from
    the cart store. Here the store is in-proc, so the receiver reads it
    directly: key count (users with carts), total items, and cumulative
    op counters if the store exposes them.
    """

    def __init__(self, store, registry: MetricRegistry | None = None):
        self.store = store
        self.registry = registry or MetricRegistry()

    def scrape(self) -> None:
        keys, items = self.store.stats()
        self.registry.gauge_set("store_db_keys", float(keys))
        self.registry.gauge_set("store_items_total", float(items))


class ProcessStatsReceiver:
    """Per-service resource stats: the docker_stats receiver analogue.

    The reference's ``docker_stats`` receiver (otelcol-config.yml:18-19)
    reports per-CONTAINER cpu/memory, one container per service. This
    framework's deployment maps the same way — each compose/k8s service
    (shop, kafka, anomaly-detector) is its own OS process — so each
    process exports ``container_*``-shaped self stats labeled with its
    service name, from /proc (no docker socket needed; works identically
    inside and outside a container):

    - ``container_cpu_usage_seconds_total``  user+system CPU (os.times)
    - ``container_memory_usage_bytes``       RSS (/proc/self/statm)
    - ``container_threads``                  live thread count
    - ``container_open_fds``                 open descriptor count

    In the single-process simulation the whole shop is one "container";
    the per-BUSINESS-service breakdown (request rates, latencies,
    per-store sizes) is the spanmetrics/store-stats layer's job — a
    process cannot honestly split its own RSS between in-proc services,
    and pretending otherwise would be fabricated data.
    """

    def __init__(self, name: str, registry: MetricRegistry | None = None):
        self.name = name
        self.registry = registry or MetricRegistry()

    def _open_fds(self) -> float:
        try:
            return float(len(os.listdir("/proc/self/fd")))
        except OSError:
            return 0.0

    def scrape(self) -> None:
        t = os.times()
        self.registry.gauge_set(
            "container_cpu_usage_seconds_total", t.user + t.system,
            container_name=self.name,
        )
        self.registry.gauge_set(
            "container_memory_usage_bytes", self_rss_bytes(),
            container_name=self.name,
        )
        self.registry.gauge_set(
            "container_threads", float(threading.active_count()),
            container_name=self.name,
        )
        self.registry.gauge_set(
            "container_open_fds", self._open_fds(),
            container_name=self.name,
        )
