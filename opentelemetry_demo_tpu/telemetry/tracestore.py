"""Trace store: the in-proc Jaeger analogue.

The reference runs Jaeger all-in-one with in-memory storage capped at
25,000 traces (/root/reference/docker-compose.yml:708-727, cap :712),
fed by the collector's OTLP trace exporter
(/root/reference/src/otel-collector/otelcol-config.yml:85-88,120-123).
This store keeps the same contract: bounded in-memory trace retention
with FIFO eviction, and the Jaeger query surface the demo's users
actually exercise — get-trace by id, find-traces filtered by service /
operation / min-duration / error, and the service & operation listings
that populate the search UI dropdowns.

Spans arrive as the framework's :class:`~..runtime.tensorize.SpanRecord`
plus an ingest timestamp (virtual clock), grouped by trace id.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

from ..runtime.tensorize import SpanRecord


@dataclass
class StoredSpan:
    ts: float  # ingest time, virtual-clock seconds
    record: SpanRecord


@dataclass
class Trace:
    trace_id: bytes
    spans: list[StoredSpan] = field(default_factory=list)

    @property
    def services(self) -> set[str]:
        return {s.record.service for s in self.spans}

    @property
    def duration_us(self) -> float:
        """Critical-path proxy: the longest single span (the root RPC in
        the shop's traces — e.g. PlaceOrder encloses its children)."""
        return max((s.record.duration_us for s in self.spans), default=0.0)

    @property
    def has_error(self) -> bool:
        return any(s.record.is_error for s in self.spans)


class TraceStore:
    """Bounded in-memory trace storage with Jaeger-shaped queries."""

    def __init__(self, max_traces: int = 25_000):
        self.max_traces = max_traces
        self._traces: "OrderedDict[bytes, Trace]" = OrderedDict()
        self.evicted_traces = 0

    def __len__(self) -> int:
        return len(self._traces)

    @property
    def span_count(self) -> int:
        return sum(len(t.spans) for t in self._traces.values())

    def add_span(self, ts: float, record: SpanRecord) -> None:
        tid = record.trace_id if isinstance(record.trace_id, bytes) else (
            int(record.trace_id).to_bytes(16, "little", signed=False)
        )
        trace = self._traces.get(tid)
        if trace is None:
            trace = Trace(trace_id=tid)
            self._traces[tid] = trace
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
                self.evicted_traces += 1
        trace.spans.append(StoredSpan(ts=ts, record=record))

    # -- Jaeger query surface -----------------------------------------

    def get_trace(self, trace_id: bytes) -> Trace | None:
        return self._traces.get(trace_id)

    def services(self) -> list[str]:
        names: set[str] = set()
        for t in self._traces.values():
            names.update(t.services)
        return sorted(names)

    def operations(self, service: str) -> list[str]:
        ops: set[str] = set()
        for t in self._traces.values():
            for s in t.spans:
                if s.record.service == service and s.record.name:
                    ops.add(s.record.name)
        return sorted(ops)

    def find_traces(
        self,
        service: str | None = None,
        operation: str | None = None,
        min_duration_us: float = 0.0,
        error_only: bool = False,
        limit: int = 20,
    ) -> list[Trace]:
        """Most-recent-first trace search (the Jaeger UI's default)."""
        out: list[Trace] = []
        for trace in reversed(self._traces.values()):
            if service is not None and service not in trace.services:
                continue
            if operation is not None and not any(
                s.record.name == operation
                and (service is None or s.record.service == service)
                for s in trace.spans
            ):
                continue
            if trace.duration_us < min_duration_us:
                continue
            if error_only and not trace.has_error:
                continue
            out.append(trace)
            if len(out) >= limit:
                break
        return out
