"""Metrics: counters/histograms + Prometheus text exposition.

The detector surfaces its results the way the reference's services
surface theirs — app-level OTel metrics scraped into Prometheus and
graphed in Grafana (SURVEY.md §5 "Metrics"; custom metric examples:
``app.frontend.requests`` InstrumentationMiddleware.ts:10,
``app.payment.transactions`` charge.js:15). The ``app.anomaly.*`` family
exported here drives deploy/grafana-anomaly-dashboard.json.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterable


def _fmt_le(le: float) -> str:
    """Prometheus-style bucket bound rendering (ints without .0)."""
    return str(int(le)) if float(le).is_integer() else repr(float(le))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricRegistry:
    """Thread-safe counters and gauges with Prometheus text output."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._help: dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        self._help[name] = help_text

    def counter_add(self, name: str, value: float = 1.0, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge_set(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = value

    def histogram_observe(
        self,
        name: str,
        value: float,
        buckets: tuple[float, ...],
        **labels: str,
    ) -> None:
        """Explicit-bucket histogram as Prometheus counter series.

        Emits cumulative ``{name}_bucket{le=...}`` (including ``+Inf``),
        ``{name}_sum`` and ``{name}_count`` — the representation the
        reference's cart latency histograms with bucket advice take on
        the Prometheus side (ValkeyCartStore.cs:30-43) and the shape the
        spanmetrics connector's duration histograms export.
        """
        # One sort per observation (this runs per span in the
        # spanmetrics hot path); bucket keys splice in the "le" pair.
        base = sorted(labels.items())
        i = 0
        while i < len(base) and base[i][0] < "le":
            i += 1

        def with_le(le_str: str) -> tuple:
            return tuple(base[:i] + [("le", le_str)] + base[i:])

        base_key = tuple(base)
        with self._lock:
            for le in buckets:
                if value <= le:
                    key = (name + "_bucket", with_le(_fmt_le(le)))
                    self._counters[key] = self._counters.get(key, 0.0) + 1.0
            key = (name + "_bucket", with_le("+Inf"))
            self._counters[key] = self._counters.get(key, 0.0) + 1.0
            key = (name + "_sum", base_key)
            self._counters[key] = self._counters.get(key, 0.0) + value
            key = (name + "_count", base_key)
            self._counters[key] = self._counters.get(key, 0.0) + 1.0

    def snapshot(self) -> tuple[dict, dict]:
        """Point-in-time copy of (counters, gauges) — the scrape surface
        the TSDB's virtual-clock scraper reads (telemetry.tsdb.Scraper),
        the in-proc analogue of Prometheus GETting ``/metrics``."""
        with self._lock:
            return dict(self._counters), dict(self._gauges)

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
        seen: set[str] = set()

        def emit(store: dict, kind: str) -> Iterable[str]:
            for (name, labels), value in sorted(store.items()):
                if name not in seen:
                    seen.add(name)
                    if name in self._help:
                        yield f"# HELP {name} {self._help[name]}"
                    yield f"# TYPE {name} {kind}"
                yield f"{name}{_fmt_labels(dict(labels))} {value}"

        lines.extend(emit(counters, "counter"))
        lines.extend(emit(gauges, "gauge"))
        return "\n".join(lines) + "\n"


class PrometheusExporter:
    """Serves a registry at ``/metrics`` (the scrape surface).

    With ``health`` (a callable returning ``(status, detail)``), also
    serves ``GET /healthz``: JSON ``{"status": ..., **detail}``, HTTP
    200 for ``ok``/``saturated`` (a deliberately-shedding daemon is
    ALIVE — k8s must not restart its way out of overload) and 503 for
    ``degraded`` (a crash-looping component is a real readiness fail).
    """

    def __init__(self, registry: MetricRegistry, host: str = "0.0.0.0",
                 port: int = 9464, health=None):
        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/healthz" and health is not None:
                    import json as _json

                    try:
                        status, detail = health()
                    except Exception:  # noqa: BLE001 — health must answer
                        status, detail = "degraded", {"error": "health probe raised"}
                    body = _json.dumps(
                        {"status": status, **detail}
                    ).encode()
                    self.send_response(503 if status == "degraded" else 200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = reg.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="prom-exporter", daemon=True
        )

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        # BaseServer.shutdown() blocks on an event only serve_forever sets;
        # calling it on a never-started server would wait forever.
        if self._thread.is_alive():
            self._server.shutdown()
        self._server.server_close()


# Metric names for the anomaly detector's export family.
ANOMALY_FLAG_TOTAL = "app_anomaly_flags_total"
ANOMALY_Z_SCORE = "app_anomaly_z_score"
ANOMALY_CARDINALITY = "app_anomaly_distinct_traces"
ANOMALY_HEAVY_HITTER = "app_anomaly_heavy_hitter_ratio"
ANOMALY_SPANS_TOTAL = "app_anomaly_spans_processed_total"
ANOMALY_LAG_P99 = "app_anomaly_detection_lag_p99_ms"
ANOMALY_CUSUM = "app_anomaly_cusum"
# The metrics-ingestion leg (OTLP /v1/metrics → metrics head).
ANOMALY_METRIC_Z = "app_anomaly_metric_z_score"
ANOMALY_METRIC_FLAG_TOTAL = "app_anomaly_metric_flags_total"
ANOMALY_METRIC_POINTS_TOTAL = "app_anomaly_metric_points_processed_total"
ANOMALY_LOG_RECORDS_TOTAL = "app_anomaly_log_records_processed_total"
# Self-telemetry gauges the daemon exports on a 1 s cadence (ingest/
# batch/backlog visibility before the first detector report — the
# otelcol_* habit). Declared here, not inline at the export site: the
# staticcheck metric-surface pass fences anomaly-family names to this
# table so a typo'd inline literal can never mint an undocumented
# series.
ANOMALY_PENDING_ROWS = "app_anomaly_pending_rows"
ANOMALY_BATCHES_DISPATCHED = "app_anomaly_batches_dispatched"
ANOMALY_SPANS_INGESTED = "app_anomaly_spans_ingested"
ANOMALY_LOG_DOCS_STORED = "app_anomaly_log_docs_stored"
# The fault-tolerant runtime's own health family (runtime.supervision):
# the sidecar's job is to stay up while everything around it misbehaves,
# so its component restarts/degradation are first-class metrics.
ANOMALY_COMPONENT_RESTARTS = "anomaly_component_restarts_total"
ANOMALY_COMPONENT_UP = "anomaly_component_up"
ANOMALY_DEGRADED = "anomaly_degraded"
ANOMALY_QUARANTINE_TOTAL = "anomaly_quarantined_records_total"
ANOMALY_QUARANTINE_LAST_ERROR_TS = "anomaly_quarantine_last_error_ts_seconds"
ANOMALY_INGEST_REJECTED = "anomaly_ingest_rejected_total"
ANOMALY_CHECKPOINT_CORRUPT = "anomaly_checkpoint_corrupt_total"
# Overload-protection family (bounded admission / brownout — the
# memory_limiter + sending_queue analogue; runtime.pipeline): the
# flow-control loop is only trustworthy if every shed/throttle/backoff
# decision leaves a number behind.
ANOMALY_SHED_ROWS = "anomaly_shed_rows_total"  # {lane=, cause=} (+ tenant= on the per-tenant quota shed)
ANOMALY_QUEUE_ROWS = "anomaly_queue_rows"
ANOMALY_QUEUE_WATERMARK = "anomaly_queue_watermark_rows"  # {mark=high|low}
ANOMALY_BROWNOUT_LEVEL = "anomaly_brownout_level"
ANOMALY_SATURATED = "anomaly_saturated"
ANOMALY_KAFKA_PAUSED = "anomaly_kafka_paused"
# Parallel host-ingest engine (runtime.ingest_pool): queue depth,
# flush/coalesce counters and worker utilization — how an operator
# sees whether the decode pool, the pipeline, or neither is the
# bottleneck at the current offered load.
ANOMALY_INGEST_POOL_DEPTH = "anomaly_ingest_pool_depth"
ANOMALY_INGEST_POOL_FLUSHES = "anomaly_ingest_pool_flushes_total"
ANOMALY_INGEST_POOL_SPANS = "anomaly_ingest_pool_spans_total"
ANOMALY_INGEST_POOL_REQUESTS = "anomaly_ingest_pool_requests_total"
ANOMALY_INGEST_POOL_UTILIZATION = "anomaly_ingest_pool_worker_utilization"
# Device-put spine (runtime.spine: the staging ring between batch
# assembly and the donated device step): whether host→device transfer
# is actually hidden behind compute, and how deep the ring runs.
ANOMALY_SPINE_PUT_OVERLAP = "anomaly_spine_put_overlap_ratio"
ANOMALY_SPINE_RING_DEPTH = "anomaly_spine_ring_depth"
# Sender-queue visibility for the OTLP exporters (otlp_export.py):
# the drop-oldest path and its backlog, per signal.
ANOMALY_EXPORT_DROPPED = "anomaly_export_dropped_total"  # {signal=}
ANOMALY_EXPORT_QUEUE_DEPTH = "anomaly_export_queue_depth"  # {signal=}
# Hot-standby replication family (runtime.replication + the daemon's
# role state machine): who is serving, at what epoch, how far behind
# the standby is, and every fenced write a resurrected stale primary
# attempted — the split-brain audit trail.
ANOMALY_ROLE = "anomaly_role"  # {role=primary|standby|promoting|fenced}
ANOMALY_EPOCH = "anomaly_epoch"
ANOMALY_REPLICATION_DELTAS = "anomaly_replication_deltas_total"  # {direction=}
ANOMALY_REPLICATION_SNAPSHOTS = "anomaly_replication_snapshots_total"  # {direction=}
ANOMALY_REPLICATION_LAG = "anomaly_replication_lag_seconds"
ANOMALY_REPLICATION_FENCED = "anomaly_replication_fenced_total"  # {path=}
ANOMALY_FAILOVERS = "anomaly_failovers_total"
# Deferred-confirmation offset list (daemon orders pump): entries shed
# when the bounded list overflows — each one is a bounded replay on
# restart, never silent loss.
ANOMALY_OFFSET_DEFER_DROPPED = "anomaly_offset_defer_dropped_total"
# Partial restores (checkpoint.restore_metrics_feed): a snapshot whose
# metrics leg could not be hydrated (geometry change) — the span leg
# restored, the metrics head cold-started.
ANOMALY_RESTORE_PARTIAL = "anomaly_restore_partial_total"
# Verified-frame family (runtime.frame — the ONE columnar wire format
# every state byte moves in): frames that failed verification at each
# hop (ingest scratch→pipeline, replication link, checkpoint file) —
# each one is corruption CAUGHT at a boundary and quarantined instead
# of merged into live sketches — plus the format version this process
# writes (a fleet mid-rolling-upgrade shows a mixed gauge).
ANOMALY_FRAME_CORRUPT = "anomaly_frame_corrupt_total"  # {hop=}
ANOMALY_FRAME_VERSION = "anomaly_frame_version"
# Live query plane (runtime.query: HTTP/gRPC reads over live sketch
# state, the Grafana JSON datasource, read-replica serving): request
# rate/latency per endpoint, the staleness bound every answer carries,
# and the exemplar trace ids captured at flag time.
ANOMALY_QUERY_REQUESTS = "anomaly_query_requests_total"  # {endpoint=, code=}
ANOMALY_QUERY_LATENCY = "anomaly_query_latency_seconds"  # histogram
ANOMALY_QUERY_STALENESS = "anomaly_query_staleness_seconds"
ANOMALY_EXEMPLARS_CAPTURED = "anomaly_exemplars_captured_total"
# Detector self-telemetry (runtime.selftrace batch-lifecycle tracer,
# runtime.flightrec flight recorder, and the phase timers PROMOTED
# from bench-only pool/spine counters into real histograms): where a
# dispatched batch's wall time goes per lifecycle phase, whether the
# device put actually hid behind compute THIS window, how far behind
# harvest runs, and the tracer/recorder's own output rates.
ANOMALY_PHASE_SECONDS = "anomaly_phase_seconds"  # histogram {phase=}
ANOMALY_SPINE_PUT_WAIT = "anomaly_spine_put_wait_seconds"  # histogram
ANOMALY_HARVEST_LAG = "anomaly_harvest_lag_seconds"  # histogram
# Windowed histogram companion to the lifetime-ratio gauge
# anomaly_spine_put_overlap_ratio: one observation per scrape window,
# so overlap quantiles come from Prometheus instead of bench-only math.
ANOMALY_SPINE_OVERLAP_WINDOW = "anomaly_spine_put_overlap_window_ratio"
# Per-answer histogram companion to the anomaly_query_staleness_seconds
# gauge (same Prometheus-owns-the-p99 promotion).
ANOMALY_QUERY_STALENESS_HIST = "anomaly_query_answer_staleness_seconds"
# Time-travel history tier (runtime.history: compaction thread folding
# expiring window banks into the on-disk retention ladder; the query
# plane's range-read backend): how much history exists, how far back it
# reaches, how often the ladder folds, and what a range read costs —
# plus corrupt records surfacing on the shared frame-corruption family
# as anomaly_frame_corrupt_total{hop="history"}.
ANOMALY_HISTORY_SEGMENTS = "anomaly_history_segments"
ANOMALY_HISTORY_BYTES = "anomaly_history_bytes"
ANOMALY_HISTORY_COMPACTIONS = "anomaly_history_compactions_total"
ANOMALY_HISTORY_OLDEST = "anomaly_history_oldest_seconds"
ANOMALY_HISTORY_READ_LATENCY = "anomaly_history_read_latency_seconds"
ANOMALY_SELFTRACE_TRACES = "anomaly_selftrace_traces_total"
ANOMALY_SELFTRACE_SPANS = "anomaly_selftrace_spans_total"
ANOMALY_FLIGHT_EVENTS = "anomaly_flight_events_total"  # {kind=}
ANOMALY_FLIGHT_DUMPS = "anomaly_flight_dumps_total"  # {reason=}
# Closed-loop auto-mitigation (runtime.remediation: the supervised
# controller driving flagd mitigation flags + the sampling policy, then
# verifying its own action recovered the system): every act / verified
# recovery / rollback / failed mitigation leaves a number behind, the
# active gauge shows what is currently mitigated, and the TTM histogram
# is the loop's headline — time-to-mitigate beside time-to-detect.
ANOMALY_MITIGATION_ACTIONS = "anomaly_mitigation_actions_total"  # {actuator=}
ANOMALY_MITIGATION_ROLLBACKS = "anomaly_mitigation_rollbacks_total"
ANOMALY_MITIGATION_VERIFIED = "anomaly_mitigation_verified_total"
ANOMALY_MITIGATION_FAILED = "anomaly_mitigation_failed_total"
ANOMALY_MITIGATION_ACTIVE = "anomaly_mitigation_active"
ANOMALY_TIME_TO_MITIGATE = "anomaly_time_to_mitigate_seconds"  # histogram
# Counterfactual pre-flight (runtime.shadow gating the controller's
# acts on a shadow replay of recorded history) + the collector-steering
# actuator: every verdict by direction, every refusal by reason (the
# fail-closed audit trail), the act→verdict wall interval, and the
# storage fraction the currently pushed tail-sampling policy implies.
ANOMALY_PREFLIGHT_VERDICTS = "anomaly_preflight_verdicts_total"  # {verdict=}
ANOMALY_PREFLIGHT_REFUSED = "anomaly_preflight_refused_total"  # {reason=}
ANOMALY_PREFLIGHT_SECONDS = "anomaly_preflight_seconds"  # histogram
ANOMALY_COLLECTOR_KEEP_RATIO = "anomaly_collector_keep_ratio"
# Sharded detector fleet (runtime.fleet membership + guardrailed
# reshard; runtime.aggregator scatter-gather reads): who is on the
# ring, how often the keyspace moved, how often a move was REFUSED by
# the reshard budget (a flapping shard exhausting its bucket freezes
# the ring — refusals are the audit trail), and each shard's own
# ingest rate (the per-shard panel beside the fleet-global view).
ANOMALY_FLEET_SHARDS_LIVE = "anomaly_fleet_shards_live"
ANOMALY_FLEET_RING_VERSION = "anomaly_fleet_ring_version"
ANOMALY_FLEET_FROZEN = "anomaly_fleet_ring_frozen"
ANOMALY_RESHARDS = "anomaly_reshards_total"
ANOMALY_RESHARDS_REFUSED = "anomaly_reshards_refused_total"
ANOMALY_FLEET_SHARD_SPANS = "anomaly_fleet_shard_ingest_spans_total"  # {shard=}
# Elastic fleet (runtime.autoscale + in-daemon frame adoption): the
# saturation-driven split/join proposer's decision trail — proposals,
# gated refusals by reason (budget / fenced / bounds / role /
# disabled), the live saturation score and last proposed size — plus
# the adoption side: automatic keyspace merges performed by a
# ring-heir when membership declared its pair dead (zero operator
# action), refusals (intern-table drift, no mirror state), and the
# measured time-to-adopt (heartbeat death declaration → merged frame
# serving), the elastic fleet's headline beside TTD and TTM.
ANOMALY_AUTOSCALE_PROPOSALS = "anomaly_autoscale_proposals_total"  # {action=}
ANOMALY_AUTOSCALE_REFUSED = "anomaly_autoscale_refused_total"  # {reason=}
ANOMALY_AUTOSCALE_TARGET = "anomaly_autoscale_target_shards"
ANOMALY_AUTOSCALE_SCORE = "anomaly_autoscale_saturation_score"
ANOMALY_FLEET_ADOPTIONS = "anomaly_fleet_adoptions_total"
ANOMALY_FLEET_ADOPTIONS_REFUSED = "anomaly_fleet_adoptions_refused_total"  # {reason=}
ANOMALY_FLEET_ADOPTION_TTA = "anomaly_fleet_adoption_seconds"

# Verdict provenance plane (runtime.provenance): evidence bundles
# built at flag time (per flagged service), bundles exported as OTLP
# log records through the background poster, and what a flag-time
# build costs on the harvester thread — plus the fleet build-identity
# gauge (one 1-valued series per process, labeled with the package
# version, the wire frame version and the jax build) a rolling resize
# checks for mixed-build shards.
ANOMALY_EXPLANATIONS_BUILT = "anomaly_explanations_built_total"
ANOMALY_EXPLANATIONS_EXPORTED = "anomaly_explanations_exported_total"
ANOMALY_EXPLAIN_LATENCY = "anomaly_explain_latency_seconds"  # histogram
ANOMALY_BUILD_INFO = "anomaly_build_info"  # {version=, frame_version=, jax=}

# Key lifecycle plane (runtime.keyspace): the budget watchdog's RSS
# sample (first-class — the soak bench's VmRSS read, promoted to a
# scrapeable gauge), the intern-table occupancy trio the fill fraction
# is computed from, the keyspace degradation-ladder level (0 normal ·
# 1 evict idle · 2 throttle new keys · 3 collapse to overflow · 4 shed
# ingest), the eviction/generation counters, and the per-tenant
# admission outcomes under ladder pressure.
ANOMALY_PROCESS_RSS = "anomaly_process_rss_bytes"
ANOMALY_KEYSPACE_ROWS = "anomaly_keyspace_rows"  # live interned keys
ANOMALY_KEYSPACE_CAPACITY = "anomaly_keyspace_capacity_rows"
ANOMALY_KEYSPACE_FILL = "anomaly_keyspace_fill_ratio"
ANOMALY_KEYSPACE_LEVEL = "anomaly_keyspace_level"
ANOMALY_KEYSPACE_GENERATION = "anomaly_keyspace_generation"
ANOMALY_KEYSPACE_EVICTED = "anomaly_keyspace_evicted_total"
ANOMALY_KEYSPACE_FREE_IDS = "anomaly_keyspace_free_ids"
ANOMALY_KEYSPACE_THROTTLED = "anomaly_keyspace_newkeys_throttled_total"  # {tenant=}
ANOMALY_KEYSPACE_OVERFLOW = "anomaly_keyspace_overflow_keys_total"  # {tenant=}


def export_metrics_report(
    registry: MetricRegistry,
    service_names: list[str],
    metric_names: list[str],
    report,
    flagged: list[str],
    seen: set | None = None,
) -> None:
    """Publish one MetricsHeadReport into the registry (host-side).

    ``seen`` (caller-owned, persisted across reports) tracks which
    (service, metric) series were ever exported: quiet cells never mint
    a series, but a series that HAS been minted keeps updating — its z
    masks to 0 when the stream stops, and freezing the last anomalous
    value on the Prometheus surface would show a permanent incident.
    """
    import numpy as np

    z = np.asarray(report.z)  # [S, M, T]
    cell = np.asarray(report.cell_flags)  # [S, M]
    for i, sname in enumerate(service_names[: z.shape[0]]):
        zi = np.abs(z[i]).max(axis=1)  # [M]
        for j, mname in enumerate(metric_names[: z.shape[1]]):
            key = (sname, mname)
            minted = seen is not None and key in seen
            if zi[j] > 0.0 or cell[i, j] or minted:
                registry.gauge_set(
                    ANOMALY_METRIC_Z, float(zi[j]), service=sname, metric=mname
                )
                if seen is not None:
                    seen.add(key)
    for name in flagged:
        registry.counter_add(ANOMALY_METRIC_FLAG_TOTAL, 1.0, service=name)


def export_report(
    registry: MetricRegistry,
    service_names: list[str],
    report,
    flagged: list[str],
) -> None:
    """Publish one DetectorReport into the registry (host-side, cheap)."""
    import numpy as np

    lat_z = np.asarray(report.lat_z)
    err_z = np.asarray(report.err_z)
    rate_z = np.asarray(report.rate_z)
    card_z = np.asarray(report.card_z)
    card = np.asarray(report.card_est)
    hh = np.asarray(report.hh_ratio)
    cusum = np.asarray(report.cusum)
    # The intern table can outgrow the sketch's service axis (overflow
    # names share the last id but keep their own table entries), so cap
    # at the report's actual row count.
    for i, name in enumerate(service_names[: lat_z.shape[0]]):
        registry.gauge_set(ANOMALY_Z_SCORE, float(np.abs(lat_z[i]).max()),
                           service=name, signal="latency")
        registry.gauge_set(ANOMALY_Z_SCORE, float(np.abs(err_z[i]).max()),
                           service=name, signal="error_rate")
        registry.gauge_set(ANOMALY_Z_SCORE, float(np.abs(rate_z[i]).max()),
                           service=name, signal="throughput")
        registry.gauge_set(ANOMALY_Z_SCORE, float(np.abs(card_z[i]).max()),
                           service=name, signal="cardinality")
        registry.gauge_set(ANOMALY_CARDINALITY, float(card[i].max()), service=name)
        registry.gauge_set(ANOMALY_HEAVY_HITTER, float(hh[i].max()), service=name)
        for j, signal in enumerate(("latency_up", "error_up", "rate_down")):
            registry.gauge_set(
                ANOMALY_CUSUM, float(cusum[i, j]), service=name, signal=signal
            )
    for name in flagged:
        registry.counter_add(ANOMALY_FLAG_TOTAL, 1.0, service=name)
