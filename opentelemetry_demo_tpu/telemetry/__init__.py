"""Telemetry: in-proc tracing SDK + metric export.

The reference instruments every service with an OTel SDK and ships
three signals through the collector (SURVEY.md §3.2). Here the tracer is
in-process (spans go straight to the detector pipeline and/or an OTLP
exporter), and metrics export in Prometheus text format — the same
surfaces Grafana scrapes in the reference stack.
"""

from .tracer import Baggage, Tracer, TraceContext
from .metrics import MetricRegistry, PrometheusExporter

__all__ = [
    "Baggage",
    "Tracer",
    "TraceContext",
    "MetricRegistry",
    "PrometheusExporter",
]
