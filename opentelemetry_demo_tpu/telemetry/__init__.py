"""Telemetry: in-proc tracing SDK, metrics, and the backend tier.

The reference instruments every service with an OTel SDK and ships
three signals through the collector into Jaeger / Prometheus /
OpenSearch / Grafana (SURVEY.md §3.2). Here the whole tier exists as a
library on a virtual clock: tracer → :class:`Collector` (processors,
spanmetrics connector, exporter fan-out) → :class:`TraceStore` (Jaeger
analogue), :class:`MetricTSDB` + :class:`Scraper` (Prometheus
analogue), :class:`LogStore` (OpenSearch analogue), with provisioned
dashboards (Grafana analogue) evaluated straight against the stores.
"""

from .tracer import Baggage, Tracer, TraceContext
from .metrics import MetricRegistry, PrometheusExporter
from .collector import Collector, CollectorConfig, normalize_span_name
from .tracestore import TraceStore
from .tsdb import MetricTSDB, Scraper
from .logstore import LogDoc, LogStore
from .hostmetrics import HostMetricsReceiver
from .receivers import HttpCheckReceiver, StoreStatsReceiver
from . import dashboards

__all__ = [
    "Baggage",
    "Tracer",
    "TraceContext",
    "MetricRegistry",
    "PrometheusExporter",
    "Collector",
    "CollectorConfig",
    "normalize_span_name",
    "TraceStore",
    "MetricTSDB",
    "Scraper",
    "LogDoc",
    "LogStore",
    "HostMetricsReceiver",
    "HttpCheckReceiver",
    "StoreStatsReceiver",
    "dashboards",
]
