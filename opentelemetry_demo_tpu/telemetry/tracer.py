"""In-proc tracing: trace context, baggage, span emission.

Behavioural contract mirrored from the reference (SURVEY.md §5
"Tracing"): W3C-style trace ids propagate across every service hop —
including the async Kafka boundary, where the reference injects context
into message headers (/root/reference/src/checkout/main.go:631-637) —
and baggage carries ``session.id`` / ``synthetic_request`` from the load
generator down to payment/ad targeting
(/root/reference/src/load-generator/locustfile.py:176-178,
/root/reference/src/payment/charge.js:77-82).

Durations are *simulated* (each service models its latency profile and
fault-flag effects) and the clock is injectable, so a minute of shop
traffic runs in milliseconds of wall time while producing span streams
with realistic per-service structure — the property the detector tests
need.
"""

from __future__ import annotations

import itertools
import secrets
from dataclasses import dataclass, field
from typing import Callable

from ..runtime.tensorize import SpanEvent, SpanRecord


def exception_event(exc: BaseException, ts_offset_us: float = 0.0) -> SpanEvent:
    """record_exception analogue (OTel semconv): the event shape the
    reference's email service attaches on failure
    (/root/reference/src/email/email_server.rb:32)."""
    return SpanEvent(
        name="exception",
        ts_offset_us=ts_offset_us,
        attrs=(
            ("exception.type", type(exc).__name__),
            ("exception.message", str(exc)),
        ),
    )

Baggage = dict  # key → str value; propagated verbatim


@dataclass
class TraceContext:
    """One distributed trace: id + baggage, passed across every hop."""

    trace_id: bytes
    baggage: Baggage = field(default_factory=dict)

    @classmethod
    def new(cls, baggage: Baggage | None = None) -> "TraceContext":
        return cls(trace_id=secrets.token_bytes(16), baggage=dict(baggage or {}))

    def to_headers(self) -> dict[str, str]:
        """W3C-traceparent-shaped header injection (Kafka/HTTP boundary)."""
        headers = {"traceparent": f"00-{self.trace_id.hex()}-{'0' * 16}-01"}
        if self.baggage:
            headers["baggage"] = ",".join(
                f"{k}={v}" for k, v in self.baggage.items()
            )
        return headers

    @classmethod
    def from_headers(cls, headers: dict[str, str]) -> "TraceContext":
        tp = headers.get("traceparent", "")
        parts = tp.split("-")
        trace_id = bytes.fromhex(parts[1]) if len(parts) >= 2 else secrets.token_bytes(16)
        baggage: Baggage = {}
        for item in headers.get("baggage", "").split(","):
            if "=" in item:
                k, v = item.split("=", 1)
                baggage[k.strip()] = v.strip()
        return cls(trace_id=trace_id, baggage=baggage)


class Tracer:
    """Emits SpanRecords into a sink; one instance per shop."""

    def __init__(self, sink: Callable[[SpanRecord], None]):
        self._sink = sink
        self.spans_emitted = 0
        self._emit_count = itertools.count(1)

    def emit(
        self,
        service: str,
        name: str,
        ctx: TraceContext,
        duration_us: float,
        is_error: bool = False,
        attr: str | None = None,
        events: tuple = (),
    ) -> None:
        # Monotonic-enough ops counter: emit() runs concurrently under
        # the gRPC edge's shared lock, and += is a read-modify-write —
        # itertools.count gives a GIL-atomic increment without a mutex
        # on the span hot path (the value is advisory telemetry).
        self.spans_emitted = next(self._emit_count)
        self._sink(
            SpanRecord(
                service=service,
                duration_us=float(duration_us),
                trace_id=ctx.trace_id,
                is_error=is_error,
                attr=attr,
                name=name,
                events=tuple(events),
            )
        )
